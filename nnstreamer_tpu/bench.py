#!/usr/bin/env python
"""Benchmark: the BASELINE.md composite workload plus the classify slice.

Headline (the JSON line's value): **MobileNetV2-SSD composite pipeline**
throughput through real elements end to end:

    device_src(uint8 300x300 frames staged in HBM)
        ! tensor_transform(typecast+normalize)      <- fused into filter
        ! tensor_filter framework=jax-xla model=ssd (backbone + box
              decode + class-aware NMS, ONE XLA computation on-device)
        ! tensor_decoder mode=bounding_boxes option1=mobilenet-ssd-postprocess
              option7=device (overlay rasterized ON the TPU — one XLA
              program writes the (B,H,W,4) canvas; nothing crosses to host)
        ! appsink

The transform element is separate in the pipeline string; the runtime
fusion pass (runtime/fusion.py) compiles it into the filter's program —
`composite_fused_vs_unfused` and `fused_vs_unfused` report the measured
speedup of that pass on the composite and classify workloads.  Extra
fields:

- p50/p99_frame_latency_ms: per-frame e2e latency, batch=1 composite
  pipeline, frames paced 10 ms apart, pts-stamped at the source and
  measured at the sink after blocking on the device result (annotated
  link- or device-dominated; under a remote tunnel the raw numbers
  include ~90 ms RTT per frame).
- p50/p99_device_ms: transport-independent — each frame is bracketed by
  trivial-jit probes (floor = min), burst-contaminated frames are
  excluded from the tail and counted in tail_excluded_frames.
- mfu + roofline: composite FLOPs from XLA cost analysis of the exact
  compiled program; the roofline block reports the program's own
  bytes/flops, its intensity ceiling, and HBM utilization.
- device_time_breakdown: backbone / postprocess / overlay / dispatch
  gap per batch, chained-dispatch two-N estimator over DISTINCT staged
  inputs (the tunnel memoizes repeated executions).
- classify_fps, vit_fps/vit_mfu (Pallas flash-attention engaged),
  yolo_fps/yolo_mfu, tflite_mobilenet_v2_fps (the reference's own
  pretrained quant model, imported and batched).
- --mesh: weak-scaling mode (writes MESH_SCALING.json).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
Baseline: BASELINE.md composite target 10,000 fps on v5e-8 => 1,250
fps/chip, p50 < 5 ms.
"""

import json
import os
import sys
import time

import numpy as np

# hardware peaks: ONE source of truth (obs/hwspec.py) shared with the
# registry's live MFU join — the names stay importable from here for
# backward compatibility
from nnstreamer_tpu.obs.hwspec import (  # noqa: F401 - re-exports
    V5E,
    V5E_BF16_PEAK,
    V5E_HBM_BW,
    V5E_ICI_BYTES_PER_S,
)
from nnstreamer_tpu.obs.xlacost import cost_of, flops_bytes

SSD_BATCH = int(os.environ.get("BENCH_SSD_BATCH", "256"))
SSD_BUFFERS = int(os.environ.get("BENCH_SSD_BUFFERS", "20"))
CLS_BATCH = int(os.environ.get("BENCH_BATCH", "512"))
CLS_BUFFERS = int(os.environ.get("BENCH_BUFFERS", "30"))
WARMUP = int(os.environ.get("BENCH_WARMUP", "3"))
LAT_FRAMES = int(os.environ.get("BENCH_LAT_FRAMES", "60"))
SSD_SIZE = 300
CLS_SIZE = 224
BASELINE_FPS_PER_CHIP = 10_000 / 8.0

# ViT slice: config chosen so the Pallas flash-attention kernel engages
# (head dim 512/4=128, patch seq (256/16)²=256 — both multiples of the
# kernel's 128 tiling; ops/kernels.py flash_attention)
VIT_BATCH = int(os.environ.get("BENCH_VIT_BATCH", "64"))
VIT_BUFFERS = int(os.environ.get("BENCH_VIT_BUFFERS", "15"))
VIT_SIZE, VIT_PATCH, VIT_DIM = 256, 16, 512
VIT_DEPTH, VIT_HEADS, VIT_MLP = 6, 4, 2048

# YOLO slice: the third model family end to end — v8-style pyramid +
# on-device decode/NMS + device overlay (round-3 verdict #8)
YOLO_BATCH = int(os.environ.get("BENCH_YOLO_BATCH", "64"))
YOLO_BUFFERS = int(os.environ.get("BENCH_YOLO_BUFFERS", "15"))
YOLO_SIZE = int(os.environ.get("BENCH_YOLO_SIZE", "640"))
# width 64 / depth 2 at 640px ≈ 9 GFLOP/frame — real yolov8n-class
# work (8.7 GFLOP), not the r4 toy (0.44 GFLOP at 320px)
YOLO_WIDTH = int(os.environ.get("BENCH_YOLO_WIDTH", "64"))
YOLO_DEPTH = int(os.environ.get("BENCH_YOLO_DEPTH", "2"))


_SSD_SHARED = {}


def _ssd_params_anchors():
    """Init the SSD weights/anchors ONCE per process: three workloads
    register the same model under different names/batches, and weight
    init costs tens of seconds on a remote device."""
    if not _SSD_SHARED:
        import jax

        from nnstreamer_tpu.models.ssd import (
            ssd_anchors,
            ssd_mobilenet_v2_init,
        )

        fs = tuple(int(np.ceil(SSD_SIZE / s))
                   for s in (16, 32, 64, 128, 256, 512))
        from nnstreamer_tpu.models.params_io import weights_to_bf16

        # bf16-RESIDENT weights (round-4 verdict #1a): halves the
        # weight-read traffic; compute consumed bf16 already
        _SSD_SHARED["params"] = weights_to_bf16(ssd_mobilenet_v2_init(
            jax.random.PRNGKey(0), num_classes=91))
        _SSD_SHARED["anchors"] = ssd_anchors(SSD_SIZE, fs)
    return _SSD_SHARED["params"], _SSD_SHARED["anchors"]


def _register_ssd_pp(name: str, batch: int):
    """Register the composite SSD with outputs in the reference
    postprocess wire order (boxes, classes, scores, num) that the
    bounding_boxes mobilenet-ssd-postprocess decoder consumes
    (parity: mobilenetssdpp.cc)."""
    import jax.numpy as jnp

    from nnstreamer_tpu.filters.jax_xla import register_model
    from nnstreamer_tpu.models.ssd import ssd_detect_apply

    params, anchors = _ssd_params_anchors()

    # max_out=10 ≈ a realistic per-frame detection count; random-weight
    # noise scores would otherwise flood the host overlay stage with the
    # full top-100 per frame, benchmarking python box-drawing instead of
    # the pipeline
    def detect(p, x):
        boxes, scores, classes = ssd_detect_apply(p, x, anchors, max_out=10)
        num = jnp.sum((scores > 0.25).astype(jnp.int32), axis=-1)
        return boxes, classes, scores, num

    register_model(name, detect, params=params,
                   in_shapes=[(batch, SSD_SIZE, SSD_SIZE, 3)],
                   in_dtypes=np.float32)
    return detect, params, anchors


def _pool_size(num_buffers: int, frame_bytes: int,
               budget_bytes: float = 2e9) -> int:
    """Distinct staged frames per pipeline, capped by an HBM budget:
    every buffer distinct at the standard bench sizes (defeats the
    tunnel's repeat-execution memoization), bounded so oversized
    BENCH_*_BUFFERS runs don't exhaust device memory."""
    cap = max(int(budget_bytes // max(frame_bytes, 1)), 4)
    return min(num_buffers, cap)


def _pull(sink, what: str):
    b = sink.pull(timeout=600)
    if b is None:
        raise RuntimeError(f"bench: {what} stalled (no buffer in 600 s)")
    return b


def _fetch_sync_small(buf):
    """Per-frame completion sync for LATENCY runs: fetch the SMALLEST
    tensor of the buffer whole (all outputs of one program materialize
    together, so any of them proves completion).  A direct tiny
    transfer — no sliced-getitem program — keeps the per-frame cost
    identical in structure to the bracketing probe, so the derived
    device excess isn't padded by an extra dispatch."""
    t = min(buf.tensors, key=lambda x: x.nbytes)
    return np.asarray(t.jax())


def _fetch_sync(out):
    """Wait for DEVICE COMPLETION of ``out`` (and, because the device
    executes dispatches in order — verified with a heavy/light program
    pair — of everything dispatched before it).

    ``jax.block_until_ready`` on the tunneled backend returns at
    dispatch-ACK, not completion (measured: a 5.3 s computation
    "blocks" in 3.7 ms) — only a host fetch forces the value, so every
    timing boundary fetches ONE element of the last output (tiny
    transfer, one round trip).  NOTE the element-getitem compiles a
    small program on first use per shape — callers must place one
    _fetch_sync BEFORE their timing window (a warmup sync) so the
    compile stall cannot let the device drain prefetched timed work;
    the pipeline benches time their own compiled program via
    _program_fps (chained differential), the only estimator that
    survived validation against known-duration programs."""
    import jax

    leaf = jax.tree_util.tree_leaves(out)[0]
    if hasattr(leaf, "jax"):
        leaf = leaf.jax()
    idx = (0,) * getattr(leaf, "ndim", 0)
    return np.asarray(leaf[idx] if idx else leaf)


def _program_fps(p, flt_name: str, src_name: str, batch: int,
                 n: int = 8, reps: int = 3, pre=None,
                 post=None) -> float:
    """Throughput of the pipeline's OWN compiled executable, timed by
    chained async dispatch over the source's freshly staged pool
    (distinct inputs) with a completion FETCH at each chain end:
    t = (T(2n) - T(n)) / n, min over reps.

    Why not time the buffer stream itself: stream-completion
    timestamps through the remote tunnel proved unreliable in BOTH
    directions (the composite stream read 2.4x faster than its own
    program's physical floor; the tflite stream read 2x slower than
    the same program chained) — completion notifications decouple
    from device time by up to ~100 ms.  The chained estimator was
    validated absolutely against a known 5.3 s program and is
    reproducible to a few percent; the pipeline still runs end to end
    first, so the element graph, negotiation and fusion pass stay
    validated, and the timed executable is bit-for-bit the one the
    pipeline dispatches.  ``pre`` optionally prepends a per-dispatch
    program (e.g. the standalone transform for an unfused filter), so
    its device time counts inside the chain."""
    import itertools

    import jax

    jitted = p[flt_name].subplugin._compiled.jitted
    if pre is not None or post is not None:
        base = jitted

        def jitted(x):  # noqa: F811
            y = base(pre(x)) if pre is not None else base(x)
            return post(*y) if post is not None else y
    pool0 = [slot[0] for slot in p[src_name]._pool]
    n = max(2, min(n, len(pool0) // 2))
    # per-CHAIN pool refresh: every chain runs on freshly salted copies
    # (x + c, uint8 wraps / float shifts noise harmlessly) so no
    # (executable, argument) pair ever repeats across chains or reps —
    # the memo-cache defense device_time_breakdown applies per
    # dispatch, done here at chain granularity because the pipeline's
    # executable has no salt operand
    salt_fn = jax.jit(lambda x, c: x + c)
    chain_no = itertools.count(1)

    def fresh_pool():
        c = np.asarray(next(chain_no)).astype(
            np.asarray(pool0[0]).dtype if not hasattr(pool0[0], "dtype")
            else pool0[0].dtype)
        pool = [salt_fn(x, c) for x in pool0]
        _fetch_sync(pool[-1])
        return pool

    _fetch_sync(jitted(pool0[0]))
    ctr = itertools.count(1)

    def chain(k):
        pool = fresh_pool()
        out = None
        t0 = time.perf_counter()
        for _ in range(k):
            out = jitted(pool[next(ctr) % len(pool)])
        _fetch_sync(out)
        return time.perf_counter() - t0

    # PAIRED differencing: each rep measures T(n) and T(2n) back to
    # back and contributes one (T2-T1)/n sample, so slow link drift
    # cancels within the pair; the median across reps rejects a
    # burst-corrupted pair (min-of-independent-chains proved fragile
    # once per-chain salting lengthened the measurement window)
    samples = []
    for _ in range(reps):
        t1 = chain(n)
        t2 = chain(2 * n)
        samples.append(max((t2 - t1) / n * 1e3, 1e-6))
    ms = float(np.median(samples))
    return batch / ms * 1000.0


def _composite_pipeline(batch: int, num_buffers: int, model: str,
                        fuse: bool = True, pool_size: int = 0,
                        flt_name: str = "net"):
    from nnstreamer_tpu.core import TensorsSpec
    from nnstreamer_tpu.elements.basic import AppSink
    from nnstreamer_tpu.elements.decoder import TensorDecoder
    from nnstreamer_tpu.elements.devicesrc import DeviceSrc
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.elements.transform import TensorTransform
    from nnstreamer_tpu.runtime import Pipeline

    spec = TensorsSpec.from_shapes([(batch, SSD_SIZE, SSD_SIZE, 3)], np.uint8)
    p = Pipeline(fuse=fuse)
    src = DeviceSrc(name="src", spec=spec, pattern="noise",
                    pool_size=pool_size or _pool_size(
                        num_buffers, batch * SSD_SIZE * SSD_SIZE * 3),
                    num_buffers=num_buffers)
    tf = TensorTransform(name="norm", mode="arithmetic",
                         option="typecast:float32,add:-127.5,div:127.5")
    flt = TensorFilter(name=flt_name, framework="jax-xla", model=model)
    # option7=device: the overlay is rasterized ON the TPU by one XLA
    # program and never crosses to the host — round 2's ceiling was one
    # host thread box-drawing at 4.2k fps while the device sat at 4% MFU
    dec = TensorDecoder(name="overlay", mode="bounding_boxes",
                        option1="mobilenet-ssd-postprocess",
                        option4=f"{SSD_SIZE}:{SSD_SIZE}",
                        option5=f"{SSD_SIZE}:{SSD_SIZE}",
                        option7="device")
    sink = AppSink(name="out", max_buffers=num_buffers + 4)
    p.add(src, tf, flt, dec, sink).link(src, tf, flt, dec, sink)
    return p, sink


def _run_composite_once(fuse: bool, model: str):
    """One composite run: async dispatch end-to-end (src→…→sink), then a
    single device sync — the device executes dispatched programs in
    order, so blocking on the LAST overlay canvas bounds every frame's
    completion.  Per-buffer host fetches would serialize a ~100 ms tunnel
    round-trip per buffer on a remote device and measure the link."""
    import jax.numpy as jnp

    from nnstreamer_tpu.obs import transfer as _xferled

    p, sink = _composite_pipeline(
        SSD_BATCH, max(WARMUP, 1) + 1, model, fuse=fuse, pool_size=16)
    # data-movement accounting over the streamed frames: ledger
    # crossings (input + drain; weights excluded — placement is
    # per-model, not per-frame) divided by buffers streamed
    x0 = _xferled.LEDGER.totals(reason="input")[0] \
        + _xferled.LEDGER.totals(reason="drain")[0]
    with p:
        for _ in range(max(WARMUP, 1) + 1):
            b = _pull(sink, "composite warmup")
        _fetch_sync(b.tensors[0])
        x1 = _xferled.LEDGER.totals(reason="input")[0] \
            + _xferled.LEDGER.totals(reason="drain")[0]
        xpf = (x1 - x0) / float(max(WARMUP, 1) + 1)
        fused = bool(p["net"]._fused_pre)
        pre = None
        post = None
        if not fused:
            # unfused mode runs THREE programs per buffer: standalone
            # transform, the filter, and the decoder's device render —
            # chain all three so the A/B compares total device time
            import jax

            from nnstreamer_tpu.decoders.boxutil import device_render_fn

            pre = jax.jit(
                lambda x: (x.astype(jnp.float32) - 127.5) / 127.5)
            post = device_render_fn(SSD_BATCH, 10, SSD_SIZE, SSD_SIZE,
                                    0.25)
        fps = _program_fps(p, "net", "src", SSD_BATCH, pre=pre,
                           post=post)
    return fps, fused, xpf


def _ab_aggregate(samples):
    """Median + relative spread of A/B samples.  Median (not best-of):
    the tunnel can only ADD time, but a repeated (executable, argument)
    execution can be served from a remote memo cache and fake an
    impossibly fast run — max() would select exactly those corrupted
    samples (this inverted the r04 fused/unfused A/B).  DeviceSrc now
    stages fresh noise per run, and the median rejects what remains."""
    med = float(np.median(samples))
    spread = (max(samples) - min(samples)) / med if med else 0.0
    return med, round(spread, 3)


def bench_composite(reps: int = 3):
    """Fused vs unfused composite, interleaved ``reps``x, MEDIAN per
    mode with the spread reported (see _ab_aggregate for why best-of
    is wrong here; three reps because a single endpoint-sync landing
    on a tunnel-jitter burst corrupts one sample in either direction
    and a 2-sample median cannot reject it).  Returns
    (fps_fused, fps_unfused, fused, spreads)."""
    model = "bench_ssd_mobilenet_v2"
    _register_ssd_pp(model, SSD_BATCH)
    runs_f, runs_u, runs_x = [], [], []
    fused = False
    for _ in range(reps):
        fps, fused, xpf = _run_composite_once(True, model)
        runs_f.append(fps)
        runs_x.append(xpf)
        fps_u, _, _ = _run_composite_once(False, model)
        runs_u.append(fps_u)
    med_f, spread_f = _ab_aggregate(runs_f)
    med_u, spread_u = _ab_aggregate(runs_u)
    return med_f, med_u, fused, {"fused": spread_f, "unfused": spread_u,
                                 "samples_fused": [round(s, 1)
                                                   for s in runs_f],
                                 "samples_unfused": [round(s, 1)
                                                     for s in runs_u],
                                 # ledger crossings per streamed frame
                                 # (fused runs; main() lifts this to a
                                 # top-level scalar for the history)
                                 "crossings_per_frame": round(
                                     float(np.median(runs_x)), 3)}


def derive_latency_stats(lats, floors):
    """Pure derivation of the latency report from per-frame e2e
    latencies and their bracketing transport-probe floors (both ms).

    Semantics (pinned by tests/test_latency_report.py, parity with the
    reference's latency-reporting CI,
    /root/reference/tests/nnstreamer_latency/unittest_latency.cc):

    - raw p50/p99 are percentiles of the e2e latencies as measured;
    - per-frame device EXCESS is ``max(latency - floor, 0)``: the
      bracketing probes see the same link, so the excess estimates
      device time;
    - frames whose excess exceeds ``3 x median_excess + 1 ms`` are
      link bursts that hit the frame but neither probe — excluded
      from the device percentiles, counted in tail_excluded_frames;
    - the report is annotated link-dominated when the probe floor
      (median) exceeds the device p50 — i.e. the e2e number mostly
      measures the link, not the framework;
    - device percentiles are UPPER BOUNDS: per-frame link jitter
      enters the excess additively (the bracketing probes bound the
      instant's link from below), so a few ms of the reported device
      time can be link noise.  The r4 values (~2 ms) used ack-based
      syncs and UNDERSTATED; the honest bound is what's reported.
    """
    lats = np.asarray(lats, np.float64)
    floors_a = np.asarray(floors, np.float64)
    excess = np.maximum(lats - floors_a, 0.0)
    med = float(np.median(excess))
    clean = excess[excess <= 3.0 * med + 1.0]
    excluded = int(excess.size - clean.size)
    floor = float(np.median(floors_a))
    p50, p99 = (float(np.percentile(lats, 50)),
                float(np.percentile(lats, 99)))
    p50_dev = float(np.percentile(clean, 50))
    p99_dev = float(np.percentile(clean, 99))
    return {
        "p50_frame_latency_ms": round(p50, 3),
        "p99_frame_latency_ms": round(p99, 3),
        "p99_frame_latency_note": "link-dominated"
        if floor > p50_dev else "device-dominated",
        "p50_device_ms": round(p50_dev, 3),
        "p99_device_ms": round(p99_dev, 3),
        "tail_excluded_frames": excluded,
        "latency_probe_floor_ms": round(floor, 3),
        "p50_device_note": "upper bound (link jitter adds to excess)",
    }


def bench_latency():
    """Per-frame e2e latency: batch=1 composite, frames paced 10 ms
    apart (a 100 fps camera), pts stamped at push with the wall clock.

    Returns a dict: raw p50/p99 include one device round-trip, which on
    a tunneled device is ~100 ms of transport; each frame is therefore
    BRACKETED by trivial-jit round-trip probes (floor = min of the two —
    tunnel jitter is additive, so the smaller probe is the cleaner
    estimate of that instant's link) and the *device* percentiles are
    computed over per-frame (latency − floor) excess.  Round-3 verdict
    #5 (tail honesty): a burst that hits the frame but neither probe
    is still link weather, not device time — frames whose excess
    exceeds 3×median + 1 ms are excluded from the device tail and
    counted in ``tail_excluded_frames``; the raw p99 is annotated as
    link-dominated when the probe floor itself exceeds the device
    excess."""
    import jax
    import jax.numpy as jnp

    from nnstreamer_tpu.core import Buffer, Tensor, TensorsSpec
    from nnstreamer_tpu.elements.basic import AppSink, AppSrc
    from nnstreamer_tpu.elements.decoder import TensorDecoder
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.elements.transform import TensorTransform
    from nnstreamer_tpu.runtime import Pipeline

    model = "bench_ssd_lat"
    _register_ssd_pp(model, 1)
    spec = TensorsSpec.from_shapes([(1, SSD_SIZE, SSD_SIZE, 3)], np.uint8)
    p = Pipeline()
    src = AppSrc(name="src", spec=spec, max_buffers=LAT_FRAMES + 8)
    tf = TensorTransform(name="norm", mode="arithmetic",
                         option="typecast:float32,add:-127.5,div:127.5")
    flt = TensorFilter(name="net", framework="jax-xla", model=model)
    dec = TensorDecoder(name="overlay", mode="bounding_boxes",
                        option1="mobilenet-ssd-postprocess",
                        option4=f"{SSD_SIZE}:{SSD_SIZE}",
                        option5=f"{SSD_SIZE}:{SSD_SIZE}",
                        option7="device")
    sink = AppSink(name="out", max_buffers=LAT_FRAMES + 8)
    p.add(src, tf, flt, dec, sink).link(src, tf, flt, dec, sink)

    rng = np.random.default_rng(0)
    # frames staged in HBM ahead of time: latency starts at "frame is in
    # device memory" (device_src semantics; host->HBM staging through a
    # remote tunnel would measure the tunnel, not the framework)
    frames = [jax.device_put(rng.integers(0, 255, (1, SSD_SIZE, SSD_SIZE, 3),
                                          np.uint8))
              for _ in range(LAT_FRAMES)]
    for fr in frames:
        _fetch_sync(fr)
    probe = jax.jit(lambda x: x.sum())
    px = jnp.zeros((8,), jnp.float32)
    _fetch_sync(probe(px))
    lats, floors = [], []
    with p:
        # warmup/compile
        src.push_buffer(Buffer.of(frames[0], pts=0))
        b = _pull(sink, "latency warmup")
        _fetch_sync_small(b)

        def probe_ms():
            # fetch-based: one execution + one tiny value round trip,
            # the same cost structure as the frame sync below
            f0 = time.perf_counter()
            _fetch_sync(probe(px))
            return (time.perf_counter() - f0) * 1e3

        pre = probe_ms()
        for i in range(LAT_FRAMES):
            t0 = time.perf_counter_ns()
            src.push_buffer(Buffer(
                tensors=[Tensor(frames[i % len(frames)])], pts=t0))
            b = _pull(sink, "latency")
            _fetch_sync_small(b)
            lats.append((time.perf_counter_ns() - b.pts) / 1e6)
            # bracketing transport probes: trivial jit round-trips under
            # the SAME link conditions; the post-probe doubles as the
            # next frame's pre-probe
            post = probe_ms()
            floors.append(min(pre, post))
            pre = post
            time.sleep(0.01)
        src.end_of_stream()
    return derive_latency_stats(lats, floors)


def register_classify_model() -> str:
    """Init + register the classify model ONCE (weight init and upload
    cost tens of seconds on a remote device; the A/B loop reuses it)."""
    import jax

    from nnstreamer_tpu.filters.jax_xla import register_model
    from nnstreamer_tpu.models.mobilenet import (
        mobilenet_v1_apply,
        mobilenet_v1_init,
    )

    from nnstreamer_tpu.models.params_io import weights_to_bf16

    params = weights_to_bf16(
        mobilenet_v1_init(jax.random.PRNGKey(0), num_classes=1001))

    def classify(params, x):
        logits = mobilenet_v1_apply(params, x)
        return jax.numpy.argmax(logits, axis=-1).astype(jax.numpy.int32)

    return register_model("bench_mobilenet_v1", classify, params=params,
                          in_shapes=[(CLS_BATCH, CLS_SIZE, CLS_SIZE, 3)])


def bench_classify(fuse: bool, buffers: int, model: str):
    from nnstreamer_tpu.core import TensorsSpec
    from nnstreamer_tpu.elements.basic import AppSink
    from nnstreamer_tpu.elements.devicesrc import DeviceSrc
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.elements.transform import TensorTransform
    from nnstreamer_tpu.runtime import Pipeline

    spec = TensorsSpec.from_shapes([(CLS_BATCH, CLS_SIZE, CLS_SIZE, 3)],
                                   np.uint8)
    warm = max(WARMUP, 1)
    p = Pipeline(fuse=fuse)
    src = DeviceSrc(name="src", spec=spec, pattern="noise",
                    pool_size=16, num_buffers=warm + 1)
    tf = TensorTransform(name="norm", mode="arithmetic",
                         option="typecast:float32,add:-127.5,div:127.5")
    flt = TensorFilter(name="net", framework="jax-xla", model=model)
    sink = AppSink(name="out", max_buffers=buffers + warm + 4)
    p.add(src, tf, flt, sink).link(src, tf, flt, sink)
    with p:
        for _ in range(warm + 1):
            b = _pull(sink, "classify warmup")
        _fetch_sync(b.tensors[0])
        pre = None
        if not p["net"]._fused_pre:
            import jax
            import jax.numpy as jnp

            pre = jax.jit(
                lambda x: (x.astype(jnp.float32) - 127.5) / 127.5)
        fps = _program_fps(p, "net", "src", CLS_BATCH, pre=pre)
    return fps


def register_vit_bench() -> str:
    from nnstreamer_tpu.models.vit import register_vit

    return register_vit("bench_vit", batch=VIT_BATCH, image_size=VIT_SIZE,
                        patch=VIT_PATCH, dim=VIT_DIM, depth=VIT_DEPTH,
                        heads=VIT_HEADS, mlp_dim=VIT_MLP, num_classes=1000)


def vit_flops_per_frame() -> float:
    """Analytic matmul FLOPs of one ViT forward (standard MFU
    accounting: embed conv + qkv/attn/proj/mlp matmuls + head; LN/gelu/
    softmax elementwise excluded).  Analytic rather than XLA cost
    analysis because the attention runs inside a Pallas kernel, whose
    inner dots the CPU-backend cost model does not see."""
    s = (VIT_SIZE // VIT_PATCH) ** 2
    d, m = VIT_DIM, VIT_MLP
    embed = 2 * s * (VIT_PATCH * VIT_PATCH * 3) * d
    per_block = (2 * s * d * 3 * d      # qkv
                 + 2 * 2 * s * s * d    # q·kᵀ and p·v
                 + 2 * s * d * d        # proj
                 + 2 * s * d * m * 2)   # mlp in+out
    head = 2 * d * 1000
    return float(embed + VIT_DEPTH * per_block + head)


def bench_vit(model: str) -> float:
    """ViT classify slice through the pipeline (flash-attention kernel on
    the hot path); classify-style async timing."""
    from nnstreamer_tpu.core import TensorsSpec
    from nnstreamer_tpu.elements.basic import AppSink
    from nnstreamer_tpu.elements.devicesrc import DeviceSrc
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.elements.transform import TensorTransform
    from nnstreamer_tpu.runtime import Pipeline

    spec = TensorsSpec.from_shapes([(VIT_BATCH, VIT_SIZE, VIT_SIZE, 3)],
                                   np.uint8)
    warm = max(WARMUP, 1)
    p = Pipeline()
    src = DeviceSrc(name="src", spec=spec, pattern="noise",
                    pool_size=16, num_buffers=warm + 1)
    tf = TensorTransform(name="norm", mode="arithmetic",
                         option="typecast:float32,add:-127.5,div:127.5")
    flt = TensorFilter(name="net", framework="jax-xla", model=model)
    sink = AppSink(name="out", max_buffers=VIT_BUFFERS + warm + 4)
    p.add(src, tf, flt, sink).link(src, tf, flt, sink)
    with p:
        for _ in range(warm + 1):
            b = _pull(sink, "vit warmup")
        _fetch_sync(b.tensors[0])
        pre = None
        if not p["net"]._fused_pre:
            import jax
            import jax.numpy as jnp

            pre = jax.jit(
                lambda x: (x.astype(jnp.float32) - 127.5) / 127.5)
        fps = _program_fps(p, "net", "src", VIT_BATCH, pre=pre)
    return fps


def device_time_breakdown(render_conf: float = 0.25):
    """Steady-state device time of the composite program, split into
    backbone / postprocess / overlay, plus an XLA cost-analysis roofline
    (round-3 verdict #2: explain the MFU, don't just assert fps).

    Methodology: each stage program is timed with chained async
    dispatches — T(n) = overhead + n·t, so t = (T(2n) − T(n))/n — and a
    min over repetitions, because tunnel jitter is strictly additive.
    The roofline comes from the compiled detect program's own cost
    analysis: arithmetic intensity (flops/byte) against the v5e ridge
    (peak_flops / HBM bandwidth) bounds the reachable MFU of THIS
    program independent of any runtime overhead.
    """
    import jax
    import jax.numpy as jnp

    from nnstreamer_tpu.decoders.boxutil import device_render_fn
    from nnstreamer_tpu.models.ssd import ssd_mobilenet_v2_apply

    params, anchors = _ssd_params_anchors()
    detect, _, _ = _register_ssd_pp("bench_ssd_breakdown", SSD_BATCH)
    dev = jax.devices()[0]
    params_d = jax.device_put(params, dev)

    def norm(x):
        return (x.astype(jnp.float32) - 127.5) / 127.5

    # every dispatch carries a UNIQUE uint8 salt folded into the input:
    # a repeated (executable, argument) execution can be served from a
    # remote memo cache faking near-zero device time, and a fixed input
    # pool only de-duplicates dispatches WITHIN one chained block, not
    # across the repetitions (measured: un-salted chains reported 0.06
    # ms for a 13 ms program)
    f_backbone = jax.jit(lambda x, i: ssd_mobilenet_v2_apply(
        params_d, norm(x + i), cls_dtype=jnp.bfloat16))
    f_detect = jax.jit(lambda x, i: detect(params_d, norm(x + i)))
    _render = device_render_fn(  # already jitted internally
        SSD_BATCH, 10, SSD_SIZE, SSD_SIZE, render_conf)
    f_render = jax.jit(lambda boxes, classes, scores, num, i:
                       _render(boxes + i * 1e-6, classes, scores, num))

    rng = np.random.default_rng(0)
    n_inputs = 32
    xs = [jax.device_put(rng.integers(
        0, 255, (SSD_BATCH, SSD_SIZE, SSD_SIZE, 3), dtype=np.uint8), dev)
        for _ in range(n_inputs)]
    salts_u8 = [jax.device_put(np.uint8(j)) for j in range(256)]
    salts_f32 = [jax.device_put(np.float32(j)) for j in range(256)]
    zero_u8 = salts_u8[0]
    det_outs = [f_detect(x, zero_u8) for x in xs]
    _fetch_sync(det_outs[-1])

    import itertools as _it

    _salt_i = _it.count()

    def chained(fn, argsets, n, salts):
        out = None
        t0 = time.perf_counter()
        for _ in range(n):
            c = next(_salt_i)
            out = fn(*argsets[c % len(argsets)], salts[c % 256])
        _fetch_sync(out)  # COMPLETION, not dispatch-ack (see helper)
        return time.perf_counter() - t0

    def per_call_ms(fn, argsets, n=16, reps=4, salts=None):
        # n chosen so n·t ≫ tunnel jitter (~±10 ms per chained block);
        # min over reps because jitter is strictly additive
        salts = salts_u8 if salts is None else salts
        _fetch_sync(fn(*argsets[0], salts[255]))  # warm
        t1 = min(chained(fn, argsets, n, salts) for _ in range(reps))
        t2 = min(chained(fn, argsets, 2 * n, salts) for _ in range(reps))
        return max((t2 - t1) / n * 1e3, 0.0)

    backbone_ms = per_call_ms(f_backbone, [(x,) for x in xs])
    detect_ms = per_call_ms(f_detect, [(x,) for x in xs])
    render_ms = per_call_ms(f_render, det_outs, salts=salts_f32)

    # roofline of the exact detect computation (the pipeline's fused
    # transform+model program; overlay adds its canvas analytically)
    roofline = {}
    try:
        c = f_detect.lower(
            jax.ShapeDtypeStruct(xs[0].shape, xs[0].dtype),
            jax.ShapeDtypeStruct((), np.uint8)).compile()
        ca = cost_of(c)  # one extraction helper (obs/xlacost.py)
        flops = float(ca.get("flops", 0.0))
        bytes_acc = float(ca.get("bytes accessed", 0.0))
        if flops and bytes_acc:
            intensity = flops / bytes_acc
            ridge = V5E.ridge
            roofline = {
                "detect_gflops_per_batch": round(flops / 1e9, 1),
                "detect_gbytes_per_batch": round(bytes_acc / 1e9, 3),
                "intensity_flops_per_byte": round(intensity, 1),
                "ridge_flops_per_byte": round(ridge, 1),
                "mfu_ceiling": round(min(intensity / ridge, 1.0), 3),
                "bw_bound_ms": round(bytes_acc / V5E_HBM_BW * 1e3, 3),
                "hbm_bw_util": round(
                    (bytes_acc / V5E_HBM_BW * 1e3) / detect_ms, 3)
                if detect_ms else None,
            }
    except Exception:
        pass  # cost analysis unsupported on this backend: timings stand

    return {
        "backbone_ms": round(backbone_ms, 3),
        "postprocess_ms": round(max(detect_ms - backbone_ms, 0.0), 3),
        "overlay_ms": round(render_ms, 3),
        "compute_total_ms": round(detect_ms + render_ms, 3),
    }, roofline


_YOLO_MODEL = []


_TFLITE_MODEL = ("/root/reference/tests/test_models/models/"
                 "mobilenet_v2_1.0_224_quant.tflite")
TFLITE_BATCH = int(os.environ.get("BENCH_TFLITE_BATCH", "256"))
TFLITE_BUFFERS = int(os.environ.get("BENCH_TFLITE_BUFFERS", "15"))


def bench_tflite():
    """Pretrained-import slice: the reference's OWN quantized
    mobilenet_v2 .tflite, imported (not interpreted) and run batched on
    the TPU through the full pipeline — the number the reference's
    tflite backend cannot reach on CPU delegates.  Returns fps, or
    None when the asset is absent."""
    if not os.path.isfile(_TFLITE_MODEL):
        return None
    import jax

    from nnstreamer_tpu.core import TensorsSpec
    from nnstreamer_tpu.elements.basic import AppSink
    from nnstreamer_tpu.elements.devicesrc import DeviceSrc
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.runtime import Pipeline

    spec = TensorsSpec.from_shapes(
        [(TFLITE_BATCH, 224, 224, 3)], np.uint8)
    warm = max(WARMUP, 1)
    p = Pipeline()
    src = DeviceSrc(name="src", spec=spec, pattern="noise",
                    pool_size=16, num_buffers=warm + 1)
    flt = TensorFilter(name="net", framework="tensorflow-lite",
                       model=_TFLITE_MODEL)
    sink = AppSink(name="out", max_buffers=TFLITE_BUFFERS + warm + 4)
    p.add(src, flt, sink).link(src, flt, sink)
    with p:
        for _ in range(warm + 1):
            b = _pull(sink, "tflite warmup")
        _fetch_sync(b.tensors[0])
        fps = _program_fps(p, "net", "src", TFLITE_BATCH)
    return fps


_ONNX_MODEL = ("/root/reference/tests/test_models/models/"
               "mobilenet_v2_quant.onnx")


def bench_onnx():
    """Imported-ONNX slice: the reference's own ORT-quantized
    mobilenet_v2 .onnx run batched through the pipeline in the exact
    bf16-code quantized execution mode.  Returns fps or None."""
    if not os.path.isfile(_ONNX_MODEL):
        return None
    from nnstreamer_tpu.core import TensorsSpec
    from nnstreamer_tpu.elements.basic import AppSink
    from nnstreamer_tpu.elements.devicesrc import DeviceSrc
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.runtime import Pipeline

    spec = TensorsSpec.from_shapes(
        [(TFLITE_BATCH, 3, 224, 224)], np.float32)
    warm = max(WARMUP, 1)
    p = Pipeline()
    src = DeviceSrc(name="src", spec=spec, pattern="noise",
                    pool_size=12, num_buffers=warm + 1)
    flt = TensorFilter(name="net", framework="onnx", model=_ONNX_MODEL)
    sink = AppSink(name="out", max_buffers=TFLITE_BUFFERS + warm + 4)
    p.add(src, flt, sink).link(src, flt, sink)
    with p:
        for _ in range(warm + 1):
            b = _pull(sink, "onnx warmup")
        _fetch_sync(b.tensors[0])
        fps = _program_fps(p, "net", "src", TFLITE_BATCH, n=5)
    return fps


def onnx_flops() -> float:
    """Per-frame FLOPs of the imported onnx graph; 0.0 if absent."""
    if not os.path.isfile(_ONNX_MODEL):
        return 0.0
    from nnstreamer_tpu.filters.onnx_import import OnnxModel, build_fn

    fn, weights, _, _ = build_fn(OnnxModel(_ONNX_MODEL))
    return _cpu_flops_per_frame(lambda x: fn(weights, x), (3, 224, 224),
                                dtype=np.float32)


def tflite_flops() -> float:
    """Per-frame FLOPs of the imported tflite graph (CPU cost
    analysis); 0.0 when the reference model is absent."""
    if not os.path.isfile(_TFLITE_MODEL):
        return 0.0
    from nnstreamer_tpu.filters.tflite_import import TFLiteModel, build_fn

    fn, weights, _, _ = build_fn(TFLiteModel(_TFLITE_MODEL))
    return _cpu_flops_per_frame(lambda x: fn(weights, x), (224, 224, 3))


def bench_yolo():
    """YOLO end-to-end slice: device_src ! transform(/255, fused) !
    jax-xla yolo(decode+NMS on device) ! bounding_boxes option7=device !
    sink — the same composite shape as SSD, third model family."""
    from nnstreamer_tpu.core import TensorsSpec
    from nnstreamer_tpu.elements.basic import AppSink
    from nnstreamer_tpu.elements.decoder import TensorDecoder
    from nnstreamer_tpu.elements.devicesrc import DeviceSrc
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.elements.transform import TensorTransform
    from nnstreamer_tpu.models.yolo import register_yolo
    from nnstreamer_tpu.runtime import Pipeline

    if not _YOLO_MODEL:  # weight init costs 10s+ on a remote device
        _YOLO_MODEL.append(register_yolo(
            "bench_yolo", batch=YOLO_BATCH, image_size=YOLO_SIZE,
            max_out=10, width=YOLO_WIDTH, depth=YOLO_DEPTH))
    model = _YOLO_MODEL[0]
    spec = TensorsSpec.from_shapes(
        [(YOLO_BATCH, YOLO_SIZE, YOLO_SIZE, 3)], np.uint8)
    warm = max(WARMUP, 1)
    p = Pipeline()
    src = DeviceSrc(name="src", spec=spec, pattern="noise",
                    pool_size=16, num_buffers=warm + 1)
    tf = TensorTransform(name="norm", mode="arithmetic",
                         option="typecast:float32,div:255.0")
    flt = TensorFilter(name="net", framework="jax-xla", model=model)
    dec = TensorDecoder(name="overlay", mode="bounding_boxes",
                        option1="mobilenet-ssd-postprocess",
                        option4=f"{YOLO_SIZE}:{YOLO_SIZE}",
                        option5=f"{YOLO_SIZE}:{YOLO_SIZE}",
                        option7="device")
    sink = AppSink(name="out", max_buffers=YOLO_BUFFERS + warm + 4)
    p.add(src, tf, flt, dec, sink).link(src, tf, flt, dec, sink)
    with p:
        for _ in range(warm + 1):
            b = _pull(sink, "yolo warmup")
        _fetch_sync(b.tensors[0])
        pre = None
        if not p["net"]._fused_pre:
            import jax
            import jax.numpy as jnp

            pre = jax.jit(lambda x: x.astype(jnp.float32) / 255.0)
        fps = _program_fps(p, "net", "src", YOLO_BATCH, pre=pre)
    return fps


def _cpu_flops_per_frame(full, shape, dtype=np.uint8, cb: int = 8) -> float:
    """Per-frame FLOPs of ``full`` via cost analysis on the (local,
    fast) CPU backend — FLOP count is computation-intrinsic, so no
    accelerator compile is spent on analysis.  ``shape`` excludes the
    batch dim; returns 0.0 when the backend lacks cost analysis."""
    import jax

    x = jax.ShapeDtypeStruct((cb,) + tuple(shape), dtype)
    try:
        cpu = jax.devices("cpu")[0]
        with jax.default_device(cpu):
            compiled = jax.jit(full).lower(x).compile()
        flops = flops_bytes(compiled)[0]  # obs/xlacost.py extraction
        return flops / cb if flops else 0.0
    except (KeyError, TypeError, RuntimeError):
        return 0.0


def yolo_flops() -> float:
    """Per-frame FLOPs of the yolo slice (normalize + pyramid + decode +
    NMS) via CPU-backend cost analysis of the exact computation."""
    import jax

    from nnstreamer_tpu.models.yolo import yolo_detect_apply, yolo_init

    params = yolo_init(jax.random.PRNGKey(0), width=YOLO_WIDTH,
                       depth=YOLO_DEPTH)
    return _cpu_flops_per_frame(
        lambda x: yolo_detect_apply(params, x.astype(np.float32) / 255.0,
                                    max_out=10),
        (YOLO_SIZE, YOLO_SIZE, 3))


def composite_flops() -> float:
    """Per-frame FLOPs of the EXACT composite computation (normalize +
    backbone + decode + NMS) from XLA cost analysis."""
    import jax

    cost_batch = 8  # FLOPs/frame is batch-invariant; small batch keeps
    detect, params, anchors = _register_ssd_pp("bench_ssd_cost", cost_batch)

    def full(x):
        # params closed over (the filter's flat_fn path does the same):
        # pytree ints like num_classes stay concrete for tracing
        xf = (x.astype(np.float32) - 127.5) / 127.5
        return detect(params, xf)

    return _cpu_flops_per_frame(full, (SSD_SIZE, SSD_SIZE, 3),
                                cb=cost_batch)


def classify_flops() -> float:
    """Per-frame FLOPs of the classify slice (normalize+backbone+argmax)
    via CPU-backend cost analysis."""
    import jax

    from nnstreamer_tpu.models.mobilenet import (
        mobilenet_v1_apply,
        mobilenet_v1_init,
    )

    from nnstreamer_tpu.models.params_io import weights_to_bf16

    params = weights_to_bf16(
        mobilenet_v1_init(jax.random.PRNGKey(0), num_classes=1001))

    def full(x):
        xf = (x.astype(np.float32) - 127.5) / 127.5
        return jax.numpy.argmax(mobilenet_v1_apply(params, xf), -1)

    return _cpu_flops_per_frame(full, (CLS_SIZE, CLS_SIZE, 3))


def device_roundtrip_floor_ms() -> float:
    """Median latency of a trivial jitted computation: everything below
    this is transport (tunnel RTT on remote devices), not framework."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x.sum())
    x = jnp.zeros((8,), jnp.float32)
    _fetch_sync(f(x))
    ts = []
    for _ in range(10):
        t0 = time.perf_counter()
        _fetch_sync(f(x))
        ts.append((time.perf_counter() - t0) * 1e3)
    return float(np.median(ts))


def _enable_compile_cache():
    """Persist compiled executables across bench runs: the workloads are
    fixed programs, so every run after the first skips the multi-10s
    accelerator compiles entirely."""
    import jax

    try:
        cache = os.environ.get("NNS_TPU_JAX_CACHE") or os.path.join(
            os.environ.get("XDG_CACHE_HOME",
                           os.path.join(os.path.expanduser("~"), ".cache")),
            "nnstreamer_tpu", "jax_cache")
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # cache unsupported: bench still runs, just recompiles


def scaling_projection(fps_per_chip: float,
                       per_frame_flops: float,
                       handoff_bytes_per_frame: float,
                       n_chips: int = 8,
                       host_fanout_margin: float = 0.03):
    """MODEL-based projection of composite scaling to a v5e pod slice
    (round-4 verdict #8): the v5e-8 claim should rest on an explicit
    bandwidth model, not a pro-rating.

    Two deployment shapes:

    - ``data_parallel``: inference is embarrassingly parallel — params
      replicated, each chip streams its own batches, ZERO steady-state
      ICI traffic.  The only sub-linearity is host-side dispatch fanout
      (one process feeding n streams), modeled as a flat margin.
    - ``split_pipeline`` (the shipped two-stage devices= split, stage A
      backbone+detect on half the chips, stage B on the other half):
      per-frame handoff bytes cross ONE submesh boundary over ICI.
      Demand = projected fps x handoff bytes; supply = the boundary
      chips' aggregate ICI.  Efficiency = min(1, supply/demand) on top
      of the data-parallel projection.

    All inputs are MEASURED single-chip numbers; the output is labeled
    a projection and carries its own assumptions.
    """
    dp_fps = fps_per_chip * n_chips * (1.0 - host_fanout_margin)
    half = max(n_chips // 2, 1)
    # each stage runs data-parallel on half the chips and the SLOWER
    # stage paces the pipe.  With the shipped split (stage B is the
    # tiny overlay head) stage A is modeled as the full per-chip
    # program, so steady-state throughput is stage A's capacity:
    # fps_per_chip x n/2 — HALF the pure-data-parallel number.  (A
    # compute-balanced split would approach dp_fps; this split exists
    # for placement/memory, not throughput.)
    split_ideal = fps_per_chip * half * (1.0 - host_fanout_margin)
    ici_supply = half * V5E_ICI_BYTES_PER_S
    ici_demand = split_ideal * handoff_bytes_per_frame
    ici_eff = min(1.0, ici_supply / ici_demand) if ici_demand else 1.0
    return {
        "model": "scaling projection (NOT a measurement)",
        "inputs": {
            "fps_per_chip_measured": round(fps_per_chip, 1),
            "per_frame_gflops": round(per_frame_flops / 1e9, 3),
            "handoff_bytes_per_frame": int(handoff_bytes_per_frame),
            "n_chips": n_chips,
            "host_fanout_margin": host_fanout_margin,
            "v5e_ici_bytes_per_s_per_chip": V5E_ICI_BYTES_PER_S,
        },
        "data_parallel": {
            "projected_fps": round(dp_fps, 0),
            "ici_traffic": 0,
            "assumption": "params replicated; no steady-state "
                          "collectives in inference",
        },
        "split_pipeline": {
            "projected_fps": round(split_ideal * ici_eff, 0),
            "ici_demand_bytes_per_s": round(ici_demand, 0),
            "ici_supply_bytes_per_s": round(ici_supply, 0),
            "ici_efficiency": round(ici_eff, 3),
        },
        "vs_baseline_target_fps": 10000,
    }


def bench_project(out_path: str = "SCALING_MODEL.json"):
    """``--project``: write the v5e-8 scaling model from this chip's
    measured composite numbers + the split pipeline's actual handoff
    tensor sizes (jax.eval_shape over the real detect program)."""
    import jax

    model = "bench_ssd_project"
    detect, params, anchors = _register_ssd_pp(model, SSD_BATCH)
    outs = jax.eval_shape(
        lambda x: detect(params, x),
        jax.ShapeDtypeStruct((SSD_BATCH, SSD_SIZE, SSD_SIZE, 3),
                             np.float32))
    handoff = sum(int(np.prod(o.shape)) * o.dtype.itemsize
                  for o in jax.tree_util.tree_leaves(outs)) / SSD_BATCH
    fps, _, _, _ = bench_composite(reps=1)
    flops = composite_flops()
    proj = scaling_projection(fps, flops, handoff)
    with open(out_path, "w") as f:
        json.dump(proj, f, indent=1)
    print(json.dumps(proj))


MESH_FRAMES = int(os.environ.get("BENCH_MESH_FRAMES", "10"))
MESH_REPS = int(os.environ.get("BENCH_MESH_REPS", "3"))


def _mesh_sizes(n_devices: int):
    spec = os.environ.get("BENCH_MESH_SIZES", "1,2,4,8")
    return [n for n in (int(t) for t in spec.split(",") if t.strip())
            if n <= n_devices]


def _mesh_attribution(row: dict, base: dict) -> dict:
    """Decompose one weak-scaling leg's efficiency loss.  With one
    dispatch per buffer, ``eff = (h_1 + d_1) / (h_n + d_n)`` where h/d
    are the measured per-dispatch host/device phases — so the gap
    splits EXACTLY into host-phase growth and device-time growth.
    The measured device seconds already *contain* pad-slot execution
    and the wait for the slowest shard, so the mesh table's pad-waste
    (``pad_frac`` of the device time burns pad slots) and
    shard-imbalance (``1 - mean/max`` of it waits on the hottest
    shard) terms are carved OUT of the device growth, not added on
    top; what remains of the growth is true contention/collectives.
    Both carve-outs are 0.0 on an even-split leg by construction.
    ``residual`` is whatever the wall-clock efficiency lost beyond the
    phase accounting (scheduler noise between dispatches)."""
    h_n, d_n = row["host_s_per_dispatch"], row["device_s_per_dispatch"]
    h_1, d_1 = base["host_s_per_dispatch"], base["device_s_per_dispatch"]
    total = h_n + d_n
    gap = 1.0 - row["efficiency"]
    host_loss = (h_n - h_1) / total if total else 0.0
    sf = row.get("shard_frames") or [1]
    mean = sum(sf) / len(sf)
    dev_frac = d_n / total if total else 0.0
    imbalance_loss = (1.0 - (mean / max(sf)) if max(sf) else 0.0) \
        * dev_frac
    pad_loss = row.get("pad_frac", 0.0) * dev_frac
    device_loss = ((d_n - d_1) / total if total else 0.0) \
        - imbalance_loss - pad_loss
    explained = host_loss + device_loss + imbalance_loss + pad_loss
    terms = {"host_phase": host_loss,
             "device_contention": device_loss,
             "shard_imbalance": imbalance_loss,
             "pad_waste": pad_loss}
    dominant = max(terms, key=lambda k: terms[k]) \
        if any(v > 0 for v in terms.values()) else "none"
    return {
        **{k: round(v, 4) for k, v in terms.items()},
        "residual": round(gap - explained, 4),
        "dominant": dominant,
    }


def bench_meshscaling(out_path: str = "MESH_SCALING.json",
                      metrics: bool = False):
    """``--meshscaling`` (also ``--mesh``): weak-scaling of the
    mesh-sharded filter over n = 1,2,4,8 devices, through the REAL
    ``tensor_filter mesh=data:n`` element path with every dispatch
    stat-sampled — so each leg yields not just frames/s but the full
    efficiency decomposition: host-phase growth vs device-time growth
    (from PR 7's cost attribution), shard imbalance and pad waste
    (from the obs mesh table), and the executable's captured XLA cost
    cross-checked byte-for-byte against this bench's own lowering.
    Writes ``MESH_SCALING.json`` with a per-n ``attribution`` block
    that *explains* the efficiency cliff instead of footnoting it."""
    # Size the CPU client BEFORE jax initializes: newer jax via the
    # config knob below, older jax via XLA_FLAGS (only settable while
    # jax is still unimported)
    if "jax" not in sys.modules:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    import jax

    from nnstreamer_tpu.core import Buffer, TensorsSpec
    from nnstreamer_tpu.elements.basic import AppSink, AppSrc, Queue
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.filters.jax_xla import register_model
    from nnstreamer_tpu.models.mobilenet import (
        mobilenet_v1_apply,
        mobilenet_v1_init,
    )
    from nnstreamer_tpu.obs.meshstat import MESH_STATS
    from nnstreamer_tpu.obs.metrics import REGISTRY
    from nnstreamer_tpu.obs.xlacost import XLA_COST
    from nnstreamer_tpu.runtime import Pipeline

    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except (RuntimeError, AttributeError):
        pass  # older jax: the XLA_FLAGS path above covered it
    devs = jax.devices()
    accel = ""
    if len(devs) <= 1:
        # single real chip: fall back to the virtual CPU mesh (sanity
        # numbers only — the same code path, not the same silicon)
        cpus = jax.devices("cpu")
        if len(cpus) > 1:
            devs = cpus
            accel = "cpu"
            jax.config.update("jax_default_device", cpus[0])
    sizes = _mesh_sizes(len(devs))
    params = mobilenet_v1_init(jax.random.PRNGKey(0), num_classes=16,
                               width=0.25)
    result = {
        "metric": "sharded-filter weak scaling (tensor_filter "
                  "mesh=data:n, batch=32n, every dispatch sampled)",
        "unit": "frames/sec",
        "platform": devs[0].platform,
        "devices_present": len(devs),
        "virtual_cpu_mesh": devs[0].platform == "cpu",
        "scaling": [],
    }
    if not sizes:
        raise SystemExit(
            f"--meshscaling: no mesh size in BENCH_MESH_SIZES="
            f"{os.environ.get('BENCH_MESH_SIZES', '1,2,4,8')!r} fits "
            f"the {len(devs)} visible device(s)")
    base_fps = base_n = None
    rows = []
    for n in sizes:
        batch = 32 * n
        name = register_model(f"bench_mesh_n{n}", mobilenet_v1_apply,
                              params=params,
                              in_shapes=[(batch, 64, 64, 3)],
                              in_dtypes=np.float32)
        spec = TensorsSpec.from_shapes([(batch, 64, 64, 3)], np.float32)
        frames = [Buffer.of(np.asarray(
            np.random.default_rng(i).standard_normal((batch, 64, 64, 3)),
            np.float32), pts=i) for i in range(MESH_FRAMES)]
        p = Pipeline(name=f"mesh{n}")
        src = AppSrc(name="src", spec=spec,
                     max_buffers=MESH_FRAMES + 4)
        q = Queue(name="q", max_size_buffers=MESH_FRAMES + 4)
        # per-leg element name: the registry's device-seconds series
        # and the MFU join key on the SOURCE label, so reusing one
        # name would merge the legs' measurement windows (and fire the
        # obs remap warning every leg)
        flt = TensorFilter(name=f"net{n}", framework="jax-xla",
                           model=name, accelerator=accel,
                           mesh=f"data:{n}",
                           stat_sample_interval_ms=0)
        sink = AppSink(name="out", max_buffers=MESH_FRAMES + 4)
        p.add(src, q, flt, sink).link(src, q, flt, sink)
        best = None
        with p:
            # warmup: compile + first blocking sample outside the
            # timed/attributed region
            for b in frames[:2]:
                src.push_buffer(b)
            for _ in range(2):
                _pull(sink, "mesh warmup")
            s0 = flt.invoke_stats.snapshot()
            for _ in range(MESH_REPS):
                t0 = time.perf_counter()
                for b in frames:
                    src.push_buffer(b)
                for _ in range(MESH_FRAMES):
                    _pull(sink, "mesh")
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            s1 = flt.invoke_stats.snapshot()
            snap = REGISTRY.snapshot()
            src.end_of_stream()
            p.wait_eos(timeout=30)
        fps = batch * MESH_FRAMES / best
        if base_fps is None:
            base_fps, base_n = fps, n
        disp = s1["phase"]["samples"] - s0["phase"]["samples"]
        host_s = ((s1["phase"]["host_prep_s"] + s1["phase"]["host_drain_s"])
                  - (s0["phase"]["host_prep_s"]
                     + s0["phase"]["host_drain_s"])) / max(disp, 1)
        dev_s = (s1["phase"]["device_s"]
                 - s0["phase"]["device_s"]) / max(disp, 1)
        mrow = MESH_STATS.get(name) or {}
        erow = XLA_COST.get(name, 0) or {}
        # independent cross-check of the capture plumbing: this bench's
        # OWN lowering of the same computation must yield the same
        # flops the filter's compile seam captured
        flops_bench = flops_bytes(jax.jit(
            lambda x: mobilenet_v1_apply(params, x)).lower(
            jax.ShapeDtypeStruct((batch, 64, 64, 3), np.float32)))[0]
        exec_live = [r for r in snap.get("executables", [])
                     if r["source"] == name]
        row = {
            "n": n, "fps": round(fps, 1),
            "fps_per_shard": round(fps / n, 1),
            # weak-scaling efficiency: per-shard throughput vs the BASE
            # leg's per-shard throughput (base leg need not be n=1 —
            # e.g. BENCH_MESH_SIZES=2,4 on real hardware)
            "efficiency": round((fps / n) / (base_fps / base_n), 3),
            "host_s_per_dispatch": host_s,
            "device_s_per_dispatch": dev_s,
            "host_frac": round(host_s / (host_s + dev_s), 4)
            if host_s + dev_s else 0.0,
            "imbalance": mrow.get("imbalance", 0.0),
            "pad_frac": mrow.get("pad_frac", 0.0),
            "shard_frames": mrow.get("shard_frames", []),
            "replicated_dispatches": mrow.get(
                "replicated_dispatches", 0),
            "flops_registry": erow.get("flops", 0.0),
            "flops_bench": flops_bench,
            "flops_exact": erow.get("flops", 0.0) == flops_bench
            and flops_bench > 0,
            "mfu": next((r["mfu"] for r in exec_live if "mfu" in r),
                        None),
            "intensity_flops_per_byte": next(
                (round(r["intensity_flops_per_byte"], 2)
                 for r in exec_live
                 if "intensity_flops_per_byte" in r), None),
        }
        rows.append(row)
    for row in rows:
        row["attribution"] = _mesh_attribution(row, rows[0])
        # JSON hygiene: round the raw seconds after attribution used
        # them at full precision
        row["host_s_per_dispatch"] = round(row["host_s_per_dispatch"], 6)
        row["device_s_per_dispatch"] = round(
            row["device_s_per_dispatch"], 6)
        result["scaling"].append(row)
    result["value"] = result["scaling"][-1]["fps"]
    result["vs_baseline"] = round(
        result["scaling"][-1]["efficiency"], 3)
    by_n = {r["n"]: r for r in rows}
    # gate scalars (tests/bench_baselines/mesh_smoke.json): efficiency
    # lower-is-worse, imbalance/pad exact-0.0 on this even-split leg
    result["efficiency_n2"] = by_n[2]["efficiency"] if 2 in by_n \
        else None
    result["imbalance_even"] = max(r["imbalance"] for r in rows)
    result["pad_frac_even"] = max(r["pad_frac"] for r in rows)
    result["flops_exact"] = all(r["flops_exact"] for r in rows)
    if result["virtual_cpu_mesh"]:
        dom = rows[-1]["attribution"]["dominant"] if rows else "none"
        result["note"] = (
            "virtual devices share one physical CPU: the attribution "
            f"blocks show the loss (dominant term at n={rows[-1]['n']}: "
            f"{dom}) is host-side contention, not ICI — code-path "
            "sanity only; run on a real multi-chip host for true "
            "scaling")
    if metrics:
        result["metrics"] = REGISTRY.snapshot()
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    return result


#: back-compat alias (the historical ``--mesh`` entry point)
bench_mesh = bench_meshscaling


MESH_SERVE_STREAMS = int(os.environ.get("BENCH_MESH_SERVE_STREAMS", "4"))
MESH_SERVE_FRAMES = int(os.environ.get("BENCH_MESH_SERVE_FRAMES", "48"))
MESH_SERVE_REPS = int(os.environ.get("BENCH_MESH_SERVE_REPS", "3"))
#: per-shard window share: each leg's pool batch is this x n, so the
#: per-chip work is constant across the ladder (weak scaling)
MESH_SERVE_BATCH_PER_SHARD = int(
    os.environ.get("BENCH_MESH_SERVE_BATCH_PER_SHARD", "8"))


def _mesh_serve_sizes(n_devices: int):
    spec = os.environ.get("BENCH_MESH_SERVE_SIZES",
                          os.environ.get("BENCH_MESH_SIZES", "1,2,4,8"))
    return [n for n in (int(t) for t in spec.split(",") if t.strip())
            if n <= n_devices]


def _mesh_row_delta(m0, m1) -> dict:
    """Per-leg mesh attribution over the TIMED region only: the
    MESH_STATS row is cumulative (warmup windows included), so the
    gate figures (imbalance/pad) derive from the delta."""
    if not m1:
        return {}
    m0 = m0 or {}
    sf0 = m0.get("shard_frames") or []
    sf = [b - (sf0[i] if i < len(sf0) else 0)
          for i, b in enumerate(m1.get("shard_frames") or [])]
    slots = m1.get("slots", 0) - m0.get("slots", 0)
    pads = m1.get("pad_slots", 0) - m0.get("pad_slots", 0)
    mean = sum(sf) / len(sf) if sf else 0.0
    return {
        "shard_frames": sf,
        "imbalance": (max(sf) / mean - 1.0) if mean > 0 else 0.0,
        "pad_frac": (pads / slots) if slots else 0.0,
        "replicated_dispatches": m1.get("replicated_dispatches", 0)
        - m0.get("replicated_dispatches", 0),
    }


def _meshserve_leg(n: int, accel: str, params, apply_fn, shape):
    """One weak-scaling leg through the REAL shared-pool element path:
    MESH_SERVE_STREAMS pipelines x ``share-model=true`` on ONE model
    placed ``mesh=data:n``, closed-loop clients sized so only the
    CROSS-stream window can fill a batch — every dispatch is one
    stacked window sharded over the n-device data axis, every dispatch
    stat-sampled (phase split feeds the attribution)."""
    import threading

    from nnstreamer_tpu.core import Buffer, TensorsSpec
    from nnstreamer_tpu.elements.basic import AppSink, AppSrc, Queue
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.filters.jax_xla import register_model
    from nnstreamer_tpu.obs.meshstat import MESH_STATS
    from nnstreamer_tpu.obs.metrics import REGISTRY
    from nnstreamer_tpu.runtime import Pipeline

    batch = MESH_SERVE_BATCH_PER_SHARD * n
    name = register_model(f"bench_meshserve_n{n}", apply_fn,
                          params=params, in_shapes=[shape],
                          in_dtypes=np.float32)
    spec = TensorsSpec.from_shapes([shape], np.float32)
    # total in-flight pinned to EXACTLY one window: every dispatch is a
    # full cross-stream window (inline flush on the batch-th frame) —
    # the ladder measures sharding, so pads would only measure client
    # scheduling noise.  Frames per client round up to a whole number
    # of refills so the rep's last window is full too.
    outstanding = max(batch // MESH_SERVE_STREAMS, 1)
    nframes = ((MESH_SERVE_FRAMES + outstanding - 1)
               // outstanding) * outstanding
    pipes = []
    for i in range(MESH_SERVE_STREAMS):
        p = Pipeline(name=f"meshserve{n}_{i}")
        src = AppSrc(name="src", spec=spec, max_buffers=outstanding + 4)
        q = Queue(name="q", max_size_buffers=MESH_SERVE_FRAMES + 4)
        flt = TensorFilter(name="net", framework="jax-xla", model=name,
                           accelerator=accel, mesh=f"data:{n}",
                           batch=batch, batch_timeout_ms=2.0,
                           batch_buckets=str(batch), share_model=True,
                           stat_sample_interval_ms=0)
        sink = AppSink(name="out", max_buffers=MESH_SERVE_FRAMES + 4)
        p.add(src, q, flt, sink).link(src, q, flt, sink)
        p.start()
        pipes.append((p, src, flt, sink))

    def run_client(src, sink, total, errs):
        sent = got = inflight = 0
        try:
            while got < total:
                while sent < total and inflight < outstanding:
                    src.push_buffer(Buffer.of(
                        np.full(shape, float(sent % 7), np.float32),
                        pts=sent))
                    sent += 1
                    inflight += 1
                if sink.pull(timeout=120) is None:
                    raise RuntimeError(
                        f"meshserve client stalled at {got}/{total}")
                got += 1
                inflight -= 1
        except Exception as e:  # noqa: BLE001 - surface on main thread
            errs.append(e)

    def run_round(total):
        errs: list = []
        threads = [threading.Thread(target=run_client,
                                    args=(src, sink, total, errs))
                   for _, src, _, sink in pipes]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            raise errs[0]
        return time.perf_counter() - t0

    entry = pipes[0][2].pool
    # the adaptive idle-flush (1 ms settle) is the right default for
    # latency-sensitive serving, but here it races the clients' refill
    # and dispatches part-filled windows — which would measure Python
    # thread wakeups, not sharding.  Give the window time to refill;
    # full windows still dispatch INLINE the moment the last frame
    # lands, so steady-state throughput is unaffected.
    entry.batcher.settle_s = 0.2
    # the window's deadline must outlive a WHOLE sampled dispatch: the
    # next window parks while the previous one executes (flush lock
    # held), so a deadline shorter than the dispatch fires the moment
    # the lock frees and ships a part-filled window
    entry.batcher.timeout_s = 10.0
    run_round(outstanding)  # warmup: compile + settle (one full window)
    best = None
    s0 = entry.stats.snapshot()
    m0 = MESH_STATS.get(name)
    for _ in range(MESH_SERVE_REPS):
        dt = run_round(nframes)
        best = dt if best is None else min(best, dt)
    s1 = entry.stats.snapshot()
    snap = REGISTRY.snapshot()
    mrow = _mesh_row_delta(m0, MESH_STATS.get(name))
    pool_row = next((r for r in snap.get("pools", [])
                     if r.get("model") == name), {})
    for p, src, _, _ in pipes:
        src.end_of_stream()
    for p, _, _, _ in pipes:
        p.wait_eos(timeout=30)
        p.stop()
    frames_total = MESH_SERVE_STREAMS * nframes
    disp = s1["phase"]["samples"] - s0["phase"]["samples"]
    host_s = ((s1["phase"]["host_prep_s"] + s1["phase"]["host_drain_s"])
              - (s0["phase"]["host_prep_s"]
                 + s0["phase"]["host_drain_s"])) / max(disp, 1)
    dev_s = (s1["phase"]["device_s"]
             - s0["phase"]["device_s"]) / max(disp, 1)
    dispatches = s1["invokes"] - s0["invokes"]
    frames_served = s1["frames"] - s0["frames"]
    return {
        "name": name, "batch": batch,
        "fps": frames_total / best,
        "frames_total": frames_total,
        "dispatches": dispatches,
        "frames_per_dispatch": frames_served / max(dispatches, 1),
        "stream_occupancy": s1.get("avg_stream_occupancy", 0.0),
        "host_s_per_dispatch": host_s,
        "device_s_per_dispatch": dev_s,
        "mesh_row": mrow,
        "pool_mesh": pool_row.get("mesh"),
        "pool_placement": pool_row.get("placement"),
    }


def bench_meshserving(out_path: str = "BENCH_mesh_serving.json",
                      metrics: bool = False):
    """``--meshserving``: the headline gate of the mesh-native serving
    rework — the weak-scaling ladder (n = 1,2,4,8 data-axis devices)
    run through the REAL ``share-model=true`` shared-pool element path
    instead of a synthetic filter: N pipelines coalesce into ONE
    cross-stream window per leg, the window is stacked once and
    dispatched with the micro-batch axis sharded over ``mesh=data:n``,
    and every dispatch is stat-sampled so each leg carries the full
    efficiency decomposition (host_phase / device_contention /
    shard_imbalance / pad_waste) plus the registry-vs-bench flops
    cross-check.  Writes ``BENCH_mesh_serving.json`` and folds a
    ``measured`` block into ``SCALING_MODEL.json`` — the projection
    finally cross-references a measurement of the real serving path."""
    if "jax" not in sys.modules:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    import jax

    from nnstreamer_tpu.models.mobilenet import (
        mobilenet_v1_apply,
        mobilenet_v1_init,
    )
    from nnstreamer_tpu.obs.metrics import REGISTRY
    from nnstreamer_tpu.obs.xlacost import XLA_COST

    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except (RuntimeError, AttributeError):
        pass
    devs = jax.devices()
    accel = ""
    if len(devs) <= 1:
        cpus = jax.devices("cpu")
        if len(cpus) > 1:
            devs = cpus
            accel = "cpu"
            jax.config.update("jax_default_device", cpus[0])
    sizes = _mesh_serve_sizes(len(devs))
    if not sizes:
        raise SystemExit(
            f"--meshserving: no ladder size fits the {len(devs)} "
            f"visible device(s)")
    shape = (32, 32, 3)
    params = mobilenet_v1_init(jax.random.PRNGKey(0), num_classes=16,
                               width=0.25)

    def per_frame_apply(p, f):
        # the pool serves FRAMES; the window stacks them, so the model
        # fn is per-frame (the conv stack wants a batch dim back)
        return mobilenet_v1_apply(p, f[None])[0]
    result = {
        "metric": "mesh-native shared serving weak scaling "
                  f"({MESH_SERVE_STREAMS} share-model pipelines x one "
                  f"pool, window {MESH_SERVE_BATCH_PER_SHARD}*n stacked "
                  "once + sharded over mesh=data:n, every dispatch "
                  "sampled)",
        "unit": "frames/sec",
        "platform": devs[0].platform,
        "devices_present": len(devs),
        "virtual_cpu_mesh": devs[0].platform == "cpu",
        "streams": MESH_SERVE_STREAMS,
        "batch_per_shard": MESH_SERVE_BATCH_PER_SHARD,
        "scaling": [],
    }
    rows = []
    base_fps = base_n = None
    for n in sizes:
        leg = _meshserve_leg(n, accel, params, per_frame_apply, shape)
        batch = leg["batch"]
        name = leg["name"]
        if base_fps is None:
            base_fps, base_n = leg["fps"], n
        mrow = leg["mesh_row"]
        erow = XLA_COST.get(name, batch) or {}
        # independent cross-check of the stacked-window capture: the
        # bench's OWN lowering of the same vmapped window program must
        # yield the flops the pool executable's compile seam captured
        flops_bench = flops_bytes(jax.jit(
            lambda x: jax.vmap(
                lambda f: per_frame_apply(params, f))(x)).lower(
            jax.ShapeDtypeStruct((batch,) + shape, np.float32)))[0]
        row = {
            "n": n, "batch": batch,
            "fps": round(leg["fps"], 1),
            "fps_per_shard": round(leg["fps"] / n, 1),
            "efficiency": round(
                (leg["fps"] / n) / (base_fps / base_n), 3),
            "dispatches": leg["dispatches"],
            "frames_per_dispatch": round(leg["frames_per_dispatch"], 2),
            "stream_occupancy": round(leg["stream_occupancy"], 2),
            "host_s_per_dispatch": leg["host_s_per_dispatch"],
            "device_s_per_dispatch": leg["device_s_per_dispatch"],
            "imbalance": mrow.get("imbalance", 0.0),
            "pad_frac": mrow.get("pad_frac", 0.0),
            "shard_frames": mrow.get("shard_frames", []),
            "replicated_dispatches": mrow.get("replicated_dispatches",
                                              0),
            "pool_placement": leg["pool_placement"],
            "pool_mesh": leg["pool_mesh"],
            "flops_registry": erow.get("flops", 0.0),
            "flops_bench": flops_bench,
            "flops_exact": erow.get("flops", 0.0) == flops_bench
            and flops_bench > 0,
        }
        rows.append(row)
    for row in rows:
        row["attribution"] = _mesh_attribution(row, rows[0])
        row["host_s_per_dispatch"] = round(row["host_s_per_dispatch"], 6)
        row["device_s_per_dispatch"] = round(
            row["device_s_per_dispatch"], 6)
        result["scaling"].append(row)
    by_n = {r["n"]: r for r in rows}
    result["value"] = rows[-1]["fps"]
    result["vs_baseline"] = rows[-1]["efficiency"]
    # gate scalars (tests/bench_baselines/mesh_serving_smoke.json):
    # n=2 efficiency lower-direction, imbalance/pad exact-0.0 on the
    # even ladder, flops + cross-stream coalescing exact
    result["efficiency_n2"] = by_n[2]["efficiency"] if 2 in by_n \
        else None
    result["imbalance_even"] = max(r["imbalance"] for r in rows)
    result["pad_frac_even"] = max(r["pad_frac"] for r in rows)
    result["flops_exact"] = all(r["flops_exact"] for r in rows)
    result["coalescing_cross_stream"] = all(
        r["frames_per_dispatch"] > 1.0 for r in rows)
    if result["virtual_cpu_mesh"]:
        dom = rows[-1]["attribution"]["dominant"] if rows else "none"
        result["note"] = (
            "virtual devices share one physical CPU: the attribution "
            f"blocks show the loss (dominant at n={rows[-1]['n']}: "
            f"{dom}) is host-side contention, not ICI — code-path "
            "measurement of the REAL shared-pool serving stack; run "
            "on a real multi-chip host for true scaling")
    if metrics:
        result["metrics"] = REGISTRY.snapshot()
    _scaling_model_measured(result)
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    return result


def _scaling_model_measured(result: dict,
                            path: str = "SCALING_MODEL.json") -> None:
    """Fold the meshserving ladder into ``SCALING_MODEL.json`` as a
    ``measured`` block: the projection stays labeled "NOT a
    measurement", but it now cross-references the bench that measures
    the same data-parallel serving claim through the real element
    path — closing (or honestly reporting) the claim/measurement
    gap."""
    try:
        with open(path) as f:
            sm = json.load(f)
    except (OSError, ValueError):
        return  # no projection file here (e.g. bare checkout): the
        # bench result stands alone
    last = result["scaling"][-1]
    sm["measured"] = {
        "bench": "BENCH_mesh_serving.json",
        "scenario": "meshserving",
        "path": "tensor_filter share-model=true mesh=data:n "
                "(shared-pool stacked window, sharded dispatch)",
        "platform": result["platform"],
        "virtual_cpu_mesh": result["virtual_cpu_mesh"],
        "n": last["n"],
        "fps": last["fps"],
        "fps_per_shard": last["fps_per_shard"],
        "efficiency_vs_linear": last["efficiency"],
        "dominant_loss": last["attribution"]["dominant"],
        "note": ("virtual CPU mesh: validates the code path, not the "
                 "silicon — the 8-chip projection remains a model "
                 "until this bench runs on a real slice"
                 if result["virtual_cpu_mesh"] else
                 "measured on real devices through the real serving "
                 "path"),
    }
    with open(path, "w") as f:
        json.dump(sm, f, indent=1)


# -- disaggregated pipeline split: conditional cascade (ISSUE 18) -------------

CASCADE_FRAMES = int(os.environ.get("BENCH_CASCADE_FRAMES", "96"))
CASCADE_REPS = int(os.environ.get("BENCH_CASCADE_REPS", "3"))
CASCADE_SHAPE = (32, 32, 3)
CASCADE_CROP = (24, 24)  # fixed region at (0,0): one static crop shape
CASCADE_PERIOD = 4       # frame values cycle 0..3 — the seeded predicate
CASCADE_THRESHOLD = 3.0  # detector adds 1: values {2,3} offload → ratio 1/2


def _cascade_leg(split: bool, det_model: str, cls_model: str,
                 frames_n: int):
    """One cascade run through the REAL element path: device_src →
    detector filter → tensor_crop → tensor_if (offload=then, seeded
    predicate) → classifier filter, both filters ``share-model=true``
    pools on ``mesh=data:4``.  ``split=True`` pins the stages on
    DISJOINT subsets (``devices=0-3`` / ``devices=4-7``) so every
    offloaded frame crosses the stage boundary through the device
    channel; ``split=False`` is the single-stage comparator (both pools
    on the default first-4 subset, no boundary).  The frame values
    cycle 0..3 (``device_src frames=`` pool), so the routing is exact:
    detector output ``v+1 >= 3`` offloads values {2,3} — HALF the
    stream, analytically."""
    from nnstreamer_tpu.core import Buffer, TensorsSpec
    from nnstreamer_tpu.elements.basic import AppSink, AppSrc, Queue
    from nnstreamer_tpu.elements.condition import TensorIf
    from nnstreamer_tpu.elements.crop import TensorCrop
    from nnstreamer_tpu.elements.devicesrc import DeviceSrc
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.obs import transfer as _xferled
    from nnstreamer_tpu.obs.metrics import REGISTRY
    from nnstreamer_tpu.obs.stagestat import STAGE_STATS
    from nnstreamer_tpu.runtime import Pipeline

    ch, cw = CASCADE_CROP
    pname = "cascade_split" if split else "cascade_fused"
    pool = [np.full(CASCADE_SHAPE, float(k), np.float32)
            for k in range(CASCADE_PERIOD)]
    p = Pipeline(name=pname)
    src = DeviceSrc(name="src", frames=pool, pool_size=CASCADE_PERIOD,
                    num_buffers=frames_n)
    info = AppSrc(name="regions",
                  spec=TensorsSpec.from_shapes([(1, 4)], np.uint32),
                  max_buffers=frames_n + 8)
    q1 = Queue(name="q1", max_size_buffers=64)
    det = TensorFilter(name="det", framework="jax-xla", model=det_model,
                       mesh="data:4", devices="0-3" if split else "",
                       batch=4, batch_buckets="4", batch_timeout_ms=20.0,
                       share_model=True, stat_sample_interval_ms=0)
    crop = TensorCrop(name="crop")
    route = TensorIf(name="route", compared_value="A_VALUE",
                     compared_value_option="0:0",
                     supplied_value=str(CASCADE_THRESHOLD),
                     operator="ge", offload="then",
                     then="PASSTHROUGH", else_="PASSTHROUGH")
    q2 = Queue(name="q2", max_size_buffers=64)
    cls = TensorFilter(name="cls", framework="jax-xla", model=cls_model,
                       mesh="data:4", devices="4-7" if split else "",
                       batch=4, batch_buckets="4", batch_timeout_ms=20.0,
                       share_model=True, stat_sample_interval_ms=0)
    sink_off = AppSink(name="off", max_buffers=frames_n + 8)
    sink_keep = AppSink(name="keep", max_buffers=frames_n + 8)
    p.add(src, info, q1, det, crop, route, q2, cls, sink_off, sink_keep)
    p.link(src, q1, det)
    p.link_pads(det, "src", crop, "sink_raw")
    p.link_pads(info, "src", crop, "sink_info")
    p.link(crop, route)
    p.link_pads(route, "src_then", q2, "sink")
    p.link(q2, cls, sink_off)
    p.link_pads(route, "src_else", sink_keep, "sink")
    region = np.array([[0, 0, cw, ch]], np.uint32)
    # crossings accounting exactly like _run_composite_once: h2d input
    # + d2h drain rows over the run — d2d stage handoffs are tagged
    # reason="handoff" on the ledger and must NOT appear here
    x0 = _xferled.LEDGER.totals(reason="input")[0] \
        + _xferled.LEDGER.totals(reason="drain")[0]
    t0 = time.perf_counter()
    p.start()
    for i in range(frames_n):
        info.push_buffer(Buffer.of(region), timeout=120)
    info.end_of_stream()
    if not p.wait_eos(timeout=300):
        p.stop()
        raise RuntimeError(f"{pname}: pipeline did not reach EOS")
    dt = time.perf_counter() - t0
    x1 = _xferled.LEDGER.totals(reason="input")[0] \
        + _xferled.LEDGER.totals(reason="drain")[0]
    # pool occupancy while the pools are still attached (stop releases)
    stage_pools = [
        {"model": r.get("model"), "stage": r.get("stage", ""),
         "placement": r.get("placement"), "streams": r.get("streams"),
         "frames": (r.get("stats") or {}).get("frames"),
         "dispatches": (r.get("stats") or {}).get("invokes"),
         "occupancy": (r.get("stats") or {}).get(
             "avg_stream_occupancy")}
        for r in REGISTRY.snapshot().get("pools", [])
        if r.get("model") in (det_model, cls_model)]
    hrow = STAGE_STATS.get(pname, "cls")
    orow = STAGE_STATS.get(pname, "route")

    def _drain(sink):
        out = []
        while True:
            b = sink.pull(timeout=0.2)
            if b is None:
                return out
            out.append(b)

    off, keep = _drain(sink_off), _drain(sink_keep)
    # checksum of the offloaded-branch classifier outputs, in arrival
    # order — the split/fused parity surface (drains happen AFTER the
    # crossings figure is taken)
    digest = [round(float(np.sum(b.tensors[0].np())), 4) for b in off]
    p.stop()
    return {
        "fps": frames_n / dt,
        "crossings_per_frame": (x1 - x0) / float(frames_n),
        "offloaded": len(off), "kept": len(keep),
        "offload_row": orow, "handoff_row": hrow,
        "stage_pools": stage_pools, "digest": digest,
    }


def bench_cascade(out_path: str = "BENCH_cascade.json",
                  metrics: bool = False):
    """``--cascade``: the headline gate of disaggregated pipeline-split
    serving — a conditional cascade (detector → tensor_crop →
    tensor_if → classifier) run twice through the REAL element path:
    once SPLIT over disjoint device subsets (detector ``devices=0-3``,
    classifier ``devices=4-7``, every offloaded frame handed
    device-to-device through the device channel) and once single-stage
    (both pools on one subset).  Gates: stage-boundary
    ``crossings_per_frame`` EXACTLY 0.0 (the d2d handoff must never
    degrade to a drain/re-upload pair), the offload ratio EXACTLY the
    seeded predicate's analytic 1/2, byte-exact handoff accounting, and
    the split-vs-fused throughput ratio as an honest floor.  Writes
    ``BENCH_cascade.json`` and folds a ``measured`` block into
    ``SCALING_MODEL.json``'s ``split_pipeline`` object — the projection
    finally cross-references a measurement of the split serving path."""
    if "jax" not in sys.modules:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    import jax
    import jax.numpy as jnp

    from nnstreamer_tpu.filters.jax_xla import register_model
    from nnstreamer_tpu.obs.metrics import REGISTRY
    from nnstreamer_tpu.obs.stagestat import STAGE_STATS

    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except (RuntimeError, AttributeError):
        pass
    devs = jax.devices()
    if len(devs) <= 1:
        cpus = jax.devices("cpu")
        if len(cpus) > 1:
            devs = cpus
            jax.config.update("jax_default_device", cpus[0])
    if len(devs) < 8:
        raise SystemExit(
            f"--cascade: the split needs 8 devices (two 4-chip "
            f"stages); {len(devs)} visible")
    frames_n = (max(CASCADE_FRAMES, 2 * CASCADE_PERIOD)
                // (2 * CASCADE_PERIOD)) * (2 * CASCADE_PERIOD)
    ch, cw = CASCADE_CROP

    def det_apply(prm, f):
        return f + prm

    def cls_apply(prm, f):
        return jnp.tanh(f * prm).sum(axis=(0, 1))

    det_model = register_model("bench_cascade_det", det_apply,
                               params=np.float32(1.0),
                               in_shapes=[CASCADE_SHAPE],
                               in_dtypes=np.float32)
    cls_model = register_model("bench_cascade_cls", cls_apply,
                               params=np.float32(1.0),
                               in_shapes=[(ch, cw, CASCADE_SHAPE[2])],
                               in_dtypes=np.float32)
    STAGE_STATS.reset()
    runs_s, runs_f, cross = [], [], []
    last_split = last_fused = None
    for _ in range(CASCADE_REPS):
        last_split = _cascade_leg(True, det_model, cls_model, frames_n)
        runs_s.append(last_split["fps"])
        cross.append(last_split["crossings_per_frame"])
        last_fused = _cascade_leg(False, det_model, cls_model, frames_n)
        runs_f.append(last_fused["fps"])
    med_s, spread_s = _ab_aggregate(runs_s)
    med_f, spread_f = _ab_aggregate(runs_f)
    hrow = last_split["handoff_row"] or {}
    orow = last_split["offload_row"] or {}
    expected_ratio = sum(
        1 for v in range(CASCADE_PERIOD)
        if v + 1.0 >= CASCADE_THRESHOLD) / CASCADE_PERIOD
    crop_bytes = ch * cw * CASCADE_SHAPE[2] * 4  # float32 crop payload
    result = {
        "metric": "conditional cascade over a pipeline split "
                  f"(detector devices=0-3 → tensor_crop → tensor_if "
                  f"offload=then → classifier devices=4-7, "
                  f"{frames_n} frames, share-model pools, batch=4 over "
                  "mesh=data:4 per stage)",
        "unit": "frames/sec",
        "platform": devs[0].platform,
        "devices_present": len(devs),
        "virtual_cpu_mesh": devs[0].platform == "cpu",
        "frames": frames_n,
        "value": round(med_s, 1),
        "fps_split": round(med_s, 1),
        "fps_fused": round(med_f, 1),
        "split_vs_fused": round(med_s / med_f, 3) if med_f else None,
        "ab_spread": {"split": spread_s, "fused": spread_f,
                      "samples_split": [round(s, 1) for s in runs_s],
                      "samples_fused": [round(s, 1) for s in runs_f]},
        # EXACT gates (tests/bench_baselines/cascade_smoke.json):
        # crossings 0.0 across the stage boundary, the analytic offload
        # ratio, byte-exact handoff accounting, drained depth
        "crossings_per_frame": max(cross),
        "offload_ratio": orow.get("ratio"),
        "offload_ratio_expected": expected_ratio,
        "offload_exact": orow.get("ratio") == expected_ratio,
        "handoff_frames": hrow.get("frames"),
        "handoff_bytes": hrow.get("bytes"),
        "handoff_bytes_per_frame":
            (hrow.get("bytes", 0) / max(hrow.get("frames", 0), 1))
            if hrow else None,
        "handoff_bytes_exact":
            bool(hrow) and hrow.get("frames", 0) > 0
            and hrow.get("bytes") == hrow.get("frames") * crop_bytes,
        "handoff_route": f"{hrow.get('from')}→{hrow.get('to')}"
        if hrow else None,
        "handoff_depth_end": hrow.get("depth"),
        "offload_parity":
            last_split is not None and last_fused is not None
            and last_split["digest"] == last_fused["digest"],
        "stage_pools": last_split["stage_pools"] if last_split else [],
    }
    if result["virtual_cpu_mesh"]:
        result["note"] = (
            "virtual devices share one physical CPU: the split/fused "
            "ratio measures the code path (handoff + per-stage pools), "
            "not ICI bandwidth — the split_pipeline projection remains "
            "a model until this bench runs on a real multi-chip host")
    if metrics:
        result["metrics"] = REGISTRY.snapshot()
    _scaling_split_measured(result)
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    return result


def _scaling_split_measured(result: dict,
                            path: str = "SCALING_MODEL.json") -> None:
    """Fold the cascade bench into ``SCALING_MODEL.json``'s
    ``split_pipeline`` object as a ``measured`` block — the projection
    (58k fps, ici_efficiency 1.0) stays labeled a model, but it now
    sits next to a measurement of the same pipeline-split claim through
    the real element path, mirroring what the data-parallel
    ``measured`` block did for the top-level projection."""
    try:
        with open(path) as f:
            sm = json.load(f)
    except (OSError, ValueError):
        return  # no projection file here: the bench result stands alone
    sp = sm.setdefault("split_pipeline", {})
    sp["measured"] = {
        "bench": "BENCH_cascade.json",
        "scenario": "cascade",
        "path": "detector devices=0-3 → tensor_crop → tensor_if "
                "offload=then → classifier devices=4-7 "
                "(share-model pools per stage, device-channel handoff)",
        "platform": result["platform"],
        "virtual_cpu_mesh": result["virtual_cpu_mesh"],
        "fps_split": result["fps_split"],
        "fps_fused": result["fps_fused"],
        "split_vs_fused": result["split_vs_fused"],
        "crossings_per_frame": result["crossings_per_frame"],
        "offload_ratio": result["offload_ratio"],
        "handoff_bytes_per_frame": result["handoff_bytes_per_frame"],
        "note": ("virtual CPU mesh: validates the split serving code "
                 "path (d2d handoff, per-stage pools), not the "
                 "silicon — the ici_efficiency=1.0 projection remains "
                 "a model until this bench runs on a real slice"
                 if result["virtual_cpu_mesh"] else
                 "measured on real devices through the real split "
                 "serving path"),
    }
    with open(path, "w") as f:
        json.dump(sm, f, indent=1)


BATCHING_FRAMES = int(os.environ.get("BENCH_BATCHING_FRAMES", "512"))
BATCHING_BATCH = int(os.environ.get("BENCH_BATCHING_BATCH", "16"))


def _batching_run(model: str, spec, n: int, batch: int,
                  capture_metrics: bool = False):
    """One micro-batching A/B leg: appsrc ! queue ! tensor_filter
    batch=N ! appsink on the CPU backend.  Frames are tiny, so the run
    is DISPATCH-bound — exactly the regime micro-batching coalesces.
    Returns (fps, dispatches, frames, occupancy)."""
    from nnstreamer_tpu.core import Buffer
    from nnstreamer_tpu.elements.basic import AppSink, AppSrc, Queue
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.runtime import Pipeline

    shape = spec.tensors[0].shape
    frames = [Buffer.of(np.full(shape, float(i % 7), np.float32), pts=i)
              for i in range(n)]
    p = Pipeline()
    src = AppSrc(name="src", spec=spec, max_buffers=n + batch + 4)
    q = Queue(name="q", max_size_buffers=n + batch + 4)
    # a single pinned bucket: partial windows (a scheduling hiccup can
    # deadline-close one mid-run) pad up to `batch` instead of JIT-ing
    # a smaller bucket's executable inside the timed region
    flt = TensorFilter(name="net", framework="jax-xla", model=model,
                       batch=batch, batch_timeout_ms=5.0,
                       batch_buckets=str(batch))
    sink = AppSink(name="out", max_buffers=n + batch + 4)
    p.add(src, q, flt, sink).link(src, q, flt, sink)
    with p:
        # warmup: one full window — with the pinned bucket this is the
        # ONLY executable any later window can need
        for i in range(batch):
            src.push_buffer(frames[i])
        _pull(sink, "batching warmup")
        for _ in range(batch - 1):
            _pull(sink, "batching warmup")
        d0 = flt.invoke_stats.total_invoke_num
        f0 = flt.invoke_stats.total_frame_num
        t0 = time.perf_counter()
        for b in frames:
            src.push_buffer(b)
        last = None
        for _ in range(n):
            last = _pull(sink, "batching")
        np.asarray(last.tensors[0].np())  # completion, not dispatch-ack
        dt = time.perf_counter() - t0
        dispatches = flt.invoke_stats.total_invoke_num - d0
        frames_done = flt.invoke_stats.total_frame_num - f0
        extras = {}
        if capture_metrics:
            from nnstreamer_tpu.obs.metrics import REGISTRY

            extras["metrics"] = REGISTRY.snapshot()
        src.end_of_stream()
        p.wait_eos(timeout=30)
    occ = frames_done / dispatches if dispatches else 0.0
    return n / dt, dispatches, frames_done, occ, extras


def bench_batching(out_path: str = "BENCH_batching.json",
                   metrics: bool = False):
    """``--batching``: dispatch-coalescing A/B on the CPU backend — the
    ISSUE-2 acceptance scenario.  A deliberately tiny model makes the
    per-dispatch Python+XLA overhead dominate; batch=1 pays it per
    frame, batch=N amortizes it N ways.  Reports frames/s AND
    dispatches/s for both legs and writes the JSON line to
    ``BENCH_batching.json``."""
    from nnstreamer_tpu.core import TensorsSpec
    from nnstreamer_tpu.filters.jax_xla import register_model

    n, batch = BATCHING_FRAMES, BATCHING_BATCH
    model = register_model("bench_batching_tiny",
                           lambda x: x * 2.0 + 1.0,
                           in_shapes=[(16,)], in_dtypes=np.float32)
    spec = TensorsSpec.from_shapes([(16,)], np.float32)
    fps1, disp1, frames1, _, _ = _batching_run(model, spec, n, 1)
    fpsN, dispN, framesN, occ, extras = _batching_run(
        model, spec, n, batch, capture_metrics=metrics)
    result = {
        "metric": "micro-batched tensor_filter dispatch coalescing "
                  f"(CPU backend, {n} frames, dispatch-bound model, "
                  "appsrc ! queue ! jax-xla ! appsink)",
        "value": round(fpsN / fps1, 3) if fps1 else None,
        "unit": "x frames/s vs batch=1",
        "vs_baseline": round(fpsN / fps1, 3) if fps1 else None,
        "frames": n,
        "batch": batch,
        "batch1_fps": round(fps1, 1),
        "batch1_dispatches": disp1,
        "batched_fps": round(fpsN, 1),
        "batched_dispatches": dispN,
        "dispatch_reduction": round(framesN / dispN, 2) if dispN else None,
        "batch_occupancy": round(occ, 2),
        "coalescing": dispN < framesN,
        "note": "frames are 16-float vectors: per-dispatch overhead "
                "dominates by construction, isolating what coalescing "
                "buys independent of model compute",
    }
    if extras:
        result["metrics"] = extras["metrics"]
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    return result


SERVE_PIPES = int(os.environ.get("BENCH_SERVE_PIPES", "8"))
SERVE_FRAMES = int(os.environ.get("BENCH_SERVE_FRAMES", "64"))
SERVE_BATCH = int(os.environ.get("BENCH_SERVE_BATCH", "16"))
SERVE_OUTSTANDING = int(os.environ.get("BENCH_SERVE_OUTSTANDING", "1"))
SERVE_TIMEOUT_MS = float(os.environ.get("BENCH_SERVE_TIMEOUT_MS", "2.0"))


def _serve_leg(model: str, spec, share: bool, capture_metrics: bool = False):
    """One shared-model serving A/B leg: SERVE_PIPES identical
    ``appsrc ! queue ! tensor_filter ! appsink`` pipelines on the SAME
    tiny model, each driven closed-loop by its own client with
    SERVE_OUTSTANDING frames in flight (the Clipper setting: N request
    streams, each with a small window of outstanding requests — no
    single stream can fill a batch window by itself).

    share=False is the per-element regime: every pipeline holds its own
    model instance and its own batch window, which closes on the
    batch-timeout deadline carrying only that client's few outstanding
    frames.  share=True pools them: one instance, one CROSS-pipeline
    window that the adaptive batcher flushes whenever the device goes
    idle.  Returns (fps, dispatches, frames_total, occupancy,
    stream_occupancy)."""
    import threading

    from nnstreamer_tpu.core import Buffer
    from nnstreamer_tpu.elements.basic import AppSink, AppSrc, Queue
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.runtime import Pipeline

    shape = spec.tensors[0].shape
    pipes = []
    for i in range(SERVE_PIPES):
        p = Pipeline(name=f"serve{i}")
        src = AppSrc(name="src", spec=spec,
                     max_buffers=SERVE_OUTSTANDING + 4)
        q = Queue(name="q", max_size_buffers=SERVE_FRAMES + 4)
        # one pinned bucket: every window pads to `batch`, so exactly
        # ONE executable exists per leg (compiled in warmup, shared by
        # every pipeline when share=True)
        flt = TensorFilter(name="net", framework="jax-xla", model=model,
                           batch=SERVE_BATCH,
                           batch_timeout_ms=SERVE_TIMEOUT_MS,
                           batch_buckets=str(SERVE_BATCH),
                           share_model=share)
        sink = AppSink(name="out", max_buffers=SERVE_FRAMES + 4)
        p.add(src, q, flt, sink).link(src, q, flt, sink)
        p.start()
        pipes.append((p, src, flt, sink))

    def run_client(src, sink, n, errs):
        sent = got = inflight = 0
        try:
            while got < n:
                while sent < n and inflight < SERVE_OUTSTANDING:
                    src.push_buffer(Buffer.of(
                        np.full(shape, float(sent % 7), np.float32),
                        pts=sent))
                    sent += 1
                    inflight += 1
                if sink.pull(timeout=60) is None:
                    raise RuntimeError(
                        f"serve client stalled at {got}/{n}")
                got += 1
                inflight -= 1
        except Exception as e:  # noqa: BLE001 - surface on the main thread
            errs.append(e)

    def run_round(n):
        errs: list = []
        threads = [threading.Thread(target=run_client,
                                    args=(src, sink, n, errs))
                   for _, src, _, sink in pipes]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            raise errs[0]
        return time.perf_counter() - t0

    def dispatches():
        if share:
            return pipes[0][2].pool.stats.total_invoke_num
        return sum(flt.invoke_stats.total_invoke_num
                   for _, _, flt, _ in pipes)

    # warmup round: compiles the (single) bucket executable per instance
    # and settles the windows, outside the timed region
    run_round(max(SERVE_OUTSTANDING, 2))
    d0 = dispatches()
    dt = run_round(SERVE_FRAMES)
    disp = dispatches() - d0
    frames_total = SERVE_PIPES * SERVE_FRAMES
    occ = frames_total / disp if disp else 0.0
    stream_occ = pipes[0][2].pool_stream_occupancy if share else 1.0
    extras = {}
    if capture_metrics:
        # registry snapshot while the pipelines/pool are still live —
        # the ground-truth cross-check for `--metrics`: the exported
        # pool dispatch counter must equal the bench's own invoke count
        # read at the same (idle, settled) moment
        from nnstreamer_tpu.obs.metrics import REGISTRY

        extras["dispatches_total"] = dispatches()
        extras["metrics"] = REGISTRY.snapshot()
    for p, src, _, _ in pipes:
        src.end_of_stream()
    for p, _, _, _ in pipes:
        p.wait_eos(timeout=30)
        p.stop()
    return frames_total / dt, disp, frames_total, occ, stream_occ, extras


def bench_serving(out_path: str = "BENCH_serving.json",
                  metrics: bool = False):
    """``--serve``: cross-pipeline batch-coalescing A/B on the CPU
    backend — the ISSUE-3 acceptance scenario.  N concurrent pipelines
    serve the SAME dispatch-bound model; the unshared leg pays N model
    copies and N nearly-empty deadline-closed windows, the shared leg
    one pooled instance and one adaptive cross-stream window.  Writes
    ``BENCH_serving.json``."""
    from nnstreamer_tpu.core import TensorsSpec
    from nnstreamer_tpu.filters.jax_xla import register_model

    model = register_model("bench_serving_tiny",
                           lambda x: x * 2.0 + 1.0,
                           in_shapes=[(16,)], in_dtypes=np.float32)
    spec = TensorsSpec.from_shapes([(16,)], np.float32)
    fps_u, disp_u, frames, _, _, _ = _serve_leg(model, spec, share=False)
    fps_s, disp_s, _, occ_s, streams_s, extras = _serve_leg(
        model, spec, share=True, capture_metrics=metrics)
    result = {
        "metric": "shared-model serving: cross-pipeline batch coalescing "
                  f"({SERVE_PIPES} concurrent pipelines x same model, "
                  f"closed-loop {SERVE_OUTSTANDING} outstanding/client, "
                  "CPU backend, dispatch-bound model)",
        "value": round(fps_s / fps_u, 3) if fps_u else None,
        "unit": f"x frames/s vs unshared batch={SERVE_BATCH}",
        "vs_baseline": round(fps_s / fps_u, 3) if fps_u else None,
        "pipes": SERVE_PIPES,
        "frames_total": frames,
        "batch": SERVE_BATCH,
        "outstanding_per_client": SERVE_OUTSTANDING,
        "batch_timeout_ms": SERVE_TIMEOUT_MS,
        "unshared_fps": round(fps_u, 1),
        "unshared_dispatches": disp_u,
        "shared_fps": round(fps_s, 1),
        "shared_dispatches": disp_s,
        "dispatch_reduction": round(disp_u / disp_s, 2) if disp_s else None,
        "shared_frames_per_dispatch": round(occ_s, 2),
        "shared_stream_occupancy": round(streams_s, 2),
        "coalescing_cross_stream": disp_s < frames,
        "note": "no client can fill a window alone (closed loop, few "
                "outstanding): the unshared leg deadline-flushes "
                "nearly-empty per-pipeline buckets while the shared leg "
                "coalesces all streams into one adaptive window — the "
                "regime of ISSUE-3 / Clipper NSDI'17",
    }
    if extras:
        # `--metrics`: embed the obs registry snapshot (the passive,
        # pull-time view) plus the bench's own cumulative dispatch count
        # read at the same moment, so CI can assert they agree
        result["shared_dispatches_total"] = extras["dispatches_total"]
        result["metrics"] = extras["metrics"]
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    return result


EDGE_FRAMES = int(os.environ.get("BENCH_EDGE_FRAMES", "256"))
EDGE_OUTSTANDING = int(os.environ.get("BENCH_EDGE_OUTSTANDING", "8"))


def bench_edge(out_path: str = "BENCH_edge.json"):
    """``--edge``: loopback-TCP tensor_query round-trip bench — the
    ground truth for the ``nns_edge_*`` link metrics (ISSUE-5).  Runs a
    client pipeline against a serversrc→filter→serversink pipeline over
    real sockets, then cross-checks the exported per-link byte counters
    against independently re-packed frame sizes (exact equality: the
    wire codec is deterministic) and reports the RTT distribution the
    LINK row in ``nns-top`` renders."""
    from nnstreamer_tpu.core import Buffer, TensorsSpec
    from nnstreamer_tpu.edge.wire import MSG_QUERY, MSG_REPLY, EdgeMessage
    from nnstreamer_tpu.elements.basic import AppSink, AppSrc
    from nnstreamer_tpu.filters.custom import register_custom_easy
    from nnstreamer_tpu.obs.metrics import REGISTRY, LinkMetrics
    from nnstreamer_tpu.runtime import Pipeline
    from nnstreamer_tpu.runtime.registry import make

    LinkMetrics.clear_all()
    spec = TensorsSpec.parse("16:1", "float32")
    register_custom_easy("bench_edge_x2", lambda xs: [xs[0] * 2.0],
                         in_spec=spec, out_spec=spec)
    srv = Pipeline(name="edge-bench-server")
    qsrc = make("tensor_query_serversrc", el_name="qsrc",
                connect_type="tcp", host="127.0.0.1", port=0, id=93)
    flt = make("tensor_filter", el_name="f", framework="custom-easy",
               model="bench_edge_x2")
    qsink = make("tensor_query_serversink", el_name="qsink", id=93)
    srv.add(qsrc, flt, qsink).link(qsrc, flt, qsink)
    srv.start()

    cli = Pipeline(name="edge-bench-client")
    src = AppSrc(name="src", spec=spec, max_buffers=EDGE_OUTSTANDING + 4)
    q = make("tensor_query_client", el_name="qcli", host="127.0.0.1",
             port=qsrc.port, connect_type="tcp", timeout=30000,
             max_request=EDGE_OUTSTANDING,
             caps="other/tensors,format=static,num_tensors=1,"
                  "dimensions=16:1,types=float32")
    sink = AppSink(name="out", max_buffers=EDGE_FRAMES + 4)
    cli.add(src, q, sink).link(src, q, sink)
    cli.start()
    frames = [Buffer.of(np.full((1, 16), float(i % 11), np.float32),
                        pts=i) for i in range(EDGE_FRAMES)]
    t0 = time.perf_counter()
    sent = got = 0
    while got < EDGE_FRAMES:
        while sent < EDGE_FRAMES and sent - got < EDGE_OUTSTANDING:
            src.push_buffer(frames[sent])
            sent += 1
        if sink.pull(timeout=60) is None:
            raise RuntimeError(f"edge bench stalled at {got}")
        got += 1
    dt = time.perf_counter() - t0
    snap = REGISTRY.snapshot()
    link = [r for r in snap["links"]
            if r["kind"] == "query" and r["link"] == "qcli"][0]
    src.end_of_stream()
    cli.wait_eos(timeout=30)
    cli.stop()
    srv.stop()
    # ground truth: re-pack the SAME messages the client/server framed
    # (4-byte length prefix + wire bytes); replies echo seq/client_id=1
    # and carry the same-sized float32 payload back
    tx_truth = sum(
        4 + len(EdgeMessage.from_buffer(MSG_QUERY, b, seq=i + 1).pack())
        for i, b in enumerate(frames))
    reply = EdgeMessage.from_buffer(MSG_REPLY, frames[0], client_id=1,
                                    seq=1)
    rx_truth = EDGE_FRAMES * (4 + len(reply.pack()))
    result = {
        "metric": "edge link observability: loopback-TCP tensor_query "
                  f"round-trips ({EDGE_FRAMES} frames, "
                  f"{EDGE_OUTSTANDING} outstanding)",
        "value": round(link["rtt"]["mean_us"], 1)
        if link["rtt"]["mean_us"] else None,
        "unit": "µs mean round-trip (client-observed, incl. server)",
        "frames": EDGE_FRAMES,
        "frames_per_s": round(EDGE_FRAMES / dt, 1),
        "tx_bytes": link["tx_bytes"],
        "rx_bytes": link["rx_bytes"],
        "tx_bytes_truth": tx_truth,
        "rx_bytes_truth": rx_truth,
        "bytes_exact": link["tx_bytes"] == tx_truth
        and link["rx_bytes"] == rx_truth,
        "tx_msgs": link["tx_msgs"],
        "rx_msgs": link["rx_msgs"],
        "timeouts": link["timeouts"],
        "reconnects": link["reconnects"],
        "rtt_mean_us": link["rtt"]["mean_us"],
        "link": link,
        "note": "tx/rx byte counters must EQUAL the re-packed framed "
                "sizes — the LinkMetrics hook sits at the socket "
                "framing layer, so any drift is an accounting bug "
                "(nns-top LINK rows render these numbers)",
    }
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    return result


# -- open-loop SLO bench (--openloop → BENCH_slo.json) ------------------------

SLO_PIPES = int(os.environ.get("BENCH_SLO_PIPES", "6"))
SLO_HIGH = int(os.environ.get("BENCH_SLO_HIGH", "2"))
SLO_FRAMES = int(os.environ.get("BENCH_SLO_FRAMES", "240"))
SLO_BATCH = int(os.environ.get("BENCH_SLO_BATCH", "8"))
SLO_TIMEOUT_MS = float(os.environ.get("BENCH_SLO_TIMEOUT_MS", "2.0"))
#: how long each open-loop leg OFFERS load: frames per stream scale
#: with the arrival rate so overload lasts long enough for the
#: admission controller's latency window to see it
SLO_LEG_S = float(os.environ.get("BENCH_SLO_LEG_S", "5.0"))


def _slo_build_pipes(model, spec, slo_ms, prios, queue_size=64):
    from nnstreamer_tpu.elements.basic import AppSink, AppSrc, Queue
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.runtime import Pipeline

    pipes = []
    for i, prio in enumerate(prios):
        p = Pipeline(name=f"slo{i}-{prio}")
        src = AppSrc(name="src", spec=spec, max_buffers=queue_size)
        q = Queue(name="q", max_size_buffers=queue_size)
        # per-class EDF deadlines: the high class's tighter deadline
        # means window formation prefers it whenever the window is
        # contended, independent of the shedding decision
        dl = 0.0
        if slo_ms > 0:
            dl = 0.5 * slo_ms if prio == "high" else 2.0 * slo_ms
        flt = TensorFilter(name="net", framework="jax-xla", model=model,
                           batch=SLO_BATCH,
                           batch_timeout_ms=SLO_TIMEOUT_MS,
                           batch_buckets=str(SLO_BATCH), share_model=True,
                           slo_ms=slo_ms, priority=prio, deadline_ms=dl)
        sink = AppSink(name="out", max_buffers=8 * SLO_FRAMES + 16)
        p.add(src, q, flt, sink).link(src, q, flt, sink)
        p.start()
        pipes.append({"pipe": p, "src": src, "q": q, "flt": flt,
                      "sink": sink, "prio": prio})
    return pipes


def _slo_teardown(pipes):
    for e in pipes:
        e["src"].end_of_stream()
    for e in pipes:
        e["pipe"].wait_eos(timeout=30, raise_on_error=False)
        e["pipe"].stop()


def _slo_warmup(pipes, spec, rounds=2):
    """Compile the bucket executable and settle the windows OUTSIDE the
    timed region (a fresh pool entry pays XLA compile on its first
    window — that must not contaminate the latency signal or arm the
    admission controller spuriously)."""
    from nnstreamer_tpu.core import Buffer

    entry = pipes[0]["flt"].pool
    adm = entry.admission if entry is not None else None
    real_slo = None
    if adm is not None:
        # no shedding while the executable compiles: warmup frames must
        # all come back, and the compile stall must not arm the
        # controller before real traffic starts
        real_slo = adm.slo_s
        adm.slo_s = float("inf")
    shape = spec.tensors[0].shape
    arr = np.zeros(shape, np.float32)
    for _ in range(rounds):
        for e in pipes:
            for i in range(SLO_BATCH):
                e["src"].push_buffer(Buffer.of(arr, pts=i), timeout=10)
        for e in pipes:
            for _i in range(SLO_BATCH):
                if e["sink"].pull(timeout=60) is None:
                    raise RuntimeError("SLO bench warmup stalled")
    if adm is not None:
        # drop the compile-inflated latencies (deque AND the exported-
        # histogram delta window), restore the real SLO
        adm.reset_signal()
        adm.slo_s = real_slo


def _slo_closed_loop(model, spec, frames):
    """Sustainable-rate probe: every stream closed-loop (full-window
    outstanding, small queues, admission off).  Returns (total fps,
    p99 latency s)."""
    import threading

    from nnstreamer_tpu.core import Buffer

    shape = spec.tensors[0].shape
    pipes = _slo_build_pipes(model, spec, 0.0,
                             ["normal"] * SLO_PIPES, queue_size=8)
    _slo_warmup(pipes, spec)
    lats, errs = [], []
    lat_lock = threading.Lock()

    # enough outstanding per stream to FILL the shared windows: batch
    # capacity rises with occupancy, so a low-occupancy probe would
    # understate the sustainable rate by up to the batch factor
    outstanding = 2 * SLO_BATCH

    def client(e):
        try:
            sent = got = 0
            ts = {}
            while got < frames:
                while sent < frames and sent - got < outstanding:
                    ts[sent] = time.monotonic()
                    e["src"].push_buffer(Buffer.of(
                        np.zeros(shape, np.float32), pts=sent), timeout=10)
                    sent += 1
                b = e["sink"].pull(timeout=30)
                if b is None:
                    raise RuntimeError("closed-loop probe stalled")
                with lat_lock:
                    lats.append(time.monotonic() - ts.pop(b.pts))
                got += 1
        except Exception as exc:  # noqa: BLE001
            errs.append(exc)

    threads = [threading.Thread(target=client, args=(e,)) for e in pipes]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    _slo_teardown(pipes)
    if errs:
        raise errs[0]
    lats.sort()
    p99 = lats[min(int(0.99 * len(lats)), len(lats) - 1)] if lats else 0.0
    return SLO_PIPES * frames / dt, p99


def _slo_open_loop_leg(model, spec, slo_ms, prios, rates, frames,
                       seed, bursty=False):
    """One open-loop leg: per-stream Poisson (optionally bursty)
    arrivals — ``rates[i]`` / ``frames[i]`` for pipe ``i``.  Returns
    per-priority accounting + latency percentiles."""
    import queue as _pyq
    import random
    import threading

    from nnstreamer_tpu.core import Buffer

    shape = spec.tensors[0].shape
    pipes = _slo_build_pipes(model, spec, slo_ms, prios)
    _slo_warmup(pipes, spec)
    entry = pipes[0]["flt"].pool
    shed0 = entry.admission.snapshot() if entry.admission else None
    stop = threading.Event()
    max_qdepth = [0]

    for e, rate, n in zip(pipes, rates, frames):
        e.update(send_ts=[0.0] * n, lats=[], ingress_dropped=0,
                 delivered=0, rate=rate, frames=n)

    def producer(e, idx):
        rng = random.Random(seed + idx)
        arr = np.zeros(shape, np.float32)
        rate = e["rate"]
        # absolute arrival schedule: sleep-until-next (not
        # sleep-for-gap) so Python's sleep overhead cannot silently
        # deflate the offered rate — a producer that falls behind
        # catches up with back-to-back arrivals, like real traffic
        t_next = time.monotonic()
        for i in range(e["frames"]):
            if rate > 0:
                # Poisson gaps; in bursty mode every 40th arrival
                # opens a burst of 4 back-to-back frames
                if not (bursty and i % 40 and (i % 40) < 4):
                    t_next += rng.expovariate(rate)
                delay = t_next - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
            e["send_ts"][i] = time.monotonic()
            try:
                # open loop: an arrival NEVER waits for the server —
                # a full ingress queue is a visible drop, not a stall
                e["src"].push_buffer(Buffer.of(arr, pts=i), timeout=0)
            except _pyq.Full:
                e["ingress_dropped"] += 1

    def consumer(e):
        while not stop.is_set():
            b = e["sink"].pull(timeout=0.1)
            if b is None:
                continue
            e["lats"].append(time.monotonic() - e["send_ts"][b.pts])
            e["delivered"] += 1

    producers = [threading.Thread(target=producer, args=(e, i))
                 for i, e in enumerate(pipes)]
    consumers = [threading.Thread(target=consumer, args=(e,))
                 for e in pipes]
    t0 = time.perf_counter()
    for t in consumers + producers:
        t.start()
    for t in producers:
        t.join()
    # drain: wait until every offered frame is accounted (delivered,
    # shed, or dropped at ingress) or the drain bound passes
    drain_deadline = time.monotonic() + 30.0
    while time.monotonic() < drain_deadline:
        max_qdepth[0] = max(max_qdepth[0],
                            max(e["q"].current_level_buffers
                                for e in pipes))
        shed_now = entry.admission.total_shed if entry.admission else 0
        shed_base = (sum(shed0["shed"].values())
                     + sum(shed0["shed_queue_full"].values())) \
            if shed0 else 0
        accounted = sum(e["delivered"] + e["ingress_dropped"]
                        for e in pipes) + (shed_now - shed_base)
        if accounted >= sum(frames):
            break
        time.sleep(0.05)
    stop.set()
    for t in consumers:
        t.join()
    wall = time.perf_counter() - t0
    shed1 = entry.admission.snapshot() if entry.admission else None
    _slo_teardown(pipes)

    slo_s = slo_ms / 1e3
    out = {}
    for prio in sorted(set(prios)):
        mine = [e for e in pipes if e["prio"] == prio]
        lats = sorted(x for e in mine for x in e["lats"])
        delivered = sum(e["delivered"] for e in mine)
        within = sum(1 for x in lats if x <= slo_s)
        shed = 0
        if shed0 is not None and shed1 is not None:
            for table in ("shed", "shed_queue_full"):
                shed += shed1[table].get(prio, 0) - \
                    shed0[table].get(prio, 0)
        out[prio] = {
            "streams": len(mine),
            "offered": sum(e["frames"] for e in mine),
            "rate_per_stream": round(mine[0]["rate"], 1),
            "delivered": delivered,
            "within_slo": within,
            "goodput_fps": round(within / wall, 1),
            "shed": shed,
            "ingress_dropped": sum(e["ingress_dropped"] for e in mine),
            "p50_ms": round(lats[len(lats) // 2] * 1e3, 2)
            if lats else None,
            "p99_ms": round(
                lats[min(int(0.99 * len(lats)), len(lats) - 1)] * 1e3, 2)
            if lats else None,
        }
        out[prio]["accounted"] = (
            out[prio]["delivered"] + out[prio]["shed"]
            + out[prio]["ingress_dropped"] >= out[prio]["offered"])
    return {"wall_s": round(wall, 2),
            "offered_fps": round(sum(rates), 1),
            "max_queue_depth": max_qdepth[0], "classes": out}


def bench_openloop(out_path: str = "BENCH_slo.json"):
    """``--openloop``: open-loop (Poisson/bursty) load against the
    SLO-aware shared serving path — goodput-under-SLO curves instead of
    closed-loop peak fps.  The acceptance shape: at 2x the sustainable
    arrival rate, load-shedding protects the high-priority class (its
    goodput stays near uncontended) while low-priority frames shed
    VISIBLY (counters nonzero) and queues stay bounded."""
    from nnstreamer_tpu.core import TensorsSpec
    from nnstreamer_tpu.filters.jax_xla import register_model

    # a service-BOUND model (chained matmuls: real per-frame compute,
    # CPU-scaled): with the full-occupancy probe below, the measured
    # sustainable rate tracks true capacity closely enough that 2x is
    # genuine overload
    import jax.numpy as jnp

    w = np.asarray(
        np.random.RandomState(7).randn(512, 512) * 0.05, np.float32)

    def _slo_model(x):
        y = x
        for _ in range(40):
            y = jnp.tanh(y @ w)
        return y

    model = register_model("bench_slo_service", _slo_model,
                           in_shapes=[(512,)], in_dtypes=np.float32)
    spec = TensorsSpec.from_shapes([(512,)], np.float32)
    prios = ["high"] * SLO_HIGH + ["low"] * (SLO_PIPES - SLO_HIGH)

    sustainable_fps, p99_closed = _slo_closed_loop(
        model, spec, max(SLO_FRAMES // 4, 32))
    # SLO with generous headroom over the (occupancy-saturated)
    # closed-loop tail: sheds should begin only when overload — not
    # machine noise — pushes the p99 past it
    slo_ms = max(20.0, 3.0 * p99_closed * 1e3)

    # traffic shape: the HIGH class is a SMALL fixed slice of measured
    # capacity (10% per stream → 20% total here) and the LOW class
    # carries the overload multiplier — the realistic serving shape
    # (the premium class is small; overload comes from bulk traffic),
    # and the one that keeps the experiment meaningful on a noisy
    # host: the closed-loop probe can overestimate true open-loop
    # capacity by 2x on a contended container, and protection can
    # shed bulk load but cannot conjure capacity for a premium class
    # that is itself oversubscribed — at 20% the high class fits even
    # through that probe error
    high_rate = 0.10 * sustainable_fps
    n_low = SLO_PIPES - SLO_HIGH

    def leg_frames(rate):
        # offer load for ~SLO_LEG_S seconds (a fixed frame count at 2x
        # would finish offering before overload can even arm the
        # controller), floored so tiny rates still mean something
        return max(64, min(int(rate * SLO_LEG_S), 16 * SLO_FRAMES))

    def leg_rates(mult):
        low_total = max(mult * sustainable_fps
                        - SLO_HIGH * high_rate, 0.0)
        return [high_rate] * SLO_HIGH + [low_total / n_low] * n_low

    # uncontended reference: ONLY the high class, at the same
    # per-stream rate it sees in every leg (well under capacity → no
    # queueing, no shedding)
    uncontended = _slo_open_loop_leg(
        model, spec, slo_ms, ["high"] * SLO_HIGH,
        [high_rate] * SLO_HIGH,
        [leg_frames(high_rate)] * SLO_HIGH, seed=11)
    curve = {}
    # the top leg (4x) anchors the acceptance fields: it stays >= 2x
    # TRUE capacity even when the closed-loop probe mis-estimates by
    # 2x in either direction on a noisy host
    overload_mult = 4.0
    for mult in (0.5, 1.0, 2.0, overload_mult):
        rates = leg_rates(mult)
        curve[str(mult)] = _slo_open_loop_leg(
            model, spec, slo_ms, prios, rates,
            [leg_frames(r) for r in rates],
            seed=17 + int(mult * 10), bursty=(mult >= 2.0))

    top = curve[str(overload_mult)]
    high_ov = top["classes"]["high"]
    high_ref = uncontended["classes"]["high"]
    low_ov = top["classes"]["low"]
    goodput_ratio = high_ov["goodput_fps"] / high_ref["goodput_fps"] \
        if high_ref["goodput_fps"] else None
    result = {
        "metric": "open-loop SLO serving: goodput under p99 SLO with "
                  f"priority-aware load shedding ({SLO_PIPES} streams, "
                  f"{SLO_HIGH} high-priority, Poisson/bursty arrivals, "
                  "CPU backend)",
        "value": round(goodput_ratio, 3) if goodput_ratio else None,
        "unit": f"x high-priority goodput at {overload_mult:g}x "
                "overload vs uncontended",
        "sustainable_fps": round(sustainable_fps, 1),
        "closed_loop_p99_ms": round(p99_closed * 1e3, 2),
        "slo_ms": round(slo_ms, 1),
        "overload_mult": overload_mult,
        "uncontended_high": uncontended,
        "curve": curve,
        "high_goodput_ratio_at_overload": round(goodput_ratio, 3)
        if goodput_ratio else None,
        "shedding_active_at_overload": low_ov["shed"] > 0,
        "all_frames_accounted": all(
            c["accounted"]
            for leg in list(curve.values()) + [uncontended]
            for c in leg["classes"].values()),
        "note": "goodput = frames completing WITHIN the SLO per "
                f"second; at {overload_mult:g}x (>= 2x) the "
                "sustainable arrival rate the admission controller "
                "sheds low-priority frames (every shed counted + "
                "bus-warned) so the high class keeps its uncontended "
                "goodput; per-stream queues stay bounded "
                "(max_queue_depth)",
    }
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    return result


# -- host-execution profiler bench (--hostprof → BENCH_hostprof.json) ---------

HOSTPROF_STREAMS = os.environ.get("BENCH_HOSTPROF_STREAMS", "1,2,4,6")
HOSTPROF_HZ = float(os.environ.get("BENCH_HOSTPROF_HZ", "47.0"))
HOSTPROF_AB_PAIRS = int(os.environ.get("BENCH_HOSTPROF_AB_PAIRS", "3"))
#: total offered load as a fraction of the closed-loop sustainable
#: rate at the TOP ladder step — under capacity on every step, so the
#: element threads show a real run/wait mix instead of saturation
HOSTPROF_LOAD_FRAC = float(os.environ.get("BENCH_HOSTPROF_LOAD_FRAC",
                                          "0.5"))
HOSTPROF_LEG_S = float(os.environ.get("BENCH_HOSTPROF_LEG_S", "2.5"))


def _hostprof_inject(pipes, spec, rate, frames, seed):
    """Open-loop Poisson injection over PREBUILT, warmed pipes — the
    measurement window proper.  Build/compile/warmup/teardown stay
    outside it, so per-leg process-CPU deltas compare steady-state
    against steady-state (the A/B overhead signal is ~1e-2; a compile
    path inside the window would bury it).  Returns (delivered,
    dropped, sorted latencies)."""
    import queue as _pyq
    import random
    import threading

    from nnstreamer_tpu.core import Buffer

    shape = spec.tensors[0].shape
    stop = threading.Event()
    for e in pipes:
        e.update(send_ts=[0.0] * frames, lats=[], dropped=0,
                 delivered=0)

    def producer(e, idx):
        rng = random.Random(seed + idx)
        arr = np.zeros(shape, np.float32)
        t_next = time.monotonic()
        for i in range(frames):
            t_next += rng.expovariate(rate)
            delay = t_next - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            e["send_ts"][i] = time.monotonic()
            try:
                e["src"].push_buffer(Buffer.of(arr, pts=i), timeout=0)
            except _pyq.Full:
                e["dropped"] += 1

    def consumer(e):
        while not stop.is_set():
            b = e["sink"].pull(timeout=0.1)
            if b is not None:
                e["lats"].append(time.monotonic() - e["send_ts"][b.pts])
                e["delivered"] += 1

    producers = [threading.Thread(target=producer, args=(e, i))
                 for i, e in enumerate(pipes)]
    consumers = [threading.Thread(target=consumer, args=(e,))
                 for e in pipes]
    for t in consumers + producers:
        t.start()
    for t in producers:
        t.join()
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        if sum(e["delivered"] + e["dropped"]
               for e in pipes) >= len(pipes) * frames:
            break
        time.sleep(0.02)
    stop.set()
    for t in consumers:
        t.join()
    lats = sorted(x for e in pipes for x in e["lats"])
    return (sum(e["delivered"] for e in pipes),
            sum(e["dropped"] for e in pipes), lats)


def _hostprof_leg(model, spec, n, rate, frames, seed, prof_hz=0.0,
                  pipes=None):
    """One open-loop leg over ``n`` streams with the sampling profiler
    on (``prof_hz`` > 0) or off.  Accounts and the profiler table are
    reset after warmup, so every number is exactly this leg's
    steady-state window.  Pass prebuilt ``pipes`` to share one set
    across legs (the A/B pairs)."""
    from nnstreamer_tpu.obs import prof as _prof

    own = pipes is None
    if own:
        pipes = _slo_build_pipes(model, spec, 0.0, ["normal"] * n)
        _slo_warmup(pipes, spec)
    try:
        # delta, not reset: the element loops hold their account
        # objects from thread start, so the leg's share is
        # (after - before) per (pipeline, element)
        rows0 = {(r["pipeline"], r["element"]): r
                 for r in _prof.account_rows()}
        prof = _prof.PROFILER
        prof.clear()
        started = prof_hz > 0 and prof.configure(prof_hz).start()
        cpu0 = time.process_time()
        t0 = time.perf_counter()
        delivered, dropped, lats = _hostprof_inject(
            pipes, spec, rate, frames, seed)
        wall = time.perf_counter() - t0
        process_cpu_s = time.process_time() - cpu0
        live = {e["pipe"].name for e in pipes}
        rows = []
        for r in _prof.account_rows():
            if r["pipeline"] not in live:
                continue
            base = rows0.get((r["pipeline"], r["element"]))
            if base is not None:
                r = dict(r, **{k: round(r[k] - base[k], 6)
                               for k in ("cpu_s", "run_s", "wait_s",
                                         "iters")})
            rows.append(r)
        if started:
            prof.stop()
    finally:
        if own:
            _slo_teardown(pipes)
    samples = {f"{p}:{e}": c
               for (p, e), c in prof.element_samples().items()}
    total_cpu = sum(r["cpu_s"] for r in rows)
    run = sum(r["run_s"] for r in rows)
    wait = sum(r["wait_s"] for r in rows)
    return {
        "streams": n,
        "rate_per_stream": round(rate, 1),
        "offered": n * frames,
        "delivered": delivered,
        "ingress_dropped": dropped,
        "wall_s": round(wall, 2),
        "p50_ms": round(lats[len(lats) // 2] * 1e3, 2)
        if lats else None,
        "p99_ms": round(
            lats[min(int(0.99 * len(lats)), len(lats) - 1)] * 1e3, 2)
        if lats else None,
        "process_cpu_s": round(process_cpu_s, 4),
        # per-element host-CPU + run/wait attribution (obs/prof.py
        # accounting), joined with the sampler's per-element counts
        "elements": [dict(r, samples=samples.get(
            f"{r['pipeline']}:{r['element']}", 0)) for r in rows],
        "element_cpu_s": round(total_cpu, 4),
        # what fraction of the whole process's CPU the element loops
        # themselves account for (the rest: pool workers, XLA compute,
        # producers/consumers of the generator, the sampler)
        "attribution_coverage": round(total_cpu / process_cpu_s, 4)
        if process_cpu_s > 0 else None,
        # exactness invariant: summed per-thread CPU can NEVER exceed
        # the process-wide CPU clock (small tolerance for clock
        # granularity at leg edges)
        "attribution_exact":
            total_cpu <= process_cpu_s * 1.02 + 0.005,
        "wait_share": round(wait / (run + wait), 4)
        if run + wait > 0 else None,
        "profiler": prof.summary() if started else None,
        "sampler_self_cpu_frac":
            round(prof.self_cpu_s / process_cpu_s, 5)
            if started and process_cpu_s > 0 else None,
    }


def bench_hostprof(out_path: str = "BENCH_hostprof.json"):
    """``--hostprof``: the host-execution profiler under an open-loop
    generator swept over 1/2/4/6 streams.  Three acceptance angles:
    per-element host-CPU + run/wait attribution on every ladder step
    (element threads of an under-capacity open-loop pipeline are
    wait-dominated), profiler overhead by interleaved A/B legs
    (< 3% extra process CPU, plus the sampler's own thread-time as a
    deterministic bound), and attribution exactness (the per-element
    CPU sum never exceeds the ``time.process_time()`` delta)."""
    import statistics

    from nnstreamer_tpu.core import TensorsSpec
    from nnstreamer_tpu.filters.jax_xla import register_model
    from nnstreamer_tpu.obs import prof as _prof

    import jax.numpy as jnp

    w = np.asarray(
        np.random.RandomState(7).randn(512, 512) * 0.05, np.float32)

    def _slo_model(x):
        y = x
        for _ in range(40):
            y = jnp.tanh(y @ w)
        return y

    model = register_model("bench_slo_service", _slo_model,
                           in_shapes=[(512,)], in_dtypes=np.float32)
    spec = TensorsSpec.from_shapes([(512,)], np.float32)

    ladder = [int(x) for x in HOSTPROF_STREAMS.split(",") if x.strip()]
    sustainable_fps, _p99 = _slo_closed_loop(
        model, spec, max(SLO_FRAMES // 8, 16))
    # constant per-stream rate: total load scales with the ladder and
    # tops out at HOSTPROF_LOAD_FRAC of measured capacity
    rate = HOSTPROF_LOAD_FRAC * sustainable_fps / max(ladder)
    frames = max(48, int(rate * HOSTPROF_LEG_S))

    steps = {}
    for i, n in enumerate(ladder):
        steps[str(n)] = _hostprof_leg(model, spec, n, rate, frames,
                                      seed=23 + i, prof_hz=HOSTPROF_HZ)

    # interleaved A/B at the middle ladder step: ONE pipe set built
    # and warmed once, then per pair one profiler-on and one
    # profiler-off injection window, order alternating within pairs;
    # overhead = median extra process-CPU fraction (CPU, not wall: an
    # open-loop leg's wall clock is pinned by the arrival schedule and
    # cannot see overhead)
    n_ab = ladder[len(ladder) // 2]
    ratios, self_fracs = [], []
    ab_pipes = _slo_build_pipes(model, spec, 0.0, ["normal"] * n_ab)
    _slo_warmup(ab_pipes, spec)
    try:
        for pair in range(HOSTPROF_AB_PAIRS):
            order = ("on", "off") if pair % 2 else ("off", "on")
            cpu = {}
            for arm in order:
                leg = _hostprof_leg(
                    model, spec, n_ab, rate, frames, seed=101 + pair,
                    prof_hz=HOSTPROF_HZ if arm == "on" else 0.0,
                    pipes=ab_pipes)
                cpu[arm] = leg["process_cpu_s"]
                if arm == "on":
                    self_fracs.append(
                        leg["sampler_self_cpu_frac"] or 0.0)
            if cpu["off"] > 0:
                ratios.append(cpu["on"] / cpu["off"] - 1.0)
    finally:
        _slo_teardown(ab_pipes)
    ab_overhead_frac = max(0.0, statistics.median(ratios)) \
        if ratios else None
    sampler_self_cpu_frac = max(self_fracs) if self_fracs else None
    overhead_ok = (ab_overhead_frac is not None
                   and ab_overhead_frac < 0.03)

    top = steps[str(max(ladder))]
    elements = top["elements"]
    result = {
        "metric": "host-execution profiler: per-element CPU + "
                  "run/wait attribution, sampler overhead "
                  f"(open-loop generator, {HOSTPROF_STREAMS} streams, "
                  f"{HOSTPROF_HZ:g} Hz, CPU backend)",
        "value": top["wait_share"],
        "unit": "wait share of element threads at "
                f"{max(ladder)} streams",
        "sustainable_fps": round(sustainable_fps, 1),
        "rate_per_stream": round(rate, 1),
        "ladder": steps,
        "frames": sum(s["delivered"] for s in steps.values()),
        "wait_share": top["wait_share"],
        # every element row of the top step carries profiler samples:
        # the deterministic-thread-name registry join works
        "registry_join_ok": bool(elements) and all(
            r["samples"] > 0 for r in elements),
        "attribution_exact": all(
            s["attribution_exact"] for s in steps.values()),
        "attribution_coverage": top["attribution_coverage"],
        "ab_pairs": HOSTPROF_AB_PAIRS,
        "ab_overhead_frac": round(ab_overhead_frac, 4)
        if ab_overhead_frac is not None else None,
        "sampler_self_cpu_frac": sampler_self_cpu_frac,
        "overhead_ok": overhead_ok,
        "profiler_errors": _prof.PROFILER.errors_total,
        "note": "wait_share = wait/(run+wait) over the per-element "
                "accounts (queue-pop wait vs chain run); "
                "attribution_exact = per-element CPU sum <= "
                "process_time delta on every ladder step; overhead by "
                "interleaved A/B process-CPU pairs (median), with the "
                "sampler's own thread-time as a deterministic bound",
    }
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    return result


# -- chaos soak (--chaos → BENCH_chaos.json) ----------------------------------

CHAOS_FRAMES = int(os.environ.get("BENCH_CHAOS_FRAMES", "96"))
CHAOS_SEED = int(os.environ.get("BENCH_CHAOS_SEED", "20260803"))
CHAOS_OUTSTANDING = int(os.environ.get("BENCH_CHAOS_OUTSTANDING", "8"))


def _chaos_query_script(name, plan_spec, timeout_ms=800.0,
                        expect_timeouts=None, expect_reconnects=None,
                        frames=None, warmup_frames=0,
                        warmup_pace_s=0.0, pace_s=0.0):
    """One seeded fault script against a loopback-TCP tensor_query
    round-trip.  Asserts the recovery contract: EOS (or a clean bus
    error) within a wall-clock bound, and every sent frame accounted —
    delivered, timed out, or dropped at max-request, never silently
    lost.

    ``warmup_frames`` run CLEAN before the plan installs (the watch
    bench needs a pre-fault baseline for its drift rules, and an
    honest install timestamp for detection latency — returned as
    ``_fault_ts_mono``); ``warmup_pace_s`` spaces the warmup sends so
    the baseline spans enough sampler ticks.  ``plan_spec=None`` runs
    the whole script clean (the zero-false-positive leg)."""
    from nnstreamer_tpu import chaos
    from nnstreamer_tpu.core import Buffer, TensorsSpec
    from nnstreamer_tpu.elements.basic import AppSink, AppSrc
    from nnstreamer_tpu.filters.custom import register_custom_easy
    from nnstreamer_tpu.runtime import Pipeline
    from nnstreamer_tpu.runtime.registry import make

    frames = int(frames or CHAOS_FRAMES)
    warmup_frames = min(int(warmup_frames), frames)
    spec = TensorsSpec.parse("16:1", "float32")
    register_custom_easy("bench_chaos_x2", lambda xs: [xs[0] * 2.0],
                         in_spec=spec, out_spec=spec)
    srv = Pipeline(name=f"chaos-srv-{name}")
    qsrc = make("tensor_query_serversrc", el_name="qsrc",
                connect_type="tcp", host="127.0.0.1", port=0, id=94)
    flt = make("tensor_filter", el_name="f", framework="custom-easy",
               model="bench_chaos_x2")
    qsink = make("tensor_query_serversink", el_name="qsink", id=94)
    srv.add(qsrc, flt, qsink).link(qsrc, flt, qsink)
    srv.start()

    cli = Pipeline(name=f"chaos-cli-{name}")
    src = AppSrc(name="src", spec=spec, max_buffers=frames + 4)
    q = make("tensor_query_client", el_name="qcli", host="127.0.0.1",
             port=qsrc.port, connect_type="tcp", timeout=timeout_ms,
             max_request=CHAOS_OUTSTANDING,
             caps="other/tensors,format=static,num_tensors=1,"
                  "dimensions=16:1,types=float32")
    sink = AppSink(name="out", max_buffers=frames + 4)
    cli.add(src, q, sink).link(src, q, sink)
    cli.start()

    plan = None
    fault_ts = None
    t0 = time.perf_counter()
    sent = got = 0
    hard_deadline = time.monotonic() + 120.0

    def lost():
        return q.timeouts + q.dropped

    def pump(until, pace_s=0.0):
        nonlocal sent, got
        while got + lost() < until and \
                time.monotonic() < hard_deadline:
            while sent < until and \
                    sent - got - lost() < CHAOS_OUTSTANDING:
                src.push_buffer(Buffer.of(
                    np.full((1, 16), float(sent % 5), np.float32),
                    pts=sent))
                sent += 1
                if pace_s > 0:
                    time.sleep(pace_s)
            if sink.pull(timeout=0.25) is not None:
                got += 1

    try:
        if warmup_frames > 0:
            pump(warmup_frames, pace_s=warmup_pace_s)
        if plan_spec is not None:
            plan = chaos.install_plan(chaos.FaultPlan.parse(plan_spec))
            fault_ts = time.monotonic()
        pump(frames, pace_s=pace_s)
        # stop injecting before teardown so EOS drain isn't itself
        # chaos'd (the script proved its point; teardown must be clean)
        chaos.uninstall_plan()
        src.end_of_stream()
        eos_clean = cli.wait_eos(timeout=30, raise_on_error=False) \
            or cli.error is not None
        # late frames may still have flushed during the EOS drain
        while sink.pull(timeout=0.05) is not None:
            got += 1
        wall = time.perf_counter() - t0
    finally:
        chaos.uninstall_plan()
        cli.stop()
        srv.stop()

    counts = plan.counts() if plan is not None else {}
    metrics = q._metrics.snapshot() if q._metrics is not None else {}
    row = {
        "script": name,
        "plan": plan_spec,
        "frames": frames,
        "warmup_frames": warmup_frames,
        "sent": sent,
        "delivered": got,
        "timeouts": q.timeouts,
        "dropped_max_request": q.dropped,
        "reconnects": metrics.get("reconnects", 0),
        "bad_frames": metrics.get("bad_frames", 0),
        "injected": counts,
        "injected_total": plan.total_injected if plan is not None else 0,
        "wall_s": round(wall, 2),
        "eos_or_clean_error": bool(eos_clean),
        "hang": not eos_clean,
        "accounted": got + q.timeouts + q.dropped >= sent,
        "_fault_ts_mono": fault_ts,
    }
    if expect_timeouts is not None:
        row["expected_timeouts_seen"] = q.timeouts > 0
    if expect_reconnects is not None:
        row["expected_reconnects_seen"] = \
            metrics.get("reconnects", 0) > 0
    return row


def _chaos_invoke_script(name, plan_spec, expect_errors=False,
                         frames=None, warmup_frames=0, stat_ms=None,
                         pace_s=0.0):
    """Seeded model-path fault script against the shared serving pool:
    slow-invoke must lose nothing; fail-invoke must surface on EVERY
    sharing pipeline's bus (the _error_all / per-owner routing
    contract), with the lost windows visible as bus errors.

    ``warmup_frames`` per pipe run clean before the plan installs (see
    ``_chaos_query_script``); ``stat_ms`` tightens the filters'
    ``stat-sample-interval-ms`` so the pool latency gauge updates fast
    enough for the watch bench's drift rule to see the fault."""
    import threading

    from nnstreamer_tpu import chaos
    from nnstreamer_tpu.core import Buffer, TensorsSpec
    from nnstreamer_tpu.elements.basic import AppSink, AppSrc, Queue
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.runtime import Pipeline
    from nnstreamer_tpu.runtime.events import MessageKind
    from nnstreamer_tpu.filters.jax_xla import register_model

    model = register_model("bench_chaos_pool", lambda x: x + 1.0,
                           in_shapes=[(8,)], in_dtypes=np.float32)
    spec = TensorsSpec.from_shapes([(8,)], np.float32)
    n_pipes, frames = 3, int(frames or CHAOS_FRAMES // 2)
    warmup_frames = min(int(warmup_frames), frames)
    errors = []
    pipes = []
    for i in range(n_pipes):
        p = Pipeline(name=f"chaos-pool{i}")
        src = AppSrc(name="src", spec=spec, max_buffers=frames + 4)
        qe = Queue(name="q", max_size_buffers=frames + 4)
        flt = TensorFilter(name="net", framework="jax-xla", model=model,
                           batch=4, batch_timeout_ms=2.0,
                           batch_buckets="4", share_model=True,
                           stat_sample_interval_ms=stat_ms)
        sink = AppSink(name="out", max_buffers=frames + 4)
        p.add(src, qe, flt, sink).link(src, qe, flt, sink)
        p.bus.add_watch(
            lambda m: errors.append(m) if m.kind == MessageKind.ERROR
            else None)
        p.start()
        pipes.append((p, src, flt, sink))

    t0 = time.perf_counter()
    delivered = [0] * n_pipes
    fault_ts = None

    if warmup_frames > 0:
        # clean pre-fault traffic: pool opens, executables compile,
        # the latency gauge settles to its baseline (paced so the
        # rolling latency window flushes the compile spike and a
        # watchdog's sampler sees enough clean ticks)
        for i in range(n_pipes):
            _p, src, _f, _s = pipes[i]
            for n in range(warmup_frames):
                src.push_buffer(
                    Buffer.of(np.zeros((8,), np.float32), pts=n),
                    timeout=10)
                if pace_s > 0:
                    time.sleep(pace_s)
        deadline = time.monotonic() + 60.0
        for i in range(n_pipes):
            _p, _src, _f, sink = pipes[i]
            while delivered[i] < warmup_frames and \
                    time.monotonic() < deadline:
                if sink.pull(timeout=0.25) is not None:
                    delivered[i] += 1

    plan = chaos.install_plan(chaos.FaultPlan.parse(plan_spec))
    fault_ts = time.monotonic()

    def run(i):
        _p, src, _f, sink = pipes[i]
        for n in range(warmup_frames, frames):
            src.push_buffer(Buffer.of(np.zeros((8,), np.float32), pts=n),
                            timeout=10)
            if pace_s > 0:
                time.sleep(pace_s)
        deadline = time.monotonic() + 60.0
        while delivered[i] < frames and time.monotonic() < deadline:
            if sink.pull(timeout=0.25) is not None:
                delivered[i] += 1
            elif errors and expect_errors:
                # errored windows never demux: drain what's coming and
                # account the rest to the (visible) bus errors
                if sink.pull(timeout=1.0) is None:
                    break

    threads = [threading.Thread(target=run, args=(i,))
               for i in range(n_pipes)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    chaos.uninstall_plan()
    eos_clean = True
    for p, src, _f, _s in pipes:
        src.end_of_stream()
    for p, *_ in pipes:
        ok = p.wait_eos(timeout=30, raise_on_error=False)
        eos_clean = eos_clean and (ok or p.error is not None
                                   or bool(errors))
        p.stop()
    wall = time.perf_counter() - t0
    counts = plan.counts()
    total_delivered = sum(delivered)
    total_sent = n_pipes * frames
    row = {
        "script": name,
        "plan": plan_spec,
        "warmup_frames": warmup_frames,
        "sent": total_sent,
        "delivered": total_delivered,
        "bus_errors": len(errors),
        "_fault_ts_mono": fault_ts,
        "injected": counts,
        "injected_total": plan.total_injected,
        "wall_s": round(wall, 2),
        "eos_or_clean_error": bool(eos_clean),
        "hang": not eos_clean,
        # slow-invoke loses nothing; fail-invoke loses whole windows
        # but every loss maps to a bus error the apps saw
        "accounted": total_delivered >= total_sent
        if not expect_errors else
        (total_delivered < total_sent) == (len(errors) > 0),
    }
    if expect_errors:
        # how many distinct pipelines saw the error.  The poisoned
        # window errors on every owner that parked a frame in it —
        # how many owners that IS depends on window composition, so
        # the strict every-sharing-bus fan-out contract is proven by
        # the deterministic test instead
        # (tests/test_chaos.py::TestPoolFaults::
        #  test_fail_invoke_fans_out_to_every_sharing_bus)
        row["bus_error_sources"] = len({m.source for m in errors})
    return row


def bench_chaos(out_path: str = "BENCH_chaos.json"):
    """``--chaos``: the seeded fault-script soak — drop, delay,
    disconnect-flap, partition on the edge wire; slow-invoke and
    fail-invoke on the model path.  The contract under EVERY script:
    the pipelines reach EOS (or a clean bus error) within a bounded
    wall clock — zero hangs — and every frame is accounted for by a
    counter (delivered / timeout / max-request drop / bus error) —
    zero silent drops."""
    from nnstreamer_tpu.obs.metrics import REGISTRY, LinkMetrics

    LinkMetrics.clear_all()
    s = CHAOS_SEED
    scripts = [
        _chaos_query_script(
            "wire-drop", f"seed={s};drop:p=0.12,dir=tx,match=qcli",
            timeout_ms=600.0, expect_timeouts=True),
        _chaos_query_script(
            "wire-delay", f"seed={s + 1};delay:ms=20,p=0.3",
            timeout_ms=5000.0),
        _chaos_query_script(
            "disconnect-flap",
            f"seed={s + 2};disconnect:every=40,dir=tx,match=qcli",
            timeout_ms=2000.0, expect_reconnects=True),
        _chaos_query_script(
            "partition",
            f"seed={s + 3};partition:ms=400,every=50,match=qcli",
            timeout_ms=1500.0, expect_timeouts=True),
        _chaos_query_script(
            "wire-corrupt", f"seed={s + 4};corrupt:p=0.1,dir=tx",
            timeout_ms=800.0),
        _chaos_query_script(
            "wire-reorder",
            f"seed={s + 7};reorder:every=6,dir=tx,match=qcli",
            timeout_ms=800.0),
        _chaos_invoke_script(
            "slow-invoke", f"seed={s + 5};slow-invoke:ms=25,p=0.2"),
        _chaos_invoke_script(
            "fail-invoke", f"seed={s + 6};fail-invoke:every=12",
            expect_errors=True),
    ]
    for r in scripts:  # watch-bench plumbing, not a soak result
        r.pop("_fault_ts_mono", None)
    snap = REGISTRY.snapshot()
    chaos_metric = snap["metrics"].get("nns_chaos_injected_total", {})
    injected_exported = sum(
        x["value"] for x in chaos_metric.get("samples", []))
    result = {
        "metric": "chaos soak: seeded fault scripts vs the recovery "
                  "machinery (retry/backoff, failover resend-once, "
                  "timeout accounting, pool error fan-out)",
        "value": sum(1 for r in scripts if not r["hang"]
                     and r["accounted"]),
        "unit": f"of {len(scripts)} scripts with zero hangs AND zero "
                "silent drops",
        "seed": s,
        "scripts": scripts,
        "zero_hangs": all(not r["hang"] for r in scripts),
        "zero_silent_drops": all(r["accounted"] for r in scripts),
        "injected_total": sum(r["injected_total"] for r in scripts),
        "nns_chaos_injected_total_exported": injected_exported,
        "note": "each script runs under a hard wall-clock bound; "
                "'accounted' means delivered + timeouts + max-request "
                "drops (+ bus-errored windows for fail-invoke) covers "
                "every sent frame — the counters in the obs registry "
                "tell the whole story, nothing vanishes silently",
    }
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    return result


# -- chaos-detection bench (--watch → BENCH_watch.json) -----------------------

WATCH_FRAMES = int(os.environ.get("BENCH_WATCH_FRAMES", "96"))
WATCH_INTERVAL_S = float(os.environ.get("BENCH_WATCH_INTERVAL", "0.05"))


def _watched_script(script_fn, expect_rule, *args, **kwargs):
    """Run one chaos script with a fresh watchdog attached (default
    rule pack, in-process registry) and grade the detection: did ANY
    alert fire after the fault installed, how long did it take, and —
    the honesty checks — which rules fired, whether the EXPECTED one
    did, and how many alerts fired while traffic was still clean
    (pre-fault alerts are false positives, same as the clean leg's)."""
    from nnstreamer_tpu.obs.watch import Watch, default_rules

    w = Watch(rules=default_rules(), interval_s=WATCH_INTERVAL_S)
    w.start()
    try:
        row = script_fn(*args, **kwargs)
        # settle: a counter bumped in the script's last moments still
        # needs a sampler tick to become a rate
        time.sleep(max(0.2, 4 * WATCH_INTERVAL_S))
    finally:
        w.stop()
    fault_ts = row.pop("_fault_ts_mono", None)
    alerts = [dict(ev) for ev in w.alert_log]
    row["expected_rule"] = expect_rule
    if fault_ts is None:  # the clean leg: every alert is a lie
        row["alerts_fired"] = sorted({ev["rule"] for ev in alerts})
        row["false_positives"] = len(alerts)
        row["detected"] = None
        return row
    post = [ev for ev in alerts if ev["ts"] >= fault_ts]
    row["detected"] = bool(post)
    row["detection_latency_s"] = round(post[0]["ts"] - fault_ts, 3) \
        if post else None
    row["alerts_fired"] = sorted({ev["rule"] for ev in post})
    row["expected_rule_fired"] = expect_rule in row["alerts_fired"]
    row["pre_fault_alerts"] = len(alerts) - len(post)
    return row


def bench_watch(out_path: str = "BENCH_watch.json"):
    """``--watch``: chaos detection as a regression-gated number.  The
    seeded fault scripts of the chaos soak replay with an ``nns-watch``
    watchdog attached (default rule pack, nothing tuned per script),
    each with a clean warmup so drift rules have an honest baseline and
    detection latency an honest zero point.  The contract: every fault
    class is DETECTED (an alert fires after the fault installs, 7/7),
    with recorded per-fault detection latency — and a full clean run
    fires NOTHING (zero false positives).  Detection without a false-
    positive bound is an alarm bell taped down; this bench gates both.

    The wire-reorder script is the deliberate exclusion: delivery-order
    faults change no rate/level/quantile series (frames still arrive,
    on time, intact), so they are invisible to metric-space alerting by
    construction — the chaos soak's per-frame accounting
    (BENCH_chaos.json) covers them instead."""
    from nnstreamer_tpu.obs.metrics import LinkMetrics

    LinkMetrics.clear_all()
    s = CHAOS_SEED
    frames = WATCH_FRAMES
    warmup = max(frames // 4, 12)
    pace = 0.025  # spread the warmup over >= min_samples sampler ticks
    scripts = [
        _watched_script(
            _chaos_query_script, "edge-timeouts",
            "wire-drop", f"seed={s};drop:p=0.12,dir=tx,match=qcli",
            timeout_ms=600.0, expect_timeouts=True, frames=frames,
            warmup_frames=warmup, warmup_pace_s=pace),
        # drift detection needs a baseline: the rtt rule's min_samples
        # requires ~11 windowed-p95 points before the fault, so this
        # leg warms up longer than the others (40 frames at 25ms ≈ 20
        # sampler ticks) and injects a decisively-out-of-regime delay
        _watched_script(
            _chaos_query_script, "edge-rtt-drift",
            "wire-delay", f"seed={s + 1};delay:ms=40,p=0.4",
            timeout_ms=5000.0, frames=frames,
            warmup_frames=max(warmup, 40), warmup_pace_s=pace,
            pace_s=0.015),
        _watched_script(
            _chaos_query_script, "edge-reconnect-flap",
            "disconnect-flap",
            f"seed={s + 2};disconnect:every=40,dir=tx,match=qcli",
            timeout_ms=2000.0, expect_reconnects=True, frames=frames,
            warmup_frames=warmup, warmup_pace_s=pace),
        _watched_script(
            _chaos_query_script, "edge-timeouts",
            "partition",
            f"seed={s + 3};partition:ms=400,every=50,match=qcli",
            timeout_ms=1500.0, expect_timeouts=True, frames=frames,
            warmup_frames=warmup, warmup_pace_s=pace),
        _watched_script(
            _chaos_query_script, "edge-bad-frames",
            "wire-corrupt", f"seed={s + 4};corrupt:p=0.1,dir=tx",
            timeout_ms=800.0, frames=frames, warmup_frames=warmup,
            warmup_pace_s=pace),
        # ms=80,p=0.3 (vs the soak's 25/0.2): the clean pool latency
        # mean legitimately swings 0.5-5ms under paced multi-stream
        # traffic, and a drift detector that pages inside that band is
        # a pager, not a detector — the detection target is a stall
        # decisively outside the baseline regime
        _watched_script(
            _chaos_invoke_script, "pool-latency-drift",
            "slow-invoke", f"seed={s + 5};slow-invoke:ms=80,p=0.3",
            frames=frames, warmup_frames=2 * frames // 3, stat_ms=50.0,
            pace_s=0.01),
        _watched_script(
            _chaos_invoke_script, "element-errors",
            "fail-invoke", f"seed={s + 6};fail-invoke:every=12",
            expect_errors=True, frames=frames // 2,
            warmup_frames=max(warmup // 2, 8), stat_ms=50.0),
    ]
    clean = _watched_script(
        _chaos_query_script, None, "clean", None, timeout_ms=2000.0,
        frames=frames, warmup_frames=0)
    detected = sum(1 for r in scripts if r["detected"])
    false_positives = clean["false_positives"] \
        + sum(r.get("pre_fault_alerts", 0) for r in scripts)
    latencies = [r["detection_latency_s"] for r in scripts
                 if r.get("detection_latency_s") is not None]
    result = {
        "metric": "chaos-detection coverage: seeded fault scripts the "
                  "watchdog (default rule pack) must alarm on, plus a "
                  "clean leg it must stay silent through",
        "value": detected,
        "unit": f"of {len(scripts)} fault scripts detected",
        "seed": s,
        "coverage": f"{detected}/{len(scripts)}",
        "detected_all": detected == len(scripts),
        "false_positives": false_positives,
        "clean_leg_false_positives": clean["false_positives"],
        "detection_latency_max_s": max(latencies) if latencies else None,
        "detection_latency_mean_s": round(
            sum(latencies) / len(latencies), 3) if latencies else None,
        "watch_interval_s": WATCH_INTERVAL_S,
        "scripts": scripts,
        "clean": clean,
        "excluded": {"wire-reorder": "delivery-order faults change no "
                                     "exported series (covered by the "
                                     "chaos soak's accounting)"},
        "note": "detection = any default-pack alert firing AFTER the "
                "fault installs (expected_rule_fired records whether "
                "the symptom-matched rule was among them); detection "
                "latency = fault install -> first alert; false "
                "positives = clean-leg alerts + pre-fault alerts "
                "across every script, gated at 0",
    }
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    return result


# -- capacity / tenancy bench (--capacity → BENCH_capacity.json) --------------

#: total frame budget for the mixed-tenant trace; every arrival rate
#: scales linearly with it and the injected per-dispatch cost scales
#: inversely, so a smaller budget replays the SAME trace geometry
#: (identical leg timings, identical overload ratio) with fewer frames
CAPACITY_FRAMES = int(os.environ.get("BENCH_CAPACITY_FRAMES", "24000"))
CAPACITY_NOMINAL_FRAMES = 24000
CAPACITY_INTERVAL_S = float(
    os.environ.get("BENCH_CAPACITY_INTERVAL", "0.2"))
CAPACITY_CLEAN_S = 4.0
CAPACITY_RAMP_S = 12.0
CAPACITY_HOLD_S = 4.0
CAPACITY_HORIZON_S = 8.0


def _capacity_build_pipes(model, spec, slo_ms, tenants,
                          queue_size=64):
    """One share-model stream per tenant over ONE shared pool — the
    ``tenant=`` property is the whole point: every dispatch's
    device-seconds split across these labels by useful-frame
    occupancy (obs/tenantstat.py)."""
    from nnstreamer_tpu.elements.basic import AppSink, AppSrc, Queue
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.runtime import Pipeline

    pipes = []
    for i, tenant in enumerate(tenants):
        p = Pipeline(name=f"cap{i}-{tenant or 'default'}")
        src = AppSrc(name="src", spec=spec, max_buffers=queue_size)
        q = Queue(name="q", max_size_buffers=queue_size)
        flt = TensorFilter(name="net", framework="jax-xla",
                           model=model, batch=SLO_BATCH,
                           batch_timeout_ms=SLO_TIMEOUT_MS,
                           batch_buckets=str(SLO_BATCH),
                           share_model=True, slo_ms=slo_ms,
                           # deep enough that ONE stream's parked depth
                           # can cross the slo_ms-equivalent depth at
                           # any scale (slo_depth = slo_ms/1e3 *
                           # capacity_fps; the default 16x batch caps
                           # below it at scale 1, so the admission
                           # controller would idle and the reactive leg
                           # of the lead gate would never arm)
                           queue_limit=64 * SLO_BATCH,
                           tenant=tenant, stat_sample_interval_ms=20.0)
        sink = AppSink(name="out", max_buffers=4096)
        p.add(src, q, flt, sink).link(src, q, flt, sink)
        p.start()
        pipes.append({"pipe": p, "src": src, "q": q, "flt": flt,
                      "sink": sink, "tenant": tenant or "default"})
    return pipes


def bench_capacity(out_path: str = "BENCH_capacity.json"):
    """``--capacity``: the predictive-alerting + tenancy gate — a
    diurnal-plus-burst mixed-tenant trace (open loop) against the
    shared serving path with a watchdog running a ``forecast`` rule
    (obs/forecast.py) next to the reactive ``slo_burn`` pack.

    The trace: three tenants (alpha/beta/default) share one pool at a
    flat healthy rate (the clean leg), then tenant alpha's arrivals
    ramp linearly to ~2.5x the pool's capacity and hold (the surge
    leg).  Capacity is pinned, machine-independently, by a seeded
    chaos ``slow-invoke`` per-dispatch cost — the sleep dominates the
    trivial model, so capacity = batch / cost by construction and the
    overload geometry replays identically everywhere.

    The contracts, each a top-level gated scalar:

    - EXACTLY zero forecast firings on the clean leg (a predictor
      that cries wolf on flat traffic is worse than none);
    - on the surge leg the forecast rule fires >= 2 s BEFORE the
      reactive slo-burn (else prediction bought nothing);
    - tenant attribution is EXACT: the sum over tenants of attributed
      device-ns equals the pool's own device-ns — same integer
      nanoseconds, not approximately (obs/tenantstat.py);
    - every tenant gets a $/kframe figure derived from the attributed
      device-seconds at the obs/hwspec.py chip-hour price."""
    import threading

    from nnstreamer_tpu import chaos
    from nnstreamer_tpu.core import Buffer, TensorsSpec
    from nnstreamer_tpu.filters.jax_xla import register_model
    from nnstreamer_tpu.obs.forecast import FORECASTS
    from nnstreamer_tpu.obs.tenantstat import TENANT_STATS
    from nnstreamer_tpu.obs.watch import AlertRule, Watch

    scale = min(max(CAPACITY_FRAMES / CAPACITY_NOMINAL_FRAMES, 0.15),
                4.0)
    # capacity = SLO_BATCH / cost; at scale 1: 8 / 8 ms = 1000 fps
    cost_ms = max(2, round(8.0 / scale))
    capacity_fps = SLO_BATCH / (cost_ms / 1e3)
    rates = {"alpha": 0.075, "beta": 0.05,
             "default": 0.025}  # clean, as fractions of capacity
    clean_fps = {t: f * capacity_fps for t, f in rates.items()}
    peak_total = 2.5 * capacity_fps
    # the surge is alpha's alone — beta/default stay flat, so the
    # per-tenant bill pins the overload on the tenant that caused it
    alpha_peak = peak_total - clean_fps["beta"] - clean_fps["default"]
    # near capacity, well above the clean plateau: the forecast
    # must predict the crossing while the level is still clearly
    # below it (once the level itself is over, the crossing is
    # reactive territory and the forecast stands down)
    thresh_fps = 0.7 * capacity_fps
    slo_ms = 300.0

    model = register_model("bench_capacity_service",
                           lambda x: x - 1.0, in_shapes=[(8,)],
                           in_dtypes=np.float32)
    spec = TensorsSpec.from_shapes([(8,)], np.float32)

    TENANT_STATS.reset()
    FORECASTS.reset()
    chaos.install_plan(chaos.FaultPlan.parse(
        f"seed={CHAOS_SEED + 8};slow-invoke:ms={cost_ms},p=1,"
        f"match=pool:"))
    pipes = _capacity_build_pipes(model, spec, slo_ms,
                                  ["alpha", "beta", "default"])
    rules = [
        # for=0.5: a trend fit over the first handful of points can be
        # confidently wrong (4 nearly-collinear noisy points have ~no
        # MAD); the sustain clause is the designed guard against it
        AlertRule(name="capacity-surge", kind="forecast",
                  metric="nns_pool_frames_total", op=">=",
                  value=thresh_fps, horizon_s=CAPACITY_HORIZON_S,
                  for_s=0.5),
        # reactive comparators at their honest best (short windows,
        # not production sizes): the latency burn — which the shed
        # ramp DEFENDS, so under graded overload it may stay quiet
        # while attainment holds — and the shed-vs-submitted error
        # budget, which is where a working admission controller
        # makes overload visible.  Lead is graded against whichever
        # reactive signal fires FIRST.
        AlertRule(name="slo-burn", kind="slo_burn",
                  metric="nns_admission_latency_seconds",
                  fast_s=1.0, slow_s=4.0, budget=0.02, burn=2.0,
                  severity="critical"),
        AlertRule(name="shed-burn", kind="slo_burn",
                  metric="nns_admission_shed_total",
                  per="nns_admission_submitted_total",
                  fast_s=1.0, slow_s=4.0, budget=0.05, burn=2.0,
                  severity="critical"),
    ]
    stop = threading.Event()
    quiesce = threading.Event()

    def alpha_rate(t):  # t: seconds since the surge leg began
        if t < 0:
            return clean_fps["alpha"]
        ramp = min(t / CAPACITY_RAMP_S, 1.0)
        return clean_fps["alpha"] + ramp * (alpha_peak
                                            - clean_fps["alpha"])

    try:
        _slo_warmup(pipes, spec)
        arr = np.zeros((8,), np.float32)
        t0 = time.monotonic()
        surge_at = [None]  # monotonic ts the surge leg begins

        def producer(e):
            # open loop on an absolute schedule: each wake pushes the
            # deficit between the integrated arrival curve and what
            # was already offered — Python sleep jitter becomes a
            # burst of back-to-back arrivals, not a deflated rate
            tenant, pushed, dropped, acc = e["tenant"], 0, 0, 0.0
            last = time.monotonic()
            while not stop.is_set():
                time.sleep(0.005)
                now = time.monotonic()
                if quiesce.is_set():
                    break
                if tenant == "alpha" and surge_at[0] is not None:
                    r = alpha_rate(now - surge_at[0])
                else:
                    r = clean_fps[tenant]
                acc += (now - last) * r
                last = now
                n = min(int(acc), 64)
                acc -= n
                for _ in range(n):
                    try:
                        e["src"].push_buffer(
                            Buffer.of(arr, pts=pushed), timeout=0)
                        pushed += 1
                    except Exception:  # noqa: BLE001 - full ingress
                        dropped += 1  # queue = a visible drop
                e["offered"] = pushed + dropped
                e["pushed"] = pushed
                e["dropped"] = dropped

        def consumer(e):
            got = 0
            while not stop.is_set():
                if e["sink"].pull(timeout=0.05) is not None:
                    got += 1
                    e["delivered"] = got

        for e in pipes:
            e.update(offered=0, pushed=0, dropped=0, delivered=0)
        threads = [threading.Thread(target=producer, args=(e,),
                                    daemon=True) for e in pipes] + \
                  [threading.Thread(target=consumer, args=(e,),
                                    daemon=True) for e in pipes]
        for t in threads:
            t.start()
        time.sleep(1.0)  # settle: the store's first points must
        # already sit on the clean plateau, not the spin-up edge
        w = Watch(rules=rules, interval_s=CAPACITY_INTERVAL_S)
        clean_end = time.monotonic() + CAPACITY_CLEAN_S
        surge_end = clean_end + CAPACITY_RAMP_S + CAPACITY_HOLD_S
        while time.monotonic() < surge_end:
            tick = time.monotonic()
            if tick >= clean_end and surge_at[0] is None:
                surge_at[0] = tick
            w.sample_once()
            time.sleep(max(
                0.0, CAPACITY_INTERVAL_S - (time.monotonic() - tick)))
        quiesce.set()
        time.sleep(0.3)
        adm = pipes[0]["flt"].pool.admission
        shed_total = adm.total_shed if adm is not None else 0
        _slo_teardown(pipes)
        time.sleep(0.2)
    finally:
        stop.set()
        chaos.uninstall_plan()

    alerts = [dict(ev) for ev in w.alert_log]
    surge_ts = surge_at[0]
    clean_fc = [ev for ev in alerts if ev["rule"] == "capacity-surge"
                and ev["ts"] < surge_ts]
    fc = [ev for ev in alerts if ev["rule"] == "capacity-surge"
          and ev["ts"] >= surge_ts]
    reactive = sorted((ev for ev in alerts
                       if ev["rule"] in ("slo-burn", "shed-burn")),
                      key=lambda ev: ev["ts"])
    lead = round(reactive[0]["ts"] - fc[0]["ts"], 3) \
        if fc and reactive else None

    tenants = {r["tenant"]: r for r in TENANT_STATS.snapshot()}
    pool_label = next(iter(TENANT_STATS.snapshot()), {}).get("pool", "")
    tenant_ns, pool_ns = TENANT_STATS.exactness(pool_label)
    dpk = {t: round(r["dollars"] / r["frames"] * 1e3, 6)
           for t, r in tenants.items() if r["frames"]}
    cap_rows = FORECASTS.snapshot()["capacity"]
    headroom = cap_rows[0]["headroom"] if cap_rows else None

    offered = sum(e["offered"] for e in pipes)
    delivered = sum(e["delivered"] for e in pipes)
    result = {
        "metric": "predictive capacity alerting + per-tenant cost "
                  "attribution on a diurnal+burst mixed-tenant trace "
                  "(3 tenants, one shared pool, open loop, pinned "
                  "capacity via seeded slow-invoke)",
        "value": lead,
        "unit": "s of forecast lead over the reactive slo-burn",
        "scale": round(scale, 3),
        "capacity_fps": round(capacity_fps, 1),
        "clean_fps": round(sum(clean_fps.values()), 1),
        "peak_fps": round(peak_total, 1),
        "forecast_threshold_fps": round(thresh_fps, 1),
        "horizon_s": CAPACITY_HORIZON_S,
        "slo_ms": slo_ms,
        "offered": offered,
        "delivered": delivered,
        "shed": shed_total,
        "ingress_dropped": sum(e["dropped"] for e in pipes),
        "forecast_fired": bool(fc),
        "reactive_fired": bool(reactive),
        "reactive_rule": reactive[0]["rule"] if reactive else None,
        "forecast_lead_s": lead,
        "lead_ok": lead is not None and lead >= 2.0,
        "forecast_false_positives": len(clean_fc),
        "clean_leg_alerts": sum(1 for ev in alerts
                                if ev["ts"] < surge_ts),
        "tenant_device_ns": tenant_ns,
        "pool_device_ns": pool_ns,
        "tenant_sum_exact": tenant_ns == pool_ns and pool_ns > 0,
        "tenants_billed": len(tenants),
        "dollars_total": round(sum(r["dollars"]
                                   for r in tenants.values()), 6),
        "dollars_per_kframe_alpha": dpk.get("alpha"),
        "dollars_per_kframe_beta": dpk.get("beta"),
        "dollars_per_kframe_default": dpk.get("default"),
        "slo_attainment_alpha":
            round(tenants["alpha"]["slo_attainment"], 4)
            if tenants.get("alpha", {}).get("slo_attainment")
            is not None else None,
        "headroom_at_peak": round(headroom, 3)
        if headroom is not None else None,
        "tenants": list(tenants.values()),
        "note": "lead = first reactive burn firing (slo-burn or "
                "shed-burn, whichever first) - first forecast "
                "firing on the surge leg, gated >= 2 s; "
                "forecast_false_positives counts capacity-surge "
                "firings on the clean leg, gated EXACT 0; "
                "tenant_sum_exact compares integer nanoseconds "
                "(same clock reads as nns_invoke_device_seconds), "
                "gated EXACT; $/kframe = attributed device-seconds "
                "x chip-hour price (NNS_TPU_CHIP_HOUR_USD)",
    }
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    return result


# -- closed-loop MTTR bench (--mttr → BENCH_mttr.json) ------------------------

MTTR_INTERVAL_S = float(os.environ.get("BENCH_MTTR_INTERVAL", "0.05"))
MTTR_DETECT_DEADLINE_S = float(
    os.environ.get("BENCH_MTTR_DETECT_DEADLINE", "20"))
MTTR_RECOVER_DEADLINE_S = float(
    os.environ.get("BENCH_MTTR_RECOVER_DEADLINE", "30"))


class _MttrPoolRig:
    """N share-model pipelines + paced open-loop pumps — the serving
    fixture every pool-side MTTR script steers.  Pumps push frames at
    a fixed pace and drain their sinks aggressively (a full sink would
    wedge the pool's demux), from warmup through fault and recovery —
    open-loop traffic does not pause because the server is sick."""

    def __init__(self, name, model_fn, n_pipes=3, batch=8,
                 timeout_ms=3.0, slo_ms=0.0, priorities=None,
                 pace_s=0.002, burst=1, canary="",
                 stat_sample_interval_ms=50.0):
        import threading

        from nnstreamer_tpu.core import Buffer, TensorsSpec
        from nnstreamer_tpu.elements.basic import AppSink, AppSrc, Queue
        from nnstreamer_tpu.elements.filter import TensorFilter
        from nnstreamer_tpu.filters.jax_xla import register_model
        from nnstreamer_tpu.runtime import Pipeline

        self._threading = threading
        self._Buffer = Buffer
        self.model = register_model(f"mttr_{name}", model_fn,
                                    in_shapes=[(8,)],
                                    in_dtypes=np.float32)
        spec = TensorsSpec.from_shapes([(8,)], np.float32)
        self.pace_s = pace_s
        # frames pushed back-to-back per pump wake: bursty arrivals
        # keep window occupancy high THROUGH scheduler lulls on a
        # loaded runner, so occupancy-shaped rule signals (dispatch/
        # frame ratios) reflect the window config, not pump timing
        self.burst = int(burst)
        self.delivered = [0] * n_pipes
        # exact pushed-frame accounting (the lifecycle bench's
        # dropped-frames-==-0 gate is pushed - delivered after drain)
        self.pushed = [0] * n_pipes
        # last output scalar each pump saw — cheap probe that a hot
        # swap actually flipped the serving function
        self.last_value = [None] * n_pipes
        self.pipes = []
        for i in range(n_pipes):
            prio = (priorities[i] if priorities else "normal")
            p = Pipeline(name=f"mttr-{name}-{i}")
            src = AppSrc(name="src", spec=spec, max_buffers=256)
            q = Queue(name="q", max_size_buffers=256)
            flt = TensorFilter(
                name="net", framework="jax-xla", model=self.model,
                batch=batch, batch_timeout_ms=timeout_ms,
                batch_buckets=str(batch), share_model=True,
                slo_ms=slo_ms, priority=prio, canary=canary,
                stat_sample_interval_ms=stat_sample_interval_ms)
            sink = AppSink(name="out", max_buffers=512)
            p.add(src, q, flt, sink).link(src, q, flt, sink)
            self.pipes.append((p, src, flt, sink))
        self._stop = threading.Event()
        self._quiesce = threading.Event()  # stop pushing, keep draining
        self._threads = []

    @property
    def entry(self):
        return self.pipes[0][2].pool

    def start(self):
        for p, *_ in self.pipes:
            p.start()
        for i, (_p, src, _f, sink) in enumerate(self.pipes):
            t = self._threading.Thread(
                target=self._pump, args=(i, src, sink), daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def _pump(self, i, src, sink):
        n = 0
        frame = np.zeros((8,), np.float32)
        while not self._stop.is_set():
            for _ in range(self.burst):
                if self._quiesce.is_set():
                    break
                try:
                    src.push_buffer(self._Buffer.of(frame, pts=n),
                                    timeout=0.5)
                    n += 1
                    self.pushed[i] += 1
                except Exception:  # noqa: BLE001 - a full source
                    # under a stalled window is backpressure, not a
                    # bench bug; keep draining and retry
                    break
            while True:
                buf = sink.pull(timeout=0)
                if buf is None:
                    break
                self.delivered[i] += 1
                try:
                    self.last_value[i] = float(
                        np.asarray(buf.tensors[0].np()).ravel()[0])
                except Exception:  # noqa: BLE001 - probe only
                    pass
            time.sleep(self.pace_s)

    def quiesce(self, timeout_s: float = 10.0) -> bool:
        """Stop pushing, keep draining, wait until every pushed frame
        reached a sink — the exact-frame-accounting gate (dropped == 0)
        measures the SWAP, not shutdown truncation of in-flight
        frames.  The window's deadline flush drains the tail."""
        self._quiesce.set()
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if sum(self.delivered) >= sum(self.pushed):
                return True
            time.sleep(0.02)
        return False

    def stop(self):
        # pipes first: their stop-path flush pushes every parked frame
        # to the sinks, and the pumps must still be DRAINING those
        # sinks — joining the pumps first would wedge the flush of a
        # backed-up window against a full sink
        for p, *_ in self.pipes:
            p.stop()
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)
        # final drain: the stop-path flush may land frames after a
        # pump's last pull — the exact-accounting gate needs them
        for i, (_p, _src, _f, sink) in enumerate(self.pipes):
            while sink.pull(timeout=0) is not None:
                self.delivered[i] += 1


def _actuate_retry(act, value, attempts=8, wait_s=0.3):
    """Seed a fault through an actuator, riding out its cooldown (a
    controller that legitimately steered the knob moments earlier must
    not crash the bench — the pre-fault-alert gate still reports that
    run honestly)."""
    from nnstreamer_tpu.runtime.actuators import CooldownActive

    for i in range(attempts):
        try:
            return act.actuate(value)
        except CooldownActive:
            if i == attempts - 1:
                raise
            time.sleep(wait_s)


def _mttr_run(name, expect_rule, rules, playbooks, fault_fn,
              recovered_fn, warmup_s=1.0, teardown_fn=None):
    """One closed-loop script: clean warmup → seeded fault → alert →
    controller actuation → recovered SLO.  Per-phase timestamps come
    from polling the SAME state the operator tools read (the watch's
    alert log / rule states, the controller's audit ring)."""
    from nnstreamer_tpu.obs.control import Controller
    from nnstreamer_tpu.obs.watch import Watch

    w = Watch(rules=rules, interval_s=MTTR_INTERVAL_S)
    ctl = Controller(playbooks=playbooks, watch=w,
                     interval_s=MTTR_INTERVAL_S)
    w.start()
    ctl.start()
    row = {"script": name, "expected_rule": expect_rule,
           "detected": False, "actuated": False, "recovered": False,
           "detect_s": None, "actuate_s": None, "mttr_s": None,
           "pre_fault_alerts": 0, "actions": 0}
    try:
        time.sleep(warmup_s)
        row["pre_fault_alerts"] = len(w.alert_log)
        t_fault = time.monotonic()
        fault_fn()

        def _firing(rule):
            return any(a["rule"] == rule and a["firing"]
                       for a in w.alerts())

        deadline = t_fault + MTTR_DETECT_DEADLINE_S
        while time.monotonic() < deadline:
            post = [ev for ev in w.alert_log if ev["ts"] >= t_fault]
            if post:
                row["detected"] = True
                row["detect_s"] = round(post[0]["ts"] - t_fault, 3)
                row["rules_fired"] = sorted({ev["rule"]
                                             for ev in post})
                break
            time.sleep(MTTR_INTERVAL_S / 2)
        while time.monotonic() < deadline:
            acted = [d for d in ctl.audit
                     if d["outcome"] in ("applied", "reverted")
                     and d["ts"] >= t_fault]
            if acted:
                row["actuated"] = True
                row["actuate_s"] = round(acted[0]["ts"] - t_fault, 3)
                break
            time.sleep(MTTR_INTERVAL_S / 2)
        # recovery must HOLD (0.5 s), not flicker: an oscillating
        # remediation that clears the symptom for one poll has not
        # recovered the SLO.  MTTR is stamped at the START of the
        # sustained-good window — the moment service was back.
        deadline = t_fault + MTTR_RECOVER_DEADLINE_S
        good_since = None
        while time.monotonic() < deadline:
            ok = row["detected"] and row["actuated"] \
                and not _firing(expect_rule) and recovered_fn()
            now = time.monotonic()
            if not ok:
                good_since = None
            elif good_since is None:
                good_since = now
            elif now - good_since >= 0.5:
                row["recovered"] = True
                row["mttr_s"] = round(good_since - t_fault, 3)
                break
            time.sleep(MTTR_INTERVAL_S / 2)
    finally:
        if teardown_fn is not None:
            teardown_fn()
        ctl.stop()
        w.stop()
    row["actions"] = ctl.actions_total
    row["audit"] = [
        {k: d.get(k) for k in ("playbook", "actuator", "target",
                               "applied", "prior", "outcome")}
        for d in ctl.audit]
    row["expected_rule_fired"] = expect_rule in row.get(
        "rules_fired", [])
    return row, ctl


def _mttr_window_stall():
    """Fault: the cross-stream window's coalescing is PAUSED (a
    misconfigured/steered-wrong window — injected through the same
    actuator seam the controller steers).  Frames park, nothing
    dispatches, nns_pool_pending climbs.  Remediation: the pool-stall
    rule trips the resume-coalescing playbook."""
    from nnstreamer_tpu.obs.watch import AlertRule
    from nnstreamer_tpu.obs.control import Playbook

    rig = _MttrPoolRig("stall", lambda x: x + 1.0, n_pipes=2,
                       batch=8, pace_s=0.002).start()
    time.sleep(1.0)  # XLA compile + first windows settle BEFORE the
    # watchdog attaches: its baseline must be steady state
    rules = [AlertRule(name="pool-stall", kind="threshold",
                       metric="nns_pool_pending", op=">=", value=16.0,
                       for_s=0.1, severity="critical")]
    playbooks = [Playbook(name="resume-coalescing", rule="pool-stall",
                          kind="pool", actuator="coalescing",
                          action="set", value=1.0, cooldown_s=0.5)]

    def fault():
        _actuate_retry(rig.entry.actuators()["coalescing"], 0.0)

    def recovered():
        b = rig.entry.batcher
        return b is not None and b.pending < 8 and not b.paused

    try:
        row, _ctl = _mttr_run("window-stall", "pool-stall", rules,
                              playbooks, fault, recovered)
    finally:
        rig.stop()
    return row


def _mttr_window_collapse():
    """Fault: the window collapses to 1 frame/dispatch on a device
    with a real per-dispatch cost (seeded slow-invoke shim, ms=2 on
    every window) — dispatch rate explodes past service capacity.
    Remediation: the dispatch-amplification rule (dispatches ≈ frames)
    reverts the max-batch knob to its pre-steering width."""
    from nnstreamer_tpu import chaos
    from nnstreamer_tpu.obs.watch import AlertRule
    from nnstreamer_tpu.obs.control import Playbook

    rig = _MttrPoolRig("collapse", lambda x: x * 2.0, n_pipes=3,
                       batch=8, pace_s=0.008, burst=4).start()
    chaos.install_plan(chaos.FaultPlan.parse(
        f"seed={CHAOS_SEED};slow-invoke:ms=2,p=1,match=pool:"))
    time.sleep(1.0)  # compile + shimmed service time settle pre-watch
    rules = [AlertRule(name="dispatch-amplification",
                       kind="threshold",
                       metric="nns_pool_dispatches_total",
                       per="nns_pool_frames_total", op=">=",
                       value=0.7, for_s=0.25, severity="warning")]
    playbooks = [Playbook(name="widen-window",
                          rule="dispatch-amplification", kind="pool",
                          actuator="max-batch", action="revert",
                          cooldown_s=0.5)]

    def fault():
        _actuate_retry(rig.entry.actuators()["max-batch"], 1.0)

    def recovered():
        b = rig.entry.batcher
        return b is not None and b.max_batch == 8 and b.pending < 32

    try:
        row, _ctl = _mttr_run("window-collapse",
                              "dispatch-amplification", rules,
                              playbooks, fault, recovered,
                              warmup_s=1.2)
    finally:
        rig.stop()
        chaos.uninstall_plan()
    return row


def _mttr_slo_burn():
    """Fault: the window is mis-tuned NARROW (max-batch 16→1) while the
    device pays a real per-dispatch cost — service capacity drops
    under the open-loop arrival rate, backlog queues, and the
    admission latency histogram burns through the pool's 250 ms SLO
    (wide enough that a shared runner's scheduler stalls never graze
    it — with a tighter SLO a legitimate 150 ms CPU stall IS a mini
    burn, and the pre-fault-alert gate demands a decisively quiet
    baseline; the fault's latencies are SECONDS, so detection stays
    decisive).
    Remediation: the slo-burn rule steps the window back open (MFU
    headroom is exactly what a wider window converts into capacity)
    and tightens the shed ramp — sticky, by design: reverting the
    ramp the instant the burn clears re-admits the traffic that
    burned it (remediation flap)."""
    from nnstreamer_tpu import chaos
    from nnstreamer_tpu.obs.watch import AlertRule
    from nnstreamer_tpu.obs.control import Playbook

    # a window of 16 on a device paying a real ~8 ms per-dispatch cost:
    # wide window → ~1700 fps capacity >> the ~1000 fps arrivals;
    # collapsed to 1 → ~110 fps, under even the HIGH class's share, so
    # the graded shed ramp cannot save the SLO and the budget burns —
    # exactly the regime where only re-widening the window helps
    rig = _MttrPoolRig("sloburn", lambda x: x - 1.0, n_pipes=3,
                       batch=16, slo_ms=250.0,
                       priorities=["high", "low", "low"],
                       pace_s=0.012, burst=4).start()
    chaos.install_plan(chaos.FaultPlan.parse(
        f"seed={CHAOS_SEED + 1};slow-invoke:ms=8,p=1,match=pool:"))
    time.sleep(1.5)  # compile spike must age out of the burn windows
    # BEFORE the watchdog attaches (honest zero-false-positive leg)
    rules = [AlertRule(name="slo-burn", kind="slo_burn",
                       metric="nns_admission_latency_seconds",
                       fast_s=0.4, slow_s=1.6, budget=0.05, burn=2.0,
                       severity="critical")]
    playbooks = [
        Playbook(name="widen-window", rule="slo-burn", kind="pool",
                 actuator="max-batch", action="step", value=15.0,
                 cooldown_s=1.0),
        # deliberately STICKY (no on_resolve revert): reverting a shed
        # ramp the instant the burn clears re-admits the very traffic
        # that burned it — a textbook remediation flap.  The graded
        # ramp at 0.5 is self-stabilizing; the revert-on-resolve
        # behavior is covered by tests/test_control.py instead.
        Playbook(name="tighten-admission", rule="slo-burn",
                 kind="pool", actuator="ramp-start", action="set",
                 value=0.5, cooldown_s=1.0),
    ]

    def fault():
        _actuate_retry(rig.entry.actuators()["max-batch"], 1.0)

    def recovered():
        adm = rig.entry.admission
        b = rig.entry.batcher
        return adm is not None and b is not None \
            and b.max_batch == 16 and adm.p99_s < 0.25

    try:
        row, _ctl = _mttr_run("slo-burn-overload", "slo-burn", rules,
                              playbooks, fault, recovered,
                              warmup_s=1.5)
    finally:
        rig.stop()
        chaos.uninstall_plan()
    return row


def _mttr_breaker_stuck():
    """Fault: the publisher dies; the subscriber's re-dial loop fails
    until its circuit breaker opens — with a production-grade LONG
    open window (8 s), the link would sit dark long after the
    publisher returns (1 s).  Remediation: the breaker-open rule
    forces the half-open probe (re-dial NOW), kicking the sleeping
    reconnect loop — recovery lands in ~1-2 s instead of 8+."""
    import threading

    from nnstreamer_tpu.core import Buffer, TensorsSpec
    from nnstreamer_tpu.elements.basic import AppSink, AppSrc
    from nnstreamer_tpu.obs.watch import AlertRule
    from nnstreamer_tpu.obs.control import Playbook
    from nnstreamer_tpu.runtime import Pipeline
    from nnstreamer_tpu.runtime.registry import make

    spec = TensorsSpec.parse("4:1", "float32")

    def publisher(port):
        p = Pipeline(name="mttr-pub")
        src = AppSrc(name="src", spec=spec, max_buffers=64)
        sink = make("edgesink", el_name="esink", host="127.0.0.1",
                    port=port, topic="mttr")
        p.add(src, sink).link(src, sink)
        p.start()
        return p, src, sink

    ppub, psrc, esink = publisher(0)
    port = esink.port
    psub = Pipeline(name="mttr-sub")
    esrc = make("edgesrc", el_name="esrc", dest_host="127.0.0.1",
                dest_port=port, topic="mttr",
                caps="other/tensors,format=static,num_tensors=1,"
                     "dimensions=4:1,types=float32",
                reconnect_timeout_s=60.0)
    outs = AppSink(name="out", max_buffers=256)
    psub.add(esrc, outs).link(esrc, outs)
    psub.start()
    # the production-shaped policy this script is ABOUT: fail fast to
    # the breaker, then a long open window (the cost the controller's
    # forced probe eliminates)
    esrc._retry.base_s = 0.05
    esrc._retry.max_s = 0.2
    esrc._retry.fail_threshold = 3
    esrc._retry.open_s = 8.0

    state = {"stop": False, "pub": (ppub, psrc), "sent": 0, "got": 0}
    lock = threading.Lock()

    def pump():
        n = 0
        while not state["stop"]:
            with lock:
                _p, src = state["pub"]
            try:
                src.push_buffer(Buffer.of(
                    np.full((1, 4), 1.0, np.float32), pts=n),
                    timeout=0.2)
                state["sent"] += 1
                n += 1
            except Exception:  # noqa: BLE001 - publisher down mid-
                # fault: open-loop traffic keeps trying
                pass
            while outs.pull(timeout=0) is not None:
                state["got"] += 1
            time.sleep(0.005)

    pump_t = threading.Thread(target=pump, daemon=True)
    pump_t.start()

    rules = [AlertRule(name="breaker-open", kind="threshold",
                       metric="nns_edge_breaker_state", op=">=",
                       value="open", severity="critical")]
    playbooks = [Playbook(name="redial-link", rule="breaker-open",
                          kind="link", actuator="breaker",
                          action="set", value=1.0, cooldown_s=0.3)]

    def fault():
        state["got_at_fault"] = state["got"]
        with lock:
            p, _src = state["pub"]
        p.stop()

        def _restart():
            time.sleep(1.0)
            with lock:
                state["pub"] = publisher(port)[:2]

        threading.Thread(target=_restart, daemon=True).start()

    def recovered():
        # breaker closed AND fresh frames delivered since the fault —
        # a closed breaker on a dead data path is not recovery
        return esrc._retry.state == 0 \
            and state["got"] > state.get("got_at_fault", 0)

    try:
        row, _ctl = _mttr_run("breaker-stuck-open", "breaker-open",
                              rules, playbooks, fault, recovered,
                              warmup_s=1.0)
    finally:
        # subscriber first while the pump still drains its sink (a
        # full sink would block the edgesrc chain against stop)
        psub.stop()
        state["stop"] = True
        pump_t.join(timeout=5)
        with lock:
            state["pub"][0].stop()
    row["open_window_s"] = 8.0
    return row


def _control_counter_total():
    from nnstreamer_tpu.obs.metrics import REGISTRY

    fam = REGISTRY.collect().get("nns_control_actions_total", {})
    return sum(s["value"] for s in fam.get("samples", []))


def _controller_inert_check() -> bool:
    """The whole controller must be strictly inert under
    NNS_TPU_OBS_DISABLE: no thread, no actuation, no audit, no
    registration (the PR-8 kill-switch contract, extended to the
    actuation plane)."""
    from nnstreamer_tpu.obs import hooks as _hooks
    from nnstreamer_tpu.obs.control import Controller, control_table

    before = control_table()["controllers"]
    saved = _hooks.DISABLED
    _hooks.DISABLED = True
    try:
        ctl = Controller()
        inert = (ctl.start() is False and ctl.tick() == []
                 and ctl.apply("pool", "*", "window-ms",
                               value=5.0) == []
                 and ctl.actions_total == 0
                 and control_table()["controllers"] == before)
    finally:
        _hooks.DISABLED = saved
    return inert


def bench_mttr(out_path: str = "BENCH_mttr.json"):
    """``--mttr``: closed-loop recovery as a regression-gated number.
    Four seeded fault scripts run end to end — fault → watch alert →
    controller actuation (through the bounded actuator API) →
    recovered SLO — with per-fault MTTR (fault install → rule
    resolved + SLO predicate true) recorded, pre-fault alerts gated
    at zero, and the decision accounting cross-checked: every
    actuation taken anywhere in the bench must appear in BOTH the
    exported ``nns_control_actions_total`` counter and the decision
    audit ring, with equal counts."""
    from nnstreamer_tpu.obs.metrics import LinkMetrics

    LinkMetrics.clear_all()
    counter_before = _control_counter_total()
    scripts = [
        _mttr_window_stall(),
        _mttr_window_collapse(),
        _mttr_slo_burn(),
        _mttr_breaker_stuck(),
    ]
    counter_delta = _control_counter_total() - counter_before
    audit_total = sum(r["actions"] for r in scripts)
    recovered = sum(1 for r in scripts if r["recovered"])
    mttrs = [r["mttr_s"] for r in scripts if r["mttr_s"] is not None]
    result = {
        "metric": "closed-loop MTTR: seeded fault scripts the "
                  "controller must detect, actuate on and recover "
                  "(fault install -> alert resolved + SLO predicate)",
        "value": recovered,
        "unit": f"of {len(scripts)} fault scripts recovered",
        "coverage": f"{recovered}/{len(scripts)}",
        "recovered_all": recovered == len(scripts),
        "detected_all": all(r["detected"] for r in scripts),
        "actuated_all": all(r["actuated"] for r in scripts),
        "pre_fault_alerts": sum(r["pre_fault_alerts"]
                                for r in scripts),
        "mttr_max_s": max(mttrs) if mttrs else None,
        "mttr_mean_s": round(sum(mttrs) / len(mttrs), 3)
        if mttrs else None,
        "control_interval_s": MTTR_INTERVAL_S,
        "actions_audit_total": audit_total,
        "actions_counter_total": counter_delta,
        "audit_equals_counter": audit_total == counter_delta,
        "controller_inert_under_obs_disable":
            _controller_inert_check(),
        "scripts": scripts,
        "note": "MTTR = fault install -> expected rule RESOLVED and "
                "the script's recovery predicate true (pending "
                "drained / window restored / p99 under SLO / breaker "
                "closed with frames flowing); every decision — "
                "applied, clamped, rejected — lands in both the "
                "audit ring and nns_control_actions_total, asserted "
                "equal",
    }
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    return result


# -- model lifecycle bench (--lifecycle → BENCH_lifecycle.json) --------------

LIFECYCLE_WINDOW_MS = float(
    os.environ.get("BENCH_LIFECYCLE_WINDOW_MS", "25.0"))
LIFECYCLE_CACHE_LAYERS = int(
    os.environ.get("BENCH_LIFECYCLE_CACHE_LAYERS", "24"))


def _lifecycle_swap_leg():
    """Live hot-swap on a share-model pool under open-loop load: the
    replacement stages + warms OFF the dispatch path, the flip lands
    at a window boundary — dropped frames must be EXACTLY 0 (pushed ==
    delivered after drain) and the measured flip stall must fit inside
    one window deadline."""
    from nnstreamer_tpu.filters.jax_xla import register_model

    rig = _MttrPoolRig("lcswap", lambda x: x + 1.0, n_pipes=3,
                       batch=8, timeout_ms=LIFECYCLE_WINDOW_MS,
                       pace_s=0.002, burst=2).start()
    try:
        time.sleep(1.0)  # compile + steady state before the swap
        v2 = register_model("mttr_lcswap_v2", lambda x: x + 3.0,
                            in_shapes=[(8,)], in_dtypes=np.float32)
        entry = rig.entry
        t0 = time.perf_counter()
        res = entry.reload_model(v2, version="v2")
        stage_to_live_s = time.perf_counter() - t0
        lc = entry.lifecycle
        stall_ms = lc.last_swap_stall_s * 1e3
        time.sleep(0.6)  # serve on the new version
        drained = rig.quiesce()
        flipped = all(v == 3.0 for v in rig.last_value
                      if v is not None) and any(
            v is not None for v in rig.last_value)
    finally:
        rig.stop()
    assert drained, "lifecycle swap leg: pipeline did not drain"
    pushed, delivered = sum(rig.pushed), sum(rig.delivered)
    return {
        "frames_pushed": pushed,
        "frames_delivered": delivered,
        "dropped_frames": pushed - delivered,
        "swap_stall_ms": round(stall_ms, 4),
        "window_ms": LIFECYCLE_WINDOW_MS,
        "stall_within_window": stall_ms <= LIFECYCLE_WINDOW_MS,
        "stage_to_live_s": round(stage_to_live_s, 4),
        "outputs_flipped": bool(flipped),
        "swapped_version": res.get("version"),
        "swaps": lc.swaps,
    }


def _lifecycle_cache_leg(cache_dir):
    """Warm-process cold start with the persistent AOT cache: the same
    model's executables (single-frame + one window bucket) built by a
    FRESH instance, cache-off vs cache-on-and-warm.  The win must be
    >= 2x, and the CompileStats ``persist_hit`` count must equal the
    executables actually loaded — asserted against BOTH the bench's
    own counter and the registry export."""
    from nnstreamer_tpu.filters.api import FilterProps
    from nnstreamer_tpu.filters.jax_xla import JaxXlaFilter, \
        register_model
    from nnstreamer_tpu.obs.metrics import REGISTRY
    from nnstreamer_tpu.runtime.compilecache import CACHE_STATS
    from nnstreamer_tpu.utils.stats import COMPILE_STATS

    rng = np.random.default_rng(7)
    w = rng.standard_normal((128, 128)).astype(np.float32)

    def heavy(x):
        import jax.numpy as jnp

        for _ in range(LIFECYCLE_CACHE_LAYERS):
            x = jnp.tanh(x @ w)
        return x

    register_model("lc_cache_model", heavy, in_shapes=[(128,)],
                   in_dtypes=np.float32)

    def cold_start():
        # a fresh instance = a fresh process's compile work: new jit
        # closures, empty executable cache (jax memoizes per function
        # object, so nothing carries over except the persistent cache)
        sp = JaxXlaFilter()
        sp.configure(FilterProps(framework="jax-xla",
                                 model="lc_cache_model"))
        t0 = time.perf_counter()
        x = np.zeros((128,), np.float32)
        _fetch_sync(sp.invoke([x]))
        outs = sp.invoke_batched([[x]] * 4, 4)
        for fo in outs:
            _fetch_sync(fo)
        dt = time.perf_counter() - t0
        sp.close()
        return dt

    def persist_hits():
        return sum(r["count"] for r in COMPILE_STATS.snapshot()
                   if r["kind"] == "persist_hit")

    prev = os.environ.pop("NNS_TPU_COMPILE_CACHE_DIR", None)
    try:
        t_off = cold_start()  # no cache armed: full trace + XLA build
        os.environ["NNS_TPU_COMPILE_CACHE_DIR"] = cache_dir
        before_stats = CACHE_STATS.snapshot()
        cold_start()  # populate (misses + stores)
        hits0 = persist_hits()
        t_warm = cold_start()  # warm-process cold start: deserialize
        hits = persist_hits() - hits0
        stats = CACHE_STATS.snapshot()
        loaded = stats["hits"] - before_stats["hits"]
        fam = REGISTRY.collect().get("nns_compiles_total", {})
        exported = sum(
            s["value"] for s in fam.get("samples", [])
            if s["labels"].get("kind") == "persist_hit")
        truth = persist_hits()
    finally:
        if prev is None:
            os.environ.pop("NNS_TPU_COMPILE_CACHE_DIR", None)
        else:
            os.environ["NNS_TPU_COMPILE_CACHE_DIR"] = prev
    return {
        "cold_start_off_s": round(t_off, 4),
        "cold_start_warm_s": round(t_warm, 4),
        "speedup": round(t_off / t_warm, 2) if t_warm > 0 else None,
        # the warm run loaded exactly its two executables (single-frame
        # + the bucket-4 window) from disk, nothing compiled
        "executables_loaded": loaded,
        "persist_hits": hits,
        "persist_hits_equal": hits == loaded == 2,
        # registry-vs-bench equality: the exported counter is the same
        # number the bench derived from the pull source
        "registry_equals_bench": exported == truth,
        "cache_stats": stats,
    }


def _lifecycle_canary_leg():
    """Seeded bad canary, automatic verdict: a pool declaring
    ``canary=next:1/2`` reloads into a deliberately slow model; the
    watch comparator (canary latency vs baseline latency via per=)
    fires, the playbook actuates ``model:*:rollback``, and the pool
    recovers to baseline-only serving — detection/actuation/recovery
    measured exactly like the --mttr scripts, pre-fault alerts gated
    at zero."""
    from nnstreamer_tpu.filters.jax_xla import register_model
    from nnstreamer_tpu.obs.watch import AlertRule
    from nnstreamer_tpu.obs.control import Playbook

    rig = _MttrPoolRig("lccanary", lambda x: x + 1.0, n_pipes=4,
                       batch=8, timeout_ms=5.0, pace_s=0.004,
                       burst=2, canary="next:1/2",
                       stat_sample_interval_ms=20.0).start()

    def bad(x):
        # ~1000x the baseline's work: the canary latency series
        # leaves the baseline's by far more than the 3x comparator
        import jax
        import jax.numpy as jnp

        def body(_i, v):
            return jnp.tanh(v * 1.0001)

        return jax.lax.fori_loop(0, 2000, body, x)

    bad_model = register_model("mttr_lccanary_bad", bad,
                               in_shapes=[(8,)],
                               in_dtypes=np.float32)
    rules = [
        # the comparator pair: latency ratio + canary error rate
        AlertRule(name="canary-regressed", kind="threshold",
                  metric="nns_model_canary_latency_us",
                  per="nns_model_baseline_latency_us",
                  op=">", value=3.0, for_s=0.1, severity="critical"),
        AlertRule(name="canary-errors", kind="threshold",
                  metric="nns_model_canary_errors_total",
                  op=">", value=0.0, severity="critical"),
    ]
    playbooks = [
        Playbook(name="canary-rollback", rule="canary-regressed",
                 kind="model", actuator="rollback", action="set",
                 value=1.0, cooldown_s=1.0),
        Playbook(name="canary-errors-rollback", rule="canary-errors",
                 kind="model", actuator="rollback", action="set",
                 value=1.0, cooldown_s=1.0),
    ]
    entry = rig.entry

    def fault():
        entry.reload_model(bad_model, version="v2-bad")

    def recovered():
        lc = entry._lifecycle
        return lc is not None and not lc.canary_active \
            and lc.rollbacks >= 1

    try:
        row, _ctl = _mttr_run("bad-canary", "canary-regressed",
                              rules, playbooks, fault, recovered,
                              warmup_s=1.5)
    finally:
        rig.stop()
    lc = entry._lifecycle
    row["rolled_back"] = bool(lc is not None and lc.rollbacks >= 1)
    row["canary_frames_served"] = (
        lc.summary().get("canary_frames", 0) if lc is not None else 0)
    return row


def bench_lifecycle(out_path: str = "BENCH_lifecycle.json",
                    metrics: bool = False):
    """``--lifecycle``: the zero-downtime model lifecycle as three
    regression-gated legs — live hot-swap (0 dropped frames, flip
    stall inside one window), persistent-AOT-cache warm-process cold
    start (>= 2x, persist_hit accounting exact), and a seeded bad
    canary that the watch comparator + rollback playbook must catch
    automatically (recovery recorded, zero pre-fault alerts)."""
    import tempfile

    from nnstreamer_tpu.obs.metrics import REGISTRY

    swap = _lifecycle_swap_leg()
    with tempfile.TemporaryDirectory(prefix="nns_aot_bench_") as d:
        cache = _lifecycle_cache_leg(d)
    canary = _lifecycle_canary_leg()
    # per-leg verdicts: the headline `value` counts legs within gate,
    # so partial regressions stay visible in the history trend
    legs_ok = [
        swap["dropped_frames"] == 0 and swap["stall_within_window"]
        and swap["outputs_flipped"],
        (cache["speedup"] or 0) >= 2.0 and cache["persist_hits_equal"]
        and cache["registry_equals_bench"],
        canary["recovered"] and canary["rolled_back"]
        and canary["pre_fault_alerts"] == 0,
    ]
    result = {
        "metric": "zero-downtime model lifecycle: hot-swap a live "
                  "share-model pool (0 dropped frames, flip at a "
                  "window boundary), warm-process cold start via the "
                  "persistent AOT cache, bad-canary auto-rollback "
                  "through watch comparator + playbook",
        "value": sum(legs_ok),
        "unit": "of 3 lifecycle legs within gate",
        "dropped_frames": swap["dropped_frames"],
        "swap_stall_ms": swap["swap_stall_ms"],
        "stall_within_window": swap["stall_within_window"],
        "outputs_flipped": swap["outputs_flipped"],
        "cold_start_speedup": cache["speedup"],
        "persist_hits_equal": cache["persist_hits_equal"],
        "registry_equals_bench": cache["registry_equals_bench"],
        "canary_rolled_back": canary["rolled_back"],
        "canary_detected": canary["detected"],
        "canary_pre_fault_alerts": canary["pre_fault_alerts"],
        "canary_recovery_s": canary["mttr_s"],
        "swap": swap,
        "cold_start": cache,
        "canary": canary,
        "note": "dropped frames = pushed - delivered after full "
                "drain, EXACT; swap stall = wall time the flip held "
                "the window-boundary lock; cold-start speedup = "
                "fresh-instance executable build time cache-off vs "
                "warm persistent cache (persist_hit count must equal "
                "executables loaded, registry export must equal the "
                "bench's own pull-source read); canary leg reuses "
                "the --mttr fault->alert->actuation->recovery "
                "machinery with the comparator rule pair as judge",
    }
    if metrics:
        result["metrics"] = REGISTRY.snapshot()
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    return result


# -- data-movement observability bench (--transfer → BENCH_transfer.json) ----

TRANSFER_FRAMES = int(os.environ.get("BENCH_TRANSFER_FRAMES", "256"))
TRANSFER_REPS = int(os.environ.get("BENCH_TRANSFER_REPS", "5"))


def _transfer_leg(model: str, spec, n: int, name: str = "xfer",
                  warmup: int = 0):
    """One run of the seed single-filter pipeline (appsrc ! queue !
    jax-xla ! appsink), every output drained to host.  ``warmup``
    frames run (and drain) before the timed window so XLA compile and
    the first blocking stat sample stay outside it.  Returns fps
    (push → all pulled+drained) over the timed frames only."""
    from nnstreamer_tpu.core import Buffer
    from nnstreamer_tpu.elements.basic import AppSink, AppSrc, Queue
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.runtime import Pipeline

    shape = spec.tensors[0].shape
    total = n + warmup
    frames = [Buffer.of(np.full(shape, float(i % 7), np.float32), pts=i)
              for i in range(total)]
    p = Pipeline(name=name)
    src = AppSrc(name="src", spec=spec, max_buffers=total + 4)
    q = Queue(name="q", max_size_buffers=total + 4)
    flt = TensorFilter(name="net", framework="jax-xla", model=model)
    sink = AppSink(name="out", max_buffers=total + 4)
    p.add(src, q, flt, sink).link(src, q, flt, sink)
    with p:
        for b in frames[:warmup]:
            src.push_buffer(b)
        for _ in range(warmup):
            out = _pull(sink, "transfer warmup")
            for t in out.tensors:
                t.np()
        t0 = time.perf_counter()
        for b in frames[warmup:]:
            src.push_buffer(b)
        for _ in range(n):
            out = _pull(sink, "transfer")
            for t in out.tensors:
                t.np()  # device→host drain: the d2h leg of the ledger
        dt = time.perf_counter() - t0
        src.end_of_stream()
        p.wait_eos(timeout=30)
    return n / dt


def bench_transfer(out_path: str = "BENCH_transfer.json",
                   metrics: bool = False):
    """``--transfer``: data-movement observability acceptance (ISSUE 8).

    Three claims on the seed single-filter pipeline, CPU backend:

    - **byte-exact ledger**: exported ``nns_transfer_bytes_total``
      equals the analytically known input+output nbytes per frame
      (h2d at the filter's upload, d2h at the sink-side drain);
    - **crossings-per-frame**: the tracer's residency-flip figure for
      the host→device→host shape of this pipeline is exactly 1 flip
      at the filter boundary (the drain happens past the sink);
    - **zero measurable overhead**: interleaved on/off A/B (ledger +
      flight recorder armed vs fully disabled), medians within the
      PR 4 tolerance (<3%)."""
    from nnstreamer_tpu.core import TensorsSpec
    from nnstreamer_tpu.filters.jax_xla import register_model
    from nnstreamer_tpu.obs import transfer as xfer
    from nnstreamer_tpu.obs.flightrec import FLIGHT
    from nnstreamer_tpu.obs.metrics import REGISTRY
    from nnstreamer_tpu.obs.tracer import LatencyTracer

    n = TRANSFER_FRAMES
    shape = (16,)
    frame_bytes = int(np.dtype(np.float32).itemsize * np.prod(shape))
    model = register_model("bench_transfer_tiny",
                           lambda x: x * 2.0 + 1.0,
                           in_shapes=[shape], in_dtypes=np.float32)
    spec = TensorsSpec.from_shapes([shape], np.float32)
    # -- leg 1: byte-exactness on a fresh ledger (warmup included in
    # the analytic expectation: every pushed frame crosses once up,
    # once down)
    xfer.set_enabled(True)
    xfer.LEDGER.clear()
    fps_exact = _transfer_leg(model, spec, n, name="xferpipe")
    h2d_count, h2d_bytes = xfer.LEDGER.totals(
        pipeline="xferpipe", direction="h2d", reason="input")
    d2h_count, d2h_bytes = xfer.LEDGER.totals(
        direction="d2h", reason="drain")
    expected = n * frame_bytes
    byte_exact = (h2d_bytes == expected and d2h_bytes == expected
                  and h2d_count == n and d2h_count == n)
    # the registry export must agree with the ledger it derives from
    snap = REGISTRY.snapshot()
    fam = snap["metrics"].get("nns_transfer_bytes_total", {})
    exported_h2d = sum(
        s["value"] for s in fam.get("samples", [])
        if s["labels"].get("pipeline") == "xferpipe"
        and s["labels"].get("direction") == "h2d"
        and s["labels"].get("reason") == "input")
    byte_exact = byte_exact and exported_h2d == expected
    # -- leg 2: crossings-per-frame via the tracer's residency flips
    with LatencyTracer(sample_every=1, max_records=64) as tr:
        _transfer_leg(model, spec, 32, name="xfertrace")
    xpf = tr.summary().get("crossings_per_frame", 0.0)
    # -- leg 3: the <3% overhead claim, two estimators.
    # (a) DETERMINISTIC seam-cost bound: the obs-on/obs-off delta is
    # exactly the gated operations — 2 ledger records + the per-element
    # context pushes per frame.  Microbench them in a tight loop
    # (stable to well under a µs) and divide by the measured per-frame
    # budget: an upper bound on the overhead fraction that does not
    # depend on shared-runner scheduler noise.
    # (b) interleaved on/off A/B over the real threaded pipeline —
    # the PR 4 methodology — reported alongside (median of per-pair
    # ratios; on a noisy runner this carries the scheduler's jitter,
    # which is why the gate reads (a)).
    # A/B frames are realistically sized (16 KiB, a small image tile):
    # the overhead bound is about a production frame's budget, not the
    # 64-byte toy vector the byte-exact leg uses for easy arithmetic.
    ab_shape = (64, 64)
    ab_bytes = int(np.dtype(np.float32).itemsize * np.prod(ab_shape))
    ab_model = register_model("bench_transfer_ab",
                              lambda x: x * 2.0 + 1.0,
                              in_shapes=[ab_shape],
                              in_dtypes=np.float32)
    ab_spec = TensorsSpec.from_shapes([ab_shape], np.float32)
    on_fps, off_fps = [], []
    rec_enabled = FLIGHT.enabled

    def _ab_leg(enabled):
        xfer.set_enabled(enabled)
        FLIGHT.enabled = enabled
        fps = _transfer_leg(ab_model, ab_spec, n,
                            name="xfer-on" if enabled else "xfer-off",
                            warmup=16)
        (on_fps if enabled else off_fps).append(fps)

    try:
        for rep in range(TRANSFER_REPS):
            # alternate within-pair order so a transient that lands on
            # "the first leg after the previous pair" (thread teardown
            # debt, GC) doesn't bias one mode systematically
            first_on = rep % 2 == 0
            _ab_leg(first_on)
            _ab_leg(not first_on)
    finally:
        xfer.set_enabled(True)
        FLIGHT.enabled = rec_enabled
    on_med = float(np.median(on_fps))
    off_med = float(np.median(off_fps))
    ratios = [a / b for a, b in zip(on_fps, off_fps) if b]
    ab_overhead = 1.0 - float(np.median(ratios)) if ratios else 0.0
    # (a) the deterministic bound: per-frame gated work = 2 records
    # (h2d + d2h, timed) + one context push/pop per element the buffer
    # chains through (3 in the seed pipeline: queue, filter, sink)
    reps_us = 20000
    t0 = time.perf_counter()
    for _ in range(reps_us):
        ts = time.perf_counter()
        xfer.record("h2d", "input", ab_bytes,
                    time.perf_counter() - ts, source="bench-seam",
                    pipeline="xfer-seam")
        ts = time.perf_counter()
        xfer.record("d2h", "drain", ab_bytes,
                    time.perf_counter() - ts, source="bench-seam",
                    pipeline="xfer-seam")
        for _e in range(3):
            prev = xfer.push_context("xfer-seam", "bench-seam", None)
            xfer.pop_context(prev)
    seam_us = (time.perf_counter() - t0) / reps_us * 1e6
    frame_us = 1e6 / off_med if off_med else 0.0
    overhead = seam_us / frame_us if frame_us else 0.0
    result = {
        "metric": "data-movement observability: byte-exact transfer "
                  f"ledger + crossings/frame + on/off overhead A/B "
                  f"({n} frames, seed single-filter pipeline, CPU)",
        "value": round(xpf, 3),
        "unit": "host<->device crossings per frame (tracer residency "
                "flips)",
        "frames": n,
        "frame_bytes": frame_bytes,
        "h2d_bytes": h2d_bytes,
        "d2h_bytes": d2h_bytes,
        "expected_bytes_each_way": expected,
        "byte_exact": byte_exact,
        "ledger_fps": round(fps_exact, 1),
        "obs_on_fps": round(on_med, 1),
        "obs_off_fps": round(off_med, 1),
        "seam_cost_us_per_frame": round(seam_us, 3),
        "frame_us": round(frame_us, 1),
        "overhead_frac": round(overhead, 4),
        "overhead_ok": overhead < 0.03,
        "ab_overhead_frac": round(ab_overhead, 4),
        "ab_on_samples": [round(s, 1) for s in on_fps],
        "ab_off_samples": [round(s, 1) for s in off_fps],
        "note": "ledger bytes are exact nbytes sums, not estimates. "
                "overhead_frac is the deterministic bound (microbenched "
                "gated seam work / measured per-frame budget); "
                "ab_overhead_frac is the interleaved pipeline A/B "
                "(median of per-pair ratios), which on a shared runner "
                "carries scheduler jitter either direction",
    }
    if metrics:
        result["metrics"] = snap
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    return result


def _composite_live_mfu():
    """ISSUE-9 acceptance: the registry's LIVE MFU (scrape-time join of
    captured executable cost with measured ``nns_invoke_device_
    seconds`` deltas) must agree with a one-shot MFU computed by hand
    from this bench's own independent lowering and the same run's
    phase stats — and the flops figures must match byte-for-byte.

    A dedicated fused composite pipeline runs with EVERY dispatch
    sampled; the join's delta window is primed after the first
    (compile-polluted) dispatch so both sides see only clean
    steady-state device time."""
    import jax

    from nnstreamer_tpu.core import TensorsSpec
    from nnstreamer_tpu.decoders.boxutil import device_render_fn
    from nnstreamer_tpu.elements.transform import _OpChain
    from nnstreamer_tpu.obs.metrics import REGISTRY
    from nnstreamer_tpu.obs.xlacost import XLA_COST

    model = "bench_ssd_live"
    detect, params, _anchors = _register_ssd_pp(model, SSD_BATCH)
    bufs = max(WARMUP, 1) + 5
    # distinct element name: the A/B composite legs already measured
    # their device-seconds series under source="net" for a DIFFERENT
    # model — reusing the name would merge the series (and fire the
    # obs remap warning)
    p, sink = _composite_pipeline(SSD_BATCH, bufs, model, fuse=True,
                                  pool_size=4, flt_name="net_live")
    p["net_live"].stat_sample_interval_ms = 0  # sample EVERY dispatch
    with p:
        b = _pull(sink, "live-mfu warmup")
        _fetch_sync_small(b)
        # prime the join's delta window AFTER the compile dispatch
        REGISTRY.snapshot()
        s0 = p["net_live"].invoke_stats.snapshot()["phase"]
        for _ in range(bufs - 1):
            b = _pull(sink, "live-mfu")
            _fetch_sync_small(b)
        s1 = p["net_live"].invoke_stats.snapshot()["phase"]
        snap = REGISTRY.snapshot()
    # the bench's OWN lowering of the exact fused program (normalize +
    # detect + device overlay): lowered OUTSIDE the filter's compile
    # seam, so it cross-checks the capture plumbing end to end.  The
    # reconstruction must match the installed program STRUCTURALLY, not
    # just mathematically, because unoptimized-HLO cost analysis counts
    # per-op buffer traffic: the decoder's epilogue returns
    # (canvas, *outs) (slicing the canvas instead re-reads it:
    # +B*H*W*4 bytes), and the normalize stage must be the transform
    # grammar's own fn — hand-inlining `(x-127.5)/127.5` lowers with
    # one fewer full-image operand read than `add:-127.5` does.
    post = device_render_fn(SSD_BATCH, 10, SSD_SIZE, SSD_SIZE, 0.25)
    norm = _OpChain("arithmetic",
                    "typecast:float32,add:-127.5,div:127.5").fn_for(
        TensorsSpec.from_shapes([(SSD_BATCH, SSD_SIZE, SSD_SIZE, 3)],
                                np.uint8).tensors[0])

    def full(x):
        outs = detect(params, norm(x))
        return (post(*outs), *outs)

    flops_bench, bytes_bench = flops_bytes(jax.jit(full).lower(
        jax.ShapeDtypeStruct((SSD_BATCH, SSD_SIZE, SSD_SIZE, 3),
                             np.uint8)))
    erow = XLA_COST.get(model, 0) or {}
    live = next((r for r in snap.get("executables", [])
                 if r["source"] == model and r["bucket"] == 0), {})
    dsum = s1["device_s"] - s0["device_s"]
    dcount = s1["samples"] - s0["samples"]
    # cost-attribution split over the same clean steady-state window:
    # host = prep + drain, device = the fenced execution phase.  The
    # device-resident dataflow gate (ISSUE 15) requires the composite
    # dispatch to be device-time-dominated — host phase < device phase
    hsum = (s1["host_prep_s"] - s0["host_prep_s"]) \
        + (s1["host_drain_s"] - s0["host_drain_s"])
    mfu_one_shot = flops_bench * dcount / (dsum * V5E.peak_flops) \
        if dsum > 0 else None
    mfu_live = live.get("mfu")
    agreement = abs(mfu_live - mfu_one_shot) / mfu_one_shot \
        if mfu_live is not None and mfu_one_shot else None
    return {
        "registry_flops": erow.get("flops"),
        "bench_flops": flops_bench,
        "registry_bytes": erow.get("bytes"),
        "bench_bytes": bytes_bench,
        "flops_exact": erow.get("flops") == flops_bench
        and flops_bench > 0,
        "bytes_exact": erow.get("bytes") == bytes_bench,
        "mfu_live_registry": mfu_live,
        "mfu_one_shot": mfu_one_shot,
        "mfu_agreement_frac": round(agreement, 4)
        if agreement is not None else None,
        "mfu_within_5pct": agreement is not None and agreement <= 0.05,
        "sampled_dispatches": dcount,
        "host_phase_us_per_dispatch": round(hsum / dcount * 1e6, 1)
        if dcount else None,
        "device_phase_us_per_dispatch": round(dsum / dcount * 1e6, 1)
        if dcount else None,
        "device_time_dominated": bool(dcount and dsum > hsum),
    }


def _composite_dispatch_overhead():
    """ISSUE-17 acceptance: the fused composite issues exactly ONE XLA
    dispatch per window — counted at the dispatch sites themselves
    (DISPATCH_STATS), cross-checked against CompileStats — and the
    python-side cost per window (pipeline wall minus the same compiled
    program chained back-to-back without any element plumbing) stays
    under a gated ceiling.

    Runs under NNS_TPU_OBS_DISABLE so the hot path is the fully async
    one: no sampling fences, no ``_last_out`` retention — what is
    measured is element plumbing + dispatch enqueue, not
    observability.  Timing starts AFTER the first (compile-polluted)
    window; the dispatch count covers the whole run, because every
    window — warmup included — must cost exactly one dispatch."""
    from nnstreamer_tpu.obs import hooks as _hooks
    from nnstreamer_tpu.utils.stats import COMPILE_STATS, DISPATCH_STATS

    model = "bench_ssd_dispatch"
    _register_ssd_pp(model, SSD_BATCH)
    bufs = max(WARMUP, 1) + 8
    saved = _hooks.DISABLED
    _hooks.DISABLED = True
    try:
        p, sink = _composite_pipeline(SSD_BATCH, bufs, model, fuse=True,
                                      pool_size=16, flt_name="net_ds")
        d0 = DISPATCH_STATS.snapshot()
        with p:
            b = _pull(sink, "dispatch warmup")  # the compile window
            _fetch_sync_small(b)
            c_after_warm = COMPILE_STATS.total_compiles
            t0 = time.perf_counter()
            for _ in range(bufs - 1):
                b = _pull(sink, "dispatch")
            _fetch_sync_small(b)
            wall_us = (time.perf_counter() - t0) / (bufs - 1) * 1e6
            d1 = DISPATCH_STATS.snapshot()
            c_end = COMPILE_STATS.total_compiles
            # the SAME executable the pipeline just dispatched, chained
            # from a bare python loop over the source's staged pool —
            # the floor the element plumbing is measured against
            jitted = p["net_ds"].subplugin._compiled.jitted
            pool = [slot[0] for slot in p["src"]._pool]
            _fetch_sync(jitted(pool[0]))
            t1 = time.perf_counter()
            out = None
            for i in range(bufs - 1):
                out = jitted(pool[i % len(pool)])
            _fetch_sync(out)
            prog_us = (time.perf_counter() - t1) / (bufs - 1) * 1e6
        overhead_us = _composite_python_overhead_us()
    finally:
        _hooks.DISABLED = saved
    delta = {k: d1.get(k, 0) - d0.get(k, 0)
             for k in set(d0) | set(d1)
             if d1.get(k, 0) - d0.get(k, 0)}
    dpf = sum(delta.values()) / float(bufs)
    return {
        "dispatches_per_frame": dpf,
        # the fused segment is ONE program: only the filter site may
        # count, exactly once per window, compiled exactly once (no
        # steady-state recompiles after the warmup window)
        "single_program_per_window": (set(delta) == {"filter"}
                                      and dpf == 1.0
                                      and c_end == c_after_warm),
        "python_overhead_per_frame_us": overhead_us,
        "ssd_wall_minus_program_us": round(max(wall_us - prog_us, 0.0),
                                           1),
        "dispatch_sites": delta,
    }


def _composite_python_overhead_us(windows: int = 128,
                                  reps: int = 3) -> float:
    """Per-window python cost of the composite element plumbing:
    pipeline wall minus the same fused program chained from a bare
    loop, on a composite-shaped pipeline whose program is tiny — with
    the SSD model the ~seconds of device time per window drowns the
    python term in run-to-run noise; with a trivial detect model the
    plumbing IS the measurement.  Median of ``reps`` fresh pipeline
    runs (a GC or scheduler burst inside one 40 ms window skews a
    single sample by 2x).  Caller holds NNS_TPU_OBS_DISABLE, so this
    times the fully-async hot path the PR ships (any synchronous
    fence or per-window retention creeping back in lands directly on
    this gated number)."""
    import jax.numpy as jnp

    from nnstreamer_tpu.filters.jax_xla import register_model

    size, b = 32, SSD_BATCH

    def detect(x):
        m = jnp.mean(x, axis=(1, 2, 3), keepdims=False)
        boxes = jnp.tile(jnp.asarray([[0.1, 0.1, 0.5, 0.5]],
                                     jnp.float32)[None], (b, 10, 1)) \
            + m[:, None, None] * 0.0
        scores = jnp.full((b, 10), 0.9, jnp.float32)
        classes = jnp.ones((b, 10), jnp.float32)
        num = jnp.full((b,), 10, jnp.int32)
        return boxes, classes, scores, num

    register_model("bench_plumbing", detect,
                   in_shapes=[(b, size, size, 3)], in_dtypes=np.float32)
    from nnstreamer_tpu.core import TensorsSpec
    from nnstreamer_tpu.elements.basic import AppSink
    from nnstreamer_tpu.elements.decoder import TensorDecoder
    from nnstreamer_tpu.elements.devicesrc import DeviceSrc
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.elements.transform import TensorTransform
    from nnstreamer_tpu.runtime import Pipeline

    bufs = windows + 1
    spec = TensorsSpec.from_shapes([(b, size, size, 3)], np.uint8)
    samples = []
    for rep in range(reps):
        p = Pipeline(fuse=True)
        src = DeviceSrc(name="src", spec=spec, pattern="noise",
                        pool_size=16, num_buffers=bufs)
        tf = TensorTransform(
            name="norm", mode="arithmetic",
            option="typecast:float32,add:-127.5,div:127.5")
        flt = TensorFilter(name=f"net_pl{rep}", framework="jax-xla",
                           model="bench_plumbing")
        dec = TensorDecoder(name="overlay", mode="bounding_boxes",
                            option1="mobilenet-ssd-postprocess",
                            option4=f"{size}:{size}",
                            option5=f"{size}:{size}", option7="device")
        sink = AppSink(name="out", max_buffers=bufs + 4)
        p.add(src, tf, flt, dec, sink).link(src, tf, flt, dec, sink)
        with p:
            buf = _pull(sink, "plumbing warmup")  # the compile window
            _fetch_sync_small(buf)
            t0 = time.perf_counter()
            for _ in range(windows):
                buf = _pull(sink, "plumbing")
            _fetch_sync_small(buf)
            wall_us = (time.perf_counter() - t0) / windows * 1e6
            jitted = p[f"net_pl{rep}"].subplugin._compiled.jitted
            pool = [slot[0] for slot in p["src"]._pool]
            _fetch_sync(jitted(pool[0]))
            t1 = time.perf_counter()
            out = None
            for i in range(windows):
                out = jitted(pool[i % len(pool)])
            _fetch_sync(out)
            prog_us = (time.perf_counter() - t1) / windows * 1e6
        samples.append(max(wall_us - prog_us, 0.0))
    return round(float(np.median(samples)), 1)


def bench_composite_only(out_path: str = "BENCH_composite.json"):
    """``--composite``: the composite workload alone (no model zoo) —
    fast enough to regenerate the headline fps AND the data-movement
    crossings-per-frame figure for the bench history, plus the ISSUE-9
    live-MFU acceptance block (registry join vs one-shot roofline)."""
    from nnstreamer_tpu.obs import hwspec

    reps = int(os.environ.get("BENCH_COMPOSITE_REPS", "3"))
    # the composite MFU figures have always been quoted against the
    # v5e peaks, whatever backend runs the dry run — pin the spec so
    # the registry join derives utilization on CPU hosts too
    prev_spec = hwspec.set_override(V5E)
    try:
        fps, fps_u, fused, ab = bench_composite(reps=reps)
        live = _composite_live_mfu()
        # ISSUE-17: single-dispatch + async hot-path acceptance —
        # dispatches_per_frame (exact 1.0) and the python-overhead
        # ceiling are gated rows in composite_smoke.json
        dispatch = _composite_dispatch_overhead()
        # the transport floor below which no per-frame host round-trip
        # can go: the ISSUE-15 gate keeps a lower-direction ceiling on
        # it so a regression that re-introduces host hops into the
        # composite dataflow cannot hide behind a faster link
        floor_ms = device_roundtrip_floor_ms()
    finally:
        hwspec.set_override(prev_spec)
    crossings = ab.pop("crossings_per_frame", None)
    result = {
        "metric": "composite MobileNetV2-SSD pipeline throughput "
                  f"(batch={SSD_BATCH}; --composite slice)",
        "value": round(fps, 1),
        "unit": "frames/sec/chip",
        "composite_fps_unfused": round(fps_u, 1),
        "fusion_active": fused,
        "crossings_per_frame": crossings,
        "device_roundtrip_floor_ms": round(floor_ms, 3),
        "composite_ab": ab,
        **live,
        **dispatch,
    }
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    return result


def main():
    # --metrics (with --batching/--serve): embed an obs registry
    # snapshot into the emitted BENCH json — resolved ONCE here so the
    # bench functions stay argv-free for programmatic callers.
    # --history: additionally append a normalized record (scenario, key
    # scalars, git sha, registry digest) to BENCH_history.jsonl — the
    # trajectory `tools/nns_bench_diff` gates CI on.
    metrics = "--metrics" in sys.argv[1:]
    history = "--history" in sys.argv[1:]

    def record(scenario, result):
        if history and result:
            from nnstreamer_tpu.obs.benchgate import append_history

            append_history(scenario, result,
                           snapshot=result.get("metrics"))

    if "--batching" in sys.argv[1:]:
        record("batching", bench_batching(metrics=metrics))
        return
    if "--serve" in sys.argv[1:]:
        record("serving", bench_serving(metrics=metrics))
        return
    if "--edge" in sys.argv[1:]:
        record("edge", bench_edge())
        return
    if "--openloop" in sys.argv[1:]:
        record("openloop", bench_openloop())
        return
    if "--hostprof" in sys.argv[1:]:
        record("hostprof", bench_hostprof())
        return
    if "--chaos" in sys.argv[1:]:
        record("chaos", bench_chaos())
        return
    if "--watch" in sys.argv[1:]:
        record("watch", bench_watch())
        return
    if "--mttr" in sys.argv[1:]:
        record("mttr", bench_mttr())
        return
    if "--lifecycle" in sys.argv[1:]:
        record("lifecycle", bench_lifecycle(metrics=metrics))
        return
    if "--transfer" in sys.argv[1:]:
        record("transfer", bench_transfer(metrics=metrics))
        return
    if "--composite" in sys.argv[1:]:
        record("composite", bench_composite_only())
        return
    if "--meshserving" in sys.argv[1:]:
        record("meshserving", bench_meshserving(metrics=metrics))
        return
    if "--cascade" in sys.argv[1:]:
        record("cascade", bench_cascade(metrics=metrics))
        return
    if "--capacity" in sys.argv[1:]:
        record("capacity", bench_capacity())
        return
    if "--mesh" in sys.argv[1:] or "--meshscaling" in sys.argv[1:]:
        record("meshscaling", bench_meshscaling(metrics=metrics))
        return
    if "--project" in sys.argv[1:]:
        bench_project()
        return
    # cost analyses first, on the CPU backend, BEFORE the persistent
    # cache is on: caching CPU AOT results across heterogeneous hosts
    # trips machine-feature mismatches (and they're fast to recompile)
    per_frame_flops = composite_flops()
    cls_flops = classify_flops()
    yolo_gflops = yolo_flops()
    tflite_flops_pf = tflite_flops()
    onnx_flops_pf = onnx_flops()
    _enable_compile_cache()
    composite_fps, composite_fps_unfused, fused, ab_spread = \
        bench_composite()
    composite_xpf = ab_spread.pop("crossings_per_frame", None)
    lat = bench_latency()
    rtt_floor = device_roundtrip_floor_ms()
    breakdown, roofline = device_time_breakdown()
    batch_period_ms = SSD_BATCH / composite_fps * 1e3
    breakdown["dispatch_gap_ms"] = round(
        max(batch_period_ms - breakdown["compute_total_ms"], 0.0), 3)
    # fusion A/B interleaved twice (compiles hit the persistent
    # cache): MEDIAN per mode — see _ab_aggregate for why best-of
    # selects memo-corrupted samples on a remote runtime
    cls_model = register_classify_model()
    runs_f, runs_u = [], []
    for _ in range(3):
        runs_f.append(bench_classify(fuse=True, buffers=15,
                                     model=cls_model))
        runs_u.append(bench_classify(fuse=False, buffers=15,
                                     model=cls_model))
    cls_fps, _cls_spread = _ab_aggregate(runs_f)
    cls_fps_unfused, _ = _ab_aggregate(runs_u)
    vit_model = register_vit_bench()
    vit_fps, _ = _ab_aggregate([bench_vit(vit_model)
                                for _ in range(3)])
    vit_flops = vit_flops_per_frame()
    yolo_fps, _ = _ab_aggregate([bench_yolo() for _ in range(3)])
    yolo_mfu = yolo_fps * yolo_gflops / V5E_BF16_PEAK if yolo_gflops \
        else None
    tflite_fps = bench_tflite()
    tflite_mfu = tflite_fps * tflite_flops_pf / V5E_BF16_PEAK \
        if tflite_fps and tflite_flops_pf else None
    onnx_fps = bench_onnx()
    onnx_mfu = onnx_fps * onnx_flops_pf / V5E_BF16_PEAK \
        if onnx_fps and onnx_flops_pf else None
    mfu = composite_fps * per_frame_flops / V5E_BF16_PEAK if per_frame_flops \
        else None
    cls_mfu = cls_fps * cls_flops / V5E_BF16_PEAK if cls_flops else None
    vit_mfu = vit_fps * vit_flops / V5E_BF16_PEAK
    print(json.dumps({
        "metric": "composite MobileNetV2-SSD pipeline throughput "
                  f"(batch={SSD_BATCH}, device_src ! transform[fused] ! "
                  "jax-xla ssd+NMS ! bounding_boxes decoder ! sink)",
        "value": round(composite_fps, 1),
        "unit": "frames/sec/chip",
        "vs_baseline": round(composite_fps / BASELINE_FPS_PER_CHIP, 3),
        "composite_fps_unfused": round(composite_fps_unfused, 1),
        "composite_fused_vs_unfused":
            round(composite_fps / composite_fps_unfused, 3)
            if composite_fps_unfused else None,
        "composite_ab": ab_spread,
        # data-movement observability (ISSUE 8): ledger crossings per
        # streamed frame on the composite pipeline — the figure the
        # device-resident-dataflow rework must hold at/near zero
        "crossings_per_frame": composite_xpf,
        **lat,
        "device_roundtrip_floor_ms": round(rtt_floor, 3),
        "device_time_breakdown": breakdown,
        "roofline": roofline,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "gflops_per_frame": round(per_frame_flops / 1e9, 3),
        "fusion_active": fused,
        "classify_fps": round(cls_fps, 1),
        "classify_mfu": round(cls_mfu, 4) if cls_mfu is not None else None,
        "classify_fps_unfused": round(cls_fps_unfused, 1),
        "fused_vs_unfused": round(cls_fps / cls_fps_unfused, 3)
        if cls_fps_unfused else None,
        "vit_fps": round(vit_fps, 1),
        "vit_mfu": round(vit_mfu, 4),
        "vit_gflops_per_frame": round(vit_flops / 1e9, 3),
        "yolo_fps": round(yolo_fps, 1),
        "yolo_mfu": round(yolo_mfu, 4) if yolo_mfu is not None else None,
        "yolo_gflops_per_frame": round(yolo_gflops / 1e9, 3),
        # pretrained-import slice: the reference's own quantized
        # mobilenet_v2 .tflite, imported and batched on the TPU
        "tflite_mobilenet_v2_fps":
            round(tflite_fps, 1) if tflite_fps else None,
        "tflite_mobilenet_v2_mfu":
            round(tflite_mfu, 4) if tflite_mfu is not None else None,
        # imported-onnx slice: the reference's ORT-quantized model in
        # exact bf16-code quantized execution
        "onnx_mobilenet_v2_fps":
            round(onnx_fps, 1) if onnx_fps else None,
        "onnx_mobilenet_v2_mfu":
            round(onnx_mfu, 4) if onnx_mfu is not None else None,
        "measurement_note": (
            "r5: every sync is a host FETCH (_fetch_sync) because "
            "block_until_ready on this backend returns at dispatch-ack, "
            "not completion; r4 import/classify slice numbers were "
            "inflated by ack-only syncs and are not comparable"),
    }))


if __name__ == "__main__":
    main()

"""N↔1 stream combinators: tensor_mux, tensor_merge, tensor_demux,
tensor_split, join.

Parity targets (SURVEY.md §2.3):
- tensor_mux   — /root/reference/gst/nnstreamer/elements/gsttensor_mux.c
  (N streams → one ``other/tensors`` frame; num_tensors grows)
- tensor_merge — gsttensor_merge.c (N → 1 tensor concatenated along a
  dimension; ``mode=linear option=<dim>``, direction enum :45-66)
- tensor_demux — gsttensor_demux.c (per-tensor streams; ``tensorpick``
  selection/reordering, grouped picks "0:1,2")
- tensor_split — gsttensor_split.c (1 tensor → N along a dim by
  ``tensorseg`` sizes)
- join         — gst/join/gstjoin.c (first-come-first-forward, no sync)

TPU note: merge concatenation happens with ``jnp.concatenate`` on device
when inputs are device-resident — fan-in of sharded branches then rides
ICI via the parallel layer (collectives.all_gather_merge) instead of this
element; this is the single-host path.
"""

from __future__ import annotations

from fractions import Fraction
from typing import Dict, List, Optional

import numpy as np

from ..core import Buffer, Caps, Tensor, TensorSpec, TensorsSpec
from ..runtime.element import (
    Element,
    NegotiationError,
    Pad,
    PadDirection,
    StreamError,
)
from ..runtime.events import Event, EventKind
from ..runtime.registry import register_element
from .sync import Collector, SyncPolicy


class CollectElement(Element):
    """Base for N-sink elements with the four time-sync policies.  Request
    sink pads are created on demand (``sink_0``, ``sink_1``, …)."""

    def __init__(self, name=None, sync_mode: str = "nosync",
                 sync_option: str = "", **props):
        self.sync_mode = sync_mode
        self.sync_option = sync_option
        super().__init__(name, **props)
        self.add_src_pad()
        self._collector: Optional[Collector] = None

    def request_pad(self, name: str) -> Optional[Pad]:
        if not name.startswith("sink"):
            return None
        # add_sink_pad expands the %u template to the lowest free index
        pad = self.add_sink_pad("sink_%u" if name == "sink" else name)
        if self._collector is not None:
            self._collector.add_pad(pad.name)
        return pad

    def start(self) -> None:
        self._collector = Collector(
            SyncPolicy.parse(self.sync_mode, self.sync_option),
            [p.name for p in self.sinkpads])

    def chain(self, pad: Pad, buf: Buffer) -> None:
        for bufset in self._collector.deposit(pad.name, buf):
            ordered = [bufset[p.name] for p in self.sinkpads
                       if p.name in bufset]
            out = self.combine(ordered)
            if out is not None:
                self.push(out)

    def handle_event(self, pad: Pad, event: Event) -> None:
        if event.kind == EventKind.EOS:
            if self._collector is None or self._collector.mark_eos(pad.name):
                self.on_eos()
                self.forward_event(event)
            return
        super().handle_event(pad, event)

    def combine(self, bufs: List[Buffer]) -> Optional[Buffer]:
        raise NotImplementedError

    def _out_pts(self, bufs: List[Buffer]) -> Optional[int]:
        ts = [b.pts for b in bufs if b.pts is not None]
        return min(ts) if ts else None


@register_element("tensor_mux")
class TensorMux(CollectElement):
    """N single/multi-tensor streams → one frame carrying all tensors."""

    FACTORY = "tensor_mux"

    def propose_src_caps(self, pad: Pad) -> Caps:
        tensors, rate = [], Fraction(0, 1)
        for sp in self.sinkpads:
            if sp.spec is None:
                raise NegotiationError(f"{self.name}: sink caps incomplete")
            tensors.extend(sp.spec.tensors)
            rate = rate or sp.spec.rate
        return Caps.from_spec(TensorsSpec(tensors=tuple(tensors), rate=rate))

    def combine(self, bufs: List[Buffer]) -> Buffer:
        tensors: List[Tensor] = []
        for b in bufs:
            tensors.extend(b.tensors)
        return Buffer(tensors=tensors, pts=self._out_pts(bufs))


@register_element("tensor_merge")
class TensorMerge(CollectElement):
    """N streams → 1 tensor concatenated along a dim.  ``option`` is the
    innermost-first dim index (mode=linear; direction enum parity)."""

    FACTORY = "tensor_merge"

    def __init__(self, name=None, mode: str = "linear", option: str = "0",
                 **props):
        self.mode = mode
        self.option = option
        super().__init__(name, **props)

    def _axis(self, spec: TensorSpec) -> int:
        d = int(str(self.option) or 0)
        return len(spec.dims) - 1 - d  # innermost-first → numpy axis

    def propose_src_caps(self, pad: Pad) -> Caps:
        if self.mode != "linear":
            raise NegotiationError(f"{self.name}: unknown mode {self.mode!r}")
        specs = []
        rate = Fraction(0, 1)
        for sp in self.sinkpads:
            if sp.spec is None or not sp.spec.tensors:
                raise NegotiationError(f"{self.name}: sink caps incomplete")
            specs.append(sp.spec.tensors[0])
            rate = rate or sp.spec.rate
        ax = self._axis(specs[0])
        dims = list(specs[0].dims)
        d = len(dims) - 1 - ax
        dims[d] = sum(s.dims[d] for s in specs)
        for s in specs[1:]:
            if s.dtype != specs[0].dtype:
                raise NegotiationError(f"{self.name}: dtype mismatch")
            for i, (a, b) in enumerate(zip(specs[0].dims, s.dims)):
                if i != d and a != b:
                    raise NegotiationError(
                        f"{self.name}: dims differ off-axis: {specs[0].dims} "
                        f"vs {s.dims}")
        out = TensorSpec(dtype=specs[0].dtype, dims=tuple(dims))
        return Caps.from_spec(TensorsSpec.of(out, rate=rate))

    def combine(self, bufs: List[Buffer]) -> Buffer:
        parts = [b.tensors[0] for b in bufs]
        ax = self._axis(parts[0].spec)
        if any(t.is_device for t in parts):
            # device fan-in: as soon as ANY branch is device-resident,
            # concatenate in HBM — uploading the host minority costs
            # their bytes once, draining the device majority would cost
            # a d2h round-trip per frame AND push the merged stream
            # (and everything downstream) off the device for good.
            # The old rule (device only when *everything* already was)
            # made one host branch a residency fence for the whole
            # fan-in.
            import jax.numpy as jnp

            merged = Tensor(jnp.concatenate([t.jax() for t in parts], axis=ax))
        else:
            merged = Tensor(np.concatenate([t.np() for t in parts], axis=ax))
        return Buffer(tensors=[merged], pts=self._out_pts(bufs))


def parse_tensorpick(s: str) -> List[List[int]]:
    """``"0,2"`` picks tensors 0 and 2 (one per src pad); ``"0:1,2"``
    groups 0+1 onto the first pad (parity: demux tensorpick grammar)."""
    if not str(s).strip():
        return []
    return [[int(x) for x in grp.split(":") if x.strip() != ""]
            for grp in str(s).split(",") if grp.strip()]


@register_element("tensor_demux")
class TensorDemux(Element):
    """1 multi-tensor stream → N streams (SOMETIMES src pads ``src_%u``)."""

    FACTORY = "tensor_demux"

    def __init__(self, name=None, tensorpick: str = "", **props):
        self.tensorpick = tensorpick
        super().__init__(name, **props)
        self.add_sink_pad()
        self._picks: List[List[int]] = []

    def request_pad(self, name: str) -> Optional[Pad]:
        if not name.startswith("src"):
            return None
        return self.add_src_pad(name)

    def _groups(self, num_tensors: int) -> List[List[int]]:
        picks = parse_tensorpick(self.tensorpick)
        if picks:
            return picks
        return [[i] for i in range(num_tensors)]

    def negotiate_src_pads(self) -> None:
        in_spec = self.sinkpad.spec
        if in_spec is None:
            raise NegotiationError(f"{self.name}: sink caps not set")
        groups = self._groups(in_spec.num_tensors)
        for i, sp in enumerate(self.srcpads):
            if sp.peer is None or sp.caps is not None:
                continue
            if i >= len(groups):
                raise NegotiationError(
                    f"{self.name}: more src pads than tensor picks")
            spec = TensorsSpec(
                tensors=tuple(in_spec.tensors[j] for j in groups[i]),
                rate=in_spec.rate)
            fixed = Caps.from_spec(spec).intersect(sp.peer.template)
            if fixed.is_empty():
                raise NegotiationError(
                    f"{self.name}.{sp.name}: downstream refuses {spec}")
            sp.caps = fixed.fixate()
            sp.spec = sp.caps.to_spec()
            sp.peer.element.set_caps(sp.peer, sp.caps)

    def chain(self, pad: Pad, buf: Buffer) -> None:
        groups = self._groups(buf.num_tensors)
        for i, sp in enumerate(self.srcpads):
            if i >= len(groups):
                break
            tensors = [buf.tensors[j] for j in groups[i]]
            self.push(Buffer(tensors=tensors, pts=buf.pts,
                             duration=buf.duration, meta=dict(buf.meta)),
                      pad=sp)


@register_element("tensor_split")
class TensorSplit(Element):
    """Split one tensor along a dim by ``tensorseg`` sizes
    (``"64:64:128" `` innermost-first dim index via ``dimension``)."""

    FACTORY = "tensor_split"

    def __init__(self, name=None, tensorseg: str = "", dimension: str = "0",
                 **props):
        self.tensorseg = tensorseg
        self.dimension = dimension
        super().__init__(name, **props)
        self.add_sink_pad()

    def request_pad(self, name: str) -> Optional[Pad]:
        if not name.startswith("src"):
            return None
        return self.add_src_pad(name)

    def _segs(self) -> List[int]:
        return [int(x) for x in str(self.tensorseg).split(":") if x.strip()]

    def negotiate_src_pads(self) -> None:
        in_spec = self.sinkpad.spec
        if in_spec is None:
            raise NegotiationError(f"{self.name}: sink caps not set")
        t = in_spec.tensors[0]
        d = int(str(self.dimension))
        segs = self._segs()
        if sum(segs) != t.dims[d]:
            raise NegotiationError(
                f"{self.name}: tensorseg {segs} does not sum to dim "
                f"{t.dims[d]}")
        for i, sp in enumerate(self.srcpads):
            if sp.peer is None or sp.caps is not None:
                continue
            dims = list(t.dims)
            dims[d] = segs[i]
            spec = TensorsSpec.of(t.with_dims(dims), rate=in_spec.rate)
            sp.caps = Caps.from_spec(spec).fixate()
            sp.spec = sp.caps.to_spec()
            sp.peer.element.set_caps(sp.peer, sp.caps)

    def chain(self, pad: Pad, buf: Buffer) -> None:
        t = buf.tensors[0]
        d = int(str(self.dimension))
        ax = len(t.spec.dims) - 1 - d
        segs = self._segs()
        offs = np.cumsum([0] + segs)
        if t.is_device:
            import jax.lax as lax  # noqa: F401
            arr = t.jax()
        else:
            arr = t.np()
        for i, sp in enumerate(self.srcpads):
            sl = [slice(None)] * arr.ndim
            sl[ax] = slice(int(offs[i]), int(offs[i + 1]))
            self.push(Buffer(tensors=[Tensor(arr[tuple(sl)])], pts=buf.pts,
                             duration=buf.duration, meta=dict(buf.meta)),
                      pad=sp)


@register_element("join")
class Join(Element):
    """N→1 path combiner: forward whichever input arrives, no sync
    (parity: gst/join/gstjoin.c — used after tensor_if branches)."""

    FACTORY = "join"

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_src_pad()

    def request_pad(self, name: str) -> Optional[Pad]:
        if not name.startswith("sink"):
            return None
        return self.add_sink_pad("sink_%u" if name == "sink" else name)

    def propose_src_caps(self, pad: Pad) -> Caps:
        for sp in self.sinkpads:
            if sp.caps is not None:
                return sp.caps
        raise NegotiationError(f"{self.name}: no sink caps yet")

    def _sink_caps_complete(self) -> bool:
        # join negotiates from the FIRST pad that fixes caps
        return any(p.caps is not None for p in self.sinkpads if p.peer)

    def chain(self, pad: Pad, buf: Buffer) -> None:
        self.push(buf)

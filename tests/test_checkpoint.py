"""Trainer checkpoint backends: orbax directories + the jax-xla-loadable
pickle format, end to end through the tensor_trainer pipeline."""

import numpy as np

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.elements.basic import AppSink, AppSrc
from nnstreamer_tpu.runtime import Pipeline
from nnstreamer_tpu.runtime.registry import make
from nnstreamer_tpu.trainers.checkpoint import (
    is_orbax_path,
    load_orbax,
    save_orbax,
)


def ck_apply(params, x, train=False):
    return x @ params["w"]


class TestCheckpointBackends:
    def test_path_classification(self):
        assert is_orbax_path("/tmp/run1/ckpt")
        assert is_orbax_path("/tmp/run1/")
        assert not is_orbax_path("/tmp/model.pkl")
        assert not is_orbax_path("/tmp/model.msgpack")
        assert not is_orbax_path("/tmp/model.jaxexp")

    def test_orbax_roundtrip(self, tmp_path):
        tree = {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
                "b": np.zeros(3, np.float32)}
        path = str(tmp_path / "ck")
        save_orbax(path, tree)
        out = load_orbax(path, template=tree)
        np.testing.assert_array_equal(np.asarray(out["w"]), tree["w"])
        np.testing.assert_array_equal(np.asarray(out["b"]), tree["b"])


class TestTrainerOrbaxResume:
    def run_training(self, save_path, load_path, n=16):
        spec = TensorsSpec.parse("4:1,1:1", "float32,int32")
        p = Pipeline()
        src = AppSrc(name="src", spec=spec)
        trn = make(
            "tensor_trainer", el_name="trn", framework="jax-optax",
            model_config={
                "apply": "tests.test_checkpoint:ck_apply",
                "init": {"w": np.zeros((4, 2), np.float32)},
                "batch_size": 8, "lr": 0.5, "mesh": "data:-1"},
            model_save_path=save_path, model_load_path=load_path,
            num_inputs=1, num_labels=1, num_training_samples=n, epochs=1)
        snk = AppSink(name="out", max_buffers=2 * n + 8)
        p.add(src, trn, snk).link(src, trn, snk)
        rng = np.random.default_rng(0)
        with p:
            for _ in range(n):
                x = rng.standard_normal((1, 4)).astype(np.float32)
                y = np.array([[int(x.sum() > 0)]], np.int32)
                src.push_buffer(Buffer.of(x, y))
            src.end_of_stream()
            assert p.wait_eos(timeout=180)
        return trn

    def test_save_orbax_then_resume(self, tmp_path):
        ck = str(tmp_path / "trainer_ck")  # no extension → orbax dir
        self.run_training(ck, "")
        restored = load_orbax(ck, template={
            "w": np.zeros((4, 2), np.float32)})
        w1 = np.asarray(restored["w"])
        assert np.abs(w1).sum() > 0  # training actually moved the params

        # second trainer resumes from the orbax checkpoint
        trn2 = self.run_training(str(tmp_path / "ck2"), ck)
        # resumed params started from w1, not zeros: after more training
        # they differ from the from-scratch result unless lr collapsed
        restored2 = load_orbax(str(tmp_path / "ck2"), template={
            "w": np.zeros((4, 2), np.float32)})
        assert np.isfinite(np.asarray(restored2["w"]).sum())

"""The ONE reconnect policy every edge transport shares: jittered
exponential backoff + a circuit breaker.

Before this module each reconnect loop in ``edge/`` had its own ad-hoc
story — the query client slept a fixed 0.3 s between failover sweeps,
the hybrid advertise loop retried the broker every 2 s forever, and
``mqttsrc`` simply gave up on the first connection error.  A fleet of
clients hammering a restarting server at a fixed interval is a
thundering herd; a loop that never gives up hides a dead dependency
forever.  This policy gives every loop the same three behaviors:

- **jittered exponential backoff** — attempt ``n`` waits
  ``min(base * multiplier^(n-1), max)`` scaled by a ±``jitter``
  fraction, so synchronized clients decorrelate;
- **circuit breaker** — after ``fail_threshold`` consecutive failures
  the breaker OPENS: attempts stop for ``open_s`` (no point dialing a
  dead peer at full cadence), then ONE probe runs half-open; its
  success closes the breaker, its failure re-opens it;
- **one-line outage logging** — the FIRST failure of an outage logs at
  WARNING, later attempts log at debug, and recovery logs one WARNING
  with the outage length — never per-attempt spam.

State (backoff level, breaker state, opens) mirrors into the link's
:class:`~nnstreamer_tpu.obs.metrics.LinkMetrics`, so it exports as
``nns_edge_backoff_level`` / ``nns_edge_breaker_state`` gauges and
shows on ``nns-top`` LINK rows.
"""

from __future__ import annotations

import random
import threading
import time
import weakref
from typing import List, Optional

from ..utils.log import logd, logw

#: breaker states (exported as the nns_edge_breaker_state gauge)
CLOSED, HALF_OPEN, OPEN = 0, 1, 2

_STATE_NAMES = {CLOSED: "closed", HALF_OPEN: "half-open", OPEN: "open"}


class BreakerOpen(Exception):
    """Raised by :meth:`RetryPolicy.check` when the breaker is open and
    the caller asked for a hard failure instead of a wait."""


class RetryPolicy:
    """Per-link reconnect policy.  Thread-safe; one instance per
    connection/loop (state is an attribute of THAT link's outage, not
    of the process).  Every instance self-registers in a process-wide
    weak registry so the actuation plane (``runtime/actuators.py`` /
    ``nns-ctl``) can find a link's breaker by name — drain it, force a
    half-open probe, or reset it — without the link having to opt in.
    """

    #: weak process registry of live policies (actuator discovery)
    _REG_LOCK = threading.Lock()
    _REG: "weakref.WeakSet[RetryPolicy]" = weakref.WeakSet()

    def __init__(self, name: str = "", base_s: float = 0.2,
                 max_s: float = 5.0, multiplier: float = 2.0,
                 jitter: float = 0.5, fail_threshold: int = 5,
                 open_s: float = 5.0, metrics=None,
                 seed: Optional[int] = None):
        self.name = name
        self.base_s = float(base_s)
        self.max_s = float(max_s)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.fail_threshold = int(fail_threshold)
        self.open_s = float(open_s)
        self.metrics = metrics  # LinkMetrics (or None)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.consecutive_failures = 0
        self.state = CLOSED
        self._opened_at = 0.0
        self._outage_started = 0.0
        self.breaker_opens = 0
        # wakes policy-paced sleeps (wait()) when an actuator forces a
        # transition: a re-dial loop sitting out a long open window
        # probes NOW instead of when its sleep expires
        self._kick = threading.Event()
        self._actuators = None
        self._sync_metrics()
        with RetryPolicy._REG_LOCK:
            RetryPolicy._REG.add(self)

    @classmethod
    def all_policies(cls) -> "List[RetryPolicy]":
        """Live policies, stable order (actuator discovery)."""
        with cls._REG_LOCK:
            pols = list(cls._REG)
        return sorted(pols, key=lambda p: (p.name, id(p)))

    # -- state transitions ----------------------------------------------------

    def failure(self, err: BaseException = None, what: str = "") -> None:
        """Record one failed attempt.  Logs the FIRST failure of an
        outage at WARNING (one line); opens the breaker at the
        threshold."""
        with self._lock:
            self.consecutive_failures += 1
            n = self.consecutive_failures
            first = n == 1
            if first:
                self._outage_started = time.monotonic()
            opened = False
            if self.state == HALF_OPEN or \
                    (self.state == CLOSED and n >= self.fail_threshold):
                self.state = OPEN
                self._opened_at = time.monotonic()
                self.breaker_opens += 1
                opened = True
            elif self.state == OPEN:
                # a failure while already open (caller attempted
                # without consulting allow()/delay()): restart the
                # open window, same episode — no double count
                self._opened_at = time.monotonic()
            self._sync_metrics_locked()
        if first:
            logw("%s: %s failed (%s); retrying with backoff",
                 self.name or "link", what or "connect", err)
        elif opened:
            logw("%s: circuit breaker OPEN after %d consecutive "
                 "failures — next probe in %.1fs",
                 self.name or "link", n, self.open_s)
        else:
            logd("%s: attempt %d failed (%s)", self.name or "link", n, err)
        if opened:
            # black box: a breaker opening is one of the flight
            # recorder's trigger conditions (obs/flightrec.py) — the
            # ring holds the seconds that led here, the dump keeps them
            from ..obs.flightrec import FLIGHT

            FLIGHT.breaker_opened(self.name or "link", n,
                                  self.breaker_opens)

    def success(self) -> None:
        """Record a successful attempt: closes the breaker, resets the
        backoff, logs recovery (once per outage)."""
        with self._lock:
            n = self.consecutive_failures
            outage = time.monotonic() - self._outage_started if n else 0.0
            self.consecutive_failures = 0
            self.state = CLOSED
            self._sync_metrics_locked()
        if n:
            logw("%s: recovered after %d failed attempt(s) (%.1fs outage)",
                 self.name or "link", n, outage)

    # -- the caller-facing schedule -------------------------------------------

    def allow(self) -> bool:
        """Whether an attempt may run now.  While OPEN, returns False
        until ``open_s`` elapsed, then transitions to HALF_OPEN and
        admits one probe."""
        with self._lock:
            if self.state != OPEN:
                return True
            if time.monotonic() - self._opened_at < self.open_s:
                return False
            self.state = HALF_OPEN
            self._sync_metrics_locked()
            return True

    def check(self) -> None:
        """Hard variant of :meth:`allow`: raises :class:`BreakerOpen`
        instead of returning False (for callers with no loop to wait
        in, e.g. a send path that must fail fast while the peer is
        known-dead)."""
        if not self.allow():
            with self._lock:
                remain = self.open_s - (time.monotonic() - self._opened_at)
            raise BreakerOpen(
                f"{self.name or 'link'}: circuit breaker open "
                f"({self.consecutive_failures} consecutive failures; "
                f"probe in {max(remain, 0.0):.1f}s)")

    def backoff(self) -> float:
        """Jittered exponential delay before the next attempt, based on
        the current failure streak (0 after a success)."""
        with self._lock:
            n = self.consecutive_failures
            if n <= 0:
                return 0.0
            d = min(self.base_s * self.multiplier ** (n - 1), self.max_s)
            if self.jitter:
                d *= 1.0 + self.jitter * self._rng.uniform(-1.0, 1.0)
            return max(d, 0.0)

    def delay(self) -> float:
        """Seconds to wait before the next attempt: the remaining open
        window while the breaker is open, else the backoff.  An open
        window that has elapsed transitions to HALF_OPEN here — loops
        that pace themselves with :meth:`wait`/:meth:`delay` (rather
        than polling :meth:`allow`) get the same one-probe half-open
        semantics: the attempt after the wait IS the probe, and its
        :meth:`failure` re-opens the breaker."""
        with self._lock:
            if self.state == OPEN:
                remain = self.open_s - (time.monotonic() - self._opened_at)
                if remain > 0:
                    return remain
                self.state = HALF_OPEN
                self._sync_metrics_locked()
        return self.backoff()

    def wait(self, stop: Optional[threading.Event] = None,
             max_s: Optional[float] = None) -> bool:
        """Sleep :meth:`delay` (capped at ``max_s``), interruptible by
        ``stop`` and by a forced breaker transition
        (:meth:`force_half_open` / :meth:`reset` kick the sleep, so a
        loop sitting out a long open window re-probes immediately).
        Returns False when ``stop`` fired during the wait."""
        # clear the kick BEFORE reading delay(): a forced transition
        # landing between the two is then reflected in the delay we
        # compute (the state already moved), while one landing after
        # the clear wakes the sleep — either way the probe runs now,
        # never after a stale open window
        self._kick.clear()
        d = self.delay()
        if max_s is not None:
            d = min(d, max_s)
        if d <= 0:
            return stop is None or not stop.is_set()
        if stop is None:
            self._kick.wait(d)
            return True
        deadline = time.monotonic() + d
        while True:
            remain = deadline - time.monotonic()
            if remain <= 0 or self._kick.is_set():
                return True
            if stop.wait(min(remain, 0.05)):
                return False

    # -- forced transitions (the actuation plane) -----------------------------

    def force_open(self) -> None:
        """Administratively OPEN the breaker — the **drain** actuation:
        the link stops attempting until ``open_s`` elapses (or a forced
        probe).  Not counted in :attr:`breaker_opens` (that counts
        failure-driven opens; the gauge reflects the state either
        way)."""
        with self._lock:
            self.state = OPEN
            self._opened_at = time.monotonic()
            self._sync_metrics_locked()
        logw("%s: circuit breaker forced OPEN (drain)",
             self.name or "link")

    def force_half_open(self) -> None:
        """Force the one-probe half-open state NOW instead of when the
        open window expires, and kick any policy-paced sleep — the
        **re-dial** actuation for a controller that knows (or suspects)
        the peer is back."""
        with self._lock:
            if self.state == OPEN:
                self.state = HALF_OPEN
                self._sync_metrics_locked()
        self._kick.set()

    def reset(self) -> None:
        """Administratively close the breaker and zero the backoff —
        the **restart-link** actuation (the next attempt runs at full
        cadence, and a failure starts a fresh outage)."""
        with self._lock:
            self.consecutive_failures = 0
            self.state = CLOSED
            self._sync_metrics_locked()
        self._kick.set()

    def actuators(self) -> dict:
        """This link's actuator set (``runtime/actuators.py``): one
        ``breaker`` knob, value = target state (0 closed/reset,
        1 half-open probe, 2 open/drain)."""
        with self._lock:
            acts = self._actuators
        if acts is not None:
            return acts
        from ..runtime.actuators import Actuator

        def _set(v: float) -> None:
            s = int(round(v))
            if s >= OPEN:
                self.force_open()
            elif s == HALF_OPEN:
                self.force_half_open()
            else:
                self.reset()

        built = {"breaker": Actuator(
            "breaker", "link", self.name or "link",
            get_fn=lambda: float(self.state), set_fn=_set,
            lo=float(CLOSED), hi=float(OPEN), unit="state",
            cooldown_s=0.5)}
        with self._lock:
            # concurrent first builds converge on one set (shared
            # cooldown/revert state)
            if self._actuators is None:
                self._actuators = built
            return self._actuators

    # -- introspection --------------------------------------------------------

    @property
    def state_name(self) -> str:
        return _STATE_NAMES[self.state]

    @property
    def backoff_level(self) -> int:
        """Failure streak length — the exponent driving the backoff."""
        return self.consecutive_failures

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": _STATE_NAMES[self.state],
                    "backoff_level": self.consecutive_failures,
                    "breaker_opens": self.breaker_opens}

    def _sync_metrics(self) -> None:
        with self._lock:
            self._sync_metrics_locked()

    def _sync_metrics_locked(self) -> None:
        m = self.metrics
        if m is not None:
            m.set_retry_state(self.state, self.consecutive_failures,
                              self.breaker_opens)

"""Basic plumbing elements: appsrc, appsink, tensor_sink, queue, tee,
identity, fakesink.

Parity targets: GStreamer appsrc/appsink semantics as used throughout the
reference tests (programmatic pipelines,
/root/reference/tests/common/unittest_common.cc) and the tensor_sink
``new-data`` callback element
(/root/reference/gst/nnstreamer/elements/gsttensor_sink.c).
The ``queue`` element is the runtime's thread boundary, standing in for
GStreamer queue threads (SURVEY.md §1 "Key structural fact").
"""

from __future__ import annotations

import collections
import queue as _q
import threading
from typing import Callable, List, Optional

from ..core import Buffer, Caps, TensorsSpec
from ..obs import hooks as _hooks
from ..runtime.element import (
    Element,
    Pad,
    SinkElement,
    SourceElement,
)
from ..runtime.events import Event, EventKind, Message, MessageKind
from ..runtime.registry import register_element


@register_element("appsrc")
class AppSrc(SourceElement):
    """Application-driven source: the app pushes Buffers via :meth:`push_buffer`
    and ends the stream with :meth:`end_of_stream`.  ``spec`` (a TensorsSpec or
    a caps-string pair) must be set before the pipeline starts."""

    FACTORY = "appsrc"

    def __init__(self, name=None, spec: Optional[TensorsSpec] = None,
                 caps=None, max_buffers: int = 64, **props):
        self.spec = spec
        self.caps = caps
        self.max_buffers = max_buffers
        super().__init__(name, **props)
        if isinstance(self.caps, str):
            from ..runtime.parser import parse_caps_string

            self.caps = parse_caps_string(self.caps)
        self._q: "_q.Queue" = _q.Queue(maxsize=int(self.max_buffers))

    def output_caps(self) -> Caps:
        if self.caps is not None:
            return self.caps
        # super() raises the structured "source has no output spec"
        # NegotiationError when neither caps nor spec is set yet
        return super().output_caps()

    def output_spec(self):
        return self.spec

    def push_buffer(self, buf: Buffer, timeout: Optional[float] = None) -> None:
        self._q.put(buf, timeout=timeout)

    def end_of_stream(self) -> None:
        self._q.put(None)

    def create(self) -> Optional[Buffer]:
        while self._running.is_set():
            try:
                return self._q.get(timeout=0.05)
            except _q.Empty:
                continue
        return None


@register_element("appsink")
class AppSink(SinkElement):
    """Pull-style sink: the app calls :meth:`pull` to take buffers out."""

    FACTORY = "appsink"

    def __init__(self, name=None, max_buffers: int = 64, drop: bool = False,
                 **props):
        self.max_buffers = max_buffers
        self.drop = drop
        super().__init__(name, **props)
        self._q: "_q.Queue" = _q.Queue(maxsize=int(self.max_buffers))

    def render(self, buf: Buffer) -> None:
        if self.drop:
            try:
                self._q.put_nowait(buf)
            except _q.Full:
                try:
                    self._q.get_nowait()
                except _q.Empty:
                    pass
                self._q.put_nowait(buf)
        else:
            self._q.put(buf)

    def pull(self, timeout: Optional[float] = None) -> Optional[Buffer]:
        try:
            return self._q.get(timeout=timeout)
        except _q.Empty:
            return None


@register_element("tensor_sink")
class TensorSink(SinkElement):
    """Callback sink (parity: gsttensor_sink.c ``new-data`` signal +
    emit-signal/signal-rate properties)."""

    FACTORY = "tensor_sink"

    def __init__(self, name=None, callback: Optional[Callable] = None,
                 emit_signal: bool = True, sync: bool = False, **props):
        self.callback = callback
        self.emit_signal = emit_signal
        self.sync = sync
        super().__init__(name, **props)
        self.buffers_rendered = 0
        self.last_buffer: Optional[Buffer] = None
        self._cbs: List[Callable] = []

    def connect(self, cb: Callable) -> None:
        """connect('new-data'-style) a callback(buffer)."""
        self._cbs.append(cb)

    def render(self, buf: Buffer) -> None:
        self.buffers_rendered += 1
        self.last_buffer = buf
        if self.emit_signal:
            if self.callback is not None:
                self.callback(buf)
            for cb in self._cbs:
                cb(buf)


@register_element("fakesink")
class FakeSink(SinkElement):
    FACTORY = "fakesink"

    def render(self, buf: Buffer) -> None:
        pass


@register_element("queue")
class Queue(Element):
    """Thread boundary with a bounded buffer (parity: GStreamer queue).
    ``leaky``: '' (block), 'upstream' (drop new), 'downstream' (drop old).

    ``prefetch_host=True`` starts an async device→host copy for every
    device-resident tensor as it ENTERS the queue (i.e. at XLA dispatch
    time, while the computation may still be running).  A host-side
    consumer on the other side of the thread boundary then finds the
    payload already on host instead of paying a blocking device
    round-trip per buffer — the TPU-native output-drain pattern for
    decoder/sink stages."""

    FACTORY = "queue"

    def __init__(self, name=None, max_size_buffers: int = 16,
                 leaky: str = "", prefetch_host: bool = False, **props):
        self.max_size_buffers = max_size_buffers
        self.leaky = leaky
        self.prefetch_host = prefetch_host
        super().__init__(name, **props)
        self.add_sink_pad()
        self.add_src_pad()
        self._dq: collections.deque = collections.deque()
        self._cv = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._eos = False

    def chain(self, pad: Pad, buf: Buffer) -> None:
        cap = int(self.max_size_buffers)
        with self._cv:
            if self.leaky == "upstream" and len(self._dq) >= cap:
                return  # drop the incoming buffer (before any prefetch)
            if self.leaky == "downstream":
                while len(self._dq) >= cap:
                    self._dq.popleft()
            else:
                while self._running and len(self._dq) >= cap:
                    self._cv.wait(0.05)
                if not self._running:
                    return
            if self.prefetch_host:  # only for buffers actually enqueued
                for t in buf.tensors:
                    t.prefetch_host()
            tracer = _hooks.tracer
            if tracer is not None:
                tracer.queue_enqueued(self, buf)
            self._dq.append(buf)
            self._cv.notify_all()

    def handle_event(self, pad: Pad, event: Event) -> None:
        if event.kind == EventKind.EOS:
            with self._cv:
                self._eos = True
                self._cv.notify_all()
        else:
            self.forward_event(event)

    def start(self) -> None:
        self._running = True
        self._eos = False
        # deterministic name (nns:<pipeline>:<element>) + thread-
        # registry coverage for profiler attribution (obs/prof.py)
        from ..obs import prof as _prof

        self._thread = _prof.element_thread(self, self._loop, "queue")
        self._thread.start()

    def stop(self) -> None:
        with self._cv:
            self._running = False
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _loop(self) -> None:
        import time

        from ..obs import prof as _prof

        # exact run/wait accounting (obs/prof.py): the cv-wait/pop is
        # the wait side, push() — the whole downstream chain runs in
        # this thread — is the run side.  None under NNS_TPU_OBS_DISABLE
        # → the loop skips every clock read.
        pipe = getattr(self, "pipeline", None)
        acct = _prof.element_account(
            getattr(pipe, "name", "") or "-", self.name)
        t0 = c0 = 0.0
        while True:
            if acct is not None:
                t0 = time.monotonic()
                c0 = time.thread_time()
            with self._cv:
                while self._running and not self._dq and not self._eos:
                    self._cv.wait(0.05)
                if not self._running:
                    return
                if self._dq:
                    buf = self._dq.popleft()
                    self._cv.notify_all()
                elif self._eos:
                    break
                else:
                    continue
            tracer = _hooks.tracer
            if tracer is not None:
                tracer.queue_dequeued(self, buf)
            if acct is None:
                self.push(buf)
            else:
                t1 = time.monotonic()
                self.push(buf)
                acct.add(t1 - t0, time.monotonic() - t1,
                         time.thread_time() - c0)
        self.forward_event(Event.eos())

    @property
    def current_level_buffers(self) -> int:
        with self._cv:
            return len(self._dq)


@register_element("tee")
class Tee(Element):
    """1→N fan-out; each downstream branch receives every buffer."""

    FACTORY = "tee"

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad()
        self._next = 0

    def request_pad(self, name: str) -> Optional[Pad]:
        if name in ("src_%u", "src"):
            name = f"src_{self._next}"
        if not name.startswith("src_"):
            return None
        self._next += 1
        return self.add_src_pad(name)

    def propose_src_caps(self, pad: Pad) -> Caps:
        if self.sinkpad.caps is not None:
            return self.sinkpad.caps
        return Caps.any_tensors()

    def chain(self, pad: Pad, buf: Buffer) -> None:
        for sp in self.srcpads:
            self.push(buf, sp)


@register_element("identity")
class Identity(Element):
    FACTORY = "identity"

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad()
        self.add_src_pad()

    def chain(self, pad: Pad, buf: Buffer) -> None:
        self.push(buf)


@register_element("filesrc")
class FileSrc(SourceElement):
    """Read a file and push its bytes as application/octet-stream buffers
    (parity: GStreamer filesrc, the head of every SSAT golden pipeline).
    ``blocksize=0`` pushes the whole file as one buffer."""

    FACTORY = "filesrc"

    def __init__(self, name=None, location: str = "", blocksize: int = 0,
                 **props):
        self.location = location
        self.blocksize = blocksize
        super().__init__(name, **props)
        self._fh = None
        self._done = False

    def output_caps(self) -> Caps:
        from ..core import CapsStruct

        return Caps.new(CapsStruct.make("application/octet-stream"))

    def output_spec(self):
        return None

    def start(self) -> None:
        self._fh = open(self.location, "rb")
        self._done = False
        super().start()

    def stop(self) -> None:
        super().stop()
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def create(self) -> Optional[Buffer]:
        import numpy as np

        if self._done or self._fh is None:
            return None
        size = int(self.blocksize)
        data = self._fh.read(size) if size > 0 else self._fh.read()
        if not data or size <= 0:
            self._done = True
        if not data:
            return None
        from ..core import Tensor, TensorSpec

        arr = np.frombuffer(data, np.uint8)
        return Buffer(tensors=[Tensor(
            arr, TensorSpec.from_shape(arr.shape, np.uint8))])


@register_element("filesink")
class FileSink(SinkElement):
    """Append every incoming buffer's payload bytes to a file (parity:
    GStreamer filesink — the tail of every SSAT golden comparison)."""

    FACTORY = "filesink"

    def __init__(self, name=None, location: str = "", **props):
        self.location = location
        super().__init__(name, **props)
        self._fh = None

    def start(self) -> None:
        self._fh = open(self.location, "wb")

    def render(self, buf: Buffer) -> None:
        for t in buf.tensors:
            self._fh.write(t.tobytes())

    def stop(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


@register_element("tensor_debug")
class TensorDebug(Element):
    """Stream introspection (parity:
    /root/reference/gst/nnstreamer/elements/gsttensor_debug.c): posts an
    ELEMENT bus message describing each buffer, passes data through."""

    FACTORY = "tensor_debug"

    def __init__(self, name=None, output_mode: str = "console", **props):
        self.output_mode = output_mode
        super().__init__(name, **props)
        self.add_sink_pad()
        self.add_src_pad()

    def chain(self, pad: Pad, buf: Buffer) -> None:
        desc = {
            "num_tensors": buf.num_tensors,
            "dims": [t.spec.dim_string() for t in buf.tensors],
            "types": [str(t.dtype) for t in buf.tensors],
            "format": str(buf.format),
            "pts": buf.pts,
        }
        if self.output_mode == "console":
            from ..utils.log import logi

            logi("buffer %s", desc, element=self.name)
        self.post_message(
            Message(MessageKind.ELEMENT, self.name, data=desc))
        self.push(buf)

"""NNS510 — static validation of ``obs/watch.py`` alert-rules files.

A watch rule that references a metric family the registry never
exports, or that cannot parse at all, fails in the worst possible way:
*silently*, at 3am, by not firing.  This pass loads a TOML/JSON rules
file (the same loader the watchdog uses — one grammar, one error
surface) WITHOUT starting anything and reports:

- malformed grammar (unknown keys/kinds/ops, bad durations, duplicate
  names, unreadable/unparseable files) — the exact :class:`RuleError`
  the watchdog would raise at startup;
- rules that can never fire: unknown metric family, a signal that
  cannot exist for the family's kind (``rate`` on a gauge, ``p99`` on
  a counter), ratio/burn shapes that can never bind (see
  :func:`nnstreamer_tpu.obs.watch.lint_rule`).

Invoked by ``nns-lint --watch-rules FILE`` (bare ``--watch-rules``
reads ``$NNS_TPU_WATCH_RULES``, the same env var the runtime loads
from).
"""

from __future__ import annotations

import os
from typing import List, Optional

from .diagnostics import Diagnostic

_HINT = ("rule grammar + the exported-family catalog: "
         "Documentation/observability.md ('Alerting & watchdog'); "
         "known families: nnstreamer_tpu.obs.watch.KNOWN_FAMILIES")


def check_watch_rules(path: Optional[str]) -> List[Diagnostic]:
    """Diagnostics for one rules file.  ``path=None`` means "use
    ``$NNS_TPU_WATCH_RULES``" — unset is itself a finding (the user
    asked for a check with nothing to check)."""
    from ..obs import watch as _watch

    if path is None:
        path = os.environ.get("NNS_TPU_WATCH_RULES", "").strip()
        if not path:
            return [Diagnostic.make(
                "NNS510",
                "--watch-rules given without a file and "
                "NNS_TPU_WATCH_RULES is unset — no rules to validate",
                hint=_HINT)]
    label = os.path.basename(path)
    try:
        rules = _watch.load_rules(path)
    except _watch.RuleError as e:
        return [Diagnostic.make(
            "NNS510", f"{label}: malformed rules file: {e}",
            element=path, hint=_HINT)]
    except OSError as e:
        return [Diagnostic.make(
            "NNS510", f"{label}: cannot read rules file: {e}",
            element=path, hint=_HINT)]
    diags: List[Diagnostic] = []
    for rule in rules:
        for problem in _watch.lint_rule(rule):
            diags.append(Diagnostic.make(
                "NNS510", f"{label}: rule {rule.name!r}: {problem}",
                element=path, pad=rule.name, hint=_HINT))
    return diags

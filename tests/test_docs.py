"""Documentation stays in lockstep with the code.

Parity model: the reference commits per-element .md files (e.g.
gst/nnstreamer/elements/gsttensor_transform.md); here the per-element
reference is GENERATED from the registry, and this test fails whenever
an element or property exists without an up-to-date committed page —
rerun ``python tools/gen_element_docs.py`` and commit.
"""

import inspect
import os

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOC_DIR = os.path.join(ROOT, "Documentation", "elements")


def _load_generator():
    from nnstreamer_tpu.tools import gen_element_docs

    return gen_element_docs


def test_every_element_documented_and_current():
    gen = _load_generator()
    pages = gen.generate()
    stale, missing = [], []
    for fname, content in pages.items():
        path = os.path.join(DOC_DIR, fname)
        if not os.path.exists(path):
            missing.append(fname)
        elif open(path).read() != content:
            stale.append(fname)
    assert not missing, (
        f"undocumented elements: {missing} — run "
        "`python tools/gen_element_docs.py` and commit")
    assert not stale, (
        f"stale element docs: {stale} — run "
        "`python tools/gen_element_docs.py` and commit")


def test_doc_pages_cover_all_properties():
    """Belt and braces: each committed page lists every constructor
    property of its element (guards against a generator regression)."""
    from nnstreamer_tpu.runtime.registry import element_factory, list_elements

    for name in list_elements():
        page = open(os.path.join(DOC_DIR, f"{name}.md")).read()
        cls = element_factory(name)
        for p in inspect.signature(cls.__init__).parameters.values():
            if p.name in ("self", "name", "props") or \
                    p.kind == inspect.Parameter.VAR_KEYWORD:
                continue
            prop = p.name.rstrip("_").replace("_", "-")
            assert f"`{prop}`" in page, (
                f"{name}.md missing property {prop!r}")


def test_check_cli_names_resolve_to_docs():
    """Round-2 verdict done-criterion: every element name the check CLI
    prints resolves to a documented page."""
    from nnstreamer_tpu.runtime.registry import list_elements

    for name in list_elements():
        assert os.path.exists(os.path.join(DOC_DIR, f"{name}.md"))


def test_guides_exist_and_are_substantial():
    for fname, min_lines in [("writing-filter-subplugin.md", 60),
                             ("getting-started.md", 60)]:
        path = os.path.join(ROOT, "Documentation", fname)
        assert os.path.exists(path), f"missing guide {fname}"
        assert len(open(path).read().splitlines()) >= min_lines, (
            f"{fname} too thin")

#!/usr/bin/env python
"""Scaffold generator for custom tensor_filter sub-plugins.

Parity target: /root/reference/tools/development/
nnstreamerCodeGenCustomFilter.py — generates a ready-to-edit custom
filter skeleton.  This one emits the Python3 script-class form
(``tensor_filter framework=python3 model=<file>.py``) or the
register_custom_easy callable form.

Usage:
    python tools/gen_custom_filter.py NAME [--easy] [--in 3:224:224:1]
        [--in-type float32] [--out 1001:1] [--out-type float32]
        [--dir OUTDIR]
"""

import argparse
import os

SCRIPT_TEMPLATE = '''"""Custom tensor_filter: {name}.

Use in a pipeline:
    ... ! tensor_filter framework=python3 model={name}.py ! ...
"""

import numpy as np


class CustomFilter:
    def getInputDim(self):
        # (dims innermost-first, numpy dtype) per input tensor
        return [("{in_dims}", np.{in_type})]

    def getOutputDim(self):
        return [("{out_dims}", np.{out_type})]

    def setInputDim(self, dims):
        # optional: accept a reshape request; raise to refuse
        raise NotImplementedError

    def invoke(self, inputs):
        """inputs: list of numpy arrays; return list of numpy arrays."""
        x = inputs[0]
        # TODO: your computation here
        y = x.astype(np.{out_type})
        return [y]
'''

EASY_TEMPLATE = '''"""Custom-easy tensor_filter: {name}.

Register then use as:
    register()
    ... ! tensor_filter framework=custom-easy model={name} ! ...
"""

import numpy as np

from nnstreamer_tpu.core import TensorsSpec
from nnstreamer_tpu.filters.custom import register_custom_easy


def {name}_invoke(inputs):
    """inputs: list of numpy arrays; return list of numpy arrays."""
    x = inputs[0]
    # TODO: your computation here
    return [x.astype(np.{out_type})]


def register():
    return register_custom_easy(
        "{name}", {name}_invoke,
        in_spec=TensorsSpec.parse("{in_dims}", "{in_type}"),
        out_spec=TensorsSpec.parse("{out_dims}", "{out_type}"))
'''


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("name")
    ap.add_argument("--easy", action="store_true",
                    help="emit the register_custom_easy form")
    ap.add_argument("--in", dest="in_dims", default="3:224:224:1")
    ap.add_argument("--in-type", default="float32")
    ap.add_argument("--out", dest="out_dims", default="1001:1")
    ap.add_argument("--out-type", default="float32")
    ap.add_argument("--dir", default=".")
    args = ap.parse_args()

    tmpl = EASY_TEMPLATE if args.easy else SCRIPT_TEMPLATE
    code = tmpl.format(name=args.name, in_dims=args.in_dims,
                       in_type=args.in_type, out_dims=args.out_dims,
                       out_type=args.out_type)
    path = os.path.join(args.dir, f"{args.name}.py")
    if os.path.exists(path):
        raise SystemExit(f"refusing to overwrite {path}")
    with open(path, "w") as f:
        f.write(code)
    print(f"wrote {path}")


if __name__ == "__main__":
    main()

"""TWO-PROCESS jax.distributed validation (round-4 verdict #5): spawn a
pair of CPU worker processes that form a real process group through
``multihost.initialize``, build the hybrid ICI/DCN mesh with a
cross-process ``replica`` axis, run a global psum over all 8 devices
(4 per process), and invoke a mesh-sharded tensor_filter whose batch
axis spans BOTH processes.

Parity: the reference validates its cross-process layer with paired
gst-launch processes (/root/reference/tests/nnstreamer_edge/query/
unittest_query.cc, runTest.sh); the DCN axis is the TPU-native
equivalent and gets the same treatment here.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""\
    import sys
    sys.path.insert(0, {repo!r})
    import numpy as np

    pid = int(sys.argv[1])
    port = sys.argv[2]

    from nnstreamer_tpu.parallel import multihost

    multihost.initialize(coordinator_address="127.0.0.1:" + port,
                         num_processes=2, process_id=pid)

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    idx, cnt = multihost.process_info()
    assert cnt == 2, cnt
    assert idx == pid, (idx, pid)
    assert len(jax.devices()) == 8, jax.devices()
    assert len(jax.local_devices()) == 4

    mesh = multihost.hybrid_mesh([("data", 4)], [("replica", 2)])
    assert mesh.axis_names == ("replica", "data")
    assert mesh.shape == {{"replica": 2, "data": 4}}

    # -- global psum across BOTH processes --------------------------------
    from jax.experimental.shard_map import shard_map

    x = np.arange(16, dtype=np.float32).reshape(8, 2)
    sharding = NamedSharding(mesh, P(("replica", "data")))
    xd = jax.device_put(x, sharding)
    f = jax.jit(shard_map(
        lambda a: jax.lax.psum(a.sum(), ("replica", "data")),
        mesh=mesh, in_specs=P(("replica", "data")), out_specs=P()))
    y = f(xd)
    got = float(np.asarray(y.addressable_shards[0].data))
    assert got == float(x.sum()), (got, x.sum())
    print(f"psum ok process={{pid}} value={{got}}", flush=True)

    # -- mesh-sharded filter invoke spanning the process group ------------
    from nnstreamer_tpu.elements.filter import FilterSingle
    from nnstreamer_tpu.filters.jax_xla import register_model

    def double(a):
        return a * 2.0 + 1.0

    register_model("twoproc_double", double,
                   in_shapes=[(8, 4)], in_dtypes=np.float32)
    flt = FilterSingle(framework="jax-xla", model="twoproc_double",
                       mesh="replica:2,data:4")
    xin = np.arange(32, dtype=np.float32).reshape(8, 4)
    out = flt.invoke([xin])[0]
    arr = out.jax() if hasattr(out, "jax") else out
    # the result is a GLOBAL array: verify this process's addressable
    # shards carry the right slices
    for sh in arr.addressable_shards:
        lo = sh.index[0].start or 0
        np.testing.assert_allclose(
            np.asarray(sh.data), xin[lo:lo + sh.data.shape[0]] * 2.0 + 1.0)
    print(f"filter ok process={{pid}} shards="
          f"{{len(arr.addressable_shards)}}", flush=True)
""")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


#: backend refusals that mean "this host cannot run cross-process
#: collectives at all" — the tests skip (environment limitation), they
#: don't fail.  "aren't implemented" is the CPU backend's own wording
#: ("Multiprocess computations aren't implemented on the CPU backend").
_SKIP_PATTERNS = ("UNIMPLEMENTED", "not supported", "aren't implemented",
                  "are not implemented")


def _run_two_workers(tmp_path, worker_src: str, timeout: int = 240):
    """Spawn the 2-process group, return per-worker outputs; skip the
    test when the backend refuses multi-process computation."""
    port = _free_port()
    script = tmp_path / "worker.py"
    script.write_text(worker_src.format(repo=REPO))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.pop("PYTHONPATH", None)  # keep the axon site hook intact
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(i), str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for i in range(2)]
    outs = []
    try:
        for pr in procs:
            out, _ = pr.communicate(timeout=timeout)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for pr in procs:
            pr.kill()
        pytest.fail("two-process workers timed out:\n" +
                    "\n".join(outs))
    for pr, out in zip(procs, outs):
        if pr.returncode != 0 and any(p in out
                                      for p in _SKIP_PATTERNS):
            pytest.skip(
                f"multi-process computation unsupported here: "
                f"{out[-400:]}")
    return procs, outs


def test_two_process_group_psum_and_sharded_filter(tmp_path):
    procs, outs = _run_two_workers(tmp_path, WORKER)
    for i, (pr, out) in enumerate(zip(procs, outs)):
        assert pr.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"psum ok process={i}" in out, out
        assert f"filter ok process={i}" in out, out


# -- ISSUE-12: two-process SHARED-POOL smoke ----------------------------------
#
# The multi-host pool: each process runs its own pipeline with
# share-model=true and a dcn-tier placement (mesh=dcn.data:2,data:4) —
# per-process window formation, ONE globally sharded dispatch whose
# micro-batch axis spans both processes' windows (2 x 4 frames over
# 8 shards).  A fleet of processes serving one logical pool.

POOL_WORKER = textwrap.dedent("""\
    import sys
    sys.path.insert(0, {repo!r})
    import numpy as np

    pid = int(sys.argv[1])
    port = sys.argv[2]

    from nnstreamer_tpu.parallel import multihost

    multihost.initialize(coordinator_address="127.0.0.1:" + port,
                         num_processes=2, process_id=pid)

    import jax
    assert len(jax.devices()) == 8, jax.devices()
    assert len(jax.local_devices()) == 4

    from nnstreamer_tpu.core import Buffer, TensorsSpec
    from nnstreamer_tpu.elements.basic import AppSink, AppSrc, Queue
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.filters.jax_xla import register_model
    from nnstreamer_tpu.runtime import Pipeline

    register_model("twoproc_pool", lambda x: x * 2.0 + 1.0,
                   in_shapes=[(4,)], in_dtypes=np.float32)
    spec = TensorsSpec.from_shapes([(4,)], np.float32)
    batch = 4
    p = Pipeline(name="pool" + str(pid))
    src = AppSrc(name="src", spec=spec, max_buffers=batch + 4)
    q = Queue(name="q", max_size_buffers=16)
    flt = TensorFilter(name="net", framework="jax-xla",
                       model="twoproc_pool", share_model=True,
                       batch=batch, batch_timeout_ms=60000.0,
                       batch_buckets=str(batch),
                       mesh="dcn.data:2,data:4")
    sink = AppSink(name="out", max_buffers=16)
    p.add(src, q, flt, sink).link(src, q, flt, sink)

    # a dispatch error (e.g. a backend that cannot run multi-process
    # computations at all) lands on the BUS; print it so the parent's
    # skip patterns can see the backend refusal instead of a timeout
    errs = []

    def watch(msg):
        if getattr(msg, "error", None) is not None:
            errs.append(msg.error)
            print("BUS ERROR:", repr(msg.error), flush=True)

    p.bus.add_watch(watch)
    p.start()
    rp = flt.pool.placement
    assert rp is not None
    assert rp.num_processes == 2, rp.num_processes
    assert rp.data_axis_size == 8, rp.data_axis_size
    assert rp.process_index == pid

    # one FULL local window per process -> exactly one globally
    # sharded dispatch; process-tagged values prove the demux hands
    # every process ITS OWN frames back
    for i in range(batch):
        src.push_buffer(Buffer.of(
            np.full((4,), 10.0 * pid + i, np.float32), pts=i))
    for i in range(batch):
        b = None
        for _ in range(18):
            b = sink.pull(timeout=5)
            if b is not None or errs:
                break
        if errs:
            raise SystemExit("dispatch error: " + repr(errs[0]))
        assert b is not None, i
        assert b.pts == i, (b.pts, i)
        np.testing.assert_allclose(
            np.asarray(b.tensors[0].np()),
            np.full((4,), (10.0 * pid + i) * 2.0 + 1.0))
    st = flt.pool.stats.snapshot()
    assert st["invokes"] == 1, st
    assert st["frames"] == batch, st
    src.end_of_stream()
    assert p.wait_eos(timeout=30)
    p.stop()
    print("pool ok process=" + str(pid), flush=True)
""")


def test_two_process_shared_pool_global_window(tmp_path):
    procs, outs = _run_two_workers(tmp_path, POOL_WORKER)
    for i, (pr, out) in enumerate(zip(procs, outs)):
        assert pr.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"pool ok process={i}" in out, out

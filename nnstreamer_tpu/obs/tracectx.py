"""Cross-device trace-context propagation — the wire side of the tracer.

A sampled buffer's trace dict (:mod:`.tracer`, ``Buffer.meta``) dies at
a process boundary: the edge wire serializes tensors, not meta.  This
module defines the small context blobs that carry a trace across a hop
and the clock math that places the remote spans back on the local
timeline (Documentation/observability.md, "Distributed tracing"):

- **request ctx** (query client → server): trace id + the client's send
  timestamp ``t1``.  The server continues the trace in its own process
  (:func:`plant_server_trace`) so its hook marks accumulate there.
- **reply ctx** (server → client): echoes ``t1``, adds the server's
  receive/send timestamps ``t2``/``t3`` and every mark the trace
  collected server-side.  :func:`absorb_reply` runs the NTP
  4-timestamp estimate (:func:`~nnstreamer_tpu.edge.ntputil
  .offset_and_delay`) over ``(t1, t2, t3, t4)`` — every traced query
  round-trip IS a clock sample — and attaches the offset-mapped remote
  marks to the local trace as a ``remote`` entry.  The estimate
  guarantees the mapped server window lands inside ``[t1, t4]``, so
  the client's network span always nests the server's spans.
- **one-way ctx** (edgesink/mqttsink/grpc sink → their sources): no
  return path, so alignment leans on wall clocks — the sender stamps an
  epoch (NTP-disciplined when the element has ``ntp-servers=``
  configured; lint ``NNS506`` flags the unaligned case) and the
  receiver derives the transit lag from its own epoch.

All timestamps inside marks and ``t1..t4`` are ``time.monotonic()``
seconds of their host — opaque to the other side, only ever differenced
or offset-mapped.  Contexts serialize as compact JSON: a few hundred
bytes, only on sampled buffers.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import time
from typing import Any, Dict, Optional, Tuple

from .tracer import PH_SOURCE, TRACE_META_KEY

CTX_VERSION = 1

#: trailer framing for transports without native extension room
#: (mqttsink payloads, the gRPC bridge frames): ``payload || json ||
#: len u32 || magic``.  Parsed from the END so the reader needs no
#: knowledge of the payload length.
TRAILER_MAGIC = b"NNSTRC01"
_TRAILER_FIXED = len(TRAILER_MAGIC) + 4


def host_tag() -> str:
    """Short stable identity of this process for remote span labels."""
    return f"{socket.gethostname()}:{os.getpid()}"


def encode_ctx(ctx: Dict[str, Any]) -> bytes:
    return json.dumps(ctx, separators=(",", ":")).encode("utf-8")


def decode_ctx(data: bytes) -> Optional[Dict[str, Any]]:
    """None (never an exception) on anything malformed — a trace ctx is
    advisory and must not break the data path."""
    try:
        ctx = json.loads(data.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        return None
    return ctx if isinstance(ctx, dict) else None


# -- query (round-trip) context ----------------------------------------------


def request_ctx(tr: Dict[str, Any], t1: float) -> Dict[str, Any]:
    """Client-side context sent WITH a traced query."""
    return {"v": CTX_VERSION, "id": tr.get("id"), "frame": tr.get("frame"),
            "t1": t1}


def plant_server_trace(meta: Dict[str, Any], ctx: Dict[str, Any],
                       source_name: str) -> None:
    """Continue a propagated trace in the server process: the planted
    dict rides ``Buffer.meta`` through the server pipeline, collecting
    hook marks exactly like a locally-sampled trace, and keeps the
    request timestamps the reply context echoes back."""
    meta[TRACE_META_KEY] = {
        "frame": ctx.get("frame"),
        "id": ctx.get("id"),
        "origin": "remote",
        "marks": [(time.monotonic(), source_name, PH_SOURCE)],
        "net": {"t1": ctx.get("t1"), "t2": ctx.get("t2")},
    }


def reply_ctx(tr: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Server-side context for the reply of a remote-origin trace (None
    when the buffer's trace did not arrive over the wire)."""
    if not isinstance(tr, dict):
        return None
    net = tr.get("net")
    if not isinstance(net, dict):
        return None
    return {"v": CTX_VERSION, "id": tr.get("id"), "frame": tr.get("frame"),
            "t1": net.get("t1"), "t2": net.get("t2"),
            "host": host_tag(),
            "marks": [list(m) for m in tr.get("marks", ())],
            # the server's wall clock at t3: lets an ntp-disciplined
            # client CROSS-CHECK the in-band span placement (the
            # symmetric-delay assumption) against wall-clock lag
            "epoch3_us": int(time.time() * 1e6),
            "t3": time.monotonic()}


def absorb_reply(tr: Dict[str, Any], ctx: Dict[str, Any], t4: float,
                 link: str) -> Optional[Tuple[float, float]]:
    """Fold a reply context into the local trace dict as a ``remote``
    entry, mapping the server marks onto the local monotonic timeline
    with the per-exchange offset.  Returns ``(offset_s, delay_s)`` for
    the caller's :class:`~nnstreamer_tpu.edge.ntputil.PeerClock`, or
    None when the context lacks usable timestamps."""
    from ..edge.ntputil import offset_and_delay

    t1, t2, t3 = ctx.get("t1"), ctx.get("t2"), ctx.get("t3")
    if not all(isinstance(t, (int, float)) for t in (t1, t2, t3)):
        return None
    offset, delay = offset_and_delay(t1, t2, t3, t4)
    marks = []
    for m in ctx.get("marks", ()):
        if isinstance(m, (list, tuple)) and len(m) == 3 \
                and isinstance(m[0], (int, float)):
            marks.append((m[0] - offset, str(m[1]), str(m[2])))
    tr.setdefault("remote", []).append({
        "link": link,
        "host": str(ctx.get("host", "?")),
        "t_out": t1, "t_in": t4,
        "t2": t2 - offset, "t3": t3 - offset,
        "rtt_s": delay, "offset_s": offset,
        "marks": marks,
    })
    return offset, delay


# -- one-way (pub/sub) context ------------------------------------------------


def oneway_ctx(tr: Dict[str, Any], epoch_us: int) -> Dict[str, Any]:
    """Sender-side context for a one-way hop (edgesink / mqttsink /
    the gRPC bridge): marks so far + a monotonic send stamp + a wall
    epoch the receiver differences against its own."""
    return {"v": CTX_VERSION, "id": tr.get("id"), "frame": tr.get("frame"),
            "host": host_tag(), "t_send": time.monotonic(),
            "epoch_us": int(epoch_us),
            "marks": [list(m) for m in tr.get("marks", ())]}


def plant_oneway(meta: Dict[str, Any], ctx: Dict[str, Any],
                 recv_epoch_us: int, link: str,
                 source_name: str) -> None:
    """Receiver side of a one-way hop: start a NEW local trace whose
    ``remote`` entry holds the sender's offset-mapped marks.  The lag
    estimate is ``local_epoch - sender_epoch`` — one-way delay plus
    inter-host wall-clock error, which is why unaligned clocks (no NTP
    on either end) skew these spans (lint NNS506)."""
    now = time.monotonic()
    t_send = ctx.get("t_send")
    epoch_us = ctx.get("epoch_us")
    if not isinstance(t_send, (int, float)) \
            or not isinstance(epoch_us, (int, float)):
        return
    lag_s = max((recv_epoch_us - float(epoch_us)) / 1e6, 0.0)
    send_local = now - lag_s
    marks = []
    for m in ctx.get("marks", ()):
        if isinstance(m, (list, tuple)) and len(m) == 3 \
                and isinstance(m[0], (int, float)):
            marks.append((min(send_local + (m[0] - t_send), now),
                          str(m[1]), str(m[2])))
    meta[TRACE_META_KEY] = {
        "frame": ctx.get("frame"),
        "id": ctx.get("id"),
        "marks": [(now, source_name, PH_SOURCE)],
        "remote": [{
            "link": link, "host": str(ctx.get("host", "?")),
            "t_out": send_local, "t_in": now,
            "t2": send_local, "t3": send_local,
            "rtt_s": None, "offset_s": lag_s,
            "marks": marks,
        }],
    }


# -- trailer framing (mqtt payloads, grpc frames) ------------------------------


def append_trailer(payload: bytes, ctx: Dict[str, Any]) -> bytes:
    """``payload || json || len u32 || magic`` — receivers that predate
    trace contexts and parse ``payload`` by its own declared sizes
    ignore the suffix."""
    blob = encode_ctx(ctx)
    return payload + blob + struct.pack("<I", len(blob)) + TRAILER_MAGIC


def split_trailer(data: bytes
                  ) -> Tuple[bytes, Optional[Dict[str, Any]]]:
    """Inverse of :func:`append_trailer`; ``(data, None)`` when no (or a
    malformed) trailer is present."""
    if len(data) < _TRAILER_FIXED \
            or data[-len(TRAILER_MAGIC):] != TRAILER_MAGIC:
        return data, None
    (blen,) = struct.unpack_from("<I", data, len(data) - _TRAILER_FIXED)
    end = len(data) - _TRAILER_FIXED
    if blen > end:
        return data, None
    ctx = decode_ctx(data[end - blen:end])
    if ctx is None:
        return data, None
    return data[:end - blen], ctx

"""Shared helpers for the model importers (tflite/tf/onnx).

The importers rebuild graphs that were exported at batch 1; keeping
them batch-flexible without silently regrouping interior reshapes is a
shared contract, implemented once here so the importers cannot drift.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def batch_flex_target(tgt: Tuple[int, ...],
                      value_shape: Sequence[int],
                      batch: int,
                      recorded_src: Optional[Sequence[int]] = None
                      ) -> Tuple[int, ...]:
    """Rewrite a concrete reshape target exported at batch 1 to be
    batch-flexible — ``(1, ...) -> (-1, ...)`` — ONLY when the leading
    1 is actually the batch dim:

    * the graph recorded a static source shape that also leads with
      the batch (``recorded_src[0] == 1``), i.e. a pure per-sample
      regroup; or
    * no static source shape is available, but the runtime value's
      per-sample element count matches the target's
      (``prod(value_shape)/batch == prod(tgt[1:])``).

    An interior reshape whose leading 1 is a genuine dimension keeps
    its concrete shape and fails loudly at batch > 1 instead of
    silently regrouping elements.
    """
    if tgt and tgt[0] == 1 and -1 in tgt[1:]:
        # wildcard tail (e.g. ONNX's (1, -1)): the per-sample count is
        # unknowable, but a leading 1 alongside a tail wildcard can
        # only mean the batch — pin it to the runtime batch so the
        # wildcard resolves per sample
        b = max(int(batch), 1)
        if int(np.prod(value_shape)) % b == 0:
            return (b,) + tgt[1:]
        return tgt
    if not (tgt and tgt[0] == 1 and -1 not in tgt[1:]):
        return tgt
    has_src = recorded_src is not None and len(recorded_src) > 0
    if has_src:
        ok = recorded_src[0] == 1
    else:
        b = max(int(batch), 1)
        total = int(np.prod(value_shape))
        ok = (total % b == 0
              and total // b == int(np.prod(tgt[1:])))
    return (-1,) + tgt[1:] if ok else tgt


def parse_custom_prop(custom: str, key: str, default: str) -> str:
    """Extract ``key:<value>`` from a tensor_filter ``custom=`` string
    (comma-separated ``k:v`` pairs, whitespace tolerated) — shared by
    the importer front ends so the grammar cannot drift."""
    for kv in (custom or "").split(","):
        kv = kv.strip()
        if kv.startswith(key + ":"):
            return kv.split(":", 1)[1].strip()
    return default

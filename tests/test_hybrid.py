"""MQTT-hybrid connect type: broker-mediated discovery, TCP data plane.

Parity target: the reference's HYBRID connect type
(/root/reference/gst/nnstreamer/tensor_query/README.md:74-99): the MQTT
broker carries only topic/discovery control — the query server publishes
its TCP address under a topic as a retained message; clients look it up
and move the actual tensors over plain TCP.  When the server dies and a
replacement registers the same topic, a reconnecting client re-queries
the broker and finds the new address (reconnect-to-alternates).
"""

import time

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, Caps, TensorsSpec
from nnstreamer_tpu.edge.mqtt import MiniBroker, MqttClient
from nnstreamer_tpu.elements.basic import AppSink, AppSrc
from nnstreamer_tpu.filters.jax_xla import register_model
from nnstreamer_tpu.runtime import Pipeline
from nnstreamer_tpu.runtime.registry import make

SPEC = TensorsSpec.parse("4:1", "float32")


@pytest.fixture
def broker():
    b = MiniBroker("127.0.0.1", 0)
    yield b
    b.stop()


def _server_pipeline(broker, sid, scale):
    """serversrc ! x*scale ! serversink over hybrid."""
    name = f"hy_scale_{sid}"
    register_model(name, lambda x: x * scale, in_shapes=[(1, 4)],
                   in_dtypes=np.float32)
    p = Pipeline(name=f"hy-server-{sid}")
    src = make("tensor_query_serversrc", el_name="qsrc", host="127.0.0.1",
               port=broker.port, connect_type="hybrid", id=sid,
               topic="hy-test", caps=Caps.from_spec(SPEC))
    flt = make("tensor_filter", el_name="f", framework="jax-xla",
               model=name)
    snk = make("tensor_query_serversink", el_name="qsink", id=sid)
    p.add(src, flt, snk).link(src, flt, snk)
    return p


def _client_pipeline(broker, **kw):
    p = Pipeline(name="hy-client")
    src = AppSrc(name="src", spec=SPEC)
    # generous timeout: the server's first invoke includes XLA compile,
    # which can exceed 10s on a loaded machine (same as test_edge.py)
    cli = make("tensor_query_client", el_name="cli", host="127.0.0.1",
               port=broker.port, connect_type="hybrid", topic="hy-test",
               timeout=30000, **kw)
    snk = AppSink(name="out", max_buffers=64)
    p.add(src, cli, snk).link(src, cli, snk)
    return p, src, cli, snk


class TestRetainedDiscovery:
    def test_broker_retains_and_clears(self, broker):
        pub = MqttClient("127.0.0.1", broker.port, "pub")
        pub.publish("nns-edge/t1/address", b"10.0.0.1:9000", retain=True)
        time.sleep(0.1)
        sub = MqttClient("127.0.0.1", broker.port, "sub", timeout=2.0)
        sub.subscribe("nns-edge/t1/address")
        got = sub.recv_publish()
        assert got is not None and got[1] == b"10.0.0.1:9000"
        sub.close()
        # empty retained payload clears the slot
        pub.publish("nns-edge/t1/address", b"", retain=True)
        time.sleep(0.1)
        sub2 = MqttClient("127.0.0.1", broker.port, "sub2", timeout=1.0)
        sub2.subscribe("nns-edge/t1/address")
        assert sub2.recv_publish() is None
        sub2.close()
        pub.close()


class TestHybridQuery:
    def test_round_trip(self, broker):
        srv = _server_pipeline(broker, sid=31, scale=2.0)
        with srv:
            p, src, cli, snk = _client_pipeline(broker)
            with p:
                for i in range(4):
                    src.push_buffer(Buffer.of(
                        np.full((1, 4), float(i), np.float32), pts=i))
                src.end_of_stream()
                assert p.wait_eos(timeout=30)
                out = []
                while True:
                    b = snk.pull(timeout=0.3)
                    if b is None:
                        break
                    out.append(b)
        assert [b.pts for b in out] == list(range(4))
        for b in out:
            np.testing.assert_array_equal(
                b.tensors[0].np(),
                np.full((1, 4), 2.0 * b.pts, np.float32))

    def test_server_moves_client_rediscovers(self, broker):
        """The reconnect-to-alternates story: the server process dies, a
        replacement registers the SAME topic at the broker (different
        ephemeral TCP port), and the client's failover re-queries the
        broker mid-stream."""
        srv1 = _server_pipeline(broker, sid=32, scale=2.0)
        srv1.start()
        srv2 = None
        p, src, cli, snk = _client_pipeline(broker)
        try:
            with p:
                src.push_buffer(Buffer.of(
                    np.zeros((1, 4), np.float32), pts=0))
                first = snk.pull(timeout=10)
                assert first is not None and first.pts == 0
                # the server moves: old one torn down, replacement with a
                # NEW data port registers the same topic
                srv1.stop()
                srv2 = _server_pipeline(broker, sid=33, scale=3.0)
                srv2.start()
                for i in range(1, 5):
                    src.push_buffer(Buffer.of(
                        np.full((1, 4), float(i), np.float32), pts=i))
                src.end_of_stream()
                assert p.wait_eos(timeout=30)
                out = []
                while True:
                    b = snk.pull(timeout=0.3)
                    if b is None:
                        break
                    out.append(b)
        finally:
            srv1.stop()  # idempotent; covers an early assertion failure
            if srv2 is not None:
                srv2.stop()
        assert [b.pts for b in out] == list(range(1, 5))
        for b in out:  # answered by the REPLACEMENT server (scale=3)
            np.testing.assert_array_equal(
                b.tensors[0].np(),
                np.full((1, 4), 3.0 * b.pts, np.float32))


class TestHybridRobustness:
    def test_cross_host_bind_and_advertise(self, broker):
        """data-host=0.0.0.0 binds all interfaces and the advertised
        address resolves to a dialable IP, not the bind wildcard."""
        from nnstreamer_tpu.edge.transport import HybridServer

        srv = HybridServer("127.0.0.1", broker.port, topic="xh",
                           data_host="0.0.0.0")
        srv.start()
        try:
            addr = srv._advertised_addr()
            host, _, port = addr.rpartition(":")
            assert host not in ("0.0.0.0", "::", "")
            assert int(port) == srv.port
            sub = MqttClient("127.0.0.1", broker.port, "chk", timeout=2.0)
            sub.subscribe("nns-edge/xh/address")
            got = sub.recv_publish()
            sub.close()
            assert got is not None and got[1].decode() == addr
        finally:
            srv.stop()

    def test_explicit_advertise_host_wins(self, broker):
        from nnstreamer_tpu.edge.transport import HybridServer

        srv = HybridServer("127.0.0.1", broker.port, topic="xh2",
                           data_host="0.0.0.0",
                           advertise_host="10.1.2.3")
        srv.start()
        try:
            assert srv._advertised_addr() == f"10.1.2.3:{srv.port}"
        finally:
            srv.stop()

    def test_broker_restart_readvertises(self):
        """A broker restart without retained persistence must not
        de-advertise a healthy server: the advertise loop re-publishes
        and reconnects, so late clients still discover the server."""
        from nnstreamer_tpu.edge.transport import (
            HybridServer,
            connect_hybrid,
        )

        b1 = MiniBroker("127.0.0.1", 0)
        port = b1.port
        srv = HybridServer("127.0.0.1", port, topic="rb")
        srv.start()
        try:
            b1.stop()                      # broker dies, retained lost
            time.sleep(0.3)
            b2 = MiniBroker("127.0.0.1", port)  # restart, same port
            try:
                conn = connect_hybrid("127.0.0.1", port, topic="rb",
                                      timeout=8.0)  # > adv interval
                assert conn.is_alive()
                conn.close()
            finally:
                b2.stop()
        finally:
            srv.stop()

    def test_subscribe_tolerates_publish_before_suback(self, broker):
        """MQTT 3.1.1 §3.8.4: a broker may deliver retained PUBLISHes
        before the SUBACK; subscribe must park them for recv_publish."""
        pub = MqttClient("127.0.0.1", broker.port, "p1")
        pub.publish("early/t", b"payload", retain=True)
        time.sleep(0.1)
        sub = MqttClient("127.0.0.1", broker.port, "s1", timeout=2.0)
        # simulate publish-before-suback by parking a frame directly:
        # the parsing path recv_publish takes must drain _pending first
        sub._pending.append(("early/t", b"parked"))
        sub.subscribe("early/t")
        assert sub.recv_publish() == ("early/t", b"parked")
        got = sub.recv_publish()
        assert got == ("early/t", b"payload")
        sub.close()
        pub.close()

    def test_rolling_restart_keeps_successor_advertised(self, broker):
        """new-up-then-old-down deploys: the old server's stop() must
        not clear the slot the replacement has already overwritten."""
        from nnstreamer_tpu.edge.transport import (
            HybridServer,
            connect_hybrid,
        )

        old = HybridServer("127.0.0.1", broker.port, topic="rr")
        old.start()
        new = HybridServer("127.0.0.1", broker.port, topic="rr")
        new.start()                      # overwrites the retained slot
        try:
            old.stop()                   # must NOT de-advertise `new`
            conn = connect_hybrid("127.0.0.1", broker.port, topic="rr",
                                  timeout=3.0)
            assert conn.is_alive()
            conn.close()
        finally:
            new.stop()
        # after the LAST server stops, the slot is actually cleared
        with pytest.raises(OSError):
            connect_hybrid("127.0.0.1", broker.port, topic="rr",
                           timeout=0.5)

    def test_broker_failures_surface_as_oserror(self, broker):
        """Broker-level failures (no server registered, broker gone)
        must be OSError so the query client's failover loop handles them
        like any unreachable server instead of dying on StreamError."""
        from nnstreamer_tpu.edge.transport import connect_hybrid

        with pytest.raises(OSError):
            connect_hybrid("127.0.0.1", broker.port, topic="nobody",
                           timeout=0.5)
        b2 = MiniBroker("127.0.0.1", 0)
        b2.stop()
        with pytest.raises(OSError):
            connect_hybrid("127.0.0.1", b2.port, topic="x", timeout=0.5)


class TestHybridEdge:
    def test_pubsub_over_hybrid(self, broker):
        pub = Pipeline(name="hy-pub")
        psrc = AppSrc(name="src", spec=SPEC)
        esink = make("edgesink", el_name="es", host="127.0.0.1",
                     port=broker.port, connect_type="hybrid",
                     topic="hy-video")
        pub.add(psrc, esink).link(psrc, esink)
        out = []
        with pub:
            sub = Pipeline(name="hy-sub")
            esrc = make("edgesrc", el_name="er", dest_host="127.0.0.1",
                        dest_port=broker.port, connect_type="hybrid",
                        topic="hy-video", num_buffers=3,
                        caps="other/tensors,dimensions=4:1,types=float32")
            ssnk = AppSink(name="out", max_buffers=16)
            sub.add(esrc, ssnk).link(esrc, ssnk)
            with sub:
                time.sleep(0.3)  # let the subscriber attach
                for i in range(3):
                    psrc.push_buffer(Buffer.of(
                        np.full((1, 4), float(i), np.float32), pts=i))
                assert sub.wait_eos(timeout=20)
                while True:
                    b = ssnk.pull(timeout=0.3)
                    if b is None:
                        break
                    out.append(b)
        assert len(out) == 3
        np.testing.assert_array_equal(
            out[2].tensors[0].np(), np.full((1, 4), 2.0, np.float32))

"""``image_labeling`` decoder: classification scores → label text.

Parity target: /root/reference/ext/nnstreamer/tensor_decoder/
tensordec-imagelabel.c (:246 register; 274 LoC): argmax over the score
tensor, label looked up from the file given as option1 (one label per line,
same as tests/test_models/labels/labels.txt).

TPU-native note: when the incoming tensor is device-resident the argmax runs
on device (a jitted reduction) and only the winning index crosses to host.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core import Buffer, Caps, CapsStruct, Tensor, TensorSpec, TensorsSpec
from . import Decoder, register_decoder


def _jit_argmax():
    import jax

    @jax.jit
    def f(x):
        # one packed (2,) result: winner index + score cross to host
        # together as a SINGLE small drain (counted via the Tensor
        # wrapper at the call site), not two separate fetches
        flat = x.reshape(-1)
        return jax.numpy.stack(
            [jax.numpy.argmax(flat).astype(jax.numpy.float32),
             jax.numpy.max(flat).astype(jax.numpy.float32)])

    return f


_argmax = None


@register_decoder
class ImageLabeling(Decoder):
    MODE = "image_labeling"

    def __init__(self):
        super().__init__()
        self.labels: List[str] = []

    def options_updated(self) -> None:
        path = self.options[0]
        if path:
            with open(path, "r", encoding="utf-8") as f:
                self.labels = [ln.strip() for ln in f if ln.strip()]

    def out_caps(self, in_spec: TensorsSpec) -> Caps:
        return Caps.new(CapsStruct.make(
            "text/x-raw", format="utf8", framerate=in_spec.rate))

    def prereduce_active(self, buf: Buffer) -> bool:
        return buf.tensors[0].is_device

    def decode(self, buf: Buffer, in_spec: Optional[TensorsSpec]) -> Buffer:
        global _argmax
        t = buf.tensors[0]
        if t.is_device:
            if _argmax is None:
                _argmax = _jit_argmax()
            pair = Tensor(_argmax(t.jax())).np()
            idx, score = int(pair[0]), float(pair[1])
        else:
            flat = t.np().reshape(-1)
            idx = int(np.argmax(flat))
            score = float(flat[idx])
        label = self.labels[idx] if idx < len(self.labels) else str(idx)
        payload = label.encode("utf-8")
        out = Tensor(np.frombuffer(payload, dtype=np.uint8),
                     TensorSpec.from_shape((len(payload),), np.uint8))
        b = Buffer(tensors=[out], pts=buf.pts, duration=buf.duration,
                   meta=dict(buf.meta))
        b.meta.update({"label": label, "label_index": idx, "score": score})
        return b

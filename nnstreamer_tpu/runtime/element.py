"""Element / Pad model: the dataflow graph nodes of the pipeline runtime.

This is the framework's replacement for the GStreamer core that the reference
leans on (SURVEY.md §1 "the scheduler/runtime is GStreamer itself"): pads with
caps templates, chain-based push scheduling, event propagation, and a forward
caps-negotiation pass standing in for transform_caps/fixate_caps/set_caps
(parity target: /root/reference/gst/nnstreamer/tensor_filter/tensor_filter.c:188-194).

Scheduling model: *push*.  Source elements run a thread each; a buffer travels
downstream through direct ``chain()`` calls in that thread until it hits a
``queue`` element (thread boundary) or a sink.  Elements that merge multiple
upstream threads (mux/merge/join) serialize internally.  Because JAX dispatch
is asynchronous, a chain of device-side elements enqueues XLA work without
blocking — the Python thread races ahead while the TPU computes.
"""

from __future__ import annotations

import enum
import threading
from fractions import Fraction
from typing import Any, Callable, Dict, List, Optional

from ..core import Buffer, Caps, TensorsSpec
from ..obs import hooks as _hooks
from ..obs import transfer as _xfer
from ..obs.tracer import TRACE_META_KEY
from ..utils import profile as _profile
from . import admission as _admission
from .events import Event, EventKind, Message, MessageKind


class PadDirection(enum.Enum):
    SRC = "src"
    SINK = "sink"


class PadPresence(enum.Enum):
    ALWAYS = "always"
    REQUEST = "request"  # mux sink_%u style
    SOMETIMES = "sometimes"  # demux src_%u style


class NegotiationError(Exception):
    """Caps negotiation failure.

    Carries optional structured context so tooling (the ``analyze`` static
    verifier) can point at the exact link and caps that failed without
    parsing the message:

    - ``reason`` — symbolic cause: ``"empty"`` (empty intersection),
      ``"unfixable"`` (caps cannot be fixated), ``"no-spec"`` (source has
      no output schema yet), ``"unlinked"``, ``"open"`` (sub-plugin could
      not be opened), or ``None`` (unclassified rejection).
    - ``src_pad`` / ``sink_pad`` — the pads of the failing link.
    - ``upstream`` / ``downstream`` — the caps on each side.
    """

    def __init__(self, message: str, *, reason: Optional[str] = None,
                 src_pad: Optional["Pad"] = None,
                 sink_pad: Optional["Pad"] = None,
                 upstream: Optional["Caps"] = None,
                 downstream: Optional["Caps"] = None):
        super().__init__(message)
        self.reason = reason
        self.src_pad = src_pad
        self.sink_pad = sink_pad
        self.upstream = upstream
        self.downstream = downstream


class StreamError(Exception):
    pass


class Pad:
    """A connection point. ``caps``/``spec`` are set once negotiation fixes
    the stream schema on this pad."""

    __slots__ = ("name", "direction", "element", "peer", "caps", "spec")

    def __init__(self, name: str, direction: PadDirection, element: "Element"):
        self.name = name
        self.direction = direction
        self.element = element
        self.peer: Optional["Pad"] = None
        self.caps: Optional[Caps] = None
        self.spec: Optional[TensorsSpec] = None

    @property
    def template(self) -> Caps:
        return self.element.pad_template_caps(self)

    def link(self, other: "Pad") -> None:
        if self.direction == other.direction:
            raise ValueError(f"cannot link two {self.direction.value} pads")
        src, sink = (self, other) if self.direction == PadDirection.SRC \
            else (other, self)
        if src.peer is not None or sink.peer is not None:
            busy = src if src.peer is not None else sink
            raise ValueError(
                f"cannot link {src.element.name}.{src.name} -> "
                f"{sink.element.name}.{sink.name}: "
                f"{busy.element.name}.{busy.name} is already linked to "
                f"{busy.peer.element.name}.{busy.peer.name} (unlink first)")
        src.peer, sink.peer = sink, src

    def unlink(self) -> None:
        if self.peer is not None:
            self.peer.peer = None
            self.peer = None

    # -- data flow (src pads only) -----------------------------------------

    def push(self, buf: Buffer) -> None:
        peer = self.peer
        if peer is None:
            return  # unlinked src pad drops data (parity: unlinked gst pad)
        peer.element._chain_guarded(peer, buf)

    def push_event(self, event: Event) -> None:
        peer = self.peer
        if peer is not None:
            peer.element.handle_event(peer, event)

    def push_upstream_event(self, event: Event) -> None:
        """sink pad → upstream element (QoS path)."""
        peer = self.peer
        if peer is not None:
            peer.element.handle_upstream_event(peer, event)

    def __repr__(self):
        return f"<Pad {self.element.name}.{self.name} {self.direction.value}>"


class Element:
    """Base class of all pipeline elements."""

    # Factory name used by the registry / pipeline parser.
    FACTORY: str = ""

    def __init__(self, name: Optional[str] = None, **props):
        # Attributes the subclass assigned *before* chaining up are its
        # declared, settable properties (the GObject install_property
        # analog), plus the universal "name".  Internal state created
        # from here on (pads, stats, locks, ...) is NOT settable via
        # set_property — a typo matching an internal attr must raise,
        # not silently overwrite state.
        self._props_declared = frozenset(vars(self)) | {"name"}
        self.name = name or f"{self.FACTORY or type(self).__name__}0"
        self.sinkpads: List[Pad] = []
        self.srcpads: List[Pad] = []
        self.pipeline = None  # set by Pipeline.add
        self._eos_seen: set = set()
        self._lock = threading.Lock()
        # dedicated lock for the flow counters: fan-in elements are fed
        # by several source threads at once, and `d[k] += 1` is a racy
        # read-modify-write; kept separate from _lock (EOS tracking) so
        # the hot path never contends with event handling
        self._stats_lock = threading.Lock()
        self.stats: Dict[str, Any] = {"buffers_in": 0, "buffers_out": 0}
        # Per-element config files (parity: gst_tensor_parse_config_file,
        # nnstreamer_plugin_api_impl.c:1902).  Precedence: the file
        # overrides constructor values; set_property afterwards (incl.
        # later keys in a pipeline string) overrides the file.
        cfg = props.pop("config_file", None) or props.pop("config-file",
                                                          None)
        if cfg:
            self.load_config_file(str(cfg))
        for k, v in props.items():
            self.set_property(k, v)

    # -- properties (parity: GObject properties) ---------------------------

    def set_property(self, key: str, value: Any) -> None:
        attr = key.replace("-", "_")
        if attr not in self._props_declared:
            raise ValueError(f"{type(self).__name__} has no property {key!r}")
        setattr(self, attr, value)

    def get_property(self, key: str) -> Any:
        return getattr(self, key.replace("-", "_"))

    def load_config_file(self, path: str, skip=()) -> None:
        """Apply ``key=value`` lines (# comments, blank lines skipped) as
        properties, with the pipeline-string value grammar.  ``skip``
        names properties that must keep their current values (the parser
        passes the keys given explicitly alongside config-file)."""
        from .parser import _parse_value

        skip = {k.replace("-", "_") for k in skip}
        with open(path) as f:
            for ln, line in enumerate(f, 1):
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                if "=" not in line:
                    raise ValueError(
                        f"{path}:{ln}: expected key=value, got {line!r}")
                k, _, v = line.partition("=")
                if k.strip().replace("-", "_") in skip:
                    continue
                self.set_property(k.strip(), _parse_value(v.strip()))

    # -- pads ---------------------------------------------------------------

    def add_sink_pad(self, name: str = "sink") -> Pad:
        p = Pad(self._pad_name(name, self.sinkpads), PadDirection.SINK,
                self)
        self.sinkpads.append(p)
        return p

    def add_src_pad(self, name: str = "src") -> Pad:
        p = Pad(self._pad_name(name, self.srcpads), PadDirection.SRC, self)
        self.srcpads.append(p)
        return p

    @staticmethod
    def _pad_name(name: str, pads: List[Pad]) -> str:
        """Expand the ``%u`` pad-template wildcard to the lowest free
        index (``sink_%u`` → ``sink_0``, ``sink_1``, ...).  Two pads must
        never share a name: EOS tracking, the sync collector, and
        ``get_pad`` are all name-keyed."""
        if "%u" not in name:
            return name
        used = {p.name for p in pads}
        n = 0
        while name.replace("%u", str(n)) in used:
            n += 1
        return name.replace("%u", str(n))

    def get_pad(self, name: str) -> Pad:
        for p in self.sinkpads + self.srcpads:
            if p.name == name:
                return p
        rp = self.request_pad(name)
        if rp is not None:
            return rp
        raise KeyError(f"{self.name} has no pad {name!r}")

    def request_pad(self, name: str) -> Optional[Pad]:
        """Override in elements with REQUEST pads (mux sink_%u)."""
        return None

    @property
    def sinkpad(self) -> Pad:
        return self.sinkpads[0]

    @property
    def srcpad(self) -> Pad:
        return self.srcpads[0]

    def pad_template_caps(self, pad: Pad) -> Caps:
        """What this pad can accept/produce *before* negotiation. Dynamic so
        e.g. tensor_filter can narrow it from model I/O info.  Default is the
        full wildcard (generic sinks/plumbing accept any media)."""
        return Caps.any()

    # -- negotiation ---------------------------------------------------------

    def propose_src_caps(self, pad: Pad) -> Caps:
        """Caps this element wants to output on ``pad`` given its negotiated
        sink specs (parity: transform_caps in SRC direction). Default:
        passthrough of the first sink pad's caps."""
        if self.sinkpads and self.sinkpads[0].caps is not None:
            return self.sinkpads[0].caps
        return self.pad_template_caps(pad)

    def set_caps(self, pad: Pad, caps: Caps) -> None:
        """Fixed caps arrive on a sink pad; validate then negotiate our own
        src pads."""
        tpl = self.pad_template_caps(pad)
        m = tpl.intersect(caps)
        if m.is_empty():
            raise NegotiationError(
                f"{self.name}.{pad.name}: caps {caps} not accepted "
                f"(template {tpl})",
                reason="empty", sink_pad=pad, upstream=caps, downstream=tpl)
        pad.caps = caps
        try:
            pad.spec = caps.to_spec()
        except ValueError:
            pad.spec = None  # non-tensor media caps
        try:
            self.caps_negotiated(pad)
        except NegotiationError:
            raise
        except (ValueError, TypeError, KeyError) as e:
            raise NegotiationError(
                f"{self.name}.{pad.name}: cannot handle caps {caps}: {e}"
            ) from e
        if self._sink_caps_complete():
            self.negotiate_src_pads()

    def _sink_caps_complete(self) -> bool:
        return all(p.caps is not None for p in self.sinkpads if p.peer)

    def caps_negotiated(self, pad: Pad) -> None:
        """Hook: element saw fixed caps on a sink pad."""

    def negotiate_src_pads(self) -> None:
        for sp in self.srcpads:
            if sp.peer is None or sp.caps is not None:
                continue
            proposed = self.propose_src_caps(sp)
            allowed = proposed.intersect(sp.peer.template)
            if allowed.is_empty():
                raise NegotiationError(
                    f"link {self.name}.{sp.name} → "
                    f"{sp.peer.element.name}.{sp.peer.name}: cannot agree "
                    f"(proposed {proposed}; downstream {sp.peer.template})",
                    reason="empty", src_pad=sp, sink_pad=sp.peer,
                    upstream=proposed, downstream=sp.peer.template)
            try:
                fixed = allowed.fixate()
            except ValueError as e:
                raise NegotiationError(
                    f"link {self.name}.{sp.name} → "
                    f"{sp.peer.element.name}.{sp.peer.name}: cannot fixate "
                    f"caps {allowed}: {e}",
                    reason="unfixable", src_pad=sp, sink_pad=sp.peer,
                    upstream=allowed) from e
            sp.caps = fixed
            try:
                sp.spec = fixed.to_spec()
            except ValueError:
                sp.spec = None
            sp.peer.element.set_caps(sp.peer, fixed)

    # -- data flow -----------------------------------------------------------

    def count_stat(self, key: str, n: int = 1) -> None:
        """Thread-safe bump of a flow counter (multiple upstream threads
        may chain into one element concurrently)."""
        with self._stats_lock:
            self.stats[key] = self.stats.get(key, 0) + n

    def _chain_guarded(self, pad: Pad, buf: Buffer) -> None:
        # transfer-ledger label context (obs/transfer.py): crossings
        # performed while this element owns the buffer are attributed
        # to (pipeline, element); one flag read when obs is off
        x_on = _xfer.ACTIVE
        xctx = None
        try:
            self.count_stat("buffers_in")
            # tracer hook (obs/hooks.py): one global read + None check
            # when no tracer is attached — the GstTracer pre/post-chain
            # hook pair, read ONCE so attach mid-buffer stays paired
            tracer = _hooks.tracer
            if x_on:
                tr = buf.meta.get(TRACE_META_KEY) \
                    if tracer is not None else None
                xctx = _xfer.push_context(
                    self.pipeline.name if self.pipeline is not None
                    else "", self.name,
                    (tr,) if tr is not None else None)
            if tracer is not None:
                tracer.pre_chain(self, buf)
            if _profile.trace_active():
                with _profile.annotate(self.name):
                    self.chain(pad, buf)
            else:
                self.chain(pad, buf)
            if tracer is not None:
                tracer.post_chain(self, buf)
        except Exception as e:  # noqa: BLE001 - any failure (FilterError,
            # XLA runtime errors, ...) must surface as an ERROR bus message,
            # not silently kill the upstream streaming thread.
            self.post_error(e)
        finally:
            if x_on:
                _xfer.pop_context(xctx)

    def chain(self, pad: Pad, buf: Buffer) -> None:
        raise NotImplementedError(f"{type(self).__name__} has no chain")

    def push(self, buf: Buffer, pad: Optional[Pad] = None) -> None:
        self.count_stat("buffers_out")
        (pad or self.srcpad).push(buf)

    # -- events --------------------------------------------------------------

    def handle_event(self, pad: Pad, event: Event) -> None:
        """Default: EOS is forwarded downstream once *all* linked sink pads
        saw it; other events forward immediately."""
        if event.kind == EventKind.EOS:
            with self._lock:
                self._eos_seen.add(pad.name)
                linked = {p.name for p in self.sinkpads if p.peer}
                ready = linked <= self._eos_seen
            if ready:
                self.on_eos()
                self.forward_event(event)
        else:
            self.forward_event(event)

    def on_eos(self) -> None:
        """Hook: flush buffered state before EOS propagates."""

    def forward_event(self, event: Event) -> None:
        for sp in self.srcpads:
            sp.push_event(event)

    def handle_upstream_event(self, pad: Pad, event: Event) -> None:
        for p in self.sinkpads:
            p.push_upstream_event(event)

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        """Pipeline going to PLAYING (after negotiation)."""

    def stop(self) -> None:
        """Pipeline going to NULL."""

    # -- bus ------------------------------------------------------------------

    def post_message(self, msg: Message) -> None:
        if self.pipeline is not None:
            self.pipeline.post(msg)

    def post_error(self, err: BaseException) -> None:
        # bus FIRST: consumers watching for the ERROR must not wait on
        # any recorder work (even spawning the dump thread adds
        # schedulable delay on the erroring streaming thread)
        self.post_message(Message(MessageKind.ERROR, self.name, error=err))
        # black-box evidence: an error reaching the bus is one of the
        # flight recorder's trigger conditions (obs/flightrec.py);
        # rare path, so the lazy import costs nothing steady-state
        try:
            from ..obs.flightrec import FLIGHT
            from ..obs.metrics import REGISTRY

            # errors-as-a-series: the counter a watchdog alert rule can
            # rate over (a bus ERROR is an event; a fleet controller
            # scraping /metrics needs it as a time series)
            REGISTRY.counter(
                "nns_element_errors_total",
                "errors posted to a pipeline bus by an element",
                labelnames=("pipeline", "element"),
            ).labels(
                pipeline=getattr(self.pipeline, "name", "") or "",
                element=self.name,
            ).inc()
            FLIGHT.element_error(self.name, err)
        except Exception:
            # the black box must never break the error path it records
            pass

    def __repr__(self):
        return f"<{type(self).__name__} {self.name!r}>"


class SourceElement(Element):
    """Push source with its own streaming thread (parity: GstPushSrc/GstBaseSrc).

    Subclasses implement :meth:`create` returning a Buffer, or ``None`` for
    EOS.  ``output_spec()`` must return the fixed stream schema (sources start
    negotiation).  An upstream QoS throttle event caps the production rate
    (parity: tensor_rate → source interplay).
    """

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_src_pad()
        self._thread: Optional[threading.Thread] = None
        self._running = threading.Event()
        self._throttle_rate: Optional[Fraction] = None
        self._throttle_lock = threading.Lock()

    def output_caps(self) -> Caps:
        spec = self.output_spec()
        if spec is None:
            raise NegotiationError(
                f"{self.name}: source has no output spec", reason="no-spec")
        return Caps.from_spec(spec)

    def output_spec(self) -> Optional[TensorsSpec]:
        return None

    def create(self) -> Optional[Buffer]:
        raise NotImplementedError

    def negotiate(self) -> None:
        sp = self.srcpad
        if sp.peer is None:
            raise NegotiationError(f"{self.name}: source not linked",
                                   reason="unlinked", src_pad=sp)
        proposed = self.output_caps()
        allowed = proposed.intersect(sp.peer.template)
        if allowed.is_empty():
            raise NegotiationError(
                f"{self.name} → {sp.peer.element.name}: cannot agree "
                f"(source {proposed}; downstream {sp.peer.template})",
                reason="empty", src_pad=sp, sink_pad=sp.peer,
                upstream=proposed, downstream=sp.peer.template)
        try:
            fixed = allowed.fixate()
        except ValueError as e:
            raise NegotiationError(
                f"{self.name} → {sp.peer.element.name}: cannot fixate caps "
                f"{allowed}: {e}",
                reason="unfixable", src_pad=sp, sink_pad=sp.peer,
                upstream=allowed) from e
        sp.caps = fixed
        try:
            sp.spec = fixed.to_spec()
        except ValueError:
            sp.spec = None
        sp.peer.element.set_caps(sp.peer, fixed)

    def handle_upstream_event(self, pad: Pad, event: Event) -> None:
        if event.kind == EventKind.QOS_THROTTLE:
            with self._throttle_lock:
                self._throttle_rate = event.data.get("rate")
        # sources terminate upstream propagation

    def start(self) -> None:
        self._running.set()
        # deterministic name (nns:<pipeline>:<element>) + thread-
        # registry coverage: obs/prof.py joins profiler samples, lockdep
        # site labels and py-spy output on this string
        from ..obs import prof as _prof

        self._thread = _prof.element_thread(self, self._loop, "src")
        self._thread.start()

    def stop(self) -> None:
        self._running.clear()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _loop(self) -> None:
        import time

        from ..obs import prof as _prof

        # exact run/wait accounting (obs/prof.py): create()+throttle is
        # the wait side, push() — the whole downstream chain runs in
        # this thread — is the run side.  None under NNS_TPU_OBS_DISABLE
        # → the loop skips every clock read.
        pipe = getattr(self, "pipeline", None)
        acct = _prof.element_account(
            getattr(pipe, "name", "") or "-", self.name)
        t0 = c0 = 0.0
        last = None
        while self._running.is_set():
            if acct is not None:
                t0 = time.monotonic()
                c0 = time.thread_time()
            try:
                buf = self.create()
            except StreamError as e:
                self.post_error(e)
                break
            except Exception as e:  # noqa: BLE001 - report, don't kill pipeline
                self.post_error(e)
                break
            if buf is None:
                self.srcpad.push_event(Event.eos())
                break
            with self._throttle_lock:
                rate = self._throttle_rate
            if rate and rate > 0:
                now = time.monotonic()
                if last is not None:
                    wait = float(1 / rate) - (now - last)
                    if wait > 0:
                        time.sleep(wait)
                last = time.monotonic()
            if _admission.ACTIVE:
                # deadline anchor for SLO-aware admission
                # (runtime/admission.py): stamped at ingress, post-
                # throttle, only while a controller is armed somewhere
                # in the process
                buf.meta[_admission.INGRESS_TS_META] = time.monotonic()
            tracer = _hooks.tracer
            if tracer is not None:
                # trace starts HERE (post-throttle): the e2e latency a
                # sampled buffer reports is pipeline time, not the time
                # it sat waiting out a QoS rate cap
                tracer.source_created(self, buf)
            if acct is None:
                self.push(buf)
            else:
                t1 = time.monotonic()
                self.push(buf)
                acct.add(t1 - t0, time.monotonic() - t1,
                         time.thread_time() - c0)


class SinkElement(Element):
    """Base sink (parity: GstBaseSink): implement :meth:`render`.

    Sinks are where the async dispatch path fences: filters enqueue XLA
    work and push futures downstream (elements/filter.py), so by the
    time a buffer reaches a sink its device work may still be in
    flight.  The fence is *depth-1 pipelined*: rendering buffer N
    blocks until buffer N-1's device arrays completed — never on N's
    own — so the streaming thread preps window N while the device runs
    window N-1 (the overlap the async rework exists for), while
    run-ahead stays bounded at one window and an async XLA error
    surfaces HERE, on this sink's bus via ``_chain_guarded``, one
    window late at most.  EOS drains the retained window, so
    ``wait_eos()`` returning means every dispatched program finished.
    """

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad()
        # buffer N-1's completion witness, fenced when buffer N
        # arrives.  ONE array, not all of them: every output of a
        # program materializes together, and the device executes
        # dispatches in order, so the last program's output proves the
        # whole window done — and an error in an upstream program of
        # the window poisons the dependent final program, so it still
        # surfaces at this fence.  (Pins at most one window's output
        # in HBM — the consumer's own data, about to be read anyway.)
        self._pending_fence: Optional[Any] = None
        self._fence_lock = threading.Lock()

    def chain(self, pad: Pad, buf: Buffer) -> None:
        cur = None
        for t in reversed(buf.tensors):
            if t.is_device:
                cur = t.jax()
                break
        with self._fence_lock:
            prev, self._pending_fence = self._pending_fence, cur
        self._fence(prev)
        self.render(buf)

    def _fence(self, arr) -> None:
        if arr is None:
            return
        tracer = _hooks.tracer
        if tracer is None:
            arr.block_until_ready()
            return
        import time

        t0 = time.monotonic()
        arr.block_until_ready()
        tracer.sink_fenced(self, time.monotonic() - t0)

    def render(self, buf: Buffer) -> None:
        raise NotImplementedError

    def handle_event(self, pad: Pad, event: Event) -> None:
        if event.kind == EventKind.EOS:
            with self._fence_lock:
                prev, self._pending_fence = self._pending_fence, None
            try:
                # flush the retained window BEFORE EOS posts: "EOS on
                # the bus" must mean the device finished every window
                self._fence(prev)
            except Exception as e:  # noqa: BLE001 - an async XLA error
                # surfacing at the EOS fence still belongs on this
                # sink's bus (event delivery has no _chain_guarded)
                self.post_error(e)
            self.on_eos()
            self.post_message(Message(MessageKind.EOS, self.name))


class TransformElement(Element):
    """1-in/1-out element (parity: GstBaseTransform): implement
    :meth:`transform`; override :meth:`propose_src_caps` when not
    passthrough-caps."""

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad()
        self.add_src_pad()

    def chain(self, pad: Pad, buf: Buffer) -> None:
        out = self.transform(buf)
        if out is not None:
            self.push(out)

    def transform(self, buf: Buffer) -> Optional[Buffer]:
        raise NotImplementedError

"""Multi-host helpers: hybrid ICI/DCN mesh construction and sharded
compute over it (single-process: DCN axes of size 1, 8 virtual CPU
devices from the conftest XLA flags)."""

import numpy as np
import pytest

from nnstreamer_tpu.parallel.multihost import hybrid_mesh, process_info


def cpu_devices(n):
    import jax

    devs = jax.devices("cpu")
    if len(devs) < n:
        pytest.skip(f"need {n} cpu devices, have {len(devs)}")
    return devs


class TestInitialize:
    """`multihost.initialize` wraps jax.distributed.initialize with
    pass-only-what-was-given semantics (TPU pods autodetect everything;
    explicit args serve CPU/GPU clusters) — previously untested."""

    def test_explicit_args_pass_through(self, monkeypatch):
        import jax

        from nnstreamer_tpu.parallel import multihost

        calls = {}
        monkeypatch.setattr(jax.distributed, "initialize",
                            lambda **kw: calls.update(kw))
        multihost.initialize(coordinator_address="10.0.0.1:1234",
                             num_processes=4, process_id=2)
        assert calls == {"coordinator_address": "10.0.0.1:1234",
                         "num_processes": 4, "process_id": 2}

    def test_autodetect_passes_nothing(self, monkeypatch):
        import jax

        from nnstreamer_tpu.parallel import multihost

        calls = {"n": 0, "kw": None}

        def fake(**kw):
            calls["n"] += 1
            calls["kw"] = kw

        monkeypatch.setattr(jax.distributed, "initialize", fake)
        multihost.initialize()
        assert calls == {"n": 1, "kw": {}}


class _FakeDev:
    def __init__(self, pi, did):
        self.process_index = pi
        self.id = did

    def __repr__(self):
        return f"fake(p{self.process_index},d{self.id})"


class TestMeshByProcess:
    """`multihost._mesh_by_process` — the non-TPU fallback that groups
    devices by process_index (DCN axes span processes, ICI axes span
    each process's local devices) — previously untested."""

    def _devs(self, procs=2, per=2):
        # deliberately interleaved + shuffled ids: the grouper must
        # sort by process then device id, not rely on input order
        out = []
        for p in range(procs):
            for d in reversed(range(per)):
                out.append(_FakeDev(p, p * 10 + d))
        return out

    def test_groups_by_process_then_device_id(self):
        import jax

        from nnstreamer_tpu.parallel.multihost import _mesh_by_process

        arr = _mesh_by_process(jax, self._devs(2, 2), (2,), (2,))
        assert arr.shape == (2, 2)
        assert [[d.id for d in row] for row in arr] == [[0, 1],
                                                        [10, 11]]

    def test_local_prefix_when_more_devices_than_ici(self):
        import jax

        from nnstreamer_tpu.parallel.multihost import _mesh_by_process

        arr = _mesh_by_process(jax, self._devs(2, 3), (2,), (2,))
        # 3 local devices, ici wants 2: the lowest-id prefix serves
        assert [[d.id for d in row] for row in arr] == [[0, 1],
                                                        [10, 11]]

    def test_wrong_process_count_raises(self):
        import jax

        from nnstreamer_tpu.parallel.multihost import _mesh_by_process

        with pytest.raises(ValueError):
            _mesh_by_process(jax, self._devs(3, 2), (2,), (2,))

    def test_too_few_local_devices_raises(self):
        import jax

        from nnstreamer_tpu.parallel.multihost import _mesh_by_process

        with pytest.raises(ValueError):
            _mesh_by_process(jax, self._devs(2, 1), (2,), (4,))


class TestHybridMesh:
    def test_single_slice_mesh_keeps_axis_names(self):
        devs = cpu_devices(4)
        m = hybrid_mesh([("model", 2), ("data", 2)], devices=devs[:4])
        assert m.axis_names == ("replica", "model", "data")
        assert m.shape == {"replica": 1, "model": 2, "data": 2}

    def test_sharded_compute_over_mesh(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        devs = cpu_devices(8)
        m = hybrid_mesh([("model", 2), ("data", 4)], devices=devs[:8])
        x = np.arange(64, dtype=np.float32).reshape(8, 8)
        s = NamedSharding(m, P("data", "model"))
        xd = jax.device_put(x, s)
        y = jax.jit(lambda a: a * 2 + 1, out_shardings=s)(xd)
        np.testing.assert_array_equal(np.asarray(y), x * 2 + 1)

    def test_insufficient_devices_raises(self):
        devs = cpu_devices(1)
        with pytest.raises(ValueError):
            hybrid_mesh([("model", 64)], devices=devs)

    def test_process_info_single_host(self):
        idx, count = process_info()
        assert idx == 0 and count >= 1

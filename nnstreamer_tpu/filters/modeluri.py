"""Model-URI resolution hook (ML-Agent analog).

Parity target: /root/reference/gst/nnstreamer/ml_agent.c (156 LoC):
``mlagent://model/<name>/<version>`` URIs in the ``model=`` property are
resolved to real model paths through the platform's model database
before the filter opens them.

Here the scheme→resolver mapping is pluggable: a deployment registers a
resolver for its model registry (an on-disk store, an artifact service,
…) and every ``tensor_filter``/``FilterSingle`` resolves URIs before
framework detection.  A built-in ``file://`` resolver is registered.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict
from urllib.parse import urlparse

_lock = threading.Lock()
_resolvers: Dict[str, Callable[[str], Any]] = {}


def register_model_resolver(scheme: str,
                            fn: Callable[[str], Any]) -> None:
    """``fn(uri) -> model`` (a path or any model object the target
    framework accepts)."""
    with _lock:
        _resolvers[scheme.lower()] = fn


def unregister_model_resolver(scheme: str) -> None:
    with _lock:
        _resolvers.pop(scheme.lower(), None)


def resolve_model_uri(model: Any) -> Any:
    """Resolve scheme-qualified string models; multi-file model lists
    resolve per entry; everything else passes through untouched."""
    if isinstance(model, (list, tuple)):
        return type(model)(resolve_model_uri(m) for m in model)
    if not isinstance(model, str) or "://" not in model:
        return model
    scheme = urlparse(model).scheme.lower()
    with _lock:
        fn = _resolvers.get(scheme)
    if fn is None:
        raise KeyError(
            f"no model resolver for scheme {scheme!r} "
            f"(register one with register_model_resolver)")
    return fn(model)


def _file_resolver(uri: str) -> str:
    return urlparse(uri).path


register_model_resolver("file", _file_resolver)

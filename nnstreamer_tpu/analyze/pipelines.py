"""Pipeline-description corpus discovery for the analyzer CLI.

Two sources, both analyzed by CI:

- ``parse_launch("...")`` string literals in ``examples/*.py``, extracted
  by AST (f-string placeholders substitute a neutral ``0`` — the analyzer
  checks structure and caps grammar, not runtime values);
- the documentation example pipelines in
  ``nnstreamer_tpu.tools.gen_element_docs.EXAMPLES`` (the strings the
  generated element docs embed).

Doc examples are *fragments* (some start with ``... !`` or reference
models that only exist at runtime), so they analyze in fragment mode:
structurally-incomplete findings downgrade to info.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import List, Optional


@dataclasses.dataclass(frozen=True)
class CorpusEntry:
    label: str      # e.g. "examples/classify_stream.py:33"
    description: str
    fragment: bool


def _literal_string(node: ast.expr) -> Optional[str]:
    """Resolve a string literal / f-string / literal concatenation to
    text; formatted placeholders become ``0``."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            elif isinstance(v, ast.FormattedValue):
                parts.append("0")
            else:
                return None
        return "".join(parts)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _literal_string(node.left)
        right = _literal_string(node.right)
        if left is not None and right is not None:
            return left + right
    return None


def extract_parse_launch_strings(path: str) -> List[CorpusEntry]:
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    out: List[CorpusEntry] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = func.id if isinstance(func, ast.Name) else (
            func.attr if isinstance(func, ast.Attribute) else "")
        if name != "parse_launch" or not node.args:
            continue
        desc = _literal_string(node.args[0])
        if desc:
            out.append(CorpusEntry(
                label=f"{path}:{node.lineno}", description=desc,
                fragment=False))
    return out


def example_pipelines(examples_dir: str) -> List[CorpusEntry]:
    out: List[CorpusEntry] = []
    if os.path.isdir(examples_dir):
        for fname in sorted(os.listdir(examples_dir)):
            if fname.endswith(".py"):
                out += extract_parse_launch_strings(
                    os.path.join(examples_dir, fname))
    return out


def doc_pipelines() -> List[CorpusEntry]:
    from ..tools.gen_element_docs import EXAMPLES

    out: List[CorpusEntry] = []
    for name in sorted(EXAMPLES):
        desc = EXAMPLES[name]
        if desc.startswith("... !"):
            desc = desc[len("... !"):].strip()
        out.append(CorpusEntry(label=f"doc:{name}", description=desc,
                               fragment=True))
    return out


def default_corpus(examples_dir: str) -> List[CorpusEntry]:
    return example_pipelines(examples_dir) + doc_pipelines()

"""Filter sub-plugin layer (L2/L3): ABI, registry, frameworks."""

from .api import FilterError, FilterProps, FilterSubplugin, SHARED_MODELS
from .registry import (
    detect_framework,
    find_filter,
    list_filters,
    register_filter,
)
from .jax_xla import JaxXlaFilter, export_model, register_model, \
    unregister_model
from .custom import (
    CustomEasyFilter,
    Python3Filter,
    register_custom_easy,
    unregister_custom_easy,
)

__all__ = [
    "FilterError", "FilterProps", "FilterSubplugin", "SHARED_MODELS",
    "detect_framework", "find_filter", "list_filters", "register_filter",
    "JaxXlaFilter", "export_model", "register_model", "unregister_model",
    "CustomEasyFilter", "Python3Filter", "register_custom_easy",
    "unregister_custom_easy",
]

"""In-band and out-of-band events flowing between pipeline elements.

TPU-native replacement for the GstEvent subset nnstreamer relies on: EOS,
caps, segment, QoS throttling (tensor_rate → tensor_filter interplay,
/root/reference/gst/nnstreamer/elements/gsttensor_rate.c:81-88 and
tensor_filter.c:511), flush, and custom events (model RELOAD,
nnstreamer_plugin_api_filter.h:351-357).
"""

from __future__ import annotations

import dataclasses
import enum
from fractions import Fraction
from typing import Any, Dict, Optional


class EventKind(enum.Enum):
    EOS = "eos"
    FLUSH = "flush"
    SEGMENT = "segment"
    QOS_THROTTLE = "qos-throttle"  # upstream: requested max framerate
    RELOAD_MODEL = "reload-model"  # custom: hot model swap
    EPOCH_COMPLETE = "epoch-complete"  # trainer notifications
    TRAINING_COMPLETE = "training-complete"
    CUSTOM = "custom"


@dataclasses.dataclass
class Event:
    kind: EventKind
    data: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @classmethod
    def eos(cls) -> "Event":
        return cls(EventKind.EOS)

    @classmethod
    def flush(cls) -> "Event":
        return cls(EventKind.FLUSH)

    @classmethod
    def qos_throttle(cls, rate: Fraction) -> "Event":
        """Ask upstream producers to cap their rate (frames/sec)."""
        return cls(EventKind.QOS_THROTTLE, {"rate": Fraction(rate)})

    @classmethod
    def reload_model(cls, model: Any) -> "Event":
        return cls(EventKind.RELOAD_MODEL, {"model": model})


class MessageKind(enum.Enum):
    ERROR = "error"
    WARNING = "warning"
    EOS = "eos"
    LATENCY = "latency"
    ELEMENT = "element"  # element-specific info (stats, training progress)
    STATE = "state"


@dataclasses.dataclass
class Message:
    """Out-of-band message posted on the pipeline bus (parity: GstBus)."""

    kind: MessageKind
    source: str  # element name
    data: Dict[str, Any] = dataclasses.field(default_factory=dict)
    error: Optional[BaseException] = None

    def __str__(self):
        e = f" error={self.error!r}" if self.error else ""
        return f"<{self.kind.value} from {self.source}{e} {self.data}>"

"""Chaos subsystem: FaultPlan determinism + seams, the shared retry
policy/circuit breaker, SLO-aware admission control, and the recovery
paths a FaultPlan now drives deterministically (query failover
resend-at-most-once, pool error fan-out, per-owner error routing,
mqtt/edge reconnect)."""

import queue as pyq
import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu import chaos
from nnstreamer_tpu.chaos import (
    BreakerOpen,
    ChaosInvokeError,
    FaultPlan,
    FaultSpec,
    RetryPolicy,
)
from nnstreamer_tpu.chaos import hooks as chaos_hooks
from nnstreamer_tpu.chaos import retrypolicy
from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.obs.metrics import LinkMetrics
from nnstreamer_tpu.runtime import Pipeline
from nnstreamer_tpu.runtime.admission import (
    AdmissionController,
    parse_priority,
    priority_name,
)
from nnstreamer_tpu.runtime.events import MessageKind
from nnstreamer_tpu.runtime.registry import make
from nnstreamer_tpu.runtime.serving import MODEL_POOL, SharedBatcher

SPEC = TensorsSpec.parse("4:1", "float32")


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.uninstall_plan()
    yield
    chaos.uninstall_plan()
    MODEL_POOL.clear()


# -- FaultPlan ----------------------------------------------------------------


class TestFaultPlan:
    def test_parse_grammar(self):
        p = FaultPlan.parse(
            "seed=42;drop:p=0.5;delay:ms=20,every=3,match=qcli;"
            "slow-invoke:ms=5,after=2,count=1;queue-pressure:ms=1")
        assert p.seed == 42
        assert [s.fault for s in p.specs] == [
            "drop", "delay", "slow-invoke", "queue-pressure"]
        assert p.specs[1].ms == 20 and p.specs[1].every == 3
        assert p.specs[2].after == 2 and p.specs[2].count == 1

    @pytest.mark.parametrize("bad", [
        "", "seed=1", "nosuchfault:p=0.5", "drop:p=2.0",
        "drop:wat=1", "drop:dir=sideways",
    ])
    def test_parse_rejects(self, bad):
        with pytest.raises(ValueError):
            FaultPlan.parse(bad)

    def test_seeded_determinism(self):
        def run():
            p = FaultPlan.parse("seed=7;drop:p=0.4")
            return [p.wire("l", "tx", b"x") is not None
                    for _ in range(50)]

        assert run() == run()
        other = FaultPlan.parse("seed=8;drop:p=0.4")
        assert run() != [other.wire("l", "tx", b"x") is not None
                         for _ in range(50)]

    def test_every_after_count(self):
        p = FaultPlan([FaultSpec("drop", every=3, after=2, count=2)])
        fired = [p.wire("l", "tx", b"x") is not None for _ in range(14)]
        # events 1-2 skipped (after); then every 3rd of the rest fires,
        # capped at 2 injections
        assert fired.count(True) == 2
        assert p.counts() == {"drop": 2}

    def test_match_filters_by_label(self):
        p = FaultPlan([FaultSpec("drop", match="qcli")])
        assert p.wire("other:peer", "tx", b"x") is None
        assert p.wire("qcli:127.0.0.1:5", "tx", b"x").frames == []

    def test_direction_filter(self):
        p = FaultPlan([FaultSpec("drop", direction="rx")])
        assert p.wire("l", "tx", b"x") is None
        assert p.wire("l", "rx", b"x").frames == []

    def test_duplicate_and_delay_compose(self):
        p = FaultPlan([FaultSpec("duplicate"), FaultSpec("delay", ms=30)])
        op = p.wire("l", "tx", b"abc")
        assert op.frames == [b"abc", b"abc"]
        assert op.delay_s == pytest.approx(0.03)

    def test_corrupt_flips_bytes_only(self):
        p = FaultPlan([FaultSpec("corrupt")], seed=5)
        op = p.wire("l", "tx", b"hello world")
        assert len(op.frames) == 1 and op.frames[0] != b"hello world"
        # object frames (inproc) cannot be corrupted: untouched
        assert p.wire("l", "tx", object()) is None

    def test_reorder_swaps_adjacent(self):
        p = FaultPlan([FaultSpec("reorder", every=1)])
        first = p.wire("l", "tx", b"A")
        assert first.frames == []  # held
        second = p.wire("l", "tx", b"B")
        assert second.frames == [b"B", b"A"]  # released after the next
        assert p.flush_held("l", "tx") is None

    def test_partition_window_drops_everything(self):
        p = FaultPlan([FaultSpec("partition", ms=150, count=1)])
        assert p.wire("l", "tx", b"x").frames == []  # opens the window
        assert p.wire("l", "rx", b"y").frames == []  # both directions
        time.sleep(0.2)
        assert p.wire("l", "tx", b"z") is None  # window closed

    def test_invoke_faults(self):
        p = FaultPlan([FaultSpec("slow-invoke", ms=10, count=1),
                       FaultSpec("fail-invoke", after=1, count=1)])
        assert p.invoke_fault("m") == ("slow", pytest.approx(0.01))
        assert p.invoke_fault("m") == ("fail", 0.0)
        assert p.invoke_fault("m") is None
        from nnstreamer_tpu.chaos.plan import apply_invoke_fault

        q = FaultPlan([FaultSpec("fail-invoke")])
        with pytest.raises(ChaosInvokeError):
            apply_invoke_fault(q, "m")

    def test_queue_stall(self):
        p = FaultPlan([FaultSpec("queue-pressure", ms=7, count=1)])
        assert p.queue_stall("b") == pytest.approx(0.007)
        assert p.queue_stall("b") == 0.0

    def test_registry_counter_exported(self):
        from nnstreamer_tpu.obs.metrics import REGISTRY

        p = FaultPlan([FaultSpec("drop", count=1)])
        p.wire("l", "tx", b"x")
        fams = REGISTRY.collect()
        samples = fams["nns_chaos_injected_total"]["samples"]
        row = [s for s in samples
               if s["labels"].get("fault") == "drop"]
        assert row and row[0]["value"] >= 1

    def test_env_install(self, monkeypatch):
        monkeypatch.setattr(chaos_hooks, "_env_checked", False)
        monkeypatch.setenv("NNS_TPU_CHAOS", "seed=3;drop:p=0.1")
        chaos_hooks.maybe_install_from_env()
        assert chaos.active_plan() is not None
        assert chaos.active_plan().seed == 3

    def test_env_malformed_is_ignored(self, monkeypatch):
        monkeypatch.setattr(chaos_hooks, "_env_checked", False)
        monkeypatch.setenv("NNS_TPU_CHAOS", "not-a-fault")
        chaos_hooks.maybe_install_from_env()
        assert chaos.active_plan() is None


# -- retry policy -------------------------------------------------------------


class TestRetryPolicy:
    def test_backoff_grows_exponentially_with_jitter_bounds(self):
        pol = RetryPolicy(base_s=0.1, max_s=10.0, multiplier=2.0,
                          jitter=0.5, seed=1)
        assert pol.backoff() == 0.0
        seen = []
        for n in range(1, 6):
            pol.failure(OSError("x"))
            d = pol.backoff()
            lo, hi = 0.1 * 2 ** (n - 1) * 0.5, 0.1 * 2 ** (n - 1) * 1.5
            assert lo <= d <= hi
            seen.append(d)
        pol.success()
        assert pol.backoff() == 0.0

    def test_backoff_caps_at_max(self):
        pol = RetryPolicy(base_s=1.0, max_s=2.0, jitter=0.0,
                          fail_threshold=100)
        for _ in range(8):
            pol.failure(OSError("x"))
        assert pol.backoff() == pytest.approx(2.0)

    def test_breaker_open_half_open_closed(self):
        pol = RetryPolicy(fail_threshold=3, open_s=0.15, jitter=0.0,
                          base_s=0.01)
        for _ in range(3):
            assert pol.allow()
            pol.failure(OSError("x"))
        assert pol.state == retrypolicy.OPEN
        assert not pol.allow()  # open: rejected
        with pytest.raises(BreakerOpen):
            pol.check()
        time.sleep(0.2)
        assert pol.allow()  # half-open probe admitted
        assert pol.state == retrypolicy.HALF_OPEN
        pol.failure(OSError("y"))  # probe failed: re-opens
        assert pol.state == retrypolicy.OPEN
        time.sleep(0.2)
        assert pol.allow()
        pol.success()
        assert pol.state == retrypolicy.CLOSED
        assert pol.breaker_opens == 2

    def test_state_mirrors_into_link_metrics(self):
        m = LinkMetrics("t-link", "peer:1", kind="test")
        pol = RetryPolicy(fail_threshold=2, metrics=m)
        pol.failure(OSError("x"))
        pol.failure(OSError("x"))
        snap = m.snapshot()
        assert snap["breaker_state"] == retrypolicy.OPEN
        assert snap["backoff_level"] == 2
        assert snap["breaker_opens"] == 1
        pol.success()
        assert m.snapshot()["breaker_state"] == retrypolicy.CLOSED

    def test_wait_interruptible(self):
        pol = RetryPolicy(base_s=5.0, jitter=0.0)
        pol.failure(OSError("x"))
        stop = threading.Event()
        stop.set()
        t0 = time.monotonic()
        assert pol.wait(stop=stop, max_s=5.0) is False
        assert time.monotonic() - t0 < 1.0


# -- admission control --------------------------------------------------------


class TestAdmission:
    def test_parse_priority(self):
        assert parse_priority("high") == 0
        assert parse_priority("normal") == 1
        assert parse_priority("LOW") == 2
        assert parse_priority(2) == 2
        assert priority_name(0) == "high"
        with pytest.raises(ValueError):
            parse_priority("urgent")

    def test_ramp_and_at_risk(self):
        adm = AdmissionController(slo_s=0.1, window=64)
        for _ in range(32):
            adm.observe(0.01)  # well under
        assert not adm.at_risk and adm.shed_probability == 0.0
        for _ in range(64):
            adm.observe(0.5)  # way over
        assert adm.at_risk
        assert adm.shed_probability == 1.0
        assert adm.risk_episodes == 1

    def test_admit_protects_high_sheds_low(self):
        adm = AdmissionController(slo_s=0.05)
        for _ in range(64):
            adm.observe(1.0)
        assert adm.admit(parse_priority("high"))
        assert not adm.admit(parse_priority("low"))
        snap = adm.snapshot()
        assert snap["shed"]["low"] == 1
        assert snap["submitted"]["high"] == 1
        assert adm.total_shed == 1

    def test_shared_batcher_edf_formation(self):
        flushed = []
        sb = SharedBatcher(max_batch=2, timeout_s=1000.0,
                           flush_fn=flushed.extend, adaptive=False)
        sb.edf = True
        # park 4 frames directly (submit would inline-drain at the
        # window size): B's deadlines are tighter, so the first window
        # is all-B even though A arrived first — and each stream keeps
        # its own relative order (stable selection)
        now = time.monotonic()
        with sb._cv:
            sb._pending.extend([
                ("A", 1, now + 50.0, now), ("A", 2, now + 50.0, now),
                ("B", 3, now + 1.0, now), ("B", 4, now + 1.0, now)])
        sb._drain()
        assert [it[:2] for it in flushed] == [("B", 3), ("B", 4)]
        sb._drain()
        assert [it[:2] for it in flushed[2:]] == [("A", 1), ("A", 2)]

    def test_wait_below_backpressure_and_timeout(self):
        sb = SharedBatcher(max_batch=64, timeout_s=1000.0,
                           flush_fn=lambda items: None, adaptive=False)
        for i in range(4):
            sb.submit_from("A", i)
        assert sb.wait_below("B", 4, timeout_s=0.1)  # other stream
        t0 = time.monotonic()
        assert not sb.wait_below("A", 4, timeout_s=0.2)  # never drains
        assert 0.15 <= time.monotonic() - t0 <= 2.0

    def test_pool_slo_is_pool_level_conflict(self):
        from nnstreamer_tpu.filters.jax_xla import register_model
        from nnstreamer_tpu.runtime.element import NegotiationError

        model = register_model("chaos_adm_conflict", lambda x: x,
                               in_shapes=[(4,)], in_dtypes=np.float32)
        pipes = []
        p1, e1 = _pool_pipe("adm-c1", model, slo_ms=50.0)
        p1.start()
        pipes.append(p1)
        p2, e2 = _pool_pipe("adm-c2", model, slo_ms=80.0)
        try:
            with pytest.raises(Exception) as ei:
                p2.start()
            assert "slo" in str(ei.value).lower() or \
                "conflict" in str(ei.value).lower()
        finally:
            for p in pipes:
                p.stop()

    def test_ingress_stamp_gated_on_active_controller(self):
        from nnstreamer_tpu.filters.jax_xla import register_model
        from nnstreamer_tpu.runtime import admission as adm_mod

        model = register_model("chaos_adm_stamp", lambda x: x * 2.0,
                               in_shapes=[(4,)], in_dtypes=np.float32)
        assert not adm_mod.ACTIVE
        p, els = _pool_pipe("adm-stamp", model, slo_ms=100.0)
        p.start()
        try:
            assert adm_mod.ACTIVE  # armed by the pool attach
            els["src"].push_buffer(Buffer.of(
                np.zeros((1, 4), np.float32), pts=0))
            out = els["sink"].pull(timeout=10)
            assert out is not None
        finally:
            p.stop()
        assert not adm_mod.ACTIVE  # disarmed with the last stream

    def test_shed_posts_counter_and_bus_warning(self):
        from nnstreamer_tpu.filters.jax_xla import register_model

        model = register_model("chaos_adm_shed", lambda x: x + 1.0,
                               in_shapes=[(4,)], in_dtypes=np.float32)
        warns = []
        p_hi, hi = _pool_pipe("adm-hi", model, slo_ms=30.0,
                              priority="high")
        p_lo, lo = _pool_pipe("adm-lo", model, slo_ms=30.0,
                              priority="low")
        p_lo.bus.add_watch(
            lambda m: warns.append(m)
            if m.kind == MessageKind.WARNING else None)
        p_hi.start()
        p_lo.start()
        try:
            entry = hi["flt"].pool
            adm = entry.admission
            # force the at-risk state directly (deterministic — no
            # need to genuinely overload a CI machine)
            for _ in range(64):
                adm.observe(10.0)
            assert adm.shed_probability == 1.0
            for n in range(8):
                lo["src"].push_buffer(Buffer.of(
                    np.zeros((1, 4), np.float32), pts=n))
                hi["src"].push_buffer(Buffer.of(
                    np.zeros((1, 4), np.float32), pts=n))
            deadline = time.monotonic() + 10
            got_hi = 0
            while got_hi < 8 and time.monotonic() < deadline:
                if hi["sink"].pull(timeout=0.2) is not None:
                    got_hi += 1
            assert got_hi == 8  # high never shed
            assert adm.snapshot()["shed"]["low"] > 0
            assert warns and warns[0].data.get("shed") is True
            assert warns[0].data["priority"] == "low"
        finally:
            p_hi.stop()
            p_lo.stop()


def _pool_pipe(name, model, slo_ms=0.0, priority="normal"):
    from nnstreamer_tpu.elements.basic import AppSink, AppSrc, Queue
    from nnstreamer_tpu.elements.filter import TensorFilter

    spec = TensorsSpec.from_shapes([(4,)], np.float32)
    p = Pipeline(name=name)
    src = AppSrc(name="src", spec=spec, max_buffers=64)
    q = Queue(name="q", max_size_buffers=64)
    flt = TensorFilter(name="net", framework="jax-xla", model=model,
                       batch=4, batch_timeout_ms=2.0, batch_buckets="4",
                       share_model=True, slo_ms=slo_ms, priority=priority)
    sink = AppSink(name="sink", max_buffers=64)
    p.add(src, q, flt, sink).link(src, q, flt, sink)
    return p, {"src": src, "q": q, "flt": flt, "sink": sink}


# -- fault-plan-driven recovery coverage --------------------------------------


class TestPoolFaults:
    def test_fail_invoke_fans_out_to_every_sharing_bus(self):
        """SharedBatcher._error_all / the window-failure guard: ONE
        injected fail-invoke on the shared window must error on EVERY
        pipeline that parked a frame in it.

        Window composition is made DETERMINISTIC through the pause
        actuator (runtime/actuators.py): with coalescing paused, both
        streams' frames park in ONE window before the count=1 fault
        installs; resume dispatches that exact 4-frame cross-stream
        window into the fault.  (The old timing-based version let the
        2 ms deadline flush stream A's frames alone ~30% of the time —
        the poisoned window then carried one owner and B never
        errored.)"""
        from nnstreamer_tpu.filters.jax_xla import register_model

        model = register_model("chaos_fanout", lambda x: x * 3.0,
                               in_shapes=[(4,)], in_dtypes=np.float32)
        errs = {"a": [], "b": []}
        pa, ea = _pool_pipe("fan-a", model)
        pb, eb = _pool_pipe("fan-b", model)
        pa.bus.add_watch(lambda m: errs["a"].append(m)
                         if m.kind == MessageKind.ERROR else None)
        pb.bus.add_watch(lambda m: errs["b"].append(m)
                         if m.kind == MessageKind.ERROR else None)
        pa.start()
        pb.start()
        try:
            entry = ea["flt"].pool
            pause = entry.actuators()["coalescing"]
            pause.actuate(0.0)
            # two frames from each stream: with the window paused they
            # ALL park before anything dispatches
            for n in range(2):
                ea["src"].push_buffer(Buffer.of(
                    np.zeros((1, 4), np.float32), pts=n))
                eb["src"].push_buffer(Buffer.of(
                    np.zeros((1, 4), np.float32), pts=n))
            deadline = time.monotonic() + 10
            while entry.batcher.pending < 4 and \
                    time.monotonic() < deadline:
                time.sleep(0.01)
            assert entry.batcher.pending == 4
            # the ONE poisoned dispatch is the resumed 4-frame window
            chaos.install_plan(FaultPlan.parse(
                "seed=1;fail-invoke:count=1,match=pool:"))
            pause.revert()  # resume: drains the composed window
            deadline = time.monotonic() + 10
            while (not errs["a"] or not errs["b"]) and \
                    time.monotonic() < deadline:
                time.sleep(0.02)
            assert errs["a"] and errs["b"], errs
            assert isinstance(errs["a"][0].error, ChaosInvokeError)
        finally:
            chaos.uninstall_plan()
            pa.stop()
            pb.stop()

    def test_per_owner_error_routing_keeps_other_stream_alive(self):
        """A broken downstream in pipeline A (its demux raises) must
        error on A's bus only — B keeps receiving results from the SAME
        shared windows (serving.PoolEntry._dispatch demux guard)."""
        from nnstreamer_tpu.filters.jax_xla import register_model

        model = register_model("chaos_routing", lambda x: x - 1.0,
                               in_shapes=[(4,)], in_dtypes=np.float32)
        errs = {"a": [], "b": []}
        pa, ea = _pool_pipe("route-a", model)
        pb, eb = _pool_pipe("route-b", model)
        pa.bus.add_watch(lambda m: errs["a"].append(m)
                         if m.kind == MessageKind.ERROR else None)
        pb.bus.add_watch(lambda m: errs["b"].append(m)
                         if m.kind == MessageKind.ERROR else None)
        pa.start()
        pb.start()
        try:
            def boom(buf):
                raise RuntimeError("sink down")

            ea["sink"].render = boom  # break A's downstream only
            for n in range(2):
                ea["src"].push_buffer(Buffer.of(
                    np.zeros((1, 4), np.float32), pts=n))
                eb["src"].push_buffer(Buffer.of(
                    np.zeros((1, 4), np.float32), pts=n))
            got_b = 0
            deadline = time.monotonic() + 10
            while got_b < 2 and time.monotonic() < deadline:
                if eb["sink"].pull(timeout=0.2) is not None:
                    got_b += 1
            assert got_b == 2  # B unaffected
            assert errs["a"] and not errs["b"]
        finally:
            pa.stop()
            pb.stop()

    def test_slow_invoke_loses_nothing(self):
        from nnstreamer_tpu.filters.jax_xla import register_model

        model = register_model("chaos_slow", lambda x: x * 5.0,
                               in_shapes=[(4,)], in_dtypes=np.float32)
        p, e = _pool_pipe("slow-a", model)
        p.start()
        try:
            chaos.install_plan(FaultPlan.parse(
                "seed=2;slow-invoke:ms=15,p=0.5,match=pool:"))
            for n in range(12):
                e["src"].push_buffer(Buffer.of(
                    np.zeros((1, 4), np.float32), pts=n))
            got = 0
            deadline = time.monotonic() + 15
            while got < 12 and time.monotonic() < deadline:
                if e["sink"].pull(timeout=0.2) is not None:
                    got += 1
            assert got == 12
            assert chaos.active_plan().counts().get("slow-invoke", 0) > 0
        finally:
            chaos.uninstall_plan()
            p.stop()


# -- FaultPlan-driven query recovery (satellites 2 + 3) ------------------------


def _query_client_pipe(host, port, **kw):
    from nnstreamer_tpu.elements.basic import AppSink, AppSrc

    p = Pipeline(name="chaos-qp")
    src = AppSrc(name="src", spec=SPEC, max_buffers=256)
    kw.setdefault("timeout", 10000)
    cli = make("tensor_query_client", el_name="cli", host=host, port=port,
               connect_type="inproc", **kw)
    snk = AppSink(name="out", max_buffers=256)
    p.add(src, cli, snk).link(src, cli, snk)
    return p, src, cli, snk


class TestQueryFaults:
    def test_resend_at_most_once_unit(self, monkeypatch):
        """Satellite 2 (unit): an in-flight entry that already rode one
        failover resend is expired as a timeout on the NEXT one — never
        resent again (the old deadline-extension made it immortal)."""
        from nnstreamer_tpu.edge import query as query_mod

        cli = make("tensor_query_client", el_name="rcli",
                   host="h", port=1, connect_type="inproc", timeout=500)

        class FakeConn:
            def __init__(self):
                self.sent = []
                self.metrics = None

            def send(self, env):
                self.sent.append(env.seq)
                return True

            def close(self):
                pass

        dead = FakeConn()
        fresh = FakeConn()
        monkeypatch.setattr(query_mod, "connect",
                            lambda *a, **k: fresh)
        now = time.monotonic()
        buf = Buffer.of(np.zeros((1, 4), np.float32))
        cli._conn = dead
        cli.connected_addr = ("h", 1)
        # seq 1 was already resent once (resends=1); seq 2 never was
        cli._inflight[1] = [buf, None, now + 0.5, dead, now, 1]
        cli._inflight[2] = [buf, None, now + 0.5, dead, now, 0]
        cli._failover(dead)
        assert cli._conn is fresh
        assert fresh.sent == [2]          # only the fresh entry resent
        assert 1 not in cli._inflight     # the spent one timed out
        assert cli.timeouts == 1
        assert cli._inflight[2][5] == 1   # its one retry is now used
        cli.stop()

    def test_disconnect_flap_recovers_and_accounts(self):
        """Satellite 2 (end to end): injected disconnects mid-stream —
        the client fails over with backoff, resends in-flight requests
        at most once, and every frame is delivered or visibly timed
        out; EOS is reached (the old behavior could stall it)."""
        from tests.test_query_pipelining import DelayServer

        srv = DelayServer("inproc-chaos-flap", 7301, 0.05).start()
        try:
            p, src, cli, snk = _query_client_pipe(
                "inproc-chaos-flap", 7301, max_request=4, timeout=1500,
                chaos="seed=4;disconnect:every=9,dir=tx")
            n = 24
            with p:
                # closed-loop pacing (in-flight stays under
                # max-request): every frame actually reaches the wire,
                # so the every=9 disconnect schedule is deterministic
                got = []
                deadline = time.monotonic() + 60
                sent = 0
                while len(got) + cli.timeouts + cli.dropped < n and \
                        time.monotonic() < deadline:
                    while sent < n and sent - len(got) - cli.timeouts \
                            - cli.dropped < 3:
                        src.push_buffer(Buffer.of(
                            np.full((1, 4), float(sent), np.float32),
                            pts=sent))
                        sent += 1
                    b = snk.pull(timeout=0.25)
                    if b is not None:
                        got.append(b)
                src.end_of_stream()
                assert p.wait_eos(timeout=30)
                got.extend(iter(lambda: snk.pull(timeout=0.1), None))
            assert cli._metrics.snapshot()["reconnects"] >= 1
            assert len(got) + cli.timeouts + cli.dropped >= n
            # delivered frames still pair with their inputs (x2 server)
            for b in got:
                np.testing.assert_array_equal(
                    b.tensors[0].np(),
                    np.full((1, 4), 2.0 * float(b.pts), np.float32))
        finally:
            srv.stop()

    def test_seqless_drop_diagnostic_via_faultplan(self):
        """Satellite 3: the seq-less silent-drop story, driven by a
        FaultPlan drop on the request path instead of a hand-rolled
        lossy server: the stream stays live, every lost frame surfaces
        as a timeout, and accounting closes."""
        from tests.test_query_pipelining import DelayServer

        srv = DelayServer("inproc-chaos-sldrop", 7302, 0.0,
                          strip_seq=True).start()
        try:
            p, src, cli, snk = _query_client_pipe(
                "inproc-chaos-sldrop", 7302, max_request=2, timeout=400,
                chaos="seed=9;drop:every=7,dir=tx")
            n = 21
            with p:
                # closed-loop pacing so every frame reaches the wire
                # (a burst would be shed at max-request before the
                # fault plan ever saw it)
                got = 0
                sent = 0
                deadline = time.monotonic() + 60
                while got + cli.timeouts + cli.dropped < n and \
                        time.monotonic() < deadline:
                    while sent < n and \
                            sent - got - cli.timeouts - cli.dropped < 2:
                        src.push_buffer(Buffer.of(
                            np.full((1, 4), float(sent), np.float32),
                            pts=sent))
                        sent += 1
                    if snk.pull(timeout=0.25) is not None:
                        got += 1
                src.end_of_stream()
                assert p.wait_eos(timeout=30)
                got += sum(1 for _ in iter(
                    lambda: snk.pull(timeout=0.1), None))
            assert cli.timeouts > 0          # drops surfaced, loudly
            assert got + cli.timeouts + cli.dropped >= n
            assert got > 0                   # ...and the stream lived on
        finally:
            srv.stop()

    def test_tombstone_expiry_via_faultplan_delay(self):
        """Satellite 3: tombstone machinery driven by an injected REPLY
        delay — one answer held past the client timeout leaves a
        tombstone that absorbs it when it finally lands; later replies
        keep pairing with the right requests."""
        from tests.test_query_pipelining import DelayServer

        srv = DelayServer("inproc-chaos-tomb", 7303, 0.0,
                          strip_seq=True).start()
        try:
            # delay the reply for request 1 past the 400ms client
            # timeout — injected at the SERVER transport's tx seam
            # (process-wide plan), so the sleep runs on the server's
            # reply thread, not on the client reader that must keep
            # expiring.  tx event 1 is the caps handshake reply; event
            # 2 is the answer to request 0; event 3 (after=2, count=1)
            # is the delayed answer to request 1.
            chaos.install_plan(FaultPlan.parse(
                "seed=1;delay:ms=700,every=1,after=2,count=1,dir=tx,"
                "match=inproc-server"))
            p, src, cli, snk = _query_client_pipe(
                "inproc-chaos-tomb", 7303, max_request=8, timeout=400)
            with p:
                src.push_buffer(Buffer.of(
                    np.zeros((1, 4), np.float32), pts=0))
                first = snk.pull(timeout=5)
                assert first is not None and first.pts == 0
                src.push_buffer(Buffer.of(
                    np.full((1, 4), 1.0, np.float32), pts=1))
                time.sleep(0.5)  # request 1 expires (tombstone parked)
                assert cli.timeouts == 1
                for i in (2, 3):
                    src.push_buffer(Buffer.of(
                        np.full((1, 4), float(i), np.float32), pts=i))
                out = []
                deadline = time.monotonic() + 10
                while len(out) < 2 and time.monotonic() < deadline:
                    b = snk.pull(timeout=0.25)
                    if b is not None:
                        out.append(b)
                src.end_of_stream()
                assert p.wait_eos(timeout=15)
            # the late reply for 1 was absorbed by its tombstone: 2 and
            # 3 pair with THEIR answers, not shifted onto 1's
            assert [b.pts for b in out] == [2, 3]
            for b in out:
                np.testing.assert_array_equal(
                    b.tensors[0].np(),
                    np.full((1, 4), 2.0 * float(b.pts), np.float32))
        finally:
            srv.stop()


# -- self-healing links (mqtt + edge pub/sub) ---------------------------------


class TestSelfHealingLinks:
    def test_mqttsrc_reconnects_through_broker_restart(self):
        from nnstreamer_tpu.edge.mqtt import MiniBroker, MqttSink, MqttSrc
        from nnstreamer_tpu.elements.basic import AppSink, AppSrc

        broker = MiniBroker()
        port = broker.port
        spec = TensorsSpec.parse("4:1", "float32")
        psrc = Pipeline(name="mq-sub")
        msrc = MqttSrc(name="msrc", port=port, sub_topic="chaos/t",
                       num_buffers=2, sub_timeout=2.0,
                       reconnect_timeout_s=20.0)
        outs = AppSink(name="out", max_buffers=16)
        psrc.add(msrc, outs).link(msrc, outs)
        psrc.start()
        try:
            psink = Pipeline(name="mq-pub")
            asrc = AppSrc(name="src", spec=spec, max_buffers=16)
            msink = MqttSink(name="msink", port=port,
                             pub_topic="chaos/t",
                             reconnect_timeout_s=20.0)
            psink.add(asrc, msink).link(asrc, msink)
            psink.start()
            time.sleep(0.2)  # let the subscription settle
            asrc.push_buffer(Buffer.of(
                np.full((1, 4), 1.0, np.float32), pts=0))
            assert outs.pull(timeout=10) is not None
            # broker restart ON THE SAME PORT: both ends must reconnect
            broker.stop()
            time.sleep(0.3)
            broker = MiniBroker(port=port)
            deadline = time.monotonic() + 20
            got = None
            n = 1
            while got is None and time.monotonic() < deadline:
                asrc.push_buffer(Buffer.of(
                    np.full((1, 4), 2.0, np.float32), pts=n))
                n += 1
                got = outs.pull(timeout=1.0)
            assert got is not None, "no frame after broker restart"
            sub_link = LinkMetrics.get("msrc", f"127.0.0.1:{port}",
                                       kind="mqtt-sub")
            assert sub_link.snapshot()["reconnects"] >= 1
            asrc.end_of_stream()
            psink.stop()
        finally:
            psrc.stop()
            broker.stop()

    def test_edgesrc_reconnects_after_publisher_restart(self):
        from nnstreamer_tpu.elements.basic import AppSink, AppSrc

        spec = TensorsSpec.parse("4:1", "float32")

        def publisher(port):
            p = Pipeline(name="edge-pub")
            src = AppSrc(name="src", spec=spec, max_buffers=16)
            sink = make("edgesink", el_name="esink", host="127.0.0.1",
                        port=port, topic="t")
            p.add(src, sink).link(src, sink)
            p.start()
            return p, src, sink

    # (split so the long body stays readable)
        ppub, psrc_el, esink = publisher(0)
        port = esink.port
        psub = Pipeline(name="edge-sub")
        esrc = make("edgesrc", el_name="esrc", dest_host="127.0.0.1",
                    dest_port=port, topic="t", num_buffers=2,
                    caps="other/tensors,format=static,num_tensors=1,"
                         "dimensions=4:1,types=float32",
                    reconnect_timeout_s=20.0)
        outs = AppSink(name="out", max_buffers=16)
        psub.add(esrc, outs).link(esrc, outs)
        psub.start()
        try:
            time.sleep(0.2)
            psrc_el.push_buffer(Buffer.of(
                np.full((1, 4), 1.0, np.float32), pts=0))
            assert outs.pull(timeout=10) is not None
            # kill the publisher, restart on the SAME port
            ppub.stop()
            time.sleep(0.3)
            ppub, psrc_el, esink = publisher(port)
            deadline = time.monotonic() + 20
            got = None
            n = 1
            while got is None and time.monotonic() < deadline:
                psrc_el.push_buffer(Buffer.of(
                    np.full((1, 4), 2.0, np.float32), pts=n))
                n += 1
                got = outs.pull(timeout=1.0)
            assert got is not None, "no frame after publisher restart"
            assert LinkMetrics.get(
                "esrc", f"127.0.0.1:{port}",
                kind="edge-sub").snapshot()["reconnects"] >= 1
        finally:
            psub.stop()
            ppub.stop()

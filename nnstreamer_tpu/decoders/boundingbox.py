"""``bounding_boxes`` decoder: detection model output → box overlay video.

Parity target: /root/reference/ext/nnstreamer/tensor_decoder/
tensordec-boundingbox.cc (981 LoC) with per-model strategies in
box_properties/: mobilenetssd.cc (:420 — box priors + scaled decode),
mobilenetssdpp.cc (:296 — post-processed 4-tensor layout), yolo.cc (:384 —
v5 and v8 layouts).  Options follow the reference grammar:

- option1 — decoding scheme: ``mobilenet-ssd`` | ``mobilenet-ssd-postprocess``
  | ``yolov5`` | ``yolov8`` | ``ov-person-detection`` (OpenVINO 7-value
  descriptor rows) | ``mp-palm-detection`` (MediaPipe palm anchors +
  clamped-sigmoid scores)
- option2 — label file path
- option3 — scheme detail (mobilenet-ssd: box-priors file path or blank to
  synthesize SSD anchors; yolo: "<conf_thresh>:<iou_thresh>")
- option4 — output video size ``WIDTH:HEIGHT``
- option5 — model input size ``WIDTH:HEIGHT`` (yolo box scaling)
- option7 — render backend: ``host`` (default, numpy rasterization) |
  ``device`` (overlay computed ON the accelerator as one XLA program —
  boxutil.device_render_fn; mobilenet-ssd-postprocess batched layout only).
  The TPU-native answer to the reference's CPU ``draw()``: the canvas
  never crosses to the host, so the decode stage cannot bottleneck the
  device (round-2 verdict: one host overlay thread held the composite
  pipeline to 4.2k fps while the device sustained 10.7k).  Device-path
  trade-offs, by design: label text is NOT rasterized (text rendering is
  a host-font operation — configuring option2 together with
  option7=device logs a one-time warning), and the structured detections
  are attached as device arrays at ``meta["detections_device"]``
  instead of host ``meta["detections"]`` — pulling per-box python
  objects would reintroduce the host round-trip this path removes.

Output: RGBA overlay frame (video/x-raw) with the structured detections
attached at ``buffer.meta["detections"]`` (host path) or
``buffer.meta["detections_device"]`` (device path, see option7) — the
TPU-native addition so downstream logic does not have to re-parse pixels.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core import Buffer, Caps, CapsStruct, Tensor, TensorSpec, TensorsSpec
from ..utils.stats import DISPATCH_STATS
from . import Decoder, JitFnCache, drain_once, register_decoder
from .boxutil import Detection, draw_boxes, load_labels, nms, sigmoid

_SCALE_XY = 10.0
_SCALE_WH = 5.0

#: yolo device pre-reduction keeps the top-K anchors by best class
#: score and drains only those (K, 6) rows — identical to the host
#: decode whenever the frame has <= K above-threshold candidates (a
#: realistic frame has tens; K bounds the worst case, e.g. noise)
_YOLO_TOPK = 512

#: (shape, v8, k) → jitted candidate filter (shared bounded cache)
_yolo_fns = JitFnCache()


def _yolo_prereduce_fn(shape, v8: bool, k: int):
    """Jitted yolo candidate filter: raw output → (K, 6) rows of
    [cx, cy, w, h, best_score, class], top-K by score, on device.  The
    full (A, 5+C) tensor never crosses to host — only the K candidate
    rows do, one packed drain (~25k x 85 floats down to 512 x 6)."""
    def build():
        import jax
        import jax.numpy as jnp

        def f(out):
            if v8:
                # (1, 4+C, A) → (A, 4+C); no objectness
                arr = out.reshape(out.shape[-2], out.shape[-1]).T
                boxes, scores = arr[:, :4], arr[:, 4:]
            else:
                # (1, A, 5+C): xywh + objectness + class confs
                arr = out.reshape(-1, out.shape[-1])
                boxes = arr[:, :4]
                scores = arr[:, 5:] * arr[:, 4:5]
            best = jnp.max(scores, axis=1)
            cls = jnp.argmax(scores, axis=1)
            kk = min(k, best.shape[0])
            val, idx = jax.lax.top_k(best, kk)
            return jnp.concatenate(
                [boxes[idx].astype(jnp.float32),
                 val[:, None].astype(jnp.float32),
                 cls[idx][:, None].astype(jnp.float32)], axis=1)

        return jax.jit(f)

    return _yolo_fns.get_or_build((tuple(shape), bool(v8), int(k)),
                                  build)


@register_decoder
class BoundingBoxes(Decoder):
    MODE = "bounding_boxes"

    def __init__(self):
        super().__init__()
        self.scheme = "mobilenet-ssd"
        self.labels: List[str] = []
        self.priors: Optional[np.ndarray] = None
        self.out_w, self.out_h = 300, 300
        self.in_w, self.in_h = 300, 300
        self.conf_thresh = 0.25
        self.iou_thresh = 0.5
        self.backend = "host"
        self._warned_device_labels = False
        #: set by the fusion pass when the device overlay program is
        #: compiled INTO the upstream jax-xla filter: decode() then
        #: consumes a ready canvas instead of rendering
        self.fused_upstream = False
        #: mp-palm score threshold (reference default 0.5), settable
        #: via option3 when the scheme is mp-palm-detection
        self._palm_thresh: Optional[float] = None
        self._palm_anchor_cache: Optional[np.ndarray] = None

    def options_updated(self) -> None:
        if self.options[6]:
            self.backend = self.options[6].strip().lower()
        if self.options[0]:
            self.scheme = self.options[0].strip().lower()
        if self.options[1]:
            self.labels = load_labels(self.options[1])
        self._interpret_opt3(self.options[2])
        if self.options[3]:
            w, _, h = self.options[3].partition(":")
            self.out_w, self.out_h = int(w), int(h or w)
        if self.options[4]:
            w, _, h = self.options[4].partition(":")
            self.in_w, self.in_h = int(w), int(h or w)

    def _interpret_opt3(self, o3: Optional[str]) -> None:
        """option3 is scheme-dependent: yolo → "<conf>:<iou>" thresholds;
        mobilenet-ssd → box-priors file path.  Interpreted against the
        *current* scheme on every options update, so the order in which
        option1/option3 arrive cannot mis-route it (and a priors path set
        before a scheme switch to yolo never reaches float())."""
        if not o3:
            return
        if self.scheme.startswith("yolo"):
            c, _, i = o3.partition(":")
            try:
                if c:
                    self.conf_thresh = float(c)
                if i:
                    self.iou_thresh = float(i)
            except ValueError:
                pass  # not a threshold pair (e.g. stale priors path)
        elif self.scheme == "mp-palm-detection":
            # reference grammar: threshold[:num_layers:min_scale:
            # max_scale:offset_x:offset_y:stride...]; the threshold is
            # the load-bearing field, the rest default to the palm
            # model's constants
            try:
                self._palm_thresh = float(o3.partition(":")[0])
            except ValueError:
                pass
        else:
            try:
                self.priors = np.loadtxt(o3, dtype=np.float32)
            except (OSError, ValueError):
                pass

    def out_caps(self, in_spec: TensorsSpec) -> Caps:
        # Batched postprocess input — boxes (B,N,4) from an on-device
        # decode+NMS head — yields one buffer of B overlay frames; the
        # ``frames`` field is this framework's batched-video extension
        # (the reference is strictly one frame per buffer).
        frames = 1
        if self.fused_upstream:
            # overlay fused into the upstream filter (runtime/fusion.py):
            # tensor 0 of the incoming schema IS the rendered canvas
            t0 = in_spec.tensors[0] if in_spec.tensors else None
            if t0 is not None and t0.rank == 4 and t0.shape[0] > 1:
                frames = t0.shape[0]
        elif in_spec.tensors and in_spec.tensors[0].rank == 3 \
                and self.scheme in ("mobilenet-ssd-postprocess",
                                    "mobilenetssd-pp"):
            frames = in_spec.tensors[0].shape[0]
        extra = {"frames": frames} if frames > 1 else {}
        return Caps.new(CapsStruct.make(
            "video/x-raw", format="RGBA", width=self.out_w,
            height=self.out_h, framerate=in_spec.rate, **extra))

    # -- schemes -------------------------------------------------------------

    def _anchors(self, num: int) -> np.ndarray:
        if self.priors is not None and len(self.priors) >= num:
            return self.priors[:num]
        from ..models.ssd import ssd_anchors

        # synthesize the standard SSD anchor table for the model input size
        fs = tuple(int(np.ceil(self.in_w / s))
                   for s in (16, 32, 64, 128, 256, 512))
        a = ssd_anchors(self.in_w, fs)
        if len(a) < num:
            a = np.vstack([a] * (num // len(a) + 1))
        return a[:num]

    def _decode_mobilenet_ssd(self, buf: Buffer) -> List[Detection]:
        """Raw 2-tensor layout: loc (A,4) or (1,A,4) + cls scores (A,C)."""
        loc = buf.tensors[0].np().reshape(-1, 4)
        cls = buf.tensors[1].np()
        cls = cls.reshape(-1, cls.shape[-1])
        anchors = self._anchors(loc.shape[0])
        cy = loc[:, 0] / _SCALE_XY * anchors[:, 2] + anchors[:, 0]
        cx = loc[:, 1] / _SCALE_XY * anchors[:, 3] + anchors[:, 1]
        h = np.exp(loc[:, 2] / _SCALE_WH) * anchors[:, 2]
        w = np.exp(loc[:, 3] / _SCALE_WH) * anchors[:, 3]
        scores = sigmoid(cls)
        dets = []
        for a in range(loc.shape[0]):
            c = int(scores[a, 1:].argmax()) + 1  # class 0 = background
            s = float(scores[a, c])
            if s < self.conf_thresh:
                continue
            dets.append(Detection(
                x=float(cx[a] - w[a] / 2), y=float(cy[a] - h[a] / 2),
                w=float(w[a]), h=float(h[a]), class_id=c, score=s))
        return nms(dets, self.iou_thresh)

    def _decode_ssd_postprocess(self, buf: Buffer):
        """Post-processed 4-tensor layout (mobilenetssdpp.cc): boxes
        (N,4 ymin,xmin,ymax,xmax normalized), classes (N,), scores (N,),
        num_detections (1,).  Batched model output — boxes (B,N,4) from an
        on-device decode+NMS head (models/ssd.py end_to_end) — yields a
        list of per-frame detection lists."""
        boxes_t = buf.tensors[0].np()
        # (1,N,4) is the canonical single-frame TFLite layout — flatten;
        # only a true multi-frame batch (B>1) takes the batched branch,
        # matching out_caps' frames= decision
        if boxes_t.ndim == 3 and boxes_t.shape[0] > 1:
            classes = buf.tensors[1].np()
            scores = buf.tensors[2].np()
            nums = buf.tensors[3].np().reshape(-1) \
                if buf.num_tensors > 3 else None
            return [
                self._ssd_pp_frame(boxes_t[b], classes[b], scores[b],
                                   int(nums[b]) if nums is not None
                                   else scores.shape[1])
                for b in range(boxes_t.shape[0])]
        boxes = boxes_t.reshape(-1, 4)
        classes = buf.tensors[1].np().reshape(-1)
        scores = buf.tensors[2].np().reshape(-1)
        n = int(buf.tensors[3].np().reshape(-1)[0]) \
            if buf.num_tensors > 3 else len(scores)
        return self._ssd_pp_frame(boxes, classes, scores, n)

    def _ssd_pp_frame(self, boxes, classes, scores, n) -> List[Detection]:
        dets = []
        for i in range(min(n, len(scores))):
            if scores[i] < self.conf_thresh:
                continue
            ymin, xmin, ymax, xmax = boxes[i]
            dets.append(Detection(
                x=float(xmin), y=float(ymin), w=float(xmax - xmin),
                h=float(ymax - ymin), class_id=int(classes[i]),
                score=float(scores[i])))
        return dets  # already NMS'd by the model

    def _decode_ov_detection(self, buf: Buffer) -> List[Detection]:
        """``ov-person-detection``: one (7, 200) tensor of rows
        [image_id, label, conf, x_min, y_min, x_max, y_max]; a negative
        image_id terminates the list, conf ≥ 0.8 keeps the row (parity:
        box_properties/ovdetection.cc — the OpenVINO person-detection
        descriptor layout)."""
        arr = buf.tensors[0].np().reshape(-1, 7)
        dets: List[Detection] = []
        for row in arr:
            if row[0] < 0:
                break
            if row[2] < 0.8:
                continue
            x0, y0, x1, y1 = (float(row[3]), float(row[4]),
                              float(row[5]), float(row[6]))
            dets.append(Detection(
                x=x0, y=y0, w=x1 - x0, h=y1 - y0,
                class_id=int(row[1]), score=float(row[2])))
        return dets

    # MediaPipe palm anchor defaults (box_properties/mppalmdetection.cc)
    _PALM_STRIDES = (8, 16, 16, 16)
    _PALM_MIN_SCALE = 1.0
    _PALM_MAX_SCALE = 1.0
    _PALM_OFFSET = 0.5
    _PALM_INPUT = 192

    def _palm_anchors(self) -> np.ndarray:
        """MediaPipe SSD anchor generation for the palm model: per run
        of equal strides, two unit-aspect anchors per layer in the run;
        centers at (cell + 0.5)/grid (parity:
        mp_palm_detection_generate_anchors).  Returns (A, 4) rows of
        [y_center, x_center, h, w]; built once and cached (the
        reference generates at option-set time)."""
        if self._palm_anchor_cache is not None:
            return self._palm_anchor_cache
        n = len(self._PALM_STRIDES)

        def scale(i):
            if n == 1:
                return (self._PALM_MIN_SCALE + self._PALM_MAX_SCALE) / 2
            return self._PALM_MIN_SCALE + \
                (self._PALM_MAX_SCALE - self._PALM_MIN_SCALE) * i / (n - 1)

        out: List[List[float]] = []
        layer = 0
        while layer < n:
            run_end = layer
            dims: List[float] = []
            while run_end < n and \
                    self._PALM_STRIDES[run_end] == self._PALM_STRIDES[layer]:
                dims.extend([scale(run_end), scale(run_end + 1)])
                run_end += 1
            grid = int(np.ceil(self._PALM_INPUT /
                               self._PALM_STRIDES[layer]))
            for y in range(grid):
                for x in range(grid):
                    cy = (y + self._PALM_OFFSET) / grid
                    cx = (x + self._PALM_OFFSET) / grid
                    for s in dims:
                        out.append([cy, cx, s, s])
            layer = run_end
        self._palm_anchor_cache = np.asarray(out, np.float32)
        return self._palm_anchor_cache

    def _decode_mp_palm(self, buf: Buffer) -> List[Detection]:
        """``mp-palm-detection``: boxes (18, A) + raw scores (A,);
        anchors regress MediaPipe-style (offsets scaled by the anchor
        box relative to the model input size), scores pass through a
        clamped sigmoid (parity: box_properties/mppalmdetection.cc
        _get_objects_mp_palm_detection)."""
        boxes = buf.tensors[0].np().reshape(-1, 18)  # (A, 18) rows
        scores = buf.tensors[1].np().ravel()
        anchors = self._palm_anchors()
        a = min(len(anchors), len(boxes), len(scores))
        s = 1.0 / (1.0 + np.exp(-np.clip(scores[:a], -100.0, 100.0)))
        thresh = 0.5 if self._palm_thresh is None else self._palm_thresh
        dets: List[Detection] = []
        for d in np.nonzero(s >= thresh)[0]:
            ay, ax, ah, aw = anchors[d]
            b = boxes[d]
            yc = b[0] / self.in_h * ah + ay
            xc = b[1] / self.in_w * aw + ax
            h = b[2] / self.in_h * ah
            w = b[3] / self.in_w * aw
            dets.append(Detection(
                x=max(float(xc - w / 2), 0.0),
                y=max(float(yc - h / 2), 0.0),
                w=float(w), h=float(h), class_id=0, score=float(s[d])))
        # the reference suppresses palms at a fixed 0.05 IoU
        # (mppalmdetection.cc nms(results, 0.05f)), far stricter than
        # the generic default
        return nms(dets, 0.05)

    def _decode_yolo(self, buf: Buffer, v8: bool) -> List[Detection]:
        t = buf.tensors[0]
        if t.is_device:
            # device pre-reduction: max/argmax/top-k run in HBM and only
            # the (K, 6) candidate rows drain — the NMS input set is
            # identical to the host decode for any frame with <= K
            # above-threshold anchors
            rows = np.asarray(Tensor(
                _yolo_prereduce_fn(t.spec.shape, v8, _YOLO_TOPK)(
                    t.jax())).np())
            DISPATCH_STATS.count("decoder")
            scale = np.array([self.in_w, self.in_h, self.in_w, self.in_h],
                             np.float32)
            dets = []
            for r in rows:
                if r[4] < self.conf_thresh:
                    break  # rows are score-sorted: nothing further passes
                cx, cy, w, h = r[:4] / scale
                dets.append(Detection(
                    x=float(cx - w / 2), y=float(cy - h / 2), w=float(w),
                    h=float(h), class_id=int(r[5]), score=float(r[4])))
            return nms(dets, self.iou_thresh)
        out = t.np()
        if v8:
            # (1, 4+C, A) → (A, 4+C); no objectness, scores are class confs
            arr = out.reshape(out.shape[-2], out.shape[-1]).T
            boxes, confs = arr[:, :4], arr[:, 4:]
            scores = confs
        else:
            # (1, A, 5+C): xywh + objectness + class confs
            arr = out.reshape(-1, out.shape[-1])
            boxes = arr[:, :4]
            scores = arr[:, 5:] * arr[:, 4:5]
        dets = []
        cand = np.nonzero(scores.max(axis=1) >= self.conf_thresh)[0]
        for a in cand:
            c = int(scores[a].argmax())
            cx, cy, w, h = boxes[a] / np.array(
                [self.in_w, self.in_h, self.in_w, self.in_h], np.float32)
            dets.append(Detection(
                x=float(cx - w / 2), y=float(cy - h / 2), w=float(w),
                h=float(h), class_id=c, score=float(scores[a, c])))
        return nms(dets, self.iou_thresh)

    # -- device render path --------------------------------------------------

    def _device_active(self) -> bool:
        return self.backend == "device" and self.scheme in (
            "mobilenet-ssd-postprocess", "mobilenetssd-pp")

    def device_post_program(self):
        """For the fusion pass (runtime/fusion.py): a jit-inlinable
        epilogue mapping the upstream filter's postprocess outputs
        (boxes, classes, scores, num) to (canvas, boxes, classes,
        scores, num) — the whole transform+model+NMS+overlay pipeline
        then compiles as ONE XLA program with a single dispatch.
        Returns None when this decoder configuration cannot render
        on-device."""
        if not self._device_active():
            return None
        import jax.numpy as jnp

        from .boxutil import device_render_fn

        out_h, out_w, conf = self.out_h, self.out_w, self.conf_thresh

        def post(*outs):
            # accept every layout the unfused device path accepts:
            # boxes (B,N,4) or single-frame (N,4); optional num tensor
            boxes = outs[0]
            if boxes.ndim == 2:
                boxes = boxes[None]
            b, n = boxes.shape[0], boxes.shape[1]
            classes = outs[1].reshape(b, n)
            scores = outs[2].reshape(b, n)
            num = outs[3].reshape(b) if len(outs) > 3 \
                else jnp.full((b,), n, jnp.int32)
            render = device_render_fn(b, n, out_h, out_w, conf)
            canvas = render(boxes, classes, scores, num)
            return (canvas, *outs)

        # persistent AOT cache identity (runtime/compilecache.py):
        # everything the traced epilogue depends on.  The render fn
        # itself is versioned code, covered by the cache's library
        # version salt like the model fn is.
        post.chain_digest = "bounding_boxes:%s:%dx%d:%s" % (
            self.scheme, out_w, out_h, conf)
        return post

    def _decode_fused(self, buf: Buffer) -> Buffer:
        """Consume the fused program's output: tensor 0 is the rendered
        canvas; 1.. are the model's original postprocess tensors, kept
        device-resident as ``meta["detections_device"]`` with the same
        normalization as the unfused device path."""
        import jax.numpy as jnp

        canvas = buf.tensors[0].jax()
        batched = canvas.ndim == 4 and canvas.shape[0] > 1
        if canvas.ndim == 4 and not batched:
            canvas = canvas[0]
        out = Buffer(
            tensors=[Tensor(canvas,
                            TensorSpec.from_shape(canvas.shape, np.uint8))],
            pts=buf.pts, duration=buf.duration, meta=dict(buf.meta))
        if buf.num_tensors >= 4:
            boxes = buf.tensors[1].jax()
            if boxes.ndim == 2:
                boxes = boxes[None]
            b, n = boxes.shape[0], boxes.shape[1]
            out.meta["detections_device"] = {
                "boxes": boxes,
                "classes": buf.tensors[2].jax().reshape(b, n),
                "scores": buf.tensors[3].jax().reshape(b, n),
                "num": buf.tensors[4].jax().reshape(b)
                if buf.num_tensors > 4
                else jnp.full((b,), n, jnp.int32)}
        return out

    def wants_host_input(self) -> bool:
        # the device renderer consumes boxes/classes/scores/num in HBM;
        # tensor_decoder must not prefetch them to host
        return not self._device_active()

    def prereduce_active(self, buf: Buffer) -> bool:
        # any device-resident frame either pre-reduces on device (yolo
        # top-k) or drains once as a single packed array (decode below)
        # — the per-tensor prefetch would transfer what the reduction
        # makes redundant
        return any(t.is_device for t in buf.tensors)

    def _decode_device(self, buf: Buffer) -> Buffer:
        """Rasterize the overlay ON the accelerator (option7=device): the
        four postprocess tensors stay device-resident, one jitted XLA
        program writes every frame's rectangles, and the (B,H,W,4) canvas
        is returned as a device tensor.  Structured detections stay
        available as device arrays at ``meta["detections_device"]``
        (pulling per-box python Detection objects would reintroduce the
        host round-trip this path exists to avoid)."""
        import jax.numpy as jnp

        from .boxutil import device_render_fn

        boxes = buf.tensors[0].jax()
        # single-frame layouts ((N,4) or canonical TFLite (1,N,4)) must
        # keep the host path's (H,W,4) output rank; only a true batch
        # (B>1) emits (B,H,W,4) — same rule as out_caps/_decode_ssd_pp
        batched = boxes.ndim == 3 and boxes.shape[0] > 1
        if boxes.ndim == 2:
            boxes = boxes[None]
        b, n = boxes.shape[0], boxes.shape[1]
        classes = buf.tensors[1].jax().reshape(b, n)
        scores = buf.tensors[2].jax().reshape(b, n)
        num = buf.tensors[3].jax().reshape(b) if buf.num_tensors > 3 \
            else jnp.full((b,), n, jnp.int32)
        render = device_render_fn(b, n, self.out_h, self.out_w,
                                  self.conf_thresh)
        canvas = render(boxes, classes, scores, num)
        DISPATCH_STATS.count("decoder")
        if not batched:
            canvas = canvas[0]
        out = Buffer(
            tensors=[Tensor(canvas,
                            TensorSpec.from_shape(canvas.shape, np.uint8))],
            pts=buf.pts, duration=buf.duration, meta=dict(buf.meta))
        out.meta["detections_device"] = {
            "boxes": boxes, "classes": classes, "scores": scores,
            "num": num}
        return out

    # -- decode --------------------------------------------------------------

    def decode(self, buf: Buffer, in_spec: Optional[TensorsSpec]) -> Buffer:
        scheme = self.scheme
        if self._device_active():
            if self.labels and not self._warned_device_labels:
                self._warned_device_labels = True
                from ..utils.log import logw

                logw("bounding_boxes: option7=device draws boxes only — "
                     "label text (option2) is not rasterized on-device; "
                     "use option7=host for labeled overlays")
            # fused path: tensor 0 must actually BE a canvas (uint8,
            # rank 3/4) — a withdrawn fusion (flexible stream) leaves
            # raw detection tensors, which route to the normal renderer
            if self.fused_upstream and buf.num_tensors >= 1 and \
                    buf.tensors[0].spec.rank >= 3 and \
                    buf.tensors[0].spec.dtype.np_dtype == np.uint8:
                return self._decode_fused(buf)
            return self._decode_device(buf)
        if scheme not in ("yolov5", "yolov8"):
            # host decoders below read every tensor: drain the device-
            # resident ones with ONE packed d2h crossing (and seed their
            # host caches) instead of one blocking .np() per tensor —
            # the boxes/classes/scores/num layout used to pay 4
            # crossings per frame here (yolo pre-reduces on device
            # instead and must NOT drain its raw tensor)
            drain_once(buf.tensors)
        if scheme == "mobilenet-ssd":
            dets = self._decode_mobilenet_ssd(buf)
        elif scheme in ("mobilenet-ssd-postprocess", "mobilenetssd-pp"):
            dets = self._decode_ssd_postprocess(buf)
        elif scheme == "yolov5":
            dets = self._decode_yolo(buf, v8=False)
        elif scheme == "yolov8":
            dets = self._decode_yolo(buf, v8=True)
        elif scheme == "ov-person-detection":
            dets = self._decode_ov_detection(buf)
        elif scheme == "mp-palm-detection":
            dets = self._decode_mp_palm(buf)
        else:
            raise ValueError(f"bounding_boxes: unknown scheme {scheme!r}")
        batched = bool(dets) and isinstance(dets[0], list)
        for d in (x for f in dets for x in f) if batched else dets:
            if d.class_id < len(self.labels):
                d.label = self.labels[d.class_id]
        if batched:
            frame = np.zeros((len(dets), self.out_h, self.out_w, 4),
                             np.uint8)
            for b, f in enumerate(dets):
                draw_boxes(f, self.out_w, self.out_h,
                           labels=bool(self.labels), out=frame[b])
        else:
            frame = draw_boxes(dets, self.out_w, self.out_h,
                               labels=bool(self.labels))
        out = Buffer(
            tensors=[Tensor(frame,
                            TensorSpec.from_shape(frame.shape, np.uint8))],
            pts=buf.pts, duration=buf.duration, meta=dict(buf.meta))
        out.meta["detections"] = dets
        return out

"""Short-horizon forecasting over the watch store's rings.

Every alert the watchdog (:mod:`.watch`) has raised so far is
*reactive*: the SLO burn fires after latency already burned budget, the
queue-saturation rule after the queue already filled.  On a serving
fleet the interesting question is usually a few seconds earlier —
"is this series GOING to cross the line?".  This module is the math
behind the fourth rule kind, ``forecast``:

- :func:`fit_trend` — a robust linear fit over a ring tail: the slope
  is the Theil–Sen estimator (median of all pairwise slopes, immune to
  a third of the points being garbage), the level a median-projected
  intercept at the window's last timestamp, and the residual scale a
  MAD band around the fitted line;
- :func:`forecast_crossing` — given a fit, a threshold and a horizon:
  the predicted value at the horizon, the ETA of the crossing, and
  whether the rule should fire.  A trend only counts when the
  projected move clears the residual noise band
  (``SIGNIFICANCE_SIGMAS`` robust sigmas) — a flat or merely noisy
  series never fires, which is what keeps the predictive layer's
  false-positive rate at zero on steady traffic (the capacity bench
  pins exactly that);
- :func:`capacity_headroom` — the arrival-vs-capacity join: sustainable
  rate extrapolated from live MFU against its roofline ceiling
  (:mod:`.xlacost`), falling back to pool window occupancy, compared
  with the *forecast* arrival rate.  Exported as
  ``nns_capacity_headroom`` and the ``/healthz`` capacity summary.

Everything here is pure computation on ``(ts, value)`` lists — no
thread, no scraping: the watch sampler feeds it on its existing tick
and publishes the results through :data:`FORECASTS` (the snapshot v9
``forecasts`` table) and the ``nns_forecast_*`` gauges.
"""

from __future__ import annotations

import dataclasses
import statistics
import threading
from typing import Dict, Iterable, List, Optional, Tuple

#: cap on points fed to the pairwise-slope fit — the estimator is
#: O(n^2) pairs, and 64 points keeps one fit in the tens of
#: microseconds while still spanning a minute of 1 Hz sampling
MAX_FIT_POINTS = 64

#: fewer points than this is a line through noise, not a trend
MIN_FIT_POINTS = 4

#: the projected move over the horizon must clear this many robust
#: sigmas (1.4826 x residual MAD) before a crossing is believed
SIGNIFICANCE_SIGMAS = 3.0

#: horizon of the capacity-headroom arrival forecast when no forecast
#: rule pins a longer one
HEADROOM_HORIZON_S = 30.0

#: cap on the capacity extrapolation multiplier: a pool idling at 0.1%
#: MFU does not credibly promise 1000x its current throughput
MAX_SCALE_OUT = 100.0

#: the ordered comparisons a forecast can project through ("=="/"!="
#: have no crossing direction — the rule grammar rejects them)
ORDERED_OPS = (">", ">=", "<", "<=")

_CMP = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}


@dataclasses.dataclass(frozen=True)
class TrendFit:
    """Robust linear fit of one ring tail."""

    slope: float     #: units per second (Theil–Sen)
    level: float     #: fitted value AT the window's last timestamp
    sigma: float     #: residual scale, 1.4826 x MAD (0 = perfect line)
    n: int           #: points fitted
    t_last: float    #: timestamp the level is anchored to

    def at(self, dt_s: float) -> float:
        """Predicted value ``dt_s`` seconds past the window end."""
        return self.level + self.slope * dt_s


def fit_trend(points: Iterable[Tuple[float, float]],
              max_points: int = MAX_FIT_POINTS) -> Optional[TrendFit]:
    """Theil–Sen slope + median-projected level + residual MAD over the
    trailing ``max_points`` of ``points`` (``(ts, value)`` pairs).
    None below :data:`MIN_FIT_POINTS` — too little history to call a
    trend."""
    pts = list(points)[-int(max_points):]
    if len(pts) < MIN_FIT_POINTS:
        return None
    t_last = pts[-1][0]
    slopes: List[float] = []
    for i, (ti, vi) in enumerate(pts):
        for tj, vj in pts[i + 1:]:
            if tj > ti:
                slopes.append((vj - vi) / (tj - ti))
    if not slopes:
        return None  # all points share one timestamp
    slope = statistics.median(slopes)
    # robust intercept: project every point to t_last along the slope,
    # take the median — outliers shift it no further than they shifted
    # the slope
    levels = [v - slope * (t - t_last) for t, v in pts]
    level = statistics.median(levels)
    resid = [abs(v - (level + slope * (t - t_last))) for t, v in pts]
    sigma = 1.4826 * statistics.median(resid)
    return TrendFit(slope=slope, level=level, sigma=sigma,
                    n=len(pts), t_last=t_last)


def forecast_crossing(fit: TrendFit, threshold: float, op: str,
                      horizon_s: float,
                      k_sigma: float = SIGNIFICANCE_SIGMAS
                      ) -> Tuple[float, Optional[float], bool]:
    """``(predicted, eta_s, firing)`` for one fitted series against an
    ordered comparison.

    - ``predicted``: the fit extrapolated to the horizon;
    - ``eta_s``: seconds until the fitted line crosses the threshold
      (0 when the current level already satisfies the comparison,
      None when no crossing lies ahead);
    - ``firing``: True only when the crossing is *predicted*, not
      current — the level is still on the safe side, the trend carries
      it across within the horizon, and the projected move clears the
      noise band (``k_sigma`` robust sigmas).  Current violations are
      the plain ``threshold`` rule's job; a flat series (slope 0)
      never fires by construction.
    """
    cmp = _CMP[op]
    predicted = fit.at(horizon_s)
    if cmp(fit.level, threshold):
        return predicted, 0.0, False  # already over: reactive territory
    if fit.slope == 0.0:
        return predicted, None, False
    eta = (threshold - fit.level) / fit.slope
    if eta < 0:
        return predicted, None, False  # trending AWAY from the line
    significant = abs(fit.slope) * horizon_s > k_sigma * fit.sigma
    firing = bool(significant and eta <= horizon_s
                  and cmp(predicted, threshold))
    return predicted, eta, firing


def capacity_headroom(current_fps: float, predicted_fps: float,
                      mfu: Optional[float] = None,
                      mfu_ceiling: Optional[float] = None,
                      occupancy: Optional[float] = None
                      ) -> Optional[dict]:
    """The arrival-vs-capacity join: ``{sustainable_fps, headroom}``.

    Sustainable rate extrapolates the CURRENT measured rate linearly to
    saturation — by live MFU against its roofline ceiling when the
    cost join knows both, else by pool window occupancy (mean frames
    per dispatch over the window size); None when neither signal
    exists (no utilization → no capacity claim, same stance as
    :mod:`.hwspec`).  ``headroom`` is the fraction of sustainable rate
    left over after the *forecast* arrival rate, clamped to [-1, 1]:
    1 = idle, 0 = predicted arrivals exactly saturate, negative =
    predicted overload."""
    if current_fps is None or current_fps <= 0:
        return None
    sustainable = None
    if mfu and mfu_ceiling and mfu > 0:
        sustainable = current_fps * min(mfu_ceiling / mfu, MAX_SCALE_OUT)
    elif occupancy and occupancy > 0:
        sustainable = current_fps * min(1.0 / min(occupancy, 1.0),
                                        MAX_SCALE_OUT)
    if not sustainable or sustainable <= 0:
        return None
    headroom = (sustainable - max(predicted_fps, 0.0)) / sustainable
    return {"sustainable_fps": sustainable,
            "headroom": max(min(headroom, 1.0), -1.0)}


class Forecasts:
    """Process-wide latest-forecast store, the pull side of the
    predictive layer: the watch sampler writes one row per forecast
    rule (and one capacity row per pool) each tick; the registry
    snapshot (v9 ``forecasts`` table), ``/healthz`` and nns-top read
    them back without touching the sampler."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rules: Dict[str, dict] = {}
        self._capacity: Dict[str, dict] = {}

    def update(self, rule: str, row: dict) -> None:
        with self._lock:
            self._rules[str(rule)] = dict(row)

    def update_capacity(self, pool: str, row: dict) -> None:
        with self._lock:
            self._capacity[str(pool)] = dict(row)

    def snapshot(self) -> dict:
        """{"rules": [...], "capacity": [...]}, sorted for stable
        output."""
        with self._lock:
            rules = [dict(self._rules[k]) for k in sorted(self._rules)]
            cap = [dict(self._capacity[k])
                   for k in sorted(self._capacity)]
        return {"rules": rules, "capacity": cap}

    def reset(self) -> None:
        """Tests/bench only."""
        with self._lock:
            self._rules.clear()
            self._capacity.clear()


#: the store the active watchdog feeds (module-global like TENANT_STATS
#: — there is one snapshot, so there is one forecasts table)
FORECASTS = Forecasts()

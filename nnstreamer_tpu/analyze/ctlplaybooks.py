"""NNS511 — static validation of ``obs/control.py`` playbook files.

A controller playbook that names a watch rule nobody evaluates, an
actuator nothing exports, or a pool/link target the deployment never
creates fails exactly like a broken alert rule: *silently*, by never
acting.  This pass loads a TOML/JSON playbook file (the same loader the
controller uses — one grammar, one error surface) WITHOUT starting
anything and reports:

- malformed grammar (unknown keys/kinds/actions, bad durations,
  duplicate names, unreadable/unparseable files) — the exact
  :class:`~nnstreamer_tpu.obs.control.PlaybookError` the controller
  would raise at startup;
- playbooks that can never act: an actuator name missing from the
  :data:`~nnstreamer_tpu.runtime.actuators.KNOWN_ACTUATORS` catalog, a
  rule name absent from the active rule set (the ``--watch-rules``
  file when one is given in the same invocation, else
  ``$NNS_TPU_WATCH_RULES``, else the built-in pack), or a concrete
  (non-glob) pool/link target that no element in the analyzed
  pipeline(s) creates — pool targets need a ``share-model=true``
  ``tensor_filter`` whose ``framework:model-tail`` label matches, link
  targets an edge element whose name matches.

Invoked by ``nns-lint --ctl-playbooks FILE`` (bare ``--ctl-playbooks``
reads ``$NNS_TPU_CTL_PLAYBOOKS``, the same env var the runtime loads
from).  The target cross-check only runs when the same invocation also
analyzed pipelines — with nothing analyzed, a missing target is
unknowable, not wrong.
"""

from __future__ import annotations

import fnmatch
import os
from typing import List, Optional, Tuple

from .diagnostics import Diagnostic

_HINT = ("playbook grammar + the actuator catalog: "
         "Documentation/observability.md ('Closed-loop control & "
         "MTTR'); known actuators: "
         "nnstreamer_tpu.runtime.actuators.KNOWN_ACTUATORS")

#: element factories whose retry policy registers a steerable link
#: breaker (chaos/retrypolicy.py) — the link-target existence check
_LINK_FACTORIES = ("tensor_query_client", "edgesrc", "mqttsrc",
                   "mqttsink")


def _pipeline_targets(pipelines) -> Tuple[List[str], List[str]]:
    """(pool labels, link names) the analyzed pipelines would create:
    pool labels as ``framework:model-tail`` for share-model filters,
    link names as the owning element's name (= the RetryPolicy /
    LinkMetrics ``link`` label)."""
    pools: List[str] = []
    links: List[str] = []
    for pipe in pipelines or []:
        for e in getattr(pipe, "elements", {}).values():
            if getattr(e, "share_model", False):
                fw = str(getattr(e, "framework", "") or "auto")
                model = getattr(e, "model", "")
                tail = os.path.basename(str(model))
                pools.append(f"{fw}:{tail}")
            if getattr(e, "FACTORY", "") in _LINK_FACTORIES:
                links.append(e.name)
    return pools, links


def check_playbooks(path: Optional[str],
                    rule_names: Optional[List[str]] = None,
                    pipelines=None) -> List[Diagnostic]:
    """Diagnostics for one playbook file.  ``path=None`` means "use
    ``$NNS_TPU_CTL_PLAYBOOKS``" — unset is itself a finding.
    ``rule_names`` is the active rule set to bind against (None →
    the env rules file when set, else the built-in watch pack);
    ``pipelines`` the parsed-but-never-started pipelines of the same
    invocation, for the target existence check."""
    from ..obs import control as _control
    from ..obs import watch as _watch

    if path is None:
        path = os.environ.get("NNS_TPU_CTL_PLAYBOOKS", "").strip()
        if not path:
            return [Diagnostic.make(
                "NNS511",
                "--ctl-playbooks given without a file and "
                "NNS_TPU_CTL_PLAYBOOKS is unset — no playbooks to "
                "validate", hint=_HINT)]
    label = os.path.basename(path)
    try:
        playbooks = _control.load_playbooks(path)
    except _control.PlaybookError as e:
        return [Diagnostic.make(
            "NNS511", f"{label}: malformed playbook file: {e}",
            element=path, hint=_HINT)]
    except OSError as e:
        return [Diagnostic.make(
            "NNS511", f"{label}: cannot read playbook file: {e}",
            element=path, hint=_HINT)]
    if rule_names is None:
        try:
            rule_names = [r.name for r in _watch.rules_from_env()]
        except (_watch.RuleError, OSError):
            rule_names = [r.name for r in _watch.default_rules()]
    rule_names = list(rule_names) + ["endpoint-down"]
    pools, links = _pipeline_targets(pipelines)
    diags: List[Diagnostic] = []
    for pb in playbooks:
        for problem in _control.lint_playbook(pb, rule_names):
            diags.append(Diagnostic.make(
                "NNS511", f"{label}: playbook {pb.name!r}: {problem}",
                element=path, pad=pb.name, hint=_HINT))
        # target existence: only for concrete targets, and only when
        # this invocation analyzed pipelines to check against
        if pipelines and pb.target and pb.target != "*":
            have = pools if pb.kind == "pool" else links
            if not any(fnmatch.fnmatch(t, pb.target) for t in have):
                what = "share-model pool" if pb.kind == "pool" \
                    else "edge link"
                diags.append(Diagnostic.make(
                    "NNS511",
                    f"{label}: playbook {pb.name!r}: target "
                    f"{pb.target!r} matches no {what} any analyzed "
                    f"pipeline creates (have: {sorted(set(have))})",
                    element=path, pad=pb.name, hint=_HINT))
    return diags

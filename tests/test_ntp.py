"""SNTP client against a mock UDP server (the reference mocks its NTP
util the same way, tests/gstreamer_mqtt/unittest_ntp_util_mock.cc)."""

import socket
import struct
import threading
import time

import pytest

from nnstreamer_tpu.edge.ntputil import (
    NTP_UNIX_DELTA,
    get_epoch,
    ntp_epoch_fn,
    query_server,
)


class MockNtpServer:
    """Answers one SNTP request with a fixed transmit timestamp."""

    def __init__(self, epoch_s: float):
        self.epoch_s = epoch_s
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind(("127.0.0.1", 0))
        self.port = self._sock.getsockname()[1]
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        self._sock.settimeout(5.0)
        try:
            while True:
                data, addr = self._sock.recvfrom(512)
                resp = bytearray(48)
                resp[0] = (0 << 6) | (4 << 3) | 4  # mode=4 (server)
                ntp_sec = int(self.epoch_s) + NTP_UNIX_DELTA
                frac = int((self.epoch_s % 1) * (1 << 32))
                resp[40:48] = struct.pack(">II", ntp_sec, frac)
                self._sock.sendto(bytes(resp), addr)
        except (socket.timeout, OSError):
            pass

    def stop(self):
        self._sock.close()


def test_query_mock_server():
    t = 1_700_000_000.5
    srv = MockNtpServer(t)
    try:
        us = query_server("127.0.0.1", srv.port)
        assert abs(us - t * 1e6) < 1e3  # sub-ms of the mock's clock
    finally:
        srv.stop()


def test_get_epoch_walks_server_list_and_falls_back():
    # first server dead (no listener), second answers
    t = 1_600_000_000.0
    srv = MockNtpServer(t)
    try:
        us = get_epoch([("127.0.0.1", 1), ("127.0.0.1", srv.port)],
                       timeout=0.3)
        assert abs(us - t * 1e6) < 1e3
    finally:
        srv.stop()
    # all dead: local clock fallback
    us = get_epoch([("127.0.0.1", 1)], timeout=0.2)
    assert abs(us - time.time() * 1e6) < 5e6


def test_epoch_fn_caches_and_advances():
    t = 1_500_000_000.0
    srv = MockNtpServer(t)
    try:
        fn = ntp_epoch_fn([("127.0.0.1", srv.port)], refresh_s=60)
        a = fn()
        time.sleep(0.05)
        b = fn()  # cached base + monotonic delta, no second query
        assert b > a
        assert abs((b - a) - 50_000) < 40_000  # ~50ms advance
    finally:
        srv.stop()


def test_mqtt_sink_accepts_ntp_clock():
    from nnstreamer_tpu.runtime.registry import make

    t = 1_400_000_000.0
    srv = MockNtpServer(t)
    try:
        fn = ntp_epoch_fn([("127.0.0.1", srv.port)])
        snk = make("mqttsink", el_name="mk", epoch_fn=fn)
        assert abs(snk._epoch_us() - t * 1e6) < 1e6
    finally:
        srv.stop()

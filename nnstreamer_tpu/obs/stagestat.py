"""Per-stage attribution of a pipeline split over device subsets.

A disaggregated pipeline (``tensor_filter model=detector devices=0-3 →
tensor_filter model=classifier devices=4-7``) moves frames *between*
device subsets instead of between host and device: the handoff is a
device→device continuation over the device channel
(``edge/devicechannel.py`` slot deposit/take + ``jax.device_put`` onto
the destination stage's chips), tagged ``d2d`` on the transfer ledger
so the ``crossings_per_frame == 0.0`` invariant extends across stages.
This module is the stage-level view of that flow — the numbers the
cascade bench gates and the nns-top STAGE section renders:

- **handoff rows** (one per receiving stage filter): frames and exact
  bytes that crossed INTO the stage from another subset, the canonical
  source/destination subset labels (``parallel.placement.subset_label``),
  and the inter-stage depth — frames handed off but not yet emitted by
  the stage (incremented at the handoff seam, decremented when the
  stage's output leaves ``tensor_filter``);
- **offload rows** (one per routing ``tensor_if``): how many frames the
  conditional cascade sent down the offload (heavy-stage) branch vs
  kept local — ``nns_cascade_offload_ratio`` is offloaded/total, the
  fraction the seeded-predicate bench pins exactly.

Pulled by the metrics registry at scrape time like every other
collected stat: the snapshot's ``stages`` table (v8), the
``nns_stage_handoff_{bytes,frames}_total`` / ``nns_stage_depth`` /
``nns_cascade_offload_ratio`` families, and nns-top's STAGE section.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from . import hooks as _hooks

#: fast-path flag (same contract as obs/transfer.py)
ACTIVE = not _hooks.DISABLED


class _HandoffRow:
    __slots__ = ("src", "dst", "frames", "bytes", "emits")

    def __init__(self, src: str, dst: str):
        self.src = src
        self.dst = dst
        self.frames = 0
        self.bytes = 0
        self.emits = 0


class _OffloadRow:
    __slots__ = ("dst", "offloaded", "kept")

    def __init__(self, dst: str):
        self.dst = dst
        self.offloaded = 0
        self.kept = 0


class StageStats:
    """Process-wide, thread-safe per-stage handoff/offload store."""

    def __init__(self):
        self._lock = threading.Lock()
        self._handoff: Dict[Tuple[str, str], _HandoffRow] = {}
        self._offload: Dict[Tuple[str, str], _OffloadRow] = {}

    def record_handoff(self, pipeline: str, stage: str, src: str,
                       dst: str, frames: int, nbytes: int) -> None:
        """Count one cross-subset handoff INTO ``stage``: ``frames``
        frames, ``nbytes`` exact payload bytes, moving from subset
        ``src`` to subset ``dst``."""
        key = (str(pipeline), str(stage))
        with self._lock:
            row = self._handoff.get(key)
            if row is None or row.src != src or row.dst != dst:
                prev = row
                row = self._handoff[key] = _HandoffRow(str(src), str(dst))
                if prev is not None:  # subset changed: keep the totals
                    row.frames, row.bytes = prev.frames, prev.bytes
                    row.emits = prev.emits
            row.frames += int(frames)
            row.bytes += int(nbytes)

    def record_emit(self, pipeline: str, stage: str,
                    frames: int = 1) -> None:
        """A handed-off frame left the stage (the depth decrement)."""
        key = (str(pipeline), str(stage))
        with self._lock:
            row = self._handoff.get(key)
            if row is not None:
                row.emits += int(frames)

    def record_offload(self, pipeline: str, element: str,
                       offloaded: bool, dst: str = "") -> None:
        """Count one cascade routing decision at ``element`` (a
        ``tensor_if`` with the ``offload=`` property): ``offloaded``
        frames go to the heavy stage, the rest stay local."""
        key = (str(pipeline), str(element))
        with self._lock:
            row = self._offload.get(key)
            if row is None:
                row = self._offload[key] = _OffloadRow(str(dst))
            elif dst and row.dst != dst:
                row.dst = str(dst)
            if offloaded:
                row.offloaded += 1
            else:
                row.kept += 1

    # -- pull side -----------------------------------------------------------

    def snapshot(self) -> List[dict]:
        """Rows for the registry's ``stages`` table (v8), sorted:
        ``kind="handoff"`` rows per receiving stage, ``kind="offload"``
        rows per routing tensor_if."""
        out: List[dict] = []
        with self._lock:
            handoff = [(k, r.src, r.dst, r.frames, r.bytes, r.emits)
                       for k, r in sorted(self._handoff.items())]
            offload = [(k, r.dst, r.offloaded, r.kept)
                       for k, r in sorted(self._offload.items())]
        for (pl, stage), src, dst, frames, nbytes, emits in handoff:
            out.append({
                "kind": "handoff", "pipeline": pl, "stage": stage,
                "from": src, "to": dst,
                "frames": frames, "bytes": nbytes,
                # frames that crossed into the stage but have not left
                # it yet: the inter-stage queue depth
                "depth": max(frames - emits, 0),
            })
        for (pl, el), dst, offed, kept in offload:
            total = offed + kept
            out.append({
                "kind": "offload", "pipeline": pl, "stage": el,
                "to": dst, "offloaded": offed, "kept": kept,
                "ratio": (offed / total) if total else 0.0,
            })
        return out

    def get(self, pipeline: str, stage: str) -> Optional[dict]:
        for row in self.snapshot():
            if row["pipeline"] == str(pipeline) \
                    and row["stage"] == str(stage):
                return row
        return None

    def reset(self) -> None:
        """Tests/bench only: drop every row."""
        with self._lock:
            self._handoff.clear()
            self._offload.clear()


#: the process-wide store the handoff/offload seams feed
STAGE_STATS = StageStats()


def record_handoff(pipeline: str, stage: str, src: str, dst: str,
                   frames: int, nbytes: int) -> None:
    """Module-level shim (inert under the global obs kill switch;
    never raises into the hot path)."""
    if not ACTIVE:
        return
    try:
        STAGE_STATS.record_handoff(pipeline, stage, src, dst,
                                   frames, nbytes)
    except Exception:  # noqa: BLE001 - telemetry must not kill a dispatch
        pass


def record_emit(pipeline: str, stage: str, frames: int = 1) -> None:
    if not ACTIVE:
        return
    try:
        STAGE_STATS.record_emit(pipeline, stage, frames)
    except Exception:  # noqa: BLE001 - telemetry must not kill a dispatch
        pass


def record_offload(pipeline: str, element: str, offloaded: bool,
                   dst: str = "") -> None:
    if not ACTIVE:
        return
    try:
        STAGE_STATS.record_offload(pipeline, element, offloaded, dst)
    except Exception:  # noqa: BLE001 - telemetry must not kill a dispatch
        pass

"""``nns-ctl`` — the closed-loop controller: rule → playbook → actuation.

``obs/watch.py`` turned the registry into alarms; this module turns
alarms into *actions*.  A :class:`Controller` subscribes to a watchdog's
alert state (in-process, or a fleet-scraping watch over the shared
``obs/scrape.py`` client) and maps firing rules through declarative
**playbooks** onto the runtime's **actuator API**
(``runtime/actuators.py``): tighten the admission shed ramp when the
SLO budget burns, widen a pool's batch window when MFU collapses with
roofline headroom to spare, force a half-open probe on a link whose
breaker is stuck open.  Every knob is bounded, cooldown-guarded and
reversible, so the controller can steer the serving plane but cannot
wedge it.

Every decision is itself observability:

- ``nns_control_actions_total{playbook,actuator,outcome}`` counts every
  decision (applied, clamped, cooldown-rejected, guard-held, failed,
  no-target, reverted — rejections are data, not silence);
- ``nns_control_state{kind,target,actuator}`` gauges the last applied
  value per knob;
- a bounded **decision audit ring** records observed series values →
  rule → chosen action → applied/prior values, exported in the registry
  snapshot's ``control`` table (v6), rendered by ``nns-top``'s CONTROL
  section, summarized on ``/healthz``, and noted + dumped by the flight
  recorder on every actuation.

Playbooks load from a TOML/JSON file (``NNS_TPU_CTL_PLAYBOOKS``;
grammar below) on top of the built-in :func:`default_playbooks` pack.
``NNS_TPU_CTL=<interval_s>`` starts a process-global controller at
first pipeline start (same activation hook as ``NNS_TPU_WATCH``),
reusing the env-started watchdog or starting one.  The global obs kill
switch ``NNS_TPU_OBS_DISABLE`` makes the whole module strictly inert:
no thread, no actuation, no export.

Playbook grammar (TOML shown; JSON is the same structure under a
top-level ``"playbook"`` list)::

    [[playbook]]
    name = "tighten-admission"
    rule = "slo-burn"           # the watch rule that triggers it
    kind = "pool"               # pool | link
    actuator = "ramp-start"     # runtime/actuators.py catalog
    action = "set"              # set | step | revert
    value = 0.5
    target = "*"                # fnmatch on the target label; the
                                # firing alert's own pool/link label
                                # narrows it further
    cooldown = "10s"            # playbook-level rate limit
    on_resolve = "revert"       # revert | none (when the rule clears)
    guard = ""                  # "" | "mfu-headroom"

``nns-lint --ctl-playbooks FILE`` statically validates a playbook file
(NNS511: unknown rule/actuator, a target no analyzed pipeline creates)
— see :mod:`nnstreamer_tpu.analyze.ctlplaybooks`.
"""

from __future__ import annotations

import collections
import dataclasses
import fnmatch
import json
import os
import threading
import time
import weakref
from typing import Any, Deque, Dict, List, Optional, Tuple

from . import hooks as _hooks
from .metrics import REGISTRY, MetricsRegistry
from .watch import RuleError as _WatchRuleError
from .watch import Watch, _parse_duration

from ..runtime.actuators import (
    KNOWN_ACTUATORS,
    ActuationError,
    Actuator,
    CooldownActive,
    find_actuators,
)

PLAYBOOK_ACTIONS = ("set", "step", "revert")

PLAYBOOK_GUARDS = ("", "mfu-headroom")

ON_RESOLVE = ("none", "revert")

#: the guard's "no headroom" ceiling: with live MFU at/above this (or
#: HBM bandwidth saturated) widening the window buys nothing — the
#: executable is already at its roofline
GUARD_MFU_CEILING = 0.85
GUARD_BW_CEILING = 0.95

#: decision outcomes (the ``outcome`` label on
#: ``nns_control_actions_total``)
OUTCOMES = ("applied", "reverted", "cooldown", "guard-hold", "failed",
            "no-target", "noop")


class PlaybookError(ValueError):
    """Malformed playbook / playbook file (the NNS511 parse failure)."""


@dataclasses.dataclass
class Playbook:
    """One declarative rule→actuation mapping (grammar in the module
    doc)."""

    name: str
    rule: str
    kind: str
    actuator: str
    action: str = "set"
    value: float = 0.0
    target: str = "*"
    cooldown_s: float = 5.0
    on_resolve: str = "none"
    guard: str = ""
    severity: str = ""
    #: only act when the firing alert's offending series carries this
    #: tenant label (tenant attribution — obs/tenantstat.py): a
    #: shed-burn playbook scoped to the tenant whose traffic it should
    #: throttle.  "" = any series (the default, tenant-blind)
    tenant: str = ""

    def __post_init__(self):
        if not str(self.name).strip():
            raise PlaybookError("playbook without a name")
        ctx = f"playbook {self.name!r}"
        for fld in ("rule", "kind", "actuator"):
            if not str(getattr(self, fld)).strip():
                raise PlaybookError(f"{ctx}: no {fld}")
        if self.kind not in KNOWN_ACTUATORS:
            raise PlaybookError(
                f"{ctx}: unknown target kind {self.kind!r}; one of "
                f"{sorted(KNOWN_ACTUATORS)}")
        if self.action not in PLAYBOOK_ACTIONS:
            raise PlaybookError(
                f"{ctx}: unknown action {self.action!r}; one of "
                f"{list(PLAYBOOK_ACTIONS)}")
        if self.on_resolve not in ON_RESOLVE:
            raise PlaybookError(
                f"{ctx}: on_resolve={self.on_resolve!r} not one of "
                f"{list(ON_RESOLVE)}")
        if self.guard not in PLAYBOOK_GUARDS:
            raise PlaybookError(
                f"{ctx}: unknown guard {self.guard!r}; one of "
                f"{[g or '(none)' for g in PLAYBOOK_GUARDS]}")
        if isinstance(self.value, bool) \
                or not isinstance(self.value, (int, float)):
            raise PlaybookError(f"{ctx}: value={self.value!r} must be "
                                f"a number")
        self.value = float(self.value)
        if not isinstance(self.cooldown_s, (int, float)) \
                or isinstance(self.cooldown_s, bool) \
                or self.cooldown_s < 0:
            raise PlaybookError(f"{ctx}: cooldown must be a "
                                f"duration >= 0")
        self.cooldown_s = float(self.cooldown_s)
        if self.action == "step" and self.value == 0.0:
            raise PlaybookError(f"{ctx}: step with value=0 never "
                                f"moves the knob")


_PB_KEY_MAP = {"cooldown": "cooldown_s"}
_PB_FIELDS = {f.name for f in dataclasses.fields(Playbook)}


def parse_playbook(item: dict) -> Playbook:
    if not isinstance(item, dict):
        raise PlaybookError(
            f"playbook entry is not a table/object: {item!r}")
    kw: Dict[str, Any] = {}
    for key, val in item.items():
        fld = _PB_KEY_MAP.get(key, key)
        if fld not in _PB_FIELDS:
            raise PlaybookError(
                f"playbook {item.get('name', '?')!r}: unknown key "
                f"{key!r} (known: "
                f"{sorted(_PB_FIELDS | set(_PB_KEY_MAP))})")
        if fld == "cooldown_s":
            val = _parse_duration(
                val, f"playbook {item.get('name', '?')!r}.{key}")
        kw[fld] = val
    for required in ("name", "rule", "kind", "actuator"):
        if required not in kw:
            raise PlaybookError(
                f"playbook {kw.get('name', '?')!r}: missing "
                f"{required!r}")
    if kw.get("action", "set") != "revert" and "value" not in kw:
        # a forgotten value would silently actuate the dataclass
        # default 0.0 — for the coalescing knob that PAUSES the very
        # window the playbook meant to fix
        raise PlaybookError(
            f"playbook {kw.get('name', '?')!r}: action "
            f"{kw.get('action', 'set')!r} needs an explicit 'value'")
    try:
        return Playbook(**kw)
    except _WatchRuleError as e:  # _parse_duration raises RuleError
        raise PlaybookError(str(e)) from None


def parse_playbooks(doc: Any) -> List[Playbook]:
    """Playbooks from a parsed TOML/JSON document: a top-level
    ``playbook`` (or ``playbooks``) list, or a bare list."""
    if isinstance(doc, dict):
        items = doc.get("playbook", doc.get("playbooks"))
        if items is None:
            raise PlaybookError(
                "playbooks document has no top-level 'playbook' list "
                "([[playbook]] tables in TOML, \"playbook\": [...] in "
                "JSON)")
    else:
        items = doc
    if not isinstance(items, list) or not items:
        raise PlaybookError("playbooks document names no playbooks")
    pbs = [parse_playbook(item) for item in items]
    seen: Dict[str, int] = {}
    for pb in pbs:
        seen[pb.name] = seen.get(pb.name, 0) + 1
    dupes = sorted(n for n, c in seen.items() if c > 1)
    if dupes:
        raise PlaybookError(
            f"duplicate playbook name(s): {dupes} — controller state "
            f"is keyed by name")
    return pbs


def load_playbooks(path: str) -> List[Playbook]:
    """Load + parse a playbook file; ``.toml`` via stdlib tomllib
    (3.11+), anything else as JSON.  Raises :class:`PlaybookError` on
    malformed grammar, ``OSError`` on unreadable files."""
    if str(path).endswith(".toml"):
        try:
            import tomllib
        except ImportError:
            raise PlaybookError(
                "TOML playbook files need Python 3.11+ (tomllib); "
                "use the JSON form instead") from None
        try:
            with open(path, "rb") as f:
                doc = tomllib.load(f)
        except tomllib.TOMLDecodeError as e:
            raise PlaybookError(f"invalid TOML: {e}") from None
    else:
        with open(path, "r", encoding="utf-8") as f:
            try:
                doc = json.load(f)
            except ValueError as e:
                raise PlaybookError(f"invalid JSON: {e}") from None
    return parse_playbooks(doc)


def lint_playbook(pb: Playbook,
                  rule_names: Optional[List[str]] = None) -> List[str]:
    """Static problems with one (well-formed) playbook — the NNS511
    checks beyond grammar: an actuator nothing exports, a rule name
    the active rule set never evaluates."""
    problems: List[str] = []
    if pb.actuator not in KNOWN_ACTUATORS.get(pb.kind, ()):
        problems.append(
            f"actuator {pb.actuator!r} does not exist on kind "
            f"{pb.kind!r} (known: "
            f"{list(KNOWN_ACTUATORS.get(pb.kind, ()))})")
    if rule_names is not None and pb.rule not in rule_names:
        problems.append(
            f"rule {pb.rule!r} is not in the active rule set (the "
            f"playbook can never trigger); known rules: "
            f"{sorted(rule_names)}")
    if pb.action == "revert" and pb.on_resolve == "revert":
        problems.append(
            "action=revert with on_resolve=revert is a double "
            "back-out (the resolve revert finds nothing to restore)")
    return problems


def default_playbooks() -> List[Playbook]:
    """The built-in pack, mirroring the ROADMAP's closed-loop triad:
    SLO burn → shed earlier/harder; MFU collapse with roofline headroom
    → widen the batch window (the clamp at the largest compiled bucket
    is the guard); breaker stuck open → force the half-open probe
    (re-dial) instead of sitting out the open window."""
    P = Playbook
    return [
        P(name="tighten-admission", rule="slo-burn", kind="pool",
          actuator="ramp-start", action="set", value=0.5,
          cooldown_s=10.0, on_resolve="revert"),
        P(name="widen-window", rule="mfu-collapse", kind="pool",
          actuator="max-batch", action="step", value=8.0,
          guard="mfu-headroom", cooldown_s=10.0),
        P(name="widen-deadline", rule="mfu-collapse", kind="pool",
          actuator="window-ms", action="step", value=2.0,
          guard="mfu-headroom", cooldown_s=10.0),
        P(name="redial-link", rule="breaker-open", kind="link",
          actuator="breaker", action="set", value=1.0,
          cooldown_s=2.0),
    ]


def playbooks_from_env() -> List[Playbook]:
    """The active playbook set: ``NNS_TPU_CTL_PLAYBOOKS=<file>`` when
    set (replacing the default pack), else :func:`default_playbooks`."""
    path = os.environ.get("NNS_TPU_CTL_PLAYBOOKS", "").strip()
    if not path:
        return default_playbooks()
    return load_playbooks(path)


# -- the controller -----------------------------------------------------------


class _PbState:
    __slots__ = ("was_firing", "last_ts", "applied")

    def __init__(self):
        self.was_firing = False
        self.last_ts: Optional[float] = None
        # (kind, target, actuator) keys this playbook steered, for the
        # on_resolve revert
        self.applied: Dict[Tuple[str, str, str], Actuator] = {}


#: live controllers (weak): the snapshot's ``control`` table and
#: ``/healthz`` aggregate over these, exactly like the pool/link tables
_CTL_LOCK = threading.Lock()
_CONTROLLERS: "weakref.WeakSet[Controller]" = weakref.WeakSet()


class Controller:
    """The actuation loop: watch alert state → playbooks → actuators.

    ``watch`` is the alert source (an :class:`~nnstreamer_tpu.obs.
    watch.Watch`, in-process or fleet-scraping — the controller only
    reads its rule states); actuation targets are always the objects of
    THIS process (``runtime/actuators.py`` discovery).  Strictly inert
    under ``NNS_TPU_OBS_DISABLE``: no thread, no actuation, no
    export."""

    def __init__(self, playbooks: Optional[List[Playbook]] = None,
                 watch: Optional[Watch] = None,
                 interval_s: float = 0.5,
                 registry: Optional[MetricsRegistry] = None,
                 audit_len: int = 256):
        self.playbooks = list(playbooks) if playbooks is not None \
            else default_playbooks()
        seen = set()
        for pb in self.playbooks:
            if pb.name in seen:
                raise PlaybookError(f"duplicate playbook {pb.name!r}")
            seen.add(pb.name)
        self.watch = watch
        self.interval_s = max(float(interval_s), 0.01)
        self.registry = registry if registry is not None else REGISTRY
        self.enabled = not _hooks.DISABLED
        self.audit: Deque[dict] = collections.deque(
            maxlen=int(audit_len))
        self.actions_total = 0
        self.last_action: Optional[dict] = None
        self.ticks = 0
        self._states: Dict[str, _PbState] = {
            pb.name: _PbState() for pb in self.playbooks}
        self._lock = threading.RLock()
        # LEAF lock for the audit/export state (_record writes,
        # snapshot/control_table/control_health read).  It exists so
        # the scrape path — registry.snapshot() → control_table(),
        # possibly called by a Watch sampler HOLDING the watch lock —
        # never needs self._lock, which tick() holds WHILE taking the
        # watch lock (alerts(), guard reads).  One lock for both paths
        # is a lock-order inversion: tick holds ctl→wants watch, the
        # sampler holds watch→wants ctl.
        self._alock = threading.Lock()  # nns-lock: leaf
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        if self.enabled:
            self._actions = self.registry.counter(
                "nns_control_actions_total",
                "controller decisions by outcome (obs/control.py)",
                labelnames=("playbook", "actuator", "outcome"))
            self._state_gauge = self.registry.gauge(
                "nns_control_state",
                "last applied value of a steered knob",
                labelnames=("kind", "target", "actuator"))
            with _CTL_LOCK:
                _CONTROLLERS.add(self)

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> bool:
        """Spawn the actuation loop (False — and strictly nothing else
        — under the global obs kill switch)."""
        if not self.enabled or self._thread is not None:
            return False
        self._stop.clear()
        from . import prof as _prof

        self._thread = _prof.named_thread("ctl", "actuator", self._run)
        self._thread.start()
        return True

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 - the controller must
                # outlive whatever it steers; one bad tick is logged,
                # not fatal
                from ..utils.log import logw

                logw("nns-ctl: tick failed: %s: %s",
                     type(e).__name__, e)

    # -- one tick -------------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> List[dict]:
        """One control round: read alert state, run due playbooks,
        revert resolved ones.  Returns this tick's decisions."""
        if not self.enabled:
            return []
        with self._lock:
            now = time.monotonic() if now is None else now
            self.ticks += 1
            alerts = {a["rule"]: a for a in self.watch.alerts()} \
                if self.watch is not None else {}
            decisions: List[dict] = []
            for pb in self.playbooks:
                st = self._states[pb.name]
                a = alerts.get(pb.rule)
                firing = bool(a and a["firing"]) and (
                    not pb.severity or a["severity"] == pb.severity)
                if firing and pb.tenant:
                    # tenant-scoped playbook: the offending series
                    # must name this tenant (forecast/threshold rules
                    # over nns_tenant_* families carry the label)
                    series = ((a.get("detail") or {})
                              .get("series") or {})
                    firing = series.get("tenant") == pb.tenant
                if firing:
                    decisions.extend(self._fire(pb, st, a, now))
                elif st.was_firing and pb.on_resolve == "revert":
                    decisions.extend(self._resolve(pb, st, now))
                st.was_firing = firing
            return decisions

    def _observed(self, alert: Optional[dict]) -> dict:
        d = (alert or {}).get("detail") or {}
        return {"metric": d.get("metric", ""),
                "value": d.get("value"),
                "series": dict(d.get("series") or {})}

    def _fire(self, pb: Playbook, st: _PbState, alert: dict,
              now: float) -> List[dict]:
        if st.last_ts is not None \
                and now - st.last_ts < pb.cooldown_s:
            return []  # playbook-level pacing: not even a decision —
            # the episode was already acted on this cooldown window
        observed = self._observed(alert)
        base = {"rule": pb.rule, "playbook": pb.name, "kind": pb.kind,
                "actuator": pb.actuator, "action": pb.action,
                "observed": observed}
        if pb.guard and not self._guard_passes(pb.guard):
            st.last_ts = now
            return [self._record(dict(
                base, target=pb.target, requested=pb.value,
                applied=None, prior=None, clamped=False,
                outcome="guard-hold", guard=pb.guard), now)]
        acts = self._resolve_targets(pb, observed["series"])
        if not acts:
            st.last_ts = now
            return [self._record(dict(
                base, target=pb.target, requested=pb.value,
                applied=None, prior=None, clamped=False,
                outcome="no-target"), now)]
        st.last_ts = now
        out = []
        for act in acts:
            out.append(self._record(
                self._execute(pb, st, act, base, now), now))
        return out

    def _resolve(self, pb: Playbook, st: _PbState,
                 now: float) -> List[dict]:
        out = []
        applied, st.applied = st.applied, {}
        for (kind, target, name), act in applied.items():
            base = {"rule": pb.rule, "playbook": pb.name,
                    "kind": kind, "actuator": name, "action": "revert",
                    "target": target,
                    "observed": {"metric": "", "value": None,
                                 "series": {}, "resolved": True}}
            try:
                res = act.revert(now=now)
            except ActuationError as e:
                out.append(self._record(dict(
                    base, requested=None, applied=None, prior=None,
                    clamped=False, outcome="failed", error=str(e)),
                    now))
                continue
            if res is None:
                out.append(self._record(dict(
                    base, requested=None, applied=None, prior=None,
                    clamped=False, outcome="noop"), now))
                continue
            out.append(self._record(dict(
                base, requested=None, applied=res["applied"],
                prior=res["prior"], clamped=False,
                outcome="reverted"), now))
        return out

    def _execute(self, pb: Playbook, st: _PbState, act: Actuator,
                 base: dict, now: float) -> dict:
        d = dict(base, target=act.target, requested=pb.value,
                 applied=None, prior=None, clamped=False)
        try:
            if pb.action == "revert":
                res = act.revert(now=now)
                if res is None:
                    return dict(d, outcome="noop")
                return dict(d, requested=None,
                            applied=res["applied"],
                            prior=res["prior"], outcome="reverted")
            value = pb.value
            if pb.action == "step":
                cur = act.read()
                if cur is None or not isinstance(cur, (int, float)):
                    return dict(d, outcome="failed",
                                error="current value unreadable")
                value = float(cur) + pb.value
            res = act.actuate(value, now=now)
            if pb.on_resolve == "revert":
                # only revert-on-resolve playbooks need the actuator
                # back; holding it otherwise would pin the pool/link
                # the closures capture for the controller's lifetime
                st.applied[(act.kind, act.target, act.name)] = act
            return dict(d, requested=value, applied=res["applied"],
                        prior=res["prior"], clamped=res["clamped"],
                        outcome="applied")
        except CooldownActive as e:
            return dict(d, outcome="cooldown", error=str(e))
        except ActuationError as e:
            return dict(d, outcome="failed", error=str(e))

    def _resolve_targets(self, pb: Playbook,
                         series: Dict[str, str]) -> List[Actuator]:
        """The firing alert's own labels narrow the playbook's target
        pattern: an alert on pool X steers pool X, not every pool.
        Model-lifecycle knobs target pools too (a canary alert carries
        the pool label of the versions it compares)."""
        label = series.get("pool") if pb.kind in ("pool", "model") \
            else series.get("link")
        target = pb.target or "*"
        acts = find_actuators(pb.kind, target, pb.actuator)
        if label:
            exact = [a for a in acts if a.target == label]
            if exact:
                return exact
            # the alert names an object this process doesn't own (a
            # fleet-scraped alert): fall through to the pattern — the
            # operator chose the playbook's blast radius via target=
        return acts

    def _guard_passes(self, guard: str) -> bool:
        """``mfu-headroom``: act only while the roofline says a wider
        window can help — live MFU below the ceiling and HBM bandwidth
        not saturated.  With no MFU series at all (unknown backend)
        headroom is unknowable and the guard stands aside."""
        if guard != "mfu-headroom" or self.watch is None:
            return True
        with self.watch._lock:
            mfus = [s.last("level")
                    for _k, s in self.watch.store.match("nns_mfu", {})]
            bws = [s.last("level")
                   for _k, s in self.watch.store.match(
                       "nns_hbm_bw_util", {})]
        mfus = [p[1] for p in mfus if p is not None]
        bws = [p[1] for p in bws if p is not None]
        if not mfus:
            return True
        if max(mfus) >= GUARD_MFU_CEILING:
            return False
        if bws and max(bws) >= GUARD_BW_CEILING:
            return False
        return True

    # -- the audit trail ------------------------------------------------------

    def _record(self, decision: dict, now: float) -> dict:
        """EVERY decision — applied or rejected — lands in the audit
        ring AND the exported counter (the bench gate asserts the two
        counts equal), is gauged when it moved a knob, and is noted +
        dumped by the flight recorder."""
        decision = dict(decision, ts=now, wall=time.time())
        with self._alock:
            self.audit.append(decision)
            self.actions_total += 1
            self.last_action = decision
        self._actions.labels(
            playbook=decision["playbook"],
            actuator=decision["actuator"],
            outcome=decision["outcome"]).inc()
        applied = decision.get("applied")
        if isinstance(applied, (int, float)) \
                and not isinstance(applied, bool):
            self._state_gauge.labels(
                kind=decision["kind"], target=decision["target"],
                actuator=decision["actuator"]).set(float(applied))
        from ..utils.log import logw

        logw("nns-ctl: %s %s.%s[%s] %s -> %s (%s)",
             decision["playbook"], decision["kind"],
             decision["actuator"], decision["target"],
             decision.get("prior"), applied, decision["outcome"])
        from .flightrec import FLIGHT

        FLIGHT.note("actuation", decision["playbook"],
                    actuator=decision["actuator"],
                    target=decision["target"],
                    outcome=decision["outcome"],
                    applied=applied, prior=decision.get("prior"))
        FLIGHT.trigger_async("actuation", decision["playbook"])
        return decision

    def apply(self, kind: str, target: str, actuator: str,
              value: Optional[float] = None,
              revert: bool = False) -> List[dict]:
        """Manual actuation (the ``nns-ctl --apply/--revert`` path):
        routed through the same guard/audit/export machinery as a
        playbook decision, under the reserved playbook name
        ``manual``.  A no-op (empty list) while obs is disabled."""
        if not self.enabled:
            return []
        with self._lock:
            now = time.monotonic()
            base = {"rule": "", "playbook": "manual", "kind": kind,
                    "actuator": actuator,
                    "action": "revert" if revert else "set",
                    "observed": {"metric": "", "value": None,
                                 "series": {}}}
            acts = find_actuators(kind, target or "*", actuator)
            if not acts:
                return [self._record(dict(
                    base, target=target or "*", requested=value,
                    applied=None, prior=None, clamped=False,
                    outcome="no-target"), now)]
            out = []
            for act in acts:
                d = dict(base, target=act.target, requested=value,
                         applied=None, prior=None, clamped=False)
                try:
                    if revert:
                        res = act.revert(now=now)
                        if res is None:
                            out.append(self._record(
                                dict(d, outcome="noop"), now))
                            continue
                        out.append(self._record(dict(
                            d, applied=res["applied"],
                            prior=res["prior"], outcome="reverted"),
                            now))
                    else:
                        # text knobs (the lifecycle's swap/canary)
                        # take the raw string — a model reference is
                        # not a number
                        v = value if (getattr(act, "text", False)
                                      and isinstance(value, str)) \
                            else float(value)
                        res = act.actuate(v, now=now)
                        out.append(self._record(dict(
                            d, applied=res["applied"],
                            prior=res["prior"],
                            clamped=res["clamped"],
                            outcome="applied"), now))
                except CooldownActive as e:
                    out.append(self._record(dict(
                        d, outcome="cooldown", error=str(e)), now))
                except ActuationError as e:
                    out.append(self._record(dict(
                        d, outcome="failed", error=str(e)), now))
            return out

    # -- pull side ------------------------------------------------------------

    def snapshot(self, recent: int = 32) -> dict:
        with self._alock:
            return {
                "playbooks": [pb.name for pb in self.playbooks],
                "actions_total": self.actions_total,
                "last_action": dict(self.last_action)
                if self.last_action else None,
                "audit": [dict(d) for d in
                          list(self.audit)[-int(recent):]],
            }


# -- snapshot/healthz integration (pulled by obs/metrics.py) ------------------


def _live_controllers() -> List[Controller]:
    with _CTL_LOCK:
        return list(_CONTROLLERS)


def control_table(recent: int = 32) -> dict:
    """The snapshot's ``control`` table (v6): every live controller's
    playbooks, decision totals and recent audit entries aggregated —
    empty-but-present when no controller runs, so the top-level
    snapshot shape is stable."""
    ctls = _live_controllers()
    snaps = [c.snapshot(recent=recent) for c in ctls]
    audit = sorted((d for s in snaps for d in s["audit"]),
                   key=lambda d: d.get("ts", 0.0))[-int(recent):]
    last = None
    for s in snaps:
        la = s["last_action"]
        if la and (last is None or la.get("ts", 0) > last.get("ts", 0)):
            last = la
    return {
        "controllers": len(ctls),
        "playbooks": sorted({n for s in snaps for n in s["playbooks"]}),
        "actions_total": sum(s["actions_total"] for s in snaps),
        "last_action": last,
        "audit": audit,
    }


def control_health() -> dict:
    """Cheap controller summary for ``/healthz``: playbooks loaded,
    decision count, last action — no full audit walk."""
    ctls = _live_controllers()
    last = None
    total = 0
    names: set = set()
    for c in ctls:
        with c._alock:
            total += c.actions_total
            la = c.last_action
        names.update(pb.name for pb in c.playbooks)
        if la and (last is None or la.get("ts", 0) > last.get("ts", 0)):
            last = la
    return {
        "controllers": len(ctls),
        "playbooks": sorted(names),
        "actions_total": total,
        "last_action": {
            "playbook": last["playbook"], "actuator": last["actuator"],
            "target": last["target"], "outcome": last["outcome"],
            "wall": last["wall"]} if last else None,
    }


# -- process-global controller (env hook) -------------------------------------

CONTROLLER: Optional[Controller] = None

_env_checked = False


def maybe_start_from_env() -> None:
    """``NNS_TPU_CTL=<interval_s>`` starts a process-global controller
    on first pipeline start, with playbooks from
    ``NNS_TPU_CTL_PLAYBOOKS`` (or the default pack) and the env-started
    watchdog as its alert source (starting one with the default rule
    pack when ``NNS_TPU_WATCH`` wasn't set — a controller without
    alarms would be deaf).  A no-op under the global obs kill
    switch."""
    global _env_checked, CONTROLLER
    if _env_checked:
        return
    _env_checked = True
    spec = os.environ.get("NNS_TPU_CTL", "").strip()
    if not spec or _hooks.DISABLED:
        return
    from . import watch as _watch

    try:
        interval = float(spec) if spec not in ("1", "true", "yes") \
            else 1.0
        if _watch.WATCH is None:
            _watch.WATCH = Watch(rules=_watch.rules_from_env(),
                                 interval_s=min(interval, 1.0))
            _watch.WATCH.start()
        CONTROLLER = Controller(playbooks=playbooks_from_env(),
                                watch=_watch.WATCH,
                                interval_s=interval)
        CONTROLLER.start()
    except (ValueError, PlaybookError, _WatchRuleError, OSError) as e:
        from ..utils.log import logw

        logw("cannot start controller from NNS_TPU_CTL=%s: %s",
             spec, e)


# -- CLI (`nns-ctl`) ----------------------------------------------------------


def _render_actuators(acts: List[Actuator]) -> str:
    lines = [f"{'KIND':<6}{'TARGET':<28}{'ACTUATOR':<13}{'VALUE':>10}"
             f"{'LO':>8}{'HI':>9}{'UNIT':>8}{'CD s':>6}{'DIRTY':>7}"]
    for a in acts:
        d = a.describe()
        val = d["value"]
        lines.append(
            f"{d['kind']:<6}{d['target']:<28.28}{d['actuator']:<13.13}"
            + (f"{val:.3g}" if isinstance(val, (int, float))
               else "-").rjust(10)
            + (f"{d['lo']:g}" if d["lo"] is not None else "-").rjust(8)
            + (f"{d['hi']:g}" if d["hi"] is not None else "-").rjust(9)
            + str(d["unit"] or "-").rjust(8)
            + f"{d['cooldown_s']:g}".rjust(6)
            + ("yes" if d["dirty"] else "no").rjust(7))
    return "\n".join(lines)


def render_audit(audit: List[dict], indent: str = "") -> str:
    """Decision rows as one table — the ONE renderer behind both
    ``nns-ctl --audit`` and ``nns-top``'s CONTROL section."""
    lines = [indent + f"{'PLAYBOOK':<20}{'RULE':<18}{'ACTUATOR':<13}"
                      f"{'TARGET':<24}{'VALUE':>10}{'OUTCOME':>11}"]
    for d in audit:
        applied = d.get("applied")
        lines.append(
            indent + f"{d.get('playbook', '?'):<20.20}"
            f"{d.get('rule', '') or '-':<18.18}"
            f"{d.get('actuator', '?'):<13.13}"
            f"{str(d.get('target', '?')):<24.24}"
            + (f"{applied:.3g}" if isinstance(applied, (int, float))
               and not isinstance(applied, bool)
               else "-").rjust(10)
            + str(d.get("outcome", "?")).rjust(11))
    return "\n".join(lines)


_render_audit = render_audit  # CLI-internal alias


def _parse_spec(spec: str) -> Tuple[str, str, str, Optional[Any]]:
    """``kind:target:actuator[=value]`` → parts (the --apply/--revert
    grammar; target may itself contain ``:`` — kind is the first
    segment, the actuator name the last).  Non-numeric values pass
    through as strings for the text-valued lifecycle knobs
    (``model:<pool>:swap=file://new.pkl@v2``)."""
    head, _, val = spec.partition("=")
    parts = head.split(":")
    if len(parts) < 3:
        raise ValueError(
            f"bad actuation spec {spec!r} (want "
            f"kind:target:actuator[=value])")
    kind, target, name = parts[0], ":".join(parts[1:-1]), parts[-1]
    if not val:
        return kind, target, name, None
    try:
        return kind, target, name, float(val)
    except ValueError:
        return kind, target, name, val


def build_parser():
    import argparse

    p = argparse.ArgumentParser(
        prog="nns-ctl",
        description="Closed-loop controller over the actuator API: "
                    "list knobs, actuate, audit, or run the "
                    "rule→playbook loop "
                    "(Documentation/observability.md)")
    p.add_argument("--list", action="store_true",
                   help="list every live actuator (value, bounds, "
                        "cooldown)")
    p.add_argument("--apply", metavar="KIND:TARGET:ACTUATOR=VALUE",
                   action="append", default=[],
                   help="one manual actuation (repeatable; audited "
                        "like a playbook decision)")
    p.add_argument("--revert", metavar="KIND:TARGET:ACTUATOR",
                   action="append", default=[],
                   help="restore a knob's pre-steering config")
    p.add_argument("--audit", action="store_true",
                   help="print the decision audit ring")
    p.add_argument("--run", action="store_true",
                   help="run the controller loop (rules + playbooks)")
    p.add_argument("--playbooks", default=None, metavar="FILE",
                   help="TOML/JSON playbook file (default: "
                        "$NNS_TPU_CTL_PLAYBOOKS, else the built-in "
                        "pack)")
    p.add_argument("--rules", default=None, metavar="FILE",
                   help="watch rules file for --run (default: "
                        "$NNS_TPU_WATCH_RULES, else the built-in "
                        "pack)")
    p.add_argument("--connect", metavar="HOST:PORT[,HOST:PORT...]",
                   action="append", default=None,
                   help="watch remote /json endpoints for --run "
                        "(alert source only; actuation targets are "
                        "in-process)")
    p.add_argument("--interval", type=float, default=0.5,
                   help="seconds between control rounds (default 0.5)")
    p.add_argument("--once", type=int, default=None, metavar="N",
                   help="with --run: N watch+control rounds, print "
                        "the audit, exit")
    p.add_argument("--json", dest="as_json", action="store_true",
                   help="machine-readable output")
    return p


def main(argv=None, out=None) -> int:
    import sys

    out = out or sys.stdout
    args = build_parser().parse_args(argv)
    if not (args.list or args.apply or args.revert or args.audit
            or args.run):
        build_parser().print_usage(sys.stderr)
        print("error: nothing to do (use --list, --apply, --revert, "
              "--audit or --run)", file=sys.stderr)
        return 2
    if _hooks.DISABLED:
        print("nns-ctl: observability disabled (NNS_TPU_OBS_DISABLE) "
              "— nothing to do", file=sys.stderr)
        return 2
    from ..runtime.actuators import list_actuators

    if args.list:
        acts = list_actuators()
        if args.as_json:
            print(json.dumps([a.describe() for a in acts], indent=1),
                  file=out)
        else:
            print(_render_actuators(acts), file=out)
        if not (args.apply or args.revert or args.run or args.audit):
            return 0
    try:
        playbooks = load_playbooks(args.playbooks) if args.playbooks \
            else playbooks_from_env()
    except (PlaybookError, OSError) as e:
        print(f"nns-ctl: bad playbooks: {e}", file=sys.stderr)
        return 2
    if args.apply or args.revert:
        ctl = Controller(playbooks=playbooks, watch=None)
        decisions = []
        try:
            for spec in args.apply:
                kind, target, name, value = _parse_spec(spec)
                if value is None:
                    raise ValueError(f"--apply {spec!r} needs =VALUE")
                decisions.extend(ctl.apply(kind, target, name,
                                           value=value))
            for spec in args.revert:
                kind, target, name, _v = _parse_spec(spec)
                decisions.extend(ctl.apply(kind, target, name,
                                           revert=True))
        except ValueError as e:
            print(f"nns-ctl: {e}", file=sys.stderr)
            return 2
        if args.as_json:
            print(json.dumps(decisions, indent=1, default=str),
                  file=out)
        else:
            print(_render_audit(decisions), file=out)
        bad = [d for d in decisions
               if d["outcome"] not in ("applied", "reverted", "noop")]
        return 1 if bad else 0
    if args.audit and not args.run:
        table = control_table(recent=64)
        if args.as_json:
            print(json.dumps(table, indent=1, default=str), file=out)
        else:
            print(_render_audit(table["audit"]), file=out)
        return 0
    # --run
    from . import watch as _watch

    try:
        rules = _watch.load_rules(args.rules) if args.rules \
            else _watch.rules_from_env()
    except (_WatchRuleError, OSError) as e:
        print(f"nns-ctl: bad rules: {e}", file=sys.stderr)
        return 2
    endpoints: List[str] = []
    for item in args.connect or []:
        endpoints.extend(tok.strip() for tok in str(item).split(",")
                         if tok.strip())
    w = Watch(rules=rules, interval_s=args.interval,
              endpoints=endpoints or None)
    ctl = Controller(playbooks=playbooks, watch=w,
                     interval_s=args.interval)
    try:
        if args.once is not None:
            for i in range(max(args.once, 1)):
                if i:
                    time.sleep(args.interval)
                w.sample_once()
                ctl.tick()
            snap = ctl.snapshot(recent=64)
            if args.as_json:
                print(json.dumps(snap, indent=1, default=str),
                      file=out)
            else:
                print(_render_audit(snap["audit"]), file=out)
            return 0
        w.start()
        ctl.start()
        while True:
            time.sleep(max(args.interval, 0.1))
    except KeyboardInterrupt:
        return 0
    finally:
        ctl.stop()
        w.stop()


if __name__ == "__main__":
    import sys

    sys.exit(main())

"""Static analyzer (`nnstreamer_tpu.analyze`) tests.

Covers every diagnostic code at least once, the good-corpus
zero-false-positive guarantee, the caps-dry-run regressions
(rank-flexible dims, framerate 0/1), JSON golden output, and the
satellite runtime fixes (Bus.remove_watch, parser positions,
double-link rejection).
"""

import io
import json
import os
import threading

import numpy as np
import pytest

from nnstreamer_tpu.analyze import (
    CODES,
    Severity,
    analyze_description,
    analyze_pipeline,
    lint_package,
    lint_source,
)
from nnstreamer_tpu.analyze.cli import main as cli_main
from nnstreamer_tpu.core import Buffer, Caps, TensorsSpec
from nnstreamer_tpu.runtime import (
    Bus,
    Pipeline,
    TransformElement,
    make,
    parse_launch,
    register_element,
)
from nnstreamer_tpu.runtime.events import Message, MessageKind
from nnstreamer_tpu.runtime.parser import ParseError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

GOOD_CAPS = ("other/tensors,format=static,num_tensors=1,"
             "dimensions=3:4:4:1,types=uint8,framerate=30/1")
GOOD = f"appsrc caps={GOOD_CAPS} ! tensor_converter ! tensor_sink"


def codes(diags):
    return {d.code for d in diags}


def above_info(diags):
    return [d for d in diags if d.severity != Severity.INFO]


# -- crafted elements used to reach the rarer codes --------------------------


@pytest.fixture(scope="module", autouse=True)
def _cleanup_test_factories():
    yield
    from nnstreamer_tpu.runtime import registry

    with registry._lock:
        for name in ("_t_anycaps", "_t_reject"):
            registry._factories.pop(name, None)


@register_element("_t_anycaps")
class _AnyCapsElement(TransformElement):
    """Proposes wildcard caps: downstream fixation must fail (NNS202)."""

    FACTORY = "_t_anycaps"

    def propose_src_caps(self, pad):
        return Caps.any()

    def transform(self, buf):
        return buf


@register_element("_t_reject")
class _RejectElement(TransformElement):
    """caps_negotiated always rejects (NNS204)."""

    FACTORY = "_t_reject"

    def caps_negotiated(self, pad):
        raise ValueError("crafted rejection")

    def transform(self, buf):
        return buf


# -- known-bad corpus: one pipeline per diagnostic code ----------------------

BAD_CORPUS = [
    ("appsrc ! bogus_thing ! tensor_sink", {"NNS100"}),
    (f"appsrc caps={GOOD_CAPS} ! tensor_sink name=s "
     f"appsrc name=b caps={GOOD_CAPS} ! s.sink", {"NNS103"}),
    # dangling src pad + zero sinks
    (f"appsrc caps={GOOD_CAPS} ! tensor_converter", {"NNS102", "NNS106"}),
    # island: unlinked sink pad, unreachable elements, unreached caps
    (f"appsrc caps={GOOD_CAPS} ! tensor_sink "
     "tensor_converter name=lost ! tensor_sink name=s2",
     {"NNS101", "NNS105", "NNS206"}),
    ("tensor_converter name=c1 ! tensor_converter name=c2 ! c1.",
     {"NNS104", "NNS107", "NNS106"}),
    ("tensor_converter ! tensor_sink", {"NNS107"}),
    (f"appsrc caps={GOOD_CAPS} ! other/tensors,format=static,"
     "num_tensors=1,dimensions=3:8:8:1,types=uint8 ! tensor_sink",
     {"NNS201"}),
    (f"appsrc caps={GOOD_CAPS} ! _t_anycaps ! fakesink", {"NNS202"}),
    ("appsrc ! tensor_sink", {"NNS203"}),
    (f"appsrc caps={GOOD_CAPS} ! _t_reject ! tensor_sink", {"NNS204"}),
    (f"appsrc caps={GOOD_CAPS} ! tensor_filter framework=jax-xla "
     "model=/nonexistent/model.pkl ! tensor_sink", {"NNS205"}),
    # fan-in framerate mismatch
    ("appsrc name=a caps=other/tensors,format=static,num_tensors=1,"
     "dimensions=4,types=uint8,framerate=30/1 ! tensor_mux name=m ! "
     "tensor_sink appsrc name=b caps=other/tensors,format=static,"
     "num_tensors=1,dimensions=4,types=uint8,framerate=15/1 ! m.sink_1",
     {"NNS108"}),
    # micro-batching without an upstream thread boundary
    (f"appsrc caps={GOOD_CAPS} ! tensor_filter framework=jax-xla "
     "model=/nonexistent/model.pkl batch=4 ! tensor_sink", {"NNS501"}),
    # micro-batching with per-invoke synchronous latency measurement
    (f"appsrc caps={GOOD_CAPS} ! queue ! tensor_filter framework=jax-xla "
     "model=/nonexistent/model.pkl batch=4 latency=1 ! tensor_sink",
     {"NNS502"}),
    # same jax-xla model opened twice without share-model: 2x HBM
    (f"appsrc caps={GOOD_CAPS} ! tensor_filter framework=jax-xla "
     "model=/nonexistent/model.pkl ! tensor_sink "
     f"appsrc name=b caps={GOOD_CAPS} ! tensor_filter name=f2 "
     "framework=jax-xla model=/nonexistent/model.pkl ! tensor_sink name=s2",
     {"NNS503"}),
    # share-model on a host-side stateful framework
    (f"appsrc caps={GOOD_CAPS} ! queue ! tensor_filter "
     "framework=custom-easy model=nope share-model=true batch=4 ! "
     "tensor_sink", {"NNS504"}),
    # latency=1 behind a queue: the reported number excludes queue
    # residency (batch=1, so neither NNS501 nor NNS502 applies)
    (f"appsrc caps={GOOD_CAPS} ! queue ! tensor_filter "
     "framework=jax-xla model=/nonexistent/model.pkl latency=1 ! "
     "tensor_sink", {"NNS505"}),
    # traced cross-host query link without NTP sync: remote spans are
    # placed by the in-band symmetric-delay estimate alone (caps= set
    # so the dry-run never dials the—nonexistent—server)
    (f"appsrc caps={GOOD_CAPS} ! tensor_query_client caps={GOOD_CAPS} "
     "dest-host=198.51.100.7 dest-port=5432 ! tensor_sink",
     {"NNS506"}),
    # cross-host query link with the in-flight bound disabled: a dead
    # server means unbounded growth and nothing ever times out
    (f"appsrc caps={GOOD_CAPS} ! tensor_query_client caps={GOOD_CAPS} "
     "dest-host=198.51.100.7 dest-port=5432 timeout=0 max-request=0 ! "
     "tensor_sink", {"NNS507"}),
    # mesh micro-batch whose bucket can't split over the data axis:
    # pad slots burn device time on every window (batch=6 over
    # data:4 — and the implied bucket list is just (6,))
    (f"appsrc caps={GOOD_CAPS} ! queue ! tensor_filter "
     "framework=jax-xla model=/nonexistent/model.pkl mesh=data:4 "
     "batch=6 ! tensor_sink", {"NNS509"}),
    # pool-level NNS509: a share-model pool whose cross-pipeline
    # window can't split over the mesh data axis pads on EVERY
    # coalesced window, for every sharer at once
    (f"appsrc caps={GOOD_CAPS} ! queue ! tensor_filter "
     "framework=jax-xla model=/nonexistent/model.pkl mesh=data:4 "
     "batch=6 share-model=true ! tensor_sink", {"NNS512"}),
    # lifecycle: canary grammar must be '<version>:1/N' (2/3 is not a
    # 1-in-N split)
    (f"appsrc caps={GOOD_CAPS} ! queue ! tensor_filter "
     "framework=jax-xla model=/nonexistent/model.pkl batch=4 "
     "share-model=true canary=next:2/3 ! tensor_sink", {"NNS513"}),
    # lifecycle: canary without share-model — one private stream has
    # nothing to split 1-in-N
    (f"appsrc caps={GOOD_CAPS} ! queue ! tensor_filter "
     "framework=jax-xla model=/nonexistent/model.pkl "
     "canary=1/4 ! tensor_sink", {"NNS513"}),
    # residency fence: a host-only converter stage between two
    # device-resident jax-xla filters forces a d2h+h2d pair per frame
    (f"appsrc caps={GOOD_CAPS} ! tensor_filter "
     "framework=jax-xla model=/nonexistent/model.pkl ! "
     "tensor_converter ! tensor_filter name=f2 framework=jax-xla "
     "model=/nonexistent/model.pkl ! tensor_sink", {"NNS514"}),
    # residency fence through transparent plumbing: the queue/tee hop
    # does not hide the host-only python3 filter from the walk
    (f"appsrc caps={GOOD_CAPS} ! tensor_transform mode=typecast "
     "option=float32 ! queue ! tensor_filter framework=python3 "
     "model=cb ! queue ! tensor_filter name=f2 framework=jax-xla "
     "model=/nonexistent/model.pkl ! tensor_sink", {"NNS514"}),
    # fusion blocked by an interposed queue between the transform and
    # an UNBATCHED filter (batch>1 would make the queue load-bearing
    # per NNS501 — see the negative tests)
    (f"appsrc caps={GOOD_CAPS} ! tensor_transform mode=typecast "
     "option=float32 ! queue ! tensor_filter framework=jax-xla "
     "model=/nonexistent/model.pkl ! tensor_decoder "
     "mode=bounding_boxes option1=mobilenet-ssd-postprocess "
     "option7=device ! tensor_sink", {"NNS515"}),
    # fusion blocked by share-model: the pooled instance serves many
    # pipelines, so this pipeline's stages can't bake into it
    (f"appsrc caps={GOOD_CAPS} ! tensor_transform mode=typecast "
     "option=float32 ! tensor_filter framework=jax-xla "
     "model=/nonexistent/model.pkl share-model=true ! tensor_decoder "
     "mode=bounding_boxes option1=mobilenet-ssd-postprocess "
     "option7=device ! tensor_sink", {"NNS515"}),
    # fusion left on the table: the decoder scheme HAS a device render
    # program but option7=device is not set, so the segment pays one
    # dispatch per stage instead of one total
    (f"appsrc caps={GOOD_CAPS} ! tensor_transform mode=typecast "
     "option=float32 ! tensor_filter framework=jax-xla "
     "model=/nonexistent/model.pkl ! tensor_decoder "
     "mode=bounding_boxes option1=mobilenet-ssd-postprocess ! "
     "tensor_sink", {"NNS515"}),
    # pipeline split: two declared stage subsets sharing chips —
    # the stages contend and per-stage attribution is unreliable
    (f"appsrc caps={GOOD_CAPS} ! queue ! tensor_filter name=f1 "
     "framework=jax-xla model=/nonexistent/model.pkl mesh=data:4 "
     "devices=0-3 batch=4 share-model=true ! tensor_sink "
     f"appsrc name=b caps={GOOD_CAPS} ! queue ! tensor_filter name=f2 "
     "framework=jax-xla model=/nonexistent/model.pkl mesh=data:4 "
     "devices=2-5 batch=4 share-model=true ! tensor_sink name=s2",
     {"NNS516"}),
    # cascade offload branch reaching the heavy stage only through a
    # host-only converter (+ the heavy stage missing share-model)
    (f"appsrc caps={GOOD_CAPS} ! tensor_if name=i operator=ge "
     "supplied-value=1 offload=then "
     "i.src_then ! tensor_converter ! tensor_filter name=hv "
     "framework=jax-xla model=/nonexistent/model.pkl mesh=data:4 "
     "devices=4-7 ! tensor_sink "
     "i.src_else ! tensor_sink name=s2", {"NNS516"}),
    # offload grammar: the branch name must be then/else
    (f"appsrc caps={GOOD_CAPS} ! tensor_if name=i offload=both ! "
     "tensor_sink i.src_else ! tensor_sink name=s2", {"NNS516"}),
    # tenancy: tenant= on a private filter — attribution splits the
    # SHARED pool's device-seconds, so nothing is ever billed here
    (f"appsrc caps={GOOD_CAPS} ! queue ! tensor_filter "
     "framework=jax-xla model=/nonexistent/model.pkl tenant=alpha ! "
     "tensor_sink", {"NNS517"}),
]


@pytest.mark.parametrize("desc,expected",
                         BAD_CORPUS, ids=[c for _, e in BAD_CORPUS
                                          for c in [sorted(e)[0]]])
def test_bad_corpus_reports_expected_codes(desc, expected):
    diags, _ = analyze_description(desc)
    assert expected <= codes(diags), \
        f"wanted {expected}, got {[str(d) for d in diags]}"


# -- source lint snippets: one per NNS3xx/NNS4xx code ------------------------

LINT_SNIPPETS = [
    ("""
import time

class P:
    def __init__(self, bus):
        bus.add_watch(self._watch)

    def _watch(self, msg):
        time.sleep(1)
""", {"NNS301"}),
    ("""
class E:
    def emit(self, msg):
        with self._lock:
            self.bus.post(msg)
""", {"NNS302"}),
    ("""
class E:
    def stop(self):
        with self._lock:
            self._thread.join(timeout=5)
""", {"NNS303"}),
    ("""
from nnstreamer_tpu.runtime.registry import register_element

@register_element("padless")
class Padless:
    def chain(self, pad, buf):
        pass
""", {"NNS401"}),
    ("""
import jax
import numpy as np

@jax.jit
def hot(x):
    return np.sum(x, axis=-1)
""", {"NNS402"}),
    ("""
def f():
    try:
        risky()
    except:
        pass
""", {"NNS403"}),
]


@pytest.mark.parametrize("src,expected", LINT_SNIPPETS,
                         ids=[sorted(e)[0] for _, e in LINT_SNIPPETS])
def test_lint_snippets(src, expected):
    assert expected <= codes(lint_source(src))


# -- NNS508 corpus: only fires while obs is globally disabled, so it
# -- runs under its own env-scoped test rather than in BAD_CORPUS ------------

OBS_DISABLED_CORPUS = [
    # stat-sample-interval-ms / latency=1 / latency-report silently
    # no-op under the kill switch (no blocking sample is ever taken)
    (f"appsrc caps={GOOD_CAPS} ! tensor_filter framework=jax-xla "
     "model=/nonexistent/model.pkl stat-sample-interval-ms=100 ! "
     "tensor_sink", {"NNS508"}),
    (f"appsrc caps={GOOD_CAPS} ! tensor_filter framework=jax-xla "
     "model=/nonexistent/model.pkl latency=1 latency-report=true ! "
     "tensor_sink", {"NNS508"}),
    # a traced query client cannot propagate contexts while the tracer
    # can never attach
    (f"appsrc caps={GOOD_CAPS} ! tensor_query_client caps={GOOD_CAPS} "
     "dest-host=198.51.100.7 dest-port=5432 ! tensor_sink",
     {"NNS508"}),
]


@pytest.mark.parametrize("desc,expected", OBS_DISABLED_CORPUS,
                         ids=["stat-interval", "latency", "trace"])
def test_nns508_fires_while_obs_disabled(desc, expected, monkeypatch):
    monkeypatch.setenv("NNS_TPU_OBS_DISABLE", "1")
    diags, _ = analyze_description(desc)
    assert expected <= codes(diags), [str(d) for d in diags]
    d = [x for x in diags if x.code == "NNS508"][0]
    assert d.severity == Severity.WARNING
    assert "NNS_TPU_OBS_DISABLE" in d.message


def test_nns508_negatives(monkeypatch):
    """No NNS508 with obs enabled (whatever the props), and none under
    the kill switch when no obs prop is set."""
    desc = (f"appsrc caps={GOOD_CAPS} ! tensor_filter framework=jax-xla "
            "model=/nonexistent/model.pkl stat-sample-interval-ms=100 ! "
            "tensor_sink")
    monkeypatch.delenv("NNS_TPU_OBS_DISABLE", raising=False)
    diags, _ = analyze_description(desc)
    assert "NNS508" not in codes(diags)
    monkeypatch.setenv("NNS_TPU_OBS_DISABLE", "1")
    plain = (f"appsrc caps={GOOD_CAPS} ! tensor_filter framework=jax-xla "
             "model=/nonexistent/model.pkl ! tensor_sink")
    diags, _ = analyze_description(plain)
    assert "NNS508" not in codes(diags)
    # trace=false on the query client silences the trace variant too
    qc = (f"appsrc caps={GOOD_CAPS} ! tensor_query_client "
          f"caps={GOOD_CAPS} dest-host=198.51.100.7 dest-port=5432 "
          "trace=false ! tensor_sink")
    diags, _ = analyze_description(qc)
    assert "NNS508" not in codes(diags)


# -- NNS510 corpus: watch-rules file validation (file-shaped, not
# -- pipeline-shaped, so it runs under its own tmp-file tests) ---------------

WATCH_RULES_CORPUS = [
    # a family the registry never exports: the rule can never fire
    ({"rule": [{"name": "r", "kind": "threshold",
                "metric": "nns_never_ever_total"}]}, {"NNS510"}),
    # malformed grammar: unknown rule kind
    ({"rule": [{"name": "r", "kind": "frobnicate",
                "metric": "nns_mfu"}]}, {"NNS510"}),
    # a signal the family's kind cannot produce (rate of a gauge)
    ({"rule": [{"name": "r", "kind": "threshold", "metric": "nns_mfu",
                "signal": "rate"}]}, {"NNS510"}),
    # burn on a gauge: neither histogram nor counter-ratio mode binds
    ({"rule": [{"name": "r", "kind": "slo_burn",
                "metric": "nns_queue_depth"}]}, {"NNS510"}),
    # [store] sizing that parses but cannot work: rings too short for
    # any quantile window — same file, still NNS510
    ({"rule": [{"name": "r", "kind": "threshold",
                "metric": "nns_mfu"}],
      "store": {"ring_points": 4}}, {"NNS510"}),
    # forecast without a horizon: nothing to predict across (the live
    # watchdog refuses the set; the lint catches it at review time)
    ({"rule": [{"name": "fc", "kind": "forecast",
                "metric": "nns_queue_depth", "op": ">=",
                "value": 100}]}, {"NNS517"}),
    # a horizon shorter than 3 sampler intervals: too little lookahead
    # to beat the reactive rules
    ({"rule": [{"name": "fc", "kind": "forecast",
                "metric": "nns_queue_depth", "op": ">=", "value": 100,
                "horizon": "1s"}]}, {"NNS517"}),
    # forecast bound to a histogram family: windowed quantiles
    # re-derive each tick — no single series to fit a trend through
    ({"rule": [{"name": "fc", "kind": "forecast",
                "metric": "nns_admission_latency_seconds", "op": ">=",
                "value": 0.5, "horizon": "30s"}]}, {"NNS517"}),
]


@pytest.mark.parametrize("doc,expected", WATCH_RULES_CORPUS,
                         ids=["unknown-family", "bad-grammar",
                              "bad-signal", "burn-gauge", "store-ring",
                              "fc-no-horizon", "fc-short-horizon",
                              "fc-histogram"])
def test_nns510_watch_rules_corpus(doc, expected, tmp_path):
    from nnstreamer_tpu.analyze.watchrules import check_watch_rules

    path = tmp_path / "rules.json"
    path.write_text(json.dumps(doc))
    diags = check_watch_rules(str(path))
    assert expected <= codes(diags), [str(d) for d in diags]
    assert all(d.severity == Severity.WARNING for d in diags)


def test_nns510_negatives(tmp_path, monkeypatch):
    """A well-formed rules file over exported families is clean; the
    env-var form resolves NNS_TPU_WATCH_RULES; unparseable JSON and an
    unreadable path each yield exactly one NNS510."""
    from nnstreamer_tpu.analyze.watchrules import check_watch_rules

    good = tmp_path / "good.json"
    good.write_text(json.dumps({"rule": [
        {"name": "brk", "kind": "threshold",
         "metric": "nns_edge_breaker_state", "op": ">=",
         "value": "open", "for": "10s", "severity": "critical"}]}))
    assert check_watch_rules(str(good)) == []
    # the default pack itself must validate clean through this path
    monkeypatch.setenv("NNS_TPU_WATCH_RULES", str(good))
    assert check_watch_rules(None) == []
    monkeypatch.delenv("NNS_TPU_WATCH_RULES")
    assert [d.code for d in check_watch_rules(None)] == ["NNS510"]
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    diags = check_watch_rules(str(bad))
    assert [d.code for d in diags] == ["NNS510"]
    assert "malformed" in diags[0].message
    assert [d.code for d in check_watch_rules(
        str(tmp_path / "missing.json"))] == ["NNS510"]


def test_nns510_cli_flag(tmp_path):
    from nnstreamer_tpu.analyze.cli import main as cli_main

    path = tmp_path / "rules.json"
    path.write_text(json.dumps({"rule": [
        {"name": "r", "kind": "threshold",
         "metric": "nns_never_ever_total"}]}))
    buf = io.StringIO()
    rc = cli_main(["--watch-rules", str(path)], out=buf)
    assert rc == 0 and "NNS510" in buf.getvalue()
    assert cli_main(["--watch-rules", str(path), "--strict"],
                    out=io.StringIO()) == 1
    doc = io.StringIO()
    rc = cli_main(["--watch-rules", str(path), "--json"], out=doc)
    parsed = json.loads(doc.getvalue())
    assert parsed["summary"]["warning"] == 1


def test_nns517_negative_cases(tmp_path):
    """tenant= WITH share-model is the supported shape (no NNS517);
    and a forecast with an ordered op, a sane horizon and a counter/
    gauge family lints clean."""
    desc = (f"appsrc caps={GOOD_CAPS} ! queue ! tensor_filter "
            "framework=jax-xla model=/nonexistent/model.pkl "
            "batch=4 share-model=true tenant=alpha ! tensor_sink")
    diags, _ = analyze_description(desc)
    assert "NNS517" not in codes(diags)
    from nnstreamer_tpu.analyze.watchrules import check_watch_rules

    good = tmp_path / "rules.json"
    good.write_text(json.dumps({"rule": [
        {"name": "surge", "kind": "forecast",
         "metric": "nns_pool_frames_total", "op": ">=",
         "value": 1000, "horizon": "30s", "for": "2s"}]}))
    assert check_watch_rules(str(good)) == []
    # the horizon check scales with the sampler interval it is told
    assert [d.code for d in check_watch_rules(
        str(good), interval_s=20.0)] == ["NNS517"]


# -- NNS518 corpus: host-profiler environment (env-shaped — the lint
# -- reads the same vars the runtime hook does) -------------------------------

PROF_ENV_CORPUS = [
    # profiler armed under the obs kill switch: strictly inert — a
    # silent no-op, the NNS508 family
    ({"NNS_TPU_PROF": "50", "NNS_TPU_OBS_DISABLE": "1"}, {"NNS518"}),
    ({"NNS_TPU_PROF_DEEP_DIR": "/tmp", "NNS_TPU_OBS_DISABLE": "1"},
     {"NNS518"}),
    # an unparsable rate: the profiler will not start
    ({"NNS_TPU_PROF": "fast"}, {"NNS518"}),
    # a rate past the low-overhead envelope
    ({"NNS_TPU_PROF": "1000"}, {"NNS518"}),
]


@pytest.mark.parametrize("env,expected", PROF_ENV_CORPUS,
                         ids=["obs-disabled", "deep-obs-disabled",
                              "bad-hz", "high-hz"])
def test_nns518_prof_env_corpus(env, expected, monkeypatch):
    from nnstreamer_tpu.analyze.watchrules import prof_env_problems

    for var in ("NNS_TPU_PROF", "NNS_TPU_PROF_DEEP_DIR",
                "NNS_TPU_OBS_DISABLE"):
        monkeypatch.delenv(var, raising=False)
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    diags = prof_env_problems()
    assert expected <= codes(diags), [str(d) for d in diags]
    assert all(d.severity == Severity.WARNING for d in diags)


def test_nns518_deep_vs_for_window(tmp_path, monkeypatch):
    """A deep-profile episode longer than a rule's for= window records
    recovery, not the incident — flagged per rule; shorter episodes
    and an unarmed deep profiler stay quiet."""
    from nnstreamer_tpu.analyze.watchrules import check_watch_rules

    rules = tmp_path / "rules.json"
    rules.write_text(json.dumps({"rule": [
        {"name": "qfull", "kind": "threshold",
         "metric": "nns_pool_pending", "op": ">=", "value": 8,
         "for": "1s"}]}))
    monkeypatch.setenv("NNS_TPU_PROF_DEEP_DIR", str(tmp_path))
    monkeypatch.setenv("NNS_TPU_PROF_DEEP_SECONDS", "5")
    diags = check_watch_rules(str(rules))
    assert codes(diags) == {"NNS518"}, [str(d) for d in diags]
    assert "outlasts" in diags[0].message and diags[0].pad == "qfull"
    monkeypatch.setenv("NNS_TPU_PROF_DEEP_SECONDS", "0.5")
    assert check_watch_rules(str(rules)) == []
    monkeypatch.delenv("NNS_TPU_PROF_DEEP_SECONDS")
    # unset seconds falls back to the 2.0 s default (> 1 s window)
    assert codes(check_watch_rules(str(rules))) == {"NNS518"}
    monkeypatch.delenv("NNS_TPU_PROF_DEEP_DIR")
    assert check_watch_rules(str(rules)) == []


def test_nns518_negatives_and_cli_target(monkeypatch):
    """A sane profiler env is clean; with no profiler env at all the
    prof-env target does not even appear (default output stays
    byte-stable); with one set, the CLI gathers it."""
    from nnstreamer_tpu.analyze.cli import main as cli_main
    from nnstreamer_tpu.analyze.watchrules import prof_env_problems

    for var in ("NNS_TPU_PROF", "NNS_TPU_PROF_DEEP_DIR",
                "NNS_TPU_OBS_DISABLE"):
        monkeypatch.delenv(var, raising=False)
    assert prof_env_problems() == []
    monkeypatch.setenv("NNS_TPU_PROF", "47")
    assert prof_env_problems() == []
    buf = io.StringIO()
    cli_main([f"appsrc caps={GOOD_CAPS} ! tensor_sink"], out=buf)
    assert "prof-env" in buf.getvalue()
    monkeypatch.delenv("NNS_TPU_PROF")
    buf = io.StringIO()
    cli_main([f"appsrc caps={GOOD_CAPS} ! tensor_sink"], out=buf)
    assert "prof-env" not in buf.getvalue()
    monkeypatch.setenv("NNS_TPU_PROF", "999")
    assert cli_main([f"appsrc caps={GOOD_CAPS} ! tensor_sink",
                     "--strict"], out=io.StringIO()) == 1


# -- NNS511 corpus: controller-playbook file validation (file-shaped,
# -- like the NNS510 corpus above) --------------------------------------------

CTL_PLAYBOOK_CORPUS = [
    # an actuator nothing exports: the playbook can never act
    ({"playbook": [{"name": "p", "rule": "slo-burn", "kind": "pool",
                    "actuator": "warp-drive", "value": 1}]},
     {"NNS511"}),
    # malformed grammar: unknown target kind
    ({"playbook": [{"name": "p", "rule": "slo-burn",
                    "kind": "frobnicate", "actuator": "ramp-start",
                    "value": 1}]}, {"NNS511"}),
    # malformed grammar: a set/step playbook with no explicit value
    # (would silently actuate the 0.0 default — e.g. PAUSE coalescing)
    ({"playbook": [{"name": "p", "rule": "slo-burn", "kind": "pool",
                    "actuator": "coalescing"}]}, {"NNS511"}),
    # a rule the active rule set never evaluates
    ({"playbook": [{"name": "p", "rule": "no-such-rule",
                    "kind": "pool", "actuator": "ramp-start",
                    "value": 0.5}]}, {"NNS511"}),
    # a double back-out: action=revert plus on_resolve=revert
    ({"playbook": [{"name": "p", "rule": "slo-burn", "kind": "pool",
                    "actuator": "max-batch", "action": "revert",
                    "on_resolve": "revert"}]}, {"NNS511"}),
]


@pytest.mark.parametrize("doc,expected", CTL_PLAYBOOK_CORPUS,
                         ids=["unknown-actuator", "bad-grammar",
                              "missing-value", "unknown-rule",
                              "double-revert"])
def test_nns511_playbook_corpus(doc, expected, tmp_path):
    from nnstreamer_tpu.analyze.ctlplaybooks import check_playbooks

    path = tmp_path / "playbooks.json"
    path.write_text(json.dumps(doc))
    diags = check_playbooks(str(path))
    assert expected <= codes(diags), [str(d) for d in diags]
    assert all(d.severity == Severity.WARNING for d in diags)


def test_nns511_negatives(tmp_path, monkeypatch):
    """The shipped default pack round-trips clean; the env-var form
    resolves NNS_TPU_CTL_PLAYBOOKS; unparseable JSON and an unreadable
    path each yield exactly one NNS511."""
    import dataclasses

    from nnstreamer_tpu.analyze.ctlplaybooks import check_playbooks
    from nnstreamer_tpu.obs.control import default_playbooks

    good = tmp_path / "good.json"
    good.write_text(json.dumps({"playbook": [
        {k: v for k, v in dataclasses.asdict(pb).items() if v != ""}
        for pb in default_playbooks()]}))
    assert check_playbooks(str(good)) == []
    monkeypatch.setenv("NNS_TPU_CTL_PLAYBOOKS", str(good))
    assert check_playbooks(None) == []
    monkeypatch.delenv("NNS_TPU_CTL_PLAYBOOKS")
    assert [d.code for d in check_playbooks(None)] == ["NNS511"]
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    diags = check_playbooks(str(bad))
    assert [d.code for d in diags] == ["NNS511"]
    assert "malformed" in diags[0].message
    assert [d.code for d in check_playbooks(
        str(tmp_path / "missing.json"))] == ["NNS511"]


def test_nns511_target_exists_check(tmp_path):
    """A concrete pool target is checked against the SAME invocation's
    analyzed pipelines: matching share-model pool → clean, no match →
    NNS511; with no pipelines analyzed the check stands aside."""
    from nnstreamer_tpu.analyze.cli import main as cli_main

    path = tmp_path / "pb.json"
    path.write_text(json.dumps({"playbook": [
        {"name": "p", "rule": "slo-burn", "kind": "pool",
         "actuator": "ramp-start", "target": "jax-xla:m1",
         "value": 0.5}]}))
    desc = ("appsrc name=s ! tensor_filter framework=jax-xla "
            "model=m1 share-model=true ! appsink")
    buf = io.StringIO()
    rc = cli_main(["--ctl-playbooks", str(path), desc], out=buf)
    assert "NNS511" not in buf.getvalue(), buf.getvalue()
    path2 = tmp_path / "pb2.json"
    path2.write_text(json.dumps({"playbook": [
        {"name": "p", "rule": "slo-burn", "kind": "pool",
         "actuator": "ramp-start", "target": "jax-xla:other",
         "value": 0.5}]}))
    buf = io.StringIO()
    cli_main(["--ctl-playbooks", str(path2), desc], out=buf)
    assert "NNS511" in buf.getvalue()
    assert "matches no share-model pool" in buf.getvalue()
    # no pipelines in the run: unknowable, not wrong
    buf = io.StringIO()
    cli_main(["--ctl-playbooks", str(path2)], out=buf)
    assert "NNS511" not in buf.getvalue()


def test_nns511_cli_flag(tmp_path):
    from nnstreamer_tpu.analyze.cli import main as cli_main

    path = tmp_path / "pb.json"
    path.write_text(json.dumps({"playbook": [
        {"name": "p", "rule": "slo-burn", "kind": "pool",
         "actuator": "warp-drive", "value": 1}]}))
    buf = io.StringIO()
    rc = cli_main(["--ctl-playbooks", str(path)], out=buf)
    assert rc == 0 and "NNS511" in buf.getvalue()
    assert cli_main(["--ctl-playbooks", str(path), "--strict"],
                    out=io.StringIO()) == 1
    doc = io.StringIO()
    cli_main(["--ctl-playbooks", str(path), "--json"], out=doc)
    parsed = json.loads(doc.getvalue())
    assert parsed["summary"]["warning"] == 1


def test_nns511_binds_rules_from_same_invocation(tmp_path):
    """--watch-rules FILE in the same run supplies the rule-name set
    NNS511 binds playbooks against (a custom rule pack must not warn)."""
    from nnstreamer_tpu.analyze.cli import main as cli_main

    rules = tmp_path / "rules.json"
    rules.write_text(json.dumps({"rule": [
        {"name": "my-own-rule", "kind": "threshold",
         "metric": "nns_pool_pending", "op": ">=", "value": 8}]}))
    pb = tmp_path / "pb.json"
    pb.write_text(json.dumps({"playbook": [
        {"name": "p", "rule": "my-own-rule", "kind": "pool",
         "actuator": "coalescing", "value": 1}]}))
    buf = io.StringIO()
    cli_main(["--watch-rules", str(rules),
              "--ctl-playbooks", str(pb)], out=buf)
    assert "NNS511" not in buf.getvalue(), buf.getvalue()


def test_every_code_has_coverage():
    """The catalog is fully exercised: every stable code appears in the
    bad corpus, the lint snippets, the obs-disabled corpus, the
    watch-rules / ctl-playbook corpora above, or the NNS6xx concurrency
    corpus (tests/test_concurrency_lint.py)."""
    from test_concurrency_lint import CONCURRENCY_CORPUS

    covered = set()
    for _, expected in BAD_CORPUS:
        covered |= expected
    for _, expected in LINT_SNIPPETS:
        covered |= expected
    for _, expected in OBS_DISABLED_CORPUS:
        covered |= expected
    for _, expected in WATCH_RULES_CORPUS:
        covered |= expected
    for _, expected in PROF_ENV_CORPUS:
        covered |= expected
    for _, expected in CTL_PLAYBOOK_CORPUS:
        covered |= expected
    for _, expected in CONCURRENCY_CORPUS:
        covered |= expected
    assert covered == set(CODES)


def test_nns514_negative_cases():
    """No sandwich, no warning: a host stage at the head (nothing
    device upstream) or the tail (nothing device downstream) of the
    chain is the normal ingest/render pattern, not a fence; and an
    all-device chain has nothing host-only to flag."""
    head = (f"appsrc caps={GOOD_CAPS} ! tensor_converter ! "
            "tensor_filter framework=jax-xla "
            "model=/nonexistent/model.pkl ! tensor_sink")
    diags, _ = analyze_description(head)
    assert "NNS514" not in codes(diags)
    tail = (f"appsrc caps={GOOD_CAPS} ! tensor_filter framework=jax-xla "
            "model=/nonexistent/model.pkl ! tensor_decoder "
            "mode=image_labeling ! tensor_sink")
    diags, _ = analyze_description(tail)
    assert "NNS514" not in codes(diags)
    all_dev = (f"appsrc caps={GOOD_CAPS} ! tensor_transform "
               "mode=typecast option=float32 ! tensor_filter "
               "framework=jax-xla model=/nonexistent/model.pkl ! "
               "tensor_sink")
    diags, _ = analyze_description(all_dev)
    assert "NNS514" not in codes(diags)
    # positive case renders with element location + hint
    fence = (f"appsrc caps={GOOD_CAPS} ! tensor_filter "
             "framework=jax-xla model=/nonexistent/model.pkl ! "
             "tensor_converter name=fence ! tensor_filter name=f2 "
             "framework=jax-xla model=/nonexistent/model.pkl ! "
             "tensor_sink")
    diags, _ = analyze_description(fence)
    d = [x for x in diags if x.code == "NNS514"]
    assert len(d) == 1 and d[0].element == "fence" and d[0].hint


def test_nns515_negative_cases():
    """NNS515 fires only on a full transform→filter→decoder segment
    broken by a BREAKABLE cause — everything else stays quiet."""
    # the fusable segment itself: direct links, device decoder scheme
    fused = (f"appsrc caps={GOOD_CAPS} ! tensor_transform "
             "mode=typecast option=float32 ! tensor_filter "
             "framework=jax-xla model=/nonexistent/model.pkl ! "
             "tensor_decoder mode=bounding_boxes "
             "option1=mobilenet-ssd-postprocess option7=device ! "
             "tensor_sink")
    diags, _ = analyze_description(fused)
    assert "NNS515" not in codes(diags)
    # no decoder downstream: a transform→filter prologue segment is
    # handled (or not) by fuse_transform_filter; not this lint's shape
    no_dec = (f"appsrc caps={GOOD_CAPS} ! tensor_transform "
              "mode=typecast option=float32 ! queue ! tensor_filter "
              "framework=jax-xla model=/nonexistent/model.pkl ! "
              "tensor_sink")
    diags, _ = analyze_description(no_dec)
    assert "NNS515" not in codes(diags)
    # batch>1: the upstream queue is LOAD-BEARING (NNS501 requires it)
    # — warning would tell the user to break the batching topology
    batched = (f"appsrc caps={GOOD_CAPS} ! tensor_transform "
               "mode=typecast option=float32 ! queue ! tensor_filter "
               "framework=jax-xla model=/nonexistent/model.pkl "
               "batch=4 ! tensor_decoder mode=bounding_boxes "
               "option1=mobilenet-ssd-postprocess option7=device ! "
               "tensor_sink")
    diags, _ = analyze_description(batched)
    assert "NNS515" not in codes(diags)
    # a decoder mode with no device render program could never fuse —
    # nothing breakable to report
    labeling = (f"appsrc caps={GOOD_CAPS} ! tensor_transform "
                "mode=typecast option=float32 ! tensor_filter "
                "framework=jax-xla model=/nonexistent/model.pkl ! "
                "tensor_decoder mode=image_labeling ! tensor_sink")
    diags, _ = analyze_description(labeling)
    assert "NNS515" not in codes(diags)
    # non-jax framework: the fusion pass only captures jax-xla filters
    other_fw = (f"appsrc caps={GOOD_CAPS} ! tensor_transform "
                "mode=typecast option=float32 ! tensor_filter "
                "framework=python3 model=cb share-model=true ! "
                "tensor_decoder mode=bounding_boxes "
                "option1=mobilenet-ssd-postprocess option7=device ! "
                "tensor_sink")
    diags, _ = analyze_description(other_fw)
    assert "NNS515" not in codes(diags)
    # positive case names the whole segment and carries a hint
    tee = (f"appsrc caps={GOOD_CAPS} ! tensor_transform "
           "mode=typecast option=float32 ! tensor_filter name=net "
           "framework=jax-xla model=/nonexistent/model.pkl ! tee "
           "name=t t. ! queue ! tensor_decoder mode=bounding_boxes "
           "option1=mobilenet-ssd-postprocess option7=device ! "
           "tensor_sink t. ! queue ! tensor_sink name=s2")
    diags, _ = analyze_description(tee)
    d = [x for x in diags if x.code == "NNS515"]
    assert len(d) == 1 and d[0].element == "net" and d[0].hint
    assert "queue/tee" in d[0].message


def test_nns516_faces():
    """Each NNS516 face fires precisely: subset overlap, inventory
    excess (jax already up in-proc), the host-interposed offload
    branch, the heavy stage missing share-model, and the offload
    grammar check."""
    import jax

    n_devs = len(jax.devices())  # conftest pins 8 virtual chips
    overlap = (f"appsrc caps={GOOD_CAPS} ! queue ! tensor_filter "
               "name=f1 framework=jax-xla "
               "model=/nonexistent/model.pkl mesh=data:4 devices=0-3 "
               "batch=4 share-model=true ! tensor_sink "
               f"appsrc name=b caps={GOOD_CAPS} ! queue ! "
               "tensor_filter name=f2 framework=jax-xla "
               "model=/nonexistent/model.pkl mesh=data:4 devices=2-5 "
               "batch=4 share-model=true ! tensor_sink name=s2")
    diags, _ = analyze_description(overlap)
    d = [x for x in diags if x.code == "NNS516"]
    assert len(d) == 1 and "overlap" in d[0].message and d[0].hint
    assert "2,3" in d[0].message  # names the shared chips

    over = (f"appsrc caps={GOOD_CAPS} ! queue ! tensor_filter name=f1 "
            "framework=jax-xla model=/nonexistent/model.pkl "
            f"mesh=data:4 devices=0-{n_devs + 3} batch=4 "
            "share-model=true ! tensor_sink")
    diags, _ = analyze_description(over)
    d = [x for x in diags if x.code == "NNS516"]
    assert len(d) == 1 and "inventory" in d[0].message

    fence = (f"appsrc caps={GOOD_CAPS} ! tensor_if name=i operator=ge "
             "supplied-value=1 offload=then "
             "i.src_then ! tensor_converter ! tensor_filter name=hv "
             "framework=jax-xla model=/nonexistent/model.pkl "
             "mesh=data:4 devices=4-7 ! tensor_sink "
             "i.src_else ! tensor_sink name=s2")
    diags, _ = analyze_description(fence)
    d = [x for x in diags if x.code == "NNS516"]
    assert len(d) == 2
    host = [x for x in d if "host-only" in x.message]
    share = [x for x in d if "share-model" in x.message]
    assert len(host) == 1 and host[0].element == "i"
    assert len(share) == 1 and share[0].element == "hv"

    grammar = (f"appsrc caps={GOOD_CAPS} ! tensor_if name=i "
               "offload=both ! tensor_sink "
               "i.src_else ! tensor_sink name=s2")
    diags, _ = analyze_description(grammar)
    d = [x for x in diags if x.code == "NNS516"]
    assert len(d) == 1 and "offload" in d[0].message
    assert d[0].element == "i"


def test_nns516_negative_cases():
    """The WELL-FORMED cascade is quiet: disjoint subsets, the offload
    branch through transparent plumbing only, share-model=true on the
    heavy stage; a single staged filter (no second subset) and an
    un-staged tensor_if are not split topologies at all."""
    clean = (f"appsrc caps={GOOD_CAPS} ! queue ! tensor_filter "
             "name=det framework=jax-xla "
             "model=/nonexistent/model.pkl mesh=data:4 devices=0-3 "
             "batch=4 share-model=true ! tensor_if name=r operator=ge "
             "supplied-value=3 offload=then "
             "r.src_then ! queue ! tensor_filter name=cls "
             "framework=jax-xla model=/nonexistent/model.pkl "
             "mesh=data:4 devices=4-7 batch=4 share-model=true ! "
             "tensor_sink "
             "r.src_else ! tensor_sink name=keep")
    diags, _ = analyze_description(clean)
    assert "NNS516" not in codes(diags)
    # one declared stage alone: nothing to overlap with
    solo = (f"appsrc caps={GOOD_CAPS} ! queue ! tensor_filter "
            "framework=jax-xla model=/nonexistent/model.pkl "
            "mesh=data:4 devices=0-3 batch=4 share-model=true ! "
            "tensor_sink")
    diags, _ = analyze_description(solo)
    assert "NNS516" not in codes(diags)
    # identical subsets on purpose (same pool, two sharers) are NOT an
    # overlap — only partially-shared subsets contend
    same = (f"appsrc caps={GOOD_CAPS} ! queue ! tensor_filter name=f1 "
            "framework=jax-xla model=/nonexistent/model.pkl "
            "mesh=data:4 devices=0-3 batch=4 share-model=true ! "
            "tensor_sink "
            f"appsrc name=b caps={GOOD_CAPS} ! queue ! tensor_filter "
            "name=f2 framework=jax-xla model=/nonexistent/model.pkl "
            "mesh=data:4 devices=0-3 batch=4 share-model=true ! "
            "tensor_sink name=s2")
    diags, _ = analyze_description(same)
    assert "NNS516" not in codes(diags)
    # tensor_if without offload= is plain branching, not a cascade
    plain = (f"appsrc caps={GOOD_CAPS} ! tensor_if name=i operator=ge "
             "supplied-value=1 ! tensor_converter ! tensor_filter "
             "framework=jax-xla model=/nonexistent/model.pkl "
             "mesh=data:4 devices=4-7 share-model=true ! tensor_sink "
             "i.src_else ! tensor_sink name=s2")
    diags, _ = analyze_description(plain)
    assert "NNS516" not in codes(diags)


def test_nns506_suppressed_by_ntp_inproc_or_trace_off():
    """NNS506 is about tracing a cross-host link on an unanchored
    clock: configuring ntp-servers, staying in-process, or disabling
    trace propagation each silence it."""
    base = (f"appsrc caps={GOOD_CAPS} ! tensor_query_client "
            f"caps={GOOD_CAPS} dest-host=198.51.100.7 dest-port=5432")
    for tail in (" ntp-servers=198.51.100.9 ! tensor_sink",
                 " trace=false ! tensor_sink"):
        diags, _ = analyze_description(base + tail)
        assert "NNS506" not in codes(diags), tail
    inproc, _ = analyze_description(
        f"appsrc caps={GOOD_CAPS} ! tensor_query_client "
        f"caps={GOOD_CAPS} connect-type=inproc ! tensor_sink")
    assert "NNS506" not in codes(inproc)
    # and the positive case renders with the element location + hint
    diags, _ = analyze_description(base + " ! tensor_sink")
    d = [x for x in diags if x.code == "NNS506"][0]
    assert d.severity == Severity.INFO
    assert "ntp-servers" in (d.hint or "")


def test_nns513_updatable_without_reload_support():
    """is-updatable on a framework with neither prepare_swap nor a
    RELOAD_MODEL handler: the reload event would raise instead of
    swapping — flagged statically; jax-xla (which implements
    prepare_swap) stays clean."""
    diags, _ = analyze_description(
        f"appsrc caps={GOOD_CAPS} ! tensor_filter "
        "framework=custom-easy model=nope is-updatable=true ! "
        "tensor_sink")
    d = [x for x in diags if x.code == "NNS513"]
    assert d and "prepare_swap" in d[0].message
    clean, _ = analyze_description(
        f"appsrc caps={GOOD_CAPS} ! tensor_filter framework=jax-xla "
        "model=/nonexistent/model.pkl is-updatable=true ! tensor_sink")
    assert "NNS513" not in codes(clean)


def test_nns513_compile_cache_dir(monkeypatch, tmp_path):
    """NNS_TPU_COMPILE_CACHE_DIR pointing nowhere writable silently
    disables the persistent AOT cache — NNS513 warns; a writable dir
    is clean, and pipelines without filters don't care."""
    desc = (f"appsrc caps={GOOD_CAPS} ! tensor_filter "
            "framework=jax-xla model=/nonexistent/model.pkl ! "
            "tensor_sink")
    monkeypatch.setenv("NNS_TPU_COMPILE_CACHE_DIR",
                       str(tmp_path / "missing"))
    diags, _ = analyze_description(desc)
    d = [x for x in diags if x.code == "NNS513"]
    assert d and "NNS_TPU_COMPILE_CACHE_DIR" in d[0].message
    monkeypatch.setenv("NNS_TPU_COMPILE_CACHE_DIR", str(tmp_path))
    diags, _ = analyze_description(desc)
    assert "NNS513" not in codes(diags)
    monkeypatch.delenv("NNS_TPU_COMPILE_CACHE_DIR")
    diags, _ = analyze_description(desc)
    assert "NNS513" not in codes(diags)


def test_nns513_canary_without_watch_rule_cli(tmp_path):
    """The rules face runs in the CLI: a canary= pipeline against the
    default pack (which binds no version-labelled series) warns; a
    rules file with a comparator rule on the canary series is clean."""
    desc = (f"appsrc caps={GOOD_CAPS} ! queue ! tensor_filter "
            "framework=jax-xla model=/nonexistent/model.pkl batch=4 "
            "share-model=true canary=next:1/4 ! tensor_sink")
    buf = io.StringIO()
    cli_main([desc], out=buf)
    out = buf.getvalue()
    assert "canary-rules:" in out and "NNS513" in out, out
    rules = tmp_path / "rules.json"
    rules.write_text(json.dumps({"rule": [
        {"name": "canary-regressed", "kind": "threshold",
         "metric": "nns_model_canary_latency_us",
         "per": "nns_model_baseline_latency_us",
         "op": ">", "value": 1.5, "for": "1s"}]}))
    buf = io.StringIO()
    cli_main([desc, "--watch-rules", str(rules)], out=buf)
    out = buf.getvalue()
    assert "canary-rules:" in out
    # the canary face is clean; (the rules file itself is NNS510-clean)
    assert not [ln for ln in out.splitlines() if "NNS513" in ln], out


def test_nns512_pool_divisibility_and_conflicts():
    """NNS512 is the POOL-level NNS509 (ISSUE-12): share-model sharers
    form one cross-pipeline window, so divisibility is checked per
    pool (union of the sharers' declared buckets), and provably
    conflicting placements — which the runtime refuses with a
    PoolConflictError — are flagged statically."""
    flt = ("tensor_filter framework=jax-xla "
           "model=/nonexistent/model.pkl share-model=true ")
    pre = f"appsrc caps={GOOD_CAPS} ! queue ! "
    # divisible pool window: clean (and no NNS509 double-fire)
    diags, _ = analyze_description(
        pre + flt + "mesh=data:4 batch=8 ! tensor_sink")
    assert "NNS512" not in codes(diags)
    assert "NNS509" not in codes(diags)
    # indivisible pool window: NNS512, NOT NNS509 (the pool check owns
    # share-model windows)
    diags, _ = analyze_description(
        pre + flt + "mesh=data:4 batch=6 ! tensor_sink")
    d = [x for x in diags if x.code == "NNS512"]
    assert d and "NNS509" not in codes(diags)
    assert "6" in d[0].message
    assert "nns_pool_pad_frac" in (d[0].hint or "")
    # two sharers, provably different placements: the static face of
    # the runtime PoolConflictError
    diags, _ = analyze_description(
        pre + flt + "name=f1 mesh=data:4 batch=4 ! tensor_sink  "
        + pre + flt + "name=f2 mesh=data:2 batch=4 ! tensor_sink")
    d = [x for x in diags if x.code == "NNS512"]
    assert d and "PoolConflictError" in d[0].message
    # same spelling, and alias spellings (dp vs replicated), are NOT
    # conflicts; wildcard vs fixed is not PROVABLY different either
    for a, b in (("mesh=data:4 sharding=dp", "mesh=data:4 "
                  "sharding=replicated"),
                 ("mesh=data:-1", "mesh=data:-1"),
                 ("mesh=data:-1", "mesh=data:8")):
        diags, _ = analyze_description(
            pre + flt + f"name=f1 {a} batch=8 ! tensor_sink  "
            + pre + flt + f"name=f2 {b} batch=8 ! tensor_sink")
        conflicts = [x for x in diags if x.code == "NNS512"
                     and "conflict" in x.message]
        assert not conflicts, (a, b, [str(x) for x in conflicts])
    # devices omitted vs an equivalent explicit subset is NOT provably
    # different (a plain mesh lays over the device prefix, which may
    # BE the named subset — the runtime joins them), and subset
    # spellings canonicalize
    for a, b in (("mesh=data:4", "mesh=data:4 devices=0-3"),
                 ("mesh=data:4 devices=0-3",
                  "mesh=data:4 devices=0,1,2,3")):
        diags, _ = analyze_description(
            pre + flt + f"name=f1 {a} batch=8 ! tensor_sink  "
            + pre + flt + f"name=f2 {b} batch=8 ! tensor_sink")
        assert not [x for x in diags if x.code == "NNS512"], (a, b)
    # two EXPLICIT different subsets ARE a conflict
    diags, _ = analyze_description(
        pre + flt + "name=f1 mesh=data:4 devices=0-3 batch=8 ! "
        "tensor_sink  "
        + pre + flt + "name=f2 mesh=data:4 devices=4-7 batch=8 ! "
        "tensor_sink")
    assert [x for x in diags if x.code == "NNS512"]
    # filters split by shared-tensor-filter-key (or custom/IO-spec)
    # open DIFFERENT pools at runtime — different placements across
    # them are NOT a conflict (review fix: grouping mirrors the
    # runtime pool identity, not just the model)
    diags, _ = analyze_description(
        pre + flt + "name=f1 shared-tensor-filter-key=a mesh=data:4 "
        "batch=4 ! tensor_sink  "
        + pre + flt + "name=f2 shared-tensor-filter-key=b mesh=data:2 "
        "batch=4 ! tensor_sink")
    assert not [x for x in diags if x.code == "NNS512"]


def test_nns509_divisible_and_unknown_axis_are_clean():
    """NNS509 only fires when a bucket provably cannot split over a
    statically-known data axis: divisible buckets, batch=1, no mesh,
    and wildcard (-1) axes with no devices= pin are all clean."""
    base = (f"appsrc caps={GOOD_CAPS} ! queue ! tensor_filter "
            "framework=jax-xla model=/nonexistent/model.pkl ")
    for props in ("mesh=data:4 batch=8",            # divisible
                  "mesh=data:4 batch=8 batch-buckets=4,8",
                  "mesh=data:4",                    # batch=1
                  "mesh=data:-1 batch=6",           # unknown axis size
                  "batch=6"):                       # no mesh at all
        diags, _ = analyze_description(base + props + " ! tensor_sink")
        assert "NNS509" not in codes(diags), props
    # an explicit bucket list with ONE bad bucket is enough, and the
    # devices= subset pins a wildcard axis statically
    for props, bad in (
            ("mesh=data:4 batch=8 batch-buckets=4,6,8", "6"),
            ("mesh=data:-1 devices=0-3 batch=6", "6"),
            ("mesh=model:2,data:2 batch=5", "5")):  # named data axis
        diags, _ = analyze_description(base + props + " ! tensor_sink")
        d = [x for x in diags if x.code == "NNS509"]
        assert d, props
        assert d[0].severity == Severity.WARNING
        assert bad in d[0].message, (props, d[0].message)
        assert "nns_mesh_pad_slots_total" in (d[0].hint or "")


def test_nns507_defaults_and_inproc_are_clean():
    """NNS507 is about DISABLED bounds on a cross-host link: the
    defaults (timeout=10000, max-request=8) are bounded, and an inproc
    link has no dead-server failure mode to bound against."""
    base = (f"appsrc caps={GOOD_CAPS} ! tensor_query_client "
            f"caps={GOOD_CAPS} dest-host=198.51.100.7 dest-port=5432")
    diags, _ = analyze_description(base + " ! tensor_sink")
    assert "NNS507" not in codes(diags)
    inproc, _ = analyze_description(
        f"appsrc caps={GOOD_CAPS} ! tensor_query_client "
        f"caps={GOOD_CAPS} connect-type=inproc timeout=0 ! tensor_sink")
    assert "NNS507" not in codes(inproc)
    # each disabled bound alone is enough to warn
    for knob in (" timeout=0", " max-request=0"):
        diags, _ = analyze_description(base + knob + " ! tensor_sink")
        d = [x for x in diags if x.code == "NNS507"]
        assert d, knob
        assert d[0].severity == Severity.WARNING
        assert "max-request" in (d[0].hint or "")


def test_lint_negatives_stay_clean():
    # Condition.wait on the held condition releases the lock: not NNS303
    clean = """
class Q:
    def pop(self):
        with self._cv:
            while not self._dq:
                self._cv.wait(0.05)
"""
    assert codes(lint_source(clean)) == set()
    # string join is not a thread join
    assert codes(lint_source("""
def render(parts, lock):
    with lock:
        return ", ".join(parts)
""")) == set()
    # trace-time shape math is allowed in jitted code
    assert codes(lint_source("""
import jax
import numpy as np

@jax.jit
def hot(x):
    n = int(np.prod(x.shape))
    return x.reshape(n)
""")) == set()


def test_suppressions():
    src = """
def f():
    try:
        risky()
    except:  # nns-lint: disable=NNS403 -- crafted test fixture
        pass
"""
    assert codes(lint_source(src)) == set()
    src_above = """
def f():
    try:
        risky()
    # nns-lint: disable=NNS403 -- reason on the line above
    except:
        pass
"""
    assert codes(lint_source(src_above)) == set()
    src_file = """
# nns-lint: disable-file=NNS403 -- fixture file
def f():
    try:
        risky()
    except:
        pass
"""
    assert codes(lint_source(src_file)) == set()


# -- good corpus: zero false positives ---------------------------------------


def test_good_linear_pipeline_is_clean():
    diags, pipe = analyze_description(GOOD)
    assert diags == []
    assert pipe is not None


def test_good_pipeline_with_registered_model_is_clean():
    from nnstreamer_tpu.filters.jax_xla import register_model, \
        unregister_model

    register_model("_t_analyze_model", lambda x: x.astype("float32") + 1,
                   in_shapes=[(1, 4, 4, 3)], in_dtypes=np.uint8)
    try:
        diags, _ = analyze_description(
            f"appsrc caps={GOOD_CAPS} ! tensor_filter framework=jax-xla "
            "model=_t_analyze_model ! tensor_sink")
        assert diags == [], [str(d) for d in diags]
    finally:
        unregister_model("_t_analyze_model")


def test_good_fan_in_same_rate_is_clean():
    base = ("appsrc name={n} caps=other/tensors,format=static,"
            "num_tensors=1,dimensions=4,types=uint8,framerate=30/1")
    diags, _ = analyze_description(
        base.format(n="a") + " ! tensor_mux name=m ! tensor_sink " +
        base.format(n="b") + " ! m.sink_1")
    assert diags == [], [str(d) for d in diags]


def test_examples_and_doc_corpus_zero_false_positives():
    """Every pipeline in examples/ and every element-doc example analyzes
    without errors or warnings (info is allowed: runtime-registered
    models/specs cannot be proven statically)."""
    from nnstreamer_tpu.analyze.pipelines import default_corpus

    entries = default_corpus(os.path.join(REPO, "examples"))
    assert len(entries) >= 8  # 2 example scripts + 7 doc pipelines
    for entry in entries:
        diags, _ = analyze_description(entry.description,
                                       fragment=entry.fragment)
        bad = above_info(diags)
        assert not bad, f"{entry.label}: {[str(d) for d in bad]}"


def test_self_lint_runs_clean():
    pkg = os.path.join(REPO, "nnstreamer_tpu")
    diags = lint_package(pkg)
    assert diags == [], [str(d) for d in diags]


# -- caps dry-run regressions ------------------------------------------------


def test_dry_run_rank_flexible_dims():
    # 3:4:4:1 vs rank-flexible 3:4:4 intersect (reference rank-flexible
    # compare); the dry run must not flag the link
    diags, _ = analyze_description(
        f"appsrc caps={GOOD_CAPS} ! other/tensors,format=static,"
        "num_tensors=1,dimensions=3:4:4,types=uint8 ! tensor_sink")
    assert diags == [], [str(d) for d in diags]


def test_dry_run_framerate_wildcard():
    # framerate=0/1 is the "any rate" wildcard on either side
    diags, _ = analyze_description(
        f"appsrc caps={GOOD_CAPS} ! other/tensors,framerate=0/1 ! "
        "tensor_sink")
    assert diags == [], [str(d) for d in diags]
    diags, _ = analyze_description(
        "appsrc caps=other/tensors,format=static,num_tensors=1,"
        "dimensions=4,types=uint8,framerate=0/1 ! "
        "other/tensors,framerate=25/1 ! tensor_sink")
    assert diags == [], [str(d) for d in diags]


def test_dry_run_is_pure():
    """The dry run leaves the pipeline unstarted and pad caps untouched,
    and the pipeline still starts normally afterwards."""
    p = parse_launch(GOOD)
    assert analyze_pipeline(p) == []
    assert not p.playing
    for e in p.elements.values():
        for pad in e.sinkpads + e.srcpads:
            assert pad.caps is None and pad.spec is None
    with p:
        assert p.playing
    assert not p.playing


def test_dry_run_names_offending_field():
    diags, _ = analyze_description(
        f"appsrc caps={GOOD_CAPS} ! other/tensors,format=static,"
        "num_tensors=1,dimensions=3:8:8:1,types=uint8 ! tensor_sink")
    [d] = [d for d in diags if d.code == "NNS201"]
    assert "dimensions" in d.message
    assert "3:4:4:1" in d.message and "3:8:8:1" in d.message


# -- CLI ---------------------------------------------------------------------


def test_cli_exit_codes():
    assert cli_main([], out=io.StringIO()) == 2
    assert cli_main([GOOD], out=io.StringIO()) == 0
    assert cli_main(["tensor_converter ! tensor_sink"],
                    out=io.StringIO()) == 1
    # NNS102+NNS106 are warnings: clean exit by default, fail --strict
    warn_only = f"appsrc caps={GOOD_CAPS} ! tensor_converter"
    assert cli_main([warn_only], out=io.StringIO()) == 0
    assert cli_main(["--strict", warn_only], out=io.StringIO()) == 1
    # fragment mode downgrades them to info: clean even under --strict
    assert cli_main(["--strict", "--fragment", warn_only],
                    out=io.StringIO()) == 0


def test_cli_dot_stdout():
    """`--dot` (bare) prints the static Pipeline.to_dot() dump for every
    target that parsed — the never-started graph, so caps stay '?'."""
    buf = io.StringIO()
    rc = cli_main([GOOD, "--dot"], out=buf)
    assert rc == 0
    text = buf.getvalue()
    assert f"// dot: {GOOD}" in text
    assert 'digraph "pipeline"' in text
    assert '"appsrc0" -> "tensor_converter1"' in text
    assert '"tensor_converter1" -> "tensor_sink2"' in text


def test_cli_dot_writes_files(tmp_path):
    d = str(tmp_path / "dots")
    buf = io.StringIO()
    rc = cli_main([GOOD, "--dot", d], out=buf)
    assert rc == 0
    files = os.listdir(d)
    assert len(files) == 1 and files[0].endswith(".dot")
    with open(os.path.join(d, files[0])) as f:
        assert f.read().startswith('digraph "pipeline"')
    assert "wrote" in buf.getvalue()


def test_cli_dot_skips_unparseable_targets(tmp_path):
    d = str(tmp_path / "dots")
    rc = cli_main(["appsrc ! bogus_thing ! tensor_sink", "--dot", d],
                  out=io.StringIO())
    assert rc == 1  # the NNS100 still fails the run
    assert not os.path.isdir(d)  # nothing parsed: nothing dumped


def test_cli_json_golden():
    """--json output is stable and matches the committed golden."""
    buf = io.StringIO()
    rc = cli_main(["--json",
                   "appsrc ! bogus_thing ! tensor_sink",
                   "tensor_converter ! tensor_sink"], out=buf)
    assert rc == 1
    got = json.loads(buf.getvalue())
    golden_path = os.path.join(REPO, "tests", "golden",
                               "analyze_cli.golden.json")
    with open(golden_path) as f:
        golden = json.load(f)
    assert got == golden
    # determinism: a second run byte-matches
    buf2 = io.StringIO()
    cli_main(["--json", "appsrc ! bogus_thing ! tensor_sink",
              "tensor_converter ! tensor_sink"], out=buf2)
    assert buf2.getvalue() == buf.getvalue()


def test_cli_self_flag():
    assert cli_main(["--self", os.path.join(REPO, "nnstreamer_tpu")],
                    out=io.StringIO()) == 0


# -- satellite: Bus.remove_watch + thread safety -----------------------------


def test_bus_remove_watch():
    bus = Bus()
    seen_a, seen_b = [], []
    ha = seen_a.append
    hb = seen_b.append
    bus.add_watch(ha)
    bus.add_watch(hb)
    bus.post(Message(MessageKind.ELEMENT, "x"))
    assert len(seen_a) == len(seen_b) == 1
    assert bus.remove_watch(ha) is True
    assert bus.remove_watch(ha) is False  # already gone
    bus.post(Message(MessageKind.ELEMENT, "x"))
    assert len(seen_a) == 1 and len(seen_b) == 2


def test_bus_remove_watch_bound_method():
    class W:
        def __init__(self):
            self.n = 0

        def on_msg(self, msg):
            self.n += 1

    w = W()
    bus = Bus()
    bus.add_watch(w.on_msg)  # a fresh bound-method object...
    assert bus.remove_watch(w.on_msg) is True  # ...compares equal


def test_bus_watch_mutation_race():
    """add_watch/remove_watch from other threads must never corrupt the
    handler list a concurrent post is iterating."""
    bus = Bus()
    stop = threading.Event()
    errors = []

    def churn():
        def h(msg):
            pass

        while not stop.is_set():
            try:
                bus.add_watch(h)
                bus.remove_watch(h)
            except Exception as e:  # pragma: no cover
                errors.append(e)
                return

    threads = [threading.Thread(target=churn) for _ in range(4)]
    for t in threads:
        t.start()
    for _ in range(2000):
        bus.post(Message(MessageKind.ELEMENT, "race"))
    stop.set()
    for t in threads:
        t.join(timeout=10)
    assert not errors


def test_bus_post_vs_remove_watch_race():
    """ISSUE 16 audit companion: post() iterates a copy-on-write tuple
    snapshot lock-free, so a remove_watch racing two poster threads
    must (a) never corrupt an in-flight delivery and (b) win promptly —
    after remove_watch returns, NO later post may call the handler."""
    bus = Bus()
    stop = threading.Event()
    errors = []
    removed = threading.Event()
    late_calls = []

    def handler(msg):
        if removed.is_set():
            late_calls.append(msg)

    def poster():
        while not stop.is_set():
            try:
                bus.post(Message(MessageKind.ELEMENT, "race"))
            except Exception as e:  # pragma: no cover
                errors.append(e)
                return

    posters = [threading.Thread(target=poster) for _ in range(2)]
    for _ in range(50):
        removed.clear()
        late_calls.clear()
        bus.add_watch(handler)
        for t in posters:
            if not t.is_alive():
                t.start()
        bus.remove_watch(handler)
        removed.set()
        # a delivery that STARTED before the removal may still be
        # draining the old snapshot; one more post must not see it
        bus.post(Message(MessageKind.ELEMENT, "after-remove"))
        assert not any(m.src == "after-remove" for m in late_calls), \
            "handler called by a post issued after remove_watch"
    stop.set()
    for t in posters:
        t.join(timeout=10)
    assert not errors


# -- satellite: parser position info -----------------------------------------


def test_parse_error_positions():
    desc = "appsrc ! nosuchelement ! tensor_sink"
    with pytest.raises(ParseError) as ei:
        parse_launch(desc)
    assert ei.value.pos == desc.index("nosuchelement")
    ctx = ei.value.context(desc)
    caret_line = ctx.splitlines()[1]
    assert caret_line.index("^") == ei.value.pos

    desc2 = "appsrc name=a ! unknownref. ! tensor_sink"
    with pytest.raises(ParseError) as ei:
        parse_launch(desc2)
    assert ei.value.pos == desc2.index("unknownref.")

    with pytest.raises(ParseError) as ei:
        parse_launch('appsrc caps="unterminated')
    assert ei.value.pos == len("appsrc ")


def test_parse_caps_field_position():
    desc = "appsrc ! other/tensors,format=static,badfield ! tensor_sink"
    with pytest.raises(ParseError) as ei:
        parse_launch(desc)
    assert ei.value.pos == desc.index("badfield")


def test_caps_string_error_offsets():
    from nnstreamer_tpu.runtime.parser import parse_caps_string

    with pytest.raises(ParseError) as ei:
        parse_caps_string("other/tensors,oops")
    assert ei.value.pos == len("other/tensors,")


# -- satellite: double-link rejection ----------------------------------------


def test_link_pads_rejects_double_link():
    p = Pipeline()
    src1 = make("appsrc", el_name="s1")
    src2 = make("appsrc", el_name="s2")
    sink = make("tensor_sink", el_name="out")
    p.add(src1, src2, sink)
    p.link_pads("s1", "src", "out", "sink")
    with pytest.raises(ValueError) as ei:
        p.link_pads("s2", "src", "out", "sink")
    msg = str(ei.value)
    assert "already linked" in msg
    assert "s1.src" in msg  # names the existing peer
    # nothing was overwritten
    assert sink.sinkpad.peer is src1.srcpad
    assert src2.srcpad.peer is None


# -- misc --------------------------------------------------------------------


def test_device_src_string_spec():
    el = make("device_src", el_name="d", spec="3:4:4:2/float32,10:2")
    spec = el.output_spec()
    assert isinstance(spec, TensorsSpec)
    assert spec.num_tensors == 2
    assert "float32" in str(spec.tensors[0].dtype)
    assert "uint8" in str(spec.tensors[1].dtype)  # default pattern dtype
    assert spec.tensors[1].dims == (10, 2)


def test_collect_request_pad_autonumbers():
    mux = make("tensor_mux", el_name="m")
    p0 = mux.request_pad("sink_%u")
    p1 = mux.request_pad("sink_%u")
    assert (p0.name, p1.name) == ("sink_0", "sink_1")
    named = mux.request_pad("sink_7")
    assert named.name == "sink_7"


def test_request_pad_names_unique_everywhere():
    """%u templates expand in shared code: every request-pad element
    yields unique names (EOS tracking and get_pad are name-keyed)."""
    for factory, req, attr in [("join", "sink_%u", "sinkpads"),
                               ("tensor_demux", "src_%u", "srcpads"),
                               ("tensor_split", "src_%u", "srcpads"),
                               ("tee", "src_%u", "srcpads")]:
        el = make(factory, el_name=f"u_{factory}")
        a = el.request_pad(req)
        b = el.request_pad(req)
        names = [p.name for p in getattr(el, attr)]
        assert len(names) == len(set(names)), (factory, names)
        assert "%u" not in a.name and "%u" not in b.name, (factory,
                                                           a.name, b.name)


def test_join_two_branches_eos_not_premature():
    """Regression: duplicate 'sink_%u' pad names made join forward EOS
    after the FIRST branch finished, dropping the other branch's tail."""
    caps = ("other/tensors,format=static,num_tensors=1,dimensions=2,"
            "types=uint8,framerate=0/1")
    p = parse_launch(
        f"appsrc name=a caps={caps} ! join name=j ! tensor_sink name=o "
        f"appsrc name=b caps={caps} ! j.")
    assert len({pd.name for pd in p["j"].sinkpads}) == 2
    got = []
    p["o"].connect(lambda buf: got.append(buf.tensors[0].np().tolist()))
    with p:
        p["a"].push_buffer(Buffer.of(np.array([1, 1], np.uint8)))
        p["a"].end_of_stream()  # first branch ends...
        import time

        time.sleep(0.2)
        # ...second branch must still flow
        p["b"].push_buffer(Buffer.of(np.array([2, 2], np.uint8)))
        p["b"].end_of_stream()
        assert p.wait_eos(timeout=30)
    assert [2, 2] in got, got


def test_bus_remove_watch_removes_one_registration():
    bus = Bus()
    seen = []
    h = seen.append
    bus.add_watch(h)
    bus.add_watch(h)  # independent callers both registered the handler
    assert bus.remove_watch(h) is True
    bus.post(Message(MessageKind.ELEMENT, "x"))
    assert len(seen) == 1  # one registration survives
    assert bus.remove_watch(h) is True
    assert bus.remove_watch(h) is False


def test_quoted_caps_token_position():
    desc = 'appsrc ! "other/tensors,badfield" ! tensor_sink'
    with pytest.raises(ParseError) as ei:
        parse_launch(desc)
    assert ei.value.pos == desc.index("badfield")


def test_parse_error_double_link_kind():
    with pytest.raises(ParseError) as ei:
        parse_launch("appsrc name=a ! tensor_sink name=s "
                     "appsrc name=b ! s.sink")
    assert ei.value.kind == "double-link"


def test_lint_blocking_with_item_under_lock():
    src = """
def f(self, path):
    with self._lock:
        with open(path) as fh:
            return fh.read()
"""
    assert "NNS303" in codes(lint_source(src))

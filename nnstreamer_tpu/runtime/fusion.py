"""Transform↔filter fusion pass (SURVEY.md §7 stage 4).

Before negotiation, every maximal run of ``tensor_transform`` elements
feeding a ``jax-xla`` ``tensor_filter`` is collapsed into the filter's own
XLA computation: the transforms become passthrough nodes and the filter
compiles ``model ∘ t_k ∘ … ∘ t_1`` as ONE jitted program.  This is the
reference's Orc multi-op fusion idea
(/root/reference/gst/nnstreamer/elements/gsttensor_transform.c:473-483,
gsttensor_transform.md:12-14) done the XLA way — the elementwise chain
fuses into the matmul program's prologue, so the separate-elements
pipeline costs the same as a hand-fused model.

Fusion is skipped for a candidate filter when any of these hold (the
pipeline still runs, just unfused): framework isn't jax-xla,
``invoke-dynamic``, input/output-combination in play, a transform mid-run
feeds more than one consumer, or a transform has no static mode.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

from ..utils.log import logi


@dataclass(frozen=True)
class FusedSegment:
    """One captured linear segment that lowers as a single XLA program:
    ``transforms → filter [→ decoder]``.  Built by :func:`fuse_pipeline`
    after both passes ran; the descriptor is what the rest of the
    system keys on —

    - ``chain_digest`` is the ordered identity of every non-model stage
      baked into the filter's executable.  The jax-xla sub-plugin folds
      it into the persistent AOT cache key (runtime/compilecache.py),
      which is what lifts the PR-14 exclusion of fused-chain programs:
      two processes building the same segment around the same model hit
      the same cache entry, and a changed transform option or decoder
      config misses instead of wrongly hitting.
    - the element names give bench/obs a stable label for "the windows
      of this segment are ONE dispatch" accounting.
    """

    filter: str
    transforms: Tuple[str, ...] = ()
    decoder: Optional[str] = None
    chain_digest: str = ""

    @property
    def stages(self) -> int:
        """Pipeline stages collapsed into the one dispatch."""
        return len(self.transforms) + 1 + (1 if self.decoder else 0)


def _is_jax_xla(flt) -> bool:
    fw = (flt.framework or "auto")
    if fw == "jax-xla":
        return True
    if fw != "auto":
        return False
    try:
        from ..filters.registry import detect_framework

        return detect_framework(flt.model) == "jax-xla"
    except Exception:
        return False


def fuse_transform_filter(pipeline, enable: bool = True) -> int:
    """Mark fusable transform runs as passthrough and hand their op
    chains to the downstream filter.  Returns the number of filters that
    received a fused prologue.  Always resets previous marks first (an
    element reused in a different topology or a fuse=False pipeline must
    not stay passthrough), then marks only when ``enable``."""
    from ..elements.filter import TensorFilter
    from ..elements.transform import TensorTransform

    for el in pipeline.elements.values():
        if isinstance(el, TensorTransform):
            el._fused = False
            el._fusion_filter = None
        elif isinstance(el, TensorFilter):
            # mutate IN PLACE: an already-opened jax-xla subplugin holds
            # this very list by reference (set_fused_pre) — rebinding
            # would leave a stale prologue baked into its executable
            el._fused_pre.clear()
    if not enable:
        return 0

    fused = 0
    for el in list(pipeline.elements.values()):
        if not isinstance(el, TensorFilter):
            continue
        if el.invoke_dynamic or el.input_combination \
                or el.output_combination:
            continue
        if el.share_model:
            # a pooled instance serves MANY pipelines: baking one
            # pipeline's transform chain into it would corrupt every
            # other sharer's stream
            continue
        if not _is_jax_xla(el):
            continue
        if not el.sinkpads or el.sinkpads[0].peer is None:
            continue
        run: List = []  # (transform, opchain), filter→source order
        up = el.sinkpads[0].peer.element
        while isinstance(up, TensorTransform):
            if up._fused or not up.mode:
                break
            if len(up.srcpads) != 1 or len(up.sinkpads) != 1 \
                    or up.sinkpads[0].peer is None:
                break
            try:
                chain = up._opchain()
            except Exception:
                break
            run.append((up, chain))
            up = up.sinkpads[0].peer.element
        if not run:
            continue
        run.reverse()  # source→filter order
        el._fused_pre[:] = [c for _, c in run]
        for t, _ in run:
            t._fused = True
            # handle to unfuse at negotiation if the stream turns out
            # flexible (per-buffer schemas can't pre-compile a prologue)
            t._fusion_filter = el
        fused += 1
        logi("fused %s into %s (one XLA computation)",
             "+".join(t.name for t, _ in run), el.name, element=el.name)
    return fused


def fuse_filter_decoder(pipeline, enable: bool = True) -> int:
    """Fuse a device-rendering decoder's program INTO its upstream
    jax-xla filter: ``tensor_filter ! tensor_decoder mode=bounding_boxes
    option7=device`` becomes ONE XLA dispatch for
    transform+model+NMS+overlay; the decoder turns into a consumer of
    the ready canvas (round-3 verdict #10).  Same reset-first contract
    as :func:`fuse_transform_filter`."""
    from ..elements.decoder import TensorDecoder
    from ..elements.filter import TensorFilter

    for el in pipeline.elements.values():
        if isinstance(el, TensorFilter):
            el._fused_post.clear()
            el._fused_post_decoder = None
        elif isinstance(el, TensorDecoder):
            dec = getattr(el, "_dec", None)
            if dec is not None and hasattr(dec, "fused_upstream"):
                dec.fused_upstream = False
    if not enable:
        return 0

    fused = 0
    for el in list(pipeline.elements.values()):
        if not isinstance(el, TensorDecoder):
            continue
        if not el.sinkpads or el.sinkpads[0].peer is None:
            continue
        up = el.sinkpads[0].peer.element
        if not isinstance(up, TensorFilter):
            continue
        if up.invoke_dynamic or up.output_combination or up._fused_post \
                or up.share_model:
            continue
        if len(up.srcpads) != 1 or \
                up.srcpads[0].peer is not el.sinkpads[0]:
            continue  # filter output must feed ONLY this decoder
        if not _is_jax_xla(up):
            continue
        try:
            dec = el._decoder()
        except Exception:
            continue
        builder = getattr(dec, "device_post_program", None)
        post = builder() if builder is not None else None
        if post is None:
            continue
        up._fused_post[:] = [post]
        up._fused_post_decoder = dec
        dec.fused_upstream = True
        fused += 1
        logi("fused %s's device overlay into %s (one XLA dispatch for "
             "model+postprocess+overlay)", el.name, up.name,
             element=up.name)
    return fused


def fuse_pipeline(pipeline, enable: bool = True) -> List[FusedSegment]:
    """Whole-graph capture: run both fusion passes, then describe every
    captured linear segment as a :class:`FusedSegment`.  Called by
    ``Pipeline.start()`` before negotiation; the result is stored on
    ``pipeline.fused_segments`` so tests/bench/obs can assert what
    actually collapsed (and the jax-xla instances can key the
    persistent cache off the same digests the descriptor carries).

    The digest is ordered and covers every fused stage: each prologue
    op chain contributes ``_OpChain.digest()`` and a fused decoder
    epilogue contributes the ``chain_digest`` its builder stamped on
    the post fn.  A fused stage WITHOUT a digest poisons the segment's
    digest (set to ``""``) — the sub-plugin then keeps such programs
    out of the persistent cache, preserving the PR-14 invariant that a
    wrong cache hit is impossible."""
    from ..elements.filter import TensorFilter

    fuse_transform_filter(pipeline, enable=enable)
    fuse_filter_decoder(pipeline, enable=enable)
    segments: List[FusedSegment] = []
    if not enable:
        pipeline.fused_segments = segments
        return segments
    for el in pipeline.elements.values():
        if not isinstance(el, TensorFilter):
            continue
        if not el._fused_pre and not el._fused_post:
            continue
        transforms = tuple(
            t.name for t in pipeline.elements.values()
            if getattr(t, "_fusion_filter", None) is el)
        decoder = None
        if el._fused_post_decoder is not None:
            for d in pipeline.elements.values():
                if getattr(d, "_dec", None) is el._fused_post_decoder:
                    decoder = d.name
                    break
        parts: List[str] = []
        ok = True
        for c in el._fused_pre:
            dig = getattr(c, "digest", None)
            if dig is None:
                ok = False
                break
            parts.append("pre:" + c.digest())
        for p in el._fused_post:
            dig = getattr(p, "chain_digest", None)
            if dig is None:
                ok = False
                break
            parts.append("post:" + dig)
        segments.append(FusedSegment(
            filter=el.name, transforms=transforms, decoder=decoder,
            chain_digest=";".join(parts) if ok else ""))
    pipeline.fused_segments = segments
    return segments

"""Per-buffer latency tracer + Chrome trace-event exporter.

The GstTracer latency-tracer analog: hook points compiled into the
runtime (``runtime/element.py`` pre/post chain, ``elements/basic.py``
queue in/out, ``runtime/batching.py`` park/dispatch and the filter's
demux) feed a :class:`LatencyTracer` when one is attached via
``obs.hooks.attach``.  Each *sampled* buffer (1-in-N, decided once at
the source) carries a small trace dict in ``Buffer.meta`` that collects
``(timestamp, element, phase)`` marks as the buffer flows; elements
that copy ``meta`` forward (queue, tensor_filter, the serving demux)
keep the trace alive across buffer rewrites.  When the buffer reaches a
sink the tracer folds the marks into one record:

- **end-to-end latency** — source timestamp to sink completion, the
  host-side walltime a JAX device trace cannot see;
- **per-element residency** — the end-to-end interval partitioned at
  the ``chain-in`` marks, so residencies sum exactly to the end-to-end
  latency: an element's residency covers its own chain *plus* any time
  the buffer sat parked behind it (queue depth, batch window) before
  the next element first touched it.

Export: :meth:`LatencyTracer.chrome_trace` renders the records as
Chrome trace-event JSON (``{"traceEvents": [...]}``, Perfetto/
``chrome://tracing`` loadable): one lane per sampled frame, the frame
span with the element residency spans and the finer queue/batch
sub-phase spans nested inside it.

Overhead: with no tracer attached every hook site is one module-global
read and an ``is None`` branch — no allocation, no callback, no
per-buffer state (asserted in ``tests/test_obs.py``).  With a tracer
attached, unsampled buffers pay one dict lookup per hook site.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List

#: Buffer.meta key carrying a sampled buffer's trace state.  The dict is
#: shared by reference across buffer rewrites that copy ``meta``.
TRACE_META_KEY = "_nns_trace"

#: mark phases (the hook vocabulary)
PH_SOURCE = "source"        # buffer created at a source element
PH_CHAIN_IN = "chain-in"    # entering an element's chain()
PH_CHAIN_OUT = "chain-out"  # chain() returned
PH_QUEUE_IN = "queue-in"    # parked in a queue (thread boundary)
PH_QUEUE_OUT = "queue-out"  # taken by the queue's streaming thread
PH_PARK = "park"            # parked in a coalescing batch window
PH_DISPATCH = "dispatch"    # the window holding this buffer flushed
PH_DEMUX = "demux"          # dispatch result pushed back downstream
#: dispatch cost-attribution sub-phases (sampled dispatches only):
#: prep -> dev -> drain are consecutive block_until_ready-fenced
#: boundaries of ONE invoke; `done` closes the drain span on the
#: single-frame chain path (batched paths close it at PH_DEMUX)
PH_INV_PREP = "invoke-prep"    # host-prep began (input gather/place)
PH_INV_DEV = "invoke-device"   # dispatch issued (device phase began)
PH_INV_DRAIN = "invoke-drain"  # device done (host-drain began)
PH_INV_DONE = "invoke-done"    # outputs wrapped (chain path only)


def _item_buf(batcher, item):
    """A MicroBatcher item is the buffer itself; a SharedBatcher item
    is ``(owner-element, buffer, deadline, enqueue-ts)``.  Returns
    ``(element-name, buffer)``."""
    if isinstance(item, tuple) and len(item) >= 2:
        owner, buf = item[0], item[1]
        return getattr(owner, "name", str(owner)), buf
    return getattr(batcher, "name", "") or "batch", item


class LatencyTracer:
    """Collects per-buffer latency records from the runtime hooks.

    ``sample_every=N`` traces one in every N source buffers (per
    process, across all sources) — tracing every buffer is fine for
    tests and short diagnostics, 1-in-100 keeps a hot pipeline honest.
    Records are kept up to ``max_records`` (further samples count into
    :attr:`dropped` instead of growing without bound).

    Use as a context manager, or call :meth:`install` /
    :meth:`uninstall` explicitly::

        with LatencyTracer(sample_every=10) as tr:
            run_pipeline()
        tr.save_chrome_trace("trace.json")
    """

    def __init__(self, sample_every: int = 1, max_records: int = 4096):
        if sample_every < 1:
            raise ValueError(
                f"sample_every must be >= 1, got {sample_every}")
        self.sample_every = int(sample_every)
        self.max_records = int(max_records)
        self.dropped = 0
        self._lock = threading.Lock()
        self._seen = 0       # source buffers observed (sampling counter)
        self._sampled = 0    # trace ids handed out
        self._records: List[dict] = []
        # sink-side depth-1 fence accounting (runtime/element.py
        # SinkElement): how often a sink had to WAIT on the previous
        # window's device work, and for how long.  An annotation, not a
        # residency phase — the fence belongs to the NEXT buffer's
        # chain span, so the residency-sum==e2e partition is untouched.
        self._fence_waits = 0
        self._fence_wait_s = 0.0
        # process-unique prefix so trace ids stay distinct across the
        # hosts of a distributed pipeline (and across tracer restarts)
        self._id_prefix = os.urandom(4).hex()

    # -- attach/detach -------------------------------------------------------

    def install(self) -> "LatencyTracer":
        from . import hooks

        hooks.attach(self)
        return self

    def uninstall(self) -> None:
        from . import hooks

        if hooks.tracer is self:
            hooks.detach()

    def __enter__(self) -> "LatencyTracer":
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    # -- hook API (called from the runtime when attached) --------------------

    def source_created(self, element, buf) -> None:
        """Sampling decision: 1-in-N buffers get a trace dict planted in
        ``meta``; the rest flow untouched (every later hook is then a
        single failed dict lookup for them).  A buffer that already
        carries a trace (a remote-origin one planted by
        tensor_query_serversrc / edgesrc from a propagated context,
        ``obs.tracectx``) keeps it — it neither re-samples nor counts
        against the local sampling budget."""
        if TRACE_META_KEY in buf.meta:
            return
        with self._lock:
            self._seen += 1
            if (self._seen - 1) % self.sample_every:
                return
            self._sampled += 1
            idx = self._sampled
        buf.meta[TRACE_META_KEY] = {
            "frame": idx,
            "id": f"{self._id_prefix}-{idx}",
            "pts": buf.pts,
            "marks": [(time.monotonic(), element.name, PH_SOURCE)],
        }

    def pre_chain(self, element, buf) -> None:
        tr = buf.meta.get(TRACE_META_KEY)
        if tr is None:
            return
        now = time.monotonic()
        tr["marks"].append((now, element.name, PH_CHAIN_IN))
        # payload-residency tagging at the element boundary: every
        # host<->device flip counts as one crossing, the per-frame
        # figure the transfer ledger's per-pipeline rates aggregate
        # (Buffer.residency, obs/transfer.py)
        res = getattr(buf, "residency", None)
        if res is None:
            return
        last = tr.get("res")
        if last is not None and res != last:
            tr["crossings"] = tr.get("crossings", 0) + 1
            tr.setdefault("res_marks", []).append(
                (now, element.name, f"{last}->{res}"))
        tr["res"] = res

    def post_chain(self, element, buf) -> None:
        tr = buf.meta.get(TRACE_META_KEY)
        if tr is None:
            return
        tr["marks"].append((time.monotonic(), element.name, PH_CHAIN_OUT))
        if element.sinkpads and not element.srcpads:
            self._finalize(tr)

    def queue_enqueued(self, element, buf) -> None:
        self._mark(buf, element.name, PH_QUEUE_IN)

    def queue_dequeued(self, element, buf) -> None:
        self._mark(buf, element.name, PH_QUEUE_OUT)

    def batch_parked(self, batcher, item) -> None:
        name, buf = _item_buf(batcher, item)
        self._mark(buf, name, PH_PARK)

    def batch_dispatch(self, batcher, items) -> None:
        now = time.monotonic()
        for item in items:
            name, buf = _item_buf(batcher, item)
            tr = buf.meta.get(TRACE_META_KEY)
            if tr is not None:
                tr["marks"].append((now, name, PH_DISPATCH))

    def batch_demuxed(self, element, buf) -> None:
        self._mark(buf, element.name, PH_DEMUX)

    def sink_fenced(self, element, waited_s: float) -> None:
        """A sink's depth-1 fence blocked ``waited_s`` on the previous
        window's device arrays before rendering the current one (0 when
        the device had already finished — the steady state whenever the
        host is the bottleneck)."""
        with self._lock:
            self._fence_waits += 1
            self._fence_wait_s += float(waited_s)

    def invoke_split(self, name_bufs, t0: float, t1: float, t2: float,
                     t3: float = None) -> None:
        """One sampled dispatch's host/device phase boundaries, fanned
        onto every traced buffer it carried.  ``name_bufs`` is an
        iterable of ``(element-name, buffer)``; t0/t1/t2 are the
        prep-start / device-start / drain-start fences and the optional
        ``t3`` closes the drain span (single-frame chain — batched
        paths leave it to each buffer's own demux mark, so the drain
        span ends when THAT buffer was demuxed).  Called BEFORE the
        results push downstream: a sink reached inline during the push
        finalizes the record, and marks appended after that are
        lost."""
        for name, buf in name_bufs:
            tr = buf.meta.get(TRACE_META_KEY)
            if tr is None:
                continue
            marks = tr["marks"]
            marks.append((t0, name, PH_INV_PREP))
            marks.append((t1, name, PH_INV_DEV))
            marks.append((t2, name, PH_INV_DRAIN))
            if t3 is not None:
                marks.append((t3, name, PH_INV_DONE))

    def _mark(self, buf, name: str, phase: str) -> None:
        tr = buf.meta.get(TRACE_META_KEY)
        if tr is not None:
            tr["marks"].append((time.monotonic(), name, phase))

    # -- record assembly -----------------------------------------------------

    def _finalize(self, tr: dict) -> None:
        # fan-out pipelines (tee) push ONE buffer object into several
        # branches that share this trace dict: only the first sink to
        # complete closes the record (later branches' marks are a
        # best-effort tail the record no longer includes).  The
        # check-then-set runs under the tracer lock — two branch
        # streaming threads reaching their sinks concurrently must not
        # both see "not done"
        with self._lock:
            if tr.get("done"):
                return
            tr["done"] = True
        marks = tr["marks"]
        t0 = marks[0][0]
        t_end = marks[-1][0]
        # Partition [t0, t_end] at the element entry marks: an element
        # owns the buffer from the moment it (or the source that made
        # it) first touched it until the NEXT element first touches it.
        # The pieces cover the interval exactly, so residencies sum to
        # the end-to-end latency by construction.
        entries = [(t, name) for t, name, phase in marks
                   if phase in (PH_SOURCE, PH_CHAIN_IN)]
        residency: Dict[str, float] = {}
        for i, (t, name) in enumerate(entries):
            nxt = entries[i + 1][0] if i + 1 < len(entries) else t_end
            residency[name] = residency.get(name, 0.0) + (nxt - t)
        record = {
            "frame": tr["frame"],
            "id": tr.get("id"),
            "pts": tr.get("pts"),
            "t0": t0,
            "end": t_end,
            "e2e_s": t_end - t0,
            "residency_s": residency,
            "marks": list(marks),
            # data-movement view (obs/transfer.py): host<->device
            # residency flips this frame paid, and the ledger-recorded
            # crossings that happened while it was sampled
            "crossings": tr.get("crossings", 0),
            "res_marks": list(tr.get("res_marks", ())),
            "xfers": list(tr.get("xfers", ())),
        }
        if tr.get("origin"):
            record["origin"] = tr["origin"]
        if tr.get("remote"):
            # cross-device hops absorbed into this trace (obs.tracectx):
            # remote marks are already mapped onto the local timeline
            record["remote"] = [dict(e) for e in tr["remote"]]
        with self._lock:
            if len(self._records) >= self.max_records:
                self.dropped += 1
            else:
                self._records.append(record)

    # -- results -------------------------------------------------------------

    def records(self) -> List[dict]:
        with self._lock:
            return list(self._records)

    def summary(self) -> dict:
        """Aggregate view: count + e2e latency distribution (seconds).

        ``started`` counts traces planted at sources; ``started`` well
        above ``count`` (+ in-flight frames) means traces are being
        LOST mid-pipeline — an element on the path rebuilds buffers
        without forwarding ``meta`` (e.g. tensor_converter's raw-media
        path, mux/aggregate), so the trace never reaches a sink."""
        recs = self.records()
        with self._lock:
            started = self._sampled
            fences = self._fence_waits
            fence_s = self._fence_wait_s
        if not recs:
            return {"count": 0, "started": started,
                    "dropped": self.dropped,
                    "sink_fence_waits": fences,
                    "sink_fence_wait_s": fence_s}
        lats = sorted(r["e2e_s"] for r in recs)
        n = len(lats)
        return {
            "count": n,
            "started": started,
            "dropped": self.dropped,
            "e2e_mean_s": sum(lats) / n,
            "e2e_p50_s": lats[n // 2],
            "e2e_p99_s": lats[min(n - 1, (n * 99) // 100)],
            # mean host<->device residency flips per sampled frame —
            # the number the device-resident-dataflow rework must
            # drive to zero (ROADMAP item 3)
            "crossings_per_frame":
                sum(r.get("crossings", 0) for r in recs) / n,
            # sink-side async-fence pressure: waits > 0 with meaningful
            # wait time means the device, not the host, paces the
            # pipeline (the depth-1 fence is providing backpressure)
            "sink_fence_waits": fences,
            "sink_fence_wait_s": fence_s,
        }

    # -- Chrome trace export -------------------------------------------------

    def chrome_trace(self, include_remote_origin: bool = False) -> dict:
        """The records as Chrome trace-event JSON: one ``tid`` lane per
        sampled frame, the frame span outermost, element residency spans
        and queue/batch sub-phase spans nested inside it.  Loadable by
        Perfetto / ``chrome://tracing``; complements (does not replace)
        ``jax.profiler`` device traces, which cannot see this host-side
        time.

        Traces that crossed a device boundary render as ONE merged
        timeline: each absorbed remote hop contributes a network span
        (``<link>:net``, send → receipt on the local clock) with the
        remote host's element spans nested inside it, placed via the
        per-exchange clock offset (``obs.tracectx``) — so the requesting
        element's residency = remote residency + true network RTT, on
        one clock.  ``include_remote_origin=True`` additionally renders
        records this process finalized *on behalf of a remote
        requester* (a query server's own view); they are excluded by
        default since the requester's merged trace already nests them."""
        events: List[dict] = []
        for rec in self.records():
            if rec.get("origin") == "remote" and not include_remote_origin:
                continue
            tid = rec["frame"]
            t0 = rec["t0"]
            events.append({
                "name": f"frame {rec['frame']}",
                "cat": "frame", "ph": "X", "pid": 1, "tid": tid,
                "ts": t0 * 1e6, "dur": rec["e2e_s"] * 1e6,
                "args": {"pts": rec["pts"], "id": rec.get("id"),
                         "e2e_ms": rec["e2e_s"] * 1e3},
            })
            marks = rec["marks"]
            entries = [(t, name) for t, name, phase in marks
                       if phase in (PH_SOURCE, PH_CHAIN_IN)]
            for i, (t, name) in enumerate(entries):
                nxt = entries[i + 1][0] if i + 1 < len(entries) \
                    else rec["end"]
                events.append({
                    "name": name, "cat": "element", "ph": "X",
                    "pid": 1, "tid": tid,
                    "ts": t * 1e6, "dur": (nxt - t) * 1e6,
                })
            events.extend(self._subphase_events(marks, tid))
            events.extend(self._xfer_events(rec, tid))
            for hop in rec.get("remote", ()):
                events.extend(self._remote_events(hop, tid))
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    @staticmethod
    def _xfer_events(rec: dict, tid) -> List[dict]:
        """Data-movement sub-spans: every ledger-recorded crossing this
        sampled frame's context saw (``<source>:<h2d|d2h>:<reason>``
        spans nested inside the owning element's residency span) and an
        instant mark per residency flip at an element boundary."""
        events: List[dict] = []
        for t0x, dur, source, direction, reason, nbytes in \
                rec.get("xfers", ()):
            events.append({
                "name": f"{source}:{direction}:{reason}", "cat": "xfer",
                "ph": "X", "pid": 1, "tid": tid,
                "ts": t0x * 1e6, "dur": max(dur, 0.0) * 1e6,
                "args": {"bytes": nbytes},
            })
        for t, name, flip in rec.get("res_marks", ()):
            events.append({
                "name": f"{name}:residency {flip}", "cat": "xfer",
                "ph": "i", "s": "t", "pid": 1, "tid": tid,
                "ts": t * 1e6,
            })
        return events

    @staticmethod
    def _remote_events(hop: dict, tid) -> List[dict]:
        """One absorbed hop: the network span on the local clock, the
        remote host's element residency spans (offset-mapped marks,
        bounded by the remote send time ``t3``) and its sub-phases,
        names prefixed with the remote host tag."""
        events: List[dict] = []
        host = hop.get("host", "?")
        t_out, t_in = hop["t_out"], hop["t_in"]
        events.append({
            "name": f"{hop.get('link', 'edge')}:net", "cat": "net",
            "ph": "X", "pid": 1, "tid": tid,
            "ts": t_out * 1e6, "dur": (t_in - t_out) * 1e6,
            "args": {"host": host,
                     "rtt_ms": hop["rtt_s"] * 1e3
                     if hop.get("rtt_s") is not None else None,
                     "offset_ms": hop.get("offset_s", 0.0) * 1e3},
        })
        marks = [tuple(m) for m in hop.get("marks", ())]
        end = hop.get("t3", t_in)
        entries = [(t, name) for t, name, phase in marks
                   if phase in (PH_SOURCE, PH_CHAIN_IN)]
        for i, (t, name) in enumerate(entries):
            nxt = entries[i + 1][0] if i + 1 < len(entries) else end
            events.append({
                "name": f"{host}/{name}", "cat": "element", "ph": "X",
                "pid": 1, "tid": tid,
                "ts": t * 1e6, "dur": (nxt - t) * 1e6,
            })
        for ev in LatencyTracer._subphase_events(marks, tid):
            ev["name"] = f"{host}/{ev['name']}"
            events.append(ev)
        return events

    #: sub-phase span grammar: phases that OPEN a span, and for each
    #: closing phase the (opener, span label) pairs it closes.  A phase
    #: may both close one span and open the next (PH_DISPATCH,
    #: PH_INV_DEV); PH_DEMUX closes both the dispatch span and — for
    #: batched paths, where the drain runs per-buffer — the invoke
    #: drain span (the chain path closes it with PH_INV_DONE instead).
    _SPAN_OPENERS = (PH_QUEUE_IN, PH_PARK, PH_DISPATCH,
                     PH_INV_PREP, PH_INV_DEV, PH_INV_DRAIN)
    _SPAN_CLOSERS = {
        PH_QUEUE_OUT: ((PH_QUEUE_IN, "queued"),),
        PH_DISPATCH: ((PH_PARK, "parked"),),
        PH_DEMUX: ((PH_DISPATCH, "dispatch"),
                   (PH_INV_DRAIN, "host-drain")),
        PH_INV_DEV: ((PH_INV_PREP, "host-prep"),),
        PH_INV_DRAIN: ((PH_INV_DEV, "device"),),
        PH_INV_DONE: ((PH_INV_DRAIN, "host-drain"),),
    }

    @staticmethod
    def _subphase_events(marks, tid) -> List[dict]:
        """Queue residency (queue-in → queue-out), batch-window wait
        (park → dispatch → demux) and the dispatch cost-attribution
        split (host-prep → device → host-drain) as finer spans nested
        inside the owning element's residency span."""
        events: List[dict] = []
        open_at: Dict[tuple, float] = {}
        for t, name, phase in marks:
            if phase in LatencyTracer._SPAN_OPENERS:
                open_at[(name, phase)] = t
            for opener, label in LatencyTracer._SPAN_CLOSERS.get(
                    phase, ()):
                t_open = open_at.pop((name, opener), None)
                if t_open is not None:
                    events.append({
                        "name": f"{name}:{label}", "cat": "phase",
                        "ph": "X", "pid": 1, "tid": tid,
                        "ts": t_open * 1e6, "dur": (t - t_open) * 1e6,
                    })
        return events

    def save_chrome_trace(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)


def trace_pipeline(sample_every: int = 1,
                   max_records: int = 4096) -> LatencyTracer:
    """Convenience: build AND attach a tracer in one call (detach with
    ``tracer.uninstall()`` or use :class:`LatencyTracer` as a context
    manager)."""
    return LatencyTracer(sample_every=sample_every,
                         max_records=max_records).install()

"""Runtime lock-order witness ("lockdep", after the kernel facility).

The static analyzer (``analyze/concurrency.py``, NNS6xx) predicts the
lock-acquisition graph; this module *measures* it.  With
``NNS_TPU_LOCKDEP=1`` the :func:`enable` hook wraps the
``threading.Lock``/``threading.RLock`` constructors so every lock whose
construction site lives in this package (or its tests) becomes a
recording proxy:

- every successful acquisition is a node hit, labelled by its
  **construction site** (``file.py:Class.__init__._lock`` — qualname
  plus the assignment target, not a line number, so the witness stays
  stable across unrelated edits yet distinguishes sibling locks);
- acquiring ``B`` while holding ``A`` records the order edge
  ``A -> B`` with the acquiring thread;
- an edge that closes a cycle in the order graph (some other thread
  ever took the locks in the opposite order) is recorded as a
  **violation the moment it happens** — no actual deadlock needed;
- :func:`check_dispatch`, called from the serving-pool window flush,
  records a **held-across-dispatch** violation when the dispatching
  thread holds any witnessed lock (a device invoke under a lock stalls
  every peer for a whole window).

``NNS_TPU_LOCKDEP_OUT=<path>`` dumps the witness JSON at interpreter
exit (or call :func:`dump` yourself).  ``tools/nns_lockdep_diff.py``
diffs a witness against the committed ``tests/lockdep_baseline.json``
and fails CI on any cycle or violation — the dynamic half of the
concurrency gate (Documentation/robustness.md).

Zero-cost when disarmed: nothing is patched until :func:`enable` runs,
and locks constructed outside the package are returned unwrapped.
"""

from __future__ import annotations

import atexit
import json
import linecache
import os
import re
import sys
import threading
import _thread
from typing import Dict, List, Optional, Set, Tuple

__all__ = ["enable", "enabled", "check_dispatch", "dump", "reset",
           "maybe_enable_from_env", "witness_dict", "find_cycles"]

ENABLED = False
#: ``NNS_TPU_LOCKDEP_SCOPE=all`` wraps every construction site (test
#: fixtures, scripts); the default "pkg" wraps only package/tests sites
_SCOPE_ALL = False

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

#: stdlib frames skipped when labelling under scope=all (the lock's
#: *owner* is whoever constructed the Queue/Event, not queue.py)
_STDLIB_SKIP = ("threading.py", "queue.py")

#: assignment target on the construction line — distinguishes two locks
#: built in the same function (``self._lock`` vs ``self._stats_lock``)
#: without baking brittle line numbers into the label
_ASSIGN_RE = re.compile(
    r"^\s*(?:self\.)?([A-Za-z_]\w*)\s*(?::[^=]+)?=[^=]")

#: ``# nns-lock: dispatch-ok`` on the construction line declares the
#: lock is the dispatch SERIALIZATION itself (e.g. the batcher's
#: flush-serial lock) — holding it across the device invoke is the
#: design, so :func:`check_dispatch` exempts it
_DISPATCH_OK_RE = re.compile(r"#\s*nns-lock:[^#]*\bdispatch-ok\b")

#: guards the witness tables; a raw lock so it is never itself wrapped
_WLOCK = _thread.allocate_lock()
_NODES: Dict[str, int] = {}
_EDGES: Dict[Tuple[str, str], dict] = {}
_VIOLATIONS: List[dict] = []
_TLS = threading.local()

#: directories whose frames count as "ours" when labelling a lock site
_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_BASE = os.path.dirname(_PKG_ROOT)
_SELF_FILE = os.path.abspath(__file__)


def _held() -> list:
    h = getattr(_TLS, "held", None)
    if h is None:
        h = _TLS.held = []
    return h


def _thread_name(tid: int) -> str:
    """The thread's name WITHOUT threading.current_thread(): on a
    foreign thread that call constructs a _DummyThread whose Event
    takes a (wrapped) lock — re-entering the witness forever."""
    t = threading._active.get(tid)
    return t.name if t is not None else f"t{tid}"


def _site_label() -> Optional[Tuple[str, bool]]:
    """(label, dispatch-ok) from the first stack frame inside the
    package or its test suite; None for foreign constructions (left
    unwrapped)."""
    f = sys._getframe(2)
    while f is not None:
        fname = f.f_code.co_filename
        if fname == _SELF_FILE:
            # construction triggered from witness internals (e.g. a
            # _DummyThread materialized mid-record): never wrap
            return None
        ours = fname.startswith(_PKG_ROOT) \
            or os.sep + "tests" + os.sep in fname \
            or os.path.basename(fname).startswith("test_")
        if not ours and _SCOPE_ALL:
            ours = os.path.basename(fname) not in _STDLIB_SKIP
        if ours:
            if fname.startswith(_BASE):
                rel = os.path.relpath(fname, _BASE).replace(os.sep, "/")
            else:
                rel = os.path.basename(fname)
            qual = getattr(f.f_code, "co_qualname", None)
            if qual is None:
                qual = f.f_code.co_name
                slf = f.f_locals.get("self")
                if slf is not None:
                    qual = f"{type(slf).__name__}.{qual}"
            line = linecache.getline(fname, f.f_lineno)
            m = _ASSIGN_RE.match(line)
            which = m.group(1) if m else f"L{f.f_lineno}"
            return (f"{rel}:{qual}.{which}",
                    _DISPATCH_OK_RE.search(line) is not None)
        f = f.f_back
    return None


def _record_acquire(proxy, label: str) -> None:
    if getattr(_TLS, "busy", False):  # re-entered mid-record: bail
        return
    _TLS.busy = True
    try:
        _record_acquire_inner(proxy, label)
    finally:
        _TLS.busy = False


def _record_acquire_inner(proxy, label: str) -> None:
    held = _held()
    if any(e[0] is proxy for e in held):
        held.append((proxy, label, True))  # reentrant: no new edges
        return
    tid = threading.get_ident()
    tname = _thread_name(tid)
    with _WLOCK:
        _NODES[label] = _NODES.get(label, 0) + 1
        for _p, hlabel, _re in held:
            if hlabel == label:
                continue
            key = (hlabel, label)
            e = _EDGES.get(key)
            if e is None:
                _EDGES[key] = {"count": 1, "threads": {tname},
                               "tids": {tid}}
                cyc = _closes_cycle(hlabel, label)
                if cyc is not None:
                    _VIOLATIONS.append({
                        "kind": "cycle",
                        "edge": [hlabel, label],
                        "path": cyc,
                        "thread": tname, "tid": tid})
            else:
                e["count"] += 1
                e["threads"].add(tname)
                e["tids"].add(tid)
    held.append((proxy, label, False))


def _closes_cycle(src: str, dst: str) -> Optional[List[str]]:
    """Path dst ->* src in the edge graph (callers hold _WLOCK) — if it
    exists, the new src->dst edge closed a cycle."""
    adj: Dict[str, List[str]] = {}
    for (a, b) in _EDGES:
        if a != b:
            adj.setdefault(a, []).append(b)
    stack: List[Tuple[str, List[str]]] = [(dst, [dst])]
    visited: Set[str] = {dst}
    while stack:
        node, path = stack.pop()
        if node == src:
            return [src, dst] + path[1:]
        for nxt in adj.get(node, ()):
            if nxt not in visited:
                visited.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _record_release(proxy) -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] is proxy:
            del held[i]
            return


class _LockProxy:
    """Wraps a real ``threading.Lock`` and reports to the witness."""

    _KIND = "Lock"
    __slots__ = ("_lk", "_label", "_dok", "__weakref__")

    def __init__(self, real, label: str, dispatch_ok: bool = False):
        self._lk = real
        self._label = label
        self._dok = dispatch_ok

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._lk.acquire(blocking, timeout)
        if ok:
            _record_acquire(self, self._label)
        return ok

    acquire_lock = acquire  # old-style alias some callers use

    def release(self):
        _record_release(self)
        self._lk.release()

    release_lock = release

    def locked(self):
        return self._lk.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<lockdep {self._KIND} {self._label} of {self._lk!r}>"


class _RLockProxy(_LockProxy):
    """RLock flavour: also speaks the private Condition protocol
    (``_is_owned``/``_release_save``/``_acquire_restore``) so wrapped
    RLocks keep working as Condition backing locks — a Condition.wait
    fully releases the lock, so the held-stack entries drop with it."""

    _KIND = "RLock"
    __slots__ = ()

    def _is_owned(self):
        return self._lk._is_owned()

    def _release_save(self):
        held = _held()
        n = 0
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is self:
                del held[i]
                n += 1
        return (self._lk._release_save(), n)

    def _acquire_restore(self, saved):
        state, n = saved
        self._lk._acquire_restore(state)
        held = _held()
        for i in range(n):
            # re-entry after a wait: the original acquisition already
            # recorded the order edges, so restore silently
            held.append((self, self._label, i > 0))

    def __exit__(self, *exc):
        self.release()
        return False


def _wrap_lock():
    real = _REAL_LOCK()
    if getattr(_TLS, "busy", False):
        return real
    site = _site_label()
    if site is None:
        return real
    return _LockProxy(real, site[0], site[1])


def _wrap_rlock():
    real = _REAL_RLOCK()
    if getattr(_TLS, "busy", False):
        return real
    site = _site_label()
    if site is None:
        return real
    return _RLockProxy(real, site[0], site[1])


# -- public API --------------------------------------------------------------


def enable() -> bool:
    """Patch the lock constructors.  Idempotent; affects only locks
    constructed *after* the call whose construction site is inside the
    package or its tests.  (``threading.Condition()`` picks the patched
    RLock up automatically — it resolves ``RLock`` from the module at
    call time.)"""
    global ENABLED, _SCOPE_ALL
    if os.environ.get("NNS_TPU_LOCKDEP_SCOPE", "") == "all":
        _SCOPE_ALL = True
    if ENABLED:
        return False
    ENABLED = True
    threading.Lock = _wrap_lock
    threading.RLock = _wrap_rlock
    return True


def enabled() -> bool:
    return ENABLED


def check_dispatch(what: str) -> bool:
    """Call at a device-dispatch fence: records a held-across-dispatch
    violation (and returns True) when the calling thread holds any
    witnessed lock."""
    if not ENABLED:
        return False
    held = [label for p, label, re in _held()
            if not re and not getattr(p, "_dok", False)]
    if not held:
        return False
    tid = threading.get_ident()
    with _WLOCK:
        _VIOLATIONS.append({
            "kind": "held-across-dispatch",
            "what": what,
            "held": held,
            "thread": _thread_name(tid),
            "tid": tid})
    return True


def find_cycles(edges) -> List[List[str]]:
    """All distinct cycles (by node set) in ``[(src, dst), ...]``."""
    adj: Dict[str, List[str]] = {}
    for a, b in edges:
        if a != b:
            adj.setdefault(a, []).append(b)
    seen: Set[frozenset] = set()
    out: List[List[str]] = []
    for a, b in sorted(set((a, b) for a, b in edges if a != b)):
        stack: List[Tuple[str, List[str]]] = [(b, [b])]
        visited = {b}
        found = None
        while stack:
            node, path = stack.pop()
            if node == a:
                found = path
                break
            for nxt in adj.get(node, ()):
                if nxt not in visited:
                    visited.add(nxt)
                    stack.append((nxt, path + [nxt]))
        if found is None:
            continue
        cyc = [a] + found  # found = [b, ..., a], so cyc closes at a
        key = frozenset(cyc)
        if key not in seen:
            seen.add(key)
            out.append(cyc)
    return out


def witness_dict() -> dict:
    """The witness as a JSON-ready dict (sorted, deterministic)."""
    with _WLOCK:
        nodes = [{"label": k, "count": v}
                 for k, v in sorted(_NODES.items())]
        edges = [{"src": a, "dst": b, "count": e["count"],
                  "threads": sorted(e["threads"]),
                  "tids": sorted(e["tids"])}
                 for (a, b), e in sorted(_EDGES.items())]
        violations = list(_VIOLATIONS)
        cycles = find_cycles(list(_EDGES))
    return {"version": 1, "nodes": nodes, "edges": edges,
            "violations": violations, "cycles": cycles}


def dump(path: str) -> dict:
    doc = witness_dict()
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return doc


def reset() -> None:
    """Clear the witness tables (tests)."""
    with _WLOCK:
        _NODES.clear()
        _EDGES.clear()
        del _VIOLATIONS[:]


def maybe_enable_from_env() -> bool:
    """``NNS_TPU_LOCKDEP=1`` arms the witness; ``NNS_TPU_LOCKDEP_OUT``
    additionally dumps the witness JSON at interpreter exit."""
    if os.environ.get("NNS_TPU_LOCKDEP", "") not in ("1", "true", "on"):
        return False
    armed = enable()
    out = os.environ.get("NNS_TPU_LOCKDEP_OUT", "")
    if armed and out:
        atexit.register(dump, out)
    return armed


# -- baseline diff (tools/nns_lockdep_diff.py shim) --------------------------


def _fmt_cycle(path: List[str]) -> str:
    return " -> ".join(path)


def diff_main(argv: Optional[List[str]] = None) -> int:
    """Diff a lockdep witness against the committed baseline.

    Exit 0 when the witness is non-empty, free of violations, and its
    cycles are all listed in the baseline's ``allowed_cycles``; exit 1
    on any cycle / violation / empty witness; exit 2 on usage errors.
    Edges absent from the baseline are reported informationally — the
    order graph may legitimately grow, only *cycles* are bugs.
    """
    import argparse

    p = argparse.ArgumentParser(
        prog="nns-lockdep-diff",
        description="diff a lockdep witness JSON against the committed "
                    "baseline (tests/lockdep_baseline.json)")
    p.add_argument("witness", help="witness JSON produced via "
                   "NNS_TPU_LOCKDEP_OUT or lockdep.dump()")
    p.add_argument("--baseline",
                   default=os.path.join(_BASE, "tests",
                                        "lockdep_baseline.json"),
                   help="baseline JSON (default: tests/lockdep_baseline"
                        ".json next to the package)")
    p.add_argument("--update", action="store_true",
                   help="rewrite the baseline from this witness instead "
                        "of diffing (refuses while violations exist)")
    args = p.parse_args(argv)

    try:
        with open(args.witness, "r", encoding="utf-8") as f:
            wit = json.load(f)
    except (OSError, ValueError) as exc:
        print(f"nns-lockdep-diff: cannot read witness: {exc}",
              file=sys.stderr)
        return 2

    nodes = wit.get("nodes") or []
    edges = wit.get("edges") or []
    cycles = wit.get("cycles") or []
    violations = wit.get("violations") or []

    if not nodes:
        print("nns-lockdep-diff: FAIL: witness is empty (no lock "
              "acquisitions recorded) — was NNS_TPU_LOCKDEP=1 set "
              "before the package imported?", file=sys.stderr)
        return 1

    if args.update:
        if violations or cycles:
            print("nns-lockdep-diff: refusing --update: witness has "
                  f"{len(violations)} violation(s) / {len(cycles)} "
                  "cycle(s); fix them first", file=sys.stderr)
            return 1
        base = {
            "version": 1,
            "edges": sorted([e["src"], e["dst"]] for e in edges),
            "allowed_cycles": [],
        }
        tmp = args.baseline + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(base, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, args.baseline)
        print(f"nns-lockdep-diff: baseline updated: {args.baseline} "
              f"({len(nodes)} nodes, {len(edges)} edges)")
        return 0

    try:
        with open(args.baseline, "r", encoding="utf-8") as f:
            base = json.load(f)
    except (OSError, ValueError) as exc:
        print(f"nns-lockdep-diff: cannot read baseline: {exc} "
              "(generate one with --update)", file=sys.stderr)
        return 2

    rc = 0
    allowed = {frozenset(c) for c in base.get("allowed_cycles", [])}
    for cyc in cycles:
        if frozenset(cyc) in allowed:
            continue
        rc = 1
        print(f"LOCK-ORDER CYCLE: {_fmt_cycle(cyc)}")
        # print the witnessed acquisition edges that make up the cycle
        ring = set(zip(cyc, cyc[1:]))
        for e in edges:
            if (e["src"], e["dst"]) in ring:
                print(f"  edge {e['src']} -> {e['dst']} "
                      f"(count={e['count']}, "
                      f"threads={','.join(e['threads'])})")
    for v in violations:
        if v.get("kind") == "cycle" and frozenset(v["path"]) in allowed:
            continue
        rc = 1
        if v.get("kind") == "cycle":
            print(f"VIOLATION cycle (thread {v['thread']}): "
                  f"{_fmt_cycle(v['path'])}")
        elif v.get("kind") == "held-across-dispatch":
            print(f"VIOLATION held-across-dispatch at {v['what']} "
                  f"(thread {v['thread']}): holding "
                  f"{', '.join(v['held'])}")
        else:
            print(f"VIOLATION {v}")

    known = {tuple(e) for e in base.get("edges", [])}
    new_edges = [e for e in edges
                 if (e["src"], e["dst"]) not in known]
    if new_edges:
        print(f"note: {len(new_edges)} order edge(s) not in baseline "
              "(informational; rerun with --update to absorb):")
        for e in new_edges:
            print(f"  {e['src']} -> {e['dst']}")

    if rc:
        print(f"nns-lockdep-diff: FAIL ({len(nodes)} nodes, "
              f"{len(edges)} edges)", file=sys.stderr)
    else:
        print(f"nns-lockdep-diff: OK ({len(nodes)} nodes, "
              f"{len(edges)} edges, {len(new_edges)} new)")
    return rc

"""``tensor_src_sensor`` — sensor device → tensor stream.

Parity target: /root/reference/gst/nnstreamer/elements/gsttensor_srciio.c
(2603 LoC): Linux IIO sources with channel enable/auto discovery
(``scan_elements/*_en``), ``frequency``, ``merge-channels-data``,
``buffer-capacity``, and raw vs processed (scale/offset applied) values.
The reference's own unit tests drive it against a mock sysfs tree
(tests/nnstreamer_source/unittest_src_iio.cc) — the same contract this
element exposes through ``device-dir``.

Two backends:
- the default file-backed IIO reader (``device_dir=`` points at an IIO
  sysfs-style directory with ``in_<name>_raw`` value files, optional
  ``in_<name>_scale`` / ``in_<name>_offset`` and
  ``scan_elements/in_<name>_en`` enables);
- a registered Python callable (``register_sensor``/``sensor=NAME``)
  returning one sample vector per call — the hook for platform sensor
  frameworks (the Tizen sensor-fw analog, tensor_src_tizensensor.c).

Output: ``merge_channels_data=True`` (reference default) emits ONE
float32 tensor of shape (buffer_capacity, n_channels); ``False`` emits
one (buffer_capacity,) tensor per channel.  ``frequency`` paces
production; pts is synthesized from the sample clock.
"""

from __future__ import annotations

import os
import re
import threading
import time
from fractions import Fraction
from typing import Callable, Dict, List, Optional

import numpy as np

from ..core import SECOND, Buffer, Tensor, TensorSpec, TensorsSpec
from ..runtime.element import NegotiationError, SourceElement
from ..runtime.registry import register_element

_sensors: Dict[str, Callable[[], "np.ndarray"]] = {}
_sensors_lock = threading.Lock()


def register_sensor(name: str, fn: Callable[[], "np.ndarray"]) -> str:
    """Register ``fn() -> (n_channels,) array`` as a named sensor."""
    with _sensors_lock:
        _sensors[name] = fn
    return name


def unregister_sensor(name: str) -> None:
    with _sensors_lock:
        _sensors.pop(name, None)


class _IIOChannel:
    __slots__ = ("name", "raw_path", "scale", "offset")

    def __init__(self, name: str, raw_path: str, scale: float,
                 offset: float):
        self.name, self.raw_path = name, raw_path
        self.scale, self.offset = scale, offset

    def read(self, process: bool) -> float:
        with open(self.raw_path) as f:
            v = float(f.read().strip() or 0)
        return (v + self.offset) * self.scale if process else v


def _read_float(path: str, default: float) -> float:
    try:
        with open(path) as f:
            return float(f.read().strip())
    except (OSError, ValueError):
        return default


def _scan_iio_dir(device_dir: str, channels: str) -> List[_IIOChannel]:
    """Discover ``in_<name>_raw`` channels; ``channels`` is ``auto``
    (honor scan_elements enables), ``all``, or a comma list of names."""
    pat = re.compile(r"^in_(.+)_raw$")
    found = []
    for fn in sorted(os.listdir(device_dir)):
        m = pat.match(fn)
        if not m:
            continue
        name = m.group(1)
        en_path = os.path.join(device_dir, "scan_elements",
                               f"in_{name}_en")
        if channels == "auto" and os.path.isfile(en_path):
            if _read_float(en_path, 1) == 0:
                continue
        elif channels not in ("auto", "all"):
            wanted = {c.strip() for c in channels.split(",") if c.strip()}
            if name not in wanted:
                continue
        found.append(_IIOChannel(
            name, os.path.join(device_dir, fn),
            scale=_read_float(os.path.join(device_dir,
                                           f"in_{name}_scale"), 1.0),
            offset=_read_float(os.path.join(device_dir,
                                            f"in_{name}_offset"), 0.0)))
    return found


@register_element("tensor_src_sensor")
class TensorSrcSensor(SourceElement):
    FACTORY = "tensor_src_sensor"

    def __init__(self, name=None, device_dir: str = "", sensor: str = "",
                 channels: str = "auto", frequency: float = 0.0,
                 merge_channels_data: bool = True,
                 buffer_capacity: int = 1, process: bool = True,
                 num_buffers: int = 0, **props):
        self.device_dir = device_dir
        self.sensor = sensor
        self.channels = channels
        self.frequency = frequency
        self.merge_channels_data = merge_channels_data
        self.buffer_capacity = buffer_capacity
        self.process = process
        self.num_buffers = num_buffers
        super().__init__(name, **props)
        self._chans: List[_IIOChannel] = []
        self._fn: Optional[Callable] = None
        self._nch = 0
        self._count = 0
        self._t0: Optional[float] = None

    # -- discovery / negotiation ---------------------------------------------

    def _discover(self) -> None:
        if self.sensor:
            with _sensors_lock:
                self._fn = _sensors.get(str(self.sensor))
            if self._fn is None:
                raise NegotiationError(
                    f"{self.name}: no sensor registered as "
                    f"{self.sensor!r}")
            self._nch = int(np.asarray(self._fn()).reshape(-1).shape[0])
            return
        if not self.device_dir:
            raise NegotiationError(
                f"{self.name}: set device-dir (IIO sysfs directory) or "
                "sensor (registered callable)")
        if not os.path.isdir(self.device_dir):
            raise NegotiationError(
                f"{self.name}: device dir not found: {self.device_dir}")
        # sampling_frequency file is the device default; the property
        # overrides it (parity: srciio frequency prop)
        if not self.frequency:
            self.frequency = _read_float(
                os.path.join(self.device_dir, "sampling_frequency"), 0.0)
        self._chans = _scan_iio_dir(self.device_dir, str(self.channels))
        if not self._chans:
            raise NegotiationError(
                f"{self.name}: no channels found in {self.device_dir} "
                f"(channels={self.channels!r})")
        self._nch = len(self._chans)

    def output_spec(self) -> TensorsSpec:
        self._discover()
        cap = max(int(self.buffer_capacity), 1)
        freq = Fraction(self.frequency).limit_denominator(10 ** 6) \
            if self.frequency else Fraction(0, 1)
        rate = freq / cap if freq else Fraction(0, 1)
        if self.merge_channels_data:
            return TensorsSpec.of(
                TensorSpec.from_shape((cap, self._nch), np.float32),
                rate=rate)
        return TensorsSpec.of(
            *[TensorSpec.from_shape((cap,), np.float32, name=c.name)
              for c in self._chans], rate=rate)

    # -- production ----------------------------------------------------------

    def _sample(self) -> np.ndarray:
        if self._fn is not None:
            return np.asarray(self._fn(), np.float32).reshape(-1)
        return np.array([c.read(bool(self.process)) for c in self._chans],
                        np.float32)

    def create(self) -> Optional[Buffer]:
        n = int(self.num_buffers)
        if n and self._count >= n:
            return None
        cap = max(int(self.buffer_capacity), 1)
        period = 1.0 / float(self.frequency) if self.frequency else 0.0
        if self._t0 is None:
            self._t0 = time.monotonic()
        rows = []
        for i in range(cap):
            if period:
                target = self._t0 + (self._count * cap + i) * period
                delay = target - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
            if not self._running.is_set():
                return None
            rows.append(self._sample())
        block = np.stack(rows)  # (cap, nch)
        pts = int(self._count * cap * (period or 0) * SECOND)
        self._count += 1
        if self.merge_channels_data:
            tensors = [Tensor(block, TensorSpec.from_shape(
                block.shape, np.float32))]
        else:
            tensors = [Tensor(np.ascontiguousarray(block[:, j]),
                              TensorSpec.from_shape((cap,), np.float32,
                                                    name=c.name))
                       for j, c in enumerate(self._chans)]
        return Buffer(tensors=tensors, pts=pts)

    def start(self) -> None:
        self._count = 0
        self._t0 = None
        super().start()

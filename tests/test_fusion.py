"""Transform↔filter fusion pass (runtime/fusion.py, SURVEY §7 stage 4):
a run of tensor_transform elements + a jax-xla tensor_filter compiles
into one XLA computation, with outputs identical to the unfused pipeline.
"""

from fractions import Fraction

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.elements.basic import AppSink, AppSrc
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.elements.transform import TensorTransform
from nnstreamer_tpu.filters.jax_xla import register_model, unregister_model
from nnstreamer_tpu.runtime import Pipeline


@pytest.fixture
def linear_model():
    import jax.numpy as jnp

    w = np.linspace(-1, 1, 12, dtype=np.float32).reshape(4, 3)

    def fn(params, x):
        return jnp.dot(x, params)

    name = register_model("fusion_linear", fn, params=w,
                          in_shapes=[(2, 4)], in_dtypes=np.float32)
    yield name
    unregister_model(name)


def run_pipeline(fuse: bool, model: str, arr: np.ndarray,
                 transforms=None):
    spec = TensorsSpec.from_shapes([arr.shape], arr.dtype,
                                   rate=Fraction(30))
    p = Pipeline(fuse=fuse)
    src = AppSrc(name="src", spec=spec)
    ts = transforms or [TensorTransform(
        name="norm", mode="arithmetic",
        option="typecast:float32,add:-127.5,div:127.5")]
    flt = TensorFilter(name="net", framework="jax-xla", model=model)
    sink = AppSink(name="out")
    p.add(src, *ts, flt, sink).link(src, *ts, flt, sink)
    with p:
        src.push_buffer(Buffer.of(arr, pts=0))
        src.end_of_stream()
        assert p.wait_eos(timeout=120)
        got = sink.pull(timeout=1)
    return got, ts, flt


class TestFusionCorrectness:
    def test_fused_matches_unfused(self, linear_model):
        arr = np.arange(8, dtype=np.uint8).reshape(2, 4)
        fused, ts_f, flt_f = run_pipeline(True, linear_model, arr)
        unfused, ts_u, flt_u = run_pipeline(False, linear_model, arr)
        assert all(t._fused for t in ts_f)
        assert flt_f._fused_pre and not flt_u._fused_pre
        assert not any(t._fused for t in ts_u)
        np.testing.assert_allclose(fused.tensors[0].np(),
                                   unfused.tensors[0].np(), rtol=1e-6)

    def test_multi_transform_run_fuses(self, linear_model):
        # transpose (2,4)<-(4,2) then normalize: two transforms, one program
        arr = np.arange(8, dtype=np.uint8).reshape(4, 2)
        ts = [
            TensorTransform(name="tr", mode="transpose", option="1:0:2:3"),
            TensorTransform(name="norm", mode="arithmetic",
                            option="typecast:float32,add:-127.5,div:127.5"),
        ]
        fused, ts_f, flt = run_pipeline(True, linear_model, arr,
                                        transforms=ts)
        assert len(flt._fused_pre) == 2
        ts_u = [
            TensorTransform(name="tr", mode="transpose", option="1:0:2:3"),
            TensorTransform(name="norm", mode="arithmetic",
                            option="typecast:float32,add:-127.5,div:127.5"),
        ]
        unfused, _, _ = run_pipeline(False, linear_model, arr,
                                     transforms=ts_u)
        # same program modulo fusion; matmul precision (bf16 on TPU)
        # is identical on both paths
        np.testing.assert_allclose(fused.tensors[0].np(),
                                   unfused.tensors[0].np(), rtol=1e-6)

    def test_same_dtype_chain_still_recompiles(self, linear_model):
        # float32→float32 chain: raw spec is caps-compatible with the
        # model's declared input, fusion must still specialize (the
        # compatible-spec shortcut would silently skip the prologue)
        arr = np.full((2, 4), 127.5 + 12.75, np.float32)
        fused, _, flt = run_pipeline(
            True, linear_model, arr,
            transforms=[TensorTransform(name="n", mode="arithmetic",
                                        option="add:-127.5,div:127.5")])
        assert flt._fused_pre
        unfused, _, _ = run_pipeline(
            False, linear_model, arr,
            transforms=[TensorTransform(name="n", mode="arithmetic",
                                        option="add:-127.5,div:127.5")])
        np.testing.assert_allclose(fused.tensors[0].np(),
                                   unfused.tensors[0].np(), rtol=1e-6)
        # and the prologue really ran: output differs from the un-normalized
        raw, _, _ = run_pipeline(False, linear_model, arr, transforms=[
            TensorTransform(name="n", mode="arithmetic", option="mul:1.0")])
        assert not np.allclose(fused.tensors[0].np(), raw.tensors[0].np())


class TestDecoderOverlayFusion:
    """Filter→decoder fusion (round-3 verdict #10): the bounding-box
    device overlay compiles INTO the filter's program — one dispatch
    for transform+model+NMS+overlay — with bytes identical to the
    unfused device path."""

    @pytest.fixture
    def detect_model(self):
        import jax.numpy as jnp

        def fn(x):
            # deterministic toy detector: 2 boxes per frame
            b = x.shape[0]
            boxes = jnp.tile(jnp.asarray(
                [[0.1, 0.1, 0.5, 0.5], [0.4, 0.4, 0.9, 0.9]],
                jnp.float32)[None], (b, 1, 1))
            classes = jnp.tile(jnp.asarray([1.0, 2.0])[None], (b, 1))
            scores = jnp.tile(jnp.asarray([0.9, 0.8])[None], (b, 1))
            num = jnp.full((b,), 2, jnp.int32)
            return boxes, classes, scores, num

        name = register_model("fusion_detect", fn,
                              in_shapes=[(2, 16, 16, 3)],
                              in_dtypes=np.float32)
        yield name
        unregister_model(name)

    def _run(self, fuse, model):
        from nnstreamer_tpu.elements.decoder import TensorDecoder

        spec = TensorsSpec.from_shapes([(2, 16, 16, 3)], np.float32,
                                       rate=Fraction(30))
        p = Pipeline(fuse=fuse)
        src = AppSrc(name="src", spec=spec)
        flt = TensorFilter(name="net", framework="jax-xla", model=model)
        dec = TensorDecoder(name="dec", mode="bounding_boxes",
                            option1="mobilenet-ssd-postprocess",
                            option4="32:32", option5="32:32",
                            option7="device")
        sink = AppSink(name="out")
        p.add(src, flt, dec, sink).link(src, flt, dec, sink)
        with p:
            src.push_buffer(Buffer.of(
                np.zeros((2, 16, 16, 3), np.float32)))
            src.end_of_stream()
            assert p.wait_eos(timeout=120)
            got = sink.pull(timeout=1)
            post_active = bool(flt._fused_post)
        return got, post_active

    def test_fused_matches_unfused_device_overlay(self, detect_model):
        fused, on = self._run(True, detect_model)
        unfused, off = self._run(False, detect_model)
        assert on and not off
        np.testing.assert_array_equal(fused[0].np(), unfused[0].np())
        assert fused[0].np().shape == (2, 32, 32, 4)
        # structured detections survive fusion as device arrays
        assert "detections_device" in fused.meta
        dd = fused.meta["detections_device"]
        assert np.asarray(dd["num"]).tolist() == [2, 2]

    def test_tee_between_filter_and_decoder_blocks_fusion(
            self, detect_model):
        from nnstreamer_tpu.elements.decoder import TensorDecoder
        from nnstreamer_tpu.runtime.registry import make

        spec = TensorsSpec.from_shapes([(2, 16, 16, 3)], np.float32,
                                       rate=Fraction(30))
        p = Pipeline(fuse=True)
        src = AppSrc(name="src", spec=spec)
        flt = TensorFilter(name="net", framework="jax-xla",
                           model=detect_model)
        tee = make("tee", el_name="t")
        dec = TensorDecoder(name="dec", mode="bounding_boxes",
                            option1="mobilenet-ssd-postprocess",
                            option4="32:32", option5="32:32",
                            option7="device")
        sink = AppSink(name="out")
        sink2 = AppSink(name="raw")
        p.add(src, flt, tee, dec, sink, sink2)
        p.link(src, flt, tee)
        p.link(tee, dec, sink)
        p.link(tee, sink2)
        with p:
            src.push_buffer(Buffer.of(
                np.zeros((2, 16, 16, 3), np.float32)))
            src.end_of_stream()
            assert p.wait_eos(timeout=120)
            assert not flt._fused_post  # tee consumer blocks fusion
            out = sink.pull(timeout=1)
        assert out[0].np().shape == (2, 32, 32, 4)

    def test_single_frame_no_num_model_fuses(self):
        """The epilogue accepts every layout the unfused device path
        accepts: single-frame (N,4) boxes and 3-output (no num) models
        (review finding: fusion must not reject what unfused ran)."""
        import jax.numpy as jnp

        from nnstreamer_tpu.elements.decoder import TensorDecoder

        def fn(x):
            boxes = jnp.asarray([[0.2, 0.2, 0.6, 0.6]], jnp.float32)
            return boxes, jnp.asarray([1.0]), jnp.asarray([0.9])

        register_model("fusion_detect_n4", fn, in_shapes=[(1, 8, 8, 3)],
                       in_dtypes=np.float32)
        try:
            outs = {}
            for fuse in (True, False):
                spec = TensorsSpec.from_shapes([(1, 8, 8, 3)], np.float32,
                                               rate=Fraction(30))
                p = Pipeline(fuse=fuse)
                src = AppSrc(name="src", spec=spec)
                flt = TensorFilter(name="net", framework="jax-xla",
                                   model="fusion_detect_n4")
                dec = TensorDecoder(name="dec", mode="bounding_boxes",
                                    option1="mobilenet-ssd-postprocess",
                                    option4="32:32", option5="32:32",
                                    option7="device")
                sink = AppSink(name="out")
                p.add(src, flt, dec, sink).link(src, flt, dec, sink)
                with p:
                    src.push_buffer(Buffer.of(
                        np.zeros((1, 8, 8, 3), np.float32)))
                    src.end_of_stream()
                    assert p.wait_eos(timeout=120)
                    outs[fuse] = sink.pull(timeout=1)
                    if fuse:
                        assert flt._fused_post
            np.testing.assert_array_equal(outs[True][0].np(),
                                          outs[False][0].np())
            assert outs[True][0].np().shape == (32, 32, 4)  # unbatched
        finally:
            unregister_model("fusion_detect_n4")

    def test_flexible_stream_withdraws_decoder_fusion(self, detect_model):
        """Per-buffer schemas can't pre-compile an overlay epilogue: the
        filter must withdraw the decoder fusion at negotiation and the
        decoder must render for itself (review finding: a stale
        fused_upstream flag would emit raw boxes as 'video')."""
        from nnstreamer_tpu.core import TensorFormat
        from nnstreamer_tpu.elements.decoder import TensorDecoder

        flex = TensorsSpec(format=TensorFormat.FLEXIBLE, rate=Fraction(30))
        p = Pipeline(fuse=True)
        src = AppSrc(name="src", spec=flex)
        flt = TensorFilter(name="net", framework="jax-xla",
                           model=detect_model, invoke_dynamic=False)
        dec = TensorDecoder(name="dec", mode="bounding_boxes",
                            option1="mobilenet-ssd-postprocess",
                            option4="32:32", option5="32:32",
                            option7="device")
        sink = AppSink(name="out")
        p.add(src, flt, dec, sink).link(src, flt, dec, sink)
        with p:
            src.push_buffer(Buffer.of(
                np.zeros((2, 16, 16, 3), np.float32)))
            src.end_of_stream()
            assert p.wait_eos(timeout=120)
            got = sink.pull(timeout=1)
            assert not flt._fused_post       # withdrew at negotiation
            assert not dec._decoder().fused_upstream
        # the decoder rendered for itself: real canvas, right dtype
        assert got[0].np().shape == (2, 32, 32, 4)
        assert got[0].np().dtype == np.uint8
        assert "detections_device" in got.meta

    def test_host_backend_not_fused(self, detect_model):
        from nnstreamer_tpu.elements.decoder import TensorDecoder

        spec = TensorsSpec.from_shapes([(2, 16, 16, 3)], np.float32,
                                       rate=Fraction(30))
        p = Pipeline(fuse=True)
        src = AppSrc(name="src", spec=spec)
        flt = TensorFilter(name="net", framework="jax-xla",
                           model=detect_model)
        dec = TensorDecoder(name="dec", mode="bounding_boxes",
                            option1="mobilenet-ssd-postprocess",
                            option4="32:32", option5="32:32")
        sink = AppSink(name="out")
        p.add(src, flt, dec, sink).link(src, flt, dec, sink)
        with p:
            src.push_buffer(Buffer.of(
                np.zeros((2, 16, 16, 3), np.float32)))
            src.end_of_stream()
            assert p.wait_eos(timeout=120)
            assert not flt._fused_post


class TestFusionGuards:
    def test_flexible_stream_unfuses(self, linear_model):
        """Per-buffer schemas can't pre-compile a prologue: the transform
        must withdraw from fusion at negotiation and run its chain itself
        (silent-drop regression: review finding r2)."""
        from nnstreamer_tpu.core import TensorFormat

        flex = TensorsSpec(format=TensorFormat.FLEXIBLE, rate=Fraction(30))
        p = Pipeline(fuse=True)
        src = AppSrc(name="src", spec=flex)
        t = TensorTransform(name="n", mode="arithmetic",
                            option="typecast:float32,add:-127.5,div:127.5")
        flt = TensorFilter(name="net", framework="jax-xla",
                           model=linear_model)
        sink = AppSink(name="out")
        p.add(src, t, flt, sink).link(src, t, flt, sink)
        arr = np.arange(8, dtype=np.uint8).reshape(2, 4)
        with p:
            src.push_buffer(Buffer.of(arr))
            src.end_of_stream()
            assert p.wait_eos(timeout=120)
            got = sink.pull(timeout=1)
        assert not t._fused           # withdrew during negotiation
        assert not flt._fused_pre     # chain returned to the transform
        # the normalize REALLY ran (raw uint8 would give a far bigger dot)
        unfused, _, _ = run_pipeline(False, linear_model,
                                     arr.astype(np.uint8))
        np.testing.assert_allclose(got.tensors[0].np(),
                                   unfused.tensors[0].np(), rtol=1e-6)

    def test_restart_rederives_fusion_state(self, linear_model):
        """Marks are reset each start: a transform reused in a fuse=False
        pipeline must not stay passthrough (one-way-latch regression)."""
        arr = np.arange(8, dtype=np.uint8).reshape(2, 4)
        fused, ts, _ = run_pipeline(True, linear_model, arr)
        t = ts[0]
        assert t._fused
        # reuse the same transform element in a fresh unfused pipeline
        t.sinkpad.unlink()
        t.srcpad.unlink()
        spec = TensorsSpec.from_shapes([arr.shape], arr.dtype,
                                       rate=Fraction(30))
        p = Pipeline(fuse=False)
        src = AppSrc(name="src", spec=spec)
        sink = AppSink(name="out")
        p.add(src, t, sink).link(src, t, sink)
        with p:
            src.push_buffer(Buffer.of(arr))
            src.end_of_stream()
            assert p.wait_eos(timeout=90)  # first jit can queue on device
            got = sink.pull(timeout=1)
        assert not t._fused
        want = (arr.astype(np.float32) - 127.5) / 127.5
        np.testing.assert_allclose(got.tensors[0].np(), want, rtol=1e-6)

    def test_custom_framework_not_fused(self):
        from nnstreamer_tpu.filters.custom import register_custom_easy

        register_custom_easy("fusion_passthrough", lambda xs: xs,
                             in_spec=TensorsSpec.from_shapes(
                                 [(2, 4)], np.float32),
                             out_spec=TensorsSpec.from_shapes(
                                 [(2, 4)], np.float32))
        spec = TensorsSpec.from_shapes([(2, 4)], np.float32,
                                       rate=Fraction(30))
        p = Pipeline(fuse=True)
        src = AppSrc(name="src", spec=spec)
        t = TensorTransform(name="n", mode="arithmetic", option="mul:2.0")
        flt = TensorFilter(name="net", framework="custom-easy",
                           model="fusion_passthrough")
        sink = AppSink(name="out")
        p.add(src, t, flt, sink).link(src, t, flt, sink)
        arr = np.ones((2, 4), np.float32)
        with p:
            src.push_buffer(Buffer.of(arr))
            src.end_of_stream()
            assert p.wait_eos(timeout=10)
            got = sink.pull(timeout=1)
        assert not t._fused and not flt._fused_pre
        np.testing.assert_allclose(got.tensors[0].np(), arr * 2.0)

    def test_tee_mid_run_limits_fusion(self, linear_model):
        """A transform whose OUTPUT also feeds a second consumer cannot
        be folded away; the pass must stop the run there."""
        from nnstreamer_tpu.elements.basic import Tee

        spec = TensorsSpec.from_shapes([(2, 4)], np.uint8,
                                       rate=Fraction(30))
        p = Pipeline(fuse=True)
        src = AppSrc(name="src", spec=spec)
        t1 = TensorTransform(name="t1", mode="arithmetic",
                             option="typecast:float32,div:127.5")
        tee = Tee(name="tee")
        t2 = TensorTransform(name="t2", mode="arithmetic",
                             option="mul:1.0")
        flt = TensorFilter(name="net", framework="jax-xla",
                           model=linear_model)
        sink = AppSink(name="out")
        side = AppSink(name="side")
        p.add(src, t1, tee, t2, flt, sink, side)
        p.link(src, t1, tee)
        p.link_pads("tee", "src_0", "t2", "sink")
        p.link(t2, flt, sink)
        p.link_pads("tee", "src_1", "side", "sink")
        arr = np.arange(8, dtype=np.uint8).reshape(2, 4)
        with p:
            src.push_buffer(Buffer.of(arr))
            src.end_of_stream()
            assert p.wait_eos(timeout=120)
            got = sink.pull(timeout=1)
        # t2 (downstream of the tee) may fuse; t1 must NOT
        assert not t1._fused
        assert got is not None


class TestFusedSegmentCapture:
    """Whole-graph capture: Pipeline.start() records a FusedSegment
    descriptor per collapsed segment, carrying the ordered chain digest
    the persistent compile cache keys on."""

    def test_prologue_segment_descriptor(self, linear_model):
        arr = np.arange(8, dtype=np.uint8).reshape(2, 4)
        _, ts, flt = run_pipeline(True, linear_model, arr)
        p = flt.pipeline
        assert len(p.fused_segments) == 1
        seg = p.fused_segments[0]
        assert seg.filter == "net"
        assert seg.transforms == ("norm",)
        assert seg.decoder is None
        assert seg.stages == 2
        assert seg.chain_digest.startswith("pre:arithmetic|")

    def test_unfused_pipeline_has_no_segments(self, linear_model):
        arr = np.arange(8, dtype=np.uint8).reshape(2, 4)
        _, _, flt = run_pipeline(False, linear_model, arr)
        assert flt.pipeline.fused_segments == []

    def test_full_segment_descriptor(self):
        import jax.numpy as jnp

        from nnstreamer_tpu.elements.decoder import TensorDecoder

        def fn(x):
            b = x.shape[0]
            boxes = jnp.tile(jnp.asarray(
                [[0.1, 0.1, 0.5, 0.5]], jnp.float32)[None], (b, 1, 1))
            classes = jnp.ones((b, 1), jnp.float32)
            scores = jnp.full((b, 1), 0.9, jnp.float32)
            num = jnp.ones((b,), jnp.int32)
            return boxes, classes, scores, num

        name = register_model("_t_seg_detect", fn,
                              in_shapes=[(2, 8, 8, 3)],
                              in_dtypes=np.float32)
        try:
            spec = TensorsSpec.from_shapes([(2, 8, 8, 3)], np.uint8,
                                           rate=Fraction(30))
            p = Pipeline(fuse=True)
            src = AppSrc(name="src", spec=spec)
            tr = TensorTransform(
                name="norm", mode="arithmetic",
                option="typecast:float32,div:255.0")
            flt = TensorFilter(name="net", framework="jax-xla",
                               model=name)
            dec = TensorDecoder(name="dec", mode="bounding_boxes",
                                option1="mobilenet-ssd-postprocess",
                                option4="16:16", option5="16:16",
                                option7="device")
            sink = AppSink(name="out")
            p.add(src, tr, flt, dec, sink).link(src, tr, flt, dec, sink)
            with p:
                src.push_buffer(Buffer.of(
                    np.zeros((2, 8, 8, 3), np.uint8)))
                src.end_of_stream()
                assert p.wait_eos(timeout=120)
                segs = list(p.fused_segments)
            assert len(segs) == 1
            seg = segs[0]
            assert (seg.filter, seg.transforms, seg.decoder) == \
                ("net", ("norm",), "dec")
            assert seg.stages == 3
            assert "pre:arithmetic|" in seg.chain_digest
            assert "post:bounding_boxes:mobilenet-ssd-postprocess" \
                in seg.chain_digest
        finally:
            unregister_model(name)


class TestFusedChainPersistCache:
    """PR-14 exclusion lifted: fused whole-graph programs participate
    in the persistent AOT cache, keyed by model digest + ordered chain
    digest — warm-process runs get persist_hit rows, and a changed
    stage config misses instead of wrongly hitting."""

    @staticmethod
    def _persist_hits():
        from nnstreamer_tpu.utils.stats import COMPILE_STATS

        return sum(r["count"] for r in COMPILE_STATS.snapshot()
                   if r["kind"] == "persist_hit")

    def test_fused_chain_warm_process_hits(self, tmp_path, monkeypatch,
                                           linear_model):
        from nnstreamer_tpu.runtime import compilecache

        monkeypatch.setenv("NNS_TPU_COMPILE_CACHE_DIR", str(tmp_path))
        arr = np.arange(8, dtype=np.uint8).reshape(2, 4)
        before = compilecache.CACHE_STATS.snapshot()
        hits0 = self._persist_hits()
        run_pipeline(True, linear_model, arr)  # cold: store
        mid = compilecache.CACHE_STATS.snapshot()
        assert mid["stores"] > before["stores"]
        assert self._persist_hits() == hits0
        run_pipeline(True, linear_model, arr)  # fresh filter: pure load
        after = compilecache.CACHE_STATS.snapshot()
        assert after["hits"] > mid["hits"]
        assert self._persist_hits() > hits0

    def test_changed_chain_config_misses(self, tmp_path, monkeypatch,
                                         linear_model):
        from nnstreamer_tpu.runtime import compilecache

        monkeypatch.setenv("NNS_TPU_COMPILE_CACHE_DIR", str(tmp_path))
        arr = np.full((2, 4), 4, np.float32)
        t1 = [TensorTransform(name="n", mode="arithmetic",
                              option="div:2.0")]
        run_pipeline(True, linear_model, arr, transforms=t1)
        mid = compilecache.CACHE_STATS.snapshot()
        # same model, different op chain: a new entry must be BUILT
        # (a wrong hit here would silently run the old prologue)
        t2 = [TensorTransform(name="n", mode="arithmetic",
                              option="div:4.0")]
        out, _, _ = run_pipeline(True, linear_model, arr, transforms=t2)
        after = compilecache.CACHE_STATS.snapshot()
        assert after["stores"] > mid["stores"]
        assert after["hits"] == mid["hits"]
        ref, _, _ = run_pipeline(False, linear_model, arr, transforms=[
            TensorTransform(name="n", mode="arithmetic",
                            option="div:4.0")])
        np.testing.assert_allclose(out.tensors[0].np(),
                                   ref.tensors[0].np(), rtol=1e-6)

    def test_undigestable_post_stays_out_of_cache(self, tmp_path,
                                                  monkeypatch,
                                                  linear_model):
        from nnstreamer_tpu.filters.api import FilterProps
        from nnstreamer_tpu.filters.jax_xla import JaxXlaFilter
        from nnstreamer_tpu.runtime import compilecache

        monkeypatch.setenv("NNS_TPU_COMPILE_CACHE_DIR", str(tmp_path))
        sp = JaxXlaFilter()
        sp.set_fused_post([lambda *outs: outs])  # no chain_digest
        before = compilecache.CACHE_STATS.snapshot()
        sp.configure(FilterProps(framework="jax-xla",
                                 model=linear_model))
        sp.invoke([np.zeros((2, 4), np.float32)])
        sp.close()
        after = compilecache.CACHE_STATS.snapshot()
        assert after["stores"] == before["stores"]

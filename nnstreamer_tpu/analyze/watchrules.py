"""NNS510/NNS517 — static validation of ``obs/watch.py`` rules files.

A watch rule that references a metric family the registry never
exports, or that cannot parse at all, fails in the worst possible way:
*silently*, at 3am, by not firing.  This pass loads a TOML/JSON rules
file (the same loader the watchdog uses — one grammar, one error
surface) WITHOUT starting anything and reports:

- malformed grammar (unknown keys/kinds/ops, bad durations, duplicate
  names, unreadable/unparseable files) — the exact :class:`RuleError`
  the watchdog would raise at startup;
- rules that can never fire: unknown metric family, a signal that
  cannot exist for the family's kind (``rate`` on a gauge, ``p99`` on
  a counter), ratio/burn shapes that can never bind (see
  :func:`nnstreamer_tpu.obs.watch.lint_rule`);
- nonsense ``[store]`` sizing (rings too short for any quantile or
  anomaly baseline, a series cap too small to hold one pool) — still
  NNS510, it is the same file;
- NNS517 — forecast rules that cannot predict: a missing or
  non-positive ``horizon`` (the watchdog refuses the set at startup;
  the lint catches it at review time), a forecast bound to a
  histogram family (windowed quantiles re-derive each tick — there is
  no single series to fit a trend through), or a horizon shorter than
  three sampler intervals (a "trend" over fewer than ~3 points of
  lookahead is noise, and the fit's significance gate would suppress
  every firing anyway).

Invoked by ``nns-lint --watch-rules FILE`` (bare ``--watch-rules``
reads ``$NNS_TPU_WATCH_RULES``, the same env var the runtime loads
from).
"""

from __future__ import annotations

import os
from typing import List, Optional

from .diagnostics import Diagnostic

_HINT = ("rule grammar + the exported-family catalog: "
         "Documentation/observability.md ('Alerting & watchdog'); "
         "known families: nnstreamer_tpu.obs.watch.KNOWN_FAMILIES")

_FC_HINT = ("forecast grammar: horizon = \"<duration>\" > 0 (and >= 3 "
            "sampler intervals), bound to a counter/gauge family — "
            "Documentation/observability.md ('Forecast rules & "
            "capacity headroom')")

#: sampler interval the horizon sanity check assumes when nobody says
#: otherwise (the watchdog's own default)
DEFAULT_INTERVAL_S = 1.0

#: a horizon shorter than this many sampler intervals forecasts over
#: fewer points than any trend needs
MIN_HORIZON_TICKS = 3


def _forecast_problems(rule, interval_s: float) -> List[str]:
    """The NNS517 faces of one well-formed forecast rule."""
    from ..obs import watch as _watch

    problems: List[str] = []
    if not rule.horizon_s > 0:
        problems.append(
            "forecast without a horizon (horizon = \"30s\") — the "
            "watchdog refuses the rule set at startup")
    elif rule.horizon_s < MIN_HORIZON_TICKS * interval_s:
        problems.append(
            f"horizon {rule.horizon_s:g}s is shorter than "
            f"{MIN_HORIZON_TICKS} sampler intervals "
            f"({MIN_HORIZON_TICKS * interval_s:g}s at {interval_s:g}s "
            f"sampling) — too little lookahead to beat the reactive "
            f"rules, and the noise gate suppresses it anyway")
    if _watch.KNOWN_FAMILIES.get(rule.metric) == "histogram":
        problems.append(
            f"forecast bound to histogram family {rule.metric!r} — "
            f"windowed quantiles re-derive each tick; trend-forecast "
            f"a counter rate or gauge level instead")
    return problems


def check_watch_rules(path: Optional[str],
                      interval_s: float = DEFAULT_INTERVAL_S
                      ) -> List[Diagnostic]:
    """Diagnostics for one rules file.  ``path=None`` means "use
    ``$NNS_TPU_WATCH_RULES``" — unset is itself a finding (the user
    asked for a check with nothing to check).  ``interval_s`` is the
    sampler interval the horizon sanity check assumes."""
    from ..obs import watch as _watch

    if path is None:
        path = os.environ.get("NNS_TPU_WATCH_RULES", "").strip()
        if not path:
            return [Diagnostic.make(
                "NNS510",
                "--watch-rules given without a file and "
                "NNS_TPU_WATCH_RULES is unset — no rules to validate",
                hint=_HINT)]
    label = os.path.basename(path)
    try:
        rules = _watch.load_rules(path)
        store_cfg = _watch.load_store(path)
    except _watch.RuleError as e:
        return [Diagnostic.make(
            "NNS510", f"{label}: malformed rules file: {e}",
            element=path, hint=_HINT)]
    except OSError as e:
        return [Diagnostic.make(
            "NNS510", f"{label}: cannot read rules file: {e}",
            element=path, hint=_HINT)]
    diags: List[Diagnostic] = []
    for rule in rules:
        for problem in _watch.lint_rule(rule):
            diags.append(Diagnostic.make(
                "NNS510", f"{label}: rule {rule.name!r}: {problem}",
                element=path, pad=rule.name, hint=_HINT))
        if rule.kind == "forecast":
            for problem in _forecast_problems(rule, interval_s):
                diags.append(Diagnostic.make(
                    "NNS517", f"{label}: rule {rule.name!r}: {problem}",
                    element=path, pad=rule.name, hint=_FC_HINT))
    for problem in _watch.lint_store(store_cfg):
        diags.append(Diagnostic.make(
            "NNS510", f"{label}: {problem}", element=path,
            hint=_HINT))
    return diags

"""The ONE placement layer: where a model's executables run.

Before this module, placement knowledge was smeared across three seams:
``filters/jax_xla.py`` parsed ``mesh=`` / ``sharding=`` / ``devices=``
and built its own mesh, ``runtime/serving.py`` keyed its ModelPool by
the RAW property strings (so ``mesh=data:-1`` and ``mesh=data:8`` on an
8-device host opened two pools and defeated sharing), and
``parallel/multihost.py`` built hybrid ICI/DCN meshes nothing in the
serving path could reach.  This module collapses them:

- :class:`Placement` — the declarative spec (the property strings,
  frozen + hashable).  Grammar: ``mesh="data:-1"``,
  ``"data:4,model:2"``, and — new — DCN axes with a ``dcn.`` prefix
  (``"dcn.data:2,data:-1"``) that span *processes* of a
  ``jax.distributed`` group, so a fleet of hosts serves one logical
  pool: per-process window formation, globally sharded dispatch.
- :class:`ResolvedPlacement` — the spec bound to real devices: the
  built ``jax.sharding.Mesh`` (DCN axes via
  :func:`~nnstreamer_tpu.parallel.multihost.hybrid_mesh`), the named
  param-layout rules, the batch (data) axes, and the **canonical key**
  every equivalent spelling resolves to — the ModelPool / shared-
  instance dedup key, so two filters that mean the same placement
  always join one pool.

Every mesh consumer (``_compile`` / ``_compile_batched`` /
``invoke_batched``, the ModelPool, the obs placement labels) reads
THIS object instead of re-deriving its own view of the properties.
"""

from __future__ import annotations

import dataclasses
import functools
import math
import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

#: DCN axis marker in the mesh grammar: ``dcn.data:2`` declares a
#: cross-process axis (outer, over DCN); unprefixed axes span the
#: ICI-connected local devices of each process.
DCN_PREFIX = "dcn."

#: Force overlap detection into error mode (None = read the
#: ``NNS_TPU_STRICT_PLACEMENT`` env var at detection time).  Two pools
#: resolving OVERLAPPING explicit ``devices=`` subsets in one process
#: share chips silently: their dispatches contend for the same HBM and
#: the per-shard attribution in ``obs/meshstat.py`` charges both
#: stages' frames to the shared chips — the numbers stop meaning
#: anything.  Default is a loud warning (the pipelines still run);
#: strict mode turns the second resolution into a ``ValueError``.
STRICT_OVERLAP: Optional[bool] = None

_SUBSET_LOCK = threading.Lock()
#: platform -> {sorted device-id tuple -> registration count} of every
#: explicit ``devices=`` subset resolved in this process (process-
#: lifetime, like the meshstat store: a stage that ran leaves its
#: claim on record so a later overlapping stage is still caught).
_SUBSETS: Dict[str, Dict[Tuple[int, ...], int]] = {}
#: detected overlaps: (platform, subset_a, subset_b) -> detections
_OVERLAPS: Dict[Tuple[str, Tuple[int, ...], Tuple[int, ...]], int] = {}


def subset_label(ids: Sequence[int]) -> str:
    """Canonical short label of a device-index subset: contiguous runs
    collapse (``"0-3"``), everything else is a comma list (``"0,2,5"``)
    — the ``stage`` label on pool rows and ``nns_stage_*`` series."""
    ids = sorted(int(i) for i in ids)
    if not ids:
        return ""
    runs: List[List[int]] = [[ids[0], ids[0]]]
    for i in ids[1:]:
        if i == runs[-1][1] + 1:
            runs[-1][1] = i
        else:
            runs.append([i, i])
    return ",".join(str(a) if a == b else f"{a}-{b}" for a, b in runs)


def _strict_overlap() -> bool:
    if STRICT_OVERLAP is not None:
        return bool(STRICT_OVERLAP)
    return os.environ.get("NNS_TPU_STRICT_PLACEMENT", "") not in (
        "", "0", "false", "no")


def register_subset(platform: str, ids: Sequence[int]) -> None:
    """Record one explicit ``devices=`` subset against the process-wide
    inventory and detect overlap with every DIFFERENT subset already
    resolved on the same platform.  Called from
    :class:`ResolvedPlacement` — i.e. at ``resolve()`` time, before the
    placement serves a single frame.  Overlap is loud (``logw``) and
    exported (``nns_placement_overlap``); under the strict flag it
    raises instead, so a mis-split stage spec cannot start."""
    subset = tuple(sorted(int(i) for i in ids))
    if not subset:
        return
    hits: List[Tuple[int, ...]] = []
    with _SUBSET_LOCK:
        table = _SUBSETS.setdefault(str(platform), {})
        for other in table:
            if other != subset and set(other) & set(subset):
                pair = (str(platform),) + tuple(sorted((other, subset)))
                _OVERLAPS[pair] = _OVERLAPS.get(pair, 0) + 1
                hits.append(other)
        table[subset] = table.get(subset, 0) + 1
    for other in hits:
        shared = subset_label(set(other) & set(subset))
        msg = (f"placement overlap on {platform}: devices="
               f"{subset_label(subset)} shares chip(s) {shared} with "
               f"already-resolved devices={subset_label(other)} — the "
               f"stages contend for the same HBM and per-shard "
               f"attribution (obs/meshstat.py) is corrupted; split "
               f"the subsets or set NNS_TPU_STRICT_PLACEMENT=1 to "
               f"make this an error")
        if _strict_overlap():
            raise ValueError(msg)
        from ..utils.log import logw

        logw(msg)


def overlap_snapshot() -> List[dict]:
    """Structured view of every detected subset overlap (for the
    ``nns_placement_overlap`` export): one row per overlapping pair
    with the shared chips and how often the pair was resolved."""
    with _SUBSET_LOCK:
        pairs = dict(_OVERLAPS)
    return [{"platform": platform,
             "a": subset_label(a), "b": subset_label(b),
             "shared": subset_label(set(a) & set(b)),
             "count": n}
            for (platform, a, b), n in sorted(pairs.items())]


def reset_subsets() -> None:
    """Tests/bench only: drop the subset inventory and overlap log."""
    with _SUBSET_LOCK:
        _SUBSETS.clear()
        _OVERLAPS.clear()


def _jax():
    import jax

    return jax


def parse_accel_kind(accl: str) -> Optional[str]:
    """Platform kind out of the ``accelerator=`` grammar
    ("true:tpu" / "tpu" / "cpu" / "" = auto) — the same parse
    ``jax_xla._parse_accelerator`` applies, shared so the canonical
    placement key and the device selection can never disagree."""
    kind = None
    for part in (accl or "").split(":"):
        p = part.strip().lower()
        if p in ("tpu", "cpu", "gpu"):
            kind = p
    return kind


@dataclasses.dataclass(frozen=True)
class Placement:
    """Declarative placement: the ``tensor_filter`` property strings,
    normalized and hashable.  ``resolve()`` binds it to devices."""

    mesh: str = ""       # mesh grammar; "" = single-device placement
    sharding: str = ""   # named param-layout rules (PARAM_RULES)
    devices: str = ""    # local device-index subset ("0-3", "4,5,6")
    accelerator: str = ""  # accelerator= grammar (selects the platform)

    @classmethod
    def from_props(cls, props: Any) -> "Placement":
        return cls(
            mesh=str(getattr(props, "mesh", "") or "").strip(),
            sharding=str(getattr(props, "sharding", "") or "").strip(),
            devices=str(getattr(props, "devices", "") or "").strip(),
            accelerator=str(getattr(props, "accelerator", "") or "").strip())

    @property
    def is_null(self) -> bool:
        """No mesh: the single-device placement (``accelerator=`` alone
        picks the device)."""
        return not self.mesh

    def axes(self) -> Tuple[Tuple[str, int, bool], ...]:
        """Parsed ``(name, size, is_dcn)`` triples in grammar order.
        DCN axes must lead (the hybrid mesh is outer-DCN by
        construction); the ``dcn.`` prefix stays part of the axis name
        so sharding annotations can address either tier."""
        out: List[Tuple[str, int, bool]] = []
        seen_ici = False
        for part in self.mesh.split(","):
            name, _, n = part.strip().partition(":")
            if not name:
                raise ValueError(f"empty axis in mesh {self.mesh!r}")
            dcn = name.startswith(DCN_PREFIX)
            if dcn and seen_ici:
                raise ValueError(
                    f"mesh {self.mesh!r}: dcn axes must come before "
                    f"local axes (outer-DCN, inner-ICI)")
            seen_ici = seen_ici or not dcn
            out.append((name, int(n) if n.strip() else -1, dcn))
        return tuple(out)

    def resolve(self, dev_kind: Optional[str] = None
                ) -> Optional["ResolvedPlacement"]:
        """Bind to the visible devices; None for the null placement.
        ``dev_kind`` defaults to the kind the ``accelerator`` property
        selects.  Raises ``ValueError`` on an unsatisfiable spec."""
        if self.is_null:
            return None
        return ResolvedPlacement(self, dev_kind)

    def key(self, dev_kind: Optional[str] = None) -> Tuple:
        """Canonical placement key: equivalent spellings (``data:-1``
        vs ``data:8`` on 8 devices, ``dp`` vs ``replicated`` rules,
        ``cpu`` vs ``true:cpu``) map to ONE tuple — the dedup key for
        the ModelPool and the framework shared-instance table.  Falls
        back to the raw strings when the spec cannot resolve here (the
        open itself will report the real error).  Cached per
        (placement, kind): the device topology is fixed once the jax
        backend initialized, and pool_key/_share_key/configure each
        ask for the same key per element start."""
        if dev_kind is None:
            dev_kind = parse_accel_kind(self.accelerator)
        if self.is_null:
            return ("device", dev_kind or "")
        return _cached_key(self, dev_kind)


@functools.lru_cache(maxsize=256)
def _resolved_key(placement: "Placement", dev_kind: Optional[str]
                  ) -> Tuple:
    return placement.resolve(dev_kind).key


def _cached_key(placement: "Placement", dev_kind: Optional[str]) -> Tuple:
    try:
        # only SUCCESSFUL resolutions cache (lru_cache never stores a
        # raised call): a spec that fails transiently — e.g. a dcn
        # placement keyed before multihost.initialize() grew the
        # process group — must re-resolve later, not pin a raw key for
        # the process lifetime
        return _resolved_key(placement, dev_kind)
    except Exception:  # noqa: BLE001 - unresolvable spec: raw-string
        # key keeps the pools distinct; configure() raises the
        # actual diagnostic
        return ("raw", placement.mesh, placement.sharding,
                placement.devices, dev_kind or "")


class ResolvedPlacement:
    """A :class:`Placement` bound to real devices: the built mesh, the
    param rules, the batch axes, and the canonical key."""

    def __init__(self, spec: Placement, dev_kind: Optional[str] = None):
        from .mesh import parse_device_indices
        from .sharded import PARAM_RULES, get_param_rules

        jax = _jax()
        self.spec = spec
        if dev_kind is None:
            dev_kind = parse_accel_kind(spec.accelerator)
        self.dev_kind = dev_kind
        axes = spec.axes()
        self.dcn_axes = tuple((n, s) for n, s, d in axes if d)
        self.ici_axes = tuple((n, s) for n, s, d in axes if not d)
        if not self.ici_axes:
            raise ValueError(
                f"mesh {spec.mesh!r} declares no local (ICI) axis")
        if self.dcn_axes:
            if spec.devices:
                raise ValueError(
                    f"devices={spec.devices!r} cannot restrict a "
                    f"multi-process (dcn) mesh — the DCN tier owns "
                    f"device assignment per process")
            n_proc = jax.process_count()
            dcn_sizes = self._fill_wildcard(
                [s for _, s in self.dcn_axes], n_proc,
                f"dcn axes of mesh {spec.mesh!r}")
            self.dcn_axes = tuple(
                (n, s) for (n, _), s in zip(self.dcn_axes, dcn_sizes))
            local = jax.local_devices() if dev_kind is None else [
                d for d in jax.local_devices() if d.platform == dev_kind]
            # a fixed local tier may use a PREFIX of the local devices
            # (hybrid_mesh validates the count); only a wildcard must
            # absorb them all
            ici_sizes = self._fill_wildcard(
                [s for _, s in self.ici_axes], len(local),
                f"local axes of mesh {spec.mesh!r}", exact=False)
            self.ici_axes = tuple(
                (n, s) for (n, _), s in zip(self.ici_axes, ici_sizes))
            from .multihost import hybrid_mesh

            # thread the accelerator-selected platform through: the
            # wildcard was sized from the dev_kind-filtered local
            # list, so the mesh must be laid over the same selection
            # (a mixed-platform host would otherwise mesh devices the
            # accelerator= property excluded)
            self.mesh = hybrid_mesh(
                list(self.ici_axes), list(self.dcn_axes),
                devices=jax.devices(dev_kind) if dev_kind else None)
        else:
            devs = jax.devices(dev_kind) if dev_kind else jax.devices()
            if spec.devices:
                idx = parse_device_indices(spec.devices, len(devs))
                devs = [devs[i] for i in idx]
                # stage-subset inventory: validate THIS subset against
                # every explicit subset already resolved in the
                # process (overlap = silent chip sharing + corrupted
                # shard attribution; error under the strict flag)
                register_subset(devs[0].platform if devs else "",
                                (d.id for d in devs))
            fixed = math.prod(s for _, s in self.ici_axes if s != -1)
            if not any(s == -1 for _, s in self.ici_axes):
                if len(devs) < fixed:
                    raise ValueError(
                        f"mesh {spec.mesh!r} wants {fixed} devices, "
                        f"have {len(devs)}")
                if spec.devices and len(devs) != fixed:
                    # an explicit placement must be used exactly:
                    # silently running on a prefix would leave declared
                    # chips idle
                    raise ValueError(
                        f"devices={spec.devices!r} names {len(devs)} "
                        f"devices but mesh {spec.mesh!r} uses {fixed}")
                devs = devs[:fixed]
            sizes = self._fill_wildcard(
                [s for _, s in self.ici_axes], len(devs),
                f"mesh {spec.mesh!r}")
            self.ici_axes = tuple(
                (n, s) for (n, _), s in zip(self.ici_axes, sizes))
            from .mesh import make_mesh

            self.mesh = make_mesh(self.ici_axes, devices=devs)
        self.rules = get_param_rules(spec.sharding)
        # canonical rules name: aliases ("dp"/"replicated",
        # "tp"/"mobilenet") resolve to one callable — key by the first
        # name that maps to it, not by what the user typed
        self.rules_name = sorted(
            k for k, v in PARAM_RULES.items() if v is self.rules)[0]
        # batch (data) axes: every axis whose base name matches the
        # primary data name — "data" when present, else the first
        # local axis — DCN tier included, so a dcn.data window shards
        # globally over processes x local chips
        names = [n for n, _ in self.dcn_axes + self.ici_axes]
        base = [n[len(DCN_PREFIX):] if n.startswith(DCN_PREFIX) else n
                for n in names]
        primary = "data" if "data" in base else (
            self.ici_axes[0][0] if self.ici_axes else base[0])
        self.data_axes = tuple(
            n for n, b in zip(names, base) if b == primary)
        for n, _ in self.dcn_axes:
            if n not in self.data_axes:
                # the DCN tier is data-parallel ONLY: every process
                # must contribute a batch slice to the global window —
                # a non-data dcn axis (tensor parallelism over DCN)
                # would require cross-host collectives per layer AND
                # break the per-process window math (feed_window's
                # global shape assumes processes = batch fan-out)
                raise ValueError(
                    f"mesh {spec.mesh!r}: dcn axis {n!r} is not a "
                    f"data axis — the DCN (cross-process) tier must "
                    f"be data-parallel (name it dcn.{primary}); put "
                    f"model/tensor parallelism on the local tier")
        #: the local (ICI) data axis — the back-compat label single-axis
        #: consumers (meshstat attribution) report against
        self.data_axis = next(
            (n for n in self.data_axes if not n.startswith(DCN_PREFIX)),
            self.data_axes[0])
        self.num_processes = math.prod(
            s for _, s in self.dcn_axes) if self.dcn_axes else 1
        self.process_index = jax.process_index() if self.dcn_axes else 0
        mesh_axes = tuple(
            (str(n), int(s)) for n, s in zip(self.mesh.axis_names,
                                             self.mesh.devices.shape))
        self.key = ("mesh",
                    self.mesh.devices.flat[0].platform,
                    mesh_axes,
                    tuple(int(d.id) for d in self.mesh.devices.flat),
                    self.rules_name)
        #: canonical stage label ("0-3") when the spec pinned an
        #: explicit ``devices=`` subset; "" for auto-placed meshes.
        #: Equivalent spellings ("4,5,6,7" vs "4-7") collapse to one
        #: label, the per-stage join key for the snapshot's ``stages``
        #: table and the nns-top STAGE section.
        self.stage = subset_label(self.device_ids) if spec.devices else ""

    @property
    def device_ids(self) -> Tuple[int, ...]:
        """The mesh's device ids in mesh order — membership test for
        the cross-stage handoff (a device-resident tensor homed outside
        this set belongs to another stage)."""
        return tuple(int(d.id) for d in self.mesh.devices.flat)

    @staticmethod
    def _fill_wildcard(sizes: List[int], total: int, what: str,
                       exact: bool = True) -> List[int]:
        wild = [i for i, s in enumerate(sizes) if s == -1]
        if len(wild) > 1:
            raise ValueError(f"{what}: more than one -1 axis")
        fixed = math.prod(s for s in sizes if s != -1)
        if wild:
            if fixed <= 0 or total % fixed:
                raise ValueError(
                    f"{what}: {total} not divisible by fixed axes "
                    f"{fixed}")
            sizes = list(sizes)
            sizes[wild[0]] = total // fixed
        elif (fixed != total) if exact else (fixed > total):
            raise ValueError(
                f"{what}: wants {fixed}, have {total}")
        return list(sizes)

    # -- shardings ------------------------------------------------------------

    @property
    def data_axis_size(self) -> int:
        """GLOBAL batch parallelism: product of every data axis
        (processes x local chips on a multi-host placement)."""
        return math.prod(int(self.mesh.shape[a]) for a in self.data_axes)

    @property
    def local_data_axis_size(self) -> int:
        """Per-process share of the data parallelism."""
        return max(self.data_axis_size // max(self.num_processes, 1), 1)

    def _P(self, *parts):
        from jax.sharding import PartitionSpec

        return PartitionSpec(*parts)

    def batch_spec(self):
        """PartitionSpec sharding a leading batch dim over every data
        axis."""
        axes = self.data_axes
        return self._P(axes[0] if len(axes) == 1 else tuple(axes))

    def batch_sharding(self):
        from jax.sharding import NamedSharding

        return NamedSharding(self.mesh, self.batch_spec())

    def replicated(self):
        from jax.sharding import NamedSharding

        return NamedSharding(self.mesh, self._P())

    def input_sharding(self, shape: Sequence[int]):
        """Batch-shard an input whose leading dim divides the data
        parallelism; replicate otherwise (small/odd inputs — e.g. a
        batch=1 frame on an 8-chip mesh — must still run)."""
        if shape and shape[0] and int(shape[0]) % self.data_axis_size == 0:
            return self.batch_sharding()
        return self.replicated()

    def window_sharding(self, bucket: int):
        """Sharding for a coalesced micro-batch window of ``bucket``
        LOCAL slots (``num_processes * bucket`` global), or None when
        the window cannot split evenly over the data axes."""
        global_bucket = int(bucket) * self.num_processes
        if global_bucket % self.data_axis_size:
            return None
        return self.batch_sharding()

    def shard_params(self, params):
        """Lay a param pytree over the mesh per the named rules."""
        from .sharded import shard_params

        return shard_params(self.mesh, params, self.rules)

    def describe(self) -> str:
        """Observability label: ``mesh(<axes>)`` with RESOLVED sizes —
        the ``placement`` label on ``nns_executable_*`` gauges."""
        axes = ",".join(f"{n}:{s}"
                        for n, s in zip(self.mesh.axis_names,
                                        self.mesh.devices.shape))
        return f"mesh({axes})"

    @property
    def platform(self) -> str:
        return next(iter(self.mesh.devices.flat)).platform

    # -- window feed (the "stack once, dispatch sharded" path) ---------------

    def feed_window(self, stacked: Sequence[np.ndarray]) -> List[Any]:
        """Place host-stacked window tensors onto the mesh, batch axis
        sharded: every shard's bytes go straight to its own device
        instead of landing replicated and resharding inside the
        program.  On a multi-process placement each process hands its
        LOCAL ``(bucket, ...)`` block and receives the global
        ``(num_processes * bucket, ...)`` array — the globally sharded
        dispatch a fleet-wide pool rides."""
        jax = _jax()
        sharding = self.batch_sharding()
        out = []
        for arr in stacked:
            if self.num_processes > 1:
                gshape = (arr.shape[0] * self.num_processes,) \
                    + tuple(arr.shape[1:])
                out.append(jax.make_array_from_process_local_data(
                    sharding, arr, gshape))
            else:
                out.append(jax.device_put(arr, sharding))
        return out

    def local_rows(self, arr) -> np.ndarray:
        """This process's rows of a batch-sharded global output: the
        addressable shards concatenated in global row order — the
        demux side of :meth:`feed_window`."""
        if self.num_processes <= 1:
            return arr
        shards = sorted(arr.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        return np.concatenate([np.asarray(s.data) for s in shards],
                              axis=0)

"""``tensor_reposink`` / ``tensor_reposrc`` — cyclic streams via an
out-of-band tensor repository.

Parity target: /root/reference/gst/nnstreamer/elements/gsttensor_repo.c
(:399, global slot table), gsttensor_reposink.c, gsttensor_reposrc.c:
dataflow graphs forbid cycles, so recurrence (RNN/LSTM state feedback —
tests/nnstreamer_repo_lstm) goes through a shared slot keyed by ``slot``
index: reposink writes, reposrc reads (blocking with timeout, with an
initial "dummy" zero frame so the loop can start).

TPU note: slots hold Tensors whose payloads may be device-resident jax
Arrays — a recurrent loop keeps its state in HBM across iterations.
"""

from __future__ import annotations

import queue as _q
import threading
from typing import Dict, Optional

import numpy as np

from ..core import Buffer, Caps, Tensor, TensorsSpec
from ..runtime.element import NegotiationError, SinkElement, SourceElement
from ..runtime.registry import register_element


class _Repo:
    """Global slot table (parity: gsttensor_repo.c TensorRepo singleton)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._slots: Dict[int, "_q.Queue"] = {}

    def slot(self, index: int) -> "_q.Queue":
        with self._lock:
            if index not in self._slots:
                self._slots[index] = _q.Queue(maxsize=2)
            return self._slots[index]

    def reset(self) -> None:
        with self._lock:
            self._slots.clear()


REPO = _Repo()


@register_element("tensor_reposink")
class TensorRepoSink(SinkElement):
    FACTORY = "tensor_reposink"

    def __init__(self, name=None, slot: int = 0, silent: bool = True,
                 **props):
        self.slot = slot
        self.silent = silent
        super().__init__(name, **props)

    def _put(self, item) -> None:
        """Bounded, non-wedging put: if the paired reposrc stopped reading
        (e.g. it hit num_buffers), displace the oldest entry instead of
        blocking the upstream streaming thread forever."""
        q = REPO.slot(int(self.slot))
        while True:
            try:
                q.put(item, timeout=0.5)
                return
            except _q.Full:
                try:
                    displaced = q.get_nowait()  # leaky: keep newest
                except _q.Empty:
                    continue
                if displaced is None:
                    # Never drop the EOS sentinel — the paired reposrc
                    # must still observe end-of-stream after this data
                    # buffer, or it blocks until timeout.
                    try:
                        q.put(item, timeout=0.5)
                    except _q.Full:
                        # another producer on the same slot refilled it;
                        # retry the whole sequence so EOS still lands last
                        self._put(item)
                    if item is not None:
                        self._put(None)  # re-append EOS after the data
                    return

    def render(self, buf: Buffer) -> None:
        self._put(buf)

    def on_eos(self) -> None:
        self._put(None)


@register_element("tensor_reposrc")
class TensorRepoSrc(SourceElement):
    """Reads slot ``slot``; emits an initial zero frame (``dummy``
    behavior) so a feedback loop has a first input."""

    FACTORY = "tensor_reposrc"

    def __init__(self, name=None, slot: int = 0, caps=None,
                 spec: Optional[TensorsSpec] = None, num_buffers: int = -1,
                 timeout: float = 10.0, dummy_first: bool = True, **props):
        self.slot = slot
        self.caps = caps
        self.spec = spec
        self.num_buffers = num_buffers
        self.timeout = timeout
        self.dummy_first = dummy_first
        super().__init__(name, **props)
        if isinstance(self.caps, str):
            from ..runtime.parser import parse_caps_string

            self.caps = parse_caps_string(self.caps)
        self._count = 0

    def output_spec(self):
        if self.spec is None and self.caps is not None:
            self.spec = self.caps.to_spec()
        if self.spec is None:
            raise NegotiationError(f"{self.name}: reposrc needs caps/spec")
        return self.spec

    def create(self) -> Optional[Buffer]:
        if 0 <= self.num_buffers <= self._count:
            return None
        self._count += 1
        if self._count == 1 and self.dummy_first:
            spec = self.output_spec()
            return Buffer(tensors=[
                Tensor(np.zeros(t.shape, t.dtype.np_dtype), t)
                for t in spec.tensors], pts=0)
        import time

        q = REPO.slot(int(self.slot))
        deadline = time.monotonic() + float(self.timeout)
        while self._running.is_set():
            try:
                return q.get(timeout=0.1)
            except _q.Empty:
                if time.monotonic() > deadline:
                    raise
        return None

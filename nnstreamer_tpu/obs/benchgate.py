"""Continuous-bench history + regression gate (``nns-bench-diff``).

The repo accumulates ``BENCH_*.json`` result files, but nothing tracks
them ACROSS runs: a PR that halves the batching speedup ships unless a
human re-reads the numbers.  This module closes that loop:

- :func:`append_history` — every ``bench.py … --history`` run appends
  one normalized JSONL record to ``BENCH_history.jsonl``: scenario,
  the result's top-level scalar fields, the git sha it ran at, and a
  digest of the metrics-registry snapshot (so two runs whose exported
  metric STATE differs are distinguishable even when the headline
  scalars agree).
- :func:`diff` / :func:`main` — compare the latest history record of a
  scenario against a committed **baseline spec**: a JSON file naming
  per-metric expected values, tolerances and directions.  The verdict
  is ``pass`` / ``regression`` / ``missing-baseline`` (exit codes
  0/1/2), printed as text or ``--json`` — the CI regression gate.

Baseline spec format (per-metric tolerance lives WITH the baseline,
not in CI flags)::

    {
      "scenario": "batching",
      "metrics": {
        "value":              {"baseline": 4.5, "tolerance": 0.5,
                               "direction": "higher"},
        "dispatch_reduction": {"baseline": 8.0, "tolerance": 0.5}
      }
    }

``direction`` is ``higher`` (default: regression when the current
value falls below ``baseline*(1-tolerance)``), ``lower`` (regression
when it rises above ``baseline*(1+tolerance)``), or ``exact``
(regression when it leaves ``baseline ± tolerance*|baseline|`` in
EITHER direction — for analytically-known figures).  A plain bench result
file (no ``metrics`` mapping) also works as a baseline: the ``value``
field is compared at the default tolerance.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

HISTORY_PATH = "BENCH_history.jsonl"
DEFAULT_TOLERANCE = 0.10

VERDICT_PASS = "pass"
VERDICT_REGRESSION = "regression"
VERDICT_MISSING = "missing-baseline"

_EXIT = {VERDICT_PASS: 0, VERDICT_REGRESSION: 1, VERDICT_MISSING: 2}


# -- history ------------------------------------------------------------------


def git_sha(cwd: Optional[str] = None) -> str:
    """HEAD sha of the repo the bench ran in, "" when not a checkout."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=cwd or os.getcwd(),
            capture_output=True, text=True, timeout=10)
        return out.stdout.strip() if out.returncode == 0 else ""
    except (OSError, subprocess.SubprocessError):
        return ""


def registry_digest(snapshot: Optional[dict] = None) -> str:
    """Stable sha256 of the metrics-registry snapshot with the volatile
    fields (scrape time, host tag) dropped — two runs that exported the
    same metric state digest identically across hosts."""
    if snapshot is None:
        from .metrics import REGISTRY

        snapshot = REGISTRY.snapshot()
    stable = {k: v for k, v in snapshot.items()
              if k not in ("time", "host")}
    blob = json.dumps(stable, sort_keys=True, default=str).encode()
    return "sha256:" + hashlib.sha256(blob).hexdigest()


def extract_scalars(result: dict) -> Dict[str, Any]:
    """The comparable surface of one bench result: its top-level
    numeric and boolean fields (nested blocks — per-leg curves, metric
    snapshots — stay in the BENCH_*.json, not the history line)."""
    out: Dict[str, Any] = {}
    for k, v in result.items():
        if isinstance(v, bool) or isinstance(v, (int, float)):
            out[k] = v
    return out


def append_history(scenario: str, result: dict,
                   path: str = HISTORY_PATH,
                   snapshot: Optional[dict] = None) -> dict:
    """Append one normalized record of a bench run to the JSONL
    history; returns the record."""
    rec = {
        "scenario": str(scenario),
        "time": time.time(),
        "git_sha": git_sha(),
        "unit": result.get("unit"),
        "scalars": extract_scalars(result),
        "registry_digest": registry_digest(snapshot),
    }
    with open(path, "a") as f:
        f.write(json.dumps(rec, sort_keys=True) + "\n")
    return rec


def read_history(path: str) -> List[dict]:
    """Every parseable record, file order (unparseable lines are
    skipped — a truncated append from a killed run must not wedge the
    gate forever)."""
    if not os.path.isfile(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


def latest_record(path: str, scenario: str) -> Optional[dict]:
    recs = [r for r in read_history(path)
            if r.get("scenario") == scenario]
    return recs[-1] if recs else None


def select_record(records: List[dict], selector: str) -> Optional[dict]:
    """Pick one record of a scenario's history by ``selector``: an
    integer index (0-based file order; negative counts from the end,
    ``-1`` = latest) or a git-sha prefix (latest match wins).  An
    all-digit selector is tried as an index first; out of range, it
    falls back to sha-prefix matching (sha prefixes like ``2740`` are
    common and histories are short, so a real index collision is rare
    and the ambiguity is resolved toward "something" over exit 2).
    None when nothing matches."""
    sel = str(selector).strip()
    neg = sel[1:] if sel.startswith("-") else sel
    if neg.isdigit():
        idx = int(sel)
        if -len(records) <= idx < len(records):
            return records[idx]
    for rec in reversed(records):
        if str(rec.get("git_sha", "")).startswith(sel):
            return rec
    return None


def record_as_baseline(record: dict,
                       tolerance: float = DEFAULT_TOLERANCE) -> dict:
    """Turn one history record into a baseline document, so ANY two
    history records can be diffed (``--against``): every scalar becomes
    a metric at the default tolerance, direction ``higher`` (for
    lower-is-better metrics, gate with a spec file instead)."""
    return {
        "scenario": record.get("scenario"),
        "git_sha": record.get("git_sha"),
        "metrics": {
            name: {"baseline": value, "tolerance": tolerance,
                   "direction": "higher"}
            for name, value in sorted(
                record.get("scalars", {}).items())
            if isinstance(value, (bool, int, float))
        },
    }


# -- the diff -----------------------------------------------------------------


def _baseline_metrics(baseline: dict,
                      default_tolerance: float) -> Dict[str, dict]:
    """Normalize a baseline document into {metric: {baseline,
    tolerance, direction}}.  Spec files carry a ``metrics`` mapping; a
    raw bench result contributes its ``value`` field."""
    metrics = baseline.get("metrics")
    if isinstance(metrics, dict) and metrics and all(
            isinstance(v, dict) for v in metrics.values()):
        out = {}
        for name, spec in metrics.items():
            out[name] = {
                "baseline": spec.get("baseline"),
                "tolerance": float(spec.get("tolerance",
                                            default_tolerance)),
                "direction": str(spec.get("direction", "higher")),
            }
        return out
    if isinstance(baseline.get("value"), (int, float)):
        return {"value": {"baseline": baseline["value"],
                          "tolerance": default_tolerance,
                          "direction": "higher"}}
    return {}


def diff(record: Optional[dict], baseline: Optional[dict],
         default_tolerance: float = DEFAULT_TOLERANCE) -> dict:
    """Compare one history record against one baseline document.
    Returns the verdict dict (``verdict``, per-metric ``checks``)."""
    if baseline is None:
        return {"verdict": VERDICT_MISSING, "checks": [],
                "reason": "no baseline document"}
    specs = _baseline_metrics(baseline, default_tolerance)
    if not specs:
        return {"verdict": VERDICT_MISSING, "checks": [],
                "reason": "baseline document names no metrics"}
    if record is None:
        return {"verdict": VERDICT_MISSING, "checks": [],
                "reason": "no history record for the scenario"}
    scalars = record.get("scalars", {})
    checks = []
    regressed = False
    for name in sorted(specs):
        spec = specs[name]
        base = spec["baseline"]
        tol = spec["tolerance"]
        direction = spec["direction"]
        cur = scalars.get(name)
        if isinstance(cur, bool):
            cur = float(cur)
        if isinstance(base, bool):
            base = float(base)
        check = {"metric": name, "baseline": base, "current": cur,
                 "tolerance": tol, "direction": direction}
        if cur is None or base is None:
            check["ok"] = False
            check["reason"] = "metric missing from " + (
                "record" if cur is None else "baseline")
            regressed = True
        else:
            if base != 0:
                check["delta_frac"] = round((cur - base) / abs(base), 4)
            if direction == "lower":
                ok = cur <= base + tol * abs(base)
            elif direction == "exact":
                # analytically-known figures (crossings-per-frame on
                # the seed pipeline): a move in EITHER direction is a
                # regression — more crossings is the exact class the
                # ledger exists to catch
                ok = abs(cur - base) <= tol * abs(base)
            else:
                ok = cur >= base - tol * abs(base)
            check["ok"] = bool(ok)
            regressed = regressed or not ok
        checks.append(check)
    return {
        "verdict": VERDICT_REGRESSION if regressed else VERDICT_PASS,
        "scenario": record.get("scenario"),
        "git_sha": record.get("git_sha"),
        "checks": checks,
    }


def _render_text(verdict: dict) -> str:
    lines = []
    for c in verdict.get("checks", []):
        mark = "ok  " if c.get("ok") else "FAIL"
        delta = c.get("delta_frac")
        lines.append(
            f"  {mark} {c['metric']}: current={c.get('current')} "
            f"baseline={c.get('baseline')} tol={c['tolerance']:g} "
            f"({c['direction']})"
            + (f" delta={delta:+.1%}" if delta is not None else "")
            + (f" [{c['reason']}]" if c.get("reason") else ""))
    head = f"verdict: {verdict['verdict']}"
    if verdict.get("reason"):
        head += f" ({verdict['reason']})"
    if verdict.get("scenario"):
        head += f" — scenario {verdict['scenario']}"
    return "\n".join([head] + lines)


# -- CLI ----------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="nns-bench-diff",
        description="Compare the latest BENCH_history.jsonl record of "
                    "a scenario against a committed baseline; exit 0 "
                    "pass / 1 regression / 2 missing baseline "
                    "(Documentation/observability.md)")
    p.add_argument("--history", default=HISTORY_PATH,
                   help=f"history JSONL path (default {HISTORY_PATH})")
    p.add_argument("--scenario", required=True,
                   help="scenario name recorded by bench.py --history "
                        "(batching, serving, edge, chaos, openloop)")
    p.add_argument("--baseline", default=None,
                   help="baseline JSON: a spec file with a 'metrics' "
                        "mapping (per-metric tolerance/direction) or a "
                        "raw BENCH_*.json (its 'value' is compared); "
                        "exactly one of --baseline/--against")
    p.add_argument("--against", default=None, metavar="RECORD",
                   help="compare against another HISTORY RECORD of the "
                        "scenario instead of a baseline file: an index "
                        "(0-based; negative from the end, -2 = "
                        "second-latest) or a git-sha prefix — every "
                        "scalar is compared at the default tolerance, "
                        "direction 'higher'")
    p.add_argument("--record", default=None, metavar="RECORD",
                   help="which history record is 'current' (same "
                        "selector grammar as --against; default: the "
                        "scenario's latest)")
    p.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                   help="default relative tolerance for metrics that "
                        "don't carry their own (default 0.10)")
    p.add_argument("--json", dest="as_json", action="store_true",
                   help="print the verdict as JSON instead of text")
    return p


def main(argv=None, out=None) -> int:
    out = out or sys.stdout
    parser = build_parser()
    args = parser.parse_args(argv)
    if (args.baseline is None) == (args.against is None):
        parser.error("exactly one of --baseline / --against required")
    records = [r for r in read_history(args.history)
               if r.get("scenario") == args.scenario]
    baseline = None
    if args.against is not None:
        against = select_record(records, args.against)
        if against is not None:
            baseline = record_as_baseline(against, args.tolerance)
    elif os.path.isfile(args.baseline):
        try:
            with open(args.baseline) as f:
                baseline = json.load(f)
        except ValueError:
            baseline = None
    if args.record is not None:
        record = select_record(records, args.record)
    else:
        record = records[-1] if records else None
    verdict = diff(record, baseline, default_tolerance=args.tolerance)
    if args.as_json:
        print(json.dumps(verdict, indent=1), file=out)
    else:
        print(_render_text(verdict), file=out)
    return _EXIT[verdict["verdict"]]


if __name__ == "__main__":
    sys.exit(main())

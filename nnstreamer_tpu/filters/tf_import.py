"""Minimal TensorFlow frozen-GraphDef importer: protobuf walk + graph → JAX.

Parity target: the reference's tensorflow filter sub-plugin
(/root/reference/ext/nnstreamer/tensor_filter/tensor_filter_tensorflow.cc
— loads a frozen .pb through the TF C API session).  TPU-native
redesign, same policy as the .tflite importer: no TF runtime — a
hand-rolled protobuf walk (no protoc codegen, like the wire codecs)
reads NodeDefs/attrs/const tensors, and the graph is rebuilt as one
jittable JAX function XLA compiles for the accelerator.

Covers the reference's frozen test models (mnist.pb,
conv_actions_frozen.pb): Placeholder, Const, Identity, MatMul,
Add/BiasAdd, Softmax, Reshape, Conv2D, Relu, MaxPool, and the speech
preprocessing ops DecodeWav (host-side WAV container parse —
the jitted graph starts at PCM), AudioSpectrogram and Mfcc
(reimplemented from the TF op semantics: Hann window, pow2 FFT,
HTK-style mel filterbank, ortho DCT-II).  Anything else raises with
the op name.
"""

from __future__ import annotations

import math
import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .importer_util import batch_flex_target

# -- protobuf wire-format walk ------------------------------------------------


from ..converters.codecs import _read_varint as _varint


def _signed64(v: int) -> int:
    """Protobuf varint ints are 64-bit two's complement."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _fields(b: bytes):
    """Yield (field_number, wire_type, value) over a message's bytes;
    value is int for varint/fixed, bytes for length-delimited."""
    p = 0
    n = len(b)
    while p < n:
        tag, p = _varint(b, p)
        f, w = tag >> 3, tag & 7
        if w == 0:
            v, p = _varint(b, p)
        elif w == 1:
            v = struct.unpack_from("<Q", b, p)[0]
            p += 8
        elif w == 2:
            ln, p = _varint(b, p)
            v = b[p:p + ln]
            p += ln
        elif w == 5:
            v = struct.unpack_from("<I", b, p)[0]
            p += 4
        else:
            raise ValueError(f"graphdef: unsupported wire type {w}")
        yield f, w, v


def _f32_of(v: int) -> float:
    return struct.unpack("<f", struct.pack("<I", v & 0xFFFFFFFF))[0]


# TF DataType enum → numpy
_DT_NP = {1: np.float32, 2: np.float64, 3: np.int32, 4: np.uint8,
          5: np.int16, 6: np.int8, 9: np.int64, 10: np.bool_}


def _parse_tensor(b: bytes) -> np.ndarray:
    """TensorProto: dtype=1, tensor_shape=2, tensor_content=4,
    float_val=5, int_val=7, int64_val=10."""
    dtype = np.float32
    dt_code = 1
    shape: List[int] = []
    content = b""
    floats: List[float] = []
    ints: List[int] = []
    for f, w, v in _fields(b):
        if f == 1:
            dt_code = v
            if v not in _DT_NP:
                raise ValueError(
                    f"graphdef: unsupported tensor dtype {v}")
            dtype = _DT_NP[v]
        elif f == 2:
            for f2, _, v2 in _fields(v):
                if f2 == 2:  # Dim
                    for f3, _, v3 in _fields(v2):
                        if f3 == 1:
                            shape.append(v3)
        elif f == 4:
            content = v
        elif f == 5:
            if w == 2:  # packed
                floats.extend(np.frombuffer(v, "<f4").tolist())
            else:
                floats.append(_f32_of(v))
        elif f == 6:  # double_val
            if w == 2:
                floats.extend(np.frombuffer(v, "<f8").tolist())
            else:
                floats.append(struct.unpack(
                    "<d", struct.pack("<Q", v))[0])
        elif f in (7, 10):
            if w == 2:
                p = 0
                while p < len(v):
                    x, p = _varint(v, p)
                    ints.append(_signed64(x))
            else:
                ints.append(_signed64(v))
    del dt_code
    if content:
        arr = np.frombuffer(content, dtype)
    elif floats:
        arr = np.asarray(floats, dtype)
    elif ints:
        arr = np.asarray(ints, dtype)
    else:
        arr = np.zeros(0, dtype)
    n = int(np.prod(shape)) if shape else arr.size
    if arr.size == 1 and n > 1:
        arr = np.full(n, arr[0], dtype)
    return arr.reshape(shape) if shape else arr


class _Attr:
    __slots__ = ("s", "i", "f", "b", "type", "tensor", "ints")

    def __init__(self):
        self.s = b""
        self.i = 0
        self.f = 0.0
        self.b = False
        self.type = 0
        self.tensor: Optional[np.ndarray] = None
        self.ints: List[int] = []


def _parse_attr(b: bytes) -> _Attr:
    """AttrValue: list=1, s=2, i=3, f=4, b=5, type=6, shape=7, tensor=8."""
    a = _Attr()
    for f, w, v in _fields(b):
        if f == 2:
            a.s = v
        elif f == 3:
            a.i = _signed64(v)
        elif f == 4:
            a.f = _f32_of(v)
        elif f == 5:
            a.b = bool(v)
        elif f == 6:
            a.type = v
        elif f == 8:
            a.tensor = _parse_tensor(v)
        elif f == 1:  # ListValue: i=3 repeated
            for f2, w2, v2 in _fields(v):
                if f2 == 3:
                    if w2 == 2:
                        p = 0
                        while p < len(v2):
                            x, p = _varint(v2, p)
                            a.ints.append(_signed64(x))
                    else:
                        a.ints.append(_signed64(v2))
    return a


class TFNode:
    __slots__ = ("name", "op", "inputs", "attrs")

    def __init__(self):
        self.name = ""
        self.op = ""
        self.inputs: List[str] = []
        self.attrs: Dict[str, _Attr] = {}


class TFGraph:
    """Parsed frozen GraphDef: name → node, topological walk by need."""

    def __init__(self, path_or_bytes):
        if isinstance(path_or_bytes, (bytes, bytearray)):
            buf = bytes(path_or_bytes)
        else:
            with open(path_or_bytes, "rb") as f:
                buf = f.read()
        self.nodes: Dict[str, TFNode] = {}
        self.order: List[TFNode] = []
        for f, w, v in _fields(buf):
            if f == 1:  # NodeDef
                n = TFNode()
                for f2, w2, v2 in _fields(v):
                    if f2 == 1:
                        n.name = v2.decode("utf-8", "replace")
                    elif f2 == 2:
                        n.op = v2.decode("utf-8", "replace")
                    elif f2 == 3:
                        n.inputs.append(v2.decode("utf-8", "replace"))
                    elif f2 == 5:  # attr map entry {key=1, value=2}
                        key = None
                        val = None
                        for f3, _, v3 in _fields(v2):
                            if f3 == 1:
                                key = v3.decode("utf-8", "replace")
                            elif f3 == 2:
                                val = _parse_attr(v3)
                        if key is not None and val is not None:
                            n.attrs[key] = val
                if not n.name:
                    continue
                self.nodes[n.name] = n
                self.order.append(n)
        if not self.nodes:
            raise ValueError("graphdef: no nodes")

    def placeholders(self) -> List[TFNode]:
        return [n for n in self.order if n.op == "Placeholder"]

    def output(self) -> TFNode:
        """The single node nobody consumes (frozen classifier shape)."""
        consumed = {i.split(":")[0].lstrip("^")
                    for n in self.order for i in n.inputs}
        outs = [n for n in self.order
                if n.name not in consumed and n.op not in
                ("Const", "Placeholder")]
        if len(outs) != 1:
            raise ValueError(
                f"graphdef: expected one output node, found "
                f"{[n.name for n in outs]}")
        return outs[0]


# -- speech preprocessing (TF op semantics) ----------------------------------


def decode_wav_bytes(data: bytes, desired_samples: int = 0,
                     desired_channels: int = 0
                     ) -> Tuple[np.ndarray, int]:
    """Host-side DecodeWav: parse a PCM16 WAV container → (samples,
    channels) float32 in [-1,1] plus sample rate (the reference feeds
    the same wav files through TF's DecodeWav,
    tests/test_models/data/yes.wav).  ``desired_samples`` > 0 trims or
    zero-pads to that length and ``desired_channels`` > 0 selects /
    duplicates channels — the TF op's normalization, so short clips
    still match the graph's declared input shape."""
    if data[:4] != b"RIFF" or data[8:12] != b"WAVE":
        raise ValueError("decode_wav: not a RIFF/WAVE file")
    p = 12
    fmt = None
    pcm = None
    rate = 16000
    while p + 8 <= len(data):
        cid = data[p:p + 4]
        (ln,) = struct.unpack_from("<I", data, p + 4)
        body = data[p + 8:p + 8 + ln]
        if cid == b"fmt ":
            fmt = struct.unpack_from("<HHIIHH", body, 0)
            rate = fmt[2]
        elif cid == b"data":
            pcm = body
        p += 8 + ln + (ln & 1)
    if fmt is None or pcm is None:
        raise ValueError("decode_wav: missing fmt/data chunk")
    channels, bits = fmt[1], fmt[5]
    if bits != 16:
        raise ValueError(f"decode_wav: only PCM16 supported, got {bits}")
    x = np.frombuffer(pcm, "<i2").astype(np.float32) / 32768.0
    x = x.reshape(-1, channels)
    if desired_channels > 0:
        if desired_channels <= x.shape[1]:
            x = x[:, :desired_channels]
        else:
            x = np.repeat(x[:, :1], desired_channels, axis=1)
    if desired_samples > 0:
        if x.shape[0] >= desired_samples:
            x = x[:desired_samples]
        else:
            x = np.pad(x, ((0, desired_samples - x.shape[0]), (0, 0)))
    return x, rate


def _hann(n: int) -> np.ndarray:
    # TF's spectrogram window (periodic Hann)
    return (0.5 - 0.5 * np.cos(2.0 * np.pi * np.arange(n) / n)).astype(
        np.float32)


def audio_spectrogram(pcm, window_size: int, stride: int,
                      magnitude_squared: bool):
    """TF AudioSpectrogram: frame → periodic Hann → pow2 FFT →
    magnitude (or squared).  ``pcm``: (samples, channels) float32 →
    (channels, frames, fft_bins)."""
    import jax.numpy as jnp

    fft_len = 1 << max(int(math.ceil(math.log2(window_size))), 0)
    x = jnp.swapaxes(pcm, 0, 1)                       # (ch, samples)
    n = x.shape[1]
    if n < window_size:
        # TF emits ZERO frames for clips shorter than one window
        return jnp.zeros((x.shape[0], 0, fft_len // 2 + 1), jnp.float32)
    frames = 1 + (n - window_size) // stride
    idx = (np.arange(frames)[:, None] * stride +
           np.arange(window_size)[None, :])
    windowed = x[:, idx] * _hann(window_size)         # (ch, fr, win)
    spec = jnp.fft.rfft(windowed, n=fft_len, axis=-1)
    mag = jnp.abs(spec)
    return (mag * mag if magnitude_squared else mag).astype(jnp.float32)


def _mel_filterbank(channels: int, fft_bins: int, rate: float,
                    lower: float, upper: float) -> np.ndarray:
    """HTK-style triangular mel filterbank, (fft_bins, channels) —
    the TF MfccMelFilterbank construction."""
    def mel(f):
        return 1127.0 * np.log1p(f / 700.0)

    centers = np.linspace(mel(lower), mel(upper), channels + 2)
    freqs = np.arange(fft_bins) * rate / ((fft_bins - 1) * 2.0)
    melf = mel(np.maximum(freqs, 1e-3))
    bank = np.zeros((fft_bins, channels), np.float32)
    for c in range(channels):
        lo, ctr, hi = centers[c], centers[c + 1], centers[c + 2]
        up_slope = (melf - lo) / max(ctr - lo, 1e-6)
        down_slope = (hi - melf) / max(hi - ctr, 1e-6)
        bank[:, c] = np.clip(np.minimum(up_slope, down_slope), 0.0, None)
    bank[0] = 0.0  # TF skips the DC bin
    return bank


def mfcc(spec, rate: float, upper: float, lower: float,
         channels: int, coeffs: int):
    """TF Mfcc: squared-magnitude spectrogram → mel energies → log →
    ortho DCT-II, first ``coeffs`` coefficients.
    ``spec``: (ch, frames, fft_bins) → (ch, frames, coeffs)."""
    import jax.numpy as jnp

    bank = _mel_filterbank(channels, spec.shape[-1], rate, lower, upper)
    mel_e = spec @ jnp.asarray(bank)
    log_e = jnp.log(jnp.maximum(mel_e, 1e-12))
    k = np.arange(coeffs)[:, None]
    n = np.arange(channels)[None, :]
    dct = (np.cos(np.pi * k * (2 * n + 1) / (2.0 * channels)) *
           np.sqrt(2.0 / channels)).astype(np.float32)
    return log_e @ jnp.asarray(dct).T


# -- graph → jax --------------------------------------------------------------


def build_fn(graph: TFGraph, sample_rate: int = 16000):
    """Compile the frozen graph into ``fn(x) -> output``.  Graphs whose
    input is a DecodeWav placeholder take the decoded (samples,
    channels) float PCM instead of wav bytes (DecodeWav is a host-side
    container parse — see :func:`decode_wav_bytes`)."""
    import jax
    import jax.numpy as jnp

    consts: Dict[str, np.ndarray] = {}
    for n in graph.order:
        if n.op == "Const" and n.attrs.get("value") is not None:
            consts[n.name] = n.attrs["value"].tensor
    phs = graph.placeholders()
    if len(phs) != 1:
        raise ValueError("graphdef: expected exactly one Placeholder")
    ph = phs[0]
    out_node = graph.output()

    structural = set()
    for n in graph.order:
        if n.op == "Reshape" and len(n.inputs) > 1:
            structural.add(n.inputs[1].split(":")[0].lstrip("^"))
        if n.op == "Mfcc" and len(n.inputs) > 1:
            structural.add(n.inputs[1].split(":")[0].lstrip("^"))
    weights = {name: arr for name, arr in consts.items()
               if name not in structural}

    # input spec: DecodeWav-fed graphs take PCM
    wav_nodes = [n for n in graph.order if n.op == "DecodeWav"]
    if wav_nodes:
        wn = wav_nodes[0]
        samples = wn.attrs.get("desired_samples")
        ch = wn.attrs.get("desired_channels")
        n_samples = int(samples.i) if samples else 0
        if n_samples <= 0:  # TF default -1 = "whole file"
            n_samples = sample_rate
        in_shape = (n_samples, max(int(ch.i) if ch else 1, 1))
        in_dtype = np.float32
    else:
        shape_attr = ph.attrs.get("shape")
        in_shape = None
        in_dtype = _DT_NP.get(ph.attrs.get("dtype", _Attr()).type,
                              np.float32)
        del shape_attr  # frozen test graphs carry unknown dims; caller
        # supplies input_spec through the filter layer

    def fn(params, x):
        vals: Dict[str, Any] = {ph.name: x}

        def get(ref):
            name = ref.split(":")[0].lstrip("^")
            if name in vals:
                return vals[name]
            if name in params:  # device-placed weights, not literals
                return jnp.asarray(params[name])
            if name in consts:
                return jnp.asarray(consts[name])
            node = graph.nodes[name]
            vals[name] = _eval(node)
            return vals[name]

        def _eval(n):
            op = n.op
            if op == "Identity":
                return get(n.inputs[0])
            if op == "Const":
                return jnp.asarray(params.get(n.name, consts[n.name]))
            if op == "DecodeWav":
                return get(n.inputs[0])  # PCM supplied as the input
            if op == "AudioSpectrogram":
                return audio_spectrogram(
                    get(n.inputs[0]),
                    int(n.attrs["window_size"].i),
                    int(n.attrs["stride"].i),
                    bool(n.attrs.get("magnitude_squared",
                                     _Attr()).b))
            if op == "Mfcc":
                a = n.attrs
                rate = float(sample_rate)
                if len(n.inputs) > 1:
                    rname = n.inputs[1].split(":")[0].lstrip("^")
                    if rname in consts:  # rate baked as a const
                        rate = float(np.asarray(consts[rname]).ravel()[0])
                # defaults apply only when the attr key is truly absent
                # — an explicit 0/0.0 value is honored (e.g.
                # lower_frequency_limit=0.0 must not become 20.0)
                return mfcc(
                    get(n.inputs[0]), rate,
                    float(a["upper_frequency_limit"].f)
                    if "upper_frequency_limit" in a else 4000.0,
                    float(a["lower_frequency_limit"].f)
                    if "lower_frequency_limit" in a else 20.0,
                    int(a["filterbank_channel_count"].i)
                    if "filterbank_channel_count" in a else 40,
                    int(a["dct_coefficient_count"].i)
                    if "dct_coefficient_count" in a else 13)
            if op == "MatMul":
                a, b = get(n.inputs[0]), get(n.inputs[1])
                if n.attrs.get("transpose_a", _Attr()).b:
                    a = a.T
                if n.attrs.get("transpose_b", _Attr()).b:
                    b = b.T
                return a @ b
            if op in ("Add", "AddV2", "BiasAdd"):
                return get(n.inputs[0]) + get(n.inputs[1])
            if op == "Softmax":
                return jax.nn.softmax(get(n.inputs[0]), axis=-1)
            if op == "Relu":
                return jnp.maximum(get(n.inputs[0]), 0.0)
            if op == "Reshape":
                v = get(n.inputs[0])
                shape = batch_flex_target(
                    tuple(int(s)
                          for s in np.asarray(consts[
                              n.inputs[1].split(":")[0]])),
                    v.shape,
                    int(x.shape[0]) if getattr(x, "ndim", 0) else 1)
                return v.reshape(shape)
            if op == "Conv2D":
                xi, w = get(n.inputs[0]), get(n.inputs[1])
                fmt = (n.attrs.get("data_format", _Attr()).s.decode()
                       or "NHWC")
                if fmt != "NHWC":
                    raise NotImplementedError(
                        f"graphdef: Conv2D data_format {fmt}")
                strides = n.attrs["strides"].ints or [1, 1, 1, 1]
                dil = n.attrs.get("dilations", _Attr()).ints or \
                    [1, 1, 1, 1]
                padding = n.attrs["padding"].s.decode() or "SAME"
                return jax.lax.conv_general_dilated(
                    xi, w, tuple(strides[1:3]), padding,
                    rhs_dilation=tuple(dil[1:3]),
                    dimension_numbers=("NHWC", "HWIO", "NHWC"))
            if op == "MaxPool":
                xi = get(n.inputs[0])
                fmt = (n.attrs.get("data_format", _Attr()).s.decode()
                       or "NHWC")
                if fmt != "NHWC":
                    raise NotImplementedError(
                        f"graphdef: MaxPool data_format {fmt}")
                ks = n.attrs["ksize"].ints or [1, 2, 2, 1]
                st = n.attrs["strides"].ints or [1, 2, 2, 1]
                padding = n.attrs["padding"].s.decode() or "SAME"
                return jax.lax.reduce_window(
                    xi, -jnp.inf, jax.lax.max, tuple(ks), tuple(st),
                    padding)
            raise NotImplementedError(
                f"graphdef: unsupported op {op} ({n.name})")

        return get(out_node.name).astype(jnp.float32)

    return fn, weights, in_shape, in_dtype

"""``python3`` decoder: user script serializes tensors however it wants.

Parity target: /root/reference/ext/nnstreamer/tensor_decoder/
tensordec-python3.cc (421 LoC) with the script contract of
tests/test_models/models/custom_decoder.py: the script defines class
``CustomDecoder`` with

- ``getOutCaps() -> str|bytes`` — the output mimetype / caps string;
- ``decode(raw_data, in_info, rate_n, rate_d) -> bytes`` — serialize the
  frame; ``raw_data`` is a list of per-tensor uint8 payload arrays and
  ``in_info`` a list of info objects exposing ``dims`` (innermost-first)
  and ``np_dtype`` (plus reference-style ``getDims()``/``getType()``).

Usage: ``tensor_decoder mode=python3 option1=FILE.py``.
"""

from __future__ import annotations

import importlib.util
import os
from typing import Optional

import numpy as np

from ..core import (
    Buffer,
    Caps,
    CapsStruct,
    Tensor,
    TensorSpec,
    TensorsSpec,
    shape_to_dims,
)
from . import Decoder, register_decoder


class _TensorInfoView:
    """Per-tensor schema handed to the user script."""

    def __init__(self, spec: TensorSpec):
        self.dims = list(spec.dims)
        self.np_dtype = spec.dtype.np_dtype
        self.type_value = int(spec.dtype.value)

    # reference-style accessors (custom_decoder.py calls these)
    def getDims(self):
        return list(self.dims)

    def getType(self):
        return self.np_dtype


def _load_script(path: str):
    if not os.path.isfile(path):
        raise FileNotFoundError(f"python3 decoder script not found: {path}")
    name = "nns_tpu_dec_" + os.path.splitext(os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    if not hasattr(mod, "CustomDecoder"):
        raise AttributeError(f"{path}: script must define class CustomDecoder")
    return mod.CustomDecoder()


@register_decoder
class Python3Decoder(Decoder):
    MODE = "python3"

    def __init__(self):
        super().__init__()
        self._obj = None

    def options_updated(self) -> None:
        path = self.options[0]
        if path:
            self._obj = _load_script(path)

    def _require(self):
        if self._obj is None:
            raise RuntimeError(
                "python3 decoder needs option1=<script.py>")
        return self._obj

    def out_caps(self, in_spec: TensorsSpec) -> Caps:
        caps = self._require().getOutCaps()
        if isinstance(caps, bytes):
            caps = caps.decode()
        if "," in caps or "=" in caps:
            from ..runtime.parser import parse_caps_string

            return parse_caps_string(caps)
        return Caps.new(CapsStruct.make(caps, framerate=in_spec.rate))

    def decode(self, buf: Buffer, in_spec: Optional[TensorsSpec]) -> Buffer:
        obj = self._require()
        raw = [np.frombuffer(t.tobytes(), np.uint8) for t in buf.tensors]
        infos = [_TensorInfoView(t.spec) for t in buf.tensors]
        rate = in_spec.rate if in_spec is not None and in_spec.rate else None
        rate_n = int(rate.numerator) if rate else 0
        rate_d = int(rate.denominator) if rate else 1
        out = obj.decode(raw, infos, rate_n, rate_d)
        arr = np.frombuffer(bytes(out), np.uint8)
        return Buffer(
            tensors=[Tensor(arr, TensorSpec.from_shape(arr.shape, np.uint8))],
            pts=buf.pts, duration=buf.duration, meta=dict(buf.meta))

#!/usr/bin/env python
"""In-pipeline training: record a dataset with datareposink, then train
MobileNet through ``datareposrc ! tensor_trainer`` and run inference
with the saved model — the full MLOps loop from getting-started §5.

    python examples/train_pipeline.py [epochs]

Uses the 8-virtual-device CPU mesh by default so the sharded train step
is exercised anywhere; on a TPU host drop the env vars to train on the
chip.
"""

import os
import sys
import tempfile

# sharded train step on 8 virtual devices (set BEFORE jax initializes);
# remove to use the real accelerator
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def record_dataset(workdir: str, n: int = 32, size: int = 8,
                   classes: int = 4):
    """appsrc ! datareposink — write n labeled samples + JSON descriptor."""
    from nnstreamer_tpu.core import Buffer, TensorsSpec
    from nnstreamer_tpu.elements.basic import AppSrc
    from nnstreamer_tpu.runtime import Pipeline
    from nnstreamer_tpu.runtime.registry import make

    data = os.path.join(workdir, "train.dat")
    js = os.path.join(workdir, "train.json")
    spec = TensorsSpec.parse(f"3:{size}:{size}:1,1:1", "float32,int32")
    p = Pipeline()
    src = AppSrc(name="src", spec=spec)
    snk = make("datareposink", el_name="sink", location=data, json=js)
    p.add(src, snk).link(src, snk)
    rng = np.random.default_rng(0)
    with p:
        for i in range(n):
            label = i % classes
            # learnable toy data: per-class mean offset + noise
            x = (rng.standard_normal((1, size, size, 3)) * 0.1
                 + label / classes).astype(np.float32)
            src.push_buffer(Buffer.of(x, np.array([[label]], np.int32)))
        src.end_of_stream()
        assert p.wait_eos(timeout=60)
    print(f"recorded {n} samples -> {data}")
    return data, js


def train(data: str, js: str, save: str, epochs: int, n: int):
    """datareposrc ! tensor_trainer (jax-optax, sharded over the mesh)."""
    from nnstreamer_tpu.elements.basic import AppSink
    from nnstreamer_tpu.runtime import Pipeline
    from nnstreamer_tpu.runtime.events import MessageKind
    from nnstreamer_tpu.runtime.registry import make

    def init(rng):
        from nnstreamer_tpu.models.mobilenet import mobilenet_v1_init

        return mobilenet_v1_init(rng, num_classes=4, width=0.25)

    p = Pipeline()
    src = make("datareposrc", el_name="src", location=data, json=js,
               is_shuffle=True, epochs=epochs, seed=1)
    trn = make("tensor_trainer", el_name="trainer", framework="jax-optax",
               model_config={
                   "apply":
                       "nnstreamer_tpu.models.mobilenet:mobilenet_v1_apply",
                   "init": init, "batch_size": 8, "lr": 5e-3,
                   "mesh": "data:-1"},  # data-parallel over all devices
               model_save_path=save, num_inputs=1, num_labels=1,
               num_training_samples=n, epochs=epochs)
    snk = AppSink(name="status", max_buffers=4096)
    p.add(src, trn, snk).link(src, trn, snk)

    def on_msg(m):
        if m.kind == MessageKind.ELEMENT and \
                m.data.get("event") == "epoch-completion":
            st = m.data
            print(f"epoch {int(st.get('epoch', -1))}: "
                  f"loss={st.get('training_loss', float('nan')):.4f} "
                  f"acc={st.get('training_accuracy', float('nan')):.3f}")
    p.bus.add_watch(on_msg)
    with p:
        assert p.wait_eos(timeout=600), "training did not complete"
    print(f"saved params -> {save}")


def infer(save: str, size: int = 8):
    """The saved model loads straight into the single-shot filter."""
    from nnstreamer_tpu.elements.filter import FilterSingle

    with FilterSingle(framework="jax-xla", model=save) as f:
        x = np.full((8, size, size, 3), 0.75, np.float32)  # class-3-ish
        logits = np.asarray(f.invoke([x])[0])
        print("single-shot inference logits shape:", logits.shape,
              "argmax:", logits.argmax(-1).tolist())


def main(epochs: int = 3):
    with tempfile.TemporaryDirectory() as d:
        data, js = record_dataset(d)
        save = os.path.join(d, "model.pkl")
        train(data, js, save, epochs=epochs, n=32)
        infer(save)


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 3)

"""``tensor_decoder`` element: dispatch to decoder sub-plugins by mode.

Parity target: /root/reference/gst/nnstreamer/elements/gsttensor_decoder.c
(1010 LoC): ``mode=`` selects the sub-plugin, option1..option9 configure it.
"""

from __future__ import annotations

from typing import Optional

from ..core import Buffer, Caps
from ..decoders import Decoder, find_decoder
from ..runtime.element import NegotiationError, Pad, TransformElement
from ..runtime.registry import register_element


@register_element("tensor_decoder")
class TensorDecoder(TransformElement):
    FACTORY = "tensor_decoder"

    def __init__(self, name=None, mode: str = "", **props):
        self.mode = mode
        self.option1 = self.option2 = self.option3 = ""
        self.option4 = self.option5 = self.option6 = ""
        self.option7 = self.option8 = self.option9 = ""
        super().__init__(name, **props)
        self._dec: Optional[Decoder] = None

    def _decoder(self) -> Decoder:
        if self._dec is None:
            if not self.mode:
                raise NegotiationError(f"{self.name}: mode not set")
            self._dec = find_decoder(self.mode)()
            for i in range(9):
                v = getattr(self, f"option{i + 1}")
                if v:
                    self._dec.set_option(i, str(v))
        return self._dec

    def propose_src_caps(self, pad: Pad) -> Caps:
        in_spec = self.sinkpad.spec
        if in_spec is None:
            raise NegotiationError(
                f"{self.name}: decoder needs tensor input caps")
        try:
            return self._decoder().out_caps(in_spec)
        except (ValueError, KeyError) as e:
            raise NegotiationError(f"{self.name}: {e}") from e

    def pad_template_caps(self, pad: Pad) -> Caps:
        return Caps.any_tensors() if pad.direction.value == "sink" else \
            Caps.any()

    def transform(self, buf: Buffer) -> Buffer:
        dec = self._decoder()
        # Host decoders read every tensor on host: start ALL device→host
        # copies before the first blocking read, so a multi-tensor frame
        # (e.g. boxes/classes/scores/num) costs one device round-trip
        # instead of one per tensor — on remote/tunneled devices each
        # blocking fetch is ~100 ms.  A device-rendering decoder
        # (bounding_boxes option7=device) consumes the tensors in HBM,
        # and a device-PREREDUCING one (argmax/top-k/packed drain of a
        # device-resident frame) drains only its small reduced result —
        # for both, prefetching would pay the full transfer for data
        # nobody reads.
        if dec.wants_host_input() and not dec.prereduce_active(buf):
            for t in buf.tensors:
                t.prefetch_host()
        return dec.decode(buf, self.sinkpad.spec)

"""Per-tenant attribution of the shared serving path.

The pool layer (PR 3/12) coalesces many pipelines' frames into one
cross-stream window, and PR 7's cost attribution times each sampled
dispatch's host/device phases — but a window mixes *tenants* (the
``tenant=`` stream property on ``tensor_filter``), and nothing said
who consumed the device-seconds.  This module is the process-wide
store behind ``nns_tenant_*``: every pool dispatch splits its
phase-split device time across the tenants that parked useful frames
in the window, proportionally to their frame counts.

The headline invariant is EXACT, not approximate: the split happens
on the SAME ``t1``/``t2`` clock reads the pool's
``nns_invoke_device_seconds`` histogram observes, converted once to
integer nanoseconds and partitioned with the residual assigned to the
window's largest tenant — so the sum over tenants of attributed
device time equals the pool's total with zero drift, dispatch after
dispatch (``exactness()`` exposes both integer accumulators; the
capacity bench and the unit test pin their equality).  Dollars are
derived at scrape time — device-seconds × the
:func:`~nnstreamer_tpu.obs.hwspec.chip_hour_price` figure
(``NNS_TPU_CHIP_HOUR_USD`` overridable) — never stored, so a price
change never has to rewrite history.

SLO attainment rides the same demux loop the admission controller's
latency signal comes from: each demuxed frame's ingress→demux latency
is graded against the pool SLO per tenant, so
``nns_tenant_slo_attainment`` answers "whose frames made it" with the
exact latencies the shedder acted on.  Sheds are counted per tenant
and reason at the same seam ``nns_admission_shed_total`` counts them.

Pulled by the metrics registry at scrape time like every collected
stat: the snapshot's ``tenants`` table (v9), the
``nns_tenant_{device_seconds,frames,dollars,shed}_total`` /
``nns_tenant_slo_attainment`` families, and nns-top's TENANT section.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from . import hooks as _hooks

#: fast-path flag (same contract as obs/transfer.py / obs/stagestat.py)
ACTIVE = not _hooks.DISABLED

#: the tenant every stream belongs to unless its filter says otherwise
DEFAULT_TENANT = "default"


class _TenantRow:
    __slots__ = ("frames", "device_ns", "lat_total", "lat_within",
                 "shed")

    def __init__(self):
        self.frames = 0
        self.device_ns = 0
        self.lat_total = 0       # demuxed frames graded against the SLO
        self.lat_within = 0      # ... of which landed within it
        self.shed: Dict[str, int] = {}


class TenantStats:
    """Process-wide, thread-safe per-(pool, tenant) attribution store."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rows: Dict[Tuple[str, str], _TenantRow] = {}
        # per-pool total device time, the OTHER side of the exactness
        # invariant: accumulated from the very same integer-ns values
        # the per-tenant shares partition
        self._pool_ns: Dict[str, int] = {}

    def _row(self, pool: str, tenant: str) -> _TenantRow:
        key = (str(pool), str(tenant) or DEFAULT_TENANT)
        row = self._rows.get(key)
        if row is None:
            row = self._rows[key] = _TenantRow()
        return row

    def record_window(self, pool: str, tenant_frames: Dict[str, int],
                      device_ns: Optional[int] = None) -> None:
        """Attribute one pool dispatch: ``tenant_frames`` maps tenant →
        useful frames it parked in the window.  ``device_ns`` (the
        sampled dispatch's device phase, integer nanoseconds from the
        same two clock reads ``nns_invoke_device_seconds`` observes) is
        split proportionally by frame count with the integer residual
        going to the largest tenant — so the per-tenant shares sum to
        ``device_ns`` EXACTLY.  None on unsampled dispatches (no
        ``block_until_ready`` fence → no honest device time): frames
        still count, device time doesn't — mirroring the histogram,
        which also only sees sampled windows."""
        items = [(str(t) or DEFAULT_TENANT, int(n))
                 for t, n in tenant_frames.items() if int(n) > 0]
        if not items:
            return
        total = sum(n for _t, n in items)
        with self._lock:
            for tenant, n in items:
                self._row(pool, tenant).frames += n
            if device_ns is None:
                return
            device_ns = int(device_ns)
            self._pool_ns[str(pool)] = \
                self._pool_ns.get(str(pool), 0) + device_ns
            shares = [(tenant, n, device_ns * n // total)
                      for tenant, n in items]
            residual = device_ns - sum(s for _t, _n, s in shares)
            # deterministic residual home: the largest tenant (first
            # such in dict order on ties) — it moves the relative
            # attribution least
            big = max(range(len(shares)), key=lambda i: shares[i][1])
            for i, (tenant, _n, share) in enumerate(shares):
                self._row(pool, tenant).device_ns += \
                    share + (residual if i == big else 0)

    def record_latency(self, pool: str, tenant: str, lat_s: float,
                       slo_s: float) -> None:
        """Grade one demuxed frame's ingress→demux latency against the
        pool SLO — the same per-frame signal the admission controller
        observes, attributed to the frame's tenant."""
        with self._lock:
            row = self._row(pool, tenant)
            row.lat_total += 1
            if lat_s <= slo_s:
                row.lat_within += 1

    def record_shed(self, pool: str, tenant: str, reason: str,
                    frames: int = 1) -> None:
        """Count frames shed at admission, per tenant and reason
        (``slo`` / ``queue-full`` — the same reasons
        ``nns_admission_shed_total`` partitions by)."""
        with self._lock:
            shed = self._row(pool, tenant).shed
            shed[str(reason)] = shed.get(str(reason), 0) + int(frames)

    # -- pull side -----------------------------------------------------------

    def exactness(self, pool: str) -> Tuple[int, int]:
        """``(sum over tenants of attributed device-ns, pool total
        device-ns)`` — equal by construction; the exactness test and
        the capacity bench assert it stays that way."""
        with self._lock:
            tenant_ns = sum(r.device_ns for (p, _t), r
                            in self._rows.items() if p == str(pool))
            return tenant_ns, self._pool_ns.get(str(pool), 0)

    def snapshot(self) -> List[dict]:
        """Rows for the registry's ``tenants`` table (v9), sorted by
        (pool, tenant).  Dollars derive from the CURRENT chip-hour
        price (``obs/hwspec.py``, env-overridable) — attribution stores
        time, never money."""
        from .hwspec import chip_hour_price

        usd_per_s = chip_hour_price() / 3600.0
        with self._lock:
            rows = [(pool, tenant, r.frames, r.device_ns, r.lat_total,
                     r.lat_within, dict(r.shed))
                    for (pool, tenant), r in sorted(self._rows.items())]
        out: List[dict] = []
        for pool, tenant, frames, ns, lt, lw, shed in rows:
            dev_s = ns / 1e9
            out.append({
                "pool": pool, "tenant": tenant,
                "frames": frames,
                "device_seconds": dev_s,
                "dollars": dev_s * usd_per_s,
                "slo_attainment": (lw / lt) if lt else None,
                "slo_frames": lt,
                "shed": shed,
            })
        return out

    def reset(self) -> None:
        """Tests/bench only: drop every row."""
        with self._lock:
            self._rows.clear()
            self._pool_ns.clear()


#: the process-wide store the pool dispatch / admission seams feed
TENANT_STATS = TenantStats()


def record_window(pool: str, tenant_frames: Dict[str, int],
                  device_ns: Optional[int] = None) -> None:
    """Module-level shim (inert under the global obs kill switch;
    never raises into the hot path)."""
    if not ACTIVE:
        return
    try:
        TENANT_STATS.record_window(pool, tenant_frames, device_ns)
    except Exception:  # noqa: BLE001 - telemetry must not kill a dispatch
        pass


def record_latency(pool: str, tenant: str, lat_s: float,
                   slo_s: float) -> None:
    if not ACTIVE:
        return
    try:
        TENANT_STATS.record_latency(pool, tenant, lat_s, slo_s)
    except Exception:  # noqa: BLE001 - telemetry must not kill a dispatch
        pass


def record_shed(pool: str, tenant: str, reason: str,
                frames: int = 1) -> None:
    if not ACTIVE:
        return
    try:
        TENANT_STATS.record_shed(pool, tenant, reason, frames)
    except Exception:  # noqa: BLE001 - telemetry must not kill a dispatch
        pass

"""``nns-top`` — live per-pipeline terminal view (gst-top / NNShark
parity for this runtime), fleet-capable.

Renders, per registered pipeline, one row per element: frames/s in/out
(counter deltas between two registry snapshots), queue depth/capacity,
rolling invoke latency, dispatches/s, batch occupancy — plus one row per
serving-pool entry (refcount, attached streams, cross-stream dispatch
rate, frames/dispatch, stream occupancy, parked frames) and one LINK
row per edge connection (tx/rx bytes and messages per second, RTT,
in-flight, timeouts, reconnects — the ``nns_edge_*`` family).  When an
``obs/watch.py`` watchdog exported alert state into the scraped
registry, an ALERTS section renders every rule's firing state and
cumulative fire count (``nns_alert_state`` / ``nns_alerts_fired_total``).

Data source:

- ``--connect HOST:PORT`` scrapes the ``/json`` endpoint of any process
  serving its registry (``serve_metrics(port)`` or the
  ``NNS_TPU_METRICS_PORT`` env hook) — observe a running serve bench
  without instrumenting it.  Repeat the flag (or comma-separate) to
  watch a FLEET: every endpoint's pipelines/pools/links render in one
  table, sectioned per host.  In live mode an endpoint that stops
  answering shows as ``unreachable (retrying)`` and polling continues —
  a restarting server doesn't kill the dashboard;
- with no ``--connect``, the *in-process* global registry is read
  (embedding ``top.main(["--once"])`` in a host application or test).
  ``NNS_TPU_METRICS_PORT`` set in the environment doubles as the
  default connect target, so ``NNS_TPU_METRICS_PORT=9464 nns-top``
  observes the process that exported on that port.

``--once`` takes two samples ``--interval`` apart, prints one table and
exits; the default live mode repaints every interval until Ctrl-C.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

CLEAR = "\x1b[2J\x1b[H"


# the one scrape/parse implementation (incl. the truncated-JSON /
# HTTPException tolerance) lives in obs/scrape.py, shared with the
# watchdog's fleet mode; re-exported here because embedding callers and
# tests monkeypatch `top.fetch_snapshot`
from .scrape import fetch_snapshot  # noqa: F401 - re-export

from . import scrape as _scrape


def fetch_fleet(endpoints: List[Optional[str]]) -> List[dict]:
    """One sample per endpoint (see :func:`obs.scrape.fetch_fleet`);
    routes through THIS module's ``fetch_snapshot`` name so a
    monkeypatched fetch is honored."""
    return _scrape.fetch_fleet(endpoints, fetch=fetch_snapshot)


# -- rate math ---------------------------------------------------------------


def _index(snap: dict) -> Dict[Tuple[str, str], dict]:
    out = {}
    for p in snap.get("pipelines", []):
        for row in p.get("elements", []):
            out[(p["pipeline"], row["element"])] = row
    return out


def _pool_index(snap: dict) -> Dict[str, dict]:
    return {row["pool"]: row for row in snap.get("pools", [])}


def _xfer_index(snap: dict) -> Dict[Tuple[str, str], Tuple[int, int]]:
    """(pipeline, source) -> (total crossings, total bytes) summed over
    directions/reasons — the XFER B/s and X/FRAME columns' source."""
    out: Dict[Tuple[str, str], Tuple[int, int]] = {}
    for row in snap.get("transfers", []):
        key = (row["pipeline"], row["source"])
        c, b = out.get(key, (0, 0))
        out[key] = (c + row["count"], b + row["bytes"])
    return out


def _exec_index(snap: dict) -> Dict[str, List[dict]]:
    """model name -> executable rows (the MFU column's source: the
    scrape-time join already annotated live mfu per bucket)."""
    out: Dict[str, List[dict]] = {}
    for row in snap.get("executables", []):
        out.setdefault(row["source"], []).append(row)
    return out


def _mfu_of(execs: Dict[str, List[dict]],
            model: Optional[str]) -> Optional[float]:
    """Best live MFU across the model's executables (None when the
    backend has no known hardware spec or nothing was measured)."""
    if not model:
        return None
    vals = [r["mfu"] for r in execs.get(model, []) if "mfu" in r]
    return max(vals) if vals else None


def _rate(cur: float, prev: Optional[float], dt: float) -> Optional[float]:
    if prev is None or dt <= 0:
        return None
    return max(cur - prev, 0) / dt


def _fmt(v, width: int, prec: int = 1) -> str:
    if v is None:
        return "-".rjust(width)
    if isinstance(v, float):
        return f"{v:.{prec}f}".rjust(width)
    return str(v).rjust(width)


# -- rendering ---------------------------------------------------------------


def _dev_host_us(stats: dict) -> Tuple[Optional[int], Optional[int]]:
    """(device µs, host µs) of a filter/pool stats dict: the rolling
    phase means from the cost-attribution split — host is prep+drain.
    None before the first sampled dispatch (and for snapshots from
    older processes that don't carry the fields)."""
    dev = stats.get("device_us", -1)
    prep = stats.get("host_prep_us", -1)
    drain = stats.get("host_drain_us", -1)
    if dev is None or dev < 0:
        return None, None
    host = max(prep, 0) + max(drain, 0)
    return dev, host


def render(cur: dict, prev: Optional[dict] = None) -> str:
    """One terminal table from a snapshot (rates need ``prev``)."""
    dt = (cur.get("time", 0) - prev.get("time", 0)) if prev else 0.0
    prev_rows = _index(prev) if prev else {}
    prev_pools = _pool_index(prev) if prev else {}
    xfers = _xfer_index(cur)
    prev_xfers = _xfer_index(prev) if prev else {}
    lines: List[str] = []
    execs = _exec_index(cur)
    hdr = (f"{'ELEMENT':<18}{'FACTORY':<18}{'IN/s':>9}{'OUT/s':>9}"
           f"{'QUEUE':>9}{'LAT µs':>9}{'DEV µs':>9}{'HOST µs':>9}"
           f"{'MFU%':>7}{'DISP/s':>9}{'B-OCC':>7}{'S-OCC':>7}"
           f"{'XFER B/s':>11}{'X/FRAME':>9}")
    for p in cur.get("pipelines", []):
        state = "PLAYING" if p.get("playing") else "STOPPED"
        lines.append(f"pipeline {p['pipeline']} [{state}]")
        lines.append("  " + hdr)
        for row in p.get("elements", []):
            pv = prev_rows.get((p["pipeline"], row["element"]), {})
            stats = row.get("stats", {})
            pstats = pv.get("stats", {})
            fin = _rate(stats.get("buffers_in", 0),
                        pstats.get("buffers_in"), dt)
            fout = _rate(stats.get("buffers_out", 0),
                         pstats.get("buffers_out"), dt)
            q = row.get("queue")
            qcol = f"{q['depth']}/{q['capacity']}" if q else None
            f = row.get("filter")
            lat = disp = bocc = socc = dev = host = mfu = None
            if f:
                lat = f["latency_us"] if f["latency_us"] >= 0 else None
                pf = pv.get("filter") or {}
                disp = _rate(f["invokes"], pf.get("invokes"), dt)
                bocc = f["avg_batch_occupancy"]
                socc = f["avg_stream_occupancy"]
                dev, host = _dev_host_us(f)
                m = _mfu_of(execs, f.get("model"))
                mfu = m * 100.0 if m is not None else None
            # row absent from prev = first crossings happened inside
            # this window: delta from zero, like the stats columns
            xrate, xpf = _xfer_cols(
                xfers.get((p["pipeline"], row["element"])),
                prev_xfers.get((p["pipeline"], row["element"]),
                               (0, 0) if prev else None),
                stats.get("buffers_in", 0), pstats.get("buffers_in"),
                dt)
            lines.append(
                "  " + f"{row['element']:<18.18}{row['factory']:<18.18}"
                + _fmt(fin, 9) + _fmt(fout, 9)
                + (qcol.rjust(9) if qcol else "-".rjust(9))
                + _fmt(lat, 9, 0) + _fmt(dev, 9, 0) + _fmt(host, 9, 0)
                + _fmt(mfu, 7, 2) + _fmt(disp, 9) + _fmt(bocc, 7, 2)
                + _fmt(socc, 7, 2) + _fmt(xrate, 11, 0)
                + _fmt(xpf, 9, 2))
        lines.append("")
    pools = cur.get("pools", [])
    if pools:
        lines.append(
            f"{'POOL':<28}{'REF':>5}{'STREAMS':>9}{'DISP/s':>9}"
            f"{'FRM/DISP':>10}{'S-OCC':>7}{'PENDING':>9}{'LAT µs':>9}"
            f"{'DEV µs':>9}{'HOST µs':>9}{'MFU%':>7}{'HIT/MISS':>10}"
            f"{'XFER B/s':>11}{'WGT MB':>8}"
            f"{'SHARE%':>8}{'IMBAL':>8}{'PAD%':>7}")
        for row in pools:
            s = row["stats"]
            ps = (prev_pools.get(row["pool"]) or {}).get("stats", {})
            disp = _rate(s["invokes"], ps.get("invokes"), dt)
            pend = (row.get("batcher") or {}).get("pending")
            lat = s["latency_us"] if s["latency_us"] >= 0 else None
            dev, host = _dev_host_us(s)
            m = _mfu_of(execs, row.get("model"))
            mfu = m * 100.0 if m is not None else None
            cache = row.get("cache")
            hm = f"{cache['hits']}/{cache['misses']}" if cache else None
            xrate, _xpf = _xfer_cols(
                xfers.get(("", row["pool"])),
                prev_xfers.get(("", row["pool"]),
                               (0, 0) if prev else None), 0, None, dt)
            w = row.get("weights")
            wmb = w["bytes"] / 1e6 if w else None
            # mesh join (sharded pools only): hottest shard's share of
            # the pool's frames, window imbalance, pad waste — the
            # pool's skew next to its MFU instead of pages away
            pm = row.get("mesh")
            share = pm["max_shard_share"] * 100.0 if pm else None
            imbal = pm["imbalance"] if pm else None
            padp = pm["pad_frac"] * 100.0 if pm else None
            lines.append(
                f"{row['pool']:<28.28}" + _fmt(row["refcount"], 5)
                + _fmt(row["streams"], 9) + _fmt(disp, 9)
                + _fmt(s["avg_batch_occupancy"], 10, 2)
                + _fmt(s["avg_stream_occupancy"], 7, 2)
                + _fmt(pend, 9) + _fmt(lat, 9, 0)
                + _fmt(dev, 9, 0) + _fmt(host, 9, 0)
                + _fmt(mfu, 7, 2)
                + (hm.rjust(10) if hm else "-".rjust(10))
                + _fmt(xrate, 11, 0) + _fmt(wmb, 8, 1)
                + _fmt(share, 8, 1) + _fmt(imbal, 8, 3)
                + _fmt(padp, 7, 2))
        lines.append("")
    tenants = cur.get("tenants", [])
    if tenants:
        # tenancy view (obs/tenantstat.py): who consumed the pools'
        # device-seconds — frames, exactly-attributed device time,
        # scrape-time dollars, SLO attainment, sheds
        prev_ten = {(r["pool"], r["tenant"]): r
                    for r in (prev or {}).get("tenants", [])}
        lines.append(
            f"{'TENANT':<16}{'POOL':<26}{'FRM/s':>9}{'FRAMES':>10}"
            f"{'DEV s':>9}{'$':>9}{'$/KFRM':>9}{'SLO%':>7}{'SHED':>7}")
        for row in tenants:
            pv = prev_ten.get((row["pool"], row["tenant"]), {})
            frate = _rate(row["frames"], pv.get("frames"), dt)
            dpk = (row["dollars"] / row["frames"] * 1e3) \
                if row["frames"] else None
            slo = row["slo_attainment"] * 100.0 \
                if row["slo_attainment"] is not None else None
            shed = sum(row.get("shed", {}).values())
            lines.append(
                f"{row['tenant']:<16.16}{row['pool']:<26.26}"
                + _fmt(frate, 9) + _fmt(row["frames"], 10)
                + _fmt(row["device_seconds"], 9, 3)
                + _fmt(row["dollars"], 9, 4) + _fmt(dpk, 9, 4)
                + _fmt(slo, 7, 1) + _fmt(shed, 7))
        lines.append("")
    stages = cur.get("stages", [])
    if stages:
        # pipeline-split view (stagestat.py): handoff rows show the
        # device-to-device flow INTO a stage's subset — rate of exact
        # payload bytes, frames, and the inter-stage queue depth
        # (handed off but not yet emitted); offload rows show a routing
        # tensor_if's cascade split.  Dashes mark the columns the other
        # kind owns.
        prev_stages = {(r["kind"], r["pipeline"], r["stage"]): r
                       for r in (prev or {}).get("stages", [])}
        lines.append(
            f"{'STAGE':<20}{'PIPELINE':<14}{'KIND':<9}{'ROUTE':<14}"
            f"{'HANDOFF B/s':>13}{'FRM/s':>8}{'DEPTH':>7}"
            f"{'OFFLOAD%':>10}{'OFF/KEPT':>11}")
        for row in stages:
            pv = prev_stages.get(
                (row["kind"], row["pipeline"], row["stage"]), {})
            if row["kind"] == "handoff":
                brate = _rate(row["bytes"], pv.get("bytes"), dt)
                frate = _rate(row["frames"], pv.get("frames"), dt)
                route = f"{row['from']}>{row['to']}"
                lines.append(
                    f"{row['stage']:<20.20}{row['pipeline']:<14.14}"
                    f"{'handoff':<9}{route:<14.14}"
                    + _fmt(brate, 13, 0) + _fmt(frate, 8)
                    + _fmt(row["depth"], 7)
                    + "-".rjust(10) + "-".rjust(11))
            else:
                ok = f"{row['offloaded']}/{row['kept']}"
                lines.append(
                    f"{row['stage']:<20.20}{row['pipeline']:<14.14}"
                    f"{'offload':<9}{(row['to'] or '-'):<14.14}"
                    + "-".rjust(13) + "-".rjust(8) + "-".rjust(7)
                    + _fmt(row["ratio"] * 100.0, 10, 1)
                    + ok.rjust(11))
        lines.append("")
    models = cur.get("models", [])
    if models:
        # model lifecycle (runtime/lifecycle.py): version registry of
        # every pool that swapped/canaried — per-version serving stats
        # next to state + provenance
        prev_models = {(r["pool"], r["version"]): r
                       for r in (prev or {}).get("models", [])}
        lines.append(
            f"{'MODELS':<28}{'VERSION':<12}{'STATE':<12}{'FRM/s':>9}"
            f"{'FRAMES':>10}{'LAT µs':>9}{'ERRORS':>8}{'CANARY':>8}"
            f"{'LOAD s':>8}  SOURCE")
        for row in models:
            pv = prev_models.get((row["pool"], row["version"]), {})
            frate = _rate(row["frames"], pv.get("frames"), dt)
            lat = row["latency_us"] if row["latency_us"] >= 0 else None
            canary = f"1/{row['canary_n']}" if row.get("canary_n") \
                else "-"
            lines.append(
                f"{row['pool']:<28.28}{row['version']:<12.12}"
                f"{row['state']:<12.12}"
                + _fmt(frate, 9) + _fmt(row["frames"], 10)
                + _fmt(lat, 9, 0) + _fmt(row["errors"], 8)
                + canary.rjust(8) + _fmt(row["load_s"], 8, 3)
                + f"  {row.get('source', '')}"[:40])
        lines.append("")
    mesh = cur.get("mesh", [])
    if mesh:
        from .meshstat import shard_device_label

        prev_mesh = {r["source"]: r for r in (prev or {}).get("mesh", [])}
        lines.append(
            f"{'MESH':<24}{'TOPOLOGY':<16}{'SHARD':>7}{'DEVICE':>22}"
            f"{'FRAMES':>10}{'FRM/s':>9}{'SHARE%':>8}{'IMBAL':>8}"
            f"{'PAD%':>7}{'REPL':>6}")
        for row in mesh:
            topo = ",".join(f"{n}:{s}" for n, s in row["axes"])
            pv = prev_mesh.get(row["source"], {})
            total = sum(row["shard_frames"]) or 1
            psf = pv.get("shard_frames", [])
            for i, n in enumerate(row["shard_frames"]):
                dev = shard_device_label(row, i, empty="-")
                frate = _rate(n, psf[i] if i < len(psf) else None, dt)
                lines.append(
                    (f"{row['source']:<24.24}" if i == 0
                     else " " * 24)
                    + (f"{topo:<16.16}" if i == 0 else " " * 16)
                    + _fmt(i, 7) + dev[:22].rjust(22)
                    + _fmt(n, 10) + _fmt(frate, 9, 0)
                    + _fmt(n / total * 100.0, 8, 1)
                    + (_fmt(row["imbalance"], 8, 3) if i == 0
                       else "-".rjust(8))
                    + (_fmt(row["pad_frac"] * 100.0, 7, 2) if i == 0
                       else "-".rjust(7))
                    + (_fmt(row["replicated_dispatches"], 6)
                       if i == 0 else "-".rjust(6)))
        lines.append("")
    devmem = cur.get("device_memory", [])
    if devmem:
        lines.append(
            f"{'DEVICE':<28}{'IN-USE MB':>11}{'PEAK MB':>10}"
            f"{'LIMIT MB':>10}")
        for row in devmem:
            lines.append(
                f"{row['device']:<28.28}"
                + _fmt(_mb(row.get("in_use")), 11, 1)
                + _fmt(_mb(row.get("peak")), 10, 1)
                + _fmt(_mb(row.get("limit")), 10, 1))
        lines.append("")
    compiles = cur.get("compiles", [])
    if compiles:
        prev_comp = _compile_index(prev) if prev else {}
        lines.append(
            f"{'COMPILE':<16}{'KIND':<10}{'BUCKET':>8}{'COUNT':>8}"
            f"{'TOTAL ms':>11}{'NEW':>5}")
        for row in compiles:
            key = (row["framework"], row["kind"], row["bucket"])
            # a row absent from the previous snapshot is ALL new — the
            # first 'reload' or a fresh bucket executable is exactly
            # the in-window compile this column exists to surface
            new = row["count"] - prev_comp.get(key, 0) if prev else 0
            lines.append(
                f"{row['framework']:<16.16}{row['kind']:<10.10}"
                + (row["bucket"] if row["bucket"] != "0"
                   else "-").rjust(8)
                + _fmt(row["count"], 8)
                + _fmt(row["seconds"] * 1e3, 11, 1)
                + _fmt(new, 5))
        lines.append("")
    links = cur.get("links", [])
    if links:
        prev_links = _link_index(prev) if prev else {}
        lines.append(
            f"{'LINK':<16}{'PEER':<22}{'KIND':<13}{'TX/s':>10}"
            f"{'RX/s':>10}{'MSG/s':>8}{'RTT µs':>9}{'INFL':>6}"
            f"{'TO':>5}{'RECON':>7}{'BRKR':>6}{'BKOFF':>7}")
        for row in links:
            pv = prev_links.get((row["kind"], row["link"], row["peer"]),
                                {})
            txr = _rate(row["tx_bytes"], pv.get("tx_bytes"), dt)
            rxr = _rate(row["rx_bytes"], pv.get("rx_bytes"), dt)
            msgr = _rate(row["tx_msgs"] + row["rx_msgs"],
                         (pv["tx_msgs"] + pv["rx_msgs"]) if pv else None,
                         dt)
            rtt = _window_rtt_us(row["rtt"], pv.get("rtt"))
            brkr = {0: "ok", 1: "half", 2: "OPEN"}.get(
                row.get("breaker_state", 0), "?")
            lines.append(
                f"{row['link']:<16.16}{row['peer']:<22.22}"
                f"{row['kind']:<13.13}"
                + _fmt(txr, 10, 0) + _fmt(rxr, 10, 0) + _fmt(msgr, 8)
                + _fmt(rtt, 9, 0) + _fmt(row["inflight"], 6)
                + _fmt(row["timeouts"], 5) + _fmt(row["reconnects"], 7)
                + brkr.rjust(6) + _fmt(row.get("backoff_level", 0), 7))
        lines.append("")
    fc = cur.get("forecasts") or {}
    if fc.get("rules") or fc.get("capacity"):
        # predictive view (obs/forecast.py): each forecast rule's
        # fitted trajectory + crossing ETA, then the capacity join —
        # forecast arrivals vs sustainable rate per pool
        lines.append(
            f"{'FORECAST':<22}{'METRIC':<30}{'VALUE@H':>10}"
            f"{'THRESH':>9}{'ETA s':>8}{'HRZN s':>8}{'STATE':>8}")
        for row in fc.get("rules", []):
            eta = row.get("eta_s")
            lines.append(
                f"{row['rule']:<22.22}{row['metric']:<30.30}"
                + _fmt(row.get("value"), 10, 1)
                + _fmt(row.get("threshold"), 9, 1)
                + _fmt(eta, 8, 1)
                + _fmt(row.get("horizon_s"), 8, 0)
                + ("FIRING" if row.get("firing") else "ok").rjust(8))
        for row in fc.get("capacity", []):
            lines.append(
                f"{'capacity':<22.22}{row['pool']:<30.30}"
                + _fmt(row.get("predicted_fps"), 10, 1)
                + _fmt(row.get("sustainable_fps"), 9, 1)
                + "-".rjust(8) + "-".rjust(8)
                + (f"{row['headroom'] * 100.0:+.0f}%").rjust(8))
        lines.append("")
    alerts = _alert_rows(cur)
    if alerts:
        lines.append(
            f"{'ALERT':<28}{'SEVERITY':<10}{'STATE':>8}{'FIRED':>7}")
        for row in alerts:
            lines.append(
                f"{row['rule']:<28.28}{row['severity']:<10.10}"
                + ("FIRING" if row["state"] else "ok").rjust(8)
                + _fmt(row["fired"], 7))
        lines.append("")
    profd = cur.get("profile") or {}
    prows = profd.get("elements", [])
    if prows:
        # host-execution view (obs/prof.py): per element-loop thread,
        # CPU%/RUN%/WAIT% over the sampling window (exact accounting),
        # SAMP% lifetime profiler sample share, then the top sampled
        # stacks and the profiler's own state
        prev_prof = {}
        for r in ((prev or {}).get("profile") or {}).get("elements",
                                                         []):
            prev_prof[(r["pipeline"], r["element"])] = r
        lines.append(
            f"{'PROF ELEMENT':<18}{'PIPELINE':<16}{'CPU%':>7}"
            f"{'RUN%':>7}{'WAIT%':>7}{'SAMP%':>7}{'ITERS':>9}")
        for row in prows:
            pv = prev_prof.get((row["pipeline"], row["element"]), {})
            cpu = _rate(row["cpu_s"], pv.get("cpu_s"), dt)
            run = _rate(row["run_s"], pv.get("run_s"), dt)
            wait = _rate(row["wait_s"], pv.get("wait_s"), dt)
            lines.append(
                f"{row['element']:<18.18}{row['pipeline']:<16.16}"
                + _fmt(cpu * 100.0 if cpu is not None else None, 7, 1)
                + _fmt(run * 100.0 if run is not None else None, 7, 1)
                + _fmt(wait * 100.0 if wait is not None else None,
                       7, 1)
                + _fmt(row.get("sample_share", 0.0) * 100.0, 7, 1)
                + _fmt(row.get("iters"), 9, 0))
        for s in profd.get("stacks", [])[:3]:
            leaf = s["stack"].rsplit(";", 1)[-1]
            lines.append(f"  top stack: {s['label']} {leaf} "
                         f"x{s['count']}")
        psum = profd.get("profiler") or {}
        if psum.get("running"):
            lines.append(
                f"  profiler: {psum.get('hz', 0):g} Hz  ticks "
                f"{psum.get('ticks', 0)}  stacks "
                f"{psum.get('stacks', 0)}  gil_waiters "
                f"{profd.get('gil_waiters', 0)}")
        lines.append("")
    ctl = cur.get("control") or {}
    if ctl.get("controllers"):
        lines.append(
            f"CONTROL  playbooks: "
            f"{','.join(ctl.get('playbooks', [])) or '-'}  "
            f"actions: {ctl.get('actions_total', 0)}")
        audit = ctl.get("audit", [])
        if audit:
            # the one decision-row renderer, shared with `nns-ctl
            # --audit` so the two views can never drift
            from .control import render_audit

            lines.append(render_audit(audit[-6:], indent="  "))
        lines.append("")
    if not cur.get("pipelines") and not pools and not links:
        lines.append("(no registered pipelines, pools or links)")
    return "\n".join(lines)


def _alert_rows(snap: dict) -> List[dict]:
    """The ALERTS table: the watchdog's exported ``nns_alert_state``
    gauges joined with the ``nns_alerts_fired_total`` counters (empty
    when no ``obs/watch.py`` watchdog exported into this registry —
    local or scraped alike, since both ride the snapshot's flat metric
    families)."""
    fams = snap.get("metrics", {})
    state = fams.get("nns_alert_state", {})
    fired = {}
    for s in fams.get("nns_alerts_fired_total", {}).get("samples", []):
        key = (s["labels"].get("rule", "?"),
               s["labels"].get("severity", "?"))
        fired[key] = s["value"]
    rows = []
    for s in state.get("samples", []):
        rule = s["labels"].get("rule", "?")
        sev = s["labels"].get("severity", "?")
        rows.append({"rule": rule, "severity": sev,
                     "state": bool(s["value"]),
                     "fired": int(fired.get((rule, sev), 0))})
    # firing first, then by name — the live view surfaces trouble
    rows.sort(key=lambda r: (not r["state"], r["rule"]))
    return rows


def _mb(v) -> Optional[float]:
    return v / 1e6 if v is not None else None


def _xfer_cols(cur: Optional[Tuple[int, int]],
               prev: Optional[Tuple[int, int]],
               frames_in: int, prev_frames_in: Optional[int],
               dt: float) -> Tuple[Optional[float], Optional[float]]:
    """(XFER B/s, crossings-per-frame) of one element/pool over the
    sampling window: byte-rate from the ledger's cumulative bytes, and
    crossings over the window divided by the frames the element took
    in over the same window."""
    if cur is None:
        return None, None
    count, nbytes = cur
    pc, pb = prev if prev is not None else (None, None)
    brate = _rate(nbytes, pb, dt)
    xpf = None
    if pc is not None and prev_frames_in is not None:
        dframes = frames_in - prev_frames_in
        if dframes > 0:
            xpf = max(count - pc, 0) / dframes
    return brate, xpf


def _link_index(snap: dict) -> Dict[Tuple[str, str, str], dict]:
    return {(r["kind"], r["link"], r["peer"]): r
            for r in snap.get("links", [])}


def _compile_index(snap: dict) -> Dict[Tuple[str, str, str], int]:
    """(framework, kind, bucket) -> count, for the NEW column (compiles
    that happened during the sampling window — a nonzero NEW on a
    steady-state pipeline is a recompile leak)."""
    return {(r["framework"], r["kind"], r["bucket"]): r["count"]
            for r in snap.get("compiles", [])}


def _window_rtt_us(cur_rtt: dict, prev_rtt: Optional[dict]
                   ) -> Optional[float]:
    """Mean RTT over the sampling window (cumulative sum/count deltas);
    falls back to the all-time mean for the first sample."""
    if prev_rtt:
        dn = cur_rtt["count"] - prev_rtt["count"]
        if dn > 0:
            return (cur_rtt["sum_s"] - prev_rtt["sum_s"]) / dn * 1e6
    return cur_rtt.get("mean_us")


def render_fleet(samples: List[dict],
                 prev: Dict[str, Optional[dict]],
                 show_host: bool) -> str:
    """One table for N endpoints: per-host section headers when the
    fleet has more than one member (or when asked), unreachable
    endpoints called out without dropping their section."""
    parts: List[str] = []
    for entry in samples:
        ep = entry["endpoint"]
        if entry["snap"] is None:
            parts.append(f"endpoint {ep}: unreachable (retrying) — "
                         f"{entry['error']}")
            parts.append("")
            continue
        if show_host:
            host = entry["snap"].get("host", "")
            parts.append(f"endpoint {ep}" + (f" [{host}]" if host else ""))
        parts.append(render(entry["snap"], prev.get(ep)))
    return "\n".join(parts)


# -- CLI ---------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="nns-top",
        description="Live per-pipeline observability table "
                    "(Documentation/observability.md)")
    p.add_argument("--connect", metavar="HOST:PORT[,HOST:PORT...]",
                   action="append", default=None,
                   help="scrape a remote process's /json metrics "
                        "endpoint; repeat (or comma-separate) for a "
                        "fleet — every endpoint renders in one table "
                        "(default: in-process registry, or "
                        "127.0.0.1:$NNS_TPU_METRICS_PORT when set)")
    p.add_argument("--once", action="store_true",
                   help="print one table (two samples --interval apart) "
                        "and exit")
    p.add_argument("--interval", type=float, default=2.0,
                   help="seconds between samples/repaints (default 2)")
    p.add_argument("--json", dest="as_json", action="store_true",
                   help="dump the raw snapshot JSON instead of the table")
    return p


def _default_connect() -> Optional[str]:
    port = os.environ.get("NNS_TPU_METRICS_PORT", "")
    return f"127.0.0.1:{port}" if port else None


def _endpoints(args) -> List[Optional[str]]:
    """Normalize --connect into the endpoint list: flatten repeats and
    comma lists.  No flag at all → the env default or the in-process
    registry; an explicit empty value (``--connect ""``) always means
    the in-process registry, env var or not."""
    eps: List[Optional[str]] = []
    for item in args.connect or []:
        for tok in str(item).split(","):
            tok = tok.strip()
            if tok:
                eps.append(tok)
    if not eps:
        eps.append(None if args.connect is not None
                   else _default_connect())
    return eps


def main(argv=None, out=None) -> int:
    out = out or sys.stdout
    args = build_parser().parse_args(argv)
    endpoints = _endpoints(args)
    # hosts label every remote section; the bare in-process view keeps
    # the old single-table shape
    show_host = any(ep is not None for ep in endpoints)
    try:
        if args.as_json:
            samples = fetch_fleet(endpoints)
            doc = samples[0]["snap"] if len(samples) == 1 \
                else {s["endpoint"]: s["snap"] for s in samples}
            if len(samples) == 1 and samples[0]["error"]:
                print(f"nns-top: cannot reach {samples[0]['endpoint']}: "
                      f"{samples[0]['error']}", file=sys.stderr)
                return 1
            print(json.dumps(doc, indent=1), file=out)
            return 0
        if args.once:
            first = fetch_fleet(endpoints)
            time.sleep(max(args.interval, 0.05))
            cur = fetch_fleet(endpoints)
            prev = {s["endpoint"]: s["snap"] for s in first}
            print(render_fleet(cur, prev, show_host), file=out)
            # --once against a fully dead fleet is an error; a partial
            # outage still rendered what answered
            if all(s["snap"] is None for s in cur):
                for s in cur:
                    print(f"nns-top: cannot reach {s['endpoint']}: "
                          f"{s['error']}", file=sys.stderr)
                return 1
            return 0
        prev: Dict[str, Optional[dict]] = {}
        while True:
            cur = fetch_fleet(endpoints)
            if out is sys.stdout and out.isatty():
                out.write(CLEAR)
            print(render_fleet(cur, prev, show_host), file=out)
            out.flush()
            # a dead endpoint keeps its last snapshot as rate baseline
            # for when it comes back
            for s in cur:
                if s["snap"] is not None:
                    prev[s["endpoint"]] = s["snap"]
            time.sleep(max(args.interval, 0.05))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())

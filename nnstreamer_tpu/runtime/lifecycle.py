"""Zero-downtime model lifecycle: hot swap, canarying, auto-verdict.

The last missing leg of the serve-measure-steer loop (ROADMAP item 3):
production serving could *measure* everything about a pool but could
not ship a new checkpoint into it — ``share-model`` refused
``is-updatable`` (PR 3), and a reload elsewhere recompiled inline on
the dispatch path.  This module is the model *lifecycle* layer on top
of the serving pool:

- :class:`ModelVersion` / :class:`VersionManager` — a per-
  :class:`~nnstreamer_tpu.runtime.serving.PoolEntry` registry of model
  versions with per-version
  :class:`~nnstreamer_tpu.utils.stats.InvokeStats` and error counts,
  exported as the ``nns_model_version_*`` registry families, the
  snapshot v7 ``models`` table, and the ``nns-top`` MODELS section.

- **Double-buffered hot swap**: :meth:`VersionManager.stage` resolves
  a (possibly versioned — ``filters/modeluri.py``) model reference and
  builds a fully-warmed SHADOW instance off the dispatch path
  (``JaxXlaFilter.prepare_swap``: single-frame + every hot bucket
  executable compiled and first-called) while the old executable keeps
  serving; :meth:`VersionManager.swap` flips atomically at a *window
  boundary* (the batcher's flush serialization lock) — zero dropped
  frames, and the measured flip stall is a pointer swap bounded well
  under one window deadline (:attr:`VersionManager.last_swap_stall_s`,
  gated by ``bench.py --lifecycle``).

- **Canarying with automatic verdict**: ``canary=<tag>:1/N``
  (pool-level ``tensor_filter`` property, or the ``canary`` actuator)
  routes 1-in-N *streams* of the pool to the staged version.  Canary
  windows dispatch through the shadow instance — a failing canary
  errors only its own streams' buses — and export the comparator pair
  ``nns_model_canary_latency_us`` / ``nns_model_baseline_latency_us``
  (+ ``nns_model_canary_errors_total``), so a plain nns-watch
  threshold rule with ``per=`` IS the canary judge, and an nns-ctl
  playbook on the ``promote``/``rollback`` actuators closes the loop
  (promotion and rollback both land in PR 11's decision audit ring).

Every knob is exposed through the actuator API
(``runtime/actuators.py``, kind ``model``): ``swap`` and ``canary``
take the model reference as a TEXT value (``nns-ctl --apply
model:<pool>:swap=file://new.pkl@v2``), ``promote``/``rollback`` are
numeric and playbook-drivable.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..utils.log import logi, logw
from ..utils.stats import InvokeStats

#: version states, also exported numerically on
#: ``nns_model_version_state`` (staged=0 serving=1 canary=2 retired=3
#: rolled-back=4)
STATES = ("staged", "serving", "canary", "retired", "rolled-back")

#: default minimum canary frames before ``promote`` is allowed —
#: a canary that served nothing has proven nothing (override per
#: manager, or force=True)
MIN_CANARY_FRAMES = 16


class LifecycleError(ValueError):
    """A lifecycle operation that cannot apply (bad canary grammar,
    nothing staged, premature promote)."""


def parse_canary(spec: str) -> Tuple[str, int]:
    """``"<tag>:1/N"`` → ``(tag, N)``; ``""`` → ``("", 0)`` (no
    canary).  ``tag`` names the version the split applies to — use
    ``next`` for "whatever gets staged next".  The short form
    ``"1/N"`` implies ``next``."""
    s = str(spec or "").strip()
    if not s:
        return "", 0
    tag, sep, ratio = s.rpartition(":")
    if not sep:
        tag, ratio = "", s
    tag = tag.strip() or "next"
    num, sep, den = ratio.partition("/")
    try:
        if not sep or int(num) != 1:
            raise ValueError
        n = int(den)
    except ValueError:
        raise LifecycleError(
            f"canary spec {spec!r}: want '<version>:1/N' (or '1/N'), "
            f"e.g. 'next:1/4' — one in N streams routes to the canary"
        ) from None
    if n < 2:
        raise LifecycleError(
            f"canary spec {spec!r}: N must be >= 2 (1/1 is a full "
            f"swap — use the swap actuator)")
    return tag, n


class ModelVersion:
    """One version of a pool's model: identity + provenance + its own
    serving stats.  ``subplugin`` is the live instance serving this
    version — the pool's shared instance for the baseline, the
    prepared shadow for a staged/canary version."""

    def __init__(self, tag: str, source: str, subplugin: Any,
                 state: str = "staged"):
        self.tag = str(tag)
        self.source = str(source)
        self.subplugin = subplugin
        self.state = state
        self.stats = InvokeStats()
        self.errors = 0  # failed dispatches attributed to this version
        self.staged_wall = time.time()
        self.load_s = 0.0  # off-path load+compile+warm seconds

    def row(self, pool: str, canary_n: int) -> dict:
        s = self.stats.snapshot()
        return {
            "pool": pool,
            "version": self.tag,
            "state": self.state,
            "source": self.source,
            "invokes": s["invokes"],
            "frames": s["frames"],
            "latency_us": s["latency_us"],
            "errors": self.errors,
            "canary_n": canary_n if self.state == "canary" else 0,
            "load_s": round(self.load_s, 6),
            "staged_wall": self.staged_wall,
        }


class VersionManager:
    """Per-PoolEntry double-buffered version registry + the swap /
    canary / promote / rollback state machine.

    Thread model: mutations (stage/swap/promote/rollback/canary
    routing) serialize on ``self._lock``; the FLIP itself additionally
    holds the pool batcher's flush-serialization lock so it lands
    between windows.  The dispatch path only ever reads
    ``self._canary``/``self._assign`` through
    :meth:`partition`/:meth:`subplugin_for` — one dict read, no lock
    ordering against the dispatch."""

    def __init__(self, entry: Any):
        import weakref

        self._entry_ref = weakref.ref(entry)
        self._lock = threading.RLock()
        sp = entry.subplugin
        self.baseline = ModelVersion(
            "v0", self._source_of(sp), sp, state="serving")
        self._canary: Optional[ModelVersion] = None
        self._staged: Optional[ModelVersion] = None
        self.canary_n = 0
        self.default_canary: Tuple[str, int] = ("", 0)  # canary= prop
        self.min_canary_frames = MIN_CANARY_FRAMES
        #: stream routing: id(owner) -> True when the stream rides the
        #: canary version (rebuilt on canary start, extended on attach)
        self._assign: Dict[int, bool] = {}
        self._attach_seq = 0
        self.swaps = 0
        self.promotes = 0
        self.rollbacks = 0
        self._rollback_ref: Optional[ModelVersion] = None
        self.last_swap_stall_s = 0.0
        self.history: List[dict] = []  # bounded swap provenance trail
        self._actuators: Dict[str, Any] = {}
        self._seq = 0  # version sequence for auto tags

    # -- introspection --------------------------------------------------------

    @property
    def entry(self):
        e = self._entry_ref()
        if e is None:
            from .actuators import ActuationError

            raise ActuationError(
                "model lifecycle: the owning pool entry is gone")
        return e

    @staticmethod
    def _source_of(sp: Any) -> str:
        mn = getattr(sp, "model_name", None)
        return str(mn()) if callable(mn) else ""

    @property
    def canary_active(self) -> bool:
        return self._canary is not None and self.canary_n > 1

    @property
    def engaged(self) -> bool:
        """Whether the lifecycle has actually been USED (a stage, swap,
        canary or rollback happened).  Actuator discovery constructs
        managers for every pool; a merely-discovered pool must not
        start exporting version rows — the `models` table stays
        "pools whose lifecycle was engaged" either way."""
        with self._lock:
            return bool(self.swaps or self.promotes or self.rollbacks
                        or self._staged is not None
                        or self._canary is not None
                        or len(self.history))

    def versions(self) -> List[ModelVersion]:
        with self._lock:
            out = [self.baseline]
            if self._canary is not None:
                out.append(self._canary)
            if self._staged is not None and self._staged is not self._canary:
                out.append(self._staged)
            return out

    def snapshot_rows(self) -> List[dict]:
        """The ``models`` table rows of this pool (snapshot v7)."""
        label = self._entry_label()
        with self._lock:
            n = self.canary_n
            rows = [v.row(label, n) for v in self.versions()]
        return rows

    def summary(self) -> dict:
        """Pool-level lifecycle figures (swaps/promotes/rollbacks +
        the live comparator pair) for the registry export."""
        with self._lock:
            out = {
                "swaps": self.swaps,
                "promotes": self.promotes,
                "rollbacks": self.rollbacks,
                "canary_n": self.canary_n if self.canary_active else 0,
                "canary_streams": sum(
                    1 for c in self._assign.values() if c),
                "last_swap_stall_s": self.last_swap_stall_s,
            }
            if self.canary_active:
                out["canary_version"] = self._canary.tag
                out["canary_latency_us"] = self._canary.stats.latency_us
                out["baseline_latency_us"] = self.baseline.stats.latency_us
                out["canary_errors"] = self._canary.errors
                out["canary_frames"] = \
                    self._canary.stats.total_frame_num
        return out

    def _entry_label(self) -> str:
        e = self._entry_ref()
        return e.label() if e is not None else "?"

    def _note(self, event: str, **data) -> None:
        rec = {"event": event, "wall": time.time(), **data}
        with self._lock:
            self.history.append(rec)
            del self.history[:-64]
        from ..obs.flightrec import FLIGHT

        FLIGHT.note("lifecycle", f"{self._entry_label()}:{event}",
                    **{k: v for k, v in data.items()
                       if isinstance(v, (str, int, float, bool))})

    # -- stage ----------------------------------------------------------------

    def stage(self, model: Any, version: str = "",
              warm: bool = True) -> ModelVersion:
        """Load + compile a replacement OFF the dispatch path: resolve
        the (possibly ``@``-versioned) reference, build the warmed
        shadow instance via the framework's ``prepare_swap``, and park
        it as the staged version.  The old executable serves throughout
        — this can take seconds and drops nothing.  Staging again
        replaces a previously staged (un-canaried) version."""
        from ..filters.api import FilterError
        from ..filters.modeluri import resolve_model_uri_versioned
        from .actuators import ActuationError

        entry = self.entry
        resolved, tag = resolve_model_uri_versioned(model)
        if isinstance(resolved, str) and _is_orbax_dir(resolved):
            # orbax checkpoint (step) directory: weights-only swap —
            # load the pytree and keep the serving architecture
            from ..trainers.checkpoint import load_orbax

            source = str(resolved)
            resolved = load_orbax(resolved)
        else:
            source = resolved if isinstance(resolved, str) \
                else getattr(resolved, "name", repr(type(resolved)))
        with self._lock:
            self._seq += 1
            version = str(version or tag or f"v{self._seq}")
        sp = entry.subplugin
        prep_fn = getattr(sp, "prepare_swap", None)
        if not callable(prep_fn):
            raise ActuationError(
                f"{entry.label()}: framework "
                f"{getattr(sp, 'NAME', type(sp).__name__)!r} has no "
                f"prepare_swap — it does not support hot reload "
                f"(nns-lint NNS513 flags is-updatable on it)")
        t0 = time.perf_counter()
        buckets = entry.buckets if entry.batcher is not None else ()
        try:
            shadow = prep_fn(resolved, buckets=buckets, warm=warm)
        except FilterError as e:
            raise ActuationError(
                f"{entry.label()}: staging {source!r} failed: {e}"
            ) from e
        ver = ModelVersion(version, f"{source}@{tag}" if tag else source,
                           shadow)
        ver.load_s = time.perf_counter() - t0
        with self._lock:
            self._staged = ver
        self._note("stage", version=version, source=ver.source,
                   load_s=round(ver.load_s, 4))
        logi("%s: staged model version %s (%s) in %.3fs off-path",
             self._entry_label(), version, ver.source, ver.load_s)
        return ver

    # -- the flip -------------------------------------------------------------

    def _window_boundary(self):
        """Context guard serializing against the pool's in-flight
        window: holding the batcher's flush lock means no window is
        mid-dispatch, so the flip lands BETWEEN windows.  Pools without
        a live batcher (per-frame fallback) flip under the entry lock
        alone — the framework's ``_swap_lock`` already keeps any single
        dispatch consistent."""
        entry = self.entry
        b = entry.batcher
        if b is not None:
            return b._flush_serial_lock
        return threading.Lock()  # uncontended stand-in

    def swap(self, version: Optional[ModelVersion] = None) -> dict:
        """Commit the staged (or given) version as the serving model:
        the double-buffer flip, at a window boundary, stall measured.
        Frames parked in the window simply ride the next dispatch on
        the new version — nothing is dropped, nothing re-queues."""
        from .actuators import ActuationError

        entry = self.entry
        with self._lock:
            ver = version or self._staged
            if ver is None:
                raise ActuationError(
                    f"{entry.label()}: nothing staged to swap in "
                    f"(stage a model first: swap=<model-ref>)")
        sp = entry.subplugin
        # retain the OUTGOING version's executable state BEFORE the
        # flip: post-commit the shared instance serves the new model,
        # so "swap back" needs this holder (commit_swap-compatible)
        prior_state = _swap_state_of(sp)
        t0 = time.perf_counter()
        with self._window_boundary():
            sp.commit_swap(ver.subplugin)
            stall = time.perf_counter() - t0
        with self._lock:
            old = self.baseline
            old.state = "retired"
            old.subplugin = prior_state
            ver.state = "serving"
            # the new baseline serves THROUGH the pool's shared
            # instance; the canary/staged stats carry over so the
            # version's history survives promotion
            nb = ModelVersion(ver.tag, ver.source, sp, state="serving")
            nb.stats = ver.stats
            nb.load_s = ver.load_s
            self.baseline = nb
            self._rollback_ref = old
            if self._staged is ver:
                self._staged = None
            if self._canary is ver:
                self._canary = None
                self.canary_n = 0
                self._assign = {}
            self.swaps += 1
            self.last_swap_stall_s = stall
        self._note("swap", version=ver.tag, source=ver.source,
                   stall_s=round(stall, 6))
        logi("%s: hot-swapped to version %s (%s), flip stall %.3f ms",
             self._entry_label(), ver.tag, ver.source, stall * 1e3)
        return {"version": ver.tag, "stall_s": stall}

    # -- canary ---------------------------------------------------------------

    def start_canary(self, n: int,
                     version: Optional[ModelVersion] = None) -> dict:
        """Route 1-in-``n`` attached streams to the staged version.
        Stream assignment is deterministic (attach order): every
        ``n``-th stream rides the canary; streams attaching later keep
        the same modulus."""
        from .actuators import ActuationError

        entry = self.entry
        n = int(n)
        if n < 2:
            raise ActuationError(
                f"{entry.label()}: canary needs N >= 2 (got {n}); use "
                f"swap for a full cutover")
        with self._lock:
            ver = version or self._staged
            if ver is None:
                raise ActuationError(
                    f"{entry.label()}: nothing staged to canary "
                    f"(stage via swap=<ref> or RELOAD_MODEL first)")
            self._canary = ver
            self._staged = ver  # promote/rollback resolve to it
            ver.state = "canary"
            self.canary_n = n
            self._assign = {}
            self._attach_seq = 0
            for sid in self._stream_ids():
                self._assign[sid] = self._attach_seq % n == n - 1
                self._attach_seq += 1
        routed = sum(1 for c in self._assign.values() if c)
        self._note("canary-start", version=ver.tag, n=n,
                   streams=routed)
        logi("%s: canarying version %s on 1-in-%d streams (%d routed)",
             self._entry_label(), ver.tag, n, routed)
        return {"version": ver.tag, "n": n, "streams": routed}

    def _stream_ids(self) -> List[int]:
        e = self._entry_ref()
        if e is None:
            return []
        with e._lock:
            return list(e._streams.keys())

    def on_attach(self, owner: Any) -> None:
        """Keep the 1-in-N routing law over streams that attach while a
        canary runs."""
        with self._lock:
            if not self.canary_active:
                return
            self._assign[id(owner)] = \
                self._attach_seq % self.canary_n == self.canary_n - 1
            self._attach_seq += 1

    def on_detach(self, owner: Any) -> None:
        with self._lock:
            self._assign.pop(id(owner), None)

    def is_canary_stream(self, owner: Any) -> bool:
        return self.canary_active and self._assign.get(id(owner), False)

    def subplugin_for(self, owner: Any) -> Any:
        """The instance serving ``owner``'s frames — the canary shadow
        for canary-routed streams, the pool's shared instance
        otherwise (the per-frame fallback path reads this)."""
        if self.is_canary_stream(owner):
            c = self._canary
            if c is not None:
                return c.subplugin
        return self.entry.subplugin

    def partition(self, items: List[Any]
                  ) -> List[Tuple[ModelVersion, Any, List[Any]]]:
        """Split one window's ``(owner, buf, ...)`` items into
        per-version groups: ``[(version, subplugin, items), ...]`` in
        baseline-first order.  Per-stream FIFO holds because every
        stream maps to exactly one version."""
        canary = self._canary
        if canary is None or not self.canary_active:
            return [(self.baseline, self.entry.subplugin, items)]
        base_items, canary_items = [], []
        assign = self._assign
        for it in items:
            (canary_items if assign.get(id(it[0]), False)
             else base_items).append(it)
        out = []
        if base_items:
            out.append((self.baseline, self.entry.subplugin, base_items))
        if canary_items:
            out.append((canary, canary.subplugin, canary_items))
        return out or [(self.baseline, self.entry.subplugin, items)]

    # -- verdicts -------------------------------------------------------------

    def promote(self, force: bool = False) -> dict:
        """Commit the canary as the serving version (the healthy
        verdict) — refused until it actually served
        ``min_canary_frames`` unless forced: a canary that saw no
        traffic has proven nothing, and a playbook firing early gets a
        clean retryable failure."""
        from .actuators import ActuationError

        with self._lock:
            ver = self._canary
            if ver is None:
                raise ActuationError(
                    f"{self._entry_label()}: no canary to promote")
            served = ver.stats.total_frame_num
            if not force and served < self.min_canary_frames:
                raise ActuationError(
                    f"{self._entry_label()}: canary {ver.tag} served "
                    f"only {served}/{self.min_canary_frames} frames — "
                    f"not enough evidence to promote (force=1 "
                    f"overrides)")
        res = self.swap(ver)
        with self._lock:
            self._canary = None
            self.canary_n = 0
            self._assign = {}
            self.promotes += 1
        self._note("promote", version=ver.tag, frames=served)
        logi("%s: promoted canary %s after %d frames",
             self._entry_label(), ver.tag, served)
        return dict(res, promoted=True, frames=served)

    def rollback(self) -> dict:
        """The unhealthy verdict: stop routing to the canary and
        discard it (the baseline never stopped serving, so recovery is
        immediate); with no canary active, swap back to the retired
        pre-swap version instead (undo of the last full swap).
        Check-and-mutate happens under ONE lock acquisition, so a
        playbook and a concurrent ``nns-ctl`` firing together roll
        back once, not twice."""
        from .actuators import ActuationError

        prior = None
        with self._lock:
            ver = self._canary
            if ver is not None:
                ver.state = "rolled-back"
                self._canary = None
                if self._staged is ver:
                    self._staged = None
                self.canary_n = 0
                self._assign = {}
                self.rollbacks += 1
            else:
                # pop atomically: two concurrent full-swap rollbacks
                # must not both commit the same prior
                prior = self._rollback_ref
                self._rollback_ref = None
        if ver is not None:
            self._note("rollback", version=ver.tag,
                       errors=ver.errors,
                       frames=ver.stats.total_frame_num)
            logw("%s: rolled back canary %s (errors=%d after %d "
                 "frames) — baseline keeps serving",
                 self._entry_label(), ver.tag, ver.errors,
                 ver.stats.total_frame_num)
            return {"version": ver.tag, "rolled_back": True,
                    "canary": True}
        if prior is not None and prior.subplugin is not None \
                and getattr(prior.subplugin, "_compiled", None) is not None:
            try:
                res = self.swap(prior)
            except Exception:
                with self._lock:  # restore the undo on failure
                    self._rollback_ref = prior
                raise
            with self._lock:
                self.rollbacks += 1
            self._note("rollback", version=prior.tag, full_swap=True)
            return dict(res, rolled_back=True, canary=False)
        raise ActuationError(
            f"{self._entry_label()}: nothing to roll back (no canary "
            f"active, no prior version retained)")

    # -- dispatch-side recording (PoolEntry drives these) ---------------------

    def record(self, version: ModelVersion, latency_s: Optional[float],
               frames: int, streams: int = 1) -> None:
        if latency_s is not None:
            version.stats.record(latency_s, frames=frames,
                                 streams=streams)
        else:
            version.stats.count(frames=frames, streams=streams)

    def record_error(self, version: ModelVersion) -> None:
        with self._lock:
            version.errors += 1

    # -- actuators (runtime/actuators.py kind "model") ------------------------

    def actuators(self) -> Dict[str, Any]:
        """The lifecycle's named knobs on this pool: ``swap`` /
        ``canary`` (text-valued: the model reference), ``promote`` /
        ``rollback`` (numeric, playbook-drivable).  Built once; state
        (cooldowns) persists for the entry's lifetime."""
        with self._lock:
            if self._actuators:
                return self._actuators
        from .actuators import Actuator

        label = self._entry_label()

        def _swap(ref) -> None:
            if isinstance(ref, str) and ref.strip():
                self.stage(ref.strip())
            self.swap()

        def _canary(ref) -> None:
            if isinstance(ref, (int, float)):
                if float(ref) <= 0:
                    # numeric 0 stops the canary without a verdict
                    with self._lock:
                        if self._canary is not None:
                            self._canary.state = "staged"
                            self._staged = self._canary
                        self._canary = None
                        self.canary_n = 0
                        self._assign = {}
                    return
                self.start_canary(int(ref))
                return
            ref = str(ref).strip()
            n = 0
            if ":" in ref and "/" in ref.rsplit(":", 1)[-1]:
                # trailing :1/N ratio on the reference (the version
                # identity rides the reference's own @tag)
                head, _, ratio = ref.rpartition(":")
                try:
                    _, n = parse_canary(ratio)
                    ref = head
                except LifecycleError:
                    n = 0
            if n == 0:
                n = self.default_canary[1] or 2
            if ref:
                self.stage(ref)
            self.start_canary(n)

        built = {
            "swap": Actuator(
                "swap", "model", label,
                get_fn=lambda: self.baseline.tag,
                set_fn=_swap, unit="ref", text=True,
                # revert of a swap IS a rollback: the retained prior
                # executable state flips back (not a re-stage by tag)
                snapshot_fn=lambda: self.baseline.tag,
                restore_fn=lambda prior: self.rollback()),
            "canary": Actuator(
                "canary", "model", label,
                get_fn=lambda: float(self.canary_n),
                set_fn=_canary, unit="ref|1/N", text=True,
                snapshot_fn=lambda: float(self.canary_n),
                restore_fn=lambda prior: _canary(float(prior or 0))),
            "promote": Actuator(
                "promote", "model", label,
                get_fn=lambda: 1.0 if self.canary_active else 0.0,
                set_fn=lambda v: self.promote(force=v >= 2.0)
                if v >= 0.5 else None,
                lo=0.0, hi=2.0, unit="go"),
            "rollback": Actuator(
                "rollback", "model", label,
                get_fn=lambda: 0.0,
                set_fn=lambda v: self.rollback()
                if v >= 0.5 else None,
                lo=0.0, hi=1.0, unit="go"),
        }
        with self._lock:
            if not self._actuators:
                self._actuators = built
            return self._actuators


def _is_orbax_dir(path: str) -> bool:
    import os

    return os.path.isdir(path)


def _swap_state_of(sp: Any) -> Any:
    """Freeze a sub-plugin's live (model, executable, bucket cache)
    into a ``commit_swap``-compatible holder — what a full swap retains
    as its rollback reference."""
    import types

    with sp._swap_lock:
        model, compiled = sp._model, sp._compiled
    with sp._batch_lock:
        batch_exec = dict(sp._batch_exec)
    ns = types.SimpleNamespace(_model=model, _compiled=compiled,
                               _batch_exec=batch_exec)
    ns.model_name = (lambda: model.name if model is not None else "")
    return ns

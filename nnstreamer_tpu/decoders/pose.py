"""``pose_estimation`` decoder: keypoint heatmaps → skeleton overlay.

Parity target: /root/reference/ext/nnstreamer/tensor_decoder/
tensordec-pose.c (845 LoC): decodes PoseNet-style heatmaps (H, W, K) into
K keypoint coordinates (per-keypoint argmax + score), draws the skeleton
connecting them; option grammar:

- option1 — output size ``WIDTH:HEIGHT``
- option2 — model input size ``WIDTH:HEIGHT``
- option3 — optional label file of keypoint names
- option4 — ``heatmap-offset`` mode: refine coords with offset tensors
  (second input tensor of shape (H, W, 2K)), as posenet emits

Structured keypoints are attached at ``buffer.meta["keypoints"]``.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..core import Buffer, Caps, CapsStruct, Tensor, TensorSpec, TensorsSpec
from . import Decoder, register_decoder
from .boxutil import load_labels, sigmoid

# COCO-17 style skeleton edge list (parity: pose.c connection table)
_EDGES: Tuple[Tuple[int, int], ...] = (
    (0, 1), (1, 3), (0, 2), (2, 4), (0, 5), (0, 6), (5, 7), (7, 9),
    (6, 8), (8, 10), (5, 11), (6, 12), (11, 13), (13, 15), (12, 14),
    (14, 16), (11, 12))


@register_decoder
class PoseEstimation(Decoder):
    MODE = "pose_estimation"

    def __init__(self):
        super().__init__()
        self.out_w, self.out_h = 192, 192
        self.in_w, self.in_h = 192, 192
        self.names: List[str] = []
        self.use_offsets = False

    def options_updated(self) -> None:
        if self.options[0]:
            w, _, h = self.options[0].partition(":")
            self.out_w, self.out_h = int(w), int(h or w)
        if self.options[1]:
            w, _, h = self.options[1].partition(":")
            self.in_w, self.in_h = int(w), int(h or w)
        if self.options[2]:
            self.names = load_labels(self.options[2])
        if self.options[3]:
            self.use_offsets = self.options[3].strip() == "heatmap-offset"

    def out_caps(self, in_spec: TensorsSpec) -> Caps:
        return Caps.new(CapsStruct.make(
            "video/x-raw", format="RGBA", width=self.out_w,
            height=self.out_h, framerate=in_spec.rate))

    def _keypoints(self, buf: Buffer) -> List[dict]:
        hm = buf.tensors[0].np()
        hm = hm.reshape(hm.shape[-3], hm.shape[-2], hm.shape[-1])  # H,W,K
        H, W, K = hm.shape
        offsets = None
        if self.use_offsets and buf.num_tensors > 1:
            off = buf.tensors[1].np()
            offsets = off.reshape(off.shape[-3], off.shape[-2],
                                  off.shape[-1])
        kps = []
        for k in range(K):
            flat = int(hm[:, :, k].argmax())
            y, x = divmod(flat, W)
            score = float(sigmoid(np.asarray(hm[y, x, k])))
            if offsets is not None:
                # posenet layout: first K channels = dy, next K = dx
                py = (y / max(H - 1, 1)) * self.in_h + offsets[y, x, k]
                px = (x / max(W - 1, 1)) * self.in_w + offsets[y, x, K + k]
                nx, ny = px / self.in_w, py / self.in_h
            else:
                nx, ny = x / max(W - 1, 1), y / max(H - 1, 1)
            kps.append({
                "index": k,
                "name": self.names[k] if k < len(self.names) else str(k),
                "x": float(np.clip(nx, 0, 1)),
                "y": float(np.clip(ny, 0, 1)),
                "score": score})
        return kps

    def _draw(self, kps: List[dict]) -> np.ndarray:
        img = np.zeros((self.out_h, self.out_w, 4), np.uint8)
        green = np.array([0, 255, 0, 255], np.uint8)
        white = np.array([255, 255, 255, 255], np.uint8)
        for a, b in _EDGES:
            if a >= len(kps) or b >= len(kps):
                continue
            x0, y0 = kps[a]["x"] * (self.out_w - 1), \
                kps[a]["y"] * (self.out_h - 1)
            x1, y1 = kps[b]["x"] * (self.out_w - 1), \
                kps[b]["y"] * (self.out_h - 1)
            n = int(max(abs(x1 - x0), abs(y1 - y0))) + 1
            xs = np.linspace(x0, x1, n).astype(int)
            ys = np.linspace(y0, y1, n).astype(int)
            img[ys, xs] = white
        for kp in kps:
            x = int(kp["x"] * (self.out_w - 1))
            y = int(kp["y"] * (self.out_h - 1))
            img[max(y - 1, 0):y + 2, max(x - 1, 0):x + 2] = green
        return img

    def decode(self, buf: Buffer, in_spec: Optional[TensorsSpec]) -> Buffer:
        kps = self._keypoints(buf)
        frame = self._draw(kps)
        out = Buffer(
            tensors=[Tensor(frame,
                            TensorSpec.from_shape(frame.shape, np.uint8))],
            pts=buf.pts, duration=buf.duration, meta=dict(buf.meta))
        out.meta["keypoints"] = kps
        return out

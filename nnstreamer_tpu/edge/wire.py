"""Edge wire codec: framed messages for cross-host tensor streams.

Parity target: the nnstreamer-edge data wire the reference's L5 layer
sends over TCP/MQTT — ``nns_edge_data_create/add/set_info/send`` usage at
/root/reference/gst/nnstreamer/tensor_query/tensor_query_client.c:673-741
and gst/edge/edge_sink.c:291-322.  One message carries N tensor payloads,
each self-described by the :class:`~nnstreamer_tpu.core.meta.MetaInfo`
header (the same header flexible streams use on-pipe), plus routing info
(client id, sequence, topic) and the buffer timestamp.

Frame layout (little-endian):

    magic u32 | version u8 | mtype u8 | flags u16 |
    client_id u64 | seq u64 | pts u64 (NONE = 2^64-1) |
    info_len u32 | npayloads u32 | info bytes |
    npayloads × (len u32 | payload)

``info`` is a small UTF-8 string whose meaning depends on ``mtype``:
topic for SUBSCRIBE/PUBLISH, a caps string for CAPS_RES, empty otherwise.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import List, Optional, Sequence

from ..core import Buffer, MediaType

WIRE_MAGIC = 0x5451E55A
WIRE_VERSION = 1
PTS_NONE = (1 << 64) - 1

# message types
MSG_QUERY = 1      # client → server: run this buffer through the pipeline
MSG_REPLY = 2      # server → client: the pipeline's answer
MSG_SUBSCRIBE = 3  # edge client → edge sink server: topic subscription
MSG_PUBLISH = 4    # edge sink server → subscribers: one stream buffer
MSG_CAPS_REQ = 5   # client → server: what caps does your output have?
MSG_CAPS_RES = 6   # server → client: info = caps string

_HDR_FMT = "<IBBHQQQII"
_HDR_SIZE = struct.calcsize(_HDR_FMT)


@dataclasses.dataclass
class EdgeMessage:
    """One framed edge message."""

    mtype: int
    client_id: int = 0
    seq: int = 0
    pts: Optional[int] = None
    info: str = ""
    payloads: List[bytes] = dataclasses.field(default_factory=list)

    # -- tensor-buffer bridging ---------------------------------------------

    @classmethod
    def from_buffer(cls, mtype: int, buf: Buffer, client_id: int = 0,
                    seq: int = 0, info: str = "") -> "EdgeMessage":
        return cls(mtype=mtype, client_id=client_id, seq=seq, pts=buf.pts,
                   info=info, payloads=buf.pack_flexible(MediaType.TENSOR))

    def to_buffer(self) -> Buffer:
        buf = Buffer.unpack_flexible(self.payloads, pts=self.pts)
        buf.meta["client_id"] = self.client_id
        buf.meta["query_seq"] = self.seq
        return buf

    # -- framing -------------------------------------------------------------

    def pack(self) -> bytes:
        info_b = self.info.encode("utf-8")
        parts = [struct.pack(
            _HDR_FMT, WIRE_MAGIC, WIRE_VERSION, self.mtype, 0,
            self.client_id, self.seq,
            PTS_NONE if self.pts is None else self.pts,
            len(info_b), len(self.payloads)), info_b]
        for p in self.payloads:
            parts.append(struct.pack("<I", len(p)))
            parts.append(p)
        return b"".join(parts)

    @classmethod
    def unpack(cls, data: bytes) -> "EdgeMessage":
        if len(data) < _HDR_SIZE:
            raise ValueError(f"edge frame truncated: {len(data)}")
        (magic, version, mtype, _flags, client_id, seq, pts, info_len,
         npay) = struct.unpack_from(_HDR_FMT, data)
        if magic != WIRE_MAGIC:
            raise ValueError(f"bad edge magic 0x{magic:08x}")
        if version != WIRE_VERSION:
            raise ValueError(f"unsupported edge version {version}")
        off = _HDR_SIZE
        info = data[off:off + info_len].decode("utf-8")
        off += info_len
        payloads = []
        for _ in range(npay):
            if off + 4 > len(data):
                raise ValueError("edge frame payload table truncated")
            (n,) = struct.unpack_from("<I", data, off)
            off += 4
            if off + n > len(data):
                raise ValueError("edge frame payload truncated")
            payloads.append(data[off:off + n])
            off += n
        return cls(mtype=mtype, client_id=client_id, seq=seq,
                   pts=None if pts == PTS_NONE else pts, info=info,
                   payloads=payloads)

"""Shared box post-processing + drawing utilities for decoders.

Parity target: the IoU/NMS helpers and label handling shared by the
reference's bounding-box decoder strategies
(/root/reference/ext/nnstreamer/tensor_decoder/tensordec-boundingbox.cc and
box_properties/*; label/util code in tensordecutil.c).

These are the *host-side compatibility* implementations used by the
decoder elements on small per-frame outputs; the performance path runs
decode+NMS on-device inside the model (models/ssd.py ssd_detect_fn).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np


@dataclasses.dataclass
class Detection:
    """One detected object in normalized [0,1] image coordinates."""

    x: float  # left
    y: float  # top
    w: float
    h: float
    class_id: int
    score: float
    label: str = ""


def load_labels(path: str) -> List[str]:
    with open(path, "r", encoding="utf-8") as f:
        return [ln.strip() for ln in f if ln.strip()]


def iou_xywh(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """IoU between one box (4,) and many boxes (N,4), xywh layout."""
    ax2, ay2 = a[0] + a[2], a[1] + a[3]
    bx2, by2 = b[:, 0] + b[:, 2], b[:, 1] + b[:, 3]
    ix = np.maximum(
        0, np.minimum(ax2, bx2) - np.maximum(a[0], b[:, 0]))
    iy = np.maximum(
        0, np.minimum(ay2, by2) - np.maximum(a[1], b[:, 1]))
    inter = ix * iy
    union = a[2] * a[3] + b[:, 2] * b[:, 3] - inter
    return inter / np.maximum(union, 1e-9)


def nms(dets: List[Detection], iou_thresh: float = 0.5,
        max_out: Optional[int] = None) -> List[Detection]:
    """Greedy class-aware NMS (parity: nms() in tensordec-boundingbox.cc)."""
    out: List[Detection] = []
    by_class: dict = {}
    for d in dets:
        by_class.setdefault(d.class_id, []).append(d)
    for cid, cds in by_class.items():
        cds.sort(key=lambda d: -d.score)
        boxes = np.array([[d.x, d.y, d.w, d.h] for d in cds], np.float32)
        alive = np.ones(len(cds), bool)
        for i, d in enumerate(cds):
            if not alive[i]:
                continue
            out.append(d)
            if i + 1 < len(cds):
                sup = iou_xywh(boxes[i], boxes[i + 1:]) > iou_thresh
                alive[i + 1:] &= ~sup
    out.sort(key=lambda d: -d.score)
    return out[:max_out] if max_out else out


def sigmoid(x: np.ndarray) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-x))


def softmax(x: np.ndarray, axis: int = -1) -> np.ndarray:
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


# -- drawing (parity: draw() in tensordec-boundingbox.cc; labels are
# stamped with the bitmap-font overlay, tensordec-font.c analog) ------------


#: default overlay palette, shared by the host and device renderers
PALETTE = np.array([
    [255, 0, 0, 255], [0, 255, 0, 255], [0, 0, 255, 255],
    [255, 255, 0, 255], [255, 0, 255, 255], [0, 255, 255, 255]],
    np.uint8)

_render_cache: dict = {}


def device_render_fn(batch: int, nbox: int, height: int, width: int,
                     conf_thresh: float, thickness: int = 2):
    """Build (and cache) a jitted on-device box rasterizer.

    The TPU-native redesign of the reference's host-side ``draw()``
    (tensordec-boundingbox.cc): instead of the CPU writing rectangle
    outlines pixel-by-pixel into a mapped GstBuffer, the overlay frame is
    computed ON the accelerator as one XLA program — ``nbox`` is static,
    so the per-box loop unrolls and fuses into a single pass over the
    (batch, H, W, 4) canvas that never touches the host.

    Signature of the returned fn:
    ``render(boxes (B,N,4) ymin,xmin,ymax,xmax normalized, classes (B,N),
    scores (B,N), num (B,)) -> (B,H,W,4) uint8 RGBA``.
    Draw semantics (coordinate rounding, clipping, edge thickness, draw
    order, palette-by-class) match :func:`draw_boxes` exactly.
    """
    key = (batch, nbox, height, width, round(float(conf_thresh), 6),
           thickness)
    fn = _render_cache.get(key)
    if fn is not None:
        return fn
    import jax
    import jax.numpy as jnp

    H, W, t = height, width, thickness

    def render(boxes, classes, scores, num):
        pal = jnp.asarray(PALETTE)
        ys = jnp.arange(H, dtype=jnp.int32)[None, None, :]  # (1,1,H)
        xs = jnp.arange(W, dtype=jnp.int32)[None, None, :]  # (1,1,W)
        valid = (jnp.arange(nbox)[None, :] < num[:, None]) & \
            (scores >= conf_thresh)
        y0 = jnp.clip((boxes[..., 0] * H).astype(jnp.int32), 0, H - 1)
        x0 = jnp.clip((boxes[..., 1] * W).astype(jnp.int32), 0, W - 1)
        y1 = jnp.clip((boxes[..., 2] * H).astype(jnp.int32), 0, H - 1)
        x1 = jnp.clip((boxes[..., 3] * W).astype(jnp.int32), 0, W - 1)
        # The per-pixel edge-strip mask factors into rows ⊗ cols: a pixel
        # is on box i's outline iff (row in top/bottom strip AND col in
        # x-range) OR (row in y-range AND col in left/right strip).  The
        # strips are the EXACT slices the host renderer assigns — each
        # bounded by only ONE opposing edge, so boxes thinner than the
        # stroke paint the same extra rows/cols.  Precomputing the
        # (B,N,H)/(B,N,W) strip vectors leaves ~4 VPU ops per pixel per
        # box instead of ~14 (this rasterizer is pixel-test bound).
        yl, xl = y0[..., None], x0[..., None]          # (B,N,1)
        yh, xh = y1[..., None], x1[..., None]
        in_y = (ys >= yl) & (ys <= yh)                 # (B,N,H)
        tb = ((ys >= yl) & (ys < yl + t)) | \
            ((ys >= jnp.maximum(yh - t + 1, 0)) & (ys <= yh))
        in_x = (xs >= xl) & (xs <= xh)                 # (B,N,W)
        lr = ((xs >= xl) & (xs < xl + t)) | \
            ((xs >= jnp.maximum(xh - t + 1, 0)) & (xs <= xh))
        tb = tb & valid[..., None]
        in_y = in_y & valid[..., None]
        # Winner pass over ONE packed-RGBA (B,H,W) int32 plane (0 =
        # transparent background) instead of rewriting the 4-channel
        # canvas per box — later boxes overwrite earlier ones, the host
        # draw order.  Packing keeps the select chain single-plane and
        # the final unpack is four shift-and-masks; no gather touches
        # the 92 MB canvas (TPU gathers of 4-byte rows are ~100× slower
        # than this arithmetic).
        color = pal[classes.astype(jnp.int32) % pal.shape[0]]  # (B,N,4)
        c32 = color.astype(jnp.int32)
        pcolor = (c32[..., 0] | (c32[..., 1] << 8) | (c32[..., 2] << 16)
                  | (c32[..., 3] << 24))                       # (B,N)
        win = jnp.zeros((batch, H, W), jnp.int32)
        for i in range(nbox):  # static unroll → one fused color pass
            mask = (tb[:, i, :, None] & in_x[:, i, None, :]) | \
                (in_y[:, i, :, None] & lr[:, i, None, :])
            win = jnp.where(mask, pcolor[:, i, None, None], win)
        # little-endian bitcast: the packed int32 already holds the RGBA
        # byte order, so the (B,H,W,4) uint8 view is free
        return jax.lax.bitcast_convert_type(win, jnp.uint8)

    fn = jax.jit(render)
    _render_cache[key] = fn
    return fn


def draw_boxes(dets: Sequence[Detection], width: int, height: int,
               thickness: int = 2, labels: bool = False,
               out: Optional[np.ndarray] = None) -> np.ndarray:
    """Render detections into an RGBA overlay frame (H, W, 4) uint8.

    With ``labels=True``, each detection carrying a ``label`` gets its
    text stamped above the box (parity: draw_label users,
    tensordec-boundingbox.cc / tensordec-font.c).  ``out`` draws into an
    existing zeroed frame (batched decode preallocates one (B,H,W,4)
    block instead of stacking per-frame copies).
    """
    img = np.zeros((height, width, 4), np.uint8) if out is None else out
    palette = PALETTE
    for d in dets:
        color = palette[d.class_id % len(palette)]
        # pure-python clipping: np.clip on scalars costs ~10µs per call,
        # which dominates batched overlay drawing (4 clips × every box).
        # Coordinates scale in float32 — the reference's gfloat math
        # (tensordec-boundingbox.cc draw()) and bit-identical to the
        # device renderer's f32 pipeline at pixel-boundary roundings.
        f32 = np.float32
        x0 = min(max(int(f32(d.x) * f32(width)), 0), width - 1)
        y0 = min(max(int(f32(d.y) * f32(height)), 0), height - 1)
        x1 = min(max(int(f32(f32(d.x) + f32(d.w)) * f32(width)), 0),
                 width - 1)
        y1 = min(max(int(f32(f32(d.y) + f32(d.h)) * f32(height)), 0),
                 height - 1)
        t = thickness
        img[y0:y0 + t, x0:x1 + 1] = color
        img[max(y1 - t + 1, 0):y1 + 1, x0:x1 + 1] = color
        img[y0:y1 + 1, x0:x0 + t] = color
        img[y0:y1 + 1, max(x1 - t + 1, 0):x1 + 1] = color
        if labels and d.label:
            from .font import draw_text, label_anchor

            lx, ly = label_anchor(x0, y0)
            draw_text(img, lx, ly, d.label, color)
    return img

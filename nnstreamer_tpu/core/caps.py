"""Capabilities: the negotiation data model between pipeline pads.

TPU-native replacement for GstCaps carrying ``other/tensor(s)`` media types
(parity targets: /root/reference/gst/nnstreamer/nnstreamer_plugin_api_impl.c:1372
``gst_tensors_caps_from_config``, :1142 ``gst_tensor_caps_can_intersect`` with
rank-flexible dimension compare, and the caps templates in
tensor_typedef.h:79-132).

A :class:`Caps` is an ordered union of :class:`CapsStruct` alternatives (order
expresses preference, as in GStreamer).  Field values may be concrete, a set of
alternatives, an inclusive range, or the wildcard ANY.  Intersection walks the
cross product preserving preference order; fixation picks the first alternative
and collapses every field to a concrete value.

Special-cased fields:
- ``dimensions`` — per-tensor rank-flexible compare ("3:224:224:1" matches
  "3:224:224"); a component of 0 in a *template* means "that dim is free".
- ``framerate`` — exact fractions; 0/1 intersects with anything.
"""

from __future__ import annotations

import dataclasses
from fractions import Fraction
from typing import Any, Dict, Optional, Tuple, Union

from .spec import TensorsSpec, dims_equal, parse_dimension, \
    split_tensor_list
from .types import TensorFormat, MIMETYPE_TENSORS


class _Any:
    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "ANY"


ANY = _Any()


@dataclasses.dataclass(frozen=True)
class Range:
    """Inclusive numeric range."""

    lo: Union[int, Fraction]
    hi: Union[int, Fraction]

    def contains(self, v) -> bool:
        return self.lo <= v <= self.hi

    def intersect(self, other: "Range") -> Optional["Range"]:
        lo, hi = max(self.lo, other.lo), min(self.hi, other.hi)
        if lo > hi:
            return None
        return Range(lo, hi)

    def __repr__(self):
        return f"[{self.lo},{self.hi}]"


FieldValue = Any  # concrete | frozenset | Range | ANY


def _dim_parts(d: str) -> list:
    """Split one dim string into int components with trailing rank-end zeros
    stripped ('3:224:224:0' → [3, 224, 224]); interior 0 = free dim."""
    parts = [int(p.strip()) if p.strip() else 1 for p in d.split(":")]
    while parts and parts[-1] == 0:
        parts.pop()
    return parts


_split_dims_list = split_tensor_list


def _dims_match_template(tpl: str, concrete: str) -> bool:
    """Rank-flexible dims-list compare; interior 0 in template = free dim."""
    tl = _split_dims_list(tpl)
    cl = _split_dims_list(concrete)
    if len(tl) != len(cl):
        return False
    for td, cd in zip(tl, cl):
        tparts = _dim_parts(td)
        cdims = parse_dimension(cd)
        n = max(len(tparts), len(cdims))
        for i in range(n):
            tv = tparts[i] if i < len(tparts) else 1
            cv = cdims[i] if i < len(cdims) else 1
            if tv == 0:  # free dimension in template
                continue
            if tv != cv:
                return False
    return True


def _dims_is_template(v: str) -> bool:
    return any(p == 0 for d in _split_dims_list(v) for p in _dim_parts(d))


def _intersect_value(field: str, a: FieldValue, b: FieldValue
                     ) -> Tuple[bool, FieldValue]:
    """Returns (ok, merged)."""
    if a is ANY:
        return True, b
    if b is ANY:
        return True, a
    if field == "framerate" and not isinstance(a, (Range, frozenset)) \
            and not isinstance(b, (Range, frozenset)):
        fa, fb = Fraction(a), Fraction(b)
        if fa == 0:
            return True, fb
        if fb == 0:
            return True, fa
        return (fa == fb), fa
    if field == "dimensions" and isinstance(a, str) and isinstance(b, str):
        a_tpl, b_tpl = _dims_is_template(a), _dims_is_template(b)
        if a_tpl and not b_tpl:
            return _dims_match_template(a, b), b
        if b_tpl and not a_tpl:
            return _dims_match_template(b, a), a
        if not a_tpl and not b_tpl:
            al = _split_dims_list(a)
            bl = _split_dims_list(b)
            ok = len(al) == len(bl) and all(
                dims_equal(parse_dimension(x), parse_dimension(y))
                for x, y in zip(al, bl))
            return ok, a
        return (a == b), a  # both templates: require textual equality
    a_set = isinstance(a, frozenset)
    b_set = isinstance(b, frozenset)
    a_rng = isinstance(a, Range)
    b_rng = isinstance(b, Range)
    if a_set and b_set:
        m = a & b
        return bool(m), m if len(m) > 1 else next(iter(m), None)
    if a_set and b_rng:
        m = frozenset(v for v in a if b.contains(v))
        return bool(m), m if len(m) > 1 else next(iter(m), None)
    if b_set and a_rng:
        m = frozenset(v for v in b if a.contains(v))
        return bool(m), m if len(m) > 1 else next(iter(m), None)
    if a_set:
        return (b in a), b
    if b_set:
        return (a in b), a
    if a_rng and b_rng:
        m = a.intersect(b)
        return (m is not None), m
    if a_rng:
        return a.contains(b), b
    if b_rng:
        return b.contains(a), a
    return (a == b), a


def _is_fixed_value(field: str, v: FieldValue) -> bool:
    if v is ANY or isinstance(v, (frozenset, Range)):
        return False
    if field == "dimensions" and isinstance(v, str) and _dims_is_template(v):
        return False
    return True


def _fixate_value(field: str, v: FieldValue) -> FieldValue:
    if v is ANY:
        raise ValueError(f"cannot fixate wildcard field {field!r}")
    if isinstance(v, frozenset):
        return sorted(v, key=str)[0]
    if isinstance(v, Range):
        return v.lo
    if field == "dimensions" and isinstance(v, str) and _dims_is_template(v):
        # free dims fixate to 1
        return ",".join(
            ":".join(str(p if p != 0 else 1) for p in _dim_parts(d))
            for d in v.split(",") if d.strip())
    return v


@dataclasses.dataclass(frozen=True)
class CapsStruct:
    """One caps alternative: mimetype + constrained fields."""

    mime: str
    fields: Tuple[Tuple[str, FieldValue], ...] = ()

    @classmethod
    def make(cls, mime: str, **fields) -> "CapsStruct":
        norm = []
        for k, v in fields.items():
            if v is None:
                continue
            if isinstance(v, (list, set)) and not isinstance(v, frozenset):
                v = frozenset(v)
            norm.append((k, v))
        return cls(mime=mime, fields=tuple(sorted(norm)))

    def as_dict(self) -> Dict[str, FieldValue]:
        return dict(self.fields)

    def get(self, key: str, default=None):
        for k, v in self.fields:
            if k == key:
                return v
        return default

    def intersect(self, other: "CapsStruct") -> Optional["CapsStruct"]:
        if self.mime != other.mime:
            if self.mime == "*":
                return other.intersect(CapsStruct(other.mime, self.fields))
            if other.mime == "*":
                return self.intersect(CapsStruct(self.mime, other.fields))
            return None
        a, b = self.as_dict(), other.as_dict()
        merged = {}
        for k in set(a) | set(b):
            if k in a and k in b:
                ok, mv = _intersect_value(k, a[k], b[k])
                if not ok:
                    return None
                merged[k] = mv
            else:
                merged[k] = a.get(k, b.get(k))
        return CapsStruct.make(self.mime, **merged)

    def is_fixed(self) -> bool:
        if self.mime == "*":
            return False
        return all(_is_fixed_value(k, v) for k, v in self.fields)

    def fixate(self) -> "CapsStruct":
        if self.mime == "*":
            raise ValueError("cannot fixate wildcard-mime caps")
        return CapsStruct.make(
            self.mime, **{k: _fixate_value(k, v) for k, v in self.fields})

    def __str__(self):
        f = ", ".join(f"{k}={v}" for k, v in self.fields)
        return f"{self.mime}" + (f", {f}" if f else "")


@dataclasses.dataclass(frozen=True)
class Caps:
    """Ordered union of alternatives; empty = EMPTY (negotiation failure)."""

    structs: Tuple[CapsStruct, ...] = ()

    @classmethod
    def new(cls, *structs: CapsStruct) -> "Caps":
        return cls(structs=tuple(structs))

    @classmethod
    def any_tensors(cls) -> "Caps":
        return cls.new(CapsStruct.make(MIMETYPE_TENSORS))

    @classmethod
    def any(cls) -> "Caps":
        """Wildcard caps: intersects with any mimetype."""
        return cls.new(CapsStruct.make("*"))

    @classmethod
    def from_spec(cls, spec: TensorsSpec) -> "Caps":
        """Parity: gst_tensors_caps_from_config
        (nnstreamer_plugin_api_impl.c:1372)."""
        fields = dict(format=str(spec.format), framerate=spec.rate)
        if spec.format == TensorFormat.STATIC:
            # "." separates tensors inside caps fields ("," separates the
            # fields themselves) — reference caps-string grammar, keeps
            # str(caps) round-trippable through parse_caps_string
            fields.update(num_tensors=spec.num_tensors,
                          dimensions=spec.dimensions_string(sep="."),
                          types=spec.types_string(sep="."))
        return cls.new(CapsStruct.make(MIMETYPE_TENSORS, **fields))

    def to_spec(self) -> TensorsSpec:
        """Build a TensorsSpec from fixed tensor caps."""
        if not self.structs:
            raise ValueError("empty caps")
        s = self.structs[0]
        if s.mime != MIMETYPE_TENSORS:
            raise ValueError(f"not a tensor caps: {s.mime}")
        if not s.is_fixed():
            raise ValueError(f"caps not fixed, cannot build spec: {s}")
        fmt = s.get("format", "static")
        rate = s.get("framerate", Fraction(0, 1))
        if TensorFormat.from_string(str(fmt)) != TensorFormat.STATIC:
            return TensorsSpec(format=TensorFormat.from_string(str(fmt)),
                               rate=Fraction(rate))
        dims, types = s.get("dimensions"), s.get("types")
        if dims is None or types is None:
            raise ValueError(f"static tensor caps missing dims/types: {s}")
        # caps-string parsing may have produced non-str scalars (e.g. a
        # single-component dimensions=1)
        return TensorsSpec.parse(str(dims), str(types), format="static",
                                 rate=rate)

    def intersect(self, other: "Caps") -> "Caps":
        out, seen = [], set()
        for a in self.structs:
            for b in other.structs:
                m = a.intersect(b)
                if m is not None and m not in seen:
                    seen.add(m)
                    out.append(m)
        return Caps(structs=tuple(out))

    def can_intersect(self, other: "Caps") -> bool:
        """Parity: gst_tensor_caps_can_intersect
        (nnstreamer_plugin_api_impl.c:1142)."""
        return bool(self.intersect(other).structs)

    def is_fixed(self) -> bool:
        return len(self.structs) == 1 and self.structs[0].is_fixed()

    def is_empty(self) -> bool:
        return not self.structs

    def fixate(self) -> "Caps":
        if not self.structs:
            raise ValueError("cannot fixate empty caps")
        return Caps.new(self.structs[0].fixate())

    def first(self) -> CapsStruct:
        return self.structs[0]

    def __bool__(self):
        return bool(self.structs)

    def __str__(self):
        return " ; ".join(str(s) for s in self.structs) or "EMPTY"

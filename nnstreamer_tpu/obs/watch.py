"""``nns-watch`` — in-process time-series store + alerting watchdog.

Everything the observability layer built so far (registry, tracer, cost
attribution, transfer ledger, MFU join) is *pull-only*: a human must run
``nns-top`` or scrape ``/metrics`` at the right moment to notice a
breaker flapping, a p99 burning through its SLO, or MFU falling off a
cliff.  Fleets of among-device edge pipelines have no such human.  This
module is the reactive layer — the time dimension:

- a **background sampler** scrapes the process registry (or, in fleet
  mode, the same ``host:port`` ``/json`` endpoints ``nns-top
  --connect`` takes, via the shared :mod:`obs.scrape` client) on an
  interval into bounded per-series ring buffers: counters become
  *rates*, gauges *levels*, histograms *windowed quantiles* (the same
  :func:`~nnstreamer_tpu.obs.metrics.bucket_quantile` interpolation the
  admission controller sheds on);
- declarative **alert rules** evaluate against those series.  Four
  kinds:

  - ``threshold`` — value (optionally a ratio via ``per=``) compared
    against a bound, sustained for ``for`` seconds
    (``nns_edge_breaker_state >= open for 10s``);
  - ``slo_burn`` — classic dual-window error-budget burn: the fraction
    of observations over the SLO (histogram mode, e.g.
    ``nns_admission_latency_seconds`` vs the pool's ``slo-ms``) or the
    ratio of two counters (``nns_admission_shed_total`` /
    ``nns_admission_submitted_total``), over a *fast* and a *slow*
    window, both exceeding ``burn`` × the error ``budget``;
  - ``anomaly`` — robust z-score drift (median/MAD with a deviation
    floor) on a rate/level/quantile series: e2e latency, MFU,
    crossings/frame, RTT;
  - ``forecast`` — predictive: a robust linear trend
    (:mod:`.forecast`, Theil–Sen + residual MAD band) over a rate or
    level ring fires when the *predicted* value crosses the threshold
    within ``horizon`` seconds — before the reactive rules would.
    Current forecasts export as ``nns_forecast_value{rule}`` /
    ``nns_forecast_eta_seconds{rule}``, and the sampler joins an
    arrival-rate forecast against live MFU/roofline capacity into
    ``nns_capacity_headroom{pool}``;

- firing alerts carry severity and the offending series snapshot, and
  the shipped **actions** close the loop: a rate-limited bus WARNING on
  every registered pipeline, a flight-recorder dump
  (``obs/flightrec.py`` — triggered exactly once per firing transition,
  off the sampler thread), and alert-state export back into the
  registry (``nns_alert_state{rule,severity}``,
  ``nns_alerts_fired_total``) so ``/healthz`` and ``nns-top`` grow an
  ALERTS view and a fleet controller can scrape watch itself.

Rules load from a TOML/JSON file (``NNS_TPU_WATCH_RULES``; grammar
below) on top of / instead of the built-in :func:`default_rules` pack
(breaker-open, edge health, SLO burn, queue saturation, latency drift,
MFU collapse).  ``NNS_TPU_WATCH=<interval_s>`` starts a process-global
watchdog at first pipeline start (same activation hook as
``NNS_TPU_METRICS_PORT`` / ``NNS_TPU_CHAOS``).  The global obs kill
switch ``NNS_TPU_OBS_DISABLE`` makes the whole module strictly inert:
no sampler thread, no rings, no export.

Rules file grammar (TOML shown; the JSON equivalent is the same
structure under a top-level ``"rule"`` list)::

    [[rule]]
    name = "breaker-open"
    kind = "threshold"
    metric = "nns_edge_breaker_state"
    op = ">="
    value = "open"          # symbolic: closed/half-open/open -> 0/1/2
    for = "10s"
    severity = "critical"

    [[rule]]
    name = "slo-burn"
    kind = "slo_burn"
    metric = "nns_admission_latency_seconds"
    # slo_ms omitted: derived from the pool's own admission slo-ms
    fast = "30s"
    slow = "300s"
    budget = 0.01           # allowed error fraction
    burn = 4.0              # fire when err_frac >= burn * budget ...
    severity = "critical"   # ... on BOTH windows

    [[rule]]
    name = "mfu-collapse"
    kind = "anomaly"
    metric = "nns_mfu"
    z = 8.0
    side = "lower"
    severity = "warning"

    [[rule]]
    name = "arrival-surge"
    kind = "forecast"
    metric = "nns_pool_frames_total"   # counter -> rate signal
    op = ">="
    value = 500.0           # frames/s the pool cannot sustain
    horizon = "30s"         # fire when the trend crosses within 30s

    [store]                 # optional: size the series store
    ring_points = 512       # points kept per derived ring
    max_series = 4096       # series cap (overflow counted, not silent)

``nns-lint --watch-rules FILE`` statically validates a rules file
(NNS510: unknown metric family / malformed grammar / nonsense store
sizing; NNS517: forecast-rule grammar) without running anything — see
:mod:`nnstreamer_tpu.analyze.watchrules`.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import threading
import time
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from . import hooks as _hooks
from . import scrape as _scrape
from .forecast import FORECASTS
from . import forecast as _forecast
from .metrics import REGISTRY, MetricsRegistry, bucket_quantile

#: symbolic threshold values (the breaker-state gauge encoding from
#: chaos/retrypolicy.py): ``value = "open"`` reads as 2
SYMBOLIC_VALUES = {"closed": 0.0, "half-open": 1.0, "open": 2.0}

SEVERITIES = ("info", "warning", "critical")

RULE_KINDS = ("threshold", "slo_burn", "anomaly", "forecast")

#: derived-series signals a rule can bind to, by family kind
SIGNALS_BY_KIND = {
    "counter": ("rate",),
    "gauge": ("level",),
    "histogram": ("p50", "p95", "p99"),
}

#: every metric family the runtime can export, name -> kind — the
#: static catalog nns-lint NNS510 validates rules files against (a rule
#: watching a family nobody ever exports will simply never fire; that
#: is a config bug worth a warning, not a runtime surprise)
KNOWN_FAMILIES: Dict[str, str] = {
    # elements / pipelines
    "nns_element_buffers_in_total": "counter",
    "nns_element_buffers_out_total": "counter",
    "nns_element_stat_total": "counter",
    "nns_element_errors_total": "counter",
    "nns_queue_depth": "gauge",
    "nns_queue_capacity": "gauge",
    # host-execution profiler (obs/prof.py)
    "nns_element_cpu_seconds_total": "counter",
    "nns_element_run_seconds_total": "counter",
    "nns_element_wait_seconds_total": "counter",
    "nns_gil_waiters": "gauge",
    # filters
    "nns_filter_invokes_total": "counter",
    "nns_filter_frames_total": "counter",
    "nns_filter_latency_us": "gauge",
    "nns_filter_throughput_milli_fps": "gauge",
    "nns_filter_dispatch_milli_fps": "gauge",
    "nns_filter_batch_occupancy": "gauge",
    "nns_filter_stream_occupancy": "gauge",
    "nns_batcher_pending": "gauge",
    "nns_batcher_flushes_total": "counter",
    "nns_executable_cache_hits_total": "counter",
    "nns_executable_cache_misses_total": "counter",
    # serving pools + admission
    "nns_pool_streams": "gauge",
    "nns_pool_refcount": "gauge",
    "nns_pool_dispatches_total": "counter",
    "nns_pool_frames_total": "counter",
    "nns_pool_latency_us": "gauge",
    "nns_pool_batch_occupancy": "gauge",
    "nns_pool_stream_occupancy": "gauge",
    "nns_pool_pending": "gauge",
    "nns_pool_flushes_total": "counter",
    "nns_model_weight_bytes": "gauge",
    # model lifecycle (runtime/lifecycle.py): per-version series + the
    # canary comparator pair a promote/rollback playbook binds to
    "nns_model_version_invokes_total": "counter",
    "nns_model_version_frames_total": "counter",
    "nns_model_version_errors_total": "counter",
    "nns_model_version_latency_us": "gauge",
    "nns_model_version_state": "gauge",
    "nns_model_swaps_total": "counter",
    "nns_model_promotions_total": "counter",
    "nns_model_rollbacks_total": "counter",
    "nns_model_swap_stall_seconds": "gauge",
    "nns_model_canary_streams": "gauge",
    "nns_model_canary_latency_us": "gauge",
    "nns_model_baseline_latency_us": "gauge",
    "nns_model_canary_errors_total": "counter",
    "nns_model_canary_frames_total": "counter",
    "nns_admission_slo_at_risk": "gauge",
    "nns_admission_p99_us": "gauge",
    "nns_admission_submitted_total": "counter",
    "nns_admission_shed_total": "counter",
    "nns_admission_latency_seconds": "histogram",
    # edge links
    "nns_edge_tx_bytes_total": "counter",
    "nns_edge_rx_bytes_total": "counter",
    "nns_edge_tx_messages_total": "counter",
    "nns_edge_rx_messages_total": "counter",
    "nns_edge_inflight": "gauge",
    "nns_edge_timeouts_total": "counter",
    "nns_edge_reconnects_total": "counter",
    "nns_edge_bad_frames_total": "counter",
    "nns_edge_backoff_level": "gauge",
    "nns_edge_breaker_state": "gauge",
    "nns_edge_breaker_opens_total": "counter",
    "nns_edge_rtt_seconds": "histogram",
    # cost attribution / compiles
    "nns_invoke_device_seconds": "histogram",
    "nns_invoke_host_seconds": "histogram",
    "nns_compiles_total": "counter",
    "nns_compile_seconds_total": "counter",
    # data movement / device memory
    "nns_transfer_bytes_total": "counter",
    "nns_transfer_count_total": "counter",
    "nns_transfer_seconds": "histogram",
    "nns_device_memory_bytes": "gauge",
    # XLA cost / MFU / mesh
    "nns_executable_flops": "gauge",
    "nns_executable_bytes": "gauge",
    "nns_executable_peak_memory_bytes": "gauge",
    "nns_mfu": "gauge",
    "nns_hbm_bw_util": "gauge",
    "nns_shard_imbalance": "gauge",
    "nns_mesh_dispatches_total": "counter",
    "nns_mesh_pad_slots_total": "counter",
    "nns_mesh_replicated_dispatches_total": "counter",
    "nns_mesh_shard_frames_total": "counter",
    # tenancy / cost export (obs/tenantstat.py)
    "nns_tenant_device_seconds_total": "counter",
    "nns_tenant_frames_total": "counter",
    "nns_tenant_dollars_total": "counter",
    "nns_tenant_slo_attainment": "gauge",
    "nns_tenant_shed_total": "counter",
    # forecasting / capacity (obs/forecast.py)
    "nns_forecast_value": "gauge",
    "nns_forecast_eta_seconds": "gauge",
    "nns_capacity_headroom": "gauge",
    # chaos + watch itself
    "nns_chaos_injected_total": "counter",
    "nns_alert_state": "gauge",
    "nns_alerts_fired_total": "counter",
    "nns_watch_samples_total": "counter",
    "nns_watch_scrape_errors_total": "counter",
    # the closed-loop controller (obs/control.py)
    "nns_control_actions_total": "counter",
    "nns_control_state": "gauge",
}


class RuleError(ValueError):
    """Malformed watch rule / rules file (the NNS510 parse failure)."""


def _parse_duration(v: Any, field: str) -> float:
    """``10``/``10.5``/``"10s"``/``"500ms"``/``"2m"`` → seconds."""
    if isinstance(v, bool):
        raise RuleError(f"{field}: expected a duration, got {v!r}")
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip().lower()
    mult = 1.0
    for suffix, m in (("ms", 1e-3), ("s", 1.0), ("m", 60.0), ("h", 3600.0)):
        if s.endswith(suffix):
            s, mult = s[: -len(suffix)], m
            break
    try:
        return float(s) * mult
    except ValueError:
        raise RuleError(
            f"{field}: cannot parse duration {v!r} "
            f"(use seconds, or a number with ms/s/m/h suffix)") from None


_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
}


@dataclasses.dataclass
class AlertRule:
    """One declarative alert rule (see the module doc for grammar)."""

    name: str
    kind: str
    metric: str
    severity: str = "warning"
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    signal: str = ""        # rate|level|p50|p95|p99; "" = kind default
    # threshold
    op: str = ">"
    value: Any = 0.0
    per: str = ""           # denominator family (value becomes a ratio)
    for_s: float = 0.0
    # slo_burn
    slo_ms: float = 0.0     # 0 = derive from the pool's admission slo-ms
    budget: float = 0.01
    burn: float = 4.0
    fast_s: float = 30.0
    slow_s: float = 300.0
    # forecast: fire when the fitted trend crosses ``value`` within
    # this many seconds (0 = unset; the watchdog refuses a forecast
    # rule without one — see Watch.__init__.  Parse stays lenient so
    # nns-lint can reach the file and report NNS517 instead.)
    horizon_s: float = 0.0
    # anomaly
    z: float = 6.0
    side: str = "upper"     # upper|lower|both
    min_samples: int = 8
    rel_floor: float = 0.05  # MAD floor as a fraction of |median|
    abs_floor: float = 0.0   # MAD floor in the series' own unit
    #: how many recent points form the anomaly baseline — a bounded
    #: window, so ancient regimes (startup compile decay, a long-gone
    #: traffic pattern) age OUT of the median/MAD instead of poisoning
    #: it forever
    baseline_points: int = 64

    def __post_init__(self):
        if not str(self.name).strip():
            raise RuleError("rule without a name")
        ctx = f"rule {self.name!r}"
        if self.kind not in RULE_KINDS:
            raise RuleError(f"{ctx}: unknown kind {self.kind!r}; one of "
                            f"{list(RULE_KINDS)}")
        if not str(self.metric).strip():
            raise RuleError(f"{ctx}: no metric")
        if self.severity not in SEVERITIES:
            raise RuleError(f"{ctx}: unknown severity {self.severity!r}; "
                            f"one of {list(SEVERITIES)}")
        if self.op not in _OPS:
            raise RuleError(f"{ctx}: unknown op {self.op!r}; one of "
                            f"{sorted(_OPS)}")
        if self.side not in ("upper", "lower", "both"):
            raise RuleError(f"{ctx}: side={self.side!r} not "
                            f"upper/lower/both")
        if isinstance(self.value, str):
            sym = SYMBOLIC_VALUES.get(self.value.strip().lower())
            if sym is None:
                raise RuleError(
                    f"{ctx}: symbolic value {self.value!r} unknown; one "
                    f"of {sorted(SYMBOLIC_VALUES)} (or a number)")
            self.value = sym
        self.value = float(self.value)
        if not isinstance(self.labels, dict):
            raise RuleError(f"{ctx}: labels must be a table/object")
        self.labels = {str(k): str(v) for k, v in self.labels.items()}
        for fld in ("for_s", "fast_s", "slow_s", "horizon_s", "slo_ms",
                    "budget", "burn", "z", "rel_floor", "abs_floor"):
            v = getattr(self, fld)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v < 0:
                raise RuleError(f"{ctx}: {fld}={v!r} must be a "
                                f"number >= 0")
        if self.kind == "forecast" \
                and self.op not in _forecast.ORDERED_OPS:
            # "=="/"!=" have no crossing direction to project through
            raise RuleError(f"{ctx}: forecast needs an ordered op "
                            f"({list(_forecast.ORDERED_OPS)}), "
                            f"not {self.op!r}")
        if self.kind == "slo_burn":
            if self.budget <= 0:
                raise RuleError(f"{ctx}: budget must be > 0")
            if self.fast_s > self.slow_s:
                raise RuleError(f"{ctx}: fast window ({self.fast_s}s) "
                                f"longer than slow ({self.slow_s}s)")
        if self.min_samples < 2:
            raise RuleError(f"{ctx}: min_samples must be >= 2")
        self.baseline_points = int(self.baseline_points)
        if self.baseline_points < self.min_samples:
            raise RuleError(f"{ctx}: baseline_points "
                            f"({self.baseline_points}) smaller than "
                            f"min_samples ({self.min_samples})")


#: rules-file keys -> dataclass fields (duration strings parsed)
_RULE_KEY_MAP = {"for": "for_s", "fast": "fast_s", "slow": "slow_s",
                 "horizon": "horizon_s"}
_DURATION_FIELDS = {"for_s", "fast_s", "slow_s", "horizon_s"}
_RULE_FIELDS = {f.name for f in dataclasses.fields(AlertRule)}


def parse_rule(item: dict) -> AlertRule:
    if not isinstance(item, dict):
        raise RuleError(f"rule entry is not a table/object: {item!r}")
    kw: Dict[str, Any] = {}
    for key, val in item.items():
        fld = _RULE_KEY_MAP.get(key, key)
        if fld not in _RULE_FIELDS:
            raise RuleError(
                f"rule {item.get('name', '?')!r}: unknown key {key!r} "
                f"(known: {sorted(_RULE_FIELDS | set(_RULE_KEY_MAP))})")
        if fld in _DURATION_FIELDS:
            val = _parse_duration(val, f"rule {item.get('name', '?')!r}"
                                       f".{key}")
        kw[fld] = val
    for required in ("name", "kind", "metric"):
        if required not in kw:
            raise RuleError(
                f"rule {kw.get('name', '?')!r}: missing {required!r}")
    return AlertRule(**kw)


def parse_rules(doc: Any) -> List[AlertRule]:
    """Rules from a parsed TOML/JSON document: a top-level ``rule`` (or
    ``rules``) list, or a bare list."""
    if isinstance(doc, dict):
        items = doc.get("rule", doc.get("rules"))
        if items is None:
            raise RuleError(
                "rules document has no top-level 'rule' list "
                "([[rule]] tables in TOML, \"rule\": [...] in JSON)")
    else:
        items = doc
    if not isinstance(items, list) or not items:
        raise RuleError("rules document names no rules")
    rules = [parse_rule(item) for item in items]
    seen: Dict[str, int] = {}
    for r in rules:
        seen[r.name] = seen.get(r.name, 0) + 1
    dupes = sorted(n for n, c in seen.items() if c > 1)
    if dupes:
        raise RuleError(f"duplicate rule name(s): {dupes} — alert state "
                        f"is keyed by name")
    return rules


def _load_doc(path: str) -> Any:
    """Parse a rules file into its document; ``.toml`` via stdlib
    tomllib (3.11+), anything else as JSON.  Raises
    :class:`RuleError` on malformed syntax, ``OSError`` on unreadable
    files."""
    if str(path).endswith(".toml"):
        try:
            import tomllib
        except ImportError:
            raise RuleError(
                "TOML rules files need Python 3.11+ (tomllib); "
                "use the JSON form instead") from None
        try:
            with open(path, "rb") as f:
                return tomllib.load(f)
        except tomllib.TOMLDecodeError as e:
            raise RuleError(f"invalid TOML: {e}") from None
    with open(path, "r", encoding="utf-8") as f:
        try:
            return json.load(f)
        except ValueError as e:
            raise RuleError(f"invalid JSON: {e}") from None


def load_rules(path: str) -> List[AlertRule]:
    """Load + parse a rules file (grammar errors raise
    :class:`RuleError`)."""
    return parse_rules(_load_doc(path))


#: keys the optional top-level ``[store]`` table may carry — they size
#: the watchdog's SeriesStore (Watch constructor kwargs of the same
#: names)
_STORE_KEYS = ("ring_points", "max_series")


def parse_store(doc: Any) -> Dict[str, int]:
    """The optional top-level ``[store]`` table of a rules file:
    ``{ring_points, max_series}`` overrides for the series store
    ({} when absent — the Watch defaults stand).  Unknown keys and
    non-positive/non-integer values are grammar errors
    (:class:`RuleError`), same strictness as the rule tables."""
    if not isinstance(doc, dict):
        return {}
    st = doc.get("store")
    if st is None:
        return {}
    if not isinstance(st, dict):
        raise RuleError("[store] is not a table/object")
    out: Dict[str, int] = {}
    for key, val in st.items():
        if key not in _STORE_KEYS:
            raise RuleError(f"[store]: unknown key {key!r} "
                            f"(known: {sorted(_STORE_KEYS)})")
        if isinstance(val, bool) or not isinstance(val, int) \
                or val <= 0:
            raise RuleError(f"[store]: {key}={val!r} must be a "
                            f"positive integer")
        out[key] = int(val)
    return out


def load_store(path: str) -> Dict[str, int]:
    """The ``[store]`` overrides of a rules file ({} when it has
    none)."""
    return parse_store(_load_doc(path))


def lint_store(cfg: Dict[str, int]) -> List[str]:
    """Static problems with a (well-formed) ``[store]`` section —
    values that parse but cannot work (the NNS510 checks beyond
    grammar)."""
    problems: List[str] = []
    rp = cfg.get("ring_points")
    if rp is not None and rp < QUANT_WINDOW_TICKS:
        problems.append(
            f"[store]: ring_points={rp} is shorter than the "
            f"{QUANT_WINDOW_TICKS}-tick quantile window — histogram "
            f"signals (and any anomaly baseline) cannot form")
    ms = cfg.get("max_series")
    if ms is not None and ms < 16:
        problems.append(
            f"[store]: max_series={ms} cannot hold even one pool's "
            f"families — everything past the cap is dropped (counted, "
            f"but every rule on a dropped series is blind)")
    return problems


def lint_rule(rule: AlertRule) -> List[str]:
    """Static problems with one (well-formed) rule — the NNS510
    checks beyond grammar: metric families the registry never exports,
    signals that cannot exist for the family's kind, burn rules that
    can never bind."""
    problems: List[str] = []
    if rule.name == "endpoint-down":
        problems.append(
            "'endpoint-down' is reserved for the built-in "
            "fleet-liveness check (the watchdog refuses the rule set)")
    kind = KNOWN_FAMILIES.get(rule.metric)
    if kind is None:
        problems.append(
            f"metric {rule.metric!r} is not a family the registry "
            f"ever exports (the rule can never fire)")
    elif rule.signal and rule.signal not in SIGNALS_BY_KIND[kind]:
        problems.append(
            f"signal {rule.signal!r} does not exist for "
            f"{kind} family {rule.metric!r} (valid: "
            f"{list(SIGNALS_BY_KIND[kind])})")
    if rule.per:
        per_kind = KNOWN_FAMILIES.get(rule.per)
        if per_kind is None:
            problems.append(
                f"per={rule.per!r} is not a family the registry ever "
                f"exports (the ratio can never form)")
        elif kind is not None and per_kind != kind:
            problems.append(
                f"per={rule.per!r} ({per_kind}) does not match "
                f"{rule.metric!r} ({kind}) — a ratio needs two "
                f"families of the same kind")
    if rule.kind == "slo_burn" and kind is not None:
        if kind == "histogram" and rule.per:
            problems.append(
                "slo_burn on a histogram family takes no per= "
                "(the error fraction comes from the buckets vs the SLO)")
        if kind == "counter" and not rule.per:
            problems.append(
                "slo_burn on a counter family needs per= (the "
                "denominator counter of the error ratio)")
        if kind == "gauge":
            problems.append(
                "slo_burn needs a histogram (latency-vs-SLO mode) or a "
                "counter pair (ratio mode), not a gauge")
    if rule.kind == "anomaly" and rule.side == "lower" \
            and rule.rel_floor > 0 and rule.z * rule.rel_floor >= 1.0:
        problems.append(
            f"z ({rule.z:g}) x rel_floor ({rule.rel_floor:g}) >= 1 on "
            f"a lower-side rule: a nonnegative series can drop at most "
            f"-median, i.e. |z| <= 1/rel_floor when the MAD floors out "
            f"— the rule can never fire on a flat baseline")
    return problems


def default_rules() -> List[AlertRule]:
    """The built-in pack: breaker-open, edge-link health, hard-shed +
    SLO burn, queue saturation, latency drift, MFU collapse.  Tuned for
    this runtime's own links and pools — a deployment with different
    baselines overrides via ``NNS_TPU_WATCH_RULES``."""
    R = AlertRule
    return [
        # a circuit breaker opening IS the outage signal
        R(name="breaker-open", kind="threshold",
          metric="nns_edge_breaker_state", op=">=", value="open",
          severity="critical"),
        # edge-link health: any timeout/reconnect/corrupt frame in a
        # sampling window is a symptom worth an alarm on an edge fleet
        R(name="edge-timeouts", kind="threshold",
          metric="nns_edge_timeouts_total", op=">", value=0.0),
        R(name="edge-reconnect-flap", kind="threshold",
          metric="nns_edge_reconnects_total", op=">", value=0.0),
        R(name="edge-bad-frames", kind="threshold",
          metric="nns_edge_bad_frames_total", op=">", value=0.0),
        R(name="edge-rtt-drift", kind="anomaly",
          metric="nns_edge_rtt_seconds", signal="p95", z=8.0,
          side="upper", min_samples=10, rel_floor=0.5),
        # model path: sustained latency drift and errored dispatches
        R(name="pool-latency-drift", kind="anomaly",
          metric="nns_pool_latency_us", z=8.0, side="upper",
          min_samples=8, rel_floor=0.35),
        R(name="filter-latency-drift", kind="anomaly",
          metric="nns_filter_latency_us", z=8.0, side="upper",
          min_samples=8, rel_floor=0.35),
        R(name="element-errors", kind="threshold",
          metric="nns_element_errors_total", op=">", value=0.0,
          severity="critical"),
        # admission: any shed is loud; the burn pair watches the error
        # budget the way an SRE console would
        R(name="hard-shed", kind="threshold",
          metric="nns_admission_shed_total", op=">", value=0.0),
        R(name="slo-burn", kind="slo_burn",
          metric="nns_admission_latency_seconds", fast_s=15.0,
          slow_s=120.0, budget=0.01, burn=4.0, severity="critical"),
        R(name="shed-burn", kind="slo_burn",
          metric="nns_admission_shed_total",
          per="nns_admission_submitted_total", fast_s=15.0,
          slow_s=120.0, budget=0.05, burn=2.0),
        R(name="queue-saturation", kind="threshold",
          metric="nns_queue_depth", per="nns_queue_capacity",
          op=">=", value=0.9, for_s=1.0),
        # efficiency: MFU falling off a cliff on a serving fleet.
        # z * rel_floor must stay < 1 on a lower-side rule: the
        # biggest possible drop of a nonnegative series is -median,
        # i.e. z = -1/rel_floor when MAD floors out — 8.0 x 0.25
        # could literally never fire
        R(name="mfu-collapse", kind="anomaly", metric="nns_mfu",
          z=3.5, side="lower", min_samples=8, rel_floor=0.25),
    ]


def rules_from_env() -> List[AlertRule]:
    """The active rule set: ``NNS_TPU_WATCH_RULES=<file>`` when set
    (replacing the default pack), else :func:`default_rules`."""
    path = os.environ.get("NNS_TPU_WATCH_RULES", "").strip()
    if not path:
        return default_rules()
    return load_rules(path)


# -- the series store ---------------------------------------------------------

#: how many per-tick histogram deltas the windowed quantile sums over
#: (the same rolling-delta idea as AdmissionController.HIST_WINDOW_DELTAS)
QUANT_WINDOW_TICKS = 16


class _Series:
    """One bounded time series: raw cumulative state + derived rings."""

    __slots__ = ("kind", "labels", "rings", "prev", "prev_ts", "raw",
                 "qwin", "bounds", "seen_tick", "reborn")

    def __init__(self, kind: str, labels: Dict[str, str],
                 ring_points: int):
        self.kind = kind
        self.labels = labels
        self.seen_tick = 0  # the endpoint tick this series last appeared
        # True when this key was EVICTED and came back: its first
        # cumulative value is history re-surfacing, not increments born
        # inside the sampling window — rate-from-zero must not apply
        self.reborn = False
        # signal -> deque[(ts, value)]
        self.rings: Dict[str, Deque[Tuple[float, float]]] = {
            sig: collections.deque(maxlen=ring_points)
            for sig in SIGNALS_BY_KIND[kind]}
        self.prev: Any = None       # counter: cum value; hist: noncum dist
        self.prev_ts: Optional[float] = None
        # counter: deque[(ts, cum)]; histogram: deque[(ts, delta_dist)]
        self.raw: Deque[Tuple] = collections.deque(maxlen=ring_points)
        # histogram only: the short delta window the live quantiles sum
        self.qwin: Deque[Tuple] = collections.deque(
            maxlen=QUANT_WINDOW_TICKS)
        self.bounds: Tuple[float, ...] = ()

    def last(self, signal: str) -> Optional[Tuple[float, float]]:
        ring = self.rings.get(signal)
        return ring[-1] if ring else None

    def tail(self, signal: str, n: int = 32) -> List[Tuple[float, float]]:
        ring = self.rings.get(signal)
        return list(ring)[-n:] if ring else []

    def cum_delta_over(self, window_s: float,
                       now: float) -> Optional[float]:
        """Counter: increments over the trailing window (None before
        two raw points exist)."""
        if len(self.raw) < 2:
            return None
        cutoff = now - window_s
        base_ts, base = self.raw[0]
        for ts, cum in self.raw:
            if ts > cutoff:
                break
            base_ts, base = ts, cum
        return max(self.raw[-1][1] - base, 0.0)

    def hist_window(self, window_s: float,
                    now: float) -> Optional[List[float]]:
        """Histogram: elementwise sum of the per-tick non-cumulative
        delta distributions inside the trailing window."""
        cutoff = now - window_s
        dist: Optional[List[float]] = None
        for ts, delta in self.raw:
            if ts < cutoff:
                continue
            if dist is None:
                dist = list(delta)
            else:
                dist = [a + b for a, b in zip(dist, delta)]
        return dist


def _labelkey(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted(labels.items()))


def _le_float(le: str) -> float:
    return float("inf") if le in ("+Inf", "inf") else float(le)


class SeriesStore:
    """Bounded store of derived series, fed one snapshot at a time.

    Keys are ``(endpoint, family, labelset)``; every ring is a
    ``deque(maxlen=ring_points)`` and the series count is capped, so a
    watchdog attached to a high-cardinality process stays bounded (the
    overflow is counted, never silent)."""

    #: ticks a series may miss from its endpoint's snapshots before
    #: rule evaluation treats it as STALE (its source is gone — a
    #: stopped pipeline, a released pool, a closed link): a stale
    #: series must stop satisfying conditions, or an alert raised on a
    #: since-dead object would stay FIRING forever on its frozen last
    #: point
    STALE_TICKS = 3
    #: ticks after which a stale series is evicted outright (restart/
    #: re-create churn must not accumulate ghost series to the cap)
    EVICT_TICKS = 128

    #: evicted keys remembered (bounded): a series re-appearing after
    #: eviction must RE-BASE, not rate-from-zero — its cumulative
    #: value is old history, and dividing it by one tick manufactures
    #: a giant phantom spike (and a phantom alert) out of nothing
    EVICT_MEMORY = 4096

    def __init__(self, ring_points: int = 512, max_series: int = 4096):
        self.ring_points = int(ring_points)
        self.max_series = int(max_series)
        self._series: Dict[Tuple, _Series] = {}
        self._evicted: "collections.OrderedDict[Tuple, None]" = \
            collections.OrderedDict()
        self.dropped_series = 0
        self._tick_no: Dict[str, int] = {}  # endpoint -> ingest count
        # (endpoint, pool) -> slo_ms hint from the pools table, for
        # slo_burn rules that don't pin their own slo_ms
        self._slo_hints: Dict[Tuple[str, str], float] = {}
        # endpoint -> ts of its last ingested snapshot: a counter/
        # histogram series first appearing AFTER the endpoint's first
        # tick was born inside the sampling window, so its initial
        # value IS a delta (from zero) — without this, a counter that
        # springs to life already at 1 (first error, first timeout)
        # never shows a nonzero rate.  On the endpoint's FIRST tick
        # everything is baseline (cumulative history, not news).
        self._last_tick: Dict[str, float] = {}

    def __len__(self) -> int:
        return len(self._series)

    def _get(self, endpoint: str, family: str, kind: str,
             labels: Dict[str, str]) -> Optional[_Series]:
        key = (endpoint, family, _labelkey(labels))
        s = self._series.get(key)
        if s is None:
            if len(self._series) >= self.max_series:
                self.dropped_series += 1
                return None
            s = _Series(kind, dict(labels), self.ring_points)
            if key in self._evicted:
                del self._evicted[key]
                s.reborn = True
            self._series[key] = s
        s.seen_tick = self._tick_no.get(endpoint, 0)
        return s

    def is_stale(self, key: Tuple, s: _Series) -> bool:
        """Whether the series stopped appearing in its endpoint's
        snapshots (source object gone)."""
        return self._tick_no.get(key[0], 0) - s.seen_tick \
            > self.STALE_TICKS

    def slo_hint(self, endpoint: str, pool: Optional[str]
                 ) -> Optional[float]:
        if pool is None:
            return None
        return self._slo_hints.get((endpoint, pool))

    def match(self, family: str,
              labels: Dict[str, str]) -> List[Tuple[Tuple, _Series]]:
        """LIVE series of ``family`` whose labels are a superset of the
        rule's filter, every endpoint (stale series — absent from their
        endpoint's recent snapshots — don't bind: their frozen last
        point must not keep an alert firing)."""
        out = []
        for key, s in self._series.items():
            if key[1] != family or self.is_stale(key, s):
                continue
            if all(s.labels.get(k) == v for k, v in labels.items()):
                out.append((key, s))
        return out

    def find(self, endpoint: str, family: str,
             labels: Dict[str, str]) -> Optional[_Series]:
        return self._series.get((endpoint, family, _labelkey(labels)))

    # -- ingest ---------------------------------------------------------------

    def ingest(self, endpoint: str, snap: dict, ts: float) -> None:
        """Fold one registry snapshot into the store (counter→rate,
        gauge→level, histogram→windowed quantiles)."""
        prev_tick = self._last_tick.get(endpoint)
        self._last_tick[endpoint] = ts
        tick = self._tick_no.get(endpoint, 0) + 1
        self._tick_no[endpoint] = tick
        for row in snap.get("pools", []):
            adm = row.get("admission")
            if adm and adm.get("slo_ms"):
                self._slo_hints[(endpoint, row.get("pool", ""))] = \
                    float(adm["slo_ms"])
        for name, fam in snap.get("metrics", {}).items():
            kind = fam.get("kind")
            if kind == "histogram":
                self._ingest_hist(endpoint, name, fam, ts, prev_tick)
            elif kind in ("counter", "gauge"):
                self._ingest_flat(endpoint, name, kind, fam, ts,
                                  prev_tick)
        # evict long-gone series so restart/re-create churn (new pool
        # per run, new link per port) never accumulates ghost series
        # up to the cap
        dead = [key for key, s in self._series.items()
                if key[0] == endpoint
                and tick - s.seen_tick > self.EVICT_TICKS]
        for key in dead:
            del self._series[key]
            # remember who left, so a reborn key re-bases instead of
            # spiking rate-from-zero (bounded LRU, oldest forgotten)
            self._evicted[key] = None
            self._evicted.move_to_end(key)
        while len(self._evicted) > self.EVICT_MEMORY:
            self._evicted.popitem(last=False)

    def _ingest_flat(self, endpoint: str, name: str, kind: str,
                     fam: dict, ts: float,
                     prev_tick: Optional[float]) -> None:
        for sample in fam.get("samples", []):
            value = float(sample.get("value", 0.0))
            s = self._get(endpoint, name, kind, sample.get("labels", {}))
            if s is None:
                continue
            if kind == "gauge":
                s.rings["level"].append((ts, value))
                continue
            s.raw.append((ts, value))
            if s.prev is not None and s.prev_ts is not None \
                    and ts > s.prev_ts:
                delta = value - s.prev
                if delta >= 0:  # negative = counter reset: skip one tick
                    s.rings["rate"].append(
                        (ts, delta / (ts - s.prev_ts)))
            elif s.prev is None and prev_tick is not None \
                    and ts > prev_tick and not s.reborn:
                # series born inside the window: its whole value is
                # this window's increments (rate-from-zero, same rule
                # nns-top applies to its XFER columns).  A REBORN
                # series (evicted, then re-appeared) is the one case
                # where that logic lies: its value is accumulated
                # history, so it re-bases silently instead
                s.rings["rate"].append((ts, value / (ts - prev_tick)))
            s.reborn = False
            s.prev, s.prev_ts = value, ts

    def _ingest_hist(self, endpoint: str, name: str, fam: dict,
                     ts: float, prev_tick: Optional[float]) -> None:
        # group the flat _bucket/_sum/_count samples by label set
        groups: Dict[Tuple, Dict[float, float]] = {}
        label_of: Dict[Tuple, Dict[str, str]] = {}
        for sample in fam.get("samples", []):
            sub = sample.get("name", name)
            if not sub.endswith("_bucket"):
                continue
            labels = dict(sample.get("labels", {}))
            le = labels.pop("le", None)
            if le is None:
                continue
            key = _labelkey(labels)
            groups.setdefault(key, {})[_le_float(le)] = \
                float(sample.get("value", 0.0))
            label_of[key] = labels
        for key, by_le in groups.items():
            bounds = tuple(sorted(by_le))
            cum = [by_le[le] for le in bounds]
            # exposition buckets are cumulative; the store works on
            # per-bucket counts
            noncum = [c - (cum[i - 1] if i else 0.0)
                      for i, c in enumerate(cum)]
            s = self._get(endpoint, name, "histogram", label_of[key])
            if s is None:
                continue
            if s.bounds and (s.bounds != bounds
                             or len(s.prev or ()) != len(noncum)):
                # bucket layout changed under us: resync, skip a tick —
                # and drop the accumulated delta rows, whose old-length
                # dists would corrupt the windowed quantiles (zip
                # truncation) and index past the new bounds in the
                # burn evaluation
                s.bounds = bounds
                s.prev = noncum
                s.raw.clear()
                s.qwin.clear()
                continue
            if s.prev is None:
                s.bounds = bounds
                s.prev = noncum
                if prev_tick is None or s.reborn:
                    # store cold (history, not news) — or the series
                    # was evicted and came back, same situation
                    s.reborn = False
                    continue
                delta = list(noncum)  # born inside the window
            else:
                delta = [c - p for c, p in zip(noncum, s.prev)]
                s.prev = noncum
                if any(d < 0 for d in delta):  # reset: resync
                    continue
            s.raw.append((ts, delta))
            s.qwin.append((ts, delta))
            if sum(delta) <= 0:
                continue  # no new observations: quantiles stay put
            dist = [0.0] * len(noncum)
            for _t, d in s.qwin:
                dist = [a + b for a, b in zip(dist, d)]
            for sig, q in (("p50", 0.50), ("p95", 0.95), ("p99", 0.99)):
                v = bucket_quantile(bounds, dist, q)
                if v is not None:
                    s.rings[sig].append((ts, v))


def _over_threshold(bounds: Tuple[float, ...], dist: List[float],
                    thr: float) -> float:
    """Observations above ``thr`` in a non-cumulative distribution,
    with linear apportioning of the straddling bucket (the whole +Inf
    bucket counts as over — conservative in the direction that pages)."""
    over = 0.0
    for i, n in enumerate(dist):
        if n <= 0:
            continue
        lo = bounds[i - 1] if i > 0 else 0.0
        hi = bounds[i]
        if lo >= thr:
            over += n
        elif hi > thr:
            over += n if hi == float("inf") \
                else n * (hi - thr) / (hi - lo)
    return over


def _robust_z(baseline: List[float], x: float, rel_floor: float,
              abs_floor: float) -> float:
    """Median/MAD z-score with a deviation floor: a series that sat
    perfectly flat (MAD 0) must not turn every epsilon into infinity."""
    import statistics

    med = statistics.median(baseline)
    mad = statistics.median(abs(b - med) for b in baseline)
    sigma = max(1.4826 * mad, rel_floor * abs(med), abs_floor, 1e-12)
    return (x - med) / sigma


# -- the watchdog -------------------------------------------------------------


class _RuleState:
    __slots__ = ("firing", "since", "fired", "bad_since", "detail")

    def __init__(self):
        self.firing = False
        self.since = 0.0
        self.fired = 0
        self.bad_since: Dict[Tuple, float] = {}
        self.detail: Optional[dict] = None


class Watch:
    """The watchdog: sampler + store + rule engine + actions.

    ``endpoints=None`` watches the in-process ``registry`` (default:
    the global one); a list of ``host:port`` strings watches a fleet
    over the shared scrape client.  ``source`` overrides the sampling
    function entirely (tests feed synthetic snapshots).  Strictly
    inert under ``NNS_TPU_OBS_DISABLE``: :meth:`start` spawns no
    thread, :meth:`sample_once` is a no-op."""

    #: consecutive scrape failures before ``endpoint-down`` fires
    DOWN_AFTER = 3

    def __init__(self, rules: Optional[List[AlertRule]] = None,
                 interval_s: float = 1.0,
                 endpoints: Optional[List[str]] = None,
                 registry: Optional[MetricsRegistry] = None,
                 source: Optional[Callable[[], List[dict]]] = None,
                 ring_points: int = 512, max_series: int = 4096):
        self.rules = list(rules) if rules is not None else default_rules()
        if any(r.name == "endpoint-down" for r in self.rules):
            # the built-in fleet check owns this name and its state —
            # a user rule sharing it would flap fire/resolve every
            # tick against the built-in's transitions
            raise RuleError("'endpoint-down' is reserved for the "
                            "built-in fleet-liveness check; rename "
                            "the rule")
        for r in self.rules:
            # grammar stays lenient (nns-lint must reach the file and
            # report NNS517); the live watchdog refuses to run it
            if r.kind == "forecast" and not r.horizon_s > 0:
                raise RuleError(
                    f"rule {r.name!r}: forecast needs horizon_s > 0 "
                    f"(e.g. horizon = \"30s\") — without one there is "
                    f"nothing to predict across")
        self.interval_s = max(float(interval_s), 0.01)
        self.endpoints = list(endpoints) if endpoints else None
        self.registry = registry if registry is not None else REGISTRY
        self._source = source
        self.store = SeriesStore(ring_points=ring_points,
                                 max_series=max_series)
        self.enabled = not _hooks.DISABLED
        self.samples = 0
        self.alert_log: Deque[dict] = collections.deque(maxlen=256)
        self._states: Dict[str, _RuleState] = {
            r.name: _RuleState() for r in self.rules}
        self._states["endpoint-down"] = _RuleState()
        self._fail_streak: Dict[str, int] = {}
        self._warn_ts = 0.0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # RLock: the bus-WARNING action dispatches handlers inline on
        # the sampler thread, and a handler is allowed to read
        # alerts() back — same-thread reentry must not deadlock
        self._lock = threading.RLock()
        if self.enabled:
            self._gauge = self.registry.gauge(
                "nns_alert_state",
                "1 while the watch rule is firing (obs/watch.py)",
                labelnames=("rule", "severity"))
            self._fired = self.registry.counter(
                "nns_alerts_fired_total",
                "watch-rule firing transitions",
                labelnames=("rule", "severity"))
            self._samples_total = self.registry.counter(
                "nns_watch_samples_total",
                "watchdog sampling ticks")
            self._scrape_errors = self.registry.counter(
                "nns_watch_scrape_errors_total",
                "failed watchdog scrapes", labelnames=("endpoint",))
            self._fc_value = self.registry.gauge(
                "nns_forecast_value",
                "forecast rule's predicted series value at its "
                "horizon (obs/forecast.py)", labelnames=("rule",))
            self._fc_eta = self.registry.gauge(
                "nns_forecast_eta_seconds",
                "seconds until the forecast rule's predicted "
                "threshold crossing (-1: none in sight)",
                labelnames=("rule",))
            self._headroom = self.registry.gauge(
                "nns_capacity_headroom",
                "fraction of sustainable rate left after the forecast "
                "arrival rate (1 idle, <=0 predicted overload)",
                labelnames=("pool",))

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> bool:
        """Spawn the sampler thread (False — and strictly nothing else
        — under the global obs kill switch, matching the PR 8
        contract: no thread, no rings, no export)."""
        if not self.enabled or self._thread is not None:
            return False
        self._stop.clear()
        from . import prof as _prof

        self._thread = _prof.named_thread("watch", "sampler", self._run)
        self._thread.start()
        return True

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception as e:  # noqa: BLE001 - the watchdog must
                # outlive whatever it watches; one bad sample is logged,
                # not fatal
                from ..utils.log import logw

                logw("nns-watch: sample failed: %s: %s",
                     type(e).__name__, e)

    # -- one tick -------------------------------------------------------------

    def _scrape(self) -> List[dict]:
        if self._source is not None:
            return self._source()
        if self.endpoints:
            return _scrape.fetch_fleet(self.endpoints)
        try:
            return [{"endpoint": "local",
                     "snap": self.registry.snapshot(), "error": None}]
        except Exception as e:  # noqa: BLE001 - same contract as the
            # fleet client: a scrape failure is data, not death
            return [{"endpoint": "local", "snap": None,
                     "error": f"{type(e).__name__}: {e}"}]

    def sample_once(self, now: Optional[float] = None) -> List[dict]:
        """One sampler tick: scrape → ingest → evaluate → act.
        Returns the alert events fired on THIS tick."""
        if not self.enabled:
            return []
        # Scrape OUTSIDE the watch lock (NNS602 fix): the scrape is a
        # pure read of the registry (its own locks) and can block on a
        # device sync (executable-table join) — holding self._lock
        # across it would stall alerts() readers (the controller tick)
        # for the whole scrape and re-widen the ctl↔watch lock-order
        # surface the _alock split narrowed.
        entries = self._scrape()
        with self._lock:
            now = time.monotonic() if now is None else now
            self.samples += 1
            self._samples_total.labels().inc()
            for entry in entries:
                ep = entry["endpoint"]
                if entry["snap"] is not None:
                    self._fail_streak[ep] = 0
                    self.store.ingest(ep, entry["snap"], now)
                else:
                    self._fail_streak[ep] = \
                        self._fail_streak.get(ep, 0) + 1
                    self._scrape_errors.labels(endpoint=ep).inc()
            fired: List[dict] = []
            for rule in self.rules:
                detail = self._evaluate(rule, now)
                ev = self._transition(rule.name, rule.severity, detail,
                                      now)
                if ev is not None:
                    fired.append(ev)
            ev = self._transition(
                "endpoint-down", "critical",
                self._endpoint_down_detail(), now)
            if ev is not None:
                fired.append(ev)
            self._capacity_tick(entries, now)
            return fired

    def _endpoint_down_detail(self) -> Optional[dict]:
        down = sorted(ep for ep, n in self._fail_streak.items()
                      if n >= self.DOWN_AFTER)
        if not down:
            return None
        return {"value": float(len(down)), "series": {},
                "endpoint": ",".join(down),
                "note": f"{len(down)} endpoint(s) unreachable for >= "
                        f"{self.DOWN_AFTER} consecutive scrapes"}

    # -- rule evaluation ------------------------------------------------------

    def _evaluate(self, rule: AlertRule, now: float) -> Optional[dict]:
        if rule.kind == "threshold":
            return self._eval_threshold(rule, now)
        if rule.kind == "anomaly":
            return self._eval_anomaly(rule, now)
        if rule.kind == "forecast":
            return self._eval_forecast(rule, now)
        return self._eval_burn(rule, now)

    def _sustained(self, rule: AlertRule, key: Tuple, bad: bool,
                   now: float) -> bool:
        """The ``for`` clause: condition held continuously since."""
        st = self._states[rule.name]
        if not bad:
            st.bad_since.pop(key, None)
            return False
        since = st.bad_since.setdefault(key, now)
        return now - since >= rule.for_s

    def _find_den(self, endpoint: str, per: str,
                  labels: Dict[str, str]) -> Optional["_Series"]:
        """The ``per=`` denominator for one numerator series: the
        exact label set when the two families share a schema, else the
        denominator whose every label agrees with the numerator's —
        the join on the SHARED labels.  Without the fallback a ratio
        across families with different label sets can never bind:
        ``nns_admission_shed_total{pool,priority,reason}`` over
        ``nns_admission_submitted_total{pool,priority}`` is the
        default pack's own shed-burn rule.  Two subset matches pick
        the most specific (largest label set)."""
        den = self.store.find(endpoint, per, labels)
        if den is not None:
            return den
        best: Optional[_Series] = None
        for (ep, _fam, _lk), s in self.store.match(per, {}):
            if ep != endpoint:
                continue
            if all(labels.get(k) == v for k, v in s.labels.items()):
                if best is None or len(s.labels) > len(best.labels):
                    best = s
        return best

    def _detail(self, rule: AlertRule, key: Tuple, series: _Series,
                signal: str, value: float, **extra: Any) -> dict:
        return {
            "endpoint": key[0], "metric": rule.metric,
            "signal": signal, "value": value,
            "series": dict(series.labels),
            "points": [(round(t, 4), v)
                       for t, v in series.tail(signal)],
            **extra,
        }

    def _eval_threshold(self, rule: AlertRule,
                        now: float) -> Optional[dict]:
        op = _OPS[rule.op]
        out: Optional[dict] = None
        for key, series in self.store.match(rule.metric, rule.labels):
            signal = rule.signal or SIGNALS_BY_KIND[series.kind][0]
            point = series.last(signal)
            if point is None:
                continue
            v = point[1]
            if rule.per:
                den = self._find_den(key[0], rule.per, series.labels)
                if den is None:
                    continue
                dsig = SIGNALS_BY_KIND[den.kind][0]
                dp = den.last(dsig)
                if dp is None or dp[1] == 0:
                    continue
                v = v / dp[1]
            if self._sustained(rule, key, op(v, rule.value), now) \
                    and out is None:
                out = self._detail(rule, key, series, signal, v,
                                   threshold=rule.value, op=rule.op)
        return out

    def _eval_anomaly(self, rule: AlertRule,
                      now: float) -> Optional[dict]:
        out: Optional[dict] = None
        for key, series in self.store.match(rule.metric, rule.labels):
            signal = rule.signal or SIGNALS_BY_KIND[series.kind][0]
            ring = series.rings.get(signal)
            if not ring or len(ring) < rule.min_samples + 1:
                continue
            values = [v for _t, v in ring]
            baseline = values[-(rule.baseline_points + 1):-1]
            z = _robust_z(baseline, values[-1], rule.rel_floor,
                          rule.abs_floor)
            bad = (z >= rule.z if rule.side == "upper"
                   else z <= -rule.z if rule.side == "lower"
                   else abs(z) >= rule.z)
            if self._sustained(rule, key, bad, now) and out is None:
                out = self._detail(rule, key, series, signal,
                                   values[-1], zscore=round(z, 2))
        return out

    def _eval_forecast(self, rule: AlertRule,
                       now: float) -> Optional[dict]:
        """The predictive kind: fit a robust trend over each bound
        series' ring tail and fire when the PREDICTED value crosses
        the threshold within the horizon (obs/forecast.py owns the
        math and its noise gate).  Also publishes the nearest forecast
        into the ``nns_forecast_*`` gauges and the FORECASTS store —
        the rule is an exporter even while nothing fires."""
        out: Optional[dict] = None
        best: Optional[dict] = None
        for key, series in self.store.match(rule.metric, rule.labels):
            if series.kind == "histogram":
                continue  # forecast binds rates/levels only (NNS517)
            signal = rule.signal or SIGNALS_BY_KIND[series.kind][0]
            # trend memory matched to the prediction span: fit over
            # ~half the horizon of history (clamped).  A full ring can
            # span several horizons, and a Theil-Sen median over that
            # much flat history damps a fresh ramp into invisibility
            # exactly when the forecast must see it.
            n_fit = max(2 * _forecast.MIN_FIT_POINTS,
                        min(int(rule.horizon_s
                                / (2 * self.interval_s)),
                            _forecast.MAX_FIT_POINTS))
            fit = _forecast.fit_trend(series.tail(signal, n_fit))
            if fit is None:
                self._states[rule.name].bad_since.pop(key, None)
                continue
            predicted, eta, crossing = _forecast.forecast_crossing(
                fit, rule.value, rule.op, rule.horizon_s)
            row = {
                "rule": rule.name, "metric": rule.metric,
                "signal": signal, "series": dict(series.labels),
                "endpoint": key[0], "value": predicted,
                "eta_s": eta, "threshold": rule.value,
                "op": rule.op, "horizon_s": rule.horizon_s,
                "slope": fit.slope, "sigma": fit.sigma,
                "firing": crossing,
            }
            if best is None or (eta is not None
                                and (best["eta_s"] is None
                                     or eta < best["eta_s"])):
                best = row
            if self._sustained(rule, key, crossing, now) \
                    and out is None:
                out = self._detail(
                    rule, key, series, signal, predicted,
                    threshold=rule.value, op=rule.op,
                    eta_s=round(eta, 3) if eta is not None else None,
                    horizon_s=rule.horizon_s,
                    slope=fit.slope)
        if best is not None:
            best["firing"] = out is not None
            self._fc_value.labels(rule=rule.name).set(best["value"])
            self._fc_eta.labels(rule=rule.name).set(
                best["eta_s"] if best["eta_s"] is not None else -1.0)
            FORECASTS.update(rule.name, best)
        return out

    def _capacity_tick(self, entries: List[dict], now: float) -> None:
        """The headroom join, once per sample: forecast each pool's
        arrival rate over the capacity horizon (the longest forecast
        rule's, else the default) and compare against the sustainable
        rate extrapolated from live MFU/roofline — falling back to
        window occupancy.  Exports ``nns_capacity_headroom{pool}`` and
        the FORECASTS capacity rows ``/healthz`` summarizes."""
        horizons = [r.horizon_s for r in self.rules
                    if r.kind == "forecast" and r.horizon_s > 0]
        horizon = max(horizons) if horizons \
            else _forecast.HEADROOM_HORIZON_S
        for entry in entries:
            snap = entry.get("snap")
            if not snap:
                continue
            ep = entry["endpoint"]
            execs = [e for e in snap.get("executables") or []
                     if e.get("mfu")]
            for row in snap.get("pools") or []:
                label = row.get("pool", "")
                s = self.store.find(ep, "nns_pool_frames_total",
                                    {"pool": label})
                if s is None:
                    continue
                pts = s.tail("rate", _forecast.MAX_FIT_POINTS)
                if not pts:
                    continue
                current = pts[-1][1]
                fit = _forecast.fit_trend(pts)
                predicted = fit.at(horizon) if fit is not None \
                    else current
                # the pooled model's live MFU vs its roofline ceiling
                # (busiest executable wins when several match)
                model = row.get("model")
                cands = [e for e in execs
                         if e.get("source") == model] or execs
                mfu = ceiling = None
                if cands:
                    top = max(cands, key=lambda e: e.get(
                        "device_seconds_window", 0.0))
                    mfu = top.get("mfu")
                    ceiling = top.get("mfu_ceiling")
                occ = None
                stats = row.get("stats") or {}
                b = row.get("batcher") or {}
                if stats.get("avg_batch_occupancy") \
                        and b.get("max_batch"):
                    occ = stats["avg_batch_occupancy"] / b["max_batch"]
                cap = _forecast.capacity_headroom(
                    current, predicted, mfu=mfu, mfu_ceiling=ceiling,
                    occupancy=occ)
                if cap is None:
                    continue
                self._headroom.labels(pool=label).set(cap["headroom"])
                FORECASTS.update_capacity(label, {
                    "pool": label, "endpoint": ep,
                    "arrival_fps": current,
                    "predicted_fps": max(predicted, 0.0),
                    "horizon_s": horizon, **cap})

    def _eval_burn(self, rule: AlertRule, now: float) -> Optional[dict]:
        out: Optional[dict] = None
        for key, series in self.store.match(rule.metric, rule.labels):
            fracs = {}
            for win, win_s in (("fast", rule.fast_s),
                               ("slow", rule.slow_s)):
                if series.kind == "histogram":
                    slo_ms = rule.slo_ms or self.store.slo_hint(
                        key[0], series.labels.get("pool"))
                    if not slo_ms:
                        fracs = None
                        break
                    dist = series.hist_window(win_s, now)
                    total = sum(dist) if dist else 0.0
                    if total <= 0:
                        fracs = None
                        break
                    fracs[win] = _over_threshold(
                        series.bounds, dist, slo_ms / 1e3) / total
                else:
                    if not rule.per:
                        fracs = None
                        break
                    den = self._find_den(key[0], rule.per,
                                          series.labels)
                    num_d = series.cum_delta_over(win_s, now)
                    den_d = den.cum_delta_over(win_s, now) \
                        if den is not None else None
                    if num_d is None or not den_d:
                        fracs = None
                        break
                    fracs[win] = num_d / den_d
            if fracs is None:
                self._states[rule.name].bad_since.pop(key, None)
                continue
            bad = all(f >= rule.burn * rule.budget
                      for f in fracs.values())
            if self._sustained(rule, key, bad, now) and out is None:
                burn_fast = fracs["fast"] / rule.budget
                out = self._detail(
                    rule, key, series,
                    rule.signal or ("p99" if series.kind == "histogram"
                                    else "rate"),
                    round(burn_fast, 3),
                    err_frac={k: round(v, 5) for k, v in fracs.items()},
                    burn_threshold=rule.burn)
        return out

    # -- transitions + actions ------------------------------------------------

    def _transition(self, name: str, severity: str,
                    detail: Optional[dict],
                    now: float) -> Optional[dict]:
        st = self._states[name]
        firing = detail is not None
        self._gauge.labels(rule=name, severity=severity).set(
            1.0 if firing else 0.0)
        if firing:
            st.detail = detail
        if firing and not st.firing:
            st.firing = True
            st.since = now
            st.fired += 1
            self._fired.labels(rule=name, severity=severity).inc()
            event = {"ts": now, "wall": time.time(), "rule": name,
                     "severity": severity, "detail": detail}
            self.alert_log.append(event)
            self._act_fire(name, severity, detail)
            return event
        if not firing and st.firing:
            st.firing = False
            self._act_resolve(name, severity, now - st.since)
        return None

    def _act_fire(self, name: str, severity: str, detail: dict) -> None:
        """The shipped actions, on the RISING edge only (one firing
        episode = one warning, one dump trigger): log + bus WARNING on
        every registered pipeline, flight-recorder note + async dump
        (the recorder's own rate limit bounds an alert storm; the dump
        work never runs on the sampler thread)."""
        from ..utils.log import logw

        series = detail.get("series", {})
        logw("nns-watch: ALERT %s [%s] %s=%s %s", name, severity,
             detail.get("metric", ""), detail.get("value"),
             series or "")
        # the bus WARNING is rate-limited across ALL rules (one per
        # second): a rule oscillating around its threshold every
        # sampler tick is a new episode per tick, and the pipelines'
        # buses must not drown in it (the log line, counter and
        # recorder note above still record every episode)
        now = time.monotonic()
        if now - self._warn_ts >= 1.0:
            self._warn_ts = now
            try:
                from ..runtime.events import Message, MessageKind

                for pipe in self.registry._live_pipelines():
                    pipe.post(Message(
                        MessageKind.WARNING, "nns-watch",
                        data={"alert": name, "severity": severity,
                              "metric": detail.get("metric", ""),
                              "value": detail.get("value"),
                              "series": series}))
            except Exception:  # noqa: BLE001 - a broken bus handler
                # must not take the watchdog down with it
                pass
        from .flightrec import FLIGHT

        FLIGHT.note("alert", name, severity=severity,
                    metric=detail.get("metric", ""),
                    value=detail.get("value"))
        FLIGHT.trigger_async("alert", name)
        # deep host profile (obs/prof.py): armed via
        # NNS_TPU_PROF_DEEP_DIR — the rising edge makes it once per
        # alert episode, the profiler's own min-interval bounds an
        # alert storm, and the capture runs on its own thread, never
        # this sampler's
        from .prof import deep_trigger

        deep_trigger(name)

    def _act_resolve(self, name: str, severity: str,
                     held_s: float) -> None:
        from ..utils.log import logi

        logi("nns-watch: resolved %s [%s] after %.1fs", name, severity,
             held_s)
        from .flightrec import FLIGHT

        FLIGHT.note("alert-resolved", name, severity=severity,
                    held_s=round(held_s, 2))

    # -- pull side ------------------------------------------------------------

    def alerts(self) -> List[dict]:
        """Current state of every rule (what ``nns-watch`` renders)."""
        with self._lock:
            out = []
            by_name = {r.name: r for r in self.rules}
            for name, st in self._states.items():
                rule = by_name.get(name)
                out.append({
                    "rule": name,
                    "severity": rule.severity if rule else "critical",
                    "firing": st.firing,
                    "fired": st.fired,
                    "since": st.since if st.firing else None,
                    "detail": st.detail if st.firing else None,
                })
            out.sort(key=lambda r: (not r["firing"], r["rule"]))
            return out


# -- process-global watchdog (env hook) ---------------------------------------

WATCH: Optional[Watch] = None

_env_checked = False


def maybe_start_from_env() -> None:
    """``NNS_TPU_WATCH=<interval_s>`` starts a process-global watchdog
    on first pipeline start (same activation hook as
    ``NNS_TPU_METRICS_PORT`` / ``NNS_TPU_CHAOS`` /
    ``NNS_TPU_FLIGHTREC_DIR``), with the rule set from
    ``NNS_TPU_WATCH_RULES`` (or the default pack).  A no-op under the
    global obs kill switch."""
    global _env_checked, WATCH
    if _env_checked:
        return
    _env_checked = True
    spec = os.environ.get("NNS_TPU_WATCH", "").strip()
    if not spec or _hooks.DISABLED:
        return
    try:
        interval = float(spec) if spec not in ("1", "true", "yes") \
            else 1.0
        path = os.environ.get("NNS_TPU_WATCH_RULES", "").strip()
        store_cfg = load_store(path) if path else {}
        WATCH = Watch(rules=rules_from_env(), interval_s=interval,
                      **store_cfg)
        WATCH.start()
    except (ValueError, RuleError, OSError) as e:
        from ..utils.log import logw

        logw("cannot start watchdog from NNS_TPU_WATCH=%s: %s", spec, e)


# -- CLI (`nns-watch`) --------------------------------------------------------


def _render_alerts(alerts: List[dict]) -> str:
    lines = [f"{'RULE':<26}{'SEVERITY':<10}{'STATE':>8}{'FIRED':>7}"
             f"  DETAIL"]
    for a in alerts:
        d = a.get("detail") or {}
        series = d.get("series") or {}
        det = ""
        if a["firing"]:
            det = f"{d.get('metric', '')}={d.get('value')}"
            if series:
                det += " " + ",".join(f"{k}={v}"
                                      for k, v in sorted(series.items()))
        lines.append(
            f"{a['rule']:<26.26}{a['severity']:<10.10}"
            + ("FIRING" if a["firing"] else "ok").rjust(8)
            + str(a["fired"]).rjust(7) + ("  " + det if det else ""))
    return "\n".join(lines)


def build_parser():
    import argparse

    p = argparse.ArgumentParser(
        prog="nns-watch",
        description="Alerting watchdog over the metrics registry: "
                    "sample, evaluate rules, alarm "
                    "(Documentation/observability.md)")
    p.add_argument("--connect", metavar="HOST:PORT[,HOST:PORT...]",
                   action="append", default=None,
                   help="watch remote /json endpoints (fleet mode; "
                        "repeat or comma-separate); default: the "
                        "in-process registry")
    p.add_argument("--rules", default=None, metavar="FILE",
                   help="TOML/JSON rules file (default: "
                        "$NNS_TPU_WATCH_RULES, else the built-in pack)")
    p.add_argument("--interval", type=float, default=1.0,
                   help="seconds between samples (default 1)")
    p.add_argument("--once", type=int, default=None, metavar="N",
                   help="take N samples, print the alert table, exit "
                        "(1 when anything is firing)")
    p.add_argument("--json", dest="as_json", action="store_true",
                   help="machine-readable output")
    return p


def main(argv=None, out=None) -> int:
    import sys

    out = out or sys.stdout
    args = build_parser().parse_args(argv)
    try:
        rules = load_rules(args.rules) if args.rules else rules_from_env()
        path = args.rules \
            or os.environ.get("NNS_TPU_WATCH_RULES", "").strip()
        store_cfg = load_store(path) if path else {}
    except (RuleError, OSError) as e:
        print(f"nns-watch: bad rules: {e}", file=sys.stderr)
        return 2
    endpoints: List[str] = []
    for item in args.connect or []:
        endpoints.extend(tok.strip() for tok in str(item).split(",")
                         if tok.strip())
    try:
        w = Watch(rules=rules, interval_s=args.interval,
                  endpoints=endpoints or None, **store_cfg)
    except RuleError as e:
        print(f"nns-watch: bad rules: {e}", file=sys.stderr)
        return 2
    if not w.enabled:
        print("nns-watch: observability disabled "
              "(NNS_TPU_OBS_DISABLE) — nothing to do", file=sys.stderr)
        return 2
    try:
        if args.once is not None:
            for i in range(max(args.once, 1)):
                if i:
                    time.sleep(args.interval)
                w.sample_once()
            alerts = w.alerts()
            if args.as_json:
                print(json.dumps(alerts, indent=1, default=str),
                      file=out)
            else:
                print(_render_alerts(alerts), file=out)
            return 1 if any(a["firing"] for a in alerts) else 0
        while True:
            events = w.sample_once()
            for ev in events:
                if args.as_json:
                    print(json.dumps(ev, default=str), file=out)
                else:
                    d = ev["detail"] or {}
                    print(f"ALERT {ev['rule']} [{ev['severity']}] "
                          f"{d.get('metric', '')}={d.get('value')} "
                          f"{d.get('series', '')}", file=out)
            out.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())

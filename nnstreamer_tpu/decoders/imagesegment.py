"""``image_segment`` decoder: segmentation map → colored RGBA video.

Parity target: /root/reference/ext/nnstreamer/tensor_decoder/
tensordec-imagesegment.c (665 LoC): schemes ``tflite-deeplab`` (H,W,C
per-class scores → argmax) and ``snpe-depth``/raw index maps; each class
index maps to a palette color (the reference's rainbow table).

- option1 — scheme: ``tflite-deeplab`` (argmax over channel scores) or
  ``index`` (input already is an integer class map)
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core import Buffer, Caps, CapsStruct, Tensor, TensorSpec, TensorsSpec
from . import Decoder, register_decoder

_PALETTE = np.array(
    [[0, 0, 0, 0]] + [
        [(37 * i) % 256, (97 * i) % 256, (157 * i) % 256, 255]
        for i in range(1, 64)],
    np.uint8)

_argmax_ch = None


def _jit_argmax_channel():
    """Device pre-reduction for the deeplab layout: argmax over the
    class channel runs in HBM, so only the (H, W) int32 index map
    drains — 1/C of the score volume (C=21 for deeplab) crosses the
    boundary, once."""
    global _argmax_ch
    if _argmax_ch is None:
        import jax

        _argmax_ch = jax.jit(
            lambda x: jax.numpy.argmax(x, axis=-1).astype(
                jax.numpy.int32))
    return _argmax_ch


@register_decoder
class ImageSegment(Decoder):
    MODE = "image_segment"

    def _dims(self, in_spec: TensorsSpec):
        t = in_spec.tensors[0]
        shape = t.shape  # row-major
        scheme = (self.options[0] or "tflite-deeplab").strip().lower()
        if scheme == "index" or shape[-1] > 64 or len(shape) < 3:
            # integer map (..., H, W)
            return shape[-1], shape[-2]
        return shape[-2], shape[-3]  # (..., H, W, C)

    def out_caps(self, in_spec: TensorsSpec) -> Caps:
        w, h = self._dims(in_spec)
        return Caps.new(CapsStruct.make(
            "video/x-raw", format="RGBA", width=w, height=h,
            framerate=in_spec.rate))

    def prereduce_active(self, buf: Buffer) -> bool:
        t = buf.tensors[0]
        scheme = (self.options[0] or "tflite-deeplab").strip().lower()
        shape = t.spec.shape
        return t.is_device and scheme != "index" \
            and len(shape) >= 3 and shape[-1] <= 64

    def decode(self, buf: Buffer, in_spec: Optional[TensorsSpec]) -> Buffer:
        t = buf.tensors[0]
        scheme = (self.options[0] or "tflite-deeplab").strip().lower()
        if self.prereduce_active(buf):
            # deeplab scores on device: argmax over the class channel
            # in HBM, drain only the (H, W) index map (one counted
            # crossing via the Tensor wrapper)
            dev = t.jax()
            dev = dev.reshape(dev.shape[-3], dev.shape[-2], dev.shape[-1])
            idx = Tensor(_jit_argmax_channel()(dev)).np().astype(np.int64)
        else:
            arr = t.np()
            if scheme == "index" or arr.ndim < 3 or arr.shape[-1] > 64:
                idx = arr.reshape(arr.shape[-2],
                                  arr.shape[-1]).astype(np.int64)
            else:
                scores = arr.reshape(arr.shape[-3], arr.shape[-2],
                                     arr.shape[-1])
                idx = scores.argmax(axis=-1)
        frame = _PALETTE[idx % len(_PALETTE)]
        out = Buffer(
            tensors=[Tensor(frame,
                            TensorSpec.from_shape(frame.shape, np.uint8))],
            pts=buf.pts, duration=buf.duration, meta=dict(buf.meta))
        out.meta["segment_map"] = idx
        return out

"""Tensor specs: shapes, dtypes, and the dim-string grammar.

Parity targets:
- dim string parse/print ``"3:224:224:1"`` —
  /root/reference/gst/nnstreamer/nnstreamer_plugin_api_util_impl.c:1031
  (``gst_tensor_parse_dimension``) and :529
  (``gst_tensors_info_parse_dimensions_string``).
- rank-flexible dimension comparison (trailing 1s are insignificant) —
  nnstreamer_plugin_api_util_impl.c (``gst_tensor_dimension_is_equal``).

Convention: ``dims`` is stored innermost-first like the reference grammar
(``3:224:224:1`` = channel:width:height:batch), while ``shape`` is the
reversed, rank-trimmed tuple handed to JAX/numpy (batch, height, width,
channel).  All device math uses ``shape``; all wire/config text uses ``dims``.
"""

from __future__ import annotations

import dataclasses
import math
from fractions import Fraction
from typing import Iterable, Optional, Sequence, Tuple

from .types import (
    DType,
    TensorFormat,
    TENSOR_COUNT_LIMIT,
    TENSOR_RANK_LIMIT,
)


def split_tensor_list(v: str) -> list:
    """Split a multi-tensor dims/types list into per-tensor strings.
    Both tensor separators are accepted: "," (property grammar) and "."
    (caps-string grammar, where "," already separates caps fields —
    reference caps use ``dimensions=(string)1:1:784:1.1:1:10:1``)."""
    return [d for d in v.replace(".", ",").split(",") if d.strip()]


def parse_dimension(dim_str: str) -> Tuple[int, ...]:
    """Parse ``"3:224:224:1"`` into an innermost-first dim tuple.

    Rank is the number of specified components (≤16).  A trailing component of
    0 terminates the dimension (reference uses 0 as "rank end" marker).
    """
    parts = dim_str.strip().split(":")
    if len(parts) > TENSOR_RANK_LIMIT:
        raise ValueError(
            f"dimension rank {len(parts)} exceeds limit {TENSOR_RANK_LIMIT}: {dim_str!r}"
        )
    dims = []
    for p in parts:
        p = p.strip()
        if p in ("", "0"):
            break
        v = int(p)
        if v < 0:
            raise ValueError(f"negative dimension in {dim_str!r}")
        dims.append(v)
    if not dims:
        raise ValueError(f"empty dimension string: {dim_str!r}")
    return tuple(dims)


def format_dimension(dims: Sequence[int]) -> str:
    return ":".join(str(d) for d in dims)


def dims_equal(a: Sequence[int], b: Sequence[int]) -> bool:
    """Rank-flexible comparison: trailing 1s are insignificant."""
    n = max(len(a), len(b))
    for i in range(n):
        da = a[i] if i < len(a) else 1
        db = b[i] if i < len(b) else 1
        if da != db:
            return False
    return True


def dims_to_shape(dims: Sequence[int]) -> Tuple[int, ...]:
    """Innermost-first dims → numpy/JAX row-major shape."""
    return tuple(reversed(dims))


def shape_to_dims(shape: Sequence[int]) -> Tuple[int, ...]:
    if len(shape) == 0:
        return (1,)
    return tuple(reversed(shape))


@dataclasses.dataclass(frozen=True)
class TensorSpec:
    """One tensor's static schema (parity: GstTensorInfo,
    tensor_typedef.h:261-268)."""

    dtype: DType
    dims: Tuple[int, ...]
    name: Optional[str] = None

    def __post_init__(self):
        object.__setattr__(self, "dims", tuple(int(d) for d in self.dims))
        if len(self.dims) > TENSOR_RANK_LIMIT:
            raise ValueError(f"rank {len(self.dims)} exceeds {TENSOR_RANK_LIMIT}")
        if any(d <= 0 for d in self.dims):
            raise ValueError(f"non-positive dimension: {self.dims}")

    @classmethod
    def from_shape(cls, shape: Sequence[int], dtype, name: Optional[str] = None
                   ) -> "TensorSpec":
        if not isinstance(dtype, DType):
            dtype = DType.from_np(dtype) if not isinstance(dtype, str) \
                else DType.from_string(dtype)
        return cls(dtype=dtype, dims=shape_to_dims(shape), name=name)

    @classmethod
    def parse(cls, dim_str: str, type_str: str, name: Optional[str] = None
              ) -> "TensorSpec":
        return cls(dtype=DType.from_string(type_str),
                   dims=parse_dimension(dim_str), name=name)

    @property
    def shape(self) -> Tuple[int, ...]:
        return dims_to_shape(self.dims)

    @property
    def rank(self) -> int:
        return len(self.dims)

    @property
    def num_elements(self) -> int:
        return math.prod(self.dims)

    @property
    def nbytes(self) -> int:
        return self.num_elements * self.dtype.size

    def dim_string(self) -> str:
        return format_dimension(self.dims)

    def is_compatible(self, other: "TensorSpec") -> bool:
        """dtype match + rank-flexible dim match (ignores name)."""
        return self.dtype == other.dtype and dims_equal(self.dims, other.dims)

    def with_dims(self, dims: Sequence[int]) -> "TensorSpec":
        return dataclasses.replace(self, dims=tuple(dims))

    def with_dtype(self, dtype: DType) -> "TensorSpec":
        return dataclasses.replace(self, dtype=dtype)

    def __str__(self) -> str:
        n = f" name={self.name}" if self.name else ""
        return f"{self.dim_string()}/{self.dtype}{n}"


@dataclasses.dataclass(frozen=True)
class TensorsSpec:
    """Schema of one stream frame: N tensors + format + framerate.

    Parity: GstTensorsInfo + GstTensorsConfig (tensor_typedef.h:273-296).
    Framerate is an exact fraction; rate 0/1 means "unknown/any" as in the
    reference's ``[0, max]`` fraction range.
    """

    tensors: Tuple[TensorSpec, ...] = ()
    format: TensorFormat = TensorFormat.STATIC
    rate: Fraction = Fraction(0, 1)

    def __post_init__(self):
        object.__setattr__(self, "tensors", tuple(self.tensors))
        if len(self.tensors) > TENSOR_COUNT_LIMIT:
            raise ValueError(
                f"{len(self.tensors)} tensors exceeds limit {TENSOR_COUNT_LIMIT}")
        if not isinstance(self.rate, Fraction):
            object.__setattr__(self, "rate", Fraction(self.rate))

    # -- construction -------------------------------------------------------

    @classmethod
    def parse(cls, dimensions: str, types: str,
              format: str = "static", rate=None) -> "TensorsSpec":
        """Parse dims/types lists (parity:
        gst_tensors_info_parse_dimensions_string,
        nnstreamer_plugin_api_util_impl.c:529); see
        :func:`split_tensor_list` for the separator grammar."""
        dim_list = split_tensor_list(dimensions)
        type_list = split_tensor_list(types)
        if len(dim_list) != len(type_list):
            raise ValueError(
                f"dims count {len(dim_list)} != types count {len(type_list)}")
        tensors = tuple(
            TensorSpec.parse(d, t) for d, t in zip(dim_list, type_list))
        return cls(tensors=tensors, format=TensorFormat.from_string(format),
                   rate=Fraction(rate) if rate is not None else Fraction(0, 1))

    @classmethod
    def of(cls, *specs: TensorSpec, format=TensorFormat.STATIC,
           rate=Fraction(0, 1)) -> "TensorsSpec":
        return cls(tensors=tuple(specs), format=format, rate=Fraction(rate))

    @classmethod
    def from_shapes(cls, shapes: Iterable[Sequence[int]], dtypes,
                    rate=Fraction(0, 1)) -> "TensorsSpec":
        shapes = list(shapes)
        if not isinstance(dtypes, (list, tuple)):
            dtypes = [dtypes] * len(shapes)
        if len(dtypes) != len(shapes):
            raise ValueError(
                f"{len(shapes)} shapes but {len(dtypes)} dtypes")
        return cls(tensors=tuple(
            TensorSpec.from_shape(s, d) for s, d in zip(shapes, dtypes)),
            rate=Fraction(rate))

    # -- accessors ----------------------------------------------------------

    @property
    def num_tensors(self) -> int:
        return len(self.tensors)

    def __len__(self) -> int:
        return len(self.tensors)

    def __getitem__(self, i: int) -> TensorSpec:
        return self.tensors[i]

    def dimensions_string(self, sep: str = ",") -> str:
        return sep.join(t.dim_string() for t in self.tensors)

    def types_string(self, sep: str = ",") -> str:
        return sep.join(str(t.dtype) for t in self.tensors)

    @property
    def frame_nbytes(self) -> int:
        return sum(t.nbytes for t in self.tensors)

    def is_static(self) -> bool:
        return self.format == TensorFormat.STATIC

    def is_compatible(self, other: "TensorsSpec") -> bool:
        """Frame-level compatibility: same format; for static streams, same
        tensor count and per-tensor compatibility. Flexible/sparse streams
        accept any payload schema (the schema travels per-buffer in meta)."""
        if self.format != other.format:
            return False
        if self.format != TensorFormat.STATIC:
            return True
        if len(self.tensors) != len(other.tensors):
            return False
        return all(a.is_compatible(b)
                   for a, b in zip(self.tensors, other.tensors))

    def with_rate(self, rate) -> "TensorsSpec":
        return dataclasses.replace(self, rate=Fraction(rate))

    def with_tensors(self, tensors: Iterable[TensorSpec]) -> "TensorsSpec":
        return dataclasses.replace(self, tensors=tuple(tensors))

    def with_format(self, format: TensorFormat) -> "TensorsSpec":
        return dataclasses.replace(self, format=format)

    def __str__(self) -> str:
        body = ",".join(str(t) for t in self.tensors)
        r = f"@{self.rate}" if self.rate else ""
        return f"tensors[{self.format}]({body}){r}"

"""Device-memory accounting: HBM usage gauges + model weight footprints.

``jax.Device.memory_stats()`` exposes the allocator's view of each
accelerator (``bytes_in_use`` / ``peak_bytes_in_use`` / ``bytes_limit``
on TPU/GPU backends; ``None``/absent on the CPU backend).  This module
polls it at *scrape* time — no background thread, nothing on the hot
path — into the registry's ``device_memory`` table (snapshot v4) and
the ``nns_device_memory_bytes{device,kind=in_use|peak|limit}`` gauges,
plus the DEVICE MEM section of ``nns-top`` and a summary on
``/healthz``.

Per-model weight footprints come from the serving pool: each PoolEntry
whose sub-plugin exposes ``weight_bytes()`` (jax-xla does) exports
``nns_model_weight_bytes{pool,placement}`` — the HBM a pooled model's
params pin, with ``placement`` naming where they live (``host`` before
first placement, ``device`` after ``device_put``, ``mesh`` when laid
out over a mesh).

The CPU backend (and any device whose allocator reports nothing)
degrades gracefully to an empty table — the gauges simply don't exist
there, mirroring how the -1 "no data" sentinels are omitted from the
exposition.
"""

from __future__ import annotations

import sys
from typing import Any, Dict, List, Optional, Sequence

#: snapshot-table kind -> jax memory_stats() key
MEMORY_KINDS = {
    "in_use": "bytes_in_use",
    "peak": "peak_bytes_in_use",
    "limit": "bytes_limit",
}


def _devices() -> Sequence[Any]:
    """The process's jax devices — WITHOUT initializing jax: a scrape
    of a process that never touched the accelerator must not pay (or
    trigger) backend startup."""
    if "jax" not in sys.modules:
        return ()
    jax = sys.modules["jax"]
    try:
        return jax.devices()
    except (RuntimeError, AttributeError):
        # RuntimeError: backend not initializable here.  AttributeError:
        # another thread is MID-first-import of jax — sys.modules holds
        # the partially initialized module, which is exactly the state
        # this sys.modules probe exists to sidestep; the scrape reports
        # no devices this tick and catches them on the next one.
        return ()


def device_memory_table(devices: Optional[Sequence[Any]] = None
                        ) -> List[dict]:
    """One row per device that reports allocator stats:
    ``{"device", "in_use", "peak", "limit"}`` (bytes; keys absent when
    the allocator doesn't report them).  Devices without
    ``memory_stats`` — or whose call returns ``None``/raises (the CPU
    backend) — are skipped, not errored."""
    rows: List[dict] = []
    for d in (devices if devices is not None else _devices()):
        stats = None
        get = getattr(d, "memory_stats", None)
        if callable(get):
            try:
                stats = get()
            except (RuntimeError, NotImplementedError, TypeError):
                stats = None
        if not stats:
            continue
        row: Dict[str, Any] = {"device": str(d)}
        for kind, key in MEMORY_KINDS.items():
            v = stats.get(key)
            if v is not None:
                row[kind] = int(v)
        if len(row) > 1:
            rows.append(row)
    return rows


def device_memory_summary(devices: Optional[Sequence[Any]] = None
                          ) -> List[dict]:
    """The ``/healthz`` slice: device + in-use bytes only (cheap to
    serialize, enough for a fleet probe to spot an HBM leak)."""
    return [{"device": r["device"], "in_use": r.get("in_use")}
            for r in device_memory_table(devices)]

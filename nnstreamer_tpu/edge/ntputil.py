"""SNTP client + clock-offset estimation for cross-host timestamp sync.

Parity target: /root/reference/gst/mqtt/ntputil.c (245 LoC,
``ntputil_get_epoch``): query a list of (host, port) NTP servers in
order, return the first answer as unix epoch microseconds, falling back
to the local clock — the clock source behind ``mqtt-ntp-sync`` so
publisher ``sent_time`` stamps are comparable across hosts
(Documentation/synchronization-in-mqtt-elements.md).

Beyond the reference's epoch-only read, this module implements the full
NTP 4-timestamp exchange (RFC 5905 §8): from ``(t1, t2, t3, t4)`` —
client send, server receive, server send, client receive, the first and
last on the client clock, the middle two on the server clock —
:func:`offset_and_delay` estimates the clock offset and the pure
network round-trip.  The same math runs against any request/response
link that stamps those four times, which is how the distributed latency
tracer (Documentation/observability.md) places a query server's spans
on the client's timeline without touching NTP at all: every traced
query round-trip IS a clock sample.  :class:`PeerClock` keeps the best
(minimum-delay) recent sample per peer, the standard NTP filter — the
lower the delay, the less room for asymmetry error in the offset.

Wire format: 48-byte SNTPv4 packet; the server's transmit timestamp
(seconds since 1900 + 32-bit fraction) converts to the unix epoch.
``MqttSink(epoch_fn=ntp_epoch_fn([...]))`` plugs it into the MQTT
header stamps.
"""

from __future__ import annotations

import collections
import socket
import struct
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

NTP_PORT = 123
#: seconds between the NTP era (1900) and the unix epoch (1970)
NTP_UNIX_DELTA = 2_208_988_800


def _parse_transmit_ts(packet: bytes) -> int:
    """Server transmit timestamp (bytes 40..47) → unix epoch µs."""
    if len(packet) < 48:
        raise ValueError(f"ntp: short packet ({len(packet)}B)")
    sec, frac = struct.unpack(">II", packet[40:48])
    if sec == 0:
        raise ValueError("ntp: empty transmit timestamp")
    usec = (sec - NTP_UNIX_DELTA) * 1_000_000 + (frac * 1_000_000 >> 32)
    return usec


def _parse_ts(packet: bytes, off: int) -> int:
    """One 64-bit NTP timestamp at ``off`` → unix epoch µs (0 if unset)."""
    sec, frac = struct.unpack_from(">II", packet, off)
    if sec == 0:
        return 0
    return (sec - NTP_UNIX_DELTA) * 1_000_000 + (frac * 1_000_000 >> 32)


def query_server(host: str, port: int = NTP_PORT,
                 timeout: float = 2.0) -> int:
    """One SNTP round-trip → unix epoch µs from the server clock."""
    req = bytearray(48)
    req[0] = (0 << 6) | (4 << 3) | 3  # LI=0, VN=4, mode=3 (client)
    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
        s.settimeout(timeout)
        s.sendto(bytes(req), (host, int(port)))
        data, _ = s.recvfrom(512)
    return _parse_transmit_ts(data)


# -- 4-timestamp offset + delay estimation ------------------------------------


def offset_and_delay(t1: float, t2: float, t3: float,
                     t4: float) -> Tuple[float, float]:
    """RFC 5905 §8 estimate from one request/response exchange.

    ``t1``/``t4`` are on the LOCAL clock (request send, response
    receive), ``t2``/``t3`` on the REMOTE clock (request receive,
    response send).  Returns ``(offset, delay)`` in the callers' time
    unit: ``offset`` estimates ``remote_clock - local_clock`` (assuming
    symmetric path delay), ``delay`` is the pure network round-trip with
    the remote's processing time removed.  The estimate has the handy
    containment property ``t2 - offset = t1 + delay/2`` and ``t3 -
    offset = t4 - delay/2``: remote events mapped with this offset
    always land inside the local ``[t1, t4]`` window."""
    return ((t2 - t1) + (t3 - t4)) / 2.0, (t4 - t1) - (t3 - t2)


def query_server_sample(host: str, port: int = NTP_PORT,
                        timeout: float = 2.0) -> dict:
    """Full SNTP exchange → ``{"epoch_us", "offset_us", "delay_us"}``.

    Unlike :func:`query_server` (transmit timestamp only), this stamps
    the request's transmit field, reads back originate/receive/transmit
    and applies :func:`offset_and_delay` — the real NTP discipline."""
    req = bytearray(48)
    req[0] = (0 << 6) | (4 << 3) | 3
    t1 = int(time.time() * 1e6)
    sec = t1 // 1_000_000 + NTP_UNIX_DELTA
    frac = ((t1 % 1_000_000) << 32) // 1_000_000
    req[40:48] = struct.pack(">II", sec, frac)
    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
        s.settimeout(timeout)
        s.sendto(bytes(req), (host, int(port)))
        data, _ = s.recvfrom(512)
    t4 = int(time.time() * 1e6)
    if len(data) < 48:
        raise ValueError(f"ntp: short packet ({len(data)}B)")
    t2 = _parse_ts(data, 32)  # receive timestamp
    t3 = _parse_ts(data, 40)  # transmit timestamp
    if not t3:
        raise ValueError("ntp: empty transmit timestamp")
    if not t2:
        t2 = t3  # degenerate server: fall back to transmit for both
    offset, delay = offset_and_delay(float(t1), float(t2), float(t3),
                                     float(t4))
    return {"epoch_us": t3, "offset_us": offset, "delay_us": delay}


class PeerClock:
    """Rolling clock-offset estimate for ONE remote peer.

    Feed it ``(t1, t2, t3, t4)`` exchanges (NTP packets, or any traced
    request/response round-trip); :attr:`offset` returns the offset of
    the minimum-delay sample in the window — the NTP clock filter: the
    fastest observed round-trip bounds the asymmetry error tightest.
    Thread-safe; samples age out by count (``window``) so a drifting
    clock re-converges."""

    def __init__(self, window: int = 16):
        self._lock = threading.Lock()
        self._samples: "collections.deque[Tuple[float, float]]" = \
            collections.deque(maxlen=int(window))

    def add(self, offset: float, delay: float) -> None:
        with self._lock:
            self._samples.append((max(delay, 0.0), offset))

    def add_exchange(self, t1: float, t2: float, t3: float,
                     t4: float) -> Tuple[float, float]:
        offset, delay = offset_and_delay(t1, t2, t3, t4)
        self.add(offset, delay)
        return offset, delay

    def _best(self) -> Optional[Tuple[float, float]]:
        with self._lock:
            if not self._samples:
                return None
            return min(self._samples)

    @property
    def offset(self) -> float:
        """Best-estimate ``remote - local`` clock offset (0.0 before
        the first sample)."""
        best = self._best()
        return best[1] if best is not None else 0.0

    @property
    def delay(self) -> Optional[float]:
        """Minimum observed network round-trip, or None when empty."""
        best = self._best()
        return best[0] if best is not None else None

    def to_local(self, t_remote: float) -> float:
        """Place a remote-clock timestamp on the local timeline."""
        return t_remote - self.offset

    def __len__(self) -> int:
        with self._lock:
            return len(self._samples)


def get_epoch(servers: Optional[Sequence[Tuple[str, int]]] = None,
              timeout: float = 2.0) -> int:
    """Epoch µs from the first answering server; local clock fallback
    (parity: ntputil_get_epoch's host-list walk + default server)."""
    for host, port in servers or ():
        try:
            return query_server(host, port, timeout)
        except (OSError, ValueError):
            continue
    return int(time.time() * 1e6)


def ntp_epoch_fn(servers: Sequence[Tuple[str, int]],
                 refresh_s: float = 60.0) -> Callable[[], int]:
    """Clock callable for ``MqttSink(epoch_fn=...)``: queries NTP at
    most every ``refresh_s`` and advances with the local monotonic
    clock in between (the reference's cacheing TODO, done)."""
    state = {"base_us": None, "base_mono": 0.0}

    def epoch() -> int:
        now = time.monotonic()
        if state["base_us"] is None or \
                now - state["base_mono"] >= refresh_s:
            state["base_us"] = get_epoch(servers)
            state["base_mono"] = now
            return state["base_us"]
        return state["base_us"] + int((now - state["base_mono"]) * 1e6)

    return epoch


def async_ntp_epoch_fn(servers: Sequence[Tuple[str, int]],
                       refresh_s: float = 60.0) -> Callable[[], int]:
    """Hot-path-safe variant of :func:`ntp_epoch_fn`: the SNTP queries
    (blocking, up to 2 s per unreachable server) run on a daemon
    refresh thread started lazily on first call; the returned callable
    itself only ever does arithmetic, so elements may invoke it inside
    ``render()``/``create()`` or under locks.  Until the first query
    answers it returns the local clock.  The attached ``.stop()``
    retires the refresh thread (element ``stop()`` paths call it)."""
    stop_evt = threading.Event()
    lock = threading.Lock()
    state = {"base_us": None, "base_mono": 0.0, "started": False}

    def refresh_loop() -> None:
        while not stop_evt.is_set():
            us = get_epoch(servers)
            now = time.monotonic()
            with lock:
                state["base_us"], state["base_mono"] = us, now
            stop_evt.wait(refresh_s)

    def epoch() -> int:
        with lock:
            if not state["started"]:
                state["started"] = True
                from ..obs import prof as _prof

                _prof.named_thread("edge-ntp", "epoch-refresh",
                                   refresh_loop).start()
            base_us, base_mono = state["base_us"], state["base_mono"]
        if base_us is None:
            return int(time.time() * 1e6)
        return base_us + int((time.monotonic() - base_mono) * 1e6)

    epoch.stop = stop_evt.set
    return epoch

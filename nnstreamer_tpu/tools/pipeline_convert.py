#!/usr/bin/env python
"""Pipeline description ⇄ pbtxt converter.

Parity target: /root/reference/tools/development/parser/ — a
flex/bison tool converting gst-launch pipeline strings to
mediapipe-style pbtxt graphs (``node: { calculator: "XCalculator"
input_stream/output_stream }``, toplevel.c/convert.c) and back.

This version goes through the real parser (``parse_launch`` builds the
element graph, so anything the runtime accepts converts), keeps the
reference's pbtxt shape (``calculator: "<factory>Calculator"``,
graph-level ``input_stream``/``output_stream`` for sources/sinks), and
completes the reference's open TODO ("Filling 'node_options' for detail
element info"): non-default element properties round-trip through
``node_options``.

Usage:
    python tools/pipeline_convert.py              # launch → pbtxt (stdin)
    python tools/pipeline_convert.py --from-pbtxt # pbtxt → launch (stdin)
"""

from __future__ import annotations

import inspect
import os
import re
import sys
from typing import Dict, List, Optional, Tuple


# -- graph model --------------------------------------------------------------

class Node:
    def __init__(self, name: str, factory: str,
                 props: Dict[str, str]):
        self.name = name
        self.factory = factory
        self.props = props
        # (own src pad or None, peer name, peer sink pad or None)
        self.outputs: List[Tuple[Optional[str], str, Optional[str]]] = []
        self.n_inputs = 0


def _non_default_props(el) -> Dict[str, str]:
    props = {}
    sig = inspect.signature(type(el).__init__)
    for p in sig.parameters.values():
        if p.name in ("self", "name", "props") or \
                p.kind == inspect.Parameter.VAR_KEYWORD:
            continue
        val = getattr(el, p.name, p.default)
        if val is None or val is p.default:
            continue
        if not isinstance(val, (str, int, float, bool)):
            continue  # runtime-only objects (callables, specs) don't ser
        if p.default is not inspect.Parameter.empty and val == p.default:
            continue
        props[p.name.rstrip("_").replace("_", "-")] = (
            str(val).lower() if isinstance(val, bool) else str(val))
    return props


def _graph_from_launch(desc: str) -> List[Node]:
    from nnstreamer_tpu.runtime.parser import parse_launch

    p = parse_launch(desc)
    nodes: Dict[str, Node] = {}
    for name, el in p.elements.items():
        nodes[name] = Node(name, el.FACTORY or type(el).__name__,
                           _non_default_props(el))
    for name, el in p.elements.items():
        multi_src = len(el.srcpads) > 1
        for pad in el.srcpads:
            if pad.peer is None:
                continue
            peer_el = pad.peer.element
            multi_sink = len(peer_el.sinkpads) > 1
            nodes[name].outputs.append((
                pad.name if multi_src else None,
                peer_el.name,
                pad.peer.name if multi_sink else None))
            nodes[peer_el.name].n_inputs += 1
    return [nodes[n] for n in p.elements]  # insertion order


# -- launch → pbtxt -----------------------------------------------------------

def launch_to_pbtxt(desc: str) -> str:
    nodes = _graph_from_launch(desc)
    out = []
    for n in nodes:
        if n.n_inputs == 0:
            out.append(f'input_stream: "{n.name}"')
    for n in nodes:
        if not n.outputs:
            out.append(f'output_stream: "{n.name}"')
    for n in nodes:
        lines = ["", "node: {",
                 f'\tcalculator: "{n.factory}Calculator"',
                 f'\tname: "{n.name}"']
        # input streams = the upstream nodes feeding us (reference
        # convention: streams are named after their producing node)
        for m in nodes:
            for spad, peer, dpad in m.outputs:
                if peer == n.name:
                    suffix = f":{dpad}" if dpad else ""
                    src = f"{m.name}:{spad}" if spad else m.name
                    lines.append(f'\tinput_stream: "{src}{suffix}"')
        if n.outputs or n.n_inputs == 0:
            lines.append(f'\toutput_stream: "{n.name}"')
        if n.props:
            lines.append("\tnode_options: {")
            for k, v in n.props.items():
                lines.append(f'\t\t{k}: "{_escape(v)}"')
            lines.append("\t}")
        lines.append("}")
        out.extend(lines)
    return "\n".join(out) + "\n"


# -- pbtxt → launch -----------------------------------------------------------

_TOKEN = re.compile(r'"(?:[^"\\]|\\.)*"|[{}:]|[^\s{}:"]+')


def _tokenize(text: str) -> List[str]:
    return _TOKEN.findall(text)


def _unquote(tok: str) -> str:
    if tok.startswith('"') and tok.endswith('"'):
        return re.sub(r"\\(.)", r"\1", tok[1:-1])
    return tok


def pbtxt_to_launch(text: str) -> str:
    toks = _tokenize(text)
    i = 0
    nodes: List[Node] = []

    def expect(t):
        nonlocal i
        if i >= len(toks) or toks[i] != t:
            raise ValueError(
                f"pbtxt: expected {t!r} at token {i} "
                f"({toks[i] if i < len(toks) else 'EOF'!r})")
        i += 1

    def parse_node() -> Node:
        nonlocal i
        expect("{")
        calc = name = None
        inputs: List[str] = []
        opts: Dict[str, str] = {}
        while i < len(toks) and toks[i] != "}":
            key = toks[i]
            i += 1
            expect(":")
            if key == "node_options":
                expect("{")
                while i < len(toks) and toks[i] != "}":
                    k = toks[i]
                    i += 1
                    expect(":")
                    if i >= len(toks):
                        raise ValueError("pbtxt: truncated node_options")
                    opts[k] = _unquote(toks[i])
                    i += 1
                expect("}")
                continue
            if i >= len(toks):
                raise ValueError(f"pbtxt: missing value for {key!r}")
            val = _unquote(toks[i])
            i += 1
            if key == "calculator":
                calc = val
            elif key == "name":
                name = val
            elif key == "input_stream":
                inputs.append(val)
            # output_stream is implied by the node name
        expect("}")
        if calc is None:
            raise ValueError("pbtxt: node without calculator")
        factory = calc[:-len("Calculator")] \
            if calc.endswith("Calculator") else calc
        node = Node(name or f"n{len(nodes)}", factory, opts)
        for s in inputs:
            # "<src>[:<srcpad>][:<sinkpad>]" — we emitted at most
            # "src:srcpad:sinkpad"; a plain stream is just "src"
            parts = s.split(":")
            src, spad, dpad = parts[0], None, None
            if len(parts) == 3:
                spad, dpad = parts[1], parts[2]
            elif len(parts) == 2:
                # ambiguity (reference streams have no pad info): treat
                # a sink_* suffix as OUR pad, else the producer's
                if parts[1].startswith("sink"):
                    dpad = parts[1]
                else:
                    spad = parts[1]
            node.n_inputs += 1
            node.outputs.append((spad, "<-" + src, dpad))  # temp marker
        return node

    while i < len(toks):
        key = toks[i]
        i += 1
        expect(":")
        if key == "node":
            nodes.append(parse_node())
        else:
            i += 1  # graph-level input_stream/output_stream value

    # invert the temp "<-src" input records into producer outputs —
    # snapshot all pendings BEFORE inverting, since inversion appends
    # real output records to nodes not yet processed
    by_name = {n.name: n for n in nodes}
    pendings = {n.name: n.outputs for n in nodes}
    for n in nodes:
        n.outputs = []
    for n in nodes:
        for spad, marker, dpad in pendings[n.name]:
            src = marker[2:]
            if src not in by_name:
                raise ValueError(f"pbtxt: unknown stream source {src!r}")
            by_name[src].outputs.append((spad, n.name, dpad))

    # emit: every element as a named segment, then one chain per link
    segs = []
    for n in nodes:
        props = " ".join(f"{k}={_quote_prop(v)}"
                         for k, v in n.props.items())
        segs.append(f"{n.factory} name={n.name}"
                    + (f" {props}" if props else ""))
    links = []
    for n in nodes:
        for spad, peer, dpad in n.outputs:
            src = f"{n.name}.{spad}" if spad else f"{n.name}."
            dst = f"{peer}.{dpad}" if dpad else f"{peer}."
            links.append(f"{src} ! {dst}")
    return "  ".join(segs + links)


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"')


def _quote_prop(v: str) -> str:
    # parse_launch tokenizes with posix shlex: backslashes must be
    # escaped even outside quotes or they are consumed on re-parse
    if any(c in v for c in ' !\t"\\'):
        return '"' + v.replace("\\", "\\\\").replace('"', '\\"') + '"'
    return v


def main() -> int:
    if "--help" in sys.argv[1:] or "-h" in sys.argv[1:]:
        print("usage: nnstreamer-tpu-convert [--from-pbtxt|-p] < input\n"
              "Convert a parse_launch pipeline string (stdin) to pbtxt, "
              "or pbtxt back to a launch string with --from-pbtxt.")
        return 0
    text = sys.stdin.read()
    if not text.strip():
        print("nnstreamer-tpu-convert: empty input (pipe a pipeline "
              "description on stdin; --help for usage)", file=sys.stderr)
        return 2
    if "--from-pbtxt" in sys.argv[1:] or "-p" in sys.argv[1:]:
        print(pbtxt_to_launch(text))
    else:
        print(launch_to_pbtxt(text.strip()))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

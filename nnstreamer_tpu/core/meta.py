"""Per-tensor binary meta header for flexible / sparse streams.

Parity target: ``GstTensorMetaInfo`` and its ser/de helpers
(/root/reference/gst/nnstreamer/include/tensor_typedef.h:310-326,
nnstreamer_plugin_api_util_impl.c:1447 ``gst_tensor_meta_info_get_header_size``
and :1496 ``gst_tensor_meta_info_update_header``).

Wire layout (little-endian u32 fields):

    magic | version | dtype | dims[16] | format | media_type [| nnz]

``nnz`` (number of non-zero elements) is appended only for SPARSE format.
The header self-describes a tensor payload so a flexible stream can change
shape per buffer and a receiver can reconstruct it without negotiated caps.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Optional, Tuple

from .spec import TensorSpec, dims_to_shape
from .types import DType, MediaType, TensorFormat, TENSOR_RANK_LIMIT

META_MAGIC = 0x545055AA  # "TPU" + marker; differs from the reference's magic
META_VERSION = 1

_BASE_FMT = "<" + "I" * (4 + TENSOR_RANK_LIMIT + 1)  # magic..media_type
_BASE_SIZE = struct.calcsize(_BASE_FMT)
_NNZ_FMT = "<I"
_NNZ_SIZE = struct.calcsize(_NNZ_FMT)


@dataclasses.dataclass
class MetaInfo:
    """Self-describing header of one tensor payload."""

    dtype: DType
    dims: Tuple[int, ...]
    format: TensorFormat = TensorFormat.FLEXIBLE
    media_type: MediaType = MediaType.TENSOR
    nnz: int = 0  # sparse only: number of stored (non-zero) elements
    version: int = META_VERSION

    @classmethod
    def from_spec(cls, spec: TensorSpec,
                  format: TensorFormat = TensorFormat.FLEXIBLE,
                  media_type: MediaType = MediaType.TENSOR,
                  nnz: int = 0) -> "MetaInfo":
        return cls(dtype=spec.dtype, dims=spec.dims, format=format,
                   media_type=media_type, nnz=nnz)

    def to_spec(self, name: Optional[str] = None) -> TensorSpec:
        return TensorSpec(dtype=self.dtype, dims=self.dims, name=name)

    @property
    def shape(self) -> Tuple[int, ...]:
        return dims_to_shape(self.dims)

    @property
    def header_size(self) -> int:
        return header_size(self.format)

    def data_nbytes(self) -> int:
        """Size of the payload that follows the header."""
        if self.format == TensorFormat.SPARSE:
            # values + u32 indices per stored element
            return self.nnz * (self.dtype.size + 4)
        n = 1
        for d in self.dims:
            n *= d
        return n * self.dtype.size

    def pack(self) -> bytes:
        if len(self.dims) > TENSOR_RANK_LIMIT:
            raise ValueError(
                f"rank {len(self.dims)} exceeds {TENSOR_RANK_LIMIT}")
        if any(not (0 < d < 2 ** 32) for d in self.dims):
            raise ValueError(f"dimension out of u32 range: {self.dims}")
        dims16 = list(self.dims) + [0] * (TENSOR_RANK_LIMIT - len(self.dims))
        hdr = struct.pack(
            _BASE_FMT, META_MAGIC, self.version, self.dtype.value, *dims16,
            self.format.value, _media_u32(self.media_type))
        if self.format == TensorFormat.SPARSE:
            hdr += struct.pack(_NNZ_FMT, self.nnz)
        return hdr

    @classmethod
    def unpack(cls, data: bytes) -> "MetaInfo":
        if len(data) < _BASE_SIZE:
            raise ValueError(f"meta header truncated: {len(data)} < {_BASE_SIZE}")
        fields = struct.unpack_from(_BASE_FMT, data)
        magic, version, dtype_v = fields[0], fields[1], fields[2]
        if magic != META_MAGIC:
            raise ValueError(f"bad meta magic: 0x{magic:08x}")
        if not (1 <= version <= META_VERSION):
            raise ValueError(f"unsupported meta version {version}")
        dims16 = fields[3:3 + TENSOR_RANK_LIMIT]
        fmt_v, media_v = fields[3 + TENSOR_RANK_LIMIT], fields[4 + TENSOR_RANK_LIMIT]
        dims = []
        for d in dims16:
            if d == 0:
                break
            dims.append(d)
        fmt = TensorFormat(fmt_v)
        nnz = 0
        if fmt == TensorFormat.SPARSE:
            if len(data) < _BASE_SIZE + _NNZ_SIZE:
                raise ValueError("sparse meta header truncated")
            (nnz,) = struct.unpack_from(_NNZ_FMT, data, _BASE_SIZE)
        return cls(dtype=DType(dtype_v), dims=tuple(dims) or (1,), format=fmt,
                   media_type=_media_from_u32(media_v), nnz=nnz,
                   version=version)


def header_size(format: TensorFormat) -> int:
    """Parity: gst_tensor_meta_info_get_header_size
    (nnstreamer_plugin_api_util_impl.c:1447)."""
    if format == TensorFormat.SPARSE:
        return _BASE_SIZE + _NNZ_SIZE
    return _BASE_SIZE


def _media_u32(m: MediaType) -> int:
    # OCTET is -1 in the enum; store as two's complement u32.
    return m.value & 0xFFFFFFFF


def _media_from_u32(v: int) -> MediaType:
    if v == 0xFFFFFFFF:
        return MediaType.OCTET
    return MediaType(v)

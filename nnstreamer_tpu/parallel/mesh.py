"""Mesh construction: the framework's "device topology" service.

The reference discovers peers by TCP host:port / MQTT topic
(tensor_query_client properties, /root/reference/gst/nnstreamer/
tensor_query/tensor_query_client.c).  Here the topology is a
`jax.sharding.Mesh`: axis names declare *intent* (``data`` batches,
``model`` weight shards) and XLA maps collectives onto ICI links.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import numpy as np


def _jax():
    import jax

    return jax


def local_device_count(platform: Optional[str] = None) -> int:
    try:
        return len(_jax().devices(platform))
    except RuntimeError:
        return 0


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh request: axis names + sizes; -1 = absorb remaining
    devices (at most one -1)."""

    axes: Tuple[Tuple[str, int], ...] = (("data", -1),)

    @classmethod
    def parse(cls, s: str) -> "MeshSpec":
        """Parse ``"data:-1"`` / ``"data:4,model:2"``."""
        axes = []
        for part in s.split(","):
            name, _, n = part.strip().partition(":")
            axes.append((name, int(n) if n else -1))
        return cls(tuple(axes))

    def resolve(self, n_devices: int) -> Tuple[Tuple[str, int], ...]:
        sizes = [n for _, n in self.axes]
        wild = [i for i, n in enumerate(sizes) if n == -1]
        if len(wild) > 1:
            raise ValueError(f"more than one -1 axis in {self.axes}")
        fixed = math.prod(n for n in sizes if n != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {fixed}")
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {self.axes} wants {fixed} devices, have {n_devices}")
        return tuple((name, n) for (name, _), n in zip(self.axes, sizes))


def parse_device_indices(s: str, n_devices: int) -> Tuple[int, ...]:
    """Parse a device-subset spec — ``"0-3"``, ``"4,5,6,7"``, ``"0-1,6"`` —
    into a tuple of device indices (deduplicated, order-preserving).

    This is the framework's *placement* grammar: where the reference
    addresses remote workers by host:port
    (tensor_query_client.c:673-741), here a pipeline stage addresses a
    subset of the slice's chips by index, and "offload" is a
    device-to-device handoff over ICI.
    """
    out: list = []
    seen = set()
    for part in str(s).split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part:
            lo_s, _, hi_s = part.partition("-")
            lo, hi = int(lo_s), int(hi_s)
            if hi < lo:
                raise ValueError(f"bad device range {part!r}")
            rng = range(lo, hi + 1)
        else:
            rng = (int(part),)
        for i in rng:
            if i < 0 or i >= n_devices:
                raise ValueError(
                    f"device index {i} out of range (have {n_devices})")
            if i not in seen:
                seen.add(i)
                out.append(i)
    if not out:
        raise ValueError(f"empty device subset {s!r}")
    return tuple(out)


def mesh_topology(mesh) -> dict:
    """Describe a built ``jax.sharding.Mesh`` for the observability
    layer (obs/meshstat.py): axis (name, size) pairs plus the device
    list in mesh order — the ``mesh`` table's topology fields."""
    return {
        "axes": [(str(name), int(size))
                 for name, size in zip(mesh.axis_names,
                                       mesh.devices.shape)],
        "devices": [str(d) for d in mesh.devices.flat],
    }


def make_mesh(spec: MeshSpec | str | Sequence[Tuple[str, int]] = "data:-1",
              devices=None):
    """Build a `jax.sharding.Mesh`.  Device order follows `jax.devices()`,
    which JAX arranges so the innermost mesh axis maps to the
    fastest-varying ICI dimension (keep ``model`` innermost)."""
    jax = _jax()
    if isinstance(spec, str):
        spec = MeshSpec.parse(spec)
    elif not isinstance(spec, MeshSpec):
        spec = MeshSpec(tuple(spec))
    if devices is None:
        devices = jax.devices()
    axes = spec.resolve(len(devices))
    shape = tuple(n for _, n in axes)
    names = tuple(name for name, _ in axes)
    dev_array = np.asarray(devices).reshape(shape)
    return jax.sharding.Mesh(dev_array, names)

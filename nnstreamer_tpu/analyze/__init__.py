"""``nnstreamer_tpu.analyze`` — static pipeline verifier + codebase lint.

The ``gst-validate`` analog for this framework: proves a pipeline
description is well-formed *before* any thread or TPU computation runs
(PAPER.md's caps-negotiation-at-PAUSED property, made a standalone pure
function), and keeps the codebase itself honest with concurrency and
style passes.

Passes / diagnostic families (catalog: ``diagnostics.CODES``,
docs: ``Documentation/analyze.md``):

1. graph verifier     — ``NNS1xx`` (:mod:`.graph`)
2. caps dry-run       — ``NNS2xx`` + ``NNS108`` (:mod:`.capsflow`)
3. concurrency + lint — ``NNS3xx``/``NNS4xx`` (:mod:`.codelint`)
4. lock-order analysis — ``NNS6xx`` (:mod:`.concurrency`): the static
   half of the concurrency correctness layer; the runtime half is the
   lockdep witness (``utils/lockdep.py``, ``NNS_TPU_LOCKDEP=1``)

CLI: ``python -m nnstreamer_tpu.analyze`` (shim: ``tools/nns_lint.py``).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from .capsflow import caps_dry_run
from .codelint import lint_package, lint_source
from .concurrency import LockGraph, analyze_package_concurrency, \
    lint_concurrency_source
from .diagnostics import CODES, Diagnostic, Severity, counts, \
    sort_diagnostics
from .graph import verify_graph

__all__ = [
    "CODES", "Diagnostic", "LockGraph", "Severity",
    "analyze_description", "analyze_package_concurrency",
    "analyze_pipeline", "caps_dry_run", "counts",
    "lint_concurrency_source", "lint_package", "lint_source",
    "sort_diagnostics", "verify_graph",
]


def analyze_pipeline(pipe, fragment: bool = False) -> List[Diagnostic]:
    """Run the graph verifier and the caps dry-run over an assembled (not
    started) Pipeline.  Pure: no threads, no element start, pad caps
    restored."""
    return sort_diagnostics(verify_graph(pipe, fragment)
                            + caps_dry_run(pipe, fragment))


def analyze_description(desc: str, fragment: bool = False
                        ) -> Tuple[List[Diagnostic], Optional[object]]:
    """Parse a ``gst-launch``-style description and analyze it.  Returns
    ``(diagnostics, pipeline-or-None)``; a description that does not
    parse yields a single NNS100/NNS103 diagnostic pointing at the
    offending offset."""
    from ..runtime.parser import ParseError, parse_launch

    try:
        pipe = parse_launch(desc)
    except ParseError as e:
        msg = str(e)
        code = "NNS103" if e.kind == "double-link" else "NNS100"
        hint = None
        if e.pos is not None:
            hint = e.context(desc)
        return [Diagnostic.make(
            code, msg,
            pad=None if e.pos is None else f"offset {e.pos}",
            hint=hint)], None
    except Exception as e:  # element constructor blew up on a bad prop
        return [Diagnostic.make(
            "NNS100", f"cannot build pipeline: "
            f"{type(e).__name__}: {e}")], None
    return analyze_pipeline(pipe, fragment), pipe

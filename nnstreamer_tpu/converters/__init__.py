"""External converter sub-plugins (L3).

Parity target: ``NNStreamerExternalConverter`` ABI
(/root/reference/gst/nnstreamer/include/nnstreamer_plugin_api_converter.h:41-85):
``query_caps``, ``get_out_config``, ``convert``, keyed by mimetype.

Built-ins (registered by this package on import, from ``wirefmt.py``):
``flexbuf`` (other/flexbuf, FlexBuffers map), ``flatbuf``
(other/flatbuf-tensor, FlatBuffers ``Tensors`` table), ``protobuf``
(other/protobuf-tensor, proto3 wire) — codecs in ``codecs.py``.  User
converters: ``register_custom`` callables (reference
``nnstreamer_converter_custom_register``,
gst/nnstreamer/tensor_converter/tensor_converter_custom.c) and
``python3`` script classes (``python3.py``), both reached through
``tensor_converter``'s ``mode=custom-code:NAME`` /
``mode=custom-script:FILE.py`` property.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from ..core import Buffer, CapsStruct, TensorsSpec

_lock = threading.Lock()
_converters: Dict[str, "ExternalConverter"] = {}
_custom: Dict[str, Callable[[Buffer], Buffer]] = {}


class ExternalConverter:
    """Sub-plugin converting foreign-mimetype payloads into tensor buffers."""

    NAME = ""
    MIMES: tuple = ()

    def get_out_config(self, caps: CapsStruct) -> TensorsSpec:
        raise NotImplementedError

    def convert(self, buf: Buffer, caps: CapsStruct) -> Buffer:
        raise NotImplementedError


def register_converter(conv) -> "ExternalConverter":
    """Register a converter sub-plugin (class or instance) by mime + name."""
    inst = conv() if isinstance(conv, type) else conv
    with _lock:
        for m in inst.MIMES:
            _converters[m] = inst
        if inst.NAME:
            _converters[inst.NAME] = inst
    return conv


def find_converter(mime_or_name: str) -> Optional["ExternalConverter"]:
    with _lock:
        return _converters.get(mime_or_name)


def list_converters():
    with _lock:
        return sorted({c.NAME for c in _converters.values()})


def registered_mimes():
    """All mimetypes any registered converter sub-plugin accepts."""
    with _lock:
        return sorted({m for c in _converters.values() for m in c.MIMES})


def register_custom(name: str, fn: Callable[[Buffer], Buffer]) -> None:
    """Register a callable as a ``mode=custom-code:name`` converter.

    Parity: ``nnstreamer_converter_custom_register``
    (/root/reference/gst/nnstreamer/tensor_converter/
    tensor_converter_custom.c).  ``fn(buf) -> Buffer`` receives the raw
    input buffer and returns the converted tensor buffer.
    """
    with _lock:
        _custom[name] = fn


def unregister_custom(name: str) -> bool:
    with _lock:
        return _custom.pop(name, None) is not None


def find_custom(name: str) -> Optional[Callable[[Buffer], Buffer]]:
    with _lock:
        return _custom.get(name)


from . import wirefmt  # noqa: E402,F401  (registers flexbuf/flatbuf/protobuf)
from .python3 import Python3Converter  # noqa: E402,F401

"""Performance observability (ISSUE 7): dispatch cost attribution,
compile & executable-cache telemetry, and the continuous-bench
regression gate.

- phase-split exactness: host-prep + device + host-drain partitions the
  dispatch at shared clock reads, and prep + device IS the recorded
  invoke latency (same block_until_ready fence);
- compile counters: ``nns_compiles_total`` equals the true number of
  ``_compile`` / ``_compile_batched`` calls across the cold, reshape,
  reload and bucket paths;
- executable-cache export: a warm re-run scrapes ZERO new misses;
- ``nns_bench_diff`` verdicts (pass / regression / missing-baseline)
  against golden history/baseline fixtures;
- the admission controller's p99 derives from the registry's exported
  latency histogram (private window only as detached-registry
  fallback).
"""

import io
import json
import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.elements.basic import AppSink, AppSrc, Queue
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.filters.api import FilterProps
from nnstreamer_tpu.filters.jax_xla import JaxXlaFilter, register_model
from nnstreamer_tpu.obs import benchgate
from nnstreamer_tpu.obs.metrics import (
    ADMISSION_LATENCY_BUCKETS,
    REGISTRY,
    MetricsRegistry,
)
from nnstreamer_tpu.obs.tracer import LatencyTracer
from nnstreamer_tpu.runtime import Pipeline
from nnstreamer_tpu.runtime.admission import AdmissionController
from nnstreamer_tpu.runtime.events import Event, EventKind
from nnstreamer_tpu.runtime.serving import MODEL_POOL
from nnstreamer_tpu.utils.stats import COMPILE_STATS

SHAPE = (8,)


@pytest.fixture(autouse=True)
def _model():
    register_model("_t_cost", lambda x: x * 2.0 + 1.0,
                   in_shapes=[SHAPE], in_dtypes=np.float32)
    yield
    MODEL_POOL.clear()


def _pipeline(batch=1, name="cost", **flt_kw):
    spec = TensorsSpec.from_shapes([SHAPE], np.float32)
    p = Pipeline(name=name)
    src = AppSrc(name="src", spec=spec, max_buffers=256)
    q = Queue(name="q", max_size_buffers=256)
    flt = TensorFilter(name="net", framework="jax-xla", model="_t_cost",
                       batch=batch, batch_timeout_ms=2.0,
                       batch_buckets=str(batch) if batch > 1 else "",
                       latency=1, **flt_kw)
    sink = AppSink(name="out", max_buffers=256)
    p.add(src, q, flt, sink).link(src, q, flt, sink)
    return p, src, flt, sink


def _run(src, sink, n):
    for i in range(n):
        src.push_buffer(Buffer.of(
            np.full(SHAPE, float(i % 5), np.float32), pts=i))
    for _ in range(n):
        assert sink.pull(timeout=30) is not None


# -- phase-split exactness ----------------------------------------------------


def test_phase_split_sums_to_invoke_latency_single_frame():
    """latency=1 samples every dispatch: the cumulative phase split
    must (a) partition each dispatch exactly (shared clock reads) and
    (b) have prep + device equal the recorded invoke latency within
    the 5% acceptance tolerance (the int-µs truncation of the latency
    accumulator is the only slack)."""
    p, src, flt, sink = _pipeline(name="cost_phase1")
    with p:
        _run(src, sink, 20)
        s = flt.invoke_stats.snapshot()
    ph = s["phase"]
    assert ph["samples"] == s["invokes"] == 20
    assert s["host_prep_us"] >= 0
    assert s["device_us"] > 0
    assert s["host_drain_us"] >= 0
    lat_total_s = flt.invoke_stats.total_invoke_latency_us / 1e6
    prep_dev = ph["host_prep_s"] + ph["device_s"]
    assert prep_dev == pytest.approx(lat_total_s, rel=0.05)
    # drain is real and separate: the full split covers more than the
    # recorded latency, by exactly the drain term
    full = prep_dev + ph["host_drain_s"]
    assert full >= lat_total_s


def test_phase_split_batched_and_registry_histograms():
    """The micro-batched path attributes phases per window and exports
    them as nns_invoke_{device,host}_seconds histograms whose sums
    agree with the element's own InvokeStats phase accumulators."""
    fam_dev = REGISTRY.collect().get("nns_invoke_device_seconds", {})
    before = sum(s["value"] for s in fam_dev.get("samples", [])
                 if s.get("name", "").endswith("_sum")
                 and s["labels"].get("source") == "net_cost_b")
    p, src, flt, sink = _pipeline(batch=4, name="cost_phaseb")
    flt.name = "net_cost_b"  # unique registry label for this test
    with p:
        _run(src, sink, 32)
        s = flt.invoke_stats.snapshot()
        fams = REGISTRY.collect()
    ph = s["phase"]
    assert ph["samples"] == s["invokes"] > 0
    assert s["frames"] == 32

    def hist_sum(name, **match):
        total = 0.0
        for sample in fams[name]["samples"]:
            if not sample.get("name", "").endswith("_sum"):
                continue
            if all(sample["labels"].get(k) == v
                   for k, v in match.items()):
                total += sample["value"]
        return total

    dev = hist_sum("nns_invoke_device_seconds", source="net_cost_b",
                   kind="element", bucket="4") - before
    host_prep = hist_sum("nns_invoke_host_seconds",
                         source="net_cost_b", phase="prep")
    host_drain = hist_sum("nns_invoke_host_seconds",
                          source="net_cost_b", phase="drain")
    assert dev == pytest.approx(ph["device_s"], rel=0.05)
    assert host_prep == pytest.approx(ph["host_prep_s"], rel=0.05) \
        or ph["host_prep_s"] < 1e-4
    assert host_drain == pytest.approx(ph["host_drain_s"], rel=0.05) \
        or ph["host_drain_s"] < 1e-4


def test_pool_dispatch_phase_split():
    """SharedBatcher dispatches attribute phases on the POOL stats."""
    p1, s1, f1, k1 = _pipeline(batch=4, name="cost_poolA",
                               share_model=True)
    p2, s2, f2, k2 = _pipeline(batch=4, name="cost_poolB",
                               share_model=True)
    p1.start()
    p2.start()
    try:
        for i in range(8):
            s1.push_buffer(Buffer.of(np.zeros(SHAPE, np.float32), pts=i))
            s2.push_buffer(Buffer.of(np.zeros(SHAPE, np.float32), pts=i))
        got = 0
        deadline = time.monotonic() + 20
        while got < 16 and time.monotonic() < deadline:
            if k1.pull(timeout=0.2) is not None:
                got += 1
            if k2.pull(timeout=0.2) is not None:
                got += 1
        assert got == 16
        stats = f1.pool.stats.snapshot()
        assert stats["phase"]["samples"] > 0
        assert stats["device_us"] > 0
    finally:
        p1.stop()
        p2.stop()


def test_chrome_trace_carries_invoke_subphases():
    """The Perfetto export nests host-prep/device/host-drain spans
    inside the frame lane, contained by the frame span."""
    p, src, flt, sink = _pipeline(batch=4, name="cost_trace")
    with LatencyTracer(sample_every=1) as tr:
        with p:
            _run(src, sink, 16)
    ct = tr.chrome_trace()
    names = {e["name"] for e in ct["traceEvents"]}
    assert {"net:host-prep", "net:device", "net:host-drain"} <= names
    by_tid = {}
    for e in ct["traceEvents"]:
        by_tid.setdefault(e["tid"], []).append(e)
    checked = 0
    for evs in by_tid.values():
        frames = [e for e in evs if e["cat"] == "frame"]
        phases = [e for e in evs if e["cat"] == "phase"
                  and e["name"].startswith("net:")]
        if not frames or not phases:
            continue
        f = frames[0]
        for e in phases:
            assert e["ts"] >= f["ts"] - 1
            assert e["ts"] + e["dur"] <= f["ts"] + f["dur"] + 1
        checked += 1
    assert checked > 0


# -- compile telemetry --------------------------------------------------------


def _totals():
    rows = COMPILE_STATS.snapshot()
    return {(r["kind"], r["bucket"]): r["count"] for r in rows
            if r["framework"] == "jax-xla"}


def test_compile_counter_matches_compile_calls():
    """One count per _compile/_compile_batched call, labeled by path:
    cold (configure), reshape (set_input_info), reload (hot swap),
    bucket (micro-batch executable) — and the registry exports the
    same totals."""
    register_model("_t_cost_b", lambda x: x - 1.0,
                   in_shapes=[SHAPE], in_dtypes=np.float32)
    before = _totals()
    sp = JaxXlaFilter()
    sp.configure(FilterProps(framework="jax-xla", model="_t_cost",
                             is_updatable=True))
    sp.set_input_info(TensorsSpec.from_shapes([(4,)], np.float32))
    sp.invoke_batched([[np.zeros((4,), np.float32)]] * 2, 2)
    sp.invoke_batched([[np.zeros((4,), np.float32)]] * 2, 2)  # cache hit
    sp.invoke_batched([[np.zeros((4,), np.float32)]], 1)
    sp.handle_event(Event(EventKind.RELOAD_MODEL,
                          data={"model": "_t_cost_b"}))
    sp.invoke_batched([[np.zeros((4,), np.float32)]] * 2, 2)  # warm hit
    after = _totals()

    def delta(kind, bucket="0"):
        return after.get((kind, bucket), 0) - before.get((kind, bucket), 0)

    assert delta("cold") == 1
    assert delta("reshape") == 1
    assert delta("reload") == 1
    # the double-buffered reload (runtime/lifecycle.py) pre-compiles
    # every HOT bucket off the dispatch path, so both live buckets
    # recompile at reload time and the post-reload window is a cache
    # hit instead of an on-path build
    assert delta("bucket", "2") == 2  # initial + off-path reload warm
    assert delta("bucket", "1") == 2  # initial + off-path reload warm
    # registry export agrees with the pull source
    fam = REGISTRY.collect()["nns_compiles_total"]
    exported = sum(s["value"] for s in fam["samples"]
                   if s["labels"]["framework"] == "jax-xla")
    assert exported == COMPILE_STATS.total_compiles \
        - sum(r["count"] for r in COMPILE_STATS.snapshot()
              if r["framework"] != "jax-xla")
    assert COMPILE_STATS.total_seconds > 0
    sp.close()


def test_compile_seconds_include_first_call():
    """The lazy XLA build lands on the executable's first invocation;
    the wrapper attributes it to the compile row (seconds strictly
    grow after the first invoke)."""
    before = {(r["kind"], r["bucket"]): r["seconds"]
              for r in COMPILE_STATS.snapshot()}
    sp = JaxXlaFilter()
    sp.configure(FilterProps(framework="jax-xla", model="_t_cost"))
    mid = {(r["kind"], r["bucket"]): r["seconds"]
           for r in COMPILE_STATS.snapshot()}
    sp.invoke([np.zeros(SHAPE, np.float32)])
    after = {(r["kind"], r["bucket"]): r["seconds"]
             for r in COMPILE_STATS.snapshot()}
    key = ("cold", "0")
    assert mid[key] > before.get(key, 0.0)
    assert after[key] > mid[key]
    sp.close()


# -- executable-cache export --------------------------------------------------


def test_executable_cache_export_warm_rerun_zero_misses():
    """The per-bucket hit/miss counters scrape through the registry;
    a warm re-run adds hits but ZERO new misses."""
    p, src, flt, sink = _pipeline(batch=4, name="cost_cache")

    def scrape():
        fams = REGISTRY.collect()
        out = {}
        for metric in ("nns_executable_cache_hits_total",
                       "nns_executable_cache_misses_total"):
            total = 0
            for s in fams.get(metric, {}).get("samples", []):
                if s["labels"].get("element") == "net" and \
                        s["labels"].get("pipeline") == "cost_cache":
                    total += s["value"]
            out[metric] = total
        return out

    with p:
        _run(src, sink, 16)
        warm = scrape()
        assert warm["nns_executable_cache_misses_total"] == 1
        _run(src, sink, 16)
        rerun = scrape()
    assert rerun["nns_executable_cache_misses_total"] == \
        warm["nns_executable_cache_misses_total"]  # 0 NEW misses
    assert rerun["nns_executable_cache_hits_total"] > \
        warm["nns_executable_cache_hits_total"]


# -- admission: p99 from the exported histogram ------------------------------


def test_admission_p99_reads_exported_histogram():
    reg = MetricsRegistry()
    hist = reg.histogram("nns_admission_latency_seconds", "t",
                         labelnames=("pool",),
                         buckets=ADMISSION_LATENCY_BUCKETS
                         ).labels(pool="t")
    adm = AdmissionController(slo_s=0.03, hist=hist)
    for _ in range(64):
        adm.observe(0.012)
    # bucket-derived estimate: inside the (0.01, 0.015] bucket
    assert 0.010 <= adm.p99_s <= 0.015
    assert not adm.at_risk
    # the exported exposition carries the SAME signal
    expo = reg.exposition()
    assert 'nns_admission_latency_seconds_bucket' in expo
    assert 'pool="t"' in expo
    # tail into the ramp -> sheds arm, from histogram-derived p99
    adm.reset_signal()
    for _ in range(64):
        adm.observe(0.028)
    assert adm.at_risk and adm.shed_probability > 0.5


def test_admission_fallbacks():
    # detached registry: the private window is the signal (unchanged
    # legacy behavior)
    adm = AdmissionController(slo_s=0.1)
    for _ in range(64):
        adm.observe(0.5)
    assert adm.p99_s == 0.5
    # latencies past the last finite bucket: fall back to the window
    reg = MetricsRegistry()
    hist = reg.histogram("nns_admission_latency_seconds", "t",
                         labelnames=("pool",),
                         buckets=ADMISSION_LATENCY_BUCKETS
                         ).labels(pool="x")
    adm2 = AdmissionController(slo_s=0.05, hist=hist)
    for _ in range(64):
        adm2.observe(10.0)
    assert adm2.p99_s == 10.0
    assert adm2.shed_probability == 1.0


def test_pool_admission_feeds_registry_histogram():
    """The wired-up path: a share-model pool with slo-ms exports its
    serve latencies as nns_admission_latency_seconds{pool=...}."""
    p, src, flt, sink = _pipeline(batch=2, name="cost_adm",
                                  share_model=True, slo_ms=500.0)
    with p:
        _run(src, sink, 8)
        assert flt.pool.admission is not None
        assert flt.pool.admission._hist is not None
        fams = REGISTRY.collect()
        fam = fams["nns_admission_latency_seconds"]
        counts = [s["value"] for s in fam["samples"]
                  if s.get("name", "").endswith("_count")
                  and "jax-xla:_t_cost" in s["labels"].get("pool", "")]
    assert counts and max(counts) >= 8


# -- bench history + regression gate -----------------------------------------


def _history_line(scenario="batching", **scalars):
    base = {"value": 4.5, "dispatch_reduction": 8.0,
            "coalescing": True}
    base.update(scalars)
    return {"scenario": scenario, "time": 1.0, "git_sha": "deadbeef",
            "unit": "x", "scalars": base,
            "registry_digest": "sha256:0"}


def _baseline_doc():
    return {"scenario": "batching", "metrics": {
        "value": {"baseline": 4.5, "tolerance": 0.5,
                  "direction": "higher"},
        "dispatch_reduction": {"baseline": 8.0, "tolerance": 0.5},
        "coalescing": {"baseline": 1, "tolerance": 0.0},
    }}


def test_bench_diff_verdicts(tmp_path):
    hist = tmp_path / "hist.jsonl"
    basef = tmp_path / "base.json"
    basef.write_text(json.dumps(_baseline_doc()))

    # missing history record
    out = io.StringIO()
    rc = benchgate.main(["--history", str(hist), "--scenario",
                         "batching", "--baseline", str(basef)], out=out)
    assert rc == 2 and "missing-baseline" in out.getvalue()

    # pass
    with open(hist, "a") as f:
        f.write(json.dumps(_history_line()) + "\n")
    out = io.StringIO()
    rc = benchgate.main(["--history", str(hist), "--scenario",
                         "batching", "--baseline", str(basef),
                         "--json"], out=out)
    doc = json.loads(out.getvalue())
    assert rc == 0 and doc["verdict"] == "pass"
    assert all(c["ok"] for c in doc["checks"])

    # doctored regression record (latest wins)
    with open(hist, "a") as f:
        f.write(json.dumps(_history_line(
            value=1.0, dispatch_reduction=1.0)) + "\n")
    out = io.StringIO()
    rc = benchgate.main(["--history", str(hist), "--scenario",
                         "batching", "--baseline", str(basef),
                         "--json"], out=out)
    doc = json.loads(out.getvalue())
    assert rc == 1 and doc["verdict"] == "regression"
    bad = {c["metric"] for c in doc["checks"] if not c["ok"]}
    assert bad == {"value", "dispatch_reduction"}

    # missing baseline file
    rc = benchgate.main(["--history", str(hist), "--scenario",
                         "batching", "--baseline",
                         str(tmp_path / "nope.json")], out=io.StringIO())
    assert rc == 2


def test_bench_diff_lower_is_better_and_raw_result_baseline(tmp_path):
    hist = tmp_path / "hist.jsonl"
    with open(hist, "a") as f:
        f.write(json.dumps(_history_line(
            scenario="edge", value=120.0)) + "\n")
    base = tmp_path / "base.json"
    # lower-is-better metric (e.g. RTT µs): 120 vs 100 at 10% -> fail
    base.write_text(json.dumps({"metrics": {
        "value": {"baseline": 100.0, "tolerance": 0.10,
                  "direction": "lower"}}}))
    rc = benchgate.main(["--history", str(hist), "--scenario", "edge",
                         "--baseline", str(base)], out=io.StringIO())
    assert rc == 1
    # a raw bench result as baseline: its `value` compared higher-better
    base.write_text(json.dumps({"value": 110.0, "unit": "x"}))
    rc = benchgate.main(["--history", str(hist), "--scenario", "edge",
                         "--baseline", str(base)], out=io.StringIO())
    assert rc == 0


def test_append_history_record_shape(tmp_path):
    hist = tmp_path / "h.jsonl"
    result = {"metric": "m", "value": 2.5, "unit": "x", "frames": 64,
              "coalescing": True, "note": "text dropped",
              "curve": {"nested": "dropped"}}
    rec = benchgate.append_history("batching", result, path=str(hist))
    assert rec["scenario"] == "batching"
    assert rec["scalars"] == {"value": 2.5, "frames": 64,
                              "coalescing": True}
    assert rec["registry_digest"].startswith("sha256:")
    # round-trips through the reader, unparseable lines skipped
    with open(hist, "a") as f:
        f.write("{truncated\n")
    assert benchgate.latest_record(str(hist), "batching")["scalars"] \
        == rec["scalars"]


def test_nns_top_renders_dev_host_and_compile(capsys):
    from nnstreamer_tpu.obs.top import main as top_main

    p, src, flt, sink = _pipeline(name="cost_top")
    out = io.StringIO()
    with p:
        _run(src, sink, 8)
        rc = top_main(["--once", "--interval", "0.05",
                       "--connect", ""], out=out)
    text = out.getvalue()
    assert rc == 0
    for col in ("DEV µs", "HOST µs", "COMPILE", "KIND", "TOTAL ms"):
        assert col in text
    assert "jax-xla" in text  # the COMPILE section has rows

"""Model zoo for the flagship ``jax-xla`` filter.

The reference treats models as opaque files consumed by backend sub-plugins
(``tests/test_models/models/mobilenet_v2_1.0_224_quant.tflite`` for tflite,
``lenet5.uff`` for TensorRT — /root/reference/tests/test_models/).  The
TPU-native framework instead ships the benchmark model families as jittable
JAX programs whose params live in HBM; they register with the jax-xla filter
via :func:`nnstreamer_tpu.filters.jax_xla.register_model` and also serialize
to ``.jaxexp`` (StableHLO) for file-based loading.

Families mirror BASELINE.json configs: MobileNetV1 classification,
SSD-MobileNetV2 detection, DeepLabV3 segmentation, PoseNet pose estimation.
"""

from .mobilenet import (  # noqa: F401
    mobilenet_v1_init,
    mobilenet_v1_apply,
    mobilenet_v2_init,
    mobilenet_v2_apply,
)
from .ssd import (  # noqa: F401
    ssd_mobilenet_v2_init,
    ssd_mobilenet_v2_apply,
    ssd_anchors,
    decode_boxes,
    batched_nms,
)
from .vit import (  # noqa: F401
    register_vit,
    vit_apply,
    vit_init,
)
from .yolo import (  # noqa: F401
    register_yolo,
    yolo_detect_apply,
    yolo_init,
    yolo_raw_apply,
)
